"""Benchmark: GDELT-style BBOX+time filter + kNN, TPU vs honest CPU baseline.

The north-star shape from BASELINE.json: post-index-scan predicate filtering
plus kNN analytics, measured as points/sec/chip. The CPU baseline is the
vectorized NumPy equivalent of the geomesa-fs Parquet scan path's compute
(config 1-style): full-width f64 mask + argpartition kNN — the strongest
simple CPU implementation we can field locally (see BASELINE.md build
obligation: measure, don't assert).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Usage: python bench.py [--smoke] [--n N] [--queries Q]
  --smoke: small sizes + force CPU (for CI; vs_baseline still computed)
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

# ---------------------------------------------------------------------------
# Harness plumbing (round 5): the round-4 driver run timed out with ZERO
# output (BENCH_r04.json rc=124, parsed=null) because this file printed one
# JSON line only at the very end of every phase. The driver parses the LAST
# JSON line of the stdout tail, so the contract is now:
#   1. print the HEADLINE line as soon as the device pipeline + parity gate
#      + CPU baseline are done (a timeout after that still leaves a number);
#   2. run budget-gated extras (phase accounting, burst) and print one
#      richer line at the end — last-line-wins upgrades the headline;
#   3. narrate progress on stderr so a timeout leaves a trace;
#   4. cache the deterministic CPU baseline on disk (.bench_cache/) and the
#      XLA executables (.jax_cache/ via the persistent compilation cache —
#      remote compiles through the tunnel cost 4-120 s each).
# ---------------------------------------------------------------------------

START = time.time()
_REPO = os.path.dirname(os.path.abspath(__file__))


def log(msg):
    """Progress note on stderr (stdout carries only the JSON lines)."""
    print(f"[bench +{time.time() - START:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


BUDGET_DEFAULT_S = 360.0
_BUDGET_CREDIT_S = 0.0


def budget_total_s():
    return float(
        os.environ.get("GEOMESA_TPU_BENCH_BUDGET_S", str(BUDGET_DEFAULT_S)))


def budget_remaining_s():
    """Seconds left of the internal wall-clock budget. Phases that are not
    needed for the headline line degrade (fewer repeats) or skip entirely
    when this runs low — a slow tunnel day must shrink the run, not kill
    it silently (VERDICT r4 weak #1). Warm-compile-cache runs earn the
    saved warmup time back as credit (credit_budget) instead of
    forfeiting it to "extras trimmed (budget -0s left)"."""
    return budget_total_s() - (time.time() - START) + _BUDGET_CREDIT_S


def credit_budget(seconds, reason):
    """Extend the extras budget by time a cache saved us (warm persistent
    compile cache, warm prep cache). The credit is bounded by what a cold
    run actually measured, so it can never invent time."""
    global _BUDGET_CREDIT_S
    if seconds > 0:
        _BUDGET_CREDIT_S += seconds
        log(f"budget credit +{seconds:.1f}s ({reason}); "
            f"remaining {budget_remaining_s():.0f}s")


_CACHE_PREPOPULATED = False  # did the persistent cache hold entries at start?


def enable_compile_cache():
    """Persistent XLA compilation cache shared across bench runs, the
    driver's run, AND the serving/planner stack (the shared helper in
    geomesa_tpu.compilecache — lifted out of this file in the zero-
    recompile-serving round). Verified working through the axon tunnel:
    a 2048^2 matmul compile drops 3.7 s -> 1.2 s; the Mosaic kernels are
    the ones that cost 60-120 s cold. The bench keeps its repo-local
    directory so cache artifacts travel with the checkout; the helper
    adds a per-backend subdir, which also makes --smoke (forced-CPU)
    runs safe alongside TPU artifacts."""
    global _CACHE_PREPOPULATED
    try:
        from geomesa_tpu.compilecache.persist import enable_persistent_cache

        got = enable_persistent_cache(
            os.path.join(_REPO, ".jax_cache"),
            min_entry_bytes=-1, min_compile_secs=0.0, force=True)
        if got is None:
            log("compile cache disabled/unavailable")
        else:
            # warmth evidence for warm_compile_credit: only a run that
            # STARTED with cached executables may claim saved-time credit
            try:
                _CACHE_PREPOPULATED = bool(os.listdir(got))
            except OSError:
                _CACHE_PREPOPULATED = False
    except Exception as e:  # cache is an optimization, never a failure
        log(f"compile cache unavailable: {e}")


def warm_compile_credit(key, compile_t):
    """Credit persistent-cache-saved warmup time back to the extras
    budget (the "extras trimmed (budget -0s left)" starvation fix): a
    run whose compile cache spared it N seconds of warmup has N more
    seconds of real budget than the cold run the defaults assume.

    Guards that keep the credit honest: (1) credit needs warmth
    evidence — the cache dir held entries at startup
    (_CACHE_PREPOPULATED); a fast run without it is variance, and only
    RATCHETS the baseline down; (2) the baseline is the SMALLEST
    observation for this key (first observation seeds it, even on a
    warm run — a warm first baseline is small, keeping every later
    credit conservative; a slow-tunnel day can never inflate it)."""
    path = os.path.join(_REPO, ".bench_cache", f"warmmeta_{key}.json")
    cold = None
    try:
        with open(path) as f:
            cold = float(json.load(f)["cold_compile_s"])
    except Exception:
        pass
    if cold is not None and compile_t < cold and _CACHE_PREPOPULATED:
        credit_budget(cold - compile_t, "warm compile cache")
        return  # warm run: never tightens the cold baseline
    if cold is None or compile_t < cold:
        # first observation for this key, or a cheaper cold run:
        # record/tighten the baseline
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"cold_compile_s": round(compile_t, 3)}, f)
            os.replace(tmp, path)
        except Exception as e:
            log(f"warm meta write failed: {e}")


def cached_cpu_baseline(key: str, compute):
    """Disk cache for deterministic bench artifacts (CPU-baseline
    measurements, generated workloads).

    `compute()` returns a dict of numpy arrays/scalars; it is stored as an
    .npz under .bench_cache/ keyed by the workload tuple. The baselines are
    deterministic (fixed seeds), so re-measuring 3x34 s of NumPy per run
    was pure waste (VERDICT r4 task 1b). Timing numbers in the cache were
    measured once on this same host."""
    d = os.path.join(_REPO, ".bench_cache")
    path = os.path.join(d, key + ".npz")
    if os.path.exists(path):
        try:
            with np.load(path, allow_pickle=False) as z:
                out = {k: z[k] for k in z.files}
            log(f"bench cache HIT {key}")
            return out
        except Exception as e:
            log(f"bench cache unreadable ({e}); recomputing")
    out = compute()
    try:
        os.makedirs(d, exist_ok=True)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez(f, **out)
        os.replace(tmp, path)
        log(f"bench cache WROTE {key}")
    except Exception as e:
        log(f"bench cache write failed: {e}")
    return out


def _clustered(rng, n, extent, ncenters=64, frac_bg=0.1):
    """Mixture-of-Gaussians hotspots + uniform background — the shape of
    real GDELT/AIS data (heavily clustered; auto_grid_params documents
    ~10x cell skew). Zipf-ish center weights make a few hotspots dominate,
    which is the worst case for grid indexes and density scatter."""
    x0, y0, x1, y1 = extent
    w = 1.0 / np.arange(1, ncenters + 1) ** 1.1
    w /= w.sum()
    cx = rng.uniform(x0, x1, ncenters)
    cy = rng.uniform(y0, y1, ncenters)
    assign = rng.choice(ncenters, n, p=w)
    sx = (x1 - x0) / 150.0
    sy = (y1 - y0) / 150.0
    x = cx[assign] + rng.normal(0, sx, n)
    y = cy[assign] + rng.normal(0, sy, n)
    bg = rng.random(n) < frac_bg
    x[bg] = rng.uniform(x0, x1, int(bg.sum()))
    y[bg] = rng.uniform(y0, y1, int(bg.sum()))
    # clip INSIDE the extent by an f32-safe margin: boundary clusters put
    # heavy mass exactly on the max edge, where f32 coordinate rounding
    # moves points across the half-open grid boundary (device drops them,
    # numpy's histogram2d last bin keeps them) and parity gates flap
    mx = (x1 - x0) * 1e-3
    my = (y1 - y0) * 1e-3
    return np.clip(x, x0 + mx, x1 - mx), np.clip(y, y0 + my, y1 - my), cx, cy


def _cpu_baseline(x, y, t, speed, qx, qy, k, bbox, t0, t1, repeats=3,
                  warm=True):
    """Vectorized NumPy: mask + argpartition kNN (per query, masked)."""
    from geomesa_tpu.engine.geodesy import haversine_m_np

    def run():
        mask = (
            (x >= bbox[0]) & (x <= bbox[2]) & (y >= bbox[1]) & (y <= bbox[3])
            & (t > t0) & (t < t1) & (speed > 5.0)
        )
        cx, cy = x[mask], y[mask]
        out = np.empty((len(qx), k))
        for i in range(len(qx)):
            d = haversine_m_np(qx[i], qy[i], cx, cy)
            if len(d) >= k:
                idx = np.argpartition(d, k - 1)[:k]
                out[i] = np.sort(d[idx])
            else:
                out[i, : len(d)] = np.sort(d)
                out[i, len(d):] = np.inf
        return int(mask.sum()), out

    if warm:
        run()  # warm caches
    best = np.inf
    for _ in range(repeats):
        s = time.perf_counter()
        count, dists = run()
        best = min(best, time.perf_counter() - s)
    return best, count, dists


def _morton64(x, y):
    """Store physical order: the SAME Z curve the Z2 index uses (one
    implementation — the bench's notion of 'store order' cannot drift
    from the store's)."""
    from geomesa_tpu.curve.z2 import Z2SFC

    return Z2SFC().index(x, y)


def _sync(out):
    """Force device completion. Under the remote-tunnel TPU platform
    `block_until_ready()` returns before execution finishes, so timings must
    instead fetch one scalar to host — that transfer cannot complete until
    the producing computation has."""
    import jax

    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf[(0,) * leaf.ndim])
    return out


def _timeit(fn, repeats=3, warm=True):
    if warm:
        fn()
    best = float("inf")
    for _ in range(repeats):
        s = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - s)
    return best


def _gen_admin_layer(rng, npoly, keep_rings=False):
    """OSM-admin-style disjoint polygon layer: one polygon per jittered
    grid cell, log-mixed edge counts (10..10k), ~10% with holes. Returns
    (x1, y1, x2, y2, pol, n_holes, rings) — rings per polygon only when
    keep_rings (the SQL path builds Geometry objects from them)."""
    side = int(np.ceil(np.sqrt(npoly)))
    cw, ch = 360.0 / side, 180.0 / side
    x1l, y1l, x2l, y2l, pol = [], [], [], [], []
    rings: list = []
    n_holes = 0
    ecounts = np.clip(
        np.round(10 ** rng.uniform(1, 4, npoly)).astype(int), 10, 10_000
    )
    pid = 0
    for gy in range(side):
        for gx in range(side):
            if pid >= npoly:
                break
            cx = -180 + (gx + 0.5) * cw + rng.uniform(-0.1, 0.1) * cw
            cy = -90 + (gy + 0.5) * ch + rng.uniform(-0.1, 0.1) * ch
            ne = int(ecounts[pid])
            th = np.sort(rng.uniform(0, 2 * np.pi, ne))
            # max lobe = 0.3*1.25 = 0.375*min(cw,ch) < 0.4*min(cw,ch) =
            # half the worst-case center separation (0.8 cell after the
            # +-0.1-cell jitter), so the layer is PROVABLY disjoint —
            # round 3 used 0.35*1.25 = 0.4375 and actually had 30
            # overlapping neighbor pairs (review finding; the parity
            # oracle is now XOR so overlap would be harmless anyway)
            rad = (0.3 * min(cw, ch)
                   * (1 + 0.25 * np.sin(3 * th + rng.uniform(0, 6))))
            ring = np.stack(
                [cx + rad * np.cos(th), cy + rad * np.sin(th)], 1)
            ring = np.concatenate([ring, ring[:1]])
            x1l.append(ring[:-1, 0]); y1l.append(ring[:-1, 1])
            x2l.append(ring[1:, 0]); y2l.append(ring[1:, 1])
            pol.append(np.full(ne, pid))
            prings = [ring]
            if rng.random() < 0.1:  # hole: reversed inner ring
                n_holes += 1
                nh = max(8, ne // 8)
                thh = np.sort(rng.uniform(0, 2 * np.pi, nh))[::-1]
                rh = rad.min() * 0.4
                hr = np.stack(
                    [cx + rh * np.cos(thh), cy + rh * np.sin(thh)], 1)
                hr = np.concatenate([hr, hr[:1]])
                x1l.append(hr[:-1, 0]); y1l.append(hr[:-1, 1])
                x2l.append(hr[1:, 0]); y2l.append(hr[1:, 1])
                pol.append(np.full(nh, pid))
                prings.append(hr)
            if keep_rings:
                rings.append(prings)
            pid += 1
    return (np.concatenate(x1l), np.concatenate(y1l),
            np.concatenate(x2l), np.concatenate(y2l),
            np.concatenate(pol), n_holes, rings)


def bench_pip_layer(n, repeats, npoly=10_000, smoke=False):
    """Config 2 (round 3): Within() over an OSM-admin-style polygon LAYER
    — npoly disjoint polygons (mixed 10..10k edges, ~10% with holes) x n
    points, via the sparse pair-list Pallas spatial join
    (engine/pip_sparse.py) with f64 refinement of boundary-band points.

    Replaces the round-1/2 single-star bench (VERDICT.md round-2 #5: the
    multi-polygon path was never benched as config 2 specifies). Points
    are Z-ordered (store layout) — that's what makes the point-tile
    bboxes tight and the pair pruning effective.

    Parity gate: 0 mismatches vs a NumPy f64 crossing oracle on a point
    subsample PLUS every adversarial near-edge point (placed within
    +-1e-6 deg of random edges)."""
    import jax.numpy as jnp

    from geomesa_tpu.engine.pip_sparse import (
        EDGE_TILE, POINT_TILE, pip_layer, pip_layer_grouped)

    rng = np.random.default_rng(29)
    x1, y1, x2, y2, pol, n_holes, _ = _gen_admin_layer(rng, npoly)

    px = rng.uniform(-180, 180, n)
    py = rng.uniform(-90, 90, n)
    # adversarial near-edge points (must be caught by the band + refined)
    na = min(n // 64, 100_000)
    ei = rng.integers(0, len(x1), na)
    tt = rng.uniform(0, 1, na)
    px[:na] = x1[ei] + tt * (x2[ei] - x1[ei]) + rng.uniform(-1e-6, 1e-6, na)
    py[:na] = y1[ei] + tt * (y2[ei] - y1[ei]) + rng.uniform(-1e-6, 1e-6, na)
    py[:na] = np.clip(py[:na], -90, 90)
    px[:na] = np.clip(px[:na], -180, 180)
    adv = np.zeros(n, bool)
    adv[:na] = True
    zo = np.argsort(_morton64(px, py))
    px, py, adv = px[zo], py[zo], adv[zo]

    # FIRST QUERY end-to-end (VERDICT r4 task 5): the prep build runs on a
    # worker thread behind the content-addressed disk cache
    # (.bench_cache/layerprep_*.npz — the prepared-geometry analog), and
    # the first full query (prep + kernel + f64 band refine) is timed as
    # one wall measurement. Cache hit: prep loads in ~0.1 s instead of the
    # ~5 s host build, so the first query stops being host-bound.
    import time as _t

    cdir = os.path.join(_REPO, ".bench_cache")
    key = None
    try:
        from geomesa_tpu.engine.pip_sparse import layer_prep_key

        key = layer_prep_key(px, py, x1, y1, x2, y2, pol)
        prep_cache_hit = os.path.exists(
            os.path.join(cdir, f"layerprep_{key}.npz"))
    except Exception:
        prep_cache_hit = False
    from geomesa_tpu.engine.pip_sparse import prepare_layer_async

    s0 = _t.perf_counter()
    prep_handle = prepare_layer_async(
        px, py, x1, y1, x2, y2, pol, cache_dir=cdir, key=key)
    # OVERLAP (the task-5 second half): the padded point upload depends
    # only on (px, py), so it rides the tunnel while the pair build runs
    # on the worker thread; pip_layer then reuses the device arrays
    npad = (-n) % POINT_TILE
    dev_pxp = jnp.asarray(
        np.concatenate([px, np.full(npad, 1e8)]), jnp.float32)
    dev_pyp = jnp.asarray(
        np.concatenate([py, np.full(npad, 1e8)]), jnp.float32)
    _sync(dev_pyp)
    upload_t = _t.perf_counter() - s0
    prep = prep_handle()
    prep_t = _t.perf_counter() - s0
    inside, info = pip_layer(px, py, x1, y1, x2, y2, pol, interpret=smoke,
                             prep=prep, points_device=(dev_pxp, dev_pyp))
    first_q_t = _t.perf_counter() - s0
    log(f"config2 first query e2e {first_q_t:.2f}s (prep "
        f"{'hit' if prep_cache_hit else 'miss'} {prep_t:.2f}s, upload "
        f"{upload_t:.2f}s overlapped)")

    # timed: the device pass over prebuilt pair structures (points ride
    # the pre-uploaded dev_pxp/dev_pyp — never re-upload in the loop)
    ex1, ey1, ex2, ey2 = prep.ex1, prep.ey1, prep.ex2, prep.ey2
    n_ptiles, n_etiles = prep.n_ptiles, prep.n_etiles
    plist = prep.pairs

    dev_args = (
        dev_pxp, dev_pyp,                    # device-resident: the timed
        jnp.asarray(ex1), jnp.asarray(ey1),  # loop must not re-upload
        jnp.asarray(ex2), jnp.asarray(ey2),  # through the 0.05 GB/s link
        plist.pair_pt, plist.pair_et,
    )

    def run():
        return pip_layer_grouped(
            *dev_args, n_ptiles=n_ptiles, n_etiles=n_etiles,
            interpret=smoke,
        )

    dev_t = _timeit(lambda: _sync(run()[0]), repeats)

    # net-of-tunnel (config-3 double-dispatch method): run() is ~one
    # pallas dispatch per capacity class, so wall includes several
    # 100-120ms tunnel RTTs and jitters run-to-run; the marginal of a
    # second back-to-back run isolates queue-resident execution
    def _dbl():
        run()
        _sync(run()[0])

    net = max(_timeit(_dbl, max(1, repeats - 1)) - dev_t, 1e-4)

    # oracle + CPU baseline: f64 crossing with the SAME pair pruning, on
    # a tile subsample + every adversarial point
    sub_tiles = rng.choice(
        np.nonzero(plist.covered)[0], min(64 if smoke else 256,
                                          int(plist.covered.sum())),
        replace=False,
    )
    et_of_pt: dict = {}
    for ptid, etid in zip(plist.pair_pt, plist.pair_et):
        et_of_pt.setdefault(int(ptid), []).append(int(etid))

    def cpu_tile(ptid):
        ets = et_of_pt.get(int(ptid), [])
        i0 = ptid * POINT_TILE
        ii = np.arange(i0, min(i0 + POINT_TILE, n))
        if not len(ii):
            return ii, np.zeros(0, bool)
        if not ets:
            return ii, np.zeros(len(ii), bool)
        sl = np.concatenate(
            [np.arange(e * EDGE_TILE, (e + 1) * EDGE_TILE) for e in ets])
        a1, b1, a2, b2 = ex1[sl], ey1[sl], ex2[sl], ey2[sl]
        pxi = px[ii][:, None]
        pyi = py[ii][:, None]
        condx = (b1[None] <= pyi) != (b2[None] <= pyi)
        ttt = (pyi - b1[None]) / np.where(b2 == b1, 1.0, b2 - b1)[None]
        xc = a1[None] + ttt * (a2 - a1)[None]
        return ii, (np.sum(condx & (xc > pxi), 1) % 2) == 1

    def cpu_pass():
        outs = []
        for ptid in sub_tiles:
            outs.append(cpu_tile(ptid))
        return outs

    cpu_t = _timeit(cpu_pass, max(1, repeats - 1))

    # ---- INDEPENDENT parity oracle (round-4 fix of the circular gate) --
    # Round 3 gated parity against cpu_tile, which evaluates the SAME
    # pruned pair list as the kernel — it could never catch a pair-build
    # bug (and didn't: the inverted x-prune shipped with "exact parity").
    # This oracle shares NOTHING with prepare_layer/build_pairs: per-
    # polygon f64 crossing parity over the ORIGINAL unpadded edge table,
    # candidate polygons by bbox containment computed here from raw edges.
    op = np.argsort(pol, kind="stable")
    xs1, ys1, xs2, ys2 = x1[op], y1[op], x2[op], y2[op]
    counts_o = np.unique(pol, return_counts=True)[1]
    starts_o = np.concatenate([[0], np.cumsum(counts_o)[:-1]])
    pbx0 = np.minimum.reduceat(np.minimum(xs1, xs2), starts_o)
    pby0 = np.minimum.reduceat(np.minimum(ys1, ys2), starts_o)
    pbx1 = np.maximum.reduceat(np.maximum(xs1, xs2), starts_o)
    pby1 = np.maximum.reduceat(np.maximum(ys1, ys2), starts_o)

    def oracle_all_edges(ii):
        """Inside-union for point indices ii, f64, all real edges of
        every bbox-candidate polygon."""
        out = np.zeros(len(ii), bool)
        pxi, pyi = px[ii], py[ii]
        for c0 in range(0, len(ii), 4096):
            sl_i = slice(c0, min(c0 + 4096, len(ii)))
            pc, qc = pxi[sl_i], pyi[sl_i]
            hitm = ((pc[:, None] >= pbx0[None]) & (pc[:, None] <= pbx1[None])
                    & (qc[:, None] >= pby0[None]) & (qc[:, None] <= pby1[None]))
            pt_k, po_k = np.nonzero(hitm)
            for k in np.unique(po_k):
                es = slice(starts_o[k], starts_o[k] + counts_o[k])
                a1, b1 = xs1[es], ys1[es]
                a2, b2 = xs2[es], ys2[es]
                pts = pt_k[po_k == k]
                pp = pc[pts][:, None]
                qq = qc[pts][:, None]
                condx = (b1[None] <= qq) != (b2[None] <= qq)
                ttt = (qq - b1[None]) / np.where(
                    b2 == b1, 1.0, b2 - b1)[None]
                xc = a1[None] + ttt * (a2 - a1)[None]
                ins = (np.sum(condx & (xc > pp), 1) % 2) == 1
                # XOR of per-polygon parities == total crossing parity
                # (the kernel's contract); identical to OR for disjoint
                # layers and still exact if any polygons overlap
                out[c0 + pts] ^= ins
        return out

    adv_idx = np.nonzero(adv)[0]
    check_idx = np.unique(np.concatenate([
        np.concatenate([
            np.arange(t * POINT_TILE, min((t + 1) * POINT_TILE, n))
            for t in sub_tiles
        ]),
        adv_idx,
    ]))
    exp_ind = oracle_all_edges(check_idx)
    mism = int((inside[check_idx] != exp_ind).sum())
    checked = int(len(check_idx))

    cpu_pps = len(sub_tiles) * POINT_TILE / cpu_t
    pps = n / dev_t
    return {
        "metric": "within_polygon_layer_point_polys_per_sec_per_chip",
        "value": round(pps * npoly, 1),
        "unit": "point*polygons/sec",
        "vs_baseline": round(pps / cpu_pps, 3),
        "detail": {
            "n": n, "polygons": npoly, "edges": int(len(x1)),
            "holes": n_holes,
            "points_per_sec": round(pps, 1),
            "device_time_s": round(dev_t, 5),
            "device_net_s": round(net, 5),
            "net_points_per_sec": round(n / net, 1),
            "pair_count": int(len(plist.pair_pt)),
            "pair_build_s": round(prep_t, 3),
            "prep_cache": "hit" if prep_cache_hit else "miss",
            "first_query_e2e_s": round(first_q_t, 3),
            "first_query_points_per_sec": round(n / first_q_t, 1),
            "adversarial_points": int(na),
            "flagged": info["flagged"], "refined": info["refined"],
            "checked": checked, "mismatches": mism,
            "parity": mism == 0,
            "cpu_points_per_sec": round(cpu_pps, 1),
            "cpu32_points_per_sec": round(cpu_pps * 32, 1),
            "vs_cpu32": round(pps / (cpu_pps * 32), 3),
            "vs_cpu32_net": round((n / net) / (cpu_pps * 32), 3),
            "note": "CPU TIMING baseline uses pair-pruned candidate sets "
                    "(overstates CPU speed => conservative ratio); the "
                    "PARITY gate is an INDEPENDENT all-edges f64 oracle "
                    "(bbox candidates from raw edges, nothing shared "
                    "with build_pairs) over the tile subsample plus "
                    "every adversarial near-edge point",
        },
    }


def bench_pip_layer_sql(n, repeats, npoly=10_000, smoke=False):
    """Config 2 THROUGH THE SQL SURFACE (round 5, VERDICT r4 task 7):
    `SELECT polys.pid, COUNT(*) FROM pts JOIN polys ON
    st_contains(polys.geom, pts.geom) GROUP BY polys.pid` against a real
    FS DataStore holding the 10k-polygon layer and the Z-ordered point
    batch — the same shape the engine-direct row runs. Parity: the SQL
    group-count total equals the engine-direct pip_layer_join pair count.
    Overhead: (t_sql - t_engine) / t_engine on warm caches, target <10%."""
    import shutil
    import tempfile
    import time as _t

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.core.wkt import Geometry
    from geomesa_tpu.engine.knn_scan import default_interpret
    from geomesa_tpu.engine.pip_sparse import (
        pip_layer_join, prepare_layer_cached)
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.sql.engine import SqlContext

    rng = np.random.default_rng(29)  # same layer/points as the direct row
    x1, y1, x2, y2, pol, n_holes, rings = _gen_admin_layer(
        rng, npoly, keep_rings=True)
    px = rng.uniform(-180, 180, n)
    py = rng.uniform(-90, 90, n)
    zo = np.argsort(_morton64(px, py))
    px, py = px[zo], py[zo]

    log(f"sql config2: building stores ({npoly} polys, {n / 1e6:.1f}M pts)")
    root = tempfile.mkdtemp(prefix="gmtpu_sqlbench_")
    try:
        ds = DataStore(root, use_device_cache=True)
        psft = SimpleFeatureType.from_spec("pts", "*geom:Point")
        psrc = ds.create_schema(psft)
        psrc.write(FeatureBatch.from_pydict(
            psft, {"geom": np.stack([px, py], 1)}))
        gsft = SimpleFeatureType.from_spec("polys", "pid:Integer,*geom:Polygon")
        gsrc = ds.create_schema(gsft)
        geoms = [Geometry("Polygon", pr) for pr in rings]
        gsrc.write(FeatureBatch.from_pydict(
            gsft, {"pid": np.arange(npoly, dtype=np.int64), "geom": geoms}))
        log("stores written; running SQL join (cold)")

        ctx = SqlContext(ds)
        q = ("SELECT polys.pid AS pid, COUNT(*) AS c FROM pts "
             "JOIN polys ON st_contains(polys.geom, pts.geom) "
             "GROUP BY polys.pid")
        s = _t.perf_counter()
        r_cold = ctx.sql(q)
        sql_cold_t = _t.perf_counter() - s
        log(f"sql cold {sql_cold_t:.2f}s; timing warm")
        sql_t = _timeit(lambda: ctx.sql(q), max(1, repeats - 1), warm=False)
        sql_total = int(np.asarray(r_cold.features.columns["c"]).sum())

        # engine-direct on the same arrays (warm prep via the same cache)
        args = (px, py, x1, y1, x2, y2, pol)
        prep = prepare_layer_cached(*args)
        interp = smoke or default_interpret()

        def direct():
            return pip_layer_join(*args, interpret=interp, prep=prep)

        pt_rows, poly_rows = direct()
        eng_t = _timeit(direct, max(1, repeats - 1), warm=False)
        eng_total = int(len(pt_rows))
        overhead = (sql_t - eng_t) / max(eng_t, 1e-9)
        return {
            "metric": "sql_spatial_join_points_per_sec_per_chip",
            "value": round(n / sql_t, 1),
            "unit": "points/sec",
            "vs_baseline": round(eng_t / sql_t, 3),
            "detail": {
                "n": n, "polygons": npoly, "holes": n_holes,
                "sql_cold_s": round(sql_cold_t, 3),
                "sql_warm_s": round(sql_t, 3),
                "engine_direct_s": round(eng_t, 3),
                "sql_overhead_frac": round(overhead, 4),
                "sql_overhead_ok": overhead < 0.10,
                "sql_pairs": sql_total,
                "engine_pairs": eng_total,
                "parity": sql_total == eng_total,
                "note": "SQL JOIN ON st_contains through SqlContext + FS "
                        "DataStore vs engine-direct pip_layer_join on the "
                        "same arrays; vs_baseline = engine/sql time ratio "
                        "(1.0 = zero overhead)",
            },
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_hw_smoke():
    """Hardware CI (VERDICT r3 #8): compile the REAL (non-interpret)
    Mosaic kernels at small shapes on the attached TPU and assert every
    parity gate — `python bench.py --hw-smoke`, one command, minutes.
    The pytest suite runs the same kernels in interpret mode on CPU;
    this is the compiled-path correctness gate that previously ran only
    inside full bench runs. Prints one JSON line; exit 0 iff all pass."""
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.geodesy import haversine_m_np

    rng = np.random.default_rng(97)
    gates = {}

    # 1. sparse + dense fused-scan kNN vs NumPy f64 oracle
    n, q, k = 1 << 20, 32, 5
    x = np.sort(rng.uniform(-60, 60, n))
    y = rng.uniform(-40, 40, n)
    mask = (x > -20) & (x < 20) & (rng.random(n) < 0.5)
    qx, qy = rng.uniform(-15, 15, q), rng.uniform(-30, 30, q)
    exp = np.empty((q, k))
    cx, cy = x[mask], y[mask]
    for i in range(q):
        d = haversine_m_np(qx[i], qy[i], cx, cy)
        exp[i] = np.sort(d[np.argpartition(d, k - 1)[:k]])
    jq = (jnp.asarray(qx, jnp.float32), jnp.asarray(qy, jnp.float32))
    jd = (jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
          jnp.asarray(mask))
    from geomesa_tpu.engine.knn_scan import knn_fullscan, knn_sparse_auto

    fd, fi, cap = knn_sparse_auto(*jq, *jd, k=k)
    gates["knn_sparse"] = bool(np.allclose(
        np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)) and cap > 0
    fd2, _ = knn_fullscan(*jq, *jd, k=k)
    gates["knn_fullscan"] = bool(np.allclose(
        np.sort(np.asarray(fd2), 1), exp, rtol=1e-4, atol=1.0))

    # 2. polygon-layer join (grouped) + per-polygon assignment vs f64
    from geomesa_tpu.engine.pip_sparse import pip_layer, pip_layer_assign

    th = np.linspace(0, 2 * np.pi, 700, endpoint=False)
    px1 = np.concatenate([10 * np.cos(th) - 20, 8 * np.cos(th) + 15])
    py1 = np.concatenate([10 * np.sin(th), 12 * np.sin(th) + 5])
    px2 = np.concatenate([np.roll(px1[:700], -1), np.roll(px1[700:], -1)])
    py2 = np.concatenate([np.roll(py1[:700], -1), np.roll(py1[700:], -1)])
    pol = np.concatenate([np.zeros(700, np.int64), np.ones(700, np.int64)])
    ppx = np.sort(rng.uniform(-35, 30, 1 << 15))
    ppy = rng.uniform(-15, 20, 1 << 15)
    inside, _info = pip_layer(ppx, ppy, px1, py1, px2, py2, pol)
    condx = (py1[None] <= ppy[:, None]) != (py2[None] <= ppy[:, None])
    tt = (ppy[:, None] - py1[None]) / np.where(
        py2 == py1, 1.0, py2 - py1)[None]
    xc = px1[None] + tt * (px2 - px1)[None]
    crossings_per = condx & (xc > ppx[:, None])
    exp_in = (crossings_per.sum(1) % 2) == 1
    gates["pip_layer"] = bool((inside == exp_in).all())
    pid, cnt, _ = pip_layer_assign(ppx, ppy, px1, py1, px2, py2, pol)
    exp_id = np.full(len(ppx), -1, np.int64)
    for p in (0, 1):
        m = pol == p
        ins = (crossings_per[:, m].sum(1) % 2) == 1
        exp_id[ins] = p
    gates["pip_assign"] = bool((pid == exp_id).all())

    # 3. z-sparse density vs the scatter kernel (exact for counts).
    # MORTON-ordered copy: x-sorted data sends every tile to the dense
    # fallback, silently skipping the sparse kernel's Mosaic compile
    # (exactly how the out-BlockSpec bug slipped past the first hw-smoke)
    from geomesa_tpu.engine.density import density_grid
    from geomesa_tpu.engine.density_zsparse import density_zsparse

    bbox = (-60.0, -40.0, 60.0, 40.0)
    zo = np.argsort(_morton64(x, y))
    zx = jnp.asarray(x[zo], jnp.float32)
    zy = jnp.asarray(y[zo], jnp.float32)
    w1 = jnp.ones(n, jnp.float32)
    dm = jnp.asarray(rng.random(n) < 0.8)
    g1, calib = density_zsparse(zx, zy, w1, dm, bbox, 256, 256)
    g2 = density_grid(zx, zy, w1, dm, bbox, 256, 256)
    gates["density_zsparse"] = bool(
        np.array_equal(np.asarray(g1), np.asarray(g2))
    ) and len(calib.tile_ids) > 0  # the sparse kernel actually compiled

    # 4. pruned tube vs dense tube
    from geomesa_tpu.engine.tube import tube_select, tube_select_pruned

    t_arr = rng.integers(0, 86_400_000, n)
    tubex = np.linspace(-30, 10, 64)
    tubey = np.linspace(-20, 20, 64)
    tubet = np.linspace(0, 86_400_000, 64).astype(np.int64)
    targs = (jd[0], jd[1], jnp.asarray(t_arr, jnp.int64),
             jnp.asarray(mask),
             jnp.asarray(tubex, jnp.float32), jnp.asarray(tubey, jnp.float32),
             jnp.asarray(tubet, jnp.int64),
             jnp.float32(50_000.0), jnp.int64(3_600_000))
    dense = np.asarray(tube_select(*targs))
    pruned, _cap = tube_select_pruned(*targs)
    gates["tube_pruned"] = bool(np.array_equal(np.asarray(pruned), dense))

    ok = all(gates.values())
    return {
        "metric": "hw_smoke_pass",
        "value": 1 if ok else 0,
        "unit": "bool",
        "vs_baseline": 1.0 if ok else 0.0,
        "detail": {"device": jax.devices()[0].platform, "gates": gates},
    }


def bench_pip(n, repeats):
    """Config 2 (legacy --single-polygon): Within() against ONE polygon."""
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.pip import points_in_polygon
    from geomesa_tpu.engine.pip_pallas import points_in_polygon_np_edges

    rng = np.random.default_rng(7)
    th = np.sort(rng.uniform(0, 2 * np.pi, 4096))
    radii = rng.uniform(20, 60, th.shape[0])
    ring = np.stack([radii * np.cos(th), radii * np.sin(th)], 1)
    ring = np.concatenate([ring, ring[:1]], 0)
    x1, y1 = ring[:-1, 0], ring[:-1, 1]
    x2, y2 = ring[1:, 0], ring[1:, 1]
    px = rng.uniform(-80, 80, n)
    py = rng.uniform(-80, 80, n)

    dev = [jnp.asarray(a, jnp.float32) for a in (px, py, x1, y1, x2, y2)]
    run = jax.jit(lambda *a: points_in_polygon(*a))
    dev_t = _timeit(lambda: _sync(run(*dev)), repeats)

    # CPU baseline: chunked NumPy f64 crossing number, measured on a point
    # subsample (the per-point cost is constant in n — O(E) each) and
    # reported as points/sec. Chunk size keeps the [chunk, E] intermediates
    # ~128MB so the baseline is compute-bound, not swap-bound.
    ncpu = min(n, 1 << 18)
    chunk = max(1024, (1 << 24) // max(len(x1), 1))

    def cpu():
        out = np.zeros(ncpu, bool)
        for off in range(0, ncpu, chunk):
            sl = slice(off, min(off + chunk, ncpu))
            out[sl] = points_in_polygon_np_edges(px[sl], py[sl], x1, y1, x2, y2)
        return out

    cpu_t = _timeit(cpu, max(1, repeats - 1))
    exp = cpu()
    got = np.asarray(run(*dev))[:ncpu]
    mismatch = int((got != exp).sum())
    cpu_pps = ncpu / cpu_t
    return {
        "metric": "within_pip_points_per_sec_per_chip",
        "value": round(n / dev_t, 1),
        "unit": "points/sec",
        "vs_baseline": round((n / dev_t) / cpu_pps, 3),
        "detail": {
            "n": n, "edges": len(x1), "device_time_s": round(dev_t, 5),
            "cpu_points": ncpu, "cpu_time_s": round(cpu_t, 5),
            "mismatch": mismatch,
            "parity": mismatch <= max(2, ncpu // 10000),
        },
    }


def bench_density(n, repeats, dist="uniform", order="store", smoke=False,
                  impl="zsparse"):
    """Config 4: DensityProcess 512x512 (NYC-TLC-style grid).

    Round 4: default kernel is the Z-locality Pallas path
    (engine/density_zsparse.py) — per-data-tile local one-hots in VMEM
    over the Morton-cell band the tile touches, with empty tiles pruned
    and span-overflow tiles routed to the dense MXU path. Requires
    store (Z) order to win (`--order store`, the layout every index scan
    emits; `--order random` exercises the fallback). Calibration (one
    small fetch) runs OUTSIDE the timed loop and is reused across
    queries, exactly like the sparse kNN tile capacity. Baseline: the
    round-3 methodology — measured single-core np.histogram2d x 32
    (perfect scaling, the worst case for the device ratio)."""
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.density import density_grid_auto
    from geomesa_tpu.engine.density_zsparse import density_zsparse

    rng = np.random.default_rng(11)
    if dist == "clustered":
        x, y, _, _ = _clustered(rng, n, (-74.3, 40.5, -73.7, 41.0))
    else:
        x = rng.uniform(-74.3, -73.7, n)
        y = rng.uniform(40.5, 41.0, n)
    if order == "store":
        zo = np.argsort(_morton64(x, y))
        x, y = x[zo], y[zo]
    w = rng.uniform(0, 5, n).astype(np.float32)
    bbox = (-74.3, 40.5, -73.7, 41.0)
    W = H = 512

    dx = jnp.asarray(x, jnp.float32)
    dy = jnp.asarray(y, jnp.float32)
    dw = jnp.asarray(w)
    m = jnp.ones(n, bool)
    if impl == "zsparse":
        _, calib = density_zsparse(
            dx, dy, dw, m, bbox, W, H, interpret=smoke)

        def run(a, b, c, d):
            # check_stale=False: the timed loop repeats the IDENTICAL
            # query, so the stale-plan mass check (one extra reduction +
            # fetch) is provably unneeded here
            return density_zsparse(
                a, b, c, d, bbox, W, H, calib=calib, interpret=smoke,
                check_stale=False,
            )[0]
    else:  # round-2 dense MXU / scatter dispatch
        run = jax.jit(
            lambda a, b, c, d: density_grid_auto(a, b, c, d, bbox, W, H))
    dev_t = _timeit(lambda: _sync(run(dx, dy, dw, m)), repeats)
    # net-of-tunnel via the double-dispatch marginal (config-3 method)
    def dbl():
        run(dx, dy, dw, m)
        _sync(run(dx, dy, dw, m))

    net = max(_timeit(dbl, 1 if smoke else 3) - dev_t, 1e-4)

    def cpu():
        g, _, _ = np.histogram2d(
            y, x, bins=(H, W),
            range=((bbox[1], bbox[3]), (bbox[0], bbox[2])), weights=w,
        )
        return g

    cpu_t = _timeit(cpu, max(1, repeats - 1))
    cpu_pps = n / cpu_t
    grid_dev = np.asarray(run(dx, dy, dw, m))
    grid_cpu = cpu()
    # histogram2d puts top-edge values in the last bin; compare total mass
    mass_ok = abs(grid_dev.sum() - grid_cpu.sum()) / max(grid_cpu.sum(), 1) < 1e-3
    # Two-part cells gate (round 5). Both gates compare the two DEVICE
    # kernels — identical binning by construction (a host-emulated f32
    # reference cannot match it: --xla_allow_excess_precision lets XLA
    # compile the f32 division as reciprocal-multiply, so boundary
    # points rebin by one cell vs IEEE division):
    #  (a) EXACT integer parity of the unweighted count grid — counts
    #      are f32-exact below 2^24 per cell, so any dropped/duplicated
    #      point is a hard mismatch (this is the data-loss gate);
    #  (b) weighted zsparse vs weighted scatter within per-cell
    #      summation-order noise: f32 accumulation of c addends walks
    #      ~ sqrt(c) * eps32 * mass (clustered hot cells hold ~1e6
    #      points = 2e-4 relative, far beyond any flat rtol); bound =
    #      5x headroom over eps32 = 6e-8 plus a 0.5 absolute floor.
    from geomesa_tpu.engine.density import density_grid as _scatter

    ones = jnp.ones_like(dw)
    if impl == "zsparse":
        cnt_dev = np.asarray(density_zsparse(
            dx, dy, ones, m, bbox, W, H, interpret=smoke)[0])
    else:
        cnt_dev = np.asarray(run(dx, dy, ones, m))
    cnt_ref = np.asarray(_scatter(dx, dy, ones, m, bbox, W, H))
    count_exact = bool(np.array_equal(cnt_dev, cnt_ref))
    grid_ref = np.asarray(
        _scatter(dx, dy, dw, m, bbox, W, H), np.float64)
    tol = 3e-7 * np.sqrt(np.maximum(cnt_ref, 1.0)) * np.abs(grid_ref) + 0.5
    cell_ok = count_exact and bool(
        (np.abs(grid_dev - grid_ref) <= tol).all())
    pps = n / dev_t
    out = {
        "metric": "density_512_points_per_sec_per_chip",
        "value": round(pps, 1),
        "unit": "points/sec",
        "vs_baseline": round(pps / (cpu_pps * 32), 3),
        "detail": {
            "n": n, "grid": f"{W}x{H}", "dist": dist, "order": order,
            "impl": impl,
            "device_time_s": round(dev_t, 5),
            "device_net_s": round(net, 5),
            "net_points_per_sec": round(n / net, 1),
            "vs_cpu32_net": round((n / net) / (cpu_pps * 32), 3),
            "cpu_time_s": round(cpu_t, 5),
            "cpu_points_per_sec": round(cpu_pps, 1),
            "cpu32_points_per_sec": round(cpu_pps * 32, 1),
            "vs_1core": round(pps / cpu_pps, 3),
            "baseline": "32-vCPU perfect-scaling extrapolation of "
                        "measured single-core np.histogram2d",
            "grid_mass_parity": bool(mass_ok),
            "grid_cells_parity": cell_ok,
            "count_grid_exact": count_exact,
        },
    }
    if impl == "zsparse":
        out["detail"]["sparse_tiles"] = int(len(calib.tile_ids))
        out["detail"]["dense_fallback_tiles"] = int(len(calib.dense_ids))
        out["detail"]["tiles_total"] = int(calib.n_tiles)
        out["detail"]["dict_capd"] = int(calib.capd)
    return out


def bench_tube(n, repeats, order="store", impl="pruned"):
    """Config 5: TubeSelect trajectory join (AIS-convoy-style).

    Round 4: default kernel is the tile-pruned pass
    (engine/tube.py tube_select_pruned) — data tiles whose envelope
    misses every corridor segment's bbox+time reach are never scanned.
    Data is store (Z) ordered by default (index-scan layout; tile
    envelopes are tight there); `--order random` exercises the
    conservative fallback. Capacity calibrates on the first call and is
    reused across queries. Baseline: measured single-core NumPy
    haversine sweep (on a subsample — per-point cost is O(T), constant
    in n) x 32 perfect scaling."""
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.geodesy import haversine_m_np
    from geomesa_tpu.engine.tube import tube_select, tube_select_pruned

    rng = np.random.default_rng(13)
    x = rng.uniform(-10, 10, n)
    y = rng.uniform(50, 60, n)
    if order == "store":
        zo = np.argsort(_morton64(x, y))
        x, y = x[zo], y[zo]
    t = rng.integers(0, 86_400_000, n)
    T = 256  # tube samples along the track
    tx = np.linspace(-8, 8, T)
    ty = np.linspace(51, 59, T) + rng.normal(0, 0.05, T)
    tt = np.linspace(0, 86_400_000, T).astype(np.int64)
    radius = 20_000.0  # 20 km corridor
    half_win = 3_600_000  # 1 h

    m = jnp.ones(n, bool)
    dev = (
        jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
        jnp.asarray(t, jnp.int64), m,
        jnp.asarray(tx, jnp.float32), jnp.asarray(ty, jnp.float32),
        jnp.asarray(tt, jnp.int64),
        jnp.asarray(radius, jnp.float32), jnp.asarray(half_win, jnp.int64),
    )
    cap_used = None
    if impl == "pruned":
        # calibration outside the timed loop (planner-stats analog)
        _, cap_used = tube_select_pruned(*dev)
        cap = cap_used if cap_used > 0 else None

        def run(*a):
            if cap is None:  # calibration overflowed: dense
                return tube_select(*a)
            return tube_select_pruned(*a, tile_capacity=cap)[0]
    else:
        run = jax.jit(lambda *a: tube_select(*a))
    dev_t = _timeit(lambda: _sync(run(*dev)), repeats)

    def dbl():
        run(*dev)
        _sync(run(*dev))

    net = max(_timeit(dbl, 2) - dev_t, 1e-4)

    # CPU baseline on a subsample: the sweep's per-point cost is O(T),
    # independent of n
    ncpu = min(n, 1 << 20)

    def cpu_sub():
        hit = np.zeros(ncpu, bool)
        for i in range(T):
            d = haversine_m_np(tx[i], ty[i], x[:ncpu], y[:ncpu])
            hit |= (d <= radius) & (np.abs(t[:ncpu] - tt[i]) <= half_win)
        return hit

    cpu_t = _timeit(cpu_sub, max(1, repeats - 1))
    cpu_pps = ncpu / cpu_t

    # full-n oracle for parity (once, outside timing)
    def cpu_full():
        hit = np.zeros(n, bool)
        for i in range(T):
            d = haversine_m_np(tx[i], ty[i], x, y)
            hit |= (d <= radius) & (np.abs(t - tt[i]) <= half_win)
        return hit

    got = np.asarray(run(*dev))
    exp = cpu_full()
    # every mismatch must be an f32 radius-edge rounding: a sample within
    # the time window whose f64 distance sits within 1 m of the radius
    # (time compares are int64-exact on both sides, so they cannot differ)
    mm = np.nonzero(got != exp)[0]
    band_ok = True
    for i in mm:
        d = haversine_m_np(x[i], y[i], tx, ty)
        near = (np.abs(t[i] - tt) <= half_win) & (np.abs(d - radius) <= 1.0)
        if not near.any():
            band_ok = False
            break
    pps = n / dev_t
    return {
        "metric": "tube_select_points_per_sec_per_chip",
        "value": round(pps, 1),
        "unit": "points/sec",
        "vs_baseline": round(pps / (cpu_pps * 32), 3),
        "detail": {
            "n": n, "tube_samples": T, "order": order, "impl": impl,
            "device_time_s": round(dev_t, 5),
            "device_net_s": round(net, 5),
            "net_points_per_sec": round(n / net, 1),
            "vs_cpu32_net": round((n / net) / (cpu_pps * 32), 3),
            "cpu_time_s": round(cpu_t, 5), "cpu_subsample": ncpu,
            "cpu_points_per_sec": round(cpu_pps, 1),
            "cpu32_points_per_sec": round(cpu_pps * 32, 1),
            "vs_1core": round(pps / cpu_pps, 3),
            "baseline": "32-vCPU perfect-scaling extrapolation of "
                        "measured single-core NumPy haversine sweep",
            "parity": bool(len(mm) == 0 or band_ok),
            "mismatches": int(len(mm)),
            "mismatches_all_radius_edge": bool(band_ok),
            "matched": int(exp.sum()),
            **({"tile_capacity": cap_used} if cap_used is not None else {}),
        },
    }


def bench_polygon_density(n, repeats):
    """Config 6 (round-2): extended-geometry density — rasterize n
    polygons into a 512x512 grid (DensityScan line/polygon parity,
    SURVEY.md:258-259). Two measurements: the raw kernel at full n
    (vectorized CSR quads -> oriented edge table -> winding scatter +
    row cumsum) and the end-to-end planner path (XZ2-partitioned store ->
    density hint) at a store-friendly subset."""
    import jax.numpy as jnp

    from geomesa_tpu.engine.raster import (
        _pow2, polygon_density, polygon_rowspan_bound)

    rng = np.random.default_rng(23)
    bbox = (-60.0, -45.0, 60.0, 45.0)
    W = H = 512

    # vectorized CCW quads: center + half-sizes + rotation
    cx = rng.uniform(bbox[0], bbox[2], n)
    cy = rng.uniform(bbox[1], bbox[3], n)
    hw = rng.uniform(0.02, 0.15, n)
    hh = rng.uniform(0.02, 0.15, n)
    th = rng.uniform(0, np.pi / 2, n)
    base = np.array([[-1, -1], [1, -1], [1, 1], [-1, 1]], np.float64)
    cosr, sinr = np.cos(th), np.sin(th)
    # corners [n, 4, 2], CCW
    ux = base[None, :, 0] * hw[:, None]
    uy = base[None, :, 1] * hh[:, None]
    corx = cx[:, None] + ux * cosr[:, None] - uy * sinr[:, None]
    cory = cy[:, None] + ux * sinr[:, None] + uy * cosr[:, None]
    nxt = [1, 2, 3, 0]
    x1 = corx.reshape(-1)
    y1 = cory.reshape(-1)
    x2 = corx[:, nxt].reshape(-1)
    y2 = cory[:, nxt].reshape(-1)
    wedge = np.repeat(rng.uniform(0.5, 2.0, n), 4).astype(np.float32)
    efeat_weights = wedge  # per-edge owner weight
    kspan = _pow2(polygon_rowspan_bound(y1, y2, bbox, H) + 1)

    jx1, jy1 = jnp.asarray(x1, jnp.float32), jnp.asarray(y1, jnp.float32)
    jx2, jy2 = jnp.asarray(x2, jnp.float32), jnp.asarray(y2, jnp.float32)
    jw = jnp.asarray(efeat_weights)
    jm = jnp.ones(len(x1), bool)

    def run():
        return polygon_density(
            jx1, jy1, jx2, jy2, jw, jm, bbox, W, H, kspan
        )

    dev_t = _timeit(lambda: _sync(run()), repeats)
    grid = np.asarray(run())

    # CPU baseline: per-polygon cell-center coverage over the polygon's
    # bbox cells (the direct rasterizer a CPU implementation would use),
    # measured on a subsample and reported per polygon
    psub = min(n, 20_000)
    dx = (bbox[2] - bbox[0]) / W
    dy = (bbox[3] - bbox[1]) / H

    def cpu(limit=psub):
        g = np.zeros((H, W))
        for i in range(limit):
            xc = corx[i]
            yc = cory[i]
            c0 = max(int((xc.min() - bbox[0]) / dx), 0)
            c1 = min(int((xc.max() - bbox[0]) / dx) + 1, W)
            r0 = max(int((yc.min() - bbox[1]) / dy), 0)
            r1 = min(int((yc.max() - bbox[1]) / dy) + 1, H)
            if c1 <= c0 or r1 <= r0:
                continue
            ccx = bbox[0] + (np.arange(c0, c1) + 0.5) * dx
            ccy = bbox[1] + (np.arange(r0, r1) + 0.5) * dy
            gx, gy = np.meshgrid(ccx, ccy)
            inside = np.zeros(gx.shape, bool)
            for e in range(4):
                ax, ay = corx[i, e], cory[i, e]
                bx, by = corx[i, nxt[e]], cory[i, nxt[e]]
                cond = (ay <= gy) != (by <= gy)
                tpar = (gy - ay) / np.where(by == ay, 1.0, by - ay)
                xcr = ax + tpar * (bx - ax)
                inside ^= cond & (xcr > gx)
            g[r0:r1, c0:c1] += inside * efeat_weights[4 * i]
        return g

    last = {}

    def cpu_timed():
        last["grid"] = cpu()

    cpu_t = _timeit(cpu_timed, max(1, repeats - 1))
    cpu_grid = last["grid"]  # reuse the final timed run's result
    # parity on the subsample: device grid over the same subset
    sub_k = _pow2(polygon_rowspan_bound(y1[: 4 * psub], y2[: 4 * psub], bbox, H) + 1)
    sub_grid = np.asarray(
        polygon_density(
            jx1[: 4 * psub], jy1[: 4 * psub], jx2[: 4 * psub], jy2[: 4 * psub],
            jw[: 4 * psub], jm[: 4 * psub], bbox, W, H, sub_k,
        )
    )
    denom = max(cpu_grid.sum(), 1.0)
    mismatch_mass = float(np.abs(sub_grid - cpu_grid).sum() / denom)

    # end-to-end: XZ2 store -> planner -> device rasterization
    import shutil
    import tempfile

    from geomesa_tpu.core.columnar import FeatureBatch, GeometryColumn
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.plan.hints import QueryHints
    from geomesa_tpu.plan.query import Query
    from geomesa_tpu.store.partition import XZ2Scheme

    n_store = min(n, 50_000)  # WKT serialization bounds the store size
    verts = np.stack(
        [
            np.concatenate([corx[:n_store], corx[:n_store, :1]], 1).reshape(-1),
            np.concatenate([cory[:n_store], cory[:n_store, :1]], 1).reshape(-1),
        ],
        1,
    )
    col = GeometryColumn(
        "Polygon",
        corx[:n_store, 0],
        cory[:n_store, 0],
        verts,
        np.arange(0, 5 * n_store + 1, 5, dtype=np.int64),
        np.arange(0, n_store + 1, dtype=np.int64),
        [[1]] * n_store,
        np.stack(
            [corx[:n_store].min(1), cory[:n_store].min(1),
             corx[:n_store].max(1), cory[:n_store].max(1)], 1,
        ),
    )
    sft = SimpleFeatureType.from_spec("polys", "w:Double,*geom:Polygon")
    pb = FeatureBatch(
        sft, {"w": efeat_weights[:: 4][:n_store].astype(np.float64), "geom": col}
    )
    root = tempfile.mkdtemp(prefix="gmtpu_polybench_")
    try:
        ds = DataStore(root, use_device_cache=True)
        src = ds.create_schema(sft, XZ2Scheme(g=2))
        src.write(pb)
        q = Query(
            "polys", "INCLUDE",
            hints=QueryHints(
                density_bbox=bbox, density_width=W, density_height=H,
                density_weight="w",
            ),
        )
        src.get_features(q)  # warm (compile + cache)
        e2e_t = _timeit(lambda: src.get_features(q), max(1, repeats - 1))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    cpu_pps = psub / cpu_t
    return {
        "metric": "polygon_density_polys_per_sec_per_chip",
        "value": round(n / dev_t, 1),
        "unit": "polygons/sec",
        "vs_baseline": round((n / dev_t) / cpu_pps, 3),
        "detail": {
            "n": n, "grid": f"{W}x{H}", "device_time_s": round(dev_t, 5),
            "cpu_polys": psub, "cpu_time_s": round(cpu_t, 5),
            "mismatch_mass_frac": round(mismatch_mass, 6),
            "parity": mismatch_mass < 1e-3,
            "store_polys": n_store,
            "e2e_query_time_s": round(e2e_t, 5),
            "e2e_polys_per_sec": round(n_store / e2e_t, 1),
            "note": "kernel at full n; e2e = XZ2 store -> planner -> "
                    "device rasterization at store_polys",
        },
    }


def bench_fs_query(n, repeats, tmpdir=None, cold=False):
    """Config 1: BBOX+time CQL through the full FS Parquet DataStore stack
    (plan -> prune -> parquet pushdown -> device residual mask), CPU
    baseline = the same filter in flat NumPy over the raw arrays."""
    import shutil
    import tempfile

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore

    rng = np.random.default_rng(17)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.integers(1_590_000_000_000, 1_600_000_000_000, n)
    score = rng.uniform(-10, 10, n)
    root = tmpdir or tempfile.mkdtemp(prefix="gmtpu_bench_")
    try:
        sft = SimpleFeatureType.from_spec(
            "gdelt", "score:Double,dtg:Date,*geom:Point"
        )
        ds = DataStore(root, use_device_cache=True)
        src = ds.create_schema(sft)
        src.write(FeatureBatch.from_pydict(
            sft, {"score": score, "dtg": t, "geom": np.stack([x, y], 1)}
        ))
        cql = ("BBOX(geom, -60, 20, 60, 70) AND score > 0 AND "
               "dtg DURING 2020-06-13T00:00:00Z/2020-08-21T00:00:00Z")
        q_t = _timeit(lambda: src.get_count(cql), repeats)
        count = src.get_count(cql)
        cold_t = None
        if cold:
            # cold path: a fresh store with NO device cache — every query
            # pays parquet read -> host columnar -> device transfer ->
            # mask (the honest end-to-end number the round-1 review asked
            # for; SURVEY.md:834-835 both-ways obligation)
            ds_cold = DataStore(root, use_device_cache=False)
            src_cold = ds_cold.get_feature_source("gdelt")
            cold_t = _timeit(
                lambda: src_cold.get_count(cql), max(1, repeats - 1)
            )
            assert src_cold.get_count(cql) == count

        import datetime as _dt

        def _ms(s):
            return int(_dt.datetime.fromisoformat(s).timestamp() * 1000)

        lo, hi = _ms("2020-06-13T00:00:00+00:00"), _ms("2020-08-21T00:00:00+00:00")

        # CPU baseline per BASELINE.json config 1: the same query through a
        # well-implemented Parquet scan path on CPU — pyarrow dataset with
        # row-group predicate pushdown (SURVEY §7 "honest CPU baseline").
        import pyarrow as pa
        import pyarrow.dataset as pads
        import pyarrow.parquet as papq

        cpu_dir = os.path.join(root, "_cpu_parquet")
        os.makedirs(cpu_dir, exist_ok=True)
        papq.write_table(
            pa.table({"x": x, "y": y, "score": score, "dtg": t}),
            os.path.join(cpu_dir, "data.parquet"),
            row_group_size=1 << 16,
        )
        fld = pads.field

        def cpu():
            dset = pads.dataset(cpu_dir, format="parquet")
            expr = (
                (fld("x") >= -60) & (fld("x") <= 60)
                & (fld("y") >= 20) & (fld("y") <= 70)
                & (fld("score") > 0) & (fld("dtg") > lo) & (fld("dtg") < hi)
            )
            return dset.scanner(filter=expr, columns=["x"]).count_rows()

        cpu_t = _timeit(cpu, max(1, repeats - 1))

        # overhead-free lower bound: the same mask over in-memory arrays
        def rawmask():
            m = ((x >= -60) & (x <= 60) & (y >= 20) & (y <= 70)
                 & (score > 0) & (t > lo) & (t < hi))
            return int(m.sum())

        raw_t = _timeit(rawmask, max(1, repeats - 1))
        parity = cpu() == count == rawmask()

        # net-of-tunnel device time for the residual mask + count over the
        # cached superbatch (double-dispatch marginal, config-3 method):
        # the warm q_t on this environment is tunnel-RTT-bound (~110 ms
        # per query against a ~ms device pass), so both are reported
        import jax
        import jax.numpy as jnp

        from geomesa_tpu.cql import parse_cql as _parse

        planner = src.planner
        sb = planner.cache.superbatch()
        compiled = planner._compile_cached(_parse(cql), sft)
        # device arrays must be ARGUMENTS, not closure captures: a
        # zero-arg jit embeds them as HLO constants and the remote
        # compile payload (hundreds of MB at 16M rows) broke the tunnel
        # pipe twice before this was diagnosed
        mask_fn = compiled.mask_fn()
        params = compiled.params(sb.batch)

        @jax.jit
        def _devcount(params, dev):
            return jnp.sum(mask_fn(params, dev), dtype=jnp.int32)

        one_t = _timeit(
            lambda: int(np.asarray(_devcount(params, sb.dev))), repeats)

        def _dbl():
            _devcount(params, sb.dev)
            int(np.asarray(_devcount(params, sb.dev)))

        net = max(_timeit(_dbl, repeats) - one_t, 1e-4)
        cpu_pps = n / cpu_t
        return {
            "metric": "fs_bbox_time_query_points_per_sec_per_chip",
            "value": round(n / q_t, 1),
            "unit": "points/sec",
            "vs_baseline": round((n / q_t) / (cpu_pps * 32), 3),
            "detail": {
                "n": n, "matched": count, "device_time_s": round(q_t, 5),
                "device_net_s": round(net, 5),
                "net_points_per_sec": round(n / net, 1),
                "vs_cpu32_net": round((n / net) / (cpu_pps * 32), 3),
                "cpu_parquet_time_s": round(cpu_t, 5),
                "cpu_points_per_sec": round(cpu_pps, 1),
                "cpu32_points_per_sec": round(cpu_pps * 32, 1),
                "vs_cpu32_wall": round((n / q_t) / (cpu_pps * 32), 3),
                "vs_1proc": round((n / q_t) / cpu_pps, 3),
                "baseline": "32-vCPU perfect-scaling extrapolation of the "
                            "measured pyarrow row-group-pushdown scan",
                "cpu_rawmask_time_s": round(raw_t, 5),
                "parity": bool(parity),
                **(
                    {
                        "cold_time_s": round(cold_t, 5),
                        "cold_points_per_sec": round(n / cold_t, 1),
                        "cold_vs_cpu": round((n / cold_t) / (n / cpu_t), 3),
                    }
                    if cold_t is not None
                    else {}
                ),
                "note": "end-to-end HBM-resident DataStore query (plan + "
                        "residual mask + device count) vs pyarrow Parquet "
                        "predicate-pushdown scan on CPU (BASELINE config 1); "
                        "cpu_rawmask is the no-IO in-memory lower bound; "
                        "cold_* (with --cold) pays parquet->host->device "
                        "every query",
            },
        }
    finally:
        if tmpdir is None:
            shutil.rmtree(root, ignore_errors=True)


def bench_stream(n_total, batches, q, k, repeats=2, smoke=False):
    """Config 3 at the GDELT-1B scale: N points streamed through HBM as
    `batches` Z-ordered superbatches with an exact cross-batch top-k merge.

    16 GB of HBM cannot hold 2^30 x 20 B, so each superbatch is produced,
    scanned (mask + sparse kNN), folded into the running top-k, and
    dropped; JAX's async dispatch overlaps production of batch b+1 with
    the scan of batch b (the double-buffering the round-2 review asked
    for). Exactness of the merge: the global top-k is a subset of the
    union of per-batch top-ks (same argument as knn_sharded's gather).

    Superbatch source: the tunnel's host->device path measures 0.05 GB/s
    (BASELINE.md round-3 notes), which makes HOST-streamed staging an
    environment artifact (~400 s for 20 GB), so the stream is produced
    ON DEVICE by inverse-Morton decode of sequential 32-bit Z keys with
    per-key jitter: batch b holds keys [b*2^32/B, (b+1)*2^32/B) — exactly
    a Z-ordered store partition (uniform world coverage, Z-sorted by
    construction, matching the layout an FS/KV partition scan emits).
    The CPU oracle regenerates identical batches host-side (bit-identical
    integer pipeline) and streams the same mask + argpartition merge.
    """
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.knn import _topk_smallest
    from geomesa_tpu.engine.knn_scan import DATA_TILE, knn_sparse_scan

    nb = n_total // batches
    BBOX = (-60.0, 20.0, 60.0, 70.0)
    T0, T1 = 1_592_000_000_000, 1_598_000_000_000
    rng = np.random.default_rng(42)
    qx = rng.uniform(-30, 30, q)
    qy = rng.uniform(30, 60, q)
    dqx = jnp.asarray(qx, jnp.float32)
    dqy = jnp.asarray(qy, jnp.float32)

    KEY_STEP = (1 << 32) // n_total  # z-key stride per point

    def unmorton_np(z):
        def squash(v):
            v = v & np.uint64(0x5555555555555555)  # NOT &=: aliases caller
            v = (v | (v >> 1)) & np.uint64(0x3333333333333333)
            v = (v | (v >> 2)) & np.uint64(0x0F0F0F0F0F0F0F0F)
            v = (v | (v >> 4)) & np.uint64(0x00FF00FF00FF00FF)
            v = (v | (v >> 8)) & np.uint64(0x0000FFFF0000FFFF)
            v = (v | (v >> 16)) & np.uint64(0x00000000FFFFFFFF)
            return v

        return squash(z), squash(z >> np.uint64(1))

    def gen_np(b):
        """Host twin of gen(): identical integer arithmetic."""
        i = np.arange(nb, dtype=np.uint64) + np.uint64(b * nb)
        # splitmix-style per-index hash for jitter + attributes
        h = (i * np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
        h ^= h >> np.uint64(31)
        h = (h * np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(0xFFFFFFFFFFFFFFFF)
        h ^= h >> np.uint64(29)
        z = i * np.uint64(KEY_STEP) + (h % np.uint64(KEY_STEP))
        gx, gy = unmorton_np(z & np.uint64(0xFFFFFFFF))
        # 16-bit cell + in-cell jitter from higher hash bits. Arithmetic
        # is carried in FLOAT32 mirroring gen_dev op-for-op: the oracle's
        # coordinates must be bit-identical to the device batch or kNN
        # distances drift by meters and the recall gate flaps
        f32 = np.float32
        jx = ((h >> np.uint64(33)) & np.uint64(0xFFFF)).astype(f32) / f32(65536.0)
        jy = ((h >> np.uint64(49)) & np.uint64(0x7FFF)).astype(f32) / f32(32768.0)
        x = (gx.astype(f32) + jx) / f32(65536.0) * f32(360.0) - f32(180.0)
        y = (gy.astype(f32) + jy) / f32(65536.0) * f32(180.0) - f32(90.0)
        t = (np.uint64(1_590_000_000_000)
             + (h >> np.uint64(13)) % np.uint64(10_000_000_000)).astype(np.int64)
        speed = ((h >> np.uint64(7)) & np.uint64(0x3FF)).astype(f32) * f32(30.0 / 1024.0)
        return x, y, t, speed

    def gen_dev(off):
        i = jnp.arange(nb, dtype=jnp.uint64) + off
        h = i * jnp.uint64(0x9E3779B97F4A7C15)
        h ^= h >> jnp.uint64(31)
        h = h * jnp.uint64(0xBF58476D1CE4E5B9)
        h ^= h >> jnp.uint64(29)
        z = (i * jnp.uint64(KEY_STEP) + h % jnp.uint64(KEY_STEP)) & jnp.uint64(0xFFFFFFFF)

        def squash(v):
            v &= jnp.uint64(0x5555555555555555)
            v = (v | (v >> 1)) & jnp.uint64(0x3333333333333333)
            v = (v | (v >> 2)) & jnp.uint64(0x0F0F0F0F0F0F0F0F)
            v = (v | (v >> 4)) & jnp.uint64(0x00FF00FF00FF00FF)
            v = (v | (v >> 8)) & jnp.uint64(0x0000FFFF0000FFFF)
            v = (v | (v >> 16)) & jnp.uint64(0x00000000FFFFFFFF)
            return v

        gx = squash(z).astype(jnp.float32)
        gy = squash(z >> jnp.uint64(1)).astype(jnp.float32)
        jx = ((h >> jnp.uint64(33)) & jnp.uint64(0xFFFF)).astype(jnp.float32) / 65536.0
        jy = ((h >> jnp.uint64(49)) & jnp.uint64(0x7FFF)).astype(jnp.float32) / 32768.0
        x = (gx + jx) / 65536.0 * 360.0 - 180.0
        y = (gy + jy) / 65536.0 * 180.0 - 90.0
        t = (jnp.uint64(1_590_000_000_000)
             + (h >> jnp.uint64(13)) % jnp.uint64(10_000_000_000)).astype(jnp.int64)
        speed = ((h >> jnp.uint64(7)) & jnp.uint64(0x3FF)).astype(jnp.float32) * jnp.float32(30.0 / 1024.0)
        return x, y, t, speed

    # tile capacity: max tiles-hit across all batches (each batch is a
    # DIFFERENT Z-region, so per-batch selectivity varies from 0 to ~4x
    # the mean — planner-stats analog; overflow flags gate the run). The
    # calibration masks are also reused by the CPU oracle below.
    ntiles = -(-nb // DATA_TILE)  # ceil: nb below one tile still pads UP
    hit = 0
    for b in range(batches):
        xb, yb, tb, sb = gen_np(b)
        mb = ((xb >= BBOX[0]) & (xb <= BBOX[2]) & (yb >= BBOX[1])
              & (yb <= BBOX[3]) & (tb > T0) & (tb < T1) & (sb > 5.0))
        hit = max(hit, int(np.pad(mb, (0, ntiles * DATA_TILE - nb))
                           .reshape(ntiles, DATA_TILE).any(1).sum()))
    cap = max(64, 1 << int(np.ceil(np.log2(max(hit, 1) * 1.5))))

    @jax.jit
    def scan_batch(off, qx, qy):
        # off is a TRACED uint64 batch offset: one compile serves every
        # superbatch (a static index would recompile per batch — 16 x
        # ~70 s through the remote-compile tunnel)
        x, y, t, speed = gen_dev(off)
        m = ((x >= BBOX[0]) & (x <= BBOX[2]) & (y >= BBOX[1])
             & (y <= BBOX[3]) & (t > T0) & (t < T1) & (speed > 5.0))
        cnt = jnp.sum(m.astype(jnp.int64))
        fd, fi, ov = knn_sparse_scan(
            qx, qy, x, y, m, k=k, tile_capacity=cap,
            interpret=smoke,
        )
        return cnt, fd, fi.astype(jnp.int64) + off.astype(jnp.int64), ov

    @jax.jit
    def merge(bd, bi, fd, fi):
        pd = jnp.concatenate([bd, fd], axis=1)
        pi = jnp.concatenate([bi, fi], axis=1)
        md, sel = _topk_smallest(pd, k)
        return md, jnp.take_along_axis(pi, sel, axis=1)

    def run():
        bd = jnp.full((q, k), jnp.inf, jnp.float32)
        bi = jnp.zeros((q, k), jnp.int64)
        total = jnp.zeros((), jnp.int64)
        ovs = []
        for b in range(batches):
            cnt, fd, fi, ov = scan_batch(
                jnp.uint64(b) * jnp.uint64(nb), dqx, dqy)
            bd, bi = merge(bd, bi, fd, fi)
            total = total + cnt
            ovs.append(ov)
            if b % 2 == 1:
                # cap in-flight superbatches: each queued scan holds its
                # ~1.4 GB generated batch live; 16 queued programs exceed
                # HBM and the tunnel wedges under allocation pressure
                # instead of erroring. Two in flight still overlaps
                # generation/scan with dispatch latency.
                _sync(bd)
        _sync(bd)
        return bd, bi, total, ovs

    wall = _timeit(run, repeats)
    bd, bi, total, ovs = run()
    overflow = any(bool(o) for o in ovs)
    pps = n_total / wall

    # CPU oracle on a query subsample: stream the same batches host-side
    qs = min(q, 8 if smoke else 32)
    best_d = np.full((qs, k), np.inf)
    cpu_total = 0
    gen_t = mask_t = knn_t = 0.0
    for b in range(batches):
        s = time.perf_counter()
        x, y, t, speed = gen_np(b)
        gen_t += time.perf_counter() - s
        s = time.perf_counter()
        m = ((x >= BBOX[0]) & (x <= BBOX[2]) & (y >= BBOX[1])
             & (y <= BBOX[3]) & (t > T0) & (t < T1) & (speed > 5.0))
        cpu_total += int(m.sum())
        mask_t += time.perf_counter() - s
        s = time.perf_counter()
        from geomesa_tpu.engine.geodesy import haversine_m_np

        cx, cy = x[m], y[m]
        for i in range(qs):
            d = haversine_m_np(qx[i], qy[i], cx, cy)
            kk = min(k, len(d))
            if kk:
                dk = np.partition(d, kk - 1)[:kk]
                pool = np.concatenate([best_d[i], dk])
                best_d[i] = np.sort(pool)[:k]
        knn_t += time.perf_counter() - s
    cpu_wall = gen_t + mask_t + knn_t
    cpu_scan_pps = n_total / (mask_t + knn_t * q / max(qs, 1))

    got = np.sort(np.asarray(bd)[:qs], axis=1)
    exp = best_d
    finite = np.isfinite(exp)
    # gate BOTH distances and the match totals — an all-inf oracle (e.g.
    # a diverged generator twin) must not pass vacuously
    recall_ok = (
        bool(np.all(
            np.abs(got[finite] - exp[finite])
            <= np.maximum(1.0, 1e-4 * exp[finite])
        ))
        and not overflow
        and np.isfinite(exp).any()
        and abs(int(total) - cpu_total) <= max(2, n_total // 10**7)
    )
    cpu32 = cpu_scan_pps * 32

    return {
        "metric": "gdelt_1b_stream_bbox_time_knn_points_per_sec_per_chip",
        "value": round(pps, 1),
        "unit": "points/sec",
        "vs_baseline": round(pps / cpu32, 3),
        "detail": {
            "n_total": n_total, "batches": batches,
            "batch_points": nb, "queries": q, "k": k,
            "wall_s": round(wall, 4),
            "match_total": int(total), "cpu_match_total": cpu_total,
            "tile_capacity": cap, "tiles_hit_b0": hit,
            "overflow": overflow,
            "recall_parity_subsample": recall_ok,
            "recall_queries_checked": qs,
            "cpu_scan_points_per_sec": round(cpu_scan_pps, 1),
            "cpu32_points_per_sec": round(cpu32, 1),
            "cpu_oracle_wall_s": round(cpu_wall, 2),
            "note": "Z-ordered superbatches produced on device "
                    "(inverse-Morton of sequential keys — the layout a "
                    "store partition scan emits); host h2d measures "
                    "0.05 GB/s through the tunnel, so host staging is "
                    "environment-bound (documented in BASELINE.md); "
                    "exact cross-batch top-k merge; CPU oracle streams "
                    "bit-identical batches",
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--n", type=int, default=None)
    p.add_argument("--queries", type=int, default=None)
    p.add_argument("--k", type=int, default=10)
    p.add_argument(
        "--config", type=int, default=None, choices=[1, 2, 3, 4, 5, 6],
        help="BASELINE.json config to run (default: 3, the headline "
             "BBOX+time+kNN metric; 1=fs-query 2=pip 4=density 5=tube "
             "6=polygon-density rasterization)",
    )
    p.add_argument(
        "--dist", choices=["uniform", "clustered"], default="uniform",
        help="data distribution for configs 3/4: uniform (best case for "
             "grids) or clustered hotspots (GDELT/AIS shape, ~10x skew)",
    )
    p.add_argument(
        "--cold", action="store_true",
        help="config 1: ALSO time the cold path (parquet -> host -> "
             "device, no HBM residency) alongside the cached query",
    )
    p.add_argument(
        "--impl",
        choices=["sparse", "fullscan", "mxu", "grid", "compact",
                 "haversine", "process"],
        default="sparse",
        help="config-3 kNN kernel: sparse = Pallas fused scan over "
             "match-bearing data tiles only (default; 570M pts/s on "
             "store-ordered 67M batches at exact recall — see "
             "engine/knn_scan.py), fullscan = the dense Pallas scan "
             "(259M pts/s, order-independent), compact = XLA candidate "
             "compaction + MXU kNN (round-2 default, 105M), mxu = "
             "augmented-matmul ranking keys over the full batch, grid = "
             "device-built spatial index + certified neighborhood search "
             "(amortizes over many query rounds), haversine = "
             "elementwise VPU",
    )
    p.add_argument(
        "--single-polygon", action="store_true",
        help="config 2: run the legacy single-polygon kernel bench "
             "instead of the polygon-LAYER spatial join (default)",
    )
    p.add_argument(
        "--sql", action="store_true",
        help="config 2: run the layer join THROUGH the SQL surface "
             "(SELECT ... JOIN ON st_contains over a real FS DataStore) "
             "and report overhead vs the engine-direct row",
    )
    p.add_argument(
        "--npoly", type=int, default=None,
        help="config 2 layer size (default 10000; smoke 200)",
    )
    p.add_argument(
        "--stream", type=int, default=None, metavar="BATCHES",
        help="config 3 at streamed scale: run N points (default 2^30) as "
             "BATCHES Z-ordered superbatches through HBM with an exact "
             "cross-batch top-k merge (the GDELT-1B regime; see "
             "bench_stream). Typical: --stream 16",
    )
    p.add_argument(
        "--hw-smoke", action="store_true",
        help="hardware CI: compile the REAL Mosaic kernels at small "
             "shapes on the attached TPU and assert every parity gate "
             "(the pytest suite runs the same kernels in interpret mode "
             "on CPU); exit 0 iff all gates pass",
    )
    p.add_argument(
        "--order", choices=["store", "random"], default="store",
        help="config-3 batch layout: store = Z-ordered (the FS/KV "
             "store's physical layout — index scans emit key-ordered "
             "rows), random = shuffled (worst case for the sparse "
             "kernel's tile pruning; the CPU baseline is order-blind)",
    )
    args = p.parse_args(argv)

    if args.smoke:
        os.environ.setdefault("XLA_FLAGS", "")
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        from jax._src import xla_bridge as xb

        # drop only the axon factory (the env var alone does not stick —
        # the axon site pins it); the "tpu" factory must STAY registered
        # or pallas' tpu lowering registration fails at import
        xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")

    # per-backend cache subdirs (compilecache.persist) ended the old
    # smoke-vs-device machine-feature mismatch: CPU smoke runs now cache
    # safely alongside the TPU artifacts, so every mode enables it
    enable_compile_cache()
    log(f"bench start: argv={argv if argv is not None else sys.argv[1:]}, "
        f"budget={budget_total_s():.0f}s")

    # 1<<26 amortizes the remote-tunnel dispatch floor (~105ms/round trip)
    # over a GDELT-realistic batch; both sides scan the same n. Configs
    # whose CPU baseline is superlinear-or-heavy in n keep a smaller default
    # so a full 5-config sweep stays within a bench budget.
    per_config = {1: 1 << 24, 2: 1 << 22, 3: 1 << 26, 4: 1 << 26, 5: 1 << 22,
                  6: 1 << 20}
    n = args.n or (
        1 << 17 if args.smoke else per_config.get(args.config or 3, 1 << 26)
    )
    # smoke still needs >= 128 queries: below that knn_mxu falls back to the
    # haversine path and --impl mxu would never exercise the matmul kernel
    q = args.queries or (128 if args.smoke else 256)
    k = args.k
    repeats = 2 if args.smoke else 3

    if args.hw_smoke:
        out = bench_hw_smoke()
        print(json.dumps(out))
        return 0 if out["value"] else 1

    if args.stream:
        n_total = args.n or (1 << 17 if args.smoke else 1 << 30)
        out = bench_stream(
            n_total, args.stream, q, k,
            repeats=1 if args.smoke else 2, smoke=args.smoke,
        )
        print(json.dumps(out))
        return 0

    if args.config in (1, 2, 4, 5, 6):
        if args.config == 1:
            out = bench_fs_query(n, repeats, cold=args.cold)
        elif args.config == 4:
            out = bench_density(
                n, repeats, dist=args.dist, order=args.order,
                smoke=args.smoke,
                impl=("auto" if args.impl in ("mxu", "compact")
                      else "zsparse"),
            )
        elif args.config == 6:
            out = bench_polygon_density(n, repeats)
        elif args.config == 2 and args.sql:
            out = bench_pip_layer_sql(
                n, repeats,
                npoly=args.npoly or (200 if args.smoke else 10_000),
                smoke=args.smoke,
            )
        elif args.config == 2 and not args.single_polygon:
            out = bench_pip_layer(
                n, repeats,
                npoly=args.npoly or (200 if args.smoke else 10_000),
                smoke=args.smoke,
            )
        elif args.config == 5:
            out = bench_tube(
                n, repeats, order=args.order,
                impl=("dense" if args.impl == "fullscan" else "pruned"),
            )
        else:
            out = bench_pip(n, repeats)
        print(json.dumps(out))
        return 0

    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.knn import knn, knn_compact, knn_mxu

    log(f"generating {n / 1e6:.0f}M-point workload ({args.dist}, "
        f"{args.order} order)")

    def _gen_workload():
        rng = np.random.default_rng(42)
        if args.dist == "clustered":
            # hotspot mixture (AIS/GDELT shape); queries drawn NEAR
            # hotspots, where cell overflow and near-ties are the worst case
            x, y, cxs, cys = _clustered(rng, n, (-180.0, -90.0, 180.0, 90.0))
            pick = rng.integers(0, len(cxs), q)
            qx = np.clip(cxs[pick] + rng.normal(0, 1.0, q), -180, 180)
            qy = np.clip(cys[pick] + rng.normal(0, 1.0, q), -90, 90)
        else:
            x = rng.uniform(-180, 180, n)
            y = rng.uniform(-90, 90, n)
            qx = rng.uniform(-30, 30, q)
            qy = rng.uniform(30, 60, q)
        if args.order == "store":
            # the store's physical layout: curve-ordered keys (an index scan
            # emits rows in Z order). The CPU baseline runs on the SAME
            # arrays — its vectorized mask + argpartition are order-blind.
            zorder = np.argsort(_morton64(x, y))
            x, y = x[zorder], y[zorder]
        t = rng.integers(1_590_000_000_000, 1_600_000_000_000, n)
        speed = rng.uniform(0, 30, n)
        return {"x": x, "y": y, "t": t, "speed": speed,
                "qx": qx, "qy": qy}

    # Deterministic (seed 42) -> disk-cacheable; the Z-order argsort at 67M
    # is ~45 s of fixed cost the driver's budget shouldn't pay twice
    # (VERDICT r4 task 1: every fixed host cost cached or budget-gated).
    _wl = cached_cpu_baseline(
        f"wl_n{n}_q{q}_{args.dist}_{args.order}_s42", _gen_workload)
    x, y, t, speed, qx, qy = (
        _wl["x"], _wl["y"], _wl["t"], _wl["speed"], _wl["qx"], _wl["qy"])
    BBOX = (-60.0, 20.0, 60.0, 70.0)
    T0, T1 = 1_592_000_000_000, 1_598_000_000_000

    # --- device pipeline ---------------------------------------------------
    # "compact": two phases exactly like the reference's scan->analytics
    # split — (1) predicate mask + match count, (2) kNN over the compacted
    # matches only. The count crosses to host to pick the static capacity
    # bucket (pow2, jit-cache-stable); that round trip is part of the timed
    # pipeline. Other impls: one fused jit over the full batch.
    @jax.jit
    def mask_count(x, y, t, speed):
        mask = (
            (x >= BBOX[0]) & (x <= BBOX[2]) & (y >= BBOX[1]) & (y <= BBOX[3])
            & (t > T0) & (t < T1) & (speed > 5.0)
        )
        return mask, jnp.sum(mask.astype(jnp.int32))

    @jax.jit
    def device_step(x, y, t, speed, qx, qy):
        mask, count = mask_count(x, y, t, speed)
        if args.impl == "mxu":
            dists, idx = knn_mxu(qx, qy, x, y, mask, k=k)  # sorts+tiles itself
        else:
            dists, idx = knn(qx, qy, x, y, mask, k=k, query_tile=q)
        return count, dists

    from geomesa_tpu.utils.padding import next_pow2

    def compact_step(x, y, t, speed, qx, qy):
        mask, count = mask_count(x, y, t, speed)
        c = int(np.asarray(count))  # host round trip: capacity bucket
        cap = max(next_pow2(max(c, 1)), 1024)
        dists, idx, _overflow = knn_compact(qx, qy, x, y, mask, k=k, capacity=cap)
        return count, dists

    def grid_step(x, y, t, speed, qx, qy):
        # the index-scan shape: build the batch-resident grid index (one
        # device sort, amortized over every query round against the batch),
        # then certified neighborhood search + exact fallback. Grid sized
        # to the match count (one host fetch, like the compact impl).
        from geomesa_tpu.engine.grid_index import (
            auto_grid_params, knn_indexed)

        mask, count = mask_count(x, y, t, speed)
        g_edge, slots = auto_grid_params(int(np.asarray(count)))
        dists, idx = knn_indexed(
            qx, qy, x, y, mask, k=k, g=g_edge, ring_radius=2,
            cell_slots=slots,
        )
        return count, dists

    def sparse_step_factory():
        # planner-style capacity calibration OUTSIDE the timed loop: a
        # real deployment derives the tile capacity from index stats
        # (selectivity x tile count), keeps it across queries, and only
        # recomputes when the overflow flag fires. 25% slack + pow2
        # bucket; dead capacity programs skip the MXU (knn_scan.py).
        from geomesa_tpu.engine.knn_scan import (
            DATA_TILE, knn_fullscan, knn_sparse_scan)

        # the Mosaic kernels need real TPU lowering; --smoke (CPU) runs
        # them in pallas interpret mode at the same semantics
        interp = bool(args.smoke)

        if args.impl == "fullscan":
            @jax.jit
            def step(x, y, t, speed, qx, qy):
                mask, count = mask_count(x, y, t, speed)
                fd, fi = knn_fullscan(
                    qx, qy, x, y, mask, k=k, interpret=interp)
                return count, fd

            return step

        mask_np = (
            (x >= BBOX[0]) & (x <= BBOX[2]) & (y >= BBOX[1]) & (y <= BBOX[3])
            & (t > T0) & (t < T1) & (speed > 5.0)
        )
        ntiles = -(-n // DATA_TILE)
        mp = np.pad(mask_np, (0, ntiles * DATA_TILE - n))
        hit = int(mp.reshape(ntiles, DATA_TILE).any(1).sum())
        cap = max(64, 1 << int(np.ceil(np.log2(max(hit, 1) * 1.25))))
        overflow_seen = []

        @jax.jit
        def run(x, y, t, speed, qx, qy):
            mask, count = mask_count(x, y, t, speed)
            fd, fi, ov = knn_sparse_scan(
                qx, qy, x, y, mask, k=k, tile_capacity=cap,
                interpret=interp,
            )
            return count, fd, ov

        def step(x, y, t, speed, qx, qy):
            count, fd, ov = run(x, y, t, speed, qx, qy)
            overflow_seen.append(ov)
            return count, fd

        step.check = lambda: not any(bool(o) for o in overflow_seen)
        step.tile_capacity = cap
        step.tiles_hit = hit
        step.ntiles = ntiles
        return step

    def process_step_factory():
        """The PRODUCT path (VERDICT r3 #1): the same workload through
        KNearestNeighborSearchProcess.execute over a materialized
        FeatureBatch — ECQL parse → compiled device mask → sparse Pallas
        scan, with the process's own capacity/filter caches. Must land
        within ~10% of the raw sparse kernel row."""
        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.process.knn import KNearestNeighborSearchProcess

        sft = SimpleFeatureType.from_spec(
            "gdelt", "speed:Double,dtg:Date,*geom:Point")
        batch = FeatureBatch.from_pydict(
            sft, {"speed": speed, "dtg": t, "geom": np.stack([x, y], 1)})
        qsft = SimpleFeatureType.from_spec("q", "*geom:Point")
        queries = FeatureBatch.from_pydict(
            qsft, {"geom": np.stack([qx, qy], 1)})
        # the exact ISO renderings of T0/T1 (strict > and <, matching the
        # kernel rows and the CPU baseline bit-for-bit)
        iso = lambda ms: str(np.datetime64(ms, "ms")) + "Z"  # noqa: E731
        cql = (f"BBOX(geom, {BBOX[0]}, {BBOX[1]}, {BBOX[2]}, {BBOX[3]}) "
               f"AND dtg > {iso(T0)} AND dtg < {iso(T1)} AND speed > 5.0")
        proc = KNearestNeighborSearchProcess()
        # bookkeeping count measured ONCE outside the timed path (the
        # process itself never needs it; the kernel rows fuse it into
        # their jit, so charging a second dispatch here would double-bill
        # the tunnel RTT against the product row)
        count = mask_count(dx, dy, dt, dspeed)[1]

        def step(dx_, dy_, dt_, dspeed_, dqx_, dqy_):
            res = proc.execute(
                queries, batch, num_desired=k, cql_filter=cql,
                impl="sparse",
            )
            return count, res.distances_m

        return step

    log("uploading arrays to device (~1.3GB at 67M; tunnel h2d ~0.05GB/s)")
    dx = jnp.asarray(x, jnp.float32)
    dy = jnp.asarray(y, jnp.float32)
    dt = jnp.asarray(t, jnp.int64)
    dspeed = jnp.asarray(speed, jnp.float32)
    dqx = jnp.asarray(qx, jnp.float32)
    dqy = jnp.asarray(qy, jnp.float32)
    _sync(dspeed)
    log("upload done; building step")

    if args.impl == "process":
        step = process_step_factory()
    elif args.impl in ("sparse", "fullscan"):
        step = sparse_step_factory()
    else:
        step = {"compact": compact_step, "grid": grid_step}.get(
            args.impl, device_step
        )
    log("compiling + warming device pipeline")
    _warm_s = time.perf_counter()
    count, dists = step(dx, dy, dt, dspeed, dqx, dqy)
    _sync(dists)  # compile + warm
    warm_t = time.perf_counter() - _warm_s
    log(f"device pipeline warm in {warm_t:.1f}s; timing")
    reps = 2 if args.smoke else (5 if budget_remaining_s() > 60 else 2)
    best = np.inf
    for _ in range(reps):
        s = time.perf_counter()
        count, dists = step(dx, dy, dt, dspeed, dqx, dqy)
        _sync(dists)
        best = min(best, time.perf_counter() - s)
    tpu_pps = n / best
    # compile vs execute split for BENCH_r*.json (previously only the log
    # tail saw the ~134s warmup): compile_time_s is the first-call wall
    # minus one steady-state pass — the inline XLA cost a cold process
    # pays and a warm persistent cache mostly eliminates
    compile_t = max(warm_t - best, 0.0)
    # baseline key includes the platform: a CPU --smoke interpret
    # compile (~2s) and a TPU Mosaic compile (~120s) must never share
    # (or overwrite) one cold baseline
    warm_compile_credit(
        f"c3_{jax.devices()[0].platform}_{args.impl}_n{n}_q{q}_k{k}",
        compile_t)
    log(f"device best-of-{reps}: {best:.4f}s ({tpu_pps / 1e6:.0f}M pts/s)")

    # --- f64-exact match count (VERDICT r3 #5), host-side (round 5) --------
    # the device mask runs on f32 coords/speed, so rows within the f32 ulp
    # band of a bbox edge or the speed threshold can flip sides vs the f64
    # oracle. NumPy f32 comparisons are bit-identical to the device's, so
    # the whole band correction runs host-side: no extra device compile and
    # no gather round trips (round 4 spent a dedicated jit on this; its
    # compile contributed to the driver timeout).
    from geomesa_tpu.cql.compile import f32_ulp_band as _eps

    f32 = np.float32
    xf, yf, sf = x.astype(f32), y.astype(f32), speed.astype(f32)

    def mask_f32_host(sel=slice(None)):
        """Bit-identical host replica of the DEVICE predicate (f32
        compares + i64 time) — the ONE definition the band correction
        and the exact-recall gate both use (review finding: three inline
        copies risked silent drift from the mask the kernel scanned)."""
        return (
            (xf[sel] >= f32(BBOX[0])) & (xf[sel] <= f32(BBOX[2]))
            & (yf[sel] >= f32(BBOX[1])) & (yf[sel] <= f32(BBOX[3]))
            & (t[sel] > T0) & (t[sel] < T1) & (sf[sel] > f32(5.0))
        )

    band_np = (
        (np.abs(xf - f32(BBOX[0])) <= _eps(BBOX[0]))
        | (np.abs(xf - f32(BBOX[2])) <= _eps(BBOX[2]))
        | (np.abs(yf - f32(BBOX[1])) <= _eps(BBOX[1]))
        | (np.abs(yf - f32(BBOX[3])) <= _eps(BBOX[3]))
        | (np.abs(sf - f32(5.0)) <= _eps(5.0))
    )
    bidx = np.nonzero(band_np)[0]
    nband = int(len(bidx))
    match_exact = int(np.asarray(count))
    if nband:
        approx = int(np.sum(mask_f32_host(bidx)))
        exact = int(np.sum(
            (x[bidx] >= BBOX[0]) & (x[bidx] <= BBOX[2])
            & (y[bidx] >= BBOX[1]) & (y[bidx] <= BBOX[3])
            & (t[bidx] > T0) & (t[bidx] < T1) & (speed[bidx] > 5.0)
        ))
        match_exact += exact - approx
    log(f"band-exact count {match_exact} ({nband} band rows, host-refined)")

    # --- CPU baseline (disk-cached — deterministic workload) ---------------
    # measured single-core NumPy (mask + argpartition kNN) and the
    # extrapolated 32-vCPU row the north star names (BASELINE.json): 32x
    # perfect scaling — the WORST case for the device ratio, see
    # BASELINE.md for the Accumulo-iterator-vs-NumPy per-core argument
    ckey = f"c3_n{n}_q{q}_k{k}_{args.dist}_{args.order}_s42"

    def _compute_cpu():
        # ~2M pts/s measured => one repeat ~ n/2e6 s; only multi-repeat
        # when the budget clearly affords it
        est = n / 2e6
        creps = 1 if (args.smoke or budget_remaining_s() < 3.5 * est) else 3
        log(f"cpu baseline: {creps} repeat(s), ~{est:.0f}s each")
        ct, cc, cd = _cpu_baseline(
            x, y, t, speed, qx, qy, k, BBOX, T0, T1,
            repeats=creps, warm=creps > 1,
        )
        return {"cpu_time": ct, "cpu_count": cc, "cpu_dists": cd,
                "cpu_repeats": creps}

    cb = cached_cpu_baseline(ckey, _compute_cpu)
    if (not args.smoke
            and int(cb.get("cpu_repeats", 3)) < 3
            and budget_remaining_s() > 4.5 * float(cb["cpu_time"])):
        # a budget-squeezed earlier run cached a single repeat; upgrade to
        # best-of-3 and keep the MIN ever measured — the strongest CPU
        # baseline is the conservative ratio. cpu_repeats records what the
        # FRESH measurement actually ran (a budget dip mid-upgrade may
        # still produce 1 — review finding: never stamp 3 unearned).
        log("upgrading cached cpu baseline to best-of-3")
        fresh = _compute_cpu()
        merged = dict(fresh) if (
            float(fresh["cpu_time"]) < float(cb["cpu_time"])) else dict(cb)
        merged["cpu_time"] = min(float(fresh["cpu_time"]),
                                 float(cb["cpu_time"]))
        merged["cpu_repeats"] = max(int(fresh["cpu_repeats"]),
                                    int(cb.get("cpu_repeats", 1)))
        cb = merged
        try:
            d = os.path.join(_REPO, ".bench_cache")
            tmp = os.path.join(d, ckey + f".npz.tmp{os.getpid()}")
            with open(tmp, "wb") as f:
                np.savez(f, **cb)
            os.replace(tmp, os.path.join(d, ckey + ".npz"))
        except Exception as e:
            log(f"cache update failed: {e}")
    cpu_time = float(cb["cpu_time"])
    cpu_count = int(cb["cpu_count"])
    cpu_dists = np.asarray(cb["cpu_dists"])
    cpu_pps = n / cpu_time
    cpu32_pps = cpu_pps * 32

    # --- recall parity gate ------------------------------------------------
    got = np.sort(np.asarray(dists), axis=1)
    exp = np.sort(cpu_dists, axis=1)
    finite = np.isfinite(exp)
    recall_ok = bool(
        np.all(np.abs(got[finite] - exp[finite]) <= np.maximum(1.0, 1e-4 * exp[finite]))
    )
    if hasattr(step, "check"):
        recall_ok = recall_ok and step.check()  # no silent tile overflow

    # --- EXACT recall gate (round 5, VERDICT r4 task 10) -------------------
    # the tolerance gate above accepts f32 ties at the k-th boundary; this
    # gate re-runs the kernel at k+8 (one extra dispatch, outside the
    # timed loop), f64-re-ranks the candidates (knn_exact_refine) and
    # demands BIT-EXACT equality with the f64 oracle. Rows that still
    # differ must be attributable to the f32 predicate band (the device
    # scans the f32 mask; the oracle the f64 one) — each is re-checked
    # against a per-row f32-mask oracle, the band-refine pattern applied
    # at the k-th boundary.
    recall_exact = None
    certified = None
    if args.impl in ("sparse", "fullscan") and budget_remaining_s() > -60:
        try:
            from geomesa_tpu.engine.knn_scan import (
                knn_exact_refine, knn_fullscan, knn_sparse_auto)

            interp = bool(args.smoke)
            kp = k + 8
            dmask = mask_count(dx, dy, dt, dspeed)[0]
            if args.impl == "sparse":
                fdp, fip, _c = knn_sparse_auto(
                    dqx, dqy, dx, dy, dmask, k=kp,
                    tile_capacity=getattr(step, "tile_capacity", None),
                    interpret=interp)
            else:
                fdp, fip = knn_fullscan(
                    dqx, dqy, dx, dy, dmask, k=kp, interpret=interp)
            d64, idxr, cert = knn_exact_refine(
                qx, qy, x, y, np.asarray(fdp), np.asarray(fip), k)
            certified = bool(cert.all())
            mism = [i for i in range(q)
                    if not np.array_equal(d64[i], exp[i])]
            attributed = True
            if mism:
                from geomesa_tpu.engine.geodesy import haversine_m_np

                m32 = mask_f32_host()
                xm, ym = x[m32], y[m32]  # loop-invariant ~0.5GB gather
                for i in mism:
                    di = haversine_m_np(qx[i], qy[i], xm, ym)
                    kk2 = min(k, len(di))
                    oi = np.sort(np.partition(di, kk2 - 1)[:kk2])
                    ref = np.concatenate([oi, np.full(k - kk2, np.inf)])
                    if not np.array_equal(d64[i], ref):
                        attributed = False
                        break
            recall_exact = certified and attributed
            log(f"exact recall gate: certified={certified}, "
                f"{len(mism)} band-attributed rows, exact={recall_exact}")
        except Exception as e:
            log(f"exact recall gate failed to run ({e}); field omitted")

    detail = {
        "n": n,
        "queries": q,
        "k": k,
        "impl": args.impl,
        "order": args.order,
        "device": jax.devices()[0].platform,
        "device_time_s": round(best, 5),
        "compile_time_s": round(compile_t, 4),
        "execute_time_s": round(best, 5),
        "cpu_time_s": round(cpu_time, 5),
        "cpu_points_per_sec": round(cpu_pps, 1),
        "cpu32_points_per_sec": round(cpu32_pps, 1),
        "vs_1core": round(tpu_pps / cpu_pps, 3),
        "baseline": "32-vCPU perfect-scaling extrapolation "
                    "of measured single-core NumPy "
                    "(BASELINE.md round-3 notes)",
        "dist": args.dist,
        "match_count": match_exact,
        "match_count_f32": int(count),
        "band_rows": nband,
        "cpu_match_count": cpu_count,
        "count_exact": match_exact == cpu_count,
        "recall_parity": recall_ok,
        **({"recall_exact": recall_exact,
            "recall_certified": certified} if recall_exact is not None
           else {}),
        **(
            {"tiles_hit": step.tiles_hit,
             "tile_capacity": step.tile_capacity,
             "ntiles": step.ntiles}
            if hasattr(step, "tiles_hit") else {}
        ),
    }
    headline = {
        "metric": "gdelt_bbox_time_knn_points_per_sec_per_chip",
        "value": round(tpu_pps, 1),
        "unit": "points/sec",
        "vs_baseline": round(tpu_pps / cpu32_pps, 3),
        "detail": detail,
    }
    # HEADLINE OUT NOW: a timeout during the extras below still leaves the
    # driver a parseable last line (the richer reprint below upgrades it)
    print(json.dumps(headline), flush=True)
    log("headline printed; running budget-gated extras")

    # --- extras: phase accounting + sustained burst (budget-gated) ---------
    # The remote tunnel adds ~100-120ms (+-20ms jitter) per dispatched
    # step, which swamps a ~10ms kernel, so net device time is measured as
    # the DOUBLE-DISPATCH MARGINAL: two back-to-back dispatches queue on
    # device, and t(2 steps, 1 sync) - t(1 step) isolates pure execution
    # from the tunnel round trip.
    try:
        if budget_remaining_s() > 20:
            one = jnp.float32(1.0)
            triv = jax.jit(lambda a: a + 1)
            rtt = _timeit(lambda: _sync(triv(one)), 3 if args.smoke else 8)

            def dbl():
                step(dx, dy, dt, dspeed, dqx, dqy)
                _sync(step(dx, dy, dt, dspeed, dqx, dqy)[1])

            t_double = _timeit(dbl, 1 if args.smoke else 3)
            net = max(t_double - best, 1e-4)
            eff_gbps = n * 20 / net / 1e9  # 20 B/pt: x,y,speed f32 + t i64
            detail["phases"] = {
                "dispatch_rtt_s": round(rtt, 5),
                "device_net_s": round(net, 5),
                "method": "double-dispatch marginal (tunnel RTT "
                          "jitter exceeds kernel time)",
            }
            detail["effective_scan_gbps"] = round(eff_gbps, 2)
            detail["hbm_peak_frac"] = round(eff_gbps / 819.0, 4)
            log(f"net device {net:.4f}s, rtt {rtt:.4f}s")
        if budget_remaining_s() > 45:
            # mask_count standalone is a separate (cacheable) compile
            def mask_dbl():
                mask_count(dx, dy, dt, dspeed)
                _sync(mask_count(dx, dy, dt, dspeed)[1])

            mask_1 = _timeit(
                lambda: _sync(mask_count(dx, dy, dt, dspeed)[1]),
                1 if args.smoke else 3)
            mask_net = max(
                _timeit(mask_dbl, 1 if args.smoke else 3) - mask_1, 0.0)
            detail["phases"]["mask_net_s"] = round(mask_net, 5)
            detail["phases"]["knn_net_s"] = round(
                max(net - mask_net, 0.0), 5)
            log(f"mask net {mask_net:.4f}s")
        if budget_remaining_s() > 20:
            # sustained throughput: R steps in flight, one sync sweep —
            # the server regime where dispatch latency overlaps compute
            R = 2 if args.smoke else 6

            def burst():
                outs = [step(dx, dy, dt, dspeed, dqx, dqy)[1]
                        for _ in range(R)]
                for o in outs:
                    _sync(o)

            sus = _timeit(burst, 1 if args.smoke else 2)
            detail["sustained_points_per_sec"] = round(R * n / sus, 1)
            log(f"sustained {R * n / sus / 1e6:.0f}M pts/s")
        else:
            log(f"extras trimmed (budget {budget_remaining_s():.0f}s left)")
    except Exception as e:  # extras must never cost us the headline
        log(f"extras failed ({type(e).__name__}: {e}); headline stands")

    print(json.dumps(headline), flush=True)  # last-line-wins, richer
    return 0


if __name__ == "__main__":
    sys.exit(main())
