"""Benchmark: GDELT-style BBOX+time filter + kNN, TPU vs honest CPU baseline.

The north-star shape from BASELINE.json: post-index-scan predicate filtering
plus kNN analytics, measured as points/sec/chip. The CPU baseline is the
vectorized NumPy equivalent of the geomesa-fs Parquet scan path's compute
(config 1-style): full-width f64 mask + argpartition kNN — the strongest
simple CPU implementation we can field locally (see BASELINE.md build
obligation: measure, don't assert).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Usage: python bench.py [--smoke] [--n N] [--queries Q]
  --smoke: small sizes + force CPU (for CI; vs_baseline still computed)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _cpu_baseline(x, y, t, speed, qx, qy, k, bbox, t0, t1, repeats=3):
    """Vectorized NumPy: mask + argpartition kNN (per query, masked)."""
    from geomesa_tpu.engine.geodesy import haversine_m_np

    def run():
        mask = (
            (x >= bbox[0]) & (x <= bbox[2]) & (y >= bbox[1]) & (y <= bbox[3])
            & (t > t0) & (t < t1) & (speed > 5.0)
        )
        cx, cy = x[mask], y[mask]
        out = np.empty((len(qx), k))
        for i in range(len(qx)):
            d = haversine_m_np(qx[i], qy[i], cx, cy)
            if len(d) >= k:
                idx = np.argpartition(d, k - 1)[:k]
                out[i] = np.sort(d[idx])
            else:
                out[i, : len(d)] = np.sort(d)
                out[i, len(d):] = np.inf
        return int(mask.sum()), out

    run()  # warm caches
    best = np.inf
    for _ in range(repeats):
        s = time.perf_counter()
        count, dists = run()
        best = min(best, time.perf_counter() - s)
    return best, count, dists


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--n", type=int, default=None)
    p.add_argument("--queries", type=int, default=None)
    p.add_argument("--k", type=int, default=10)
    args = p.parse_args(argv)

    if args.smoke:
        import os

        os.environ.setdefault("XLA_FLAGS", "")
        import jax
        from jax._src import xla_bridge as xb

        for name in ("axon", "tpu"):
            xb._backend_factories.pop(name, None)
        jax.config.update("jax_platforms", "cpu")

    n = args.n or (1 << 17 if args.smoke else 1 << 22)
    q = args.queries or (32 if args.smoke else 256)
    k = args.k

    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.knn import knn

    rng = np.random.default_rng(42)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.integers(1_590_000_000_000, 1_600_000_000_000, n)
    speed = rng.uniform(0, 30, n)
    qx = rng.uniform(-30, 30, q)
    qy = rng.uniform(30, 60, q)
    BBOX = (-60.0, 20.0, 60.0, 70.0)
    T0, T1 = 1_592_000_000_000, 1_598_000_000_000

    # --- device pipeline (one fused jit: mask + kNN) ----------------------
    @jax.jit
    def device_step(x, y, t, speed, qx, qy):
        mask = (
            (x >= BBOX[0]) & (x <= BBOX[2]) & (y >= BBOX[1]) & (y <= BBOX[3])
            & (t > T0) & (t < T1) & (speed > 5.0)
        )
        dists, idx = knn(qx, qy, x, y, mask, k=k, query_tile=q)
        return jnp.sum(mask.astype(jnp.int32)), dists

    dx = jnp.asarray(x, jnp.float32)
    dy = jnp.asarray(y, jnp.float32)
    dt = jnp.asarray(t, jnp.int64)
    dspeed = jnp.asarray(speed, jnp.float32)
    dqx = jnp.asarray(qx, jnp.float32)
    dqy = jnp.asarray(qy, jnp.float32)

    count, dists = device_step(dx, dy, dt, dspeed, dqx, dqy)
    count.block_until_ready()  # compile + warm
    best = np.inf
    for _ in range(5 if not args.smoke else 2):
        s = time.perf_counter()
        count, dists = device_step(dx, dy, dt, dspeed, dqx, dqy)
        jax.block_until_ready((count, dists))
        best = min(best, time.perf_counter() - s)
    tpu_pps = n / best

    # --- CPU baseline ------------------------------------------------------
    cpu_time, cpu_count, cpu_dists = _cpu_baseline(
        x, y, t, speed, qx, qy, k, BBOX, T0, T1,
        repeats=1 if args.smoke else 3,
    )
    cpu_pps = n / cpu_time

    # --- recall parity gate ------------------------------------------------
    got = np.sort(np.asarray(dists), axis=1)
    exp = np.sort(cpu_dists, axis=1)
    finite = np.isfinite(exp)
    recall_ok = bool(
        np.all(np.abs(got[finite] - exp[finite]) <= np.maximum(1.0, 1e-4 * exp[finite]))
    )

    print(
        json.dumps(
            {
                "metric": "gdelt_bbox_time_knn_points_per_sec_per_chip",
                "value": round(tpu_pps, 1),
                "unit": "points/sec",
                "vs_baseline": round(tpu_pps / cpu_pps, 3),
                "detail": {
                    "n": n,
                    "queries": q,
                    "k": k,
                    "device": jax.devices()[0].platform,
                    "device_time_s": round(best, 5),
                    "cpu_time_s": round(cpu_time, 5),
                    "cpu_points_per_sec": round(cpu_pps, 1),
                    "match_count": int(count),
                    "cpu_match_count": cpu_count,
                    "recall_parity": recall_ok,
                },
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
