"""Benchmark: GDELT-style BBOX+time filter + kNN, TPU vs honest CPU baseline.

The north-star shape from BASELINE.json: post-index-scan predicate filtering
plus kNN analytics, measured as points/sec/chip. The CPU baseline is the
vectorized NumPy equivalent of the geomesa-fs Parquet scan path's compute
(config 1-style): full-width f64 mask + argpartition kNN — the strongest
simple CPU implementation we can field locally (see BASELINE.md build
obligation: measure, don't assert).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Usage: python bench.py [--smoke] [--n N] [--queries Q]
  --smoke: small sizes + force CPU (for CI; vs_baseline still computed)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _clustered(rng, n, extent, ncenters=64, frac_bg=0.1):
    """Mixture-of-Gaussians hotspots + uniform background — the shape of
    real GDELT/AIS data (heavily clustered; auto_grid_params documents
    ~10x cell skew). Zipf-ish center weights make a few hotspots dominate,
    which is the worst case for grid indexes and density scatter."""
    x0, y0, x1, y1 = extent
    w = 1.0 / np.arange(1, ncenters + 1) ** 1.1
    w /= w.sum()
    cx = rng.uniform(x0, x1, ncenters)
    cy = rng.uniform(y0, y1, ncenters)
    assign = rng.choice(ncenters, n, p=w)
    sx = (x1 - x0) / 150.0
    sy = (y1 - y0) / 150.0
    x = cx[assign] + rng.normal(0, sx, n)
    y = cy[assign] + rng.normal(0, sy, n)
    bg = rng.random(n) < frac_bg
    x[bg] = rng.uniform(x0, x1, int(bg.sum()))
    y[bg] = rng.uniform(y0, y1, int(bg.sum()))
    # clip INSIDE the extent by an f32-safe margin: boundary clusters put
    # heavy mass exactly on the max edge, where f32 coordinate rounding
    # moves points across the half-open grid boundary (device drops them,
    # numpy's histogram2d last bin keeps them) and parity gates flap
    mx = (x1 - x0) * 1e-3
    my = (y1 - y0) * 1e-3
    return np.clip(x, x0 + mx, x1 - mx), np.clip(y, y0 + my, y1 - my), cx, cy


def _cpu_baseline(x, y, t, speed, qx, qy, k, bbox, t0, t1, repeats=3):
    """Vectorized NumPy: mask + argpartition kNN (per query, masked)."""
    from geomesa_tpu.engine.geodesy import haversine_m_np

    def run():
        mask = (
            (x >= bbox[0]) & (x <= bbox[2]) & (y >= bbox[1]) & (y <= bbox[3])
            & (t > t0) & (t < t1) & (speed > 5.0)
        )
        cx, cy = x[mask], y[mask]
        out = np.empty((len(qx), k))
        for i in range(len(qx)):
            d = haversine_m_np(qx[i], qy[i], cx, cy)
            if len(d) >= k:
                idx = np.argpartition(d, k - 1)[:k]
                out[i] = np.sort(d[idx])
            else:
                out[i, : len(d)] = np.sort(d)
                out[i, len(d):] = np.inf
        return int(mask.sum()), out

    run()  # warm caches
    best = np.inf
    for _ in range(repeats):
        s = time.perf_counter()
        count, dists = run()
        best = min(best, time.perf_counter() - s)
    return best, count, dists


def _sync(out):
    """Force device completion. Under the remote-tunnel TPU platform
    `block_until_ready()` returns before execution finishes, so timings must
    instead fetch one scalar to host — that transfer cannot complete until
    the producing computation has."""
    import jax

    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf[(0,) * leaf.ndim])
    return out


def _timeit(fn, repeats=3, warm=True):
    if warm:
        fn()
    best = float("inf")
    for _ in range(repeats):
        s = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - s)
    return best


def bench_pip(n, repeats):
    """Config 2: Within() point-in-polygon (OSM-admin-style polygon)."""
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.pip import points_in_polygon
    from geomesa_tpu.engine.pip_pallas import points_in_polygon_np_edges

    rng = np.random.default_rng(7)
    th = np.sort(rng.uniform(0, 2 * np.pi, 4096))
    radii = rng.uniform(20, 60, th.shape[0])
    ring = np.stack([radii * np.cos(th), radii * np.sin(th)], 1)
    ring = np.concatenate([ring, ring[:1]], 0)
    x1, y1 = ring[:-1, 0], ring[:-1, 1]
    x2, y2 = ring[1:, 0], ring[1:, 1]
    px = rng.uniform(-80, 80, n)
    py = rng.uniform(-80, 80, n)

    dev = [jnp.asarray(a, jnp.float32) for a in (px, py, x1, y1, x2, y2)]
    run = jax.jit(lambda *a: points_in_polygon(*a))
    dev_t = _timeit(lambda: _sync(run(*dev)), repeats)

    # CPU baseline: chunked NumPy f64 crossing number, measured on a point
    # subsample (the per-point cost is constant in n — O(E) each) and
    # reported as points/sec. Chunk size keeps the [chunk, E] intermediates
    # ~128MB so the baseline is compute-bound, not swap-bound.
    ncpu = min(n, 1 << 18)
    chunk = max(1024, (1 << 24) // max(len(x1), 1))

    def cpu():
        out = np.zeros(ncpu, bool)
        for off in range(0, ncpu, chunk):
            sl = slice(off, min(off + chunk, ncpu))
            out[sl] = points_in_polygon_np_edges(px[sl], py[sl], x1, y1, x2, y2)
        return out

    cpu_t = _timeit(cpu, max(1, repeats - 1))
    exp = cpu()
    got = np.asarray(run(*dev))[:ncpu]
    mismatch = int((got != exp).sum())
    cpu_pps = ncpu / cpu_t
    return {
        "metric": "within_pip_points_per_sec_per_chip",
        "value": round(n / dev_t, 1),
        "unit": "points/sec",
        "vs_baseline": round((n / dev_t) / cpu_pps, 3),
        "detail": {
            "n": n, "edges": len(x1), "device_time_s": round(dev_t, 5),
            "cpu_points": ncpu, "cpu_time_s": round(cpu_t, 5),
            "mismatch": mismatch,
            "parity": mismatch <= max(2, ncpu // 10000),
        },
    }


def bench_density(n, repeats, dist="uniform"):
    """Config 4: DensityProcess 512x512 (NYC-TLC-style grid)."""
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.density import density_grid_auto as density_grid

    rng = np.random.default_rng(11)
    if dist == "clustered":
        x, y, _, _ = _clustered(rng, n, (-74.3, 40.5, -73.7, 41.0))
    else:
        x = rng.uniform(-74.3, -73.7, n)
        y = rng.uniform(40.5, 41.0, n)
    w = rng.uniform(0, 5, n).astype(np.float32)
    bbox = (-74.3, 40.5, -73.7, 41.0)
    W = H = 512

    dx = jnp.asarray(x, jnp.float32)
    dy = jnp.asarray(y, jnp.float32)
    dw = jnp.asarray(w)
    m = jnp.ones(n, bool)
    run = jax.jit(lambda a, b, c, d: density_grid(a, b, c, d, bbox, W, H))
    dev_t = _timeit(lambda: _sync(run(dx, dy, dw, m)), repeats)

    def cpu():
        g, _, _ = np.histogram2d(
            y, x, bins=(H, W),
            range=((bbox[1], bbox[3]), (bbox[0], bbox[2])), weights=w,
        )
        return g

    cpu_t = _timeit(cpu, max(1, repeats - 1))
    grid_dev = np.asarray(run(dx, dy, dw, m))
    grid_cpu = cpu()
    # histogram2d puts top-edge values in the last bin; compare total mass
    mass_ok = abs(grid_dev.sum() - grid_cpu.sum()) / max(grid_cpu.sum(), 1) < 1e-3
    return {
        "metric": "density_512_points_per_sec_per_chip",
        "value": round(n / dev_t, 1),
        "unit": "points/sec",
        "vs_baseline": round((n / dev_t) / (n / cpu_t), 3),
        "detail": {
            "n": n, "grid": f"{W}x{H}", "dist": dist,
            "device_time_s": round(dev_t, 5),
            "cpu_time_s": round(cpu_t, 5), "grid_mass_parity": bool(mass_ok),
        },
    }


def bench_tube(n, repeats):
    """Config 5: TubeSelect trajectory join (AIS-convoy-style)."""
    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.geodesy import haversine_m_np
    from geomesa_tpu.engine.tube import tube_select

    rng = np.random.default_rng(13)
    x = rng.uniform(-10, 10, n)
    y = rng.uniform(50, 60, n)
    t = rng.integers(0, 86_400_000, n)
    T = 256  # tube samples along the track
    tx = np.linspace(-8, 8, T)
    ty = np.linspace(51, 59, T) + rng.normal(0, 0.05, T)
    tt = np.linspace(0, 86_400_000, T).astype(np.int64)
    radius = 20_000.0  # 20 km corridor
    half_win = 3_600_000  # 1 h

    m = jnp.ones(n, bool)
    dev = (
        jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
        jnp.asarray(t, jnp.int64), m,
        jnp.asarray(tx, jnp.float32), jnp.asarray(ty, jnp.float32),
        jnp.asarray(tt, jnp.int64),
        jnp.asarray(radius, jnp.float32), jnp.asarray(half_win, jnp.int64),
    )
    run = jax.jit(lambda *a: tube_select(*a))
    dev_t = _timeit(lambda: _sync(run(*dev)), repeats)

    def cpu():
        hit = np.zeros(n, bool)
        for i in range(T):
            d = haversine_m_np(tx[i], ty[i], x, y)
            hit |= (d <= radius) & (np.abs(t - tt[i]) <= half_win)
        return hit

    cpu_t = _timeit(cpu, max(1, repeats - 1))
    got = np.asarray(run(*dev))
    exp = cpu()
    return {
        "metric": "tube_select_points_per_sec_per_chip",
        "value": round(n / dev_t, 1),
        "unit": "points/sec",
        "vs_baseline": round((n / dev_t) / (n / cpu_t), 3),
        "detail": {
            "n": n, "tube_samples": T, "device_time_s": round(dev_t, 5),
            "cpu_time_s": round(cpu_t, 5),
            "parity": bool((got == exp).mean() > 0.9999),
            "matched": int(exp.sum()),
        },
    }


def bench_polygon_density(n, repeats):
    """Config 6 (round-2): extended-geometry density — rasterize n
    polygons into a 512x512 grid (DensityScan line/polygon parity,
    SURVEY.md:258-259). Two measurements: the raw kernel at full n
    (vectorized CSR quads -> oriented edge table -> winding scatter +
    row cumsum) and the end-to-end planner path (XZ2-partitioned store ->
    density hint) at a store-friendly subset."""
    import jax.numpy as jnp

    from geomesa_tpu.engine.raster import (
        _pow2, polygon_density, polygon_rowspan_bound)

    rng = np.random.default_rng(23)
    bbox = (-60.0, -45.0, 60.0, 45.0)
    W = H = 512

    # vectorized CCW quads: center + half-sizes + rotation
    cx = rng.uniform(bbox[0], bbox[2], n)
    cy = rng.uniform(bbox[1], bbox[3], n)
    hw = rng.uniform(0.02, 0.15, n)
    hh = rng.uniform(0.02, 0.15, n)
    th = rng.uniform(0, np.pi / 2, n)
    base = np.array([[-1, -1], [1, -1], [1, 1], [-1, 1]], np.float64)
    cosr, sinr = np.cos(th), np.sin(th)
    # corners [n, 4, 2], CCW
    ux = base[None, :, 0] * hw[:, None]
    uy = base[None, :, 1] * hh[:, None]
    corx = cx[:, None] + ux * cosr[:, None] - uy * sinr[:, None]
    cory = cy[:, None] + ux * sinr[:, None] + uy * cosr[:, None]
    nxt = [1, 2, 3, 0]
    x1 = corx.reshape(-1)
    y1 = cory.reshape(-1)
    x2 = corx[:, nxt].reshape(-1)
    y2 = cory[:, nxt].reshape(-1)
    wedge = np.repeat(rng.uniform(0.5, 2.0, n), 4).astype(np.float32)
    efeat_weights = wedge  # per-edge owner weight
    kspan = _pow2(polygon_rowspan_bound(y1, y2, bbox, H) + 1)

    jx1, jy1 = jnp.asarray(x1, jnp.float32), jnp.asarray(y1, jnp.float32)
    jx2, jy2 = jnp.asarray(x2, jnp.float32), jnp.asarray(y2, jnp.float32)
    jw = jnp.asarray(efeat_weights)
    jm = jnp.ones(len(x1), bool)

    def run():
        return polygon_density(
            jx1, jy1, jx2, jy2, jw, jm, bbox, W, H, kspan
        )

    dev_t = _timeit(lambda: _sync(run()), repeats)
    grid = np.asarray(run())

    # CPU baseline: per-polygon cell-center coverage over the polygon's
    # bbox cells (the direct rasterizer a CPU implementation would use),
    # measured on a subsample and reported per polygon
    psub = min(n, 20_000)
    dx = (bbox[2] - bbox[0]) / W
    dy = (bbox[3] - bbox[1]) / H

    def cpu(limit=psub):
        g = np.zeros((H, W))
        for i in range(limit):
            xc = corx[i]
            yc = cory[i]
            c0 = max(int((xc.min() - bbox[0]) / dx), 0)
            c1 = min(int((xc.max() - bbox[0]) / dx) + 1, W)
            r0 = max(int((yc.min() - bbox[1]) / dy), 0)
            r1 = min(int((yc.max() - bbox[1]) / dy) + 1, H)
            if c1 <= c0 or r1 <= r0:
                continue
            ccx = bbox[0] + (np.arange(c0, c1) + 0.5) * dx
            ccy = bbox[1] + (np.arange(r0, r1) + 0.5) * dy
            gx, gy = np.meshgrid(ccx, ccy)
            inside = np.zeros(gx.shape, bool)
            for e in range(4):
                ax, ay = corx[i, e], cory[i, e]
                bx, by = corx[i, nxt[e]], cory[i, nxt[e]]
                cond = (ay <= gy) != (by <= gy)
                tpar = (gy - ay) / np.where(by == ay, 1.0, by - ay)
                xcr = ax + tpar * (bx - ax)
                inside ^= cond & (xcr > gx)
            g[r0:r1, c0:c1] += inside * efeat_weights[4 * i]
        return g

    last = {}

    def cpu_timed():
        last["grid"] = cpu()

    cpu_t = _timeit(cpu_timed, max(1, repeats - 1))
    cpu_grid = last["grid"]  # reuse the final timed run's result
    # parity on the subsample: device grid over the same subset
    sub_k = _pow2(polygon_rowspan_bound(y1[: 4 * psub], y2[: 4 * psub], bbox, H) + 1)
    sub_grid = np.asarray(
        polygon_density(
            jx1[: 4 * psub], jy1[: 4 * psub], jx2[: 4 * psub], jy2[: 4 * psub],
            jw[: 4 * psub], jm[: 4 * psub], bbox, W, H, sub_k,
        )
    )
    denom = max(cpu_grid.sum(), 1.0)
    mismatch_mass = float(np.abs(sub_grid - cpu_grid).sum() / denom)

    # end-to-end: XZ2 store -> planner -> device rasterization
    import shutil
    import tempfile

    from geomesa_tpu.core.columnar import FeatureBatch, GeometryColumn
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.plan.hints import QueryHints
    from geomesa_tpu.plan.query import Query
    from geomesa_tpu.store.partition import XZ2Scheme

    n_store = min(n, 50_000)  # WKT serialization bounds the store size
    verts = np.stack(
        [
            np.concatenate([corx[:n_store], corx[:n_store, :1]], 1).reshape(-1),
            np.concatenate([cory[:n_store], cory[:n_store, :1]], 1).reshape(-1),
        ],
        1,
    )
    col = GeometryColumn(
        "Polygon",
        corx[:n_store, 0],
        cory[:n_store, 0],
        verts,
        np.arange(0, 5 * n_store + 1, 5, dtype=np.int64),
        np.arange(0, n_store + 1, dtype=np.int64),
        [[1]] * n_store,
        np.stack(
            [corx[:n_store].min(1), cory[:n_store].min(1),
             corx[:n_store].max(1), cory[:n_store].max(1)], 1,
        ),
    )
    sft = SimpleFeatureType.from_spec("polys", "w:Double,*geom:Polygon")
    pb = FeatureBatch(
        sft, {"w": efeat_weights[:: 4][:n_store].astype(np.float64), "geom": col}
    )
    root = tempfile.mkdtemp(prefix="gmtpu_polybench_")
    try:
        ds = DataStore(root, use_device_cache=True)
        src = ds.create_schema(sft, XZ2Scheme(g=2))
        src.write(pb)
        q = Query(
            "polys", "INCLUDE",
            hints=QueryHints(
                density_bbox=bbox, density_width=W, density_height=H,
                density_weight="w",
            ),
        )
        src.get_features(q)  # warm (compile + cache)
        e2e_t = _timeit(lambda: src.get_features(q), max(1, repeats - 1))
    finally:
        shutil.rmtree(root, ignore_errors=True)

    cpu_pps = psub / cpu_t
    return {
        "metric": "polygon_density_polys_per_sec_per_chip",
        "value": round(n / dev_t, 1),
        "unit": "polygons/sec",
        "vs_baseline": round((n / dev_t) / cpu_pps, 3),
        "detail": {
            "n": n, "grid": f"{W}x{H}", "device_time_s": round(dev_t, 5),
            "cpu_polys": psub, "cpu_time_s": round(cpu_t, 5),
            "mismatch_mass_frac": round(mismatch_mass, 6),
            "parity": mismatch_mass < 1e-3,
            "store_polys": n_store,
            "e2e_query_time_s": round(e2e_t, 5),
            "e2e_polys_per_sec": round(n_store / e2e_t, 1),
            "note": "kernel at full n; e2e = XZ2 store -> planner -> "
                    "device rasterization at store_polys",
        },
    }


def bench_fs_query(n, repeats, tmpdir=None, cold=False):
    """Config 1: BBOX+time CQL through the full FS Parquet DataStore stack
    (plan -> prune -> parquet pushdown -> device residual mask), CPU
    baseline = the same filter in flat NumPy over the raw arrays."""
    import os
    import shutil
    import tempfile

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore

    rng = np.random.default_rng(17)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = rng.integers(1_590_000_000_000, 1_600_000_000_000, n)
    score = rng.uniform(-10, 10, n)
    root = tmpdir or tempfile.mkdtemp(prefix="gmtpu_bench_")
    try:
        sft = SimpleFeatureType.from_spec(
            "gdelt", "score:Double,dtg:Date,*geom:Point"
        )
        ds = DataStore(root, use_device_cache=True)
        src = ds.create_schema(sft)
        src.write(FeatureBatch.from_pydict(
            sft, {"score": score, "dtg": t, "geom": np.stack([x, y], 1)}
        ))
        cql = ("BBOX(geom, -60, 20, 60, 70) AND score > 0 AND "
               "dtg DURING 2020-06-13T00:00:00Z/2020-08-21T00:00:00Z")
        q_t = _timeit(lambda: src.get_count(cql), repeats)
        count = src.get_count(cql)
        cold_t = None
        if cold:
            # cold path: a fresh store with NO device cache — every query
            # pays parquet read -> host columnar -> device transfer ->
            # mask (the honest end-to-end number the round-1 review asked
            # for; SURVEY.md:834-835 both-ways obligation)
            ds_cold = DataStore(root, use_device_cache=False)
            src_cold = ds_cold.get_feature_source("gdelt")
            cold_t = _timeit(
                lambda: src_cold.get_count(cql), max(1, repeats - 1)
            )
            assert src_cold.get_count(cql) == count

        import datetime as _dt

        def _ms(s):
            return int(_dt.datetime.fromisoformat(s).timestamp() * 1000)

        lo, hi = _ms("2020-06-13T00:00:00+00:00"), _ms("2020-08-21T00:00:00+00:00")

        # CPU baseline per BASELINE.json config 1: the same query through a
        # well-implemented Parquet scan path on CPU — pyarrow dataset with
        # row-group predicate pushdown (SURVEY §7 "honest CPU baseline").
        import pyarrow as pa
        import pyarrow.dataset as pads
        import pyarrow.parquet as papq

        cpu_dir = os.path.join(root, "_cpu_parquet")
        os.makedirs(cpu_dir, exist_ok=True)
        papq.write_table(
            pa.table({"x": x, "y": y, "score": score, "dtg": t}),
            os.path.join(cpu_dir, "data.parquet"),
            row_group_size=1 << 16,
        )
        fld = pads.field

        def cpu():
            dset = pads.dataset(cpu_dir, format="parquet")
            expr = (
                (fld("x") >= -60) & (fld("x") <= 60)
                & (fld("y") >= 20) & (fld("y") <= 70)
                & (fld("score") > 0) & (fld("dtg") > lo) & (fld("dtg") < hi)
            )
            return dset.scanner(filter=expr, columns=["x"]).count_rows()

        cpu_t = _timeit(cpu, max(1, repeats - 1))

        # overhead-free lower bound: the same mask over in-memory arrays
        def rawmask():
            m = ((x >= -60) & (x <= 60) & (y >= 20) & (y <= 70)
                 & (score > 0) & (t > lo) & (t < hi))
            return int(m.sum())

        raw_t = _timeit(rawmask, max(1, repeats - 1))
        parity = cpu() == count == rawmask()
        return {
            "metric": "fs_bbox_time_query_points_per_sec_per_chip",
            "value": round(n / q_t, 1),
            "unit": "points/sec",
            "vs_baseline": round((n / q_t) / (n / cpu_t), 3),
            "detail": {
                "n": n, "matched": count, "device_time_s": round(q_t, 5),
                "cpu_parquet_time_s": round(cpu_t, 5),
                "cpu_rawmask_time_s": round(raw_t, 5),
                "parity": bool(parity),
                **(
                    {
                        "cold_time_s": round(cold_t, 5),
                        "cold_points_per_sec": round(n / cold_t, 1),
                        "cold_vs_cpu": round((n / cold_t) / (n / cpu_t), 3),
                    }
                    if cold_t is not None
                    else {}
                ),
                "note": "end-to-end HBM-resident DataStore query (plan + "
                        "residual mask + device count) vs pyarrow Parquet "
                        "predicate-pushdown scan on CPU (BASELINE config 1); "
                        "cpu_rawmask is the no-IO in-memory lower bound; "
                        "cold_* (with --cold) pays parquet->host->device "
                        "every query",
            },
        }
    finally:
        if tmpdir is None:
            shutil.rmtree(root, ignore_errors=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--n", type=int, default=None)
    p.add_argument("--queries", type=int, default=None)
    p.add_argument("--k", type=int, default=10)
    p.add_argument(
        "--config", type=int, default=None, choices=[1, 2, 3, 4, 5, 6],
        help="BASELINE.json config to run (default: 3, the headline "
             "BBOX+time+kNN metric; 1=fs-query 2=pip 4=density 5=tube "
             "6=polygon-density rasterization)",
    )
    p.add_argument(
        "--dist", choices=["uniform", "clustered"], default="uniform",
        help="data distribution for configs 3/4: uniform (best case for "
             "grids) or clustered hotspots (GDELT/AIS shape, ~10x skew)",
    )
    p.add_argument(
        "--cold", action="store_true",
        help="config 1: ALSO time the cold path (parquet -> host -> "
             "device, no HBM residency) alongside the cached query",
    )
    p.add_argument(
        "--impl", choices=["mxu", "grid", "compact", "haversine"],
        default="compact",
        help="config-3 kNN kernel: compact = device candidate compaction "
             "+ MXU kNN over matches only (default; fastest measured at "
             "GDELT selectivity — 108M vs 102M pts/s for mxu on v5e), "
             "mxu = augmented-matmul ranking keys + deferred block "
             "selection over the full batch, grid = device-built spatial "
             "index + certified neighborhood search (amortizes over many "
             "queries; wins at >=2048 queries/batch), haversine = "
             "elementwise VPU",
    )
    args = p.parse_args(argv)

    if args.smoke:
        import os

        os.environ.setdefault("XLA_FLAGS", "")
        import jax
        from jax._src import xla_bridge as xb

        for name in ("axon", "tpu"):
            xb._backend_factories.pop(name, None)
        jax.config.update("jax_platforms", "cpu")

    # 1<<26 amortizes the remote-tunnel dispatch floor (~105ms/round trip)
    # over a GDELT-realistic batch; both sides scan the same n. Configs
    # whose CPU baseline is superlinear-or-heavy in n keep a smaller default
    # so a full 5-config sweep stays within a bench budget.
    per_config = {1: 1 << 24, 2: 1 << 22, 3: 1 << 26, 4: 1 << 26, 5: 1 << 22,
                  6: 1 << 20}
    n = args.n or (
        1 << 17 if args.smoke else per_config.get(args.config or 3, 1 << 26)
    )
    # smoke still needs >= 128 queries: below that knn_mxu falls back to the
    # haversine path and --impl mxu would never exercise the matmul kernel
    q = args.queries or (128 if args.smoke else 256)
    k = args.k
    repeats = 2 if args.smoke else 3

    if args.config in (1, 2, 4, 5, 6):
        if args.config == 1:
            out = bench_fs_query(n, repeats, cold=args.cold)
        elif args.config == 4:
            out = bench_density(n, repeats, dist=args.dist)
        elif args.config == 6:
            out = bench_polygon_density(n, repeats)
        else:
            out = {2: bench_pip, 5: bench_tube}[args.config](n, repeats)
        print(json.dumps(out))
        return 0

    import jax
    import jax.numpy as jnp

    from geomesa_tpu.engine.knn import knn, knn_compact, knn_mxu

    rng = np.random.default_rng(42)
    if args.dist == "clustered":
        # hotspot mixture (AIS/GDELT shape); queries drawn NEAR hotspots,
        # where cell overflow and near-ties are the worst case
        x, y, cxs, cys = _clustered(rng, n, (-180.0, -90.0, 180.0, 90.0))
        pick = rng.integers(0, len(cxs), q)
        qx = np.clip(cxs[pick] + rng.normal(0, 1.0, q), -180, 180)
        qy = np.clip(cys[pick] + rng.normal(0, 1.0, q), -90, 90)
    else:
        x = rng.uniform(-180, 180, n)
        y = rng.uniform(-90, 90, n)
        qx = rng.uniform(-30, 30, q)
        qy = rng.uniform(30, 60, q)
    t = rng.integers(1_590_000_000_000, 1_600_000_000_000, n)
    speed = rng.uniform(0, 30, n)
    BBOX = (-60.0, 20.0, 60.0, 70.0)
    T0, T1 = 1_592_000_000_000, 1_598_000_000_000

    # --- device pipeline ---------------------------------------------------
    # "compact": two phases exactly like the reference's scan->analytics
    # split — (1) predicate mask + match count, (2) kNN over the compacted
    # matches only. The count crosses to host to pick the static capacity
    # bucket (pow2, jit-cache-stable); that round trip is part of the timed
    # pipeline. Other impls: one fused jit over the full batch.
    @jax.jit
    def mask_count(x, y, t, speed):
        mask = (
            (x >= BBOX[0]) & (x <= BBOX[2]) & (y >= BBOX[1]) & (y <= BBOX[3])
            & (t > T0) & (t < T1) & (speed > 5.0)
        )
        return mask, jnp.sum(mask.astype(jnp.int32))

    @jax.jit
    def device_step(x, y, t, speed, qx, qy):
        mask, count = mask_count(x, y, t, speed)
        if args.impl == "mxu":
            dists, idx = knn_mxu(qx, qy, x, y, mask, k=k)  # sorts+tiles itself
        else:
            dists, idx = knn(qx, qy, x, y, mask, k=k, query_tile=q)
        return count, dists

    from geomesa_tpu.utils.padding import next_pow2

    def compact_step(x, y, t, speed, qx, qy):
        mask, count = mask_count(x, y, t, speed)
        c = int(np.asarray(count))  # host round trip: capacity bucket
        cap = max(next_pow2(max(c, 1)), 1024)
        dists, idx, _overflow = knn_compact(qx, qy, x, y, mask, k=k, capacity=cap)
        return count, dists

    def grid_step(x, y, t, speed, qx, qy):
        # the index-scan shape: build the batch-resident grid index (one
        # device sort, amortized over every query round against the batch),
        # then certified neighborhood search + exact fallback. Grid sized
        # to the match count (one host fetch, like the compact impl).
        from geomesa_tpu.engine.grid_index import (
            auto_grid_params, knn_indexed)

        mask, count = mask_count(x, y, t, speed)
        g_edge, slots = auto_grid_params(int(np.asarray(count)))
        dists, idx = knn_indexed(
            qx, qy, x, y, mask, k=k, g=g_edge, ring_radius=2,
            cell_slots=slots,
        )
        return count, dists

    dx = jnp.asarray(x, jnp.float32)
    dy = jnp.asarray(y, jnp.float32)
    dt = jnp.asarray(t, jnp.int64)
    dspeed = jnp.asarray(speed, jnp.float32)
    dqx = jnp.asarray(qx, jnp.float32)
    dqy = jnp.asarray(qy, jnp.float32)

    step = {"compact": compact_step, "grid": grid_step}.get(
        args.impl, device_step
    )
    count, dists = step(dx, dy, dt, dspeed, dqx, dqy)
    _sync(dists)  # compile + warm
    best = np.inf
    for _ in range(5 if not args.smoke else 2):
        s = time.perf_counter()
        count, dists = step(dx, dy, dt, dspeed, dqx, dqy)
        _sync(dists)
        best = min(best, time.perf_counter() - s)
    tpu_pps = n / best

    # --- CPU baseline ------------------------------------------------------
    cpu_time, cpu_count, cpu_dists = _cpu_baseline(
        x, y, t, speed, qx, qy, k, BBOX, T0, T1,
        repeats=1 if args.smoke else 3,
    )
    cpu_pps = n / cpu_time

    # --- recall parity gate ------------------------------------------------
    got = np.sort(np.asarray(dists), axis=1)
    exp = np.sort(cpu_dists, axis=1)
    finite = np.isfinite(exp)
    recall_ok = bool(
        np.all(np.abs(got[finite] - exp[finite]) <= np.maximum(1.0, 1e-4 * exp[finite]))
    )

    print(
        json.dumps(
            {
                "metric": "gdelt_bbox_time_knn_points_per_sec_per_chip",
                "value": round(tpu_pps, 1),
                "unit": "points/sec",
                "vs_baseline": round(tpu_pps / cpu_pps, 3),
                "detail": {
                    "n": n,
                    "queries": q,
                    "k": k,
                    "device": jax.devices()[0].platform,
                    "device_time_s": round(best, 5),
                    "cpu_time_s": round(cpu_time, 5),
                    "cpu_points_per_sec": round(cpu_pps, 1),
                    "dist": args.dist,
                    "match_count": int(count),
                    "cpu_match_count": cpu_count,
                    "recall_parity": recall_ok,
                },
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
