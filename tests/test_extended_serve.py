"""Extended-geometry serving (docs/SERVING.md "Extended geometries &
TubeSelect"): CPU mesh parity for the XZ-sliced residency tier.

The load-bearing claims, proven on a 4-device CPU mesh (conftest forces
an 8-device host platform):

- extended stores (LineStrings here) build MESH residency: the
  superbatch row-shards across chips AND carries per-shard CSR tiles
  (vertex/ring/edge buffers with shard-local offsets), with the same
  partition->shard ownership map the point tier has;
- INTERSECTS/DWITHIN counts, kNN-on-lines and TubeSelect answer
  bit-identically across every route — serial, pipelined, mesh,
  ring-fed mesh — against the host f64 oracle, over >= 16 consecutive
  windows (the ring arms once and stays fresh);
- a coalesced TubeSelect window is ONE dispatch: the service dispatch
  counter, the engine jit caches (JitTracker: zero module-jit calls on
  the mesh route) and the `serve.device.ops` accounting all agree;
- the tube ring retires the blanket non-point refusal: tube windows
  arm and ride ring programs (`serve.ring.windows` moves, fallbacks
  stay empty).

Budget note (tier-1 wall): ONE tiny 4-partition LineString store
(512 rows), every test shares its warm mesh programs.
"""

import json

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.engine.tube import tube_select_host
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.serve import QueryService, ServeConfig
from geomesa_tpu.utils.metrics import metrics

MESH_D = 4
ROWS_PER_DAY = 128
DAYS = ("2021-03-01", "2021-03-02", "2021-03-03", "2021-03-04")
POLY = "POLYGON ((-6 -6, 6 -6, 6 6, -6 6, -6 -6))"
CQL_INTERSECTS = f"INTERSECTS(geom, {POLY})"
CQL_DWITHIN = "DWITHIN(geom, POINT(0 0), 400000, meters)"

RADIUS_M = 150_000.0
HALF_WINDOW_MS = 12 * 3_600_000
T = 17  # pads to 32: one tube ring class for every window below


def _day_millis(day: str) -> int:
    return int(np.datetime64(day, "ms").astype(np.int64))


def make_batch():
    """4 day-partitions x 128 rows of 3-vertex linestrings: each
    partition pow2-pads to exactly 128 rows, so under a 4-chip mesh
    (shard_rows = 512/4 = 128) partition i is owned by shard i alone."""
    rng = np.random.default_rng(23)
    sft = SimpleFeatureType.from_spec(
        "corridors", "name:String,score:Double,dtg:Date,*geom:LineString")
    frames = []
    for d, day in enumerate(DAYS):
        n = ROWS_PER_DAY
        x0 = rng.uniform(-12, 12, n)
        y0 = rng.uniform(-12, 12, n)
        wkts = [
            f"LINESTRING ({x0[i]} {y0[i]}, {x0[i] + 0.08} {y0[i] + 0.05},"
            f" {x0[i] + 0.16} {y0[i] - 0.03})"
            for i in range(n)
        ]
        frames.append({
            "name": [f"f{d}_{i}" for i in range(n)],
            "score": rng.uniform(-10, 10, n),
            "dtg": _day_millis(day)
            + rng.integers(6 * 3600_000, 18 * 3600_000, n),
            "geom": wkts,
        })
    return sft, frames


def track():
    tx = np.linspace(-8.0, 8.0, T)
    ty = np.linspace(-5.0, 5.0, T)
    tt = np.linspace(_day_millis(DAYS[0]),
                     _day_millis(DAYS[-1]) + 86_400_000, T).astype(np.int64)
    return tx, ty, tt


@pytest.fixture(scope="module")
def catalog(tmp_path_factory):
    sft, frames = make_batch()
    root = str(tmp_path_factory.mktemp("extended_serve"))
    ds = DataStore(root, use_device_cache=True)
    ds.create_schema(sft)
    src = ds.get_feature_source("corridors")
    for data in frames:
        src.write(FeatureBatch.from_pydict(sft, data))
    del ds
    return root


@pytest.fixture(scope="module")
def mesh_store(catalog):
    return DataStore(catalog, use_device_cache=True)


@pytest.fixture(scope="module")
def serial_store(catalog):
    """Independent single-chip store over the same files — the oracle
    the mesh answers must match bit-for-bit."""
    return DataStore(catalog, use_device_cache=True)


@pytest.fixture(scope="module")
def host_batch(serial_store):
    src = serial_store.get_feature_source("corridors")
    return src.get_features("INCLUDE").features


def _counter(name: str) -> float:
    return json.loads(metrics.to_json())["counters"].get(name, 0.0)


def _mesh_service(store, **kw) -> QueryService:
    return QueryService(
        store, ServeConfig(mesh=MESH_D, max_wait_ms=20.0, **kw),
        autostart=False)


def _tube_names(svc, started=False) -> list:
    tx, ty, tt = track()
    fut = svc.tube("corridors", "INCLUDE", tx, ty, tt,
                   RADIUS_M, HALF_WINDOW_MS)
    if not started:
        svc.start()
    r = fut.result(timeout=300)
    return sorted(r.features.columns["name"].decode())


def test_extended_mesh_residency_csr_tiles(mesh_store):
    """The extended superbatch row-shards across the mesh AND carries
    per-shard CSR tiles with shard-local offsets; the partition
    ownership map mirrors the point tier's."""
    svc = _mesh_service(mesh_store)
    svc.start()
    try:
        svc.count("corridors", CQL_INTERSECTS).result(timeout=300)
    finally:
        svc.close(drain=True)
    src = mesh_store.get_feature_source("corridors")
    sb = src.planner.cache.superbatch()
    assert sb.extended
    assert sb.mesh is not None and sb.shard_rows == ROWS_PER_DAY
    owned = sorted(sb.owners.items())
    assert [o for _, o in owned] == [(0,), (1,), (2,), (3,)], owned
    # CSR tiles: [D, ...] stacked per-shard slices, offsets rewritten
    # shard-local — every shard's feature-offset table spans exactly
    # its shard_rows rows and ends at its own vertex count
    tiles = sb.tiles
    featr = np.asarray(tiles["geom__featr"])
    verts = np.asarray(tiles["geom__verts"])
    assert featr.shape == (MESH_D, ROWS_PER_DAY + 1)
    assert verts.shape[0] == MESH_D and verts.shape[2] == 2
    assert (featr[:, 0] == 0).all()
    # one ring per linestring, offsets rewritten shard-local
    assert (featr[:, -1] == ROWS_PER_DAY).all()
    # vertex-feature ownership stays in-shard: padded entries map to
    # the sentinel row (shard_rows), real ones below it
    vfeat = np.asarray(tiles["geom__vfeat"])
    assert vfeat.max() <= ROWS_PER_DAY
    # upload accounting: the residency walk metered tile rows
    assert src.planner.cache.stats()["upload_tile_rows"] > 0


def test_counts_bit_identical_across_routes(mesh_store, serial_store):
    serial_src = serial_store.get_feature_source("corridors")
    want_int = serial_src.get_count(CQL_INTERSECTS)
    want_dw = serial_src.get_count(CQL_DWITHIN)
    assert want_int > 0 and want_dw > 0
    svc = _mesh_service(mesh_store)
    svc.start()
    try:
        got_int = svc.count("corridors", CQL_INTERSECTS).result(timeout=300)
        got_dw = svc.count("corridors", CQL_DWITHIN).result(timeout=300)
    finally:
        svc.close(drain=True)
    assert got_int == want_int
    assert got_dw == want_dw


def test_knn_on_lines_bit_identical(mesh_store, serial_store):
    """kNN over an extended store runs on the representative coords —
    mesh route bit-identical to single-chip serial."""
    rng = np.random.default_rng(5)
    qx = rng.uniform(-10, 10, 1)
    qy = rng.uniform(-10, 10, 1)
    serial_src = serial_store.get_feature_source("corridors")
    sd, six, _ = serial_src.knn(CQL_INTERSECTS, qx, qy, k=5)
    svc = _mesh_service(mesh_store)
    svc.start()
    try:
        d, ix, _ = svc.knn("corridors", CQL_INTERSECTS, qx, qy,
                           k=5).result(timeout=300)
    finally:
        svc.close(drain=True)
    np.testing.assert_array_equal(ix, six)
    assert np.array_equal(d, sd), (d, sd)


def tube_oracle(host_batch) -> list:
    tx, ty, tt = track()
    col = host_batch.columns["geom"]
    t = np.asarray(host_batch.columns["dtg"]).astype(
        "datetime64[ms]").astype("int64")
    hits = tube_select_host(np.asarray(col.x), np.asarray(col.y), t,
                            tx, ty, tt, RADIUS_M, HALF_WINDOW_MS)
    names = host_batch.columns["name"].decode()
    return sorted(names[i] for i in np.nonzero(hits)[0])


def test_tube_parity_16_windows_all_routes(mesh_store, serial_store,
                                           host_batch):
    """TubeSelect bit-identical to the f64 host oracle on every route,
    over >= 16 CONSECUTIVE windows on the ring-fed mesh service (the
    armed program stays fresh; fallbacks stay empty)."""
    want = tube_oracle(host_batch)
    assert want, "oracle matched nothing; bad fixture"

    # serial route (no pipeline, no mesh)
    svc = QueryService(serial_store,
                       ServeConfig(pipeline=False, max_wait_ms=5.0),
                       autostart=False)
    try:
        got = _tube_names(svc)
        assert got == want
    finally:
        svc.close(drain=True)

    # pipelined route (no mesh): same answer
    svc = QueryService(serial_store, ServeConfig(max_wait_ms=5.0),
                       autostart=False)
    try:
        got = _tube_names(svc)
        assert got == want
    finally:
        svc.close(drain=True)

    # mesh + ring: 16 consecutive windows, every one bit-identical;
    # the ring arms on the first and feeds the rest
    svc = _mesh_service(mesh_store)
    svc.start()
    try:
        base_ring = _counter("serve.ring.windows")
        for i in range(16):
            got = _tube_names(svc, started=True)
            assert got == want, f"window {i} diverged"
        stats = svc.stats()
    finally:
        svc.close(drain=True)
    ring = (stats.get("pipeline") or {}).get("ring") or {}
    assert ring.get("windows", 0) >= 15, ring
    assert not ring.get("fallbacks"), ring
    assert _counter("serve.ring.windows") - base_ring >= 15


def test_tube_coalesced_window_one_dispatch(mesh_store, host_batch):
    """>= 8 identical concurrent TubeSelect requests coalesce (dedup
    key) into ONE window and ONE device dispatch: service counter says
    one dispatch, the engine tube module's jit caches see zero calls
    (mesh route = AOT registry), and serve.device.ops moves by a
    per-window constant, not per-rider."""
    import geomesa_tpu.engine.tube as tube_mod

    from geomesa_tpu.analysis.runtime import JitTracker

    want = tube_oracle(host_batch)
    tx, ty, tt = track()

    # warm the mesh tube route at this T bucket
    svc = _mesh_service(mesh_store)
    f = svc.tube("corridors", "INCLUDE", tx, ty, tt,
                 RADIUS_M, HALF_WINDOW_MS)
    svc.start()
    f.result(timeout=300)
    svc.close(drain=True)

    tracker = JitTracker()
    tracker.install(tube_mod)
    try:
        base_mesh = _counter("tube.mesh.dispatches")
        base_ring = _counter("serve.ring.windows")
        base_ops = _counter("serve.device.ops")
        svc = _mesh_service(mesh_store)
        futs = [svc.tube("corridors", "INCLUDE", tx, ty, tt,
                         RADIUS_M, HALF_WINDOW_MS) for _ in range(8)]
        svc.start()
        results = [f.result(timeout=300) for f in futs]
        svc.close(drain=True)
        jit_calls = sum(rec["calls"] for rec in tracker.report().values())
    finally:
        tracker.unwrap()

    assert svc.stats()["dispatches"] == 1, svc.stats()
    assert jit_calls == 0, tracker.report()
    # one window: exactly one mesh dispatch on whichever route (ring or
    # pipelined launch) took it
    d_mesh = _counter("tube.mesh.dispatches") - base_mesh
    d_ring = _counter("serve.ring.windows") - base_ring
    assert d_mesh == 1, (d_mesh, d_ring)
    # per-window device-op budget: slot/stage transfer + program
    # dispatch + combined sync read (+ nothing per rider)
    assert _counter("serve.device.ops") - base_ops <= 4
    for r in results:
        got = sorted(r.features.columns["name"].decode())
        assert got == want


def test_tube_ring_retires_non_point_refusal(mesh_store):
    """The extended tier's whole point on the ring: tube windows ARM
    (no `non_point`/`no_geometry` refusal), and the per-reason
    ineligibility meter stays quiet for them."""
    svc = _mesh_service(mesh_store)
    tx, ty, tt = track()
    f = svc.tube("corridors", "score > -100", tx, ty, tt,
                 RADIUS_M, HALF_WINDOW_MS)
    svc.start()
    try:
        f.result(timeout=300)
        # second window of the same class rides the armed program
        svc.tube("corridors", "score > -100", tx, ty, tt,
                 RADIUS_M, HALF_WINDOW_MS).result(timeout=300)
        stats = svc.stats()
    finally:
        svc.close(drain=True)
    ring = (stats.get("pipeline") or {}).get("ring") or {}
    falls = ring.get("fallbacks", {})
    assert "no_geometry" not in falls and "non_point" not in falls, falls
    assert ring.get("armed", 0) >= 1, ring
