"""ORC encoding, compaction, and the partition-management CLI surface."""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.query import Query
from geomesa_tpu.store.fs import FileSystemStorage
from geomesa_tpu.store.partition import DateTimeScheme

SFT = SimpleFeatureType.from_spec(
    "t", "name:String,score:Double,dtg:Date,*geom:Point"
)


def _batch(n=100, seed=0):
    rng = np.random.default_rng(seed)
    return FeatureBatch.from_pydict(
        SFT,
        {
            "name": rng.choice(["a", "b"], n).tolist(),
            "score": rng.uniform(-5, 5, n),
            "dtg": rng.integers(1_590_000_000_000, 1_590_400_000_000, n),
            "geom": np.stack([rng.uniform(-60, 60, n), rng.uniform(-45, 45, n)], 1),
        },
        fids=[f"f{i}" for i in range(n)],
    )


class TestOrc:
    def test_round_trip_and_query(self, tmp_path):
        ds = DataStore(str(tmp_path / "cat"))
        src = ds.create_schema(SFT, encoding="orc")
        batch = _batch(200)
        src.write(batch)
        # reload from disk: encoding persists in metadata
        ds2 = DataStore(str(tmp_path / "cat"))
        src2 = ds2.get_feature_source("t")
        assert src2.storage.encoding == "orc"
        res = src2.get_features(Query("t", "BBOX(geom, -30, -20, 30, 20) AND score > 0"))
        gc = batch.geometry
        s = np.asarray(batch.column("score"))
        want = int(np.sum((gc.x >= -30) & (gc.x <= 30) & (gc.y >= -20)
                          & (gc.y <= 20) & (s > 0)))
        assert len(res.features) == want

    def test_bad_encoding_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="encoding"):
            FileSystemStorage.create(
                str(tmp_path / "x"), SFT, DateTimeScheme(dtg_attr="dtg"), "feather"
            )


class TestCompact:
    @pytest.mark.parametrize("encoding", ["parquet", "orc"])
    def test_compact_preserves_data(self, tmp_path, encoding):
        ds = DataStore(str(tmp_path / "cat"))
        src = ds.create_schema(SFT, encoding=encoding)
        for seed in range(3):  # three writes -> three files per partition
            src.write(_batch(50, seed=seed))
        storage = src.storage
        multi = [p for p in storage.partitions()
                 if len(storage.manifest[p]) > 1]
        assert multi, "expected multi-file partitions"
        before = src.get_count("INCLUDE")
        removed = storage.compact()
        assert removed > 0
        assert all(len(v) == 1 for v in storage.manifest.values())
        assert src.get_count("INCLUDE") == before
        # reload sees the compacted manifest
        ds2 = DataStore(str(tmp_path / "cat"))
        assert ds2.get_feature_source("t").get_count("INCLUDE") == before


class TestCli:
    def test_manage_partitions_and_compact(self, tmp_path, capsys):
        from geomesa_tpu.cli.main import main

        cat = str(tmp_path / "cat")
        ds = DataStore(cat)
        src = ds.create_schema(SFT)
        src.write(_batch(40, seed=0))
        src.write(_batch(40, seed=1))
        assert main(["manage-partitions", "-c", cat, "-f", "t"]) == 0
        out = capsys.readouterr().out
        assert "file(s)" in out
        assert main(["compact", "-c", cat, "-f", "t"]) == 0
        out = capsys.readouterr().out
        assert "compacted" in out

    def test_export_shp_and_leaflet(self, tmp_path):
        from geomesa_tpu.cli.main import main
        from geomesa_tpu.convert.formats import read_shapefile

        cat = str(tmp_path / "cat")
        ds = DataStore(cat)
        src = ds.create_schema(SFT)
        src.write(_batch(20))
        shp = str(tmp_path / "out.shp")
        assert main(["export", "-c", cat, "-f", "t", "-F", "shp",
                     "-o", shp]) == 0
        assert len(list(read_shapefile(shp))) == 20
        html = str(tmp_path / "out.html")
        assert main(["export", "-c", cat, "-f", "t", "-F", "leaflet",
                     "-o", html, "-m", "5"]) == 0
        text = open(html).read()
        assert "leaflet" in text and "FeatureCollection" in text
