"""Index layer tests: lexicoders, keyspaces, KV datastore parity.

Strategy (SURVEY.md §4): the KVDataStore's full stack — FilterSplitter,
StrategyDecider, range scans, residual mask — is validated for exact result
parity against the brute-force NumPy reference engine, for every index type.
"""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.cql import parse_cql
from geomesa_tpu.index import (
    AttributeIndex,
    DurableKVDataStore,
    KVDataStore,
    MemoryIndexAdapter,
    SqliteIndexAdapter,
    Z3Index,
    default_indices,
)
from geomesa_tpu.index import lexicoders as lx
from geomesa_tpu.plan.query import Query
from geomesa_tpu.plan.hints import QueryHints

from tests.reference_engine import eval_filter


# -- lexicoders ------------------------------------------------------------


def test_int_lexicoder_order_preserving():
    vals = [-(2**62), -1000, -1, 0, 1, 7, 2**40, 2**62]
    encs = [lx.encode_int(v) for v in vals]
    assert encs == sorted(encs)
    assert [lx.decode_int(e) for e in encs] == vals


def test_float_lexicoder_order_preserving():
    vals = [-1e300, -2.5, -1e-9, 0.0, 1e-9, 1.0, 3.14, 1e300]
    encs = [lx.encode_float(v) for v in vals]
    assert encs == sorted(encs)
    back = [lx.decode_float(e) for e in encs]
    assert np.allclose(back, vals)


def test_string_lexicoder_roundtrip_and_order():
    vals = ["", "a", "ab", "b", "ba", "z\x00q", "z\x01q", "zz"]
    encs = [lx.encode_string(v) for v in vals]
    assert [lx.decode_string(e) for e in encs] == vals


def test_successor_is_prefix_upper_bound():
    for b in [b"abc", b"a\xff", b"\xff\xff", b"x"]:
        s = lx.successor(b)
        assert s > b
        assert s > b + b"zzzz"
        assert s > b + b"\xfe\xfe"


# -- fixtures --------------------------------------------------------------


SPEC = "actor:String:index=true,score:Double,count:Integer,dtg:Date,*geom:Point"


def make_point_batch(n=400, seed=7):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec("gdelt", SPEC)
    return sft, FeatureBatch.from_pydict(
        sft,
        {
            "actor": rng.choice(["USA", "FRA", "CHN", "GBR", None], n).tolist(),
            "score": rng.uniform(-10, 10, n),
            "count": rng.integers(0, 100, n),
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack(
                [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1
            ),
        },
    )


POINT_FILTERS = [
    "BBOX(geom, -50, -40, 50, 40) AND dtg DURING 2020-06-01T00:00:00Z/2020-08-01T00:00:00Z",
    "BBOX(geom, 0, 0, 90, 60)",
    "actor = 'USA'",
    "actor IN ('FRA', 'CHN') AND score > 0",
    "count BETWEEN 10 AND 30",
    "score < -5.0",
    "actor LIKE 'U%'",
    "BBOX(geom, -50, -40, 50, 40) AND actor = 'GBR'",
    "dtg AFTER 2020-08-10T00:00:00Z",
]


# every KV test runs over BOTH adapters: the in-memory reference backend
# and the durable SQLite backend — the SPI-plurality the reference proves
# with its four storage backends (SURVEY.md C9-C11)
ADAPTERS = ["memory", "sqlite"]


@pytest.fixture(scope="module", params=ADAPTERS)
def kv_source(request, tmp_path_factory):
    sft, batch = make_point_batch()
    if request.param == "memory":
        ds = KVDataStore()
    else:
        ds = DurableKVDataStore(str(tmp_path_factory.mktemp("kvdur")))
    src = ds.create_schema(sft)
    src.write(batch)
    return sft, batch, src


@pytest.fixture(params=ADAPTERS)
def make_ds(request, tmp_path):
    seq = [0]

    def _make():
        if request.param == "memory":
            return KVDataStore()
        seq[0] += 1
        return DurableKVDataStore(str(tmp_path / f"kv{seq[0]}"))

    return _make


# -- parity ----------------------------------------------------------------


@pytest.mark.parametrize("cql", POINT_FILTERS)
def test_kv_query_parity(kv_source, cql):
    sft, batch, src = kv_source
    f = parse_cql(cql)
    expected = set(
        np.asarray(range(len(batch)))[eval_filter(f, batch)].tolist()
    )
    r = src.get_features(cql)
    got = set() if r.features is None else {
        int(fid.split("-")[-1]) for fid in r.features.fids.decode()
    }
    assert got == expected, cql


def test_kv_strategy_choice(kv_source):
    sft, batch, src = kv_source
    # equality on an indexed attribute should choose the attribute index
    ex = src.explain("actor = 'USA'")
    assert "attr:actor" in ex and "chose attr:actor" in ex
    # bbox+time should pick a z index (z3 beats z2 on selectivity here)
    ex = src.explain(POINT_FILTERS[0])
    assert "chose z" in ex


def test_kv_index_override(kv_source):
    sft, batch, src = kv_source
    q = Query("gdelt", POINT_FILTERS[0], hints=QueryHints(query_index="z2"))
    _, _, chosen = src.plan(q)
    assert chosen is not None and chosen.index.name == "z2"
    # result parity still holds under the override
    f = parse_cql(POINT_FILTERS[0])
    expected = int(eval_filter(f, batch).sum())
    assert src.get_count(q) == expected


def test_kv_overwrite_same_fid(make_ds):
    sft, batch = make_point_batch(50)
    ds = make_ds()
    src = ds.create_schema(sft)
    fids = src.write(batch)
    assert src.live_count == 50
    # rewrite the same fids: replaces, not duplicates
    src.write(batch, fids=fids)
    assert src.live_count == 50
    r = src.get_features("INCLUDE")
    assert len(r.features) == 50


def test_kv_delete_features(make_ds):
    sft, batch = make_point_batch(80)
    ds = make_ds()
    src = ds.create_schema(sft)
    src.write(batch)
    f = parse_cql("actor = 'USA'")
    n_usa = int(eval_filter(f, batch).sum())
    deleted = src.delete_features("actor = 'USA'")
    assert deleted == n_usa
    assert src.get_count("actor = 'USA'") == 0
    assert src.live_count == 80 - n_usa
    # deleted rows are gone from every index, not just attr
    r = src.get_features("BBOX(geom, -180, -90, 180, 90)")
    got = 0 if r.features is None else len(r.features)
    assert got == 80 - n_usa


def test_kv_id_queries(make_ds):
    sft, batch = make_point_batch(30)
    ds = make_ds()
    src = ds.create_schema(sft)
    fids = src.write(batch)
    some = [fids[3], fids[17], fids[29]]
    got = src.get_features_by_id(some)
    assert sorted(got.fids.decode()) == sorted(some)
    # __fid__ pseudo-attribute rides the ID index
    q = f"__fid__ IN ('{some[0]}', '{some[1]}')"
    _, _, chosen = src.plan(q)
    assert chosen is not None and chosen.index.name == "id"


def test_kv_aggregation_hints(kv_source):
    sft, batch, src = kv_source
    cql = "BBOX(geom, -50, -40, 50, 40)"
    f = parse_cql(cql)
    expected_count = int(eval_filter(f, batch).sum())
    # density over the matched set
    q = Query(
        "gdelt", cql,
        hints=QueryHints(density_bbox=(-50, -40, 50, 40),
                         density_width=16, density_height=16),
    )
    r = src.get_features(q)
    assert r.kind == "density"
    assert int(round(float(r.grid.sum()))) == expected_count
    # stats
    q = Query("gdelt", cql, hints=QueryHints(stats_string="MinMax(score)"))
    r = src.get_features(q)
    assert r.kind == "stats"
    # arrow (ArrowScan analog) rides the same shared aggregation
    import io

    import pyarrow as pa

    q = Query("gdelt", cql, hints=QueryHints(arrow_encode=True))
    r = src.get_features(q)
    assert r.kind == "arrow"
    t = pa.ipc.open_stream(io.BytesIO(r.arrow_bytes)).read_all()
    assert t.num_rows == expected_count
    assert "__fid__" in t.schema.names


def test_kv_extended_geometries_xz2(make_ds):
    rng = np.random.default_rng(3)
    sft = SimpleFeatureType.from_spec("polys", "name:String,*geom:Polygon")
    n = 60
    geoms = []
    for i in range(n):
        cx, cy = rng.uniform(-150, 150), rng.uniform(-70, 70)
        w, h = rng.uniform(0.5, 8, 2)
        geoms.append(
            f"POLYGON (({cx-w} {cy-h}, {cx+w} {cy-h}, {cx+w} {cy+h}, "
            f"{cx-w} {cy+h}, {cx-w} {cy-h}))"
        )
    batch = FeatureBatch.from_pydict(
        sft, {"name": [f"p{i}" for i in range(n)], "geom": geoms}
    )
    ds = make_ds()
    src = ds.create_schema(sft)
    src.write(batch)
    # default index set for extended geoms: xz2 (+id)
    assert any(i.name == "xz2" for i in src.indices)
    for cql in ["BBOX(geom, -60, -40, -10, 10)", "BBOX(geom, 100, 20, 160, 70)"]:
        f = parse_cql(cql)
        expected = int(eval_filter(f, batch).sum())
        assert src.get_count(cql) == expected, cql


def test_default_indices_selection():
    sft, _ = make_point_batch(1)
    names = [getattr(i, "full_name", i.name) for i in default_indices(sft)]
    assert "z3" in names and "z2" in names and "id" in names
    assert "attr:actor" in names
    sft2 = SimpleFeatureType.from_spec("lines", "n:Integer,*geom:LineString")
    names2 = [i.name for i in default_indices(sft2)]
    assert "xz2" in names2 and "z3" not in names2


def test_attribute_index_range_scan_counts():
    """The attribute index must return a covering set for range predicates."""
    sft, batch = make_point_batch(200, seed=11)
    adapter = MemoryIndexAdapter()
    idx = AttributeIndex(sft, "count")
    adapter.create_index(idx.full_name)
    fids = [f"f-{i}" for i in range(len(batch))]
    adapter.write(idx.full_name, idx.write_keys(batch, fids, list(range(len(batch)))))
    f = parse_cql("count BETWEEN 20 AND 40")
    rows = adapter.scan(idx.full_name, idx.ranges(f))
    vals = np.asarray(batch.columns["count"])
    expected = set(np.nonzero((vals >= 20) & (vals <= 40))[0].tolist())
    assert expected.issubset(set(rows))
    # and tight: nothing outside [20, 40] at the key level for ints
    assert set(rows) == expected


def test_kv_like_underscore_not_prefix_scanned(make_ds):
    """'_' is a LIKE wildcard; the attr index must not treat it as a literal
    prefix byte (that would silently drop matches)."""
    sft, batch = make_point_batch(100, seed=13)
    ds = make_ds()
    src = ds.create_schema(sft)
    src.write(batch)
    f = parse_cql("actor LIKE 'U_A%'")
    expected = int(eval_filter(f, batch).sum())
    assert expected > 0  # USA matches U_A
    assert src.get_count("actor LIKE 'U_A%'") == expected


def test_kv_bulk_write_scales(make_ds):
    """Bulk writes use one sorted merge, not per-key insertion."""
    import time

    sft, batch = make_point_batch(5000, seed=17)
    ds = make_ds()
    src = ds.create_schema(sft)
    t0 = time.perf_counter()
    src.write(batch)
    assert time.perf_counter() - t0 < 10.0
    assert src.live_count == 5000
    assert src.get_count("actor = 'USA'") == int(
        eval_filter(parse_cql("actor = 'USA'"), batch).sum()
    )


# -- durability ------------------------------------------------------------


def test_durable_survives_restart(tmp_path):
    """The whole point of the second adapter: a reopened store serves
    identical results — schema, features, tombstones, fid map."""
    root = str(tmp_path / "kv")
    sft, batch = make_point_batch(120, seed=23)
    ds = DurableKVDataStore(root)
    src = ds.create_schema(sft)
    fids = src.write(batch)
    n_usa = int(eval_filter(parse_cql("actor = 'USA'"), batch).sum())
    src.delete_features("actor = 'USA'")
    expected_live = 120 - n_usa
    expected = {
        cql: src.get_count(cql) for cql in POINT_FILTERS
    }
    ds.close()

    ds2 = DurableKVDataStore(root)
    assert ds2.get_type_names() == ["gdelt"]
    src2 = ds2.get_feature_source("gdelt")
    assert src2.sft.to_spec() == sft.to_spec()
    assert src2.live_count == expected_live
    for cql, want in expected.items():
        assert src2.get_count(cql) == want, cql
    # fid map restored: id lookups still work, overwrite still replaces
    live = [f for f in fids if f in src2._fid_row]
    got = src2.get_features_by_id(live[:5])
    assert sorted(got.fids.decode()) == sorted(live[:5])
    src2.write(src2.get_features_by_id(live[:5]), fids=live[:5])
    assert src2.live_count == expected_live
    ds2.close()


def test_durable_age_off_survives_restart(tmp_path):
    root = str(tmp_path / "kv")
    sft, batch = make_point_batch(100, seed=29)
    ds = DurableKVDataStore(root)
    src = ds.create_schema(sft)
    src.write(batch)
    dtg = np.asarray(batch.columns["dtg"], np.int64)
    now = 1_600_000_000_000
    ttl = 5_000_000_000
    expected_removed = int((dtg < now - ttl).sum())
    removed = src.age_off(ttl, now_ms=now)
    assert removed == expected_removed
    ds.close()

    ds2 = DurableKVDataStore(root)
    src2 = ds2.get_feature_source("gdelt")
    assert src2.live_count == 100 - expected_removed
    # aged-off rows stay gone from every index after reopen
    r = src2.get_features("BBOX(geom, -180, -90, 180, 90)")
    got = 0 if r.features is None else len(r.features)
    assert got == 100 - expected_removed
    ds2.close()


def test_sqlite_adapter_spi_direct(tmp_path):
    """The SPI contract directly: byte-ordered range scans, idempotent
    overwrite, delete, counts."""
    from geomesa_tpu.index.keyspace import WriteKey

    a = SqliteIndexAdapter(str(tmp_path / "x.db"))
    a.create_index("t")
    assert a.size("t") == 0
    a.write("t", [WriteKey(b"\x00\x05", 5), WriteKey(b"\x00\x01", 1),
                  WriteKey(b"\x01\x00", 256)])
    a.write("t", [WriteKey(b"\x00\x05", 50)])  # overwrite same key
    assert a.size("t") == 3
    assert a.scan("t", [(b"\x00", b"\x01")]) == [1, 50]
    assert a.scan_count("t", [(b"\x00", b"\x02")]) == 3
    a.delete("t", [b"\x00\x01"])
    assert a.scan("t", [(b"\x00", b"\x01")]) == [50]
    a.close()


def test_durable_write_is_atomic(tmp_path):
    """A failure mid-write (after tombstones + row store, before all index
    keys) must roll back the WHOLE logical write on disk."""
    root = str(tmp_path / "kv")
    sft, batch = make_point_batch(40, seed=31)
    ds = DurableKVDataStore(root)
    src = ds.create_schema(sft)
    fids = src.write(batch)
    baseline = src.get_count("INCLUDE")

    # sabotage: the LAST index write raises, after rows + earlier indexes
    real_write = src.adapter.write
    calls = []

    def flaky(name, keys):
        calls.append(name)
        if len(calls) == len(src.indices):
            raise RuntimeError("simulated crash")
        real_write(name, keys)

    src.adapter.write = flaky
    with pytest.raises(RuntimeError):
        src.write(batch, fids=fids)  # replace-by-id: tombstones first
    src.adapter.write = real_write
    ds.close()

    # the failed write must be invisible: no tombstoned originals, no
    # duplicate batch, same counts
    ds2 = DurableKVDataStore(root)
    src2 = ds2.get_feature_source("gdelt")
    assert src2.live_count == baseline
    assert src2.get_count("INCLUDE") == baseline
    for cql in POINT_FILTERS[:3]:
        assert src2.get_count(cql) == int(
            eval_filter(parse_cql(cql), batch).sum()
        ), cql
    ds2.close()


def test_stale_hash_sketches_dropped(tmp_path):
    """stats.json persisted under an older hash family must be dropped on
    load (regenerable derived data), not served corrupt."""
    import json as _json

    from geomesa_tpu.stats.sketches import Cardinality, Stat

    c = Cardinality("x")
    c.observe(np.arange(100))
    d = c.to_json()
    # round trip works at the current version
    assert Stat.from_json(d).result() == pytest.approx(c.result())
    d_old = dict(d)
    d_old.pop("hash")  # as written by the round-1 blake2b code
    with pytest.raises(ValueError, match="rerun stats-analyze"):
        Stat.from_json(d_old)


class TestS2Index:
    """S2 cube-face keyspace (round 3 — SURVEY.md:241-242): result parity
    against the brute-force reference through the full KV stack, plus the
    polar regime where S2 beats Z2 structurally."""

    def _store(self, tmp_path, n=600, polar=False):
        from geomesa_tpu.index import S2Index

        rng = np.random.default_rng(41)
        sft = SimpleFeatureType.from_spec(
            "ais", "speed:Double,dtg:Date,*geom:Point"
        )
        lat = (rng.uniform(60, 90, n) if polar
               else rng.uniform(-80, 80, n))
        batch = FeatureBatch.from_pydict(sft, {
            "speed": rng.uniform(0, 30, n),
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-180, 180, n), lat], 1),
        })
        ds = KVDataStore()
        src = ds.create_schema(
            sft, indices=[S2Index(sft, shards=2, level=13)]
        )
        src.write(batch)
        return src, batch

    @pytest.mark.parametrize("polar", [False, True])
    def test_bbox_parity(self, tmp_path, polar):
        src, batch = self._store(tmp_path, polar=polar)
        boxes = [
            "BBOX(geom, -60, 20, 60, 70)",
            "BBOX(geom, 150, 60, 180, 90)",   # polar + antimeridian edge
            "BBOX(geom, -10, -5, 10, 5)",
        ]
        for cql in boxes:
            f = parse_cql(cql)
            exp = int(eval_filter(f, batch).sum())
            got = src.get_features(Query("ais", f))
            n_got = 0 if got.features is None else len(got.features)
            assert n_got == exp, cql

    def test_planner_picks_s2_and_explains(self, tmp_path):
        src, batch = self._store(tmp_path)
        f = parse_cql("BBOX(geom, -60, 20, 60, 70) AND speed > 5")
        r = src.get_features(Query("ais", f))
        exp = int(eval_filter(f, batch).sum())
        assert (0 if r.features is None else len(r.features)) == exp
