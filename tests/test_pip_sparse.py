"""Sparse pair-list polygon-layer join tests (interpret mode on CPU —
the same kernels Mosaic-compile on TPU for bench config 2).

Oracle: full f64 crossing number over ALL edges (union-by-total-parity
for disjoint layers), the same contract the bench gates on."""

import numpy as np

from geomesa_tpu.engine.pip_sparse import (
    chunk_pairs, pip_layer, pip_layer_sparse, prepare_layer)


def make_layer(rng, npoly=18, grid=5, hole_p=0.4):
    x1l, y1l, x2l, y2l, pol = [], [], [], [], []
    pid = 0
    for gy in range(grid):
        for gx in range(grid):
            if pid >= npoly:
                break
            cx = -50 + gx * 20 + rng.uniform(-2, 2)
            cy = -40 + gy * 16 + rng.uniform(-2, 2)
            ne = int(rng.integers(8, 60))
            th = np.sort(rng.uniform(0, 2 * np.pi, ne))
            r = rng.uniform(3, 7) * (1 + 0.3 * np.sin(3 * th))
            ring = np.stack([cx + r * np.cos(th), cy + r * np.sin(th)], 1)
            ring = np.concatenate([ring, ring[:1]])
            x1l.append(ring[:-1, 0]); y1l.append(ring[:-1, 1])
            x2l.append(ring[1:, 0]); y2l.append(ring[1:, 1])
            pol.append(np.full(ne, pid))
            if rng.random() < hole_p:
                thh = np.sort(rng.uniform(0, 2 * np.pi, 12))[::-1]
                rh = r.min() * 0.4
                hr = np.stack(
                    [cx + rh * np.cos(thh), cy + rh * np.sin(thh)], 1)
                hr = np.concatenate([hr, hr[:1]])
                x1l.append(hr[:-1, 0]); y1l.append(hr[:-1, 1])
                x2l.append(hr[1:, 0]); y2l.append(hr[1:, 1])
                pol.append(np.full(12, pid))
            pid += 1
    return (np.concatenate(x1l), np.concatenate(y1l),
            np.concatenate(x2l), np.concatenate(y2l),
            np.concatenate(pol))


def oracle(px, py, x1, y1, x2, y2):
    condx = (y1[None] <= py[:, None]) != (y2[None] <= py[:, None])
    t = (py[:, None] - y1[None]) / np.where(y2 == y1, 1.0, y2 - y1)[None]
    xc = x1[None] + t * (x2 - x1)[None]
    return (np.sum(condx & (xc > px[:, None]), 1) % 2) == 1


def make_points(rng, x1, y1, x2, y2, n=30_000, na=300):
    px = rng.uniform(-60, 60, n)
    py = rng.uniform(-50, 50, n)
    ei = rng.integers(0, len(x1), na)
    tt = rng.uniform(0, 1, na)
    off = rng.uniform(-1e-6, 1e-6, na)
    px[:na] = x1[ei] + tt * (x2[ei] - x1[ei]) + off
    py[:na] = y1[ei] + tt * (y2[ei] - y1[ei]) + off
    order = np.argsort(px + 1e-3 * py)  # pseudo store order
    return px[order], py[order]


class TestPipLayer:
    def test_parity_with_holes_and_adversarial(self):
        rng = np.random.default_rng(2)
        x1, y1, x2, y2, pol = make_layer(rng)
        px, py = make_points(rng, x1, y1, x2, y2)
        inside, info = pip_layer(px, py, x1, y1, x2, y2, pol,
                                 interpret=True)
        exp = oracle(px, py, x1, y1, x2, y2)
        assert (inside == exp).all()
        assert info["pairs"] > 0 and info["refined"] > 0

    def test_vertex_aligned_far_points_exact_and_unflagged(self):
        """Points whose y sits on polygon-vertex ys but far away in x:
        the pre-round-5 endpoint strip flagged essentially all of them
        (23% of config-2 points — the first-query bottleneck); the
        vertex-consistency argument (_crossing_and_band docstring) says
        they need no f64 refinement and must still match the oracle."""
        rng = np.random.default_rng(7)
        x1, y1, x2, y2, pol = make_layer(rng)
        k = 4096
        vi = rng.integers(0, len(x1), k)
        py = y1[vi] + rng.choice([0.0, 1e-7, -1e-7], k)
        px = rng.uniform(-60, 60, k)
        o = np.argsort(px + 1e-3 * py)
        px, py = px[o], py[o]
        inside, info = pip_layer(px, py, x1, y1, x2, y2, pol,
                                 interpret=True)
        exp = oracle(px, py, x1, y1, x2, y2)
        assert (inside == exp).all()
        # flagging must be edge-proximity-local now, not strip-global
        assert info["flagged"] < k // 8

    def test_near_horizontal_edge_points_exact(self):
        """A long near-horizontal edge: both endpoint comparisons can
        flip independently, so points within rounding distance above or
        below it across its whole x-span must be flagged (near_flat)
        and refined to the f64 answer."""
        h = 2.5e-5  # edge y-slope smaller than the 1e-4 band
        ring = np.array([
            [-40.0, 10.0], [40.0, 10.0 + h], [40.0, 30.0],
            [-40.0, 30.0], [-40.0, 10.0],
        ])
        x1, y1 = ring[:-1, 0], ring[:-1, 1]
        x2, y2 = ring[1:, 0], ring[1:, 1]
        pol = np.zeros(4, np.int64)
        rng = np.random.default_rng(9)
        k = 2048
        px = rng.uniform(-39, 39, k)
        # y on/around the shallow edge at each point's x, within f32 noise
        ye = 10.0 + (px + 40.0) / 80.0 * h
        py = ye + rng.uniform(-1e-6, 1e-6, k)
        o = np.argsort(px)
        px, py = px[o], py[o]
        inside, info = pip_layer(px, py, x1, y1, x2, y2, pol,
                                 interpret=True)
        exp = oracle(px, py, x1, y1, x2, y2)
        assert (inside == exp).all()
        assert info["refined"] > 0  # the band caught them

    def test_chunked_calls_match_single_call(self):
        # force multi-chunk execution INCLUDING an intra-tile split: the
        # per-chunk partial counts must add exactly (round-3 review:
        # chunking had zero coverage)
        rng = np.random.default_rng(3)
        x1, y1, x2, y2, pol = make_layer(rng, npoly=10)
        px, py = make_points(rng, x1, y1, x2, y2, n=8000, na=0)
        prep = prepare_layer(px, py, x1, y1, x2, y2, pol)
        import jax.numpy as jnp

        args = (jnp.asarray(prep.pxp), jnp.asarray(prep.pyp),
                jnp.asarray(prep.ex1), jnp.asarray(prep.ey1),
                jnp.asarray(prep.ex2), jnp.asarray(prep.ey2),
                prep.pairs.pair_pt, prep.pairs.pair_et)
        kw = dict(n_ptiles=prep.n_ptiles, n_etiles=prep.n_etiles,
                  interpret=True)
        c1, b1 = pip_layer_sparse(*args, **kw)
        assert len(prep.pairs.pair_pt) > 3
        c2, b2 = pip_layer_sparse(*args, max_pairs_per_call=2, **kw)
        cov = np.repeat(prep.pairs.covered, 512)
        np.testing.assert_array_equal(np.asarray(c1)[cov],
                                      np.asarray(c2)[cov])
        np.testing.assert_array_equal(np.asarray(b1)[cov],
                                      np.asarray(b2)[cov])

    def test_chunk_pairs_splits_dense_tile(self):
        pt = np.array([0, 0, 0, 0, 0, 1, 2], np.int32)
        et = np.arange(7, dtype=np.int32)
        chunks = chunk_pairs(pt, et, cap=2)
        # tile 0 (5 pairs) splits mid-tile instead of raising
        assert sum(e - s for s, e in chunks) == 7
        assert all(e - s <= 2 for s, e in chunks)

    def test_empty_layer_region(self):
        rng = np.random.default_rng(5)
        x1, y1, x2, y2, pol = make_layer(rng, npoly=4, grid=2)
        # points far from every polygon
        px = np.sort(rng.uniform(100, 170, 2000))
        py = rng.uniform(-80, 80, 2000)
        inside, info = pip_layer(px, py, x1, y1, x2, y2, pol,
                                 interpret=True)
        assert not inside.any()


class TestMultiTilePolygon:
    """Rings spanning >1 edge tile (>512 edges) exercise the per-tile
    x/y prune inside build_pairs — the path where the round-3 inverted
    x-prune lived (edge tiles RIGHT of the point tile were dropped,
    losing every +x-ray crossing; fixed round 4)."""

    def _ring(self, cx, cy, ne, rx, ry):
        th = np.linspace(0, 2 * np.pi, ne, endpoint=False)
        ring = np.stack([cx + rx * np.cos(th), cy + ry * np.sin(th)], 1)
        ring = np.concatenate([ring, ring[:1]])
        return (ring[:-1, 0], ring[:-1, 1], ring[1:, 0], ring[1:, 1])

    def test_2000_edge_ring_left_interior(self):
        # points hug the LEFT interior edge in a narrow tile: every
        # crossing comes from edge tiles strictly to their right
        x1, y1, x2, y2 = self._ring(0.0, 0.0, 2000, 30.0, 20.0)
        pol = np.zeros(2000, np.int64)
        rng = np.random.default_rng(7)
        px = np.sort(rng.uniform(-29.5, -27.0, 4096))
        py = rng.uniform(-3.0, 3.0, 4096)
        inside, info = pip_layer(px, py, x1, y1, x2, y2, pol,
                                 interpret=True)
        exp = oracle(px, py, x1, y1, x2, y2)
        assert exp.sum() > 3000  # the scenario is non-vacuous
        np.testing.assert_array_equal(inside, exp)

    def test_random_points_multi_tile_layer(self):
        # a 2000-edge ring + a 900-edge ring + small polygons, random
        # points everywhere, vs the all-edges oracle
        parts = [self._ring(0.0, 0.0, 2000, 30.0, 20.0),
                 self._ring(70.0, 10.0, 900, 12.0, 25.0),
                 self._ring(-60.0, -30.0, 64, 8.0, 8.0)]
        x1 = np.concatenate([p[0] for p in parts])
        y1 = np.concatenate([p[1] for p in parts])
        x2 = np.concatenate([p[2] for p in parts])
        y2 = np.concatenate([p[3] for p in parts])
        pol = np.concatenate([np.full(2000, 0), np.full(900, 1),
                              np.full(64, 2)])
        rng = np.random.default_rng(11)
        px, py = make_points(rng, x1, y1, x2, y2, n=4096, na=64)
        inside, info = pip_layer(px, py, x1, y1, x2, y2, pol,
                                 interpret=True)
        exp = oracle(px, py, x1, y1, x2, y2)
        np.testing.assert_array_equal(inside, exp)


def test_build_pairs_out_of_domain_polygon():
    # grid pruning must not drop polygons whose bbox leaves the lon/lat
    # domain (review finding: one-sided clamping emitted 0 pairs)
    from geomesa_tpu.engine.pip_sparse import PairList, build_pairs

    ptile_bbox = np.array([[190.0, 10.0, 191.0, 11.0]])
    etile_bbox = np.array([[189.0, 9.0, 196.0, 20.0]])
    poly_of_tile = np.array([0])
    poly_bbox = np.array([[189.0, 9.0, 196.0, 20.0]])
    pl = build_pairs(ptile_bbox, etile_bbox, poly_of_tile, poly_bbox)
    assert len(pl.pair_pt) == 1


def test_pip_layer_sharded_matches_single_device():
    # mesh variant (round 5): point tiles sharded over the 8-device CPU
    # mesh, edge table replicated — must reproduce pip_layer (and the f64
    # oracle) exactly, including band refinement of adversarial points
    from geomesa_tpu.engine.pip_sparse import pip_layer_sharded
    from geomesa_tpu.parallel import default_mesh

    rng = np.random.default_rng(11)
    x1, y1, x2, y2, pol = make_layer(rng)
    px, py = make_points(rng, x1, y1, x2, y2, n=20_000)
    mesh = default_mesh()
    inside_s, info_s = pip_layer_sharded(
        mesh, px, py, x1, y1, x2, y2, pol, interpret=True)
    exp = oracle(px, py, x1, y1, x2, y2)
    assert (inside_s == exp).all()
    assert info_s["shards"] == int(np.prod(mesh.devices.shape))
    assert info_s["pairs"] > 0


def test_layer_prep_cache_roundtrip(tmp_path):
    # persistence (round 5): save/load round trip is exact, the disk cache
    # hits on identical inputs, and a cached prep yields identical results
    from geomesa_tpu.engine.pip_sparse import (
        _PREP_MEM_CACHE, layer_prep_key, load_layer_prep, pip_layer,
        prepare_layer, prepare_layer_cached, save_layer_prep)

    rng = np.random.default_rng(17)
    x1, y1, x2, y2, pol = make_layer(rng, npoly=8)
    px, py = make_points(rng, x1, y1, x2, y2, n=4_000, na=50)
    prep = prepare_layer(px, py, x1, y1, x2, y2, pol)
    p = str(tmp_path / "prep.npz")
    save_layer_prep(prep, p)
    back = load_layer_prep(p)
    for a, b in zip(prep[:6], back[:6]):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(prep.pairs, back.pairs):
        np.testing.assert_array_equal(a, b)
    assert (prep.n_ptiles, prep.n_etiles) == (back.n_ptiles, back.n_etiles)

    _PREP_MEM_CACHE.clear()
    c1 = prepare_layer_cached(px, py, x1, y1, x2, y2, pol,
                              cache_dir=str(tmp_path))
    key = layer_prep_key(px, py, x1, y1, x2, y2, pol)
    assert (tmp_path / f"layerprep_{key}.npz").exists()
    _PREP_MEM_CACHE.clear()  # force the DISK path
    c2 = prepare_layer_cached(px, py, x1, y1, x2, y2, pol,
                              cache_dir=str(tmp_path))
    np.testing.assert_array_equal(c1.pairs.pair_pt, c2.pairs.pair_pt)
    i1, _ = pip_layer(px, py, x1, y1, x2, y2, pol, interpret=True, prep=c2)
    exp = oracle(px, py, x1, y1, x2, y2)
    assert (i1 == exp).all()
