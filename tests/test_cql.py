"""CQL tests: parser golden cases, extraction, compiled-mask parity vs oracle."""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.cql import (
    compile_filter,
    extract_bbox,
    extract_intervals,
    parse_cql,
)
from geomesa_tpu.cql import ast
from geomesa_tpu.engine.device import to_device

import reference_engine as oracle

SPEC = "name:String,age:Integer,score:Double,dtg:Date,*geom:Point:srid=4326"
T0 = int(np.datetime64("2020-06-01T00:00:00", "ms").astype(np.int64))


def make_batch(n=500, seed=0):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec("t", SPEC)
    names = rng.choice(["alpha", "beta", "gamma", "delta"], n).tolist()
    names = [None if i % 17 == 0 else v for i, v in enumerate(names)]
    return FeatureBatch.from_pydict(
        sft,
        {
            "name": names,
            "age": rng.integers(0, 100, n),
            "score": rng.uniform(-5, 5, n),
            "dtg": rng.integers(T0, T0 + 30 * 86400_000, n),
            "geom": np.stack(
                [rng.uniform(-60, 60, n), rng.uniform(-45, 45, n)], axis=1
            ),
        },
        fids=[f"f{i}" for i in range(n)],
    )


class TestParser:
    def test_simple_comparisons(self):
        f = parse_cql("age > 5")
        assert isinstance(f, ast.Comparison) and f.op == ">"
        f = parse_cql("name = 'it''s'")
        assert f.right.value == "it's"

    def test_precedence(self):
        f = parse_cql("age > 5 AND name = 'x' OR score < 3")
        assert isinstance(f, ast.Or)
        assert isinstance(f.children[0], ast.And)

    def test_not_and_parens(self):
        f = parse_cql("NOT (age > 5 OR age < 1)")
        assert isinstance(f, ast.Not) and isinstance(f.child, ast.Or)

    def test_bbox(self):
        f = parse_cql("BBOX(geom, -10, -20, 30, 40)")
        assert isinstance(f, ast.SpatialPredicate)
        assert f.geometry.bbox == (-10.0, -20.0, 30.0, 40.0)
        f2 = parse_cql("BBOX(geom, -10, -20, 30, 40, 'EPSG:4326')")
        assert f2.geometry.bbox == f.geometry.bbox

    def test_intersects_wkt(self):
        f = parse_cql("INTERSECTS(geom, POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0)))")
        assert f.op == "INTERSECTS" and f.geometry.kind == "Polygon"

    def test_dwithin_units(self):
        f = parse_cql("DWITHIN(geom, POINT (1 2), 3, kilometers)")
        assert f.distance_m == 3000.0
        f = parse_cql("DWITHIN(geom, POINT (1 2), 2, nautical miles)")
        assert f.distance_m == 3704.0

    def test_during(self):
        f = parse_cql("dtg DURING 2020-06-01T00:00:00Z/2020-06-02T00:00:00Z")
        assert f.op == "DURING" and f.end - f.start == 86400_000

    def test_during_tz_offset(self):
        f = parse_cql("dtg AFTER 2020-06-01T02:00:00+02:00")
        assert f.start == T0

    def test_between_like_in_null(self):
        assert isinstance(parse_cql("age BETWEEN 1 AND 10"), ast.Between)
        assert isinstance(parse_cql("name LIKE 'a%'"), ast.Like)
        assert parse_cql("name ILIKE 'A%'").case_insensitive
        assert parse_cql("name NOT IN ('a', 'b')").negate
        assert parse_cql("name IS NOT NULL").negate

    def test_include_exclude_empty(self):
        assert isinstance(parse_cql("INCLUDE"), ast.Include)
        assert isinstance(parse_cql("EXCLUDE"), ast.Exclude)
        assert isinstance(parse_cql(""), ast.Include)

    def test_roundtrip_through_to_cql(self):
        texts = [
            "age > 5",
            "BBOX(geom, -10, -20, 30, 40) AND dtg DURING 2020-06-01T00:00:00Z/2020-06-02T00:00:00Z",
            "name IN ('a', 'b') OR NOT (score <= 1.5)",
        ]
        for t in texts:
            f = parse_cql(t)
            f2 = parse_cql(ast.to_cql(f))
            assert f == f2, t

    def test_errors(self):
        for bad in ["age >", "BBOX(geom, 1, 2)", "name LIKE 5 AND", "((age = 1)"]:
            with pytest.raises(ValueError):
                parse_cql(bad)


class TestExtract:
    def test_bbox_and(self):
        f = parse_cql("BBOX(geom, -10, -20, 30, 40) AND age > 5")
        bb = extract_bbox(f, "geom")
        assert (bb.xmin, bb.ymin, bb.xmax, bb.ymax) == (-10, -20, 30, 40)

    def test_bbox_intersection(self):
        f = parse_cql("BBOX(geom, -10, -10, 10, 10) AND BBOX(geom, 0, 0, 20, 20)")
        bb = extract_bbox(f, "geom")
        assert (bb.xmin, bb.ymin, bb.xmax, bb.ymax) == (0, 0, 10, 10)

    def test_bbox_or_union(self):
        f = parse_cql("BBOX(geom, -10, -10, 0, 0) OR BBOX(geom, 5, 5, 20, 20)")
        bb = extract_bbox(f, "geom")
        assert (bb.xmin, bb.ymin, bb.xmax, bb.ymax) == (-10, -10, 20, 20)

    def test_bbox_or_with_unconstrained(self):
        f = parse_cql("BBOX(geom, -10, -10, 0, 0) OR age > 5")
        assert extract_bbox(f, "geom").is_whole_world

    def test_not_is_unconstrained(self):
        f = parse_cql("NOT (BBOX(geom, -10, -10, 0, 0))")
        assert extract_bbox(f, "geom").is_whole_world

    def test_dwithin_buffered(self):
        f = parse_cql("DWITHIN(geom, POINT (0 0), 111.3, kilometers)")
        bb = extract_bbox(f, "geom")
        assert bb.xmin == pytest.approx(-1.0, abs=0.02)
        assert bb.ymax == pytest.approx(1.0, abs=0.02)

    def test_intervals(self):
        f = parse_cql(
            "dtg DURING 2020-06-01T00:00:00Z/2020-06-02T00:00:00Z AND BBOX(geom, 0, 0, 1, 1)"
        )
        iv = extract_intervals(f, "dtg")
        assert iv.start == T0 and iv.end == T0 + 86400_000

    def test_interval_or_union(self):
        f = parse_cql(
            "dtg BEFORE 2020-06-01T00:00:00Z OR dtg AFTER 2020-06-03T00:00:00Z"
        )
        iv = extract_intervals(f, "dtg")
        assert iv.start is None and iv.end is None

    def test_interval_comparison(self):
        f = parse_cql("dtg >= 2020-06-01T00:00:00Z AND dtg < 2020-06-02T00:00:00Z")
        iv = extract_intervals(f, "dtg")
        assert iv.start == T0 and iv.end == T0 + 86400_000


PARITY_FILTERS = [
    "INCLUDE",
    "EXCLUDE",
    "age > 50",
    "age <= 10 OR age >= 90",
    "score BETWEEN -1.0 AND 1.0",
    "17 < age",
    "name = 'alpha'",
    "name <> 'beta'",
    "name < 'c'",
    "name LIKE 'a%'",
    "name LIKE '%ta'",
    "name ILIKE 'AL%'",
    "name NOT LIKE 'a%'",
    "name IN ('alpha', 'gamma')",
    "name NOT IN ('alpha', 'gamma')",
    "age IN (1, 2, 3, 50)",
    "name IS NULL",
    "name IS NOT NULL",
    "score IS NULL",
    "dtg DURING 2020-06-05T00:00:00Z/2020-06-10T00:00:00Z",
    "dtg BEFORE 2020-06-05T00:00:00Z",
    "dtg AFTER 2020-06-20T12:00:00Z",
    "dtg = 2020-06-05T00:00:00Z",
    "BBOX(geom, -20, -20, 20, 20)",
    "BBOX(geom, -20, -20, 20, 20) AND dtg DURING 2020-06-05T00:00:00Z/2020-06-20T00:00:00Z AND age > 30",
    "INTERSECTS(geom, POLYGON ((-30 -30, 30 -30, 30 30, -30 30, -30 -30)))",
    "WITHIN(geom, POLYGON ((-30 -30, 30 -30, 0 40, -30 30, -30 -30)))",
    "INTERSECTS(geom, POLYGON ((-30 -30, 30 -30, 30 30, -30 30, -30 -30), (-10 -10, 10 -10, 10 10, -10 10, -10 -10)))",
    "DISJOINT(geom, POLYGON ((-30 -30, 30 -30, 30 30, -30 30, -30 -30)))",
    "DWITHIN(geom, POINT (0 0), 2000, kilometers)",
    "BEYOND(geom, POINT (10 10), 1000, kilometers)",
    "DWITHIN(geom, LINESTRING (-40 -40, 40 40), 500, kilometers)",
    "NOT (age > 50 AND name = 'alpha')",
    "(name = 'alpha' OR name = 'beta') AND score > 0 AND BBOX(geom, -50, -50, 50, 50)",
    "name NOT BETWEEN 'a' AND 'c'",
    "age NOT BETWEEN 20 AND 80",
    "TOUCHES(geom, POINT (1 2))",
]


class TestCompileParity:
    @pytest.mark.parametrize("cql", PARITY_FILTERS)
    def test_parity(self, cql):
        import jax.numpy as jnp

        batch = make_batch(500)
        f = parse_cql(cql)
        expected = oracle.eval_filter(f, batch)
        compiled = compile_filter(f, batch.sft)
        dev = to_device(batch, coord_dtype=jnp.float64)
        got = np.asarray(compiled.mask(dev, batch))
        np.testing.assert_array_equal(got, expected, err_msg=cql)

    def test_parity_with_padding(self):
        import jax.numpy as jnp

        batch = make_batch(100).pad_to(128)
        f = parse_cql("age >= 0")  # matches everything valid
        compiled = compile_filter(f, batch.sft)
        dev = to_device(batch, coord_dtype=jnp.float64)
        got = np.asarray(compiled.mask(dev, batch))
        assert got.sum() == 100  # padding never matches

    def test_unknown_attribute_raises(self):
        batch = make_batch(10)
        with pytest.raises(ValueError, match="unknown attribute"):
            compile_filter(parse_cql("bogus = 1"), batch.sft)

    def test_param_reuse_across_batches(self):
        import jax.numpy as jnp

        f = parse_cql("name = 'alpha' AND age > 30")
        b1 = make_batch(200, seed=1)
        compiled = compile_filter(f, b1.sft)
        for seed in (1, 2, 3):
            b = make_batch(200, seed=seed)
            dev = to_device(b, coord_dtype=jnp.float64)
            got = np.asarray(compiled.mask(dev, b))
            np.testing.assert_array_equal(got, oracle.eval_filter(f, b))


POLY_SPEC = "name:String,*geom:Polygon"

POLY_FILTERS = [
    "BBOX(geom, 2, 2, 8, 8)",
    "INTERSECTS(geom, POLYGON ((2 2, 8 2, 8 8, 2 8, 2 2)))",
    "WITHIN(geom, POLYGON ((-1 -1, 11 -1, 11 11, -1 11, -1 -1)))",
    "CONTAINS(geom, POINT (3.5 3.5))",
    "CONTAINS(geom, POLYGON ((3.1 3.1, 3.4 3.1, 3.4 3.4, 3.1 3.4, 3.1 3.1)))",
    "DISJOINT(geom, POLYGON ((20 20, 30 20, 30 30, 20 30, 20 20)))",
    "DWITHIN(geom, POINT (12 5), 300, kilometers)",
]


def make_poly_batch(n=60, seed=3):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec("p", POLY_SPEC)
    wkts = []
    for i in range(n):
        cx, cy = rng.uniform(0, 10, 2)
        w, h = rng.uniform(0.2, 3.0, 2)
        wkts.append(
            f"POLYGON (({cx-w} {cy-h}, {cx+w} {cy-h}, {cx+w} {cy+h}, {cx-w} {cy+h}, {cx-w} {cy-h}))"
        )
    return FeatureBatch.from_pydict(
        sft, {"name": [f"p{i}" for i in range(n)], "geom": wkts}
    )


class TestExtendedGeometryParity:
    @pytest.mark.parametrize("cql", POLY_FILTERS)
    def test_parity(self, cql):
        import jax.numpy as jnp

        batch = make_poly_batch()
        f = parse_cql(cql)
        expected = oracle.eval_filter(f, batch)
        compiled = compile_filter(f, batch.sft)
        dev = to_device(batch, coord_dtype=jnp.float64)
        got = np.asarray(compiled.mask(dev, batch))
        np.testing.assert_array_equal(got, expected, err_msg=cql)

    def test_linestring_data_parity(self):
        import jax.numpy as jnp

        sft = SimpleFeatureType.from_spec("l", "name:String,*geom:LineString")
        batch = FeatureBatch.from_pydict(
            sft,
            {
                "name": ["through", "outside", "inside"],
                "geom": [
                    "LINESTRING (0 0, 10 5)",       # passes through the literal
                    "LINESTRING (20 20, 30 25)",    # far away
                    "LINESTRING (1.2 2.2, 1.8 2.8)",  # wholly inside
                ],
            },
        )
        dev = to_device(batch, coord_dtype=jnp.float64)
        for cql, expect in [
            ("INTERSECTS(geom, POLYGON ((1 2, 6 2, 6 4, 1 4, 1 2)))", [True, False, True]),
            ("WITHIN(geom, POLYGON ((1 2, 6 2, 6 4, 1 4, 1 2)))", [False, False, True]),
            ("DISJOINT(geom, POLYGON ((1 2, 2 2, 2 3, 1 3, 1 2)))", [True, True, False]),
        ]:
            f = parse_cql(cql)
            got = np.asarray(compile_filter(f, sft).mask(dev, batch)).tolist()
            assert got == expect, cql
            np.testing.assert_array_equal(got, oracle.eval_filter(f, batch), err_msg=cql)

    def test_known_answers(self):
        import jax.numpy as jnp

        sft = SimpleFeatureType.from_spec("p", POLY_SPEC)
        batch = FeatureBatch.from_pydict(
            sft,
            {
                "name": ["inside", "straddle", "outside", "surrounds"],
                "geom": [
                    "POLYGON ((1 1, 2 1, 2 2, 1 2, 1 1))",
                    "POLYGON ((4 4, 6 4, 6 6, 4 6, 4 4))",
                    "POLYGON ((20 20, 21 20, 21 21, 20 21, 20 20))",
                    "POLYGON ((-5 -5, 15 -5, 15 15, -5 15, -5 -5))",
                ],
            },
        )
        dev = to_device(batch, coord_dtype=jnp.float64)
        lit = "POLYGON ((0 0, 5 0, 5 5, 0 5, 0 0))"
        got = lambda cql: np.asarray(
            compile_filter(parse_cql(cql), sft).mask(dev, batch)
        ).tolist()
        assert got(f"INTERSECTS(geom, {lit})") == [True, True, False, True]
        assert got(f"WITHIN(geom, {lit})") == [True, False, False, False]
        assert got(f"DISJOINT(geom, {lit})") == [False, False, True, False]
        assert got(f"CONTAINS(geom, POINT (1.5 1.5))") == [True, False, False, True]


class TestBBoxBandExactCount:
    """f64-exact counts under f32 device coords (round 4, VERDICT #5):
    points planted within f32-ulp of bbox edges must count exactly."""

    def _batch(self):
        import numpy as np

        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType

        rng = np.random.default_rng(61)
        n = 4096
        sft = SimpleFeatureType.from_spec("t", "score:Double,*geom:Point")
        x = rng.uniform(-170, 170, n)
        y = rng.uniform(-80, 80, n)
        # adversarial: coordinates straddling the bbox edge x=60 closer
        # than f32 can represent (f32(60 +- 2e-6) rounds to 60.000002/
        # 59.999998 unpredictably vs the f64 truth)
        for i in range(64):
            x[i] = 60.0 + rng.uniform(-1, 1) * 2.0e-6
            y[i] = rng.uniform(-20, 20)
        return sft, FeatureBatch.from_pydict(
            sft, {"score": rng.uniform(-1, 1, n),
                  "geom": np.stack([x, y], 1)}), x, y

    def test_count_exact_matches_f64(self):
        import jax.numpy as jnp
        import numpy as np

        from geomesa_tpu.cql import compile_filter, parse_cql
        from geomesa_tpu.engine.device import to_device

        sft, batch, x, y = self._batch()
        f = parse_cql("BBOX(geom, -60, -30, 60, 30)")
        compiled = compile_filter(f, sft)
        assert compiled.has_band  # bbox filters now carry a band
        dev = to_device(batch, coord_dtype=jnp.float32)
        got = compiled.count_exact(dev, batch)
        exp = int(np.sum((x >= -60) & (x <= 60) & (y >= -30) & (y <= 30)))
        assert got == exp
        # extra mask participates in both count and correction
        extra = jnp.asarray(np.arange(len(batch)) % 2 == 0)
        got_e = compiled.count_exact(dev, batch, extra=extra)
        exp_e = int(np.sum((x >= -60) & (x <= 60) & (y >= -30) & (y <= 30)
                           & (np.arange(len(batch)) % 2 == 0)))
        assert got_e == exp_e

    def test_store_count_exact(self, tmp_path):
        import numpy as np

        from geomesa_tpu.plan.datastore import DataStore

        sft, batch, x, y = self._batch()
        for cached in (False, True):
            ds = DataStore(str(tmp_path / ("c" if cached else "p")),
                           use_device_cache=cached)
            src = ds.create_schema(sft)
            src.write(batch)
            got = src.get_count("BBOX(geom, -60, -30, 60, 30)")
            exp = int(np.sum(
                (x >= -60) & (x <= 60) & (y >= -30) & (y <= 30)))
            assert got == exp, ("cached" if cached else "scan")
