"""Tests: converter DSL + framework, visibility security, flags, metrics, CLI."""

import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from geomesa_tpu.convert import (
    DelimitedTextConverter,
    EvalContext,
    JsonConverter,
    compile_expression,
    converter_from_config,
    schemas,
)
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.security import (
    StaticAuthorizationsProvider,
    VisibilityEvaluator,
    allow_mask,
)
from geomesa_tpu.utils.config import SystemProperties
from geomesa_tpu.utils.metrics import MetricsRegistry


class TestTransforms:
    def ctx(self, *pos, **named):
        return EvalContext(list(pos), named, line_no=3)

    def test_refs_and_casts(self):
        assert compile_expression("$1::int")(self.ctx("x", "42")) == 42
        assert compile_expression("$2::double")(self.ctx("x", "1", "2.5")) == 2.5
        assert compile_expression("$name")(self.ctx(named={})) is None

    def test_functions(self):
        assert compile_expression("concat($1, '-', $2)")(self.ctx("", "a", "b")) == "a-b"
        assert compile_expression("lowercase(trim($1))")(self.ctx("", "  AB ")) == "ab"
        assert compile_expression("point($1, $2)")(self.ctx("", "1.5", "2.5")) == (1.5, 2.5)
        assert compile_expression("toInt($1, 7)")(self.ctx("", "bad")) == 7
        assert compile_expression("withDefault($1, 'x')")(self.ctx("", "")) == "x"
        assert compile_expression("lineNo()")(self.ctx("")) == 3
        assert len(compile_expression("md5($1)")(self.ctx("", "v"))) == 32

    def test_dates(self):
        ms = compile_expression("dateParse('yyyyMMdd', $1)")(self.ctx("", "20200601"))
        assert ms == int(np.datetime64("2020-06-01", "ms").astype(np.int64))
        ms = compile_expression("isoDateTime($1)")(self.ctx("", "2020-06-01T12:00:00Z"))
        assert ms == int(np.datetime64("2020-06-01T12:00:00", "ms").astype(np.int64))
        assert compile_expression("secsToDate($1)")(self.ctx("", "100")) == 100_000

    def test_nested(self):
        e = compile_expression("concat(uppercase($1), toString(toInt($2)))")
        assert e(self.ctx("", "ab", "9")) == "AB9"

    def test_errors(self):
        with pytest.raises(ValueError):
            compile_expression("nosuchfn($1)")
        with pytest.raises(ValueError):
            compile_expression("$1::nosuchtype")
        with pytest.raises(ValueError):
            compile_expression("toInt(")


CSV = """id,name,lat,lon,when
1,alpha,51.5,-0.1,2020-06-01T00:00:00Z
2,beta,48.8,2.35,2020-06-02T00:00:00Z
3,,48.8,2.35,2020-06-03T00:00:00Z
bad,gamma,not_a_lat,xx,2020-06-04T00:00:00Z
"""


class TestConverters:
    def make(self):
        sft = SimpleFeatureType.from_spec(
            "t", "name:String,dtg:Date,*geom:Point"
        )
        config = {
            "type": "delimited-text",
            "format": "CSV",
            "options": {"skip-lines": 1},
            "id-field": "$1",
            "fields": [
                {"name": "name", "transform": "withDefault($2, 'unknown')"},
                {"name": "dtg", "transform": "isoDateTime($5)"},
                {"name": "geom", "transform": "point($4, $3)"},
            ],
        }
        return sft, config

    def test_csv(self):
        sft, config = self.make()
        conv = DelimitedTextConverter(sft, config)
        batch = conv.convert(io.StringIO(CSV))
        assert len(batch) == 3  # bad record skipped
        assert conv.failed == 1
        assert batch.fids.decode() == ["1", "2", "3"]
        assert batch.column("name").decode() == ["alpha", "beta", "unknown"]
        np.testing.assert_allclose(batch.geometry.x, [-0.1, 2.35, 2.35])

    def test_skip_keeps_columns_aligned(self):
        # a record failing geometry validation must not leave earlier
        # columns partially appended (silent row misalignment)
        sft = SimpleFeatureType.from_spec("t", "name:String,*geom:Point")
        config = {
            "type": "delimited-text",
            "fields": [
                {"name": "name", "transform": "$1"},
                {"name": "geom", "transform": "point($2, $3)"},
            ],
        }
        conv = DelimitedTextConverter(sft, config)
        batch = conv.convert(io.StringIO("a,1,2\nbad,,\nc,5,6\n"))
        assert conv.failed == 1
        assert batch.column("name").decode() == ["a", "c"]
        np.testing.assert_allclose(batch.geometry.x, [1.0, 5.0])

    def test_json_missing_path_stays_null(self):
        # $0 must be the extracted path value (None when missing), never the
        # whole record object
        sft = SimpleFeatureType.from_spec("t", "name:String,*geom:Point")
        config = {
            "type": "json",
            "fields": [
                {"name": "name", "path": "$.props.name",
                 "transform": "withDefault($0, 'UNKNOWN')"},
                {"name": "lon", "path": "$.loc.0"},
                {"name": "lat", "path": "$.loc.1"},
                {"name": "geom", "transform": "point($lon, $lat)"},
            ],
        }
        conv = converter_from_config(sft, config)
        batch = conv.convert(io.StringIO(json.dumps({"loc": [1.0, 2.0]})))
        assert batch.column("name").decode() == ["UNKNOWN"]

    def test_raise_mode(self):
        sft, config = self.make()
        config["options"]["error-mode"] = "raise-errors"
        conv = DelimitedTextConverter(sft, config)
        with pytest.raises(Exception):
            conv.convert(io.StringIO(CSV))

    def test_json(self):
        sft = SimpleFeatureType.from_spec("t", "name:String,dtg:Date,*geom:Point")
        config = {
            "type": "json",
            "id-field": "$name",
            "fields": [
                {"name": "name", "path": "$.props.name"},
                {"name": "dtg", "path": "$.when", "transform": "isoDateTime($0)"},
                {"name": "lon", "path": "$.loc.0"},
                {"name": "lat", "path": "$.loc.1"},
                {"name": "geom", "transform": "point($lon, $lat)"},
            ],
        }
        lines = "\n".join(
            json.dumps(
                {"props": {"name": f"n{i}"}, "when": "2020-06-01T00:00:00Z",
                 "loc": [i * 1.0, i * 2.0]}
            )
            for i in range(4)
        )
        conv = converter_from_config(sft, config)
        assert isinstance(conv, JsonConverter)
        batch = conv.convert(io.StringIO(lines))
        assert len(batch) == 4
        np.testing.assert_allclose(batch.geometry.x, [0, 1, 2, 3])
        np.testing.assert_allclose(batch.geometry.y, [0, 2, 4, 6])

    def test_gdelt_schema(self):
        sft, config = schemas.WELL_KNOWN["gdelt"]
        cols = [""] * 57
        cols[0] = "e1"
        cols[1] = "20200601"
        cols[6] = "FRANCE"
        cols[26] = "043"
        cols[30] = "2.4"
        cols[31] = "12"
        cols[53] = "48.85"  # ActionGeo_Lat ($54)
        cols[54] = "2.35"   # ActionGeo_Long ($55)
        tsv = "\t".join(cols)
        conv = converter_from_config(sft, config)
        batch = conv.convert(io.StringIO(tsv))
        assert len(batch) == 1
        assert batch.column("Actor1Name").decode() == ["FRANCE"]
        assert batch.column("GoldsteinScale")[0] == pytest.approx(2.4)
        assert batch.geometry.x[0] == pytest.approx(2.35)

    def test_ais_schema(self):
        sft, config = schemas.WELL_KNOWN["ais"]
        csv_text = (
            "MMSI,BaseDateTime,LAT,LON,SOG,COG,Heading,VesselName\n"
            "367000001,2021-03-01T00:00:01,29.9,-90.1,7.5,180.0,181.0,EVER GIVEN\n"
        )
        conv = converter_from_config(sft, config)
        batch = conv.convert(io.StringIO(csv_text))
        assert len(batch) == 1
        assert batch.column("VesselName").decode() == ["EVER GIVEN"]
        assert batch.geometry.y[0] == pytest.approx(29.9)

    def test_osm_schema(self):
        sft, config = schemas.WELL_KNOWN["osm"]
        csv_text = "42,2.35,48.85,mapper,3,2021-05-01T12:00:00,amenity=cafe\n"
        conv = converter_from_config(sft, config)
        batch = conv.convert(io.StringIO(csv_text))
        assert len(batch) == 1
        assert batch.column("osm_id").decode() == ["42"]
        assert batch.column("version")[0] == 3
        assert batch.geometry.x[0] == pytest.approx(2.35)

    def test_twitter_schema(self):
        import json as _json

        sft, config = schemas.WELL_KNOWN["twitter"]
        tweet = {
            "id_str": "123", "text": "hello",
            "user": {"screen_name": "alice"},
            "created_at": "Wed Aug 27 13:08:45 +0000 2008",
            "coordinates": {"type": "Point", "coordinates": [-74.0, 40.7]},
        }
        conv = converter_from_config(sft, config)
        batch = conv.convert(io.StringIO(_json.dumps(tweet)))
        assert len(batch) == 1
        assert batch.column("user_name").decode() == ["alice"]
        assert batch.geometry.y[0] == pytest.approx(40.7)
        assert batch.column("dtg")[0] == 1219842525000


class TestVisibility:
    def test_parse_eval(self):
        ev = VisibilityEvaluator()
        assert ev.can_see("", ["any"])
        assert ev.can_see(None, [])
        assert ev.can_see("admin", ["admin"])
        assert not ev.can_see("admin", ["user"])
        assert ev.can_see("admin&(usa|gbr)", ["admin", "gbr"])
        assert not ev.can_see("admin&(usa|gbr)", ["admin"])
        assert not ev.can_see("admin&(usa|gbr)", ["usa", "gbr"])
        assert ev.can_see("a|b|c", ["c"])
        assert ev.can_see('"weird label"&x', ["weird label", "x"])

    def test_mixing_requires_parens(self):
        ev = VisibilityEvaluator()
        with pytest.raises(ValueError):
            ev.can_see("a&b|c", ["a"])

    def test_allow_mask(self):
        vocab = ["admin", "admin&usa", None, "public|admin"]
        codes = np.array([0, 1, 2, 3, -1, 1], np.int32)
        m = allow_mask(vocab, codes, ["admin"])
        np.testing.assert_array_equal(m, [True, False, True, True, True, False])
        m2 = allow_mask(vocab, codes, ["admin", "usa"])
        assert m2.all()

    def test_provider(self):
        p = StaticAuthorizationsProvider(["a", "b"])
        assert p.get_authorizations() == ["a", "b"]


class TestSystemProperties:
    def test_default_env_override(self, monkeypatch):
        prop = SystemProperties.SCAN_RANGES_TARGET
        assert prop.get() == 2000
        assert prop.provenance == "default"
        monkeypatch.setenv("GEOMESA_TPU_SCAN_RANGES_TARGET", "512")
        assert prop.get() == 512
        assert prop.provenance.startswith("env:")
        SystemProperties.set(prop.name, 64)
        assert prop.get() == 64
        assert prop.provenance == "override"
        SystemProperties.clear(prop.name)
        assert prop.get() == 512

    def test_registry(self):
        assert "geomesa.scan.ranges.target" in SystemProperties.all()


class TestMetrics:
    def test_counters_timers(self):
        m = MetricsRegistry()
        m.counter("ingest.features", 10)
        m.counter("ingest.features", 5)
        m.gauge("cache.bytes", 1024)
        with m.timer("query"):
            pass
        data = json.loads(m.to_json())
        assert data["counters"]["ingest.features"] == 15
        assert data["gauges"]["cache.bytes"] == 1024
        assert data["timers"]["query"]["count"] == 1
        prom = m.to_prometheus()
        assert "ingest_features 15" in prom
        assert "query_seconds_count 1" in prom


@pytest.fixture()
def cli_env(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = "/root/repo"
    env["JAX_PLATFORMS"] = "cpu"
    # the axon sitecustomize pins jax_platforms; geomesa CLI paths that
    # touch jax need the conftest-style workaround, applied via sitecustomize
    site = tmp_path / "site"
    site.mkdir()
    (site / "sitecustomize.py").write_text(
        "import jax\n"
        "from jax._src import xla_bridge as xb\n"
        "for k in ('axon','tpu'): xb._backend_factories.pop(k, None)\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
    )
    env["PYTHONPATH"] = f"{site}:/root/repo"
    return env


def run_cli(args, env):
    return subprocess.run(
        [sys.executable, "-m", "geomesa_tpu.cli.main"] + args,
        capture_output=True, text=True, env=env, timeout=120,
    )


class TestCLI:
    def test_end_to_end(self, tmp_path, cli_env):
        cat = str(tmp_path / "catalog")
        r = run_cli(["create-schema", "-c", cat, "-f", "pois",
                     "-s", "name:String,dtg:Date,*geom:Point"], cli_env)
        assert r.returncode == 0, r.stderr
        r = run_cli(["get-type-names", "-c", cat], cli_env)
        assert r.stdout.strip() == "pois"
        r = run_cli(["describe-schema", "-c", cat, "-f", "pois"], cli_env)
        assert "String" in r.stdout and "*default geometry" in r.stdout

        # ingest via a converter config file
        conv = tmp_path / "conv.json"
        conv.write_text(json.dumps({
            "type": "delimited-text", "format": "CSV",
            "options": {"skip-lines": 1},
            "id-field": "$1",
            "fields": [
                {"name": "name", "transform": "$2::string"},
                {"name": "dtg", "transform": "isoDateTime($3)"},
                {"name": "geom", "transform": "point($4, $5)"},
            ],
        }))
        data = tmp_path / "data.csv"
        data.write_text(
            "id,name,when,lon,lat\n"
            "1,cafe,2020-06-01T00:00:00Z,2.35,48.85\n"
            "2,pub,2020-06-02T00:00:00Z,-0.1,51.5\n"
        )
        r = run_cli(["ingest", "-c", cat, "-f", "pois", "-C", str(conv), str(data)], cli_env)
        assert "ingested 2 features" in r.stdout, r.stderr

        r = run_cli(["stats-count", "-c", cat, "-f", "pois"], cli_env)
        assert r.stdout.strip() == "2"
        r = run_cli(["export", "-c", cat, "-f", "pois", "-q", "name = 'cafe'",
                     "-F", "csv"], cli_env)
        assert "cafe" in r.stdout and "pub" not in r.stdout

        # round 5: export in a projected CRS (explicit EPSG and auto-UTM)
        r = run_cli(["export", "-c", cat, "-f", "pois", "-q",
                     "BBOX(geom, 0, 45, 5, 50)", "-F", "csv",
                     "--crs", "3857"], cli_env)
        assert r.returncode == 0, r.stderr
        assert "261600.80" in r.stdout  # 2.35 deg lon -> 261600.8 m web mercator
        r = run_cli(["export", "-c", cat, "-f", "pois", "-q",
                     "BBOX(geom, 0, 45, 5, 50)", "-F", "csv",
                     "--crs", "utm"], cli_env)
        assert r.returncode == 0, r.stderr
        assert "auto UTM zone: EPSG:32631" in r.stderr  # lon 2.5 -> zone 31
        r = run_cli(["export", "-c", cat, "-f", "pois", "-q", "INCLUDE",
                     "-F", "csv", "--crs", "utm"], cli_env)
        assert r.returncode != 0  # no spatial filter: zone is ambiguous
        # mixed-case prefix parses; garbage gets a clear error, not a
        # traceback; projected CRS is rejected for formats that would
        # silently corrupt (bin stores raw lon/lat, leaflet plots lat/lng)
        r = run_cli(["export", "-c", cat, "-f", "pois", "-q",
                     "BBOX(geom, 0, 45, 5, 50)", "-F", "csv",
                     "--crs", "Epsg:3857"], cli_env)
        assert r.returncode == 0 and "261600.80" in r.stdout, r.stderr
        r = run_cli(["export", "-c", cat, "-f", "pois", "-F", "csv",
                     "--crs", "3857m"], cli_env)
        assert r.returncode != 0 and "EPSG" in r.stderr
        r = run_cli(["export", "-c", cat, "-f", "pois", "-F", "bin",
                     "--crs", "3857"], cli_env)
        assert r.returncode != 0 and "bin" in r.stderr.lower()
        r = run_cli(["export", "-c", cat, "-f", "pois", "-F", "leaflet",
                     "--crs", "3857"], cli_env)
        assert r.returncode != 0 and "leaflet" in r.stderr.lower()
        r = run_cli(["export", "-c", cat, "-f", "pois", "-F", "gml"], cli_env)
        assert r.returncode == 0, r.stderr
        assert "<gml:FeatureCollection" in r.stdout and "gml:pos" in r.stdout
        for fmt in ("parquet", "orc"):
            out = str(tmp_path / f"out.{fmt}")
            r = run_cli(["export", "-c", cat, "-f", "pois", "-F", fmt,
                         "-o", out], cli_env)
            assert r.returncode == 0, r.stderr
            import pyarrow.orc as paorc
            import pyarrow.parquet as papq

            t = (papq if fmt == "parquet" else paorc).read_table(out)
            assert t.num_rows == 2
        r = run_cli(["explain", "-c", cat, "-f", "pois",
                     "-q", "BBOX(geom, 0, 40, 5, 50)"], cli_env)
        assert "Partitions" in r.stdout
        r = run_cli(["stats-analyze", "-c", cat, "-f", "pois"], cli_env)
        assert r.returncode == 0, r.stderr
        r = run_cli(["stats-top-k", "-c", cat, "-f", "pois", "-a", "name"], cli_env)
        assert "cafe\t1" in r.stdout
        r = run_cli(["env"], cli_env)
        assert "geomesa.scan.ranges.target" in r.stdout

    def test_version_and_help(self, cli_env):
        assert run_cli(["version"], cli_env).returncode == 0
        r = run_cli([], cli_env)
        assert r.returncode == 1


class TestCliSql:
    def test_sql_subcommand(self, tmp_path, cli_env):
        cat = str(tmp_path / "catalog")
        r = run_cli(["create-schema", "-c", cat, "-f", "ev",
                     "-s", "actor:String,score:Double,dtg:Date,*geom:Point"],
                    cli_env)
        assert r.returncode == 0, r.stderr
        conv = tmp_path / "conv.json"
        conv.write_text(json.dumps({
            "type": "delimited-text", "format": "CSV",
            "id-field": "$1",
            "fields": [
                {"name": "actor", "transform": "$2::string"},
                {"name": "score", "transform": "$3::double"},
                {"name": "dtg", "transform": "isoDateTime($4)"},
                {"name": "geom", "transform": "point($5, $6)"},
            ],
        }))
        data = tmp_path / "ev.csv"
        rows = [
            "1,USA,2.0,2020-06-01T00:00:00Z,1.0,2.0",
            "2,USA,4.0,2020-06-01T00:00:00Z,3.0,4.0",
            "3,FRA,6.0,2020-06-01T00:00:00Z,5.0,6.0",
        ]
        data.write_text("\n".join(rows) + "\n")
        r = run_cli(["ingest", "-c", cat, "-f", "ev", "-C", str(conv),
                     str(data)], cli_env)
        assert "ingested 3 features" in r.stdout, r.stderr
        r = run_cli(["sql", "-c", cat, "-q",
                     "SELECT actor, COUNT(*) AS n, SUM(score) AS s FROM ev "
                     "GROUP BY actor ORDER BY actor"], cli_env)
        assert r.returncode == 0, r.stderr
        lines = r.stdout.strip().splitlines()
        assert lines[0] == "actor,n,s"
        assert lines[1].startswith("FRA,1,6") and lines[2].startswith("USA,2,6")
        r = run_cli(["sql", "-c", cat, "-F", "json", "-q",
                     "SELECT COUNT(*) FROM ev WHERE score > 3"], cli_env)
        assert r.stdout.strip() == "2"


class TestCLIDeleteFeatures:
    def test_delete_and_age_off(self, tmp_path, cli_env):
        cat = str(tmp_path / "catalog")
        r = run_cli(["create-schema", "-c", cat, "-f", "ev",
                     "-s", "name:String,dtg:Date,*geom:Point"], cli_env)
        assert r.returncode == 0, r.stderr
        csv = tmp_path / "rows.csv"
        csv.write_text(
            "id,name,dtg,lon,lat\n"
            "1,alpha,2020-06-01T00:00:00,10.0,20.0\n"
            "2,beta,2020-06-20T00:00:00,11.0,21.0\n"
            "3,alpha,2020-07-05T00:00:00,12.0,22.0\n"
        )
        conv = tmp_path / "conv.json"
        conv.write_text(json.dumps({
            "type": "delimited-text", "format": "CSV",
            "options": {"skip-lines": 1},
            "id-field": "$1",
            "fields": [
                {"name": "name", "transform": "$2::string"},
                {"name": "dtg", "transform": "isoDateTime($3)"},
                {"name": "geom", "transform": "point($4, $5)"},
            ],
        }))
        r = run_cli(["ingest", "-c", cat, "-f", "ev",
                     "--converter", str(conv), str(csv)], cli_env)
        assert r.returncode == 0, r.stderr
        r = run_cli(["delete-features", "-c", cat, "-f", "ev",
                     "-q", "name = 'beta'"], cli_env)
        assert r.returncode == 0, r.stderr
        assert "deleted 1 features" in r.stdout
        r = run_cli(["age-off", "-c", cat, "-f", "ev",
                     "--older-than", "2020-07-01T00:00:00Z"], cli_env)
        assert "aged off 1 features" in r.stdout
        r = run_cli(["stats-count", "-c", cat, "-f", "ev",
                     "-q", "INCLUDE"], cli_env)
        assert r.returncode == 0, r.stderr
        assert "1" in r.stdout
