"""Reprojection tests (round 4, VERDICT #7): registry, round trip,
closed-form oracle, runner finish step, st_transform."""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.crs import R_MAJOR, reproject_batch, transform
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.query import Query


class TestTransform:
    def test_closed_form_oracle(self):
        # independent mercator formula on a few known points
        lon = np.array([0.0, 10.0, -77.0365, 151.2093])
        lat = np.array([0.0, 53.55, 38.8977, -33.8688])
        mx, my = transform(lon, lat, 4326, 3857)
        np.testing.assert_allclose(mx, lon * np.pi / 180.0 * R_MAJOR,
                                   rtol=1e-12)
        exp_y = R_MAJOR * np.log(
            np.tan(np.pi / 4 + np.radians(lat) / 2))
        np.testing.assert_allclose(my, exp_y, rtol=1e-12)
        # independent constant: y(45N) = R * ln(tan(3pi/8)) = R * asinh(1)
        y45 = transform([0.0], [45.0], 4326, 3857)[1][0]
        assert abs(y45 - R_MAJOR * np.arcsinh(1.0)) < 1e-6

    def test_round_trip(self):
        rng = np.random.default_rng(3)
        lon = rng.uniform(-179, 179, 1000)
        lat = rng.uniform(-84, 84, 1000)
        mx, my = transform(lon, lat, 4326, 3857)
        lon2, lat2 = transform(mx, my, 3857, 4326)
        np.testing.assert_allclose(lon2, lon, atol=1e-9)
        np.testing.assert_allclose(lat2, lat, atol=1e-9)

    def test_identity_and_unknown(self):
        x, y = transform([1.0], [2.0], 4326, 4326)
        assert x[0] == 1.0 and y[0] == 2.0
        with pytest.raises(ValueError, match="unsupported CRS"):
            transform([0.0], [0.0], 4326, 27700)  # OSGB: not registered


def _snyder_utm(lon, lat, lon0, fn):
    """INDEPENDENT oracle: Snyder (1987) eq. 8-9..8-13 truncated series for
    the ellipsoidal transverse Mercator — a different formulation from the
    Krueger flattening series in core.crs (different expansion variable:
    e^2, not n). Agreement << 1 mm in-zone certifies both."""
    a, f = 6378137.0, 1 / 298.257223563
    e2 = f * (2 - f)
    ep2 = e2 / (1 - e2)
    k0 = 0.9996
    phi = np.radians(np.asarray(lat, np.float64))
    lam = np.radians(np.asarray(lon, np.float64) - lon0)
    sp, cp = np.sin(phi), np.cos(phi)
    N = a / np.sqrt(1 - e2 * sp**2)
    T = (sp / cp) ** 2
    C = ep2 * cp**2
    A = lam * cp
    M = a * (
        (1 - e2 / 4 - 3 * e2**2 / 64 - 5 * e2**3 / 256) * phi
        - (3 * e2 / 8 + 3 * e2**2 / 32 + 45 * e2**3 / 1024) * np.sin(2 * phi)
        + (15 * e2**2 / 256 + 45 * e2**3 / 1024) * np.sin(4 * phi)
        - (35 * e2**3 / 3072) * np.sin(6 * phi)
    )
    E = 500000.0 + k0 * N * (
        A + (1 - T + C) * A**3 / 6
        + (5 - 18 * T + T**2 + 72 * C - 58 * ep2) * A**5 / 120
    )
    Nn = fn + k0 * (
        M + N * (sp / cp) * (
            A**2 / 2 + (5 - T + 9 * C + 4 * C**2) * A**4 / 24
            + (61 - 58 * T + T**2 + 600 * C - 330 * ep2) * A**6 / 720
        )
    )
    return E, Nn


class TestUTM:
    def test_against_snyder_oracle(self):
        # in-zone points across hemispheres and latitudes (zone 33: lon0=15)
        lon = np.array([15.0, 12.5, 17.9, 13.3, 16.7])
        lat = np.array([0.5, 48.2, 67.9, 22.0, 5.1])
        ex, ey = _snyder_utm(lon, lat, 15.0, 0.0)
        gx, gy = transform(lon, lat, 4326, 32633)
        np.testing.assert_allclose(gx, ex, atol=1e-3)  # < 1 mm
        np.testing.assert_allclose(gy, ey, atol=1e-3)
        # southern hemisphere, zone 56 (lon0=153): Sydney-ish
        ex, ey = _snyder_utm([151.2093], [-33.8688], 153.0, 10_000_000.0)
        gx, gy = transform([151.2093], [-33.8688], 4326, 32756)
        np.testing.assert_allclose(gx, ex, atol=1e-3)
        np.testing.assert_allclose(gy, ey, atol=1e-3)

    def test_anchor_points(self):
        # equator on the central meridian is EXACTLY (500000, 0) north
        e, n = transform([15.0], [0.0], 4326, 32633)
        assert abs(e[0] - 500000.0) < 1e-6 and abs(n[0]) < 1e-6
        # and (500000, 10000000) south
        e, n = transform([153.0], [0.0], 4326, 32756)
        assert abs(e[0] - 500000.0) < 1e-6 and abs(n[0] - 1e7) < 1e-6
        # meridian scale factor == k0: 1 deg of northing near the equator
        e1, n1 = transform([15.0], [0.0], 4326, 32633)
        e2, n2 = transform([15.0], [1e-4], 4326, 32633)
        # local meridian arc at the equator: ds = rho(0) dphi with the
        # meridional radius of curvature rho(0) = a(1-e^2)
        a, f = 6378137.0, 1 / 298.257223563
        e2_ = f * (2 - f)
        arc = a * (1 - e2_) * np.radians(1e-4)
        assert abs((n2[0] - n1[0]) / arc - 0.9996) < 1e-6

    def test_round_trip_mm(self):
        rng = np.random.default_rng(5)
        for srid, lon0, latr in ((32633, 15.0, (0.0, 84.0)),
                                 (32756, 153.0, (-80.0, 0.0))):
            lon = rng.uniform(lon0 - 3, lon0 + 3, 500)
            lat = rng.uniform(*latr, 500)
            e, n = transform(lon, lat, 4326, srid)
            lon2, lat2 = transform(e, n, srid, 4326)
            # < 1e-9 deg ~ 0.1 mm
            np.testing.assert_allclose(lon2, lon, atol=1e-9)
            np.testing.assert_allclose(lat2, lat, atol=1e-9)

    def test_cross_frame_routes(self):
        # UTM -> UTM (adjacent zones) and UTM <-> 3857 route through 4326
        lon, lat = np.array([17.5]), np.array([59.3])
        e33, n33 = transform(lon, lat, 4326, 32633)
        e34, n34 = transform(e33, n33, 32633, 32634)
        ed, nd = transform(lon, lat, 4326, 32634)
        np.testing.assert_allclose([e34[0], n34[0]], [ed[0], nd[0]],
                                   atol=1e-6)
        mx, my = transform(e33, n33, 32633, 3857)
        ex, ey = transform(lon, lat, 4326, 3857)
        np.testing.assert_allclose([mx[0], my[0]], [ex[0], ey[0]], atol=1e-6)

    def test_zone_picker(self):
        from geomesa_tpu.core.crs import utm_zone_srid

        assert utm_zone_srid(15.0, 48.0) == 32633
        assert utm_zone_srid(151.2, -33.9) == 32756
        assert utm_zone_srid(-179.9, 10.0) == 32601
        assert utm_zone_srid(179.9, -10.0) == 32760

    def test_sql_st_transform_utm(self):
        from geomesa_tpu.core.wkt import Geometry
        from geomesa_tpu.sql.functions import st_transform

        g = Geometry("Point", [np.array([[15.0, 48.0]])])
        out = st_transform(g, "EPSG:4326", "EPSG:32633")
        ex, ey = transform([15.0], [48.0], 4326, 32633)
        np.testing.assert_allclose(out.rings[0][0], [ex[0], ey[0]],
                                   rtol=1e-12)


class TestQueryReprojection:
    def test_query_crs_output(self, tmp_path):
        rng = np.random.default_rng(7)
        n = 500
        sft = SimpleFeatureType.from_spec("t", "v:Double,*geom:Point")
        x = rng.uniform(-170, 170, n)
        y = rng.uniform(-80, 80, n)
        batch = FeatureBatch.from_pydict(
            sft, {"v": rng.uniform(0, 1, n), "geom": np.stack([x, y], 1)})
        ds = DataStore(str(tmp_path / "c"))
        src = ds.create_schema(sft)
        src.write(batch)
        r = src.get_features(Query("t", "BBOX(geom, -60, -30, 60, 30)",
                                   crs=3857))
        g = r.features.columns["geom"]
        sel = ((x >= -60) & (x <= 60) & (y >= -30) & (y <= 30))
        ex, ey = transform(x[sel], y[sel], 4326, 3857)
        got = np.stack([np.sort(np.asarray(g.x)), np.sort(np.asarray(g.y))])
        np.testing.assert_allclose(
            got, np.stack([np.sort(ex), np.sort(ey)]), rtol=1e-12)
        # the result schema records its CRS
        assert r.features.sft.attribute("geom").options["srid"] == "3857"

    def test_extended_geometry_reprojection(self):
        from geomesa_tpu.core.wkt import Geometry

        sft = SimpleFeatureType.from_spec("p", "*geom:Polygon")
        sq = np.array([[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]], float)
        batch = FeatureBatch.from_pydict(
            sft, {"geom": [Geometry("Polygon", [sq])]})
        out = reproject_batch(batch, 3857)
        col = out.columns["geom"]
        vx, vy = transform(sq[:, 0], sq[:, 1], 4326, 3857)
        np.testing.assert_allclose(col.vertices[:, 0], vx, rtol=1e-12)
        np.testing.assert_allclose(col.vertices[:, 1], vy, rtol=1e-12)
        assert col.bbox[0, 2] == pytest.approx(vx.max())


def test_sql_st_transform():
    from geomesa_tpu.core.wkt import Geometry
    from geomesa_tpu.sql.functions import st_transform

    g = Geometry("Point", [np.array([[10.0, 53.55]])])
    out = st_transform(g, "EPSG:4326", "EPSG:3857")
    ex, ey = transform([10.0], [53.55], 4326, 3857)
    np.testing.assert_allclose(out.rings[0][0], [ex[0], ey[0]], rtol=1e-12)


class TestPolarLAEA:
    """Round-5 families. Oracles are geometric INVARIANTS of the
    projections (no external library exists in this env to compare
    against): polar stereographic has scale factor exactly 1 along its
    standard parallel; LAEA preserves area element exactly; both must
    round-trip to sub-mm."""

    def _scale_along_parallel(self, srid, lat, lon):
        """Local east-west scale factor k = |dE/dlam| / (parallel radius)
        by central difference, on the ellipsoid."""
        from geomesa_tpu.core.crs import transform

        a, f = 6378137.0, 1 / 298.257223563
        e2 = f * (2 - f)
        h = 1e-6
        x1, y1 = transform(np.array([lon - h]), np.array([lat]), 4326, srid)
        x2, y2 = transform(np.array([lon + h]), np.array([lat]), 4326, srid)
        dm = np.hypot(x2 - x1, y2 - y1)[0]
        phi = np.radians(lat)
        # radius of the parallel circle on the ellipsoid
        rp = a * np.cos(phi) / np.sqrt(1 - e2 * np.sin(phi) ** 2)
        return dm / (rp * np.radians(2 * h))

    def test_polar_unit_scale_at_standard_parallel(self):
        for srid, lat_ts in ((3413, 70.0), (3031, -71.0), (3976, -70.0)):
            for lon in (-120.0, -45.0, 0.0, 60.0, 179.0):
                k = self._scale_along_parallel(srid, lat_ts, lon)
                assert k == pytest.approx(1.0, abs=1e-7), (srid, lon)

    def test_polar_round_trip_mm(self):
        from geomesa_tpu.core.crs import transform

        rng = np.random.default_rng(3)
        for srid, south in ((3413, False), (3031, True), (3976, True)):
            lat = (rng.uniform(-88, -45, 500) if south
                   else rng.uniform(45, 88, 500))
            lon = rng.uniform(-180, 180, 500)
            ex, ny = transform(lon, lat, 4326, srid)
            lo, la = transform(ex, ny, srid, 4326)
            # direct comparison, no modulo-360 masking: _from_polar must
            # return the canonical [-180,180] branch itself (a wrapped
            # longitude like -190 for a true 170 is a bug, not a
            # representation choice). 1e-8 deg ~ 1 mm.
            assert np.all(lo >= -180.0) and np.all(lo <= 180.0)
            assert np.abs(lo - lon).max() < 1e-8
            assert np.abs(la - lat).max() < 1e-8

    def test_polar_pole_and_meridian_geometry(self):
        from geomesa_tpu.core.crs import transform

        # the pole maps to the origin (FE=FN=0 for all three)
        for srid, pole in ((3413, 90.0), (3031, -90.0), (3976, -90.0)):
            ex, ny = transform(np.array([33.0]), np.array([pole]),
                               4326, srid)
            assert abs(ex[0]) < 1e-6 and abs(ny[0]) < 1e-6
        # 3413: the central meridian (45W) runs down the -y axis
        ex, ny = transform(np.array([-45.0]), np.array([75.0]), 4326, 3413)
        assert abs(ex[0]) < 1e-6 and ny[0] < 0

    def test_laea_equal_area_jacobian(self):
        """The defining property: |det J| equals the ellipsoidal area
        element M*N*cos(phi) (meridian x parallel curvature radii)
        everywhere, checked by central differences across Europe."""
        from geomesa_tpu.core.crs import transform

        a, f = 6378137.0, 1 / 298.257223563
        e2 = f * (2 - f)
        h = 1e-6
        for lon, lat in ((10.0, 52.0), (-10.0, 35.0), (30.0, 70.0),
                         (25.0, 40.0), (0.0, 60.0)):
            def T(lo, la):
                x, y = transform(np.array([lo]), np.array([la]), 4326, 3035)
                return x[0], y[0]

            x0, _ = T(lon - h, lat); x1, _ = T(lon + h, lat)
            _, y0 = T(lon, lat - h); _, y1 = T(lon, lat + h)
            xa, ya = T(lon - h, lat); xb, yb = T(lon + h, lat)
            xc, yc = T(lon, lat - h); xd, yd = T(lon, lat + h)
            dxdlam = (xb - xa) / (2 * h); dydlam = (yb - ya) / (2 * h)
            dxdphi = (xd - xc) / (2 * h); dydphi = (yd - yc) / (2 * h)
            det = abs(dxdlam * dydphi - dydlam * dxdphi) * (180 / np.pi) ** 2
            phi = np.radians(lat)
            w2 = 1 - e2 * np.sin(phi) ** 2
            mrad = a * (1 - e2) / w2 ** 1.5
            nrad = a / np.sqrt(w2)
            assert det == pytest.approx(
                mrad * nrad * np.cos(phi), rel=1e-6), (lon, lat)

    def test_laea_round_trip_and_origin(self):
        from geomesa_tpu.core.crs import transform

        rng = np.random.default_rng(5)
        lon = rng.uniform(-15, 45, 1000)
        lat = rng.uniform(30, 72, 1000)
        ex, ny = transform(lon, lat, 4326, 3035)
        lo, la = transform(ex, ny, 3035, 4326)
        assert np.abs(lo - lon).max() < 1e-8
        assert np.abs(la - lat).max() < 1e-8
        # projection origin lands on the false easting/northing
        ex, ny = transform(np.array([10.0]), np.array([52.0]), 4326, 3035)
        assert ex[0] == pytest.approx(4_321_000.0, abs=1e-6)
        assert ny[0] == pytest.approx(3_210_000.0, abs=1e-6)

    def test_cross_family_routing(self):
        from geomesa_tpu.core.crs import transform

        # arctic frame -> web mercator -> back, through 4326 internally
        lon = np.array([20.0]); lat = np.array([72.0])
        ex, ny = transform(lon, lat, 4326, 3413)
        mx, my = transform(ex, ny, 3413, 3857)
        lo, la = transform(mx, my, 3857, 4326)
        assert lo[0] == pytest.approx(20.0, abs=1e-8)
        assert la[0] == pytest.approx(72.0, abs=1e-8)
