"""Reprojection tests (round 4, VERDICT #7): registry, round trip,
closed-form oracle, runner finish step, st_transform."""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.crs import R_MAJOR, reproject_batch, transform
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.query import Query


class TestTransform:
    def test_closed_form_oracle(self):
        # independent mercator formula on a few known points
        lon = np.array([0.0, 10.0, -77.0365, 151.2093])
        lat = np.array([0.0, 53.55, 38.8977, -33.8688])
        mx, my = transform(lon, lat, 4326, 3857)
        np.testing.assert_allclose(mx, lon * np.pi / 180.0 * R_MAJOR,
                                   rtol=1e-12)
        exp_y = R_MAJOR * np.log(
            np.tan(np.pi / 4 + np.radians(lat) / 2))
        np.testing.assert_allclose(my, exp_y, rtol=1e-12)
        # independent constant: y(45N) = R * ln(tan(3pi/8)) = R * asinh(1)
        y45 = transform([0.0], [45.0], 4326, 3857)[1][0]
        assert abs(y45 - R_MAJOR * np.arcsinh(1.0)) < 1e-6

    def test_round_trip(self):
        rng = np.random.default_rng(3)
        lon = rng.uniform(-179, 179, 1000)
        lat = rng.uniform(-84, 84, 1000)
        mx, my = transform(lon, lat, 4326, 3857)
        lon2, lat2 = transform(mx, my, 3857, 4326)
        np.testing.assert_allclose(lon2, lon, atol=1e-9)
        np.testing.assert_allclose(lat2, lat, atol=1e-9)

    def test_identity_and_unknown(self):
        x, y = transform([1.0], [2.0], 4326, 4326)
        assert x[0] == 1.0 and y[0] == 2.0
        with pytest.raises(ValueError, match="unsupported CRS"):
            transform([0.0], [0.0], 4326, 32633)


class TestQueryReprojection:
    def test_query_crs_output(self, tmp_path):
        rng = np.random.default_rng(7)
        n = 500
        sft = SimpleFeatureType.from_spec("t", "v:Double,*geom:Point")
        x = rng.uniform(-170, 170, n)
        y = rng.uniform(-80, 80, n)
        batch = FeatureBatch.from_pydict(
            sft, {"v": rng.uniform(0, 1, n), "geom": np.stack([x, y], 1)})
        ds = DataStore(str(tmp_path / "c"))
        src = ds.create_schema(sft)
        src.write(batch)
        r = src.get_features(Query("t", "BBOX(geom, -60, -30, 60, 30)",
                                   crs=3857))
        g = r.features.columns["geom"]
        sel = ((x >= -60) & (x <= 60) & (y >= -30) & (y <= 30))
        ex, ey = transform(x[sel], y[sel], 4326, 3857)
        got = np.stack([np.sort(np.asarray(g.x)), np.sort(np.asarray(g.y))])
        np.testing.assert_allclose(
            got, np.stack([np.sort(ex), np.sort(ey)]), rtol=1e-12)
        # the result schema records its CRS
        assert r.features.sft.attribute("geom").options["srid"] == "3857"

    def test_extended_geometry_reprojection(self):
        from geomesa_tpu.core.wkt import Geometry

        sft = SimpleFeatureType.from_spec("p", "*geom:Polygon")
        sq = np.array([[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]], float)
        batch = FeatureBatch.from_pydict(
            sft, {"geom": [Geometry("Polygon", [sq])]})
        out = reproject_batch(batch, 3857)
        col = out.columns["geom"]
        vx, vy = transform(sq[:, 0], sq[:, 1], 4326, 3857)
        np.testing.assert_allclose(col.vertices[:, 0], vx, rtol=1e-12)
        np.testing.assert_allclose(col.vertices[:, 1], vy, rtol=1e-12)
        assert col.bbox[0, 2] == pytest.approx(vx.max())


def test_sql_st_transform():
    from geomesa_tpu.core.wkt import Geometry
    from geomesa_tpu.sql.functions import st_transform

    g = Geometry("Point", [np.array([[10.0, 53.55]])])
    out = st_transform(g, "EPSG:4326", "EPSG:3857")
    ex, ey = transform([10.0], [53.55], 4326, 3857)
    np.testing.assert_allclose(out.rings[0][0], [ex[0], ey[0]], rtol=1e-12)
