"""Reprojection tests (round 4, VERDICT #7): registry, round trip,
closed-form oracle, runner finish step, st_transform."""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.crs import R_MAJOR, reproject_batch, transform
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.query import Query


class TestTransform:
    def test_closed_form_oracle(self):
        # independent mercator formula on a few known points
        lon = np.array([0.0, 10.0, -77.0365, 151.2093])
        lat = np.array([0.0, 53.55, 38.8977, -33.8688])
        mx, my = transform(lon, lat, 4326, 3857)
        np.testing.assert_allclose(mx, lon * np.pi / 180.0 * R_MAJOR,
                                   rtol=1e-12)
        exp_y = R_MAJOR * np.log(
            np.tan(np.pi / 4 + np.radians(lat) / 2))
        np.testing.assert_allclose(my, exp_y, rtol=1e-12)
        # independent constant: y(45N) = R * ln(tan(3pi/8)) = R * asinh(1)
        y45 = transform([0.0], [45.0], 4326, 3857)[1][0]
        assert abs(y45 - R_MAJOR * np.arcsinh(1.0)) < 1e-6

    def test_round_trip(self):
        rng = np.random.default_rng(3)
        lon = rng.uniform(-179, 179, 1000)
        lat = rng.uniform(-84, 84, 1000)
        mx, my = transform(lon, lat, 4326, 3857)
        lon2, lat2 = transform(mx, my, 3857, 4326)
        np.testing.assert_allclose(lon2, lon, atol=1e-9)
        np.testing.assert_allclose(lat2, lat, atol=1e-9)

    def test_identity_and_unknown(self):
        x, y = transform([1.0], [2.0], 4326, 4326)
        assert x[0] == 1.0 and y[0] == 2.0
        with pytest.raises(ValueError, match="unsupported CRS"):
            transform([0.0], [0.0], 4326, 27700)  # OSGB: not registered


def _snyder_utm(lon, lat, lon0, fn):
    """INDEPENDENT oracle: Snyder (1987) eq. 8-9..8-13 truncated series for
    the ellipsoidal transverse Mercator — a different formulation from the
    Krueger flattening series in core.crs (different expansion variable:
    e^2, not n). Agreement << 1 mm in-zone certifies both."""
    a, f = 6378137.0, 1 / 298.257223563
    e2 = f * (2 - f)
    ep2 = e2 / (1 - e2)
    k0 = 0.9996
    phi = np.radians(np.asarray(lat, np.float64))
    lam = np.radians(np.asarray(lon, np.float64) - lon0)
    sp, cp = np.sin(phi), np.cos(phi)
    N = a / np.sqrt(1 - e2 * sp**2)
    T = (sp / cp) ** 2
    C = ep2 * cp**2
    A = lam * cp
    M = a * (
        (1 - e2 / 4 - 3 * e2**2 / 64 - 5 * e2**3 / 256) * phi
        - (3 * e2 / 8 + 3 * e2**2 / 32 + 45 * e2**3 / 1024) * np.sin(2 * phi)
        + (15 * e2**2 / 256 + 45 * e2**3 / 1024) * np.sin(4 * phi)
        - (35 * e2**3 / 3072) * np.sin(6 * phi)
    )
    E = 500000.0 + k0 * N * (
        A + (1 - T + C) * A**3 / 6
        + (5 - 18 * T + T**2 + 72 * C - 58 * ep2) * A**5 / 120
    )
    Nn = fn + k0 * (
        M + N * (sp / cp) * (
            A**2 / 2 + (5 - T + 9 * C + 4 * C**2) * A**4 / 24
            + (61 - 58 * T + T**2 + 600 * C - 330 * ep2) * A**6 / 720
        )
    )
    return E, Nn


class TestUTM:
    def test_against_snyder_oracle(self):
        # in-zone points across hemispheres and latitudes (zone 33: lon0=15)
        lon = np.array([15.0, 12.5, 17.9, 13.3, 16.7])
        lat = np.array([0.5, 48.2, 67.9, 22.0, 5.1])
        ex, ey = _snyder_utm(lon, lat, 15.0, 0.0)
        gx, gy = transform(lon, lat, 4326, 32633)
        np.testing.assert_allclose(gx, ex, atol=1e-3)  # < 1 mm
        np.testing.assert_allclose(gy, ey, atol=1e-3)
        # southern hemisphere, zone 56 (lon0=153): Sydney-ish
        ex, ey = _snyder_utm([151.2093], [-33.8688], 153.0, 10_000_000.0)
        gx, gy = transform([151.2093], [-33.8688], 4326, 32756)
        np.testing.assert_allclose(gx, ex, atol=1e-3)
        np.testing.assert_allclose(gy, ey, atol=1e-3)

    def test_anchor_points(self):
        # equator on the central meridian is EXACTLY (500000, 0) north
        e, n = transform([15.0], [0.0], 4326, 32633)
        assert abs(e[0] - 500000.0) < 1e-6 and abs(n[0]) < 1e-6
        # and (500000, 10000000) south
        e, n = transform([153.0], [0.0], 4326, 32756)
        assert abs(e[0] - 500000.0) < 1e-6 and abs(n[0] - 1e7) < 1e-6
        # meridian scale factor == k0: 1 deg of northing near the equator
        e1, n1 = transform([15.0], [0.0], 4326, 32633)
        e2, n2 = transform([15.0], [1e-4], 4326, 32633)
        # local meridian arc at the equator: ds = rho(0) dphi with the
        # meridional radius of curvature rho(0) = a(1-e^2)
        a, f = 6378137.0, 1 / 298.257223563
        e2_ = f * (2 - f)
        arc = a * (1 - e2_) * np.radians(1e-4)
        assert abs((n2[0] - n1[0]) / arc - 0.9996) < 1e-6

    def test_round_trip_mm(self):
        rng = np.random.default_rng(5)
        for srid, lon0, latr in ((32633, 15.0, (0.0, 84.0)),
                                 (32756, 153.0, (-80.0, 0.0))):
            lon = rng.uniform(lon0 - 3, lon0 + 3, 500)
            lat = rng.uniform(*latr, 500)
            e, n = transform(lon, lat, 4326, srid)
            lon2, lat2 = transform(e, n, srid, 4326)
            # < 1e-9 deg ~ 0.1 mm
            np.testing.assert_allclose(lon2, lon, atol=1e-9)
            np.testing.assert_allclose(lat2, lat, atol=1e-9)

    def test_cross_frame_routes(self):
        # UTM -> UTM (adjacent zones) and UTM <-> 3857 route through 4326
        lon, lat = np.array([17.5]), np.array([59.3])
        e33, n33 = transform(lon, lat, 4326, 32633)
        e34, n34 = transform(e33, n33, 32633, 32634)
        ed, nd = transform(lon, lat, 4326, 32634)
        np.testing.assert_allclose([e34[0], n34[0]], [ed[0], nd[0]],
                                   atol=1e-6)
        mx, my = transform(e33, n33, 32633, 3857)
        ex, ey = transform(lon, lat, 4326, 3857)
        np.testing.assert_allclose([mx[0], my[0]], [ex[0], ey[0]], atol=1e-6)

    def test_zone_picker(self):
        from geomesa_tpu.core.crs import utm_zone_srid

        assert utm_zone_srid(15.0, 48.0) == 32633
        assert utm_zone_srid(151.2, -33.9) == 32756
        assert utm_zone_srid(-179.9, 10.0) == 32601
        assert utm_zone_srid(179.9, -10.0) == 32760

    def test_sql_st_transform_utm(self):
        from geomesa_tpu.core.wkt import Geometry
        from geomesa_tpu.sql.functions import st_transform

        g = Geometry("Point", [np.array([[15.0, 48.0]])])
        out = st_transform(g, "EPSG:4326", "EPSG:32633")
        ex, ey = transform([15.0], [48.0], 4326, 32633)
        np.testing.assert_allclose(out.rings[0][0], [ex[0], ey[0]],
                                   rtol=1e-12)


class TestQueryReprojection:
    def test_query_crs_output(self, tmp_path):
        rng = np.random.default_rng(7)
        n = 500
        sft = SimpleFeatureType.from_spec("t", "v:Double,*geom:Point")
        x = rng.uniform(-170, 170, n)
        y = rng.uniform(-80, 80, n)
        batch = FeatureBatch.from_pydict(
            sft, {"v": rng.uniform(0, 1, n), "geom": np.stack([x, y], 1)})
        ds = DataStore(str(tmp_path / "c"))
        src = ds.create_schema(sft)
        src.write(batch)
        r = src.get_features(Query("t", "BBOX(geom, -60, -30, 60, 30)",
                                   crs=3857))
        g = r.features.columns["geom"]
        sel = ((x >= -60) & (x <= 60) & (y >= -30) & (y <= 30))
        ex, ey = transform(x[sel], y[sel], 4326, 3857)
        got = np.stack([np.sort(np.asarray(g.x)), np.sort(np.asarray(g.y))])
        np.testing.assert_allclose(
            got, np.stack([np.sort(ex), np.sort(ey)]), rtol=1e-12)
        # the result schema records its CRS
        assert r.features.sft.attribute("geom").options["srid"] == "3857"

    def test_extended_geometry_reprojection(self):
        from geomesa_tpu.core.wkt import Geometry

        sft = SimpleFeatureType.from_spec("p", "*geom:Polygon")
        sq = np.array([[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]], float)
        batch = FeatureBatch.from_pydict(
            sft, {"geom": [Geometry("Polygon", [sq])]})
        out = reproject_batch(batch, 3857)
        col = out.columns["geom"]
        vx, vy = transform(sq[:, 0], sq[:, 1], 4326, 3857)
        np.testing.assert_allclose(col.vertices[:, 0], vx, rtol=1e-12)
        np.testing.assert_allclose(col.vertices[:, 1], vy, rtol=1e-12)
        assert col.bbox[0, 2] == pytest.approx(vx.max())


def test_sql_st_transform():
    from geomesa_tpu.core.wkt import Geometry
    from geomesa_tpu.sql.functions import st_transform

    g = Geometry("Point", [np.array([[10.0, 53.55]])])
    out = st_transform(g, "EPSG:4326", "EPSG:3857")
    ex, ey = transform([10.0], [53.55], 4326, 3857)
    np.testing.assert_allclose(out.rings[0][0], [ex[0], ey[0]], rtol=1e-12)
