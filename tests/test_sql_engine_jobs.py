"""SQL pushdown engine + parallel jobs tests."""

import json
import os

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.jobs import export_partitions, ingest_files
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.sql.engine import SqlContext, SqlError

from tests.reference_engine import eval_filter
from geomesa_tpu.cql import parse_cql


def make_store(tmp_path, n=400, seed=21):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec(
        "gdelt", "actor:String,score:Double,dtg:Date,*geom:Point"
    )
    batch = FeatureBatch.from_pydict(
        sft,
        {
            "actor": rng.choice(["USA", "FRA", "CHN"], n).tolist(),
            "score": rng.uniform(-10, 10, n),
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack(
                [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1
            ),
        },
    )
    ds = DataStore(str(tmp_path / "cat"))
    ds.create_schema(sft).write(batch)
    return sft, batch, ds


class TestSqlEngine:
    def test_select_where_pushdown_parity(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT actor, score FROM gdelt WHERE "
            "st_intersects(geom, st_makeBBOX(-60, -30, 60, 30)) "
            "AND score > 2.5"
        )
        f = parse_cql("BBOX(geom, -60, -30, 60, 30) AND score > 2.5")
        assert r.count == int(eval_filter(f, batch).sum())
        assert list(r.features.sft.attribute_names) == ["actor", "score"]

    def test_count_star(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql("SELECT COUNT(*) FROM gdelt WHERE actor = 'USA'")
        f = parse_cql("actor = 'USA'")
        assert r.kind == "count"
        assert r.count == int(eval_filter(f, batch).sum())

    def test_order_limit(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT score FROM gdelt WHERE score > 0 "
            "ORDER BY score DESC LIMIT 5"
        )
        got = np.asarray(r.features.columns["score"])
        allv = np.asarray(batch.columns["score"])
        exp = np.sort(allv[allv > 0])[::-1][:5]
        np.testing.assert_allclose(got, exp)

    def test_contains_argument_flip(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        wkt = "POLYGON ((-60 -30, 60 -30, 60 30, -60 30, -60 -30))"
        a = ctx.sql(
            f"SELECT COUNT(*) FROM gdelt WHERE st_contains(st_geomFromWKT('{wkt}'), geom)"
        )
        b = ctx.sql(
            f"SELECT COUNT(*) FROM gdelt WHERE st_within(geom, st_geomFromWKT('{wkt}'))"
        )
        assert a.count == b.count > 0

    def test_temporal_between(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT COUNT(*) FROM gdelt WHERE dtg BETWEEN "
            "'2020-06-01T00:00:00Z' AND '2020-08-01T00:00:00Z'"
        )
        t = np.asarray(batch.columns["dtg"])
        f = parse_cql(
            "dtg >= 2020-06-01T00:00:00Z AND dtg <= 2020-08-01T00:00:00Z"
        )
        assert r.count == int(eval_filter(f, batch).sum())

    def test_dwithin_meters(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT COUNT(*) FROM gdelt WHERE "
            "st_dwithin(geom, st_point(0, 0), 2000000)"
        )
        f = parse_cql("DWITHIN(geom, POINT (0 0), 2000000, meters)")
        assert r.count == int(eval_filter(f, batch).sum())

    def test_compute_predicate_local_fallback(self, tmp_path):
        # non-pushable scalar st_* predicates post-filter locally
        # (LocalQueryRunner contract) instead of raising (round-1 weak #7)
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql("SELECT * FROM gdelt WHERE st_area(geom) > 2")
        assert r.features is None or len(r.features) == 0  # points: area 0
        r = ctx.sql(
            "SELECT * FROM gdelt WHERE st_x(geom) > 0 AND score > 0"
        )
        exp = int(
            ((np.asarray(batch.columns["geom"].x) > 0)
             & (np.asarray(batch.column("score")) > 0)).sum()
        )
        assert (0 if r.features is None else len(r.features)) == exp
        # under OR the index part would be unsound -> still raises clearly
        with pytest.raises(SqlError, match="OR over a non-pushable"):
            ctx.sql(
                "SELECT * FROM gdelt WHERE st_x(geom) > 0 OR score > 0"
            )

    def test_in_like_null(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT COUNT(*) FROM gdelt WHERE actor IN ('USA', 'FRA')"
        )
        f = parse_cql("actor IN ('USA', 'FRA')")
        assert r.count == int(eval_filter(f, batch).sum())
        r2 = ctx.sql("SELECT COUNT(*) FROM gdelt WHERE actor LIKE 'U%'")
        assert r2.count == int(
            eval_filter(parse_cql("actor LIKE 'U%'"), batch).sum()
        )


class TestJobs:
    def _csv_files(self, tmp_path, n_files=4, rows=30):
        paths = []
        rng = np.random.default_rng(0)
        for i in range(n_files):
            p = tmp_path / f"in_{i}.csv"
            lines = []
            for j in range(rows):
                lines.append(
                    f"a{i}_{j},{rng.uniform(-10, 10):.3f},"
                    f"2020-06-0{1 + (j % 9)}T00:00:00Z,"
                    f"{rng.uniform(-170, 170):.4f},{rng.uniform(-80, 80):.4f}"
                )
            p.write_text("\n".join(lines) + "\n")
            paths.append(str(p))
        return paths

    def _converter_cfg(self):
        return {
            "type": "delimited-text",
            "format": "CSV",
            "id-field": "$1",
            "fields": [
                {"name": "actor", "transform": "$1::string"},
                {"name": "score", "transform": "$2::double"},
                {"name": "dtg", "transform": "isoDateTime($3)"},
                {"name": "geom", "transform": "point($4::double, $5::double)"},
            ],
        }

    def test_parallel_ingest_and_resume(self, tmp_path):
        from geomesa_tpu.convert import converter_from_config

        sft = SimpleFeatureType.from_spec(
            "t", "actor:String,score:Double,dtg:Date,*geom:Point"
        )
        ds = DataStore(str(tmp_path / "cat"))
        src = ds.create_schema(sft)
        files = self._csv_files(tmp_path)
        cfg = self._converter_cfg()
        factory = lambda: converter_from_config(sft, cfg)
        rep = ingest_files(src, factory, files, workers=3)
        assert not rep.files_failed
        assert rep.features == 4 * 30
        assert src.get_count("INCLUDE") == 120
        # re-run: everything skipped, nothing double-written
        rep2 = ingest_files(src, factory, files, workers=3)
        assert sorted(rep2.skipped) == sorted(files)
        assert rep2.features == 0
        assert src.get_count("INCLUDE") == 120

    def test_ingest_failure_isolation(self, tmp_path):
        from geomesa_tpu.convert import converter_from_config

        sft = SimpleFeatureType.from_spec(
            "t", "actor:String,score:Double,dtg:Date,*geom:Point"
        )
        ds = DataStore(str(tmp_path / "cat"))
        src = ds.create_schema(sft)
        files = self._csv_files(tmp_path, n_files=2)
        missing = str(tmp_path / "nope.csv")
        cfg = self._converter_cfg()
        rep = ingest_files(
            src, lambda: converter_from_config(sft, cfg), files + [missing],
            workers=2,
        )
        assert len(rep.files_ok) == 2
        assert len(rep.files_failed) == 1 and missing in rep.files_failed[0]
        assert src.get_count("INCLUDE") == 60

    def test_export_partitions(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        src = ds.get_feature_source("gdelt")
        out = {}

        def writer(name, b):
            out[name] = len(b)

        names = export_partitions(src, writer, cql="score > 0", workers=3)
        assert names
        f = parse_cql("score > 0")
        assert sum(out.values()) == int(eval_filter(f, batch).sum())


class TestSqlAggregation:
    """GROUP BY / aggregates via device segment reductions (round-1
    missing #3; SURVEY.md:381-383)."""

    def _oracle_groups(self, batch, mask=None):
        actors = np.array(
            ["" if a is None else a for a in batch.columns["actor"].decode()]
        )
        scores = np.asarray(batch.column("score"))
        if mask is not None:
            actors, scores = actors[mask], scores[mask]
        out = {}
        for a in np.unique(actors):
            s = scores[actors == a]
            out[a] = (len(s), s.sum(), s.min(), s.max(), s.mean())
        return out

    def test_group_by_aggregates_parity(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT actor, COUNT(*), SUM(score), MIN(score), MAX(score), "
            "AVG(score) AS mean_score FROM gdelt GROUP BY actor "
            "ORDER BY actor"
        )
        t = r.features
        exp = self._oracle_groups(batch)
        assert len(t) == len(exp)
        actors = t.columns["actor"].decode()
        assert actors == sorted(exp)
        for i, a in enumerate(actors):
            cnt, s, lo, hi, mean = exp[a]
            assert int(np.asarray(t.column("count"))[i]) == cnt
            np.testing.assert_allclose(
                np.asarray(t.column("sum_score"))[i], s, rtol=1e-9)
            np.testing.assert_allclose(
                np.asarray(t.column("min_score"))[i], lo, rtol=1e-9)
            np.testing.assert_allclose(
                np.asarray(t.column("max_score"))[i], hi, rtol=1e-9)
            np.testing.assert_allclose(
                np.asarray(t.column("mean_score"))[i], mean, rtol=1e-9)

    def test_group_by_with_where_and_order_limit(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT actor, COUNT(*) AS n FROM gdelt WHERE score > 0 "
            "GROUP BY actor ORDER BY n DESC LIMIT 2"
        )
        t = r.features
        mask = np.asarray(batch.column("score")) > 0
        exp = self._oracle_groups(batch, mask)
        counts = sorted((c for c, *_ in exp.values()), reverse=True)[:2]
        assert np.asarray(t.column("n")).tolist() == counts

    def test_global_aggregates_single_row(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT COUNT(*) AS n, AVG(score) AS m FROM gdelt"
        )
        t = r.features
        assert len(t) == 1
        assert int(np.asarray(t.column("n"))[0]) == len(batch)
        np.testing.assert_allclose(
            np.asarray(t.column("m"))[0],
            np.asarray(batch.column("score")).mean(),
            rtol=1e-9,
        )

    def test_group_by_multi_key(self, tmp_path):
        sft, batch, ds = make_store(tmp_path, n=300, seed=5)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT actor, COUNT(*) AS n FROM gdelt "
            "WHERE st_intersects(geom, st_makeBBOX(-100, -60, 100, 60)) "
            "GROUP BY actor ORDER BY actor"
        )
        t = r.features
        f = parse_cql("BBOX(geom, -100, -60, 100, 60)")
        mask = eval_filter(f, batch)
        exp = self._oracle_groups(batch, mask)
        got = dict(zip(t.columns["actor"].decode(),
                       np.asarray(t.column("n")).tolist()))
        assert got == {a: c for a, (c, *_) in exp.items()}

    def test_bare_column_outside_group_by_rejected(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        with pytest.raises(SqlError, match="must appear in GROUP BY"):
            ctx.sql("SELECT score, COUNT(*) FROM gdelt GROUP BY actor")

    def test_sum_of_string_rejected(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        with pytest.raises(SqlError, match="cannot aggregate string"):
            ctx.sql("SELECT SUM(actor) FROM gdelt")


class TestStBuffer:
    def test_buffer_in_where_via_pushdown(self, tmp_path):
        # st_buffer literal feeds a pushable spatial predicate
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        from geomesa_tpu.sql.functions import st_buffer, st_point, st_asText

        poly = st_buffer(st_point(0.0, 0.0), 40.0)
        r = ctx.sql(
            "SELECT COUNT(*) FROM gdelt WHERE "
            f"st_within(geom, st_geomFromWKT('{st_asText(poly)}'))"
        )
        from geomesa_tpu.engine.pip import points_in_polygon_np

        g = batch.columns["geom"]
        exp = int(points_in_polygon_np(g.x, g.y, poly).sum())
        assert abs(r.count - exp) <= max(2, exp // 200)

    def test_null_skipping_and_empty_set_semantics(self, tmp_path):
        # SQL NULL semantics: NaN doubles are skipped by SUM/MIN/MAX/AVG,
        # COUNT(col) counts non-null only; empty sets yield NULL (NaN) for
        # MIN/MAX/AVG and 0 for COUNT (round-2 review findings)
        rng = np.random.default_rng(9)
        sft = SimpleFeatureType.from_spec(
            "t", "actor:String,score:Double,*geom:Point"
        )
        scores = np.array([1.0, np.nan, 3.0, np.nan, 5.0])
        batch = FeatureBatch.from_pydict(
            sft,
            {
                "actor": ["a", "a", "a", "b", "b"],
                "score": scores,
                "geom": rng.uniform(-10, 10, (5, 2)),
            },
        )
        ds = DataStore(str(tmp_path / "cat"))
        ds.create_schema(sft).write(batch)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT actor, COUNT(*) AS n, COUNT(score) AS nn, "
            "SUM(score) AS s, MIN(score) AS lo, AVG(score) AS m "
            "FROM t GROUP BY actor ORDER BY actor"
        )
        t = r.features
        assert np.asarray(t.column("n")).tolist() == [3, 2]
        assert np.asarray(t.column("nn")).tolist() == [2, 1]
        np.testing.assert_allclose(np.asarray(t.column("s")), [4.0, 5.0])
        np.testing.assert_allclose(np.asarray(t.column("lo")), [1.0, 5.0])
        np.testing.assert_allclose(np.asarray(t.column("m")), [2.0, 5.0])
        # empty set
        r = ctx.sql(
            "SELECT COUNT(*) AS n, MIN(score) AS lo, AVG(score) AS m "
            "FROM t WHERE score > 1000000000"
        )
        t = r.features
        assert int(np.asarray(t.column("n"))[0]) == 0
        assert np.isnan(np.asarray(t.column("lo"))[0])
        assert np.isnan(np.asarray(t.column("m"))[0])


class TestSqlJoin:
    """Inner equi-join with per-side pushdown (SURVEY.md:381-383 relation
    joins)."""

    def _two_tables(self, tmp_path):
        rng = np.random.default_rng(31)
        events_sft = SimpleFeatureType.from_spec(
            "events", "actor:String,score:Double,*geom:Point"
        )
        n = 200
        actors = rng.choice(["USA", "FRA", "CHN", "XXX"], n)
        events = FeatureBatch.from_pydict(events_sft, {
            "actor": actors.tolist(),
            "score": rng.uniform(-10, 10, n),
            "geom": np.stack([rng.uniform(-170, 170, n),
                              rng.uniform(-80, 80, n)], 1)})
        countries_sft = SimpleFeatureType.from_spec(
            "countries", "code:String,pop:Double,*geom:Point"
        )
        countries = FeatureBatch.from_pydict(countries_sft, {
            "code": ["USA", "FRA", "CHN", "GBR"],
            "pop": [331.0, 67.0, 1412.0, 67.2],
            "geom": np.array([[-98.0, 39.0], [2.0, 46.0],
                              [104.0, 35.0], [-2.0, 54.0]])})
        ds = DataStore(str(tmp_path / "cat"))
        ds.create_schema(events_sft).write(events)
        ds.create_schema(countries_sft).write(countries)
        return ds, events, countries, actors

    def test_join_parity(self, tmp_path):
        ds, events, countries, actors = self._two_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.actor, e.score, c.pop FROM events e "
            "JOIN countries c ON e.actor = c.code "
            "WHERE e.score > 0 AND c.pop > 100"
        )
        t = r.features
        scores = np.asarray(events.column("score"))
        pops = dict(zip(countries.columns["code"].decode(),
                        np.asarray(countries.column("pop"))))
        exp = sum(
            1 for a, s in zip(actors, scores)
            if s > 0 and a in pops and pops[a] > 100
        )
        assert len(t) == exp
        got_pop = np.asarray(t.column("pop"))
        got_actor = t.columns["actor"].decode()
        for a, p in zip(got_actor, got_pop):
            assert pops[a] == p and pops[a] > 100
        # XXX actors (no matching country) never appear
        assert "XXX" not in set(got_actor)

    def test_join_order_limit_and_aliases(self, tmp_path):
        ds, events, countries, actors = self._two_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.score AS s, c.code FROM events e "
            "JOIN countries c ON e.actor = c.code "
            "ORDER BY s DESC LIMIT 5"
        )
        t = r.features
        assert len(t) == 5
        s = np.asarray(t.column("s"))
        assert (np.diff(s) <= 0).all()
        scores = np.asarray(events.column("score"))
        joined = scores[np.isin(actors, ["USA", "FRA", "CHN", "GBR"])]
        np.testing.assert_allclose(s, np.sort(joined)[::-1][:5])

    def test_join_errors(self, tmp_path):
        ds, *_ = self._two_tables(tmp_path)
        ctx = SqlContext(ds)
        with pytest.raises(SqlError, match="select list"):
            ctx.sql("SELECT * FROM events e JOIN countries c ON e.actor = c.code")
        with pytest.raises(SqlError, match="ambiguous"):
            ctx.sql("SELECT geom FROM events e JOIN countries c ON e.actor = c.code")
        with pytest.raises(SqlError, match="two tables"):
            ctx.sql("SELECT e.actor FROM events e JOIN countries c ON e.actor = e.actor")

    def test_join_spatial_pushdown_per_side(self, tmp_path):
        ds, events, countries, actors = self._two_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.actor FROM events e JOIN countries c "
            "ON e.actor = c.code "
            "WHERE st_intersects(e.geom, st_makeBBOX(-90, -45, 90, 45))"
        )
        g = events.columns["geom"]
        sel = (g.x >= -90) & (g.x <= 90) & (g.y >= -45) & (g.y <= 45)
        exp = sum(
            1 for a, m in zip(actors, sel)
            if m and a in ("USA", "FRA", "CHN", "GBR")
        )
        assert (0 if r.features is None else len(r.features)) == exp

    def test_join_empty_side_and_between(self, tmp_path):
        # (round-2 review) an empty side must yield an empty result, not
        # crash; BETWEEN's AND must not split JOIN WHERE conjuncts
        ds, events, countries, actors = self._two_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.actor, c.pop FROM events e "
            "JOIN countries c ON e.actor = c.code "
            "WHERE e.score > 1000000000"
        )
        assert len(r.features) == 0 and r.count == 0
        r = ctx.sql(
            "SELECT e.actor FROM events e "
            "JOIN countries c ON e.actor = c.code "
            "WHERE e.score BETWEEN 0 AND 5 AND c.pop > 100"
        )
        scores = np.asarray(events.column("score"))
        pops = dict(zip(countries.columns["code"].decode(),
                        np.asarray(countries.column("pop"))))
        exp = sum(1 for a, s in zip(actors, scores)
                  if 0 <= s <= 5 and a in pops and pops[a] > 100)
        assert (0 if r.features is None else len(r.features)) == exp

    def test_single_table_alias_binds(self, tmp_path):
        # (round-2 review) a consumed alias must resolve qualified refs
        ds, events, countries, actors = self._two_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql("SELECT e.score FROM events e WHERE e.score > 0 "
                    "ORDER BY e.score DESC LIMIT 3")
        scores = np.asarray(events.column("score"))
        np.testing.assert_allclose(
            np.asarray(r.features.column("score")),
            np.sort(scores[scores > 0])[::-1][:3])

    def test_join_parenthesized_between_and_alias_collision(self, tmp_path):
        ds, events, countries, actors = self._two_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.actor FROM events e JOIN countries c "
            "ON e.actor = c.code "
            "WHERE (e.score BETWEEN 0 AND 5) AND c.pop > 100"
        )
        scores = np.asarray(events.column("score"))
        pops = dict(zip(countries.columns["code"].decode(),
                        np.asarray(countries.column("pop"))))
        exp = sum(1 for a, s in zip(actors, scores)
                  if 0 <= s <= 5 and a in pops and pops[a] > 100)
        assert (0 if r.features is None else len(r.features)) == exp
        with pytest.raises(SqlError, match="duplicate output column"):
            ctx.sql("SELECT e.score AS pop, c.pop FROM events e "
                    "JOIN countries c ON e.actor = c.code")

    def test_join_group_by_aggregates(self, tmp_path):
        ds, events, countries, actors = self._two_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT c.code, COUNT(*) AS n, AVG(e.score) AS m, SUM(c.pop) "
            "FROM events e JOIN countries c ON e.actor = c.code "
            "WHERE e.score > 0 GROUP BY c.code ORDER BY c.code"
        )
        t = r.features
        scores = np.asarray(events.column("score"))
        pops = dict(zip(countries.columns["code"].decode(),
                        np.asarray(countries.column("pop"))))
        exp = {}
        for a, s in zip(actors, scores):
            if s > 0 and a in pops:
                cnt, tot = exp.get(a, (0, 0.0))
                exp[a] = (cnt + 1, tot + s)
        codes = t.columns["code"].decode()
        assert codes == sorted(exp)
        for i, a in enumerate(codes):
            cnt, tot = exp[a]
            assert int(np.asarray(t.column("n"))[i]) == cnt
            np.testing.assert_allclose(
                np.asarray(t.column("m"))[i], tot / cnt, rtol=1e-9)
            np.testing.assert_allclose(
                np.asarray(t.column("sum_pop"))[i], pops[a] * cnt, rtol=1e-9)

    def test_join_global_aggregate(self, tmp_path):
        ds, events, countries, actors = self._two_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT COUNT(*) AS n FROM events e "
            "JOIN countries c ON e.actor = c.code"
        )
        exp = sum(1 for a in actors if a in ("USA", "FRA", "CHN", "GBR"))
        assert int(np.asarray(r.features.column("n"))[0]) == exp

    def test_join_aggregate_duplicate_alias_rejected(self, tmp_path):
        ds, *_ = self._two_tables(tmp_path)
        ctx = SqlContext(ds)
        with pytest.raises(SqlError, match="duplicate output column"):
            ctx.sql("SELECT COUNT(*) AS x, SUM(e.score) AS x FROM events e "
                    "JOIN countries c ON e.actor = c.code")


class TestSqlHaving:
    """HAVING + COUNT(*) LIMIT semantics (round-2 advisor findings)."""

    def test_count_star_limit_not_capped(self, tmp_path):
        # LIMIT applies to the single result row, never to the counted rows
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        full = ctx.sql("SELECT COUNT(*) FROM gdelt WHERE score > 0").count
        assert full > 5
        r = ctx.sql("SELECT COUNT(*) FROM gdelt WHERE score > 0 LIMIT 5")
        assert r.count == full

    def test_having_on_group_by(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT actor, COUNT(*) AS n, AVG(score) AS m FROM gdelt "
            "GROUP BY actor HAVING COUNT(*) > 100 AND m > -5 ORDER BY actor"
        )
        actors = batch.columns["actor"].decode()
        scores = np.asarray(batch.column("score"))
        exp = {}
        for a, s in zip(actors, scores):
            c, t = exp.get(a, (0, 0.0))
            exp[a] = (c + 1, t + s)
        keep = sorted(
            a for a, (c, t) in exp.items() if c > 100 and t / c > -5
        )
        t = r.features
        assert t.columns["actor"].decode() == keep
        for i, a in enumerate(keep):
            assert int(np.asarray(t.column("n"))[i]) == exp[a][0]

    def test_having_agg_not_selected_rejected(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        with pytest.raises(SqlError, match="not in the\n?.*select list|not in the select"):
            ctx.sql(
                "SELECT actor, COUNT(*) FROM gdelt GROUP BY actor "
                "HAVING SUM(score) > 0"
            )

    def test_having_without_aggregates_rejected(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        with pytest.raises(SqlError, match="HAVING requires"):
            ctx.sql("SELECT actor FROM gdelt HAVING actor = 'USA'")

    def test_join_having_qualified_agg(self, tmp_path):
        rng = np.random.default_rng(31)
        events_sft = SimpleFeatureType.from_spec(
            "events", "actor:String,score:Double,*geom:Point"
        )
        n = 200
        actors = rng.choice(["USA", "FRA", "CHN", "XXX"], n)
        events = FeatureBatch.from_pydict(events_sft, {
            "actor": actors.tolist(),
            "score": rng.uniform(-10, 10, n),
            "geom": np.stack([rng.uniform(-170, 170, n),
                              rng.uniform(-80, 80, n)], 1)})
        countries_sft = SimpleFeatureType.from_spec(
            "countries", "code:String,pop:Double,*geom:Point"
        )
        countries = FeatureBatch.from_pydict(countries_sft, {
            "code": ["USA", "FRA", "CHN", "GBR"],
            "pop": [331.0, 67.0, 1412.0, 67.2],
            "geom": np.array([[-98.0, 39.0], [2.0, 46.0],
                              [104.0, 35.0], [-2.0, 54.0]])})
        ds = DataStore(str(tmp_path / "cat"))
        ds.create_schema(events_sft).write(events)
        ds.create_schema(countries_sft).write(countries)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT c.code, COUNT(*) AS n, SUM(e.score) FROM events e "
            "JOIN countries c ON e.actor = c.code "
            "GROUP BY c.code HAVING SUM(e.score) > 0 ORDER BY c.code"
        )
        scores = np.asarray(events.column("score"))
        exp = {}
        for a, s in zip(actors, scores):
            if a in ("USA", "FRA", "CHN", "GBR"):
                c, t = exp.get(a, (0, 0.0))
                exp[a] = (c + 1, t + s)
        keep = sorted(a for a, (c, t) in exp.items() if t > 0)
        assert r.features.columns["code"].decode() == keep

    def test_join_order_by_unambiguous_bare_name(self, tmp_path):
        # both sides carry 'geom'; 'pop' only exists on countries but was
        # renamed is not the case -- select both sides' score-like columns
        rng = np.random.default_rng(31)
        a_sft = SimpleFeatureType.from_spec("ta", "k:String,v:Double,*geom:Point")
        b_sft = SimpleFeatureType.from_spec("tb", "k:String,w:Double,*geom:Point")
        na = 20
        ka = rng.choice(["p", "q"], na)
        ds = DataStore(str(tmp_path / "cat"))
        ds.create_schema(a_sft).write(FeatureBatch.from_pydict(a_sft, {
            "k": ka.tolist(), "v": rng.uniform(0, 1, na),
            "geom": np.stack([rng.uniform(-10, 10, na),
                              rng.uniform(-10, 10, na)], 1)}))
        ds.create_schema(b_sft).write(FeatureBatch.from_pydict(b_sft, {
            "k": ["p", "q"], "w": [1.0, 2.0],
            "geom": np.array([[0.0, 0.0], [1.0, 1.0]])}))
        ctx = SqlContext(ds)
        # 'k' exists on both sides -> selected a.k is renamed a_k; the bare
        # spelling still resolves because only ONE selected output carries it
        r = ctx.sql(
            "SELECT a.k, a.v FROM ta a JOIN tb b ON a.k = b.k ORDER BY k"
        )
        got = r.features.columns["a_k"].decode()
        assert got == sorted(got)
        # ambiguous bare name in ORDER BY lists valid spellings
        with pytest.raises(SqlError, match="valid spellings"):
            ctx.sql(
                "SELECT a.k AS x, b.k AS yz, a.v FROM ta a "
                "JOIN tb b ON a.k = b.k ORDER BY nosuch"
            )

    def test_having_review_fixes(self, tmp_path):
        # string-vs-number HAVING comparisons error instead of silently
        # stringifying; COUNT(*) LIMIT 0 yields zero rows; qualified group
        # keys resolve in JOIN HAVING
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        with pytest.raises(SqlError, match="string column"):
            ctx.sql("SELECT actor, COUNT(*) FROM gdelt GROUP BY actor "
                    "HAVING actor > 5")
        with pytest.raises(SqlError, match="numeric column"):
            ctx.sql("SELECT actor, COUNT(*) AS n FROM gdelt GROUP BY actor "
                    "HAVING n = 'x'")
        r = ctx.sql("SELECT COUNT(*) FROM gdelt LIMIT 0")
        assert r.features is not None and len(r.features) == 0

    def test_join_having_qualified_group_key(self, tmp_path):
        ds, events, countries, actors = TestSqlJoin()._two_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT c.code, COUNT(*) AS n FROM events e "
            "JOIN countries c ON e.actor = c.code "
            "GROUP BY c.code HAVING c.code <> 'USA' ORDER BY c.code"
        )
        got = r.features.columns["code"].decode()
        assert "USA" not in got and got == sorted(got)

    def test_join_having_raw_column_rejected(self, tmp_path):
        # a raw ungrouped column in JOIN HAVING must error, not silently
        # become its aggregate
        ds, events, countries, actors = TestSqlJoin()._two_tables(tmp_path)
        ctx = SqlContext(ds)
        with pytest.raises(SqlError, match="unknown column"):
            ctx.sql(
                "SELECT c.code, SUM(e.score) FROM events e "
                "JOIN countries c ON e.actor = c.code "
                "GROUP BY c.code HAVING e.score > 0"
            )


class TestSqlJoinVariants:
    """Round-3 surface: multi-table chains, LEFT/RIGHT OUTER, DISTINCT
    (VERDICT.md round-2 task 6)."""

    def _three_tables(self, tmp_path):
        rng = np.random.default_rng(37)
        ev_sft = SimpleFeatureType.from_spec(
            "events", "actor:String,score:Double,*geom:Point")
        n = 120
        actors = rng.choice(["USA", "FRA", "CHN", "XXX"], n)
        ds = DataStore(str(tmp_path / "cat"))
        ds.create_schema(ev_sft).write(FeatureBatch.from_pydict(ev_sft, {
            "actor": actors.tolist(),
            "score": rng.uniform(-10, 10, n),
            "geom": np.stack([rng.uniform(-170, 170, n),
                              rng.uniform(-80, 80, n)], 1)}))
        c_sft = SimpleFeatureType.from_spec(
            "countries", "code:String,region:String,pop:Double,*geom:Point")
        ds.create_schema(c_sft).write(FeatureBatch.from_pydict(c_sft, {
            "code": ["USA", "FRA", "CHN", "GBR"],
            "region": ["AM", "EU", "AS", "EU"],
            "pop": [331.0, 67.0, 1412.0, 67.2],
            "geom": np.array([[-98.0, 39.0], [2.0, 46.0],
                              [104.0, 35.0], [-2.0, 54.0]])}))
        r_sft = SimpleFeatureType.from_spec(
            "regions", "rcode:String,rname:String,*geom:Point")
        ds.create_schema(r_sft).write(FeatureBatch.from_pydict(r_sft, {
            "rcode": ["AM", "EU"],
            "rname": ["America", "Europe"],
            "geom": np.array([[-90.0, 40.0], [10.0, 50.0]])}))
        return ds, actors

    def test_three_table_chain(self, tmp_path):
        ds, actors = self._three_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.actor, c.region, r.rname FROM events e "
            "JOIN countries c ON e.actor = c.code "
            "JOIN regions r ON c.region = r.rcode "
            "ORDER BY e.actor"
        )
        t = r.features
        reg = {"USA": "AM", "FRA": "EU", "CHN": None, "GBR": "EU"}
        exp = sum(1 for a in actors if reg.get(a) in ("AM", "EU"))
        assert len(t) == exp
        names = dict(AM="America", EU="Europe")
        for a, rn in zip(t.columns["actor"].decode(),
                         t.columns["rname"].decode()):
            assert names[reg[a]] == rn

    def test_left_outer_join(self, tmp_path):
        ds, actors = self._three_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.actor, c.pop FROM events e "
            "LEFT JOIN countries c ON e.actor = c.code"
        )
        t = r.features
        assert len(t) == len(actors)  # every event row survives
        pops = {"USA": 331.0, "FRA": 67.0, "CHN": 1412.0}
        got_pop = np.asarray(t.column("pop"))
        for a, p in zip(t.columns["actor"].decode(), got_pop):
            if a in pops:
                assert p == pops[a]
            else:
                assert np.isnan(p)  # XXX has no country -> NULL

    def test_right_outer_join(self, tmp_path):
        ds, actors = self._three_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.actor, c.code FROM events e "
            "RIGHT JOIN countries c ON e.actor = c.code"
        )
        t = r.features
        n_matched = sum(1 for a in actors if a in ("USA", "FRA", "CHN"))
        assert len(t) == n_matched + 1  # GBR row survives unmatched
        codes = t.columns["code"].decode()
        assert "GBR" in codes
        i = codes.index("GBR")
        assert t.columns["actor"].decode()[i] is None  # null-extended

    def test_left_join_aggregate_counts_nulls_correctly(self, tmp_path):
        ds, actors = self._three_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.actor, COUNT(c.pop) AS npop, COUNT(*) AS nrows "
            "FROM events e LEFT JOIN countries c ON e.actor = c.code "
            "GROUP BY e.actor ORDER BY e.actor"
        )
        t = r.features
        for a, np_, nr in zip(t.columns["actor"].decode(),
                              np.asarray(t.column("npop")),
                              np.asarray(t.column("nrows"))):
            exp_rows = int((actors == a).sum())
            assert nr == exp_rows
            assert np_ == (exp_rows if a != "XXX" else 0)  # NULLs skipped

    def test_distinct_single_table(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql("SELECT DISTINCT actor FROM gdelt ORDER BY actor")
        got = r.features.columns["actor"].decode()
        assert got == sorted(set(batch.columns["actor"].decode()))
        # DISTINCT + LIMIT: dedup happens before the limit
        r2 = ctx.sql("SELECT DISTINCT actor FROM gdelt LIMIT 2")
        assert len(r2.features) == 2
        assert len(set(r2.features.columns["actor"].decode())) == 2

    def test_distinct_join(self, tmp_path):
        ds, actors = self._three_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT DISTINCT c.region FROM events e "
            "JOIN countries c ON e.actor = c.code ORDER BY c.region"
        )
        got = r.features.columns["region"].decode()
        present = {a for a in actors if a in ("USA", "FRA", "CHN")}
        exp = sorted({{"USA": "AM", "FRA": "EU", "CHN": "AS"}[a]
                      for a in present})
        assert got == exp

    def test_outer_join_empty_side(self, tmp_path):
        # an outer join whose filtered side is EMPTY must null-extend,
        # not crash (round-3 review finding). NB: WHERE pushes into the
        # SCAN (ON-clause placement; documented in _join) — post-join
        # WHERE semantics would instead collapse the join to inner
        ds, actors = self._three_tables(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.actor, c.pop FROM events e "
            "LEFT JOIN countries c ON e.actor = c.code "
            "WHERE c.pop > 1e9"
        )
        t = r.features
        assert len(t) == len(actors)
        assert np.isnan(np.asarray(t.column("pop"))).all()


def test_join_side_size_guard(tmp_path):
    # round-4 (VERDICT weak #8): a join side exceeding
    # geomesa.sql.join.max.rows must refuse instead of silently
    # materializing; filters that shrink the side below the cap pass
    from geomesa_tpu.utils.config import SystemProperties

    sft, batch, ds = make_store(tmp_path, n=400)
    dim_sft = SimpleFeatureType.from_spec(
        "dim", "actor:String,weight:Double,*geom:Point")
    ds.create_schema(dim_sft).write(FeatureBatch.from_pydict(
        dim_sft,
        {"actor": ["USA", "FRA", "CHN"],
         "weight": [1.0, 2.0, 3.0],
         "geom": np.zeros((3, 2))}))
    ctx = SqlContext(ds)
    q = ("SELECT g.actor AS a, d.weight AS w FROM gdelt g "
         "JOIN dim d ON g.actor = d.actor LIMIT 5")
    SystemProperties.set("geomesa.sql.join.max.rows", 100)
    try:
        with pytest.raises(SqlError, match="join.max.rows"):
            ctx.sql(q)
        # a pushdown filter under the cap goes through
        r = ctx.sql("SELECT g.actor AS a, d.weight AS w FROM gdelt g "
                    "JOIN dim d ON g.actor = d.actor "
                    "WHERE g.score > 9.8 LIMIT 5")
        assert r.kind == "features"
    finally:
        SystemProperties.clear("geomesa.sql.join.max.rows")
    r = ctx.sql(q)  # default cap: fine
    assert r.count == 5
