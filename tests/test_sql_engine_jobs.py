"""SQL pushdown engine + parallel jobs tests."""

import json
import os

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.jobs import export_partitions, ingest_files
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.sql.engine import SqlContext, SqlError

from tests.reference_engine import eval_filter
from geomesa_tpu.cql import parse_cql


def make_store(tmp_path, n=400, seed=21):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec(
        "gdelt", "actor:String,score:Double,dtg:Date,*geom:Point"
    )
    batch = FeatureBatch.from_pydict(
        sft,
        {
            "actor": rng.choice(["USA", "FRA", "CHN"], n).tolist(),
            "score": rng.uniform(-10, 10, n),
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack(
                [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1
            ),
        },
    )
    ds = DataStore(str(tmp_path / "cat"))
    ds.create_schema(sft).write(batch)
    return sft, batch, ds


class TestSqlEngine:
    def test_select_where_pushdown_parity(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT actor, score FROM gdelt WHERE "
            "st_intersects(geom, st_makeBBOX(-60, -30, 60, 30)) "
            "AND score > 2.5"
        )
        f = parse_cql("BBOX(geom, -60, -30, 60, 30) AND score > 2.5")
        assert r.count == int(eval_filter(f, batch).sum())
        assert list(r.features.sft.attribute_names) == ["actor", "score"]

    def test_count_star(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql("SELECT COUNT(*) FROM gdelt WHERE actor = 'USA'")
        f = parse_cql("actor = 'USA'")
        assert r.kind == "count"
        assert r.count == int(eval_filter(f, batch).sum())

    def test_order_limit(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT score FROM gdelt WHERE score > 0 "
            "ORDER BY score DESC LIMIT 5"
        )
        got = np.asarray(r.features.columns["score"])
        allv = np.asarray(batch.columns["score"])
        exp = np.sort(allv[allv > 0])[::-1][:5]
        np.testing.assert_allclose(got, exp)

    def test_contains_argument_flip(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        wkt = "POLYGON ((-60 -30, 60 -30, 60 30, -60 30, -60 -30))"
        a = ctx.sql(
            f"SELECT COUNT(*) FROM gdelt WHERE st_contains(st_geomFromWKT('{wkt}'), geom)"
        )
        b = ctx.sql(
            f"SELECT COUNT(*) FROM gdelt WHERE st_within(geom, st_geomFromWKT('{wkt}'))"
        )
        assert a.count == b.count > 0

    def test_temporal_between(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT COUNT(*) FROM gdelt WHERE dtg BETWEEN "
            "'2020-06-01T00:00:00Z' AND '2020-08-01T00:00:00Z'"
        )
        t = np.asarray(batch.columns["dtg"])
        f = parse_cql(
            "dtg >= 2020-06-01T00:00:00Z AND dtg <= 2020-08-01T00:00:00Z"
        )
        assert r.count == int(eval_filter(f, batch).sum())

    def test_dwithin_meters(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT COUNT(*) FROM gdelt WHERE "
            "st_dwithin(geom, st_point(0, 0), 2000000)"
        )
        f = parse_cql("DWITHIN(geom, POINT (0 0), 2000000, meters)")
        assert r.count == int(eval_filter(f, batch).sum())

    def test_unsupported_compute_predicate_raises(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        with pytest.raises(SqlError, match="not pushable"):
            ctx.sql("SELECT * FROM gdelt WHERE st_area(geom) > 2")

    def test_in_like_null(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT COUNT(*) FROM gdelt WHERE actor IN ('USA', 'FRA')"
        )
        f = parse_cql("actor IN ('USA', 'FRA')")
        assert r.count == int(eval_filter(f, batch).sum())
        r2 = ctx.sql("SELECT COUNT(*) FROM gdelt WHERE actor LIKE 'U%'")
        assert r2.count == int(
            eval_filter(parse_cql("actor LIKE 'U%'"), batch).sum()
        )


class TestJobs:
    def _csv_files(self, tmp_path, n_files=4, rows=30):
        paths = []
        rng = np.random.default_rng(0)
        for i in range(n_files):
            p = tmp_path / f"in_{i}.csv"
            lines = []
            for j in range(rows):
                lines.append(
                    f"a{i}_{j},{rng.uniform(-10, 10):.3f},"
                    f"2020-06-0{1 + (j % 9)}T00:00:00Z,"
                    f"{rng.uniform(-170, 170):.4f},{rng.uniform(-80, 80):.4f}"
                )
            p.write_text("\n".join(lines) + "\n")
            paths.append(str(p))
        return paths

    def _converter_cfg(self):
        return {
            "type": "delimited-text",
            "format": "CSV",
            "id-field": "$1",
            "fields": [
                {"name": "actor", "transform": "$1::string"},
                {"name": "score", "transform": "$2::double"},
                {"name": "dtg", "transform": "isoDateTime($3)"},
                {"name": "geom", "transform": "point($4::double, $5::double)"},
            ],
        }

    def test_parallel_ingest_and_resume(self, tmp_path):
        from geomesa_tpu.convert import converter_from_config

        sft = SimpleFeatureType.from_spec(
            "t", "actor:String,score:Double,dtg:Date,*geom:Point"
        )
        ds = DataStore(str(tmp_path / "cat"))
        src = ds.create_schema(sft)
        files = self._csv_files(tmp_path)
        cfg = self._converter_cfg()
        factory = lambda: converter_from_config(sft, cfg)
        rep = ingest_files(src, factory, files, workers=3)
        assert not rep.files_failed
        assert rep.features == 4 * 30
        assert src.get_count("INCLUDE") == 120
        # re-run: everything skipped, nothing double-written
        rep2 = ingest_files(src, factory, files, workers=3)
        assert sorted(rep2.skipped) == sorted(files)
        assert rep2.features == 0
        assert src.get_count("INCLUDE") == 120

    def test_ingest_failure_isolation(self, tmp_path):
        from geomesa_tpu.convert import converter_from_config

        sft = SimpleFeatureType.from_spec(
            "t", "actor:String,score:Double,dtg:Date,*geom:Point"
        )
        ds = DataStore(str(tmp_path / "cat"))
        src = ds.create_schema(sft)
        files = self._csv_files(tmp_path, n_files=2)
        missing = str(tmp_path / "nope.csv")
        cfg = self._converter_cfg()
        rep = ingest_files(
            src, lambda: converter_from_config(sft, cfg), files + [missing],
            workers=2,
        )
        assert len(rep.files_ok) == 2
        assert len(rep.files_failed) == 1 and missing in rep.files_failed[0]
        assert src.get_count("INCLUDE") == 60

    def test_export_partitions(self, tmp_path):
        sft, batch, ds = make_store(tmp_path)
        src = ds.get_feature_source("gdelt")
        out = {}

        def writer(name, b):
            out[name] = len(b)

        names = export_partitions(src, writer, cql="score > 0", workers=3)
        assert names
        f = parse_cql("score > 0")
        assert sum(out.values()) == int(eval_filter(f, batch).sum())
