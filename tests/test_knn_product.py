"""The sparse fused-scan kNN through the PRODUCT paths (round-4
integration: VERDICT r3 #1 — the framework API must run the same kernel
the bench headline runs, not an 8x slower fallback).

Covers: process impl="sparse"/"fullscan"/auto resolution, the planner's
knn push-down (cached + scan paths), capacity calibration + overflow
fallback, and the sharded sparse scan's all_gather merge parity.
Interpret-mode Pallas on CPU — the same code Mosaic-compiles on TPU.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.engine.geodesy import haversine_m_np
from geomesa_tpu.engine.knn_scan import (
    capacity_bucket, count_match_tiles, knn_sparse_auto, knn_sparse_sharded)
from geomesa_tpu.plan import DataStore
from geomesa_tpu.process.knn import KNearestNeighborSearchProcess

SPEC = "speed:Double,dtg:Date,*geom:Point"
T0 = int(np.datetime64("2021-03-01T00:00:00", "ms").astype(np.int64))
DAY = 86400_000


def oracle(qx, qy, x, y, mask, k):
    out = np.empty((len(qx), k))
    cx, cy = x[mask], y[mask]
    for i in range(len(qx)):
        d = haversine_m_np(qx[i], qy[i], cx, cy)
        if len(d) >= k:
            out[i] = np.sort(d[np.argpartition(d, k - 1)[:k]])
        else:
            out[i, : len(d)] = np.sort(d)
            out[i, len(d):] = np.inf
    return out


def make_batch(n=20_000, seed=3):
    r = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec("ais", SPEC)
    x = np.sort(r.uniform(-5, 5, n))  # pseudo store order
    y = r.uniform(50, 60, n)
    return FeatureBatch.from_pydict(
        sft,
        {
            "speed": r.uniform(0, 30, n),
            "dtg": r.integers(T0, T0 + 7 * DAY, n),
            "geom": np.stack([x, y], 1),
        },
    )


class TestProcessSparse:
    @pytest.mark.parametrize("impl", ["sparse", "fullscan"])
    def test_filtered_batch_parity(self, impl):
        batch = make_batch()
        g = batch.columns["geom"]
        x, y = np.asarray(g.x), np.asarray(g.y)
        speed = np.asarray(batch.columns["speed"])
        rng = np.random.default_rng(5)
        qsft = SimpleFeatureType.from_spec("q", "*geom:Point")
        qx = rng.uniform(-4, 4, 12)
        qy = rng.uniform(52, 58, 12)
        queries = FeatureBatch.from_pydict(
            qsft, {"geom": np.stack([qx, qy], 1)}
        )
        proc = KNearestNeighborSearchProcess()
        res = proc.execute(
            queries, batch, num_desired=5,
            cql_filter="speed > 20 AND BBOX(geom, -3, 51, 3, 59)",
            impl=impl,
        )
        mask = (speed > 20) & (x >= -3) & (x <= 3) & (y >= 51) & (y <= 59)
        exp = oracle(qx, qy, x, y, mask, 5)
        np.testing.assert_allclose(
            np.sort(res.distances_m, 1), exp, rtol=1e-4, atol=1.0)
        # indices refer to the FULL batch and land on true matches
        assert res.features is batch
        assert mask[res.indices[np.isfinite(res.distances_m)]].all()
        if impl == "sparse":
            # capacity cached for the repeat query (planner-stats analog)
            assert len(proc._cap_cache) == 1
            res2 = proc.execute(
                queries, batch, num_desired=5,
                cql_filter="speed > 20 AND BBOX(geom, -3, 51, 3, 59)",
                impl=impl,
            )
            np.testing.assert_array_equal(res.distances_m, res2.distances_m)

    def test_polygon_filter_band_refine(self):
        # points within the f32 band of a polygon edge must classify
        # exactly on the fused-scan path (f64 refine — the filter_batch
        # path it replaces was f64 end-to-end)
        rng = np.random.default_rng(31)
        n = 4096
        sft = SimpleFeatureType.from_spec("t", "speed:Double,*geom:Point")
        x = np.sort(rng.uniform(0.0, 2.0, n))
        # plant points straddling the x=1.0 edge closer than f32 epsilon
        x[100] = 1.0 - 1e-9   # inside (f64), on-edge at f32
        x[101] = 1.0 + 1e-9   # outside (f64)
        y = rng.uniform(0.0, 1.0, n)
        y[100] = y[101] = 0.5
        batch = FeatureBatch.from_pydict(
            sft, {"speed": rng.uniform(0, 30, n),
                  "geom": np.stack([x, y], 1)})
        qsft = SimpleFeatureType.from_spec("q", "*geom:Point")
        queries = FeatureBatch.from_pydict(
            qsft, {"geom": np.array([[0.99, 0.5]])})
        proc = KNearestNeighborSearchProcess()
        cql = "INTERSECTS(geom, POLYGON((0 0, 1 0, 1 1, 0 1, 0 0)))"
        res = proc.execute(queries, batch, num_desired=5,
                           cql_filter=cql, impl="sparse")
        mask = (x <= 1.0) & (x >= 0.0) & (y >= 0.0) & (y <= 1.0)
        exp = oracle(np.array([0.99]), np.array([0.5]), x, y, mask, 5)
        np.testing.assert_allclose(
            np.sort(res.distances_m, 1), exp, rtol=1e-4, atol=1.0)
        fin = np.isfinite(res.distances_m)
        assert mask[res.indices[fin]].all()
        assert 101 not in res.indices[fin]

    def test_auto_resolution(self):
        r = KNearestNeighborSearchProcess._resolve_impl
        assert r("auto", 1 << 21, "speed > 5") == "sparse"
        assert r("auto", 1 << 21, "INCLUDE") == "fullscan"
        assert r("auto", 1 << 10, "speed > 5") == "haversine"
        assert r("mxu", 1 << 21, "INCLUDE") == "mxu"


class TestSparseAuto:
    def test_calibration_and_overflow_fallback(self):
        rng = np.random.default_rng(11)
        n = 1 << 15
        x = np.sort(rng.uniform(-180, 180, n))
        y = rng.uniform(-90, 90, n)
        mask = (x > -30) & (x < 30)
        qx = jnp.asarray(rng.uniform(-20, 20, 8), jnp.float32)
        qy = jnp.asarray(rng.uniform(-40, 40, 8), jnp.float32)
        jx = jnp.asarray(x, jnp.float32)
        jy = jnp.asarray(y, jnp.float32)
        jm = jnp.asarray(mask)
        exp = oracle(np.asarray(qx), np.asarray(qy), x, y, mask, 4)
        # auto-calibrated capacity covers the matching tiles
        fd, fi, cap = knn_sparse_auto(
            qx, qy, jx, jy, jm, k=4, interpret=True)
        assert cap >= int(np.asarray(count_match_tiles(jm)))
        np.testing.assert_allclose(
            np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)
        # undersized capacity overflows -> dense fallback, still exact
        fd2, fi2, cap2 = knn_sparse_auto(
            qx, qy, jx, jy, jm, k=4, tile_capacity=1, interpret=True)
        assert cap2 == -1
        np.testing.assert_allclose(
            np.sort(np.asarray(fd2), 1), exp, rtol=1e-4, atol=1.0)

    def test_capacity_bucket(self):
        assert capacity_bucket(0) == 64
        assert capacity_bucket(100) == 128
        assert capacity_bucket(120) == 256  # slack pushes past 128


class TestPlannerKnn:
    def _mk_store(self, tmp_path, cached):
        batch = make_batch(n=6000, seed=9)
        ds = DataStore(str(tmp_path / ("c" if cached else "p")),
                       use_device_cache=cached)
        src = ds.create_schema(batch.sft)
        src.write(batch)
        return src, batch

    @pytest.mark.parametrize("cached", [False, True])
    def test_store_parity(self, tmp_path, cached):
        src, batch = self._mk_store(tmp_path, cached)
        g = batch.columns["geom"]
        x, y = np.asarray(g.x), np.asarray(g.y)
        speed = np.asarray(batch.columns["speed"])
        rng = np.random.default_rng(13)
        qx = rng.uniform(-4, 4, 6)
        qy = rng.uniform(52, 58, 6)
        d, i, got = src.knn(
            "speed > 10 AND BBOX(geom, -4, 51, 4, 59)", qx, qy, k=3)
        mask = (speed > 10) & (x >= -4) & (x <= 4) & (y >= 51) & (y <= 59)
        exp = oracle(qx, qy, x, y, mask, 3)
        np.testing.assert_allclose(np.sort(d, 1), exp, rtol=1e-4, atol=1.0)
        # indices resolve to real matching rows of the returned batch
        gg = got.columns["geom"]
        gx, gy = np.asarray(gg.x), np.asarray(gg.y)
        gs = np.asarray(got.columns["speed"])
        fin = np.isfinite(d)
        sel = i[fin]
        assert (gs[sel] > 10).all()
        assert ((gx[sel] >= -4) & (gx[sel] <= 4)).all()

    def test_process_routes_through_planner(self, tmp_path):
        src, batch = self._mk_store(tmp_path, True)
        g = batch.columns["geom"]
        x, y = np.asarray(g.x), np.asarray(g.y)
        rng = np.random.default_rng(17)
        qsft = SimpleFeatureType.from_spec("q", "*geom:Point")
        qx = rng.uniform(-2, 2, 4)
        qy = rng.uniform(53, 57, 4)
        queries = FeatureBatch.from_pydict(
            qsft, {"geom": np.stack([qx, qy], 1)})
        proc = KNearestNeighborSearchProcess()
        res = proc.execute(
            queries, src, num_desired=4, estimated_distance_m=500_000.0,
            max_search_distance_m=2_000_000.0, impl="sparse",
        )
        mask = np.ones(len(x), bool)
        exp = oracle(qx, qy, x, y, mask, 4)
        # window-grown search must still be exact (recall condition)
        np.testing.assert_allclose(
            np.sort(res.distances_m, 1), exp, rtol=1e-4, atol=1.0)


class TestSparseSharded:
    def test_matches_single_device(self):
        import jax
        from jax.sharding import Mesh

        from geomesa_tpu.parallel.mesh import SHARD_AXIS

        devs = jax.devices()
        if len(devs) < 4:
            pytest.skip("needs >=4 virtual devices")
        mesh = Mesh(np.asarray(devs[:4]), (SHARD_AXIS,))
        rng = np.random.default_rng(23)
        n = 4 * 4096
        x = np.sort(rng.uniform(-60, 60, n))
        y = rng.uniform(-45, 45, n)
        mask = rng.random(n) < 0.3
        qx = rng.uniform(-30, 30, 8)
        qy = rng.uniform(-30, 30, 8)
        jq = (jnp.asarray(qx, jnp.float32), jnp.asarray(qy, jnp.float32))
        jd = (jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
              jnp.asarray(mask))
        fd, fi, ov = knn_sparse_sharded(
            mesh, *jq, *jd, k=4, tile_capacity=8, interpret=True)
        assert not bool(np.asarray(ov))
        exp = oracle(qx, qy, x, y, mask, 4)
        np.testing.assert_allclose(
            np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)
        # global indices hit true matches
        idx = np.asarray(fi)
        assert mask[idx[np.isfinite(np.asarray(fd))]].all()
