"""gmtpu-lint rule tests: for every rule GT01..GT06 a fixture module
with known violations (asserting exact rule codes and line numbers) and
a clean counterpart, the waiver channels, the two seeded advisor bugs
replayed against faithful pre-fix excerpts, and the self-lint check that
the shipped package is violation-free modulo committed waivers."""

import os
import subprocess
import sys
import textwrap

import pytest

from geomesa_tpu.analysis import lint_paths
from geomesa_tpu.analysis.linter import exit_code, render_json

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_src(tmp_path, source, name="mod.py", rules=None, **kw):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    # extra_ref_paths=[]: fixture universes are self-contained
    return lint_paths([str(tmp_path)], rules=rules,
                      extra_ref_paths=[], **kw)


def active(findings):
    return [f for f in findings if not f.waived]


def codes_lines(findings):
    return {(f.rule, f.line) for f in active(findings)}


# -- GT01 -------------------------------------------------------------------


class TestGT01Retrace:
    def test_loop_var_and_unhashable_static(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("k",))
            def kern(x, k):
                return x * k

            def run(xs):
                out = []
                for i in range(10):
                    out.append(kern(xs, k=i))
                bad = kern(xs, k=[1, 2])
                return out, bad
        """)
        assert ("GT01", 11) in codes_lines(fs)   # loop var into static
        assert ("GT01", 12) in codes_lines(fs)   # unhashable list literal
        assert all(f.rule == "GT01" for f in active(fs))

    def test_clean_constant_static_and_traced_loop_arg(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("k",))
            def kern(x, k):
                return x * k

            def run(xs):
                out = []
                for i in range(10):
                    out.append(kern(xs[i], k=4))
                return out
        """)
        assert not [f for f in active(fs) if f.rule == "GT01"]


# -- GT02 -------------------------------------------------------------------


class TestGT02HostTransfer:
    def test_host_ops_on_tracers(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import jax
            import numpy as np

            @jax.jit
            def bad(x):
                y = np.asarray(x)
                z = float(x)
                w = x.item()
                for v in x:
                    z = z + 1.0
                return y, z, w
        """)
        got = codes_lines(fs)
        assert ("GT02", 6) in got    # np.asarray on tracer
        assert ("GT02", 7) in got    # float() on tracer
        assert ("GT02", 8) in got    # .item() on tracer
        assert ("GT02", 9) in got    # host for-loop over tracer
        assert len([f for f in active(fs) if f.rule == "GT02"]) == 4

    def test_clean_jnp_and_static_args(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import functools
            import jax
            import jax.numpy as jnp
            import numpy as np

            @functools.partial(jax.jit, static_argnames=("n",))
            def good(x, n):
                consts = np.asarray([1.0, 2.0])
                acc = jnp.asarray(x)
                for i in range(n):
                    acc = acc + consts[0]
                return acc
        """)
        assert not [f for f in active(fs) if f.rule == "GT02"]


# -- GT03 -------------------------------------------------------------------


class TestGT03DtypeDrift:
    def test_f64_in_kernel_and_transitive_helper(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import jax
            import jax.numpy as jnp

            @jax.jit
            def kernel(x):
                y = x.astype(jnp.float64)
                z = x.astype("float64")
                return helper(y) + z

            def helper(v):
                return v + jnp.float64(1.0)
        """)
        got = codes_lines(fs)
        assert ("GT03", 6) in got    # jnp.float64 attr in kernel
        assert ("GT03", 7) in got    # 'float64' string dtype
        assert ("GT03", 11) in got   # transitively reachable helper

    def test_waiver_comment_suppresses(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import jax
            import jax.numpy as jnp

            @jax.jit
            def kernel(x):
                y = x.astype(jnp.float64)  # gt: f64-refine
                # gt: f64-refine
                z = x.astype(jnp.float64)
                return y + z
        """)
        gt03 = [f for f in fs if f.rule == "GT03"]
        assert gt03 and all(f.waived for f in gt03)
        assert not [f for f in active(fs) if f.rule == "GT03"]

    def test_f64_outside_kernel_paths_is_clean(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import jax
            import jax.numpy as jnp
            import numpy as np

            @jax.jit
            def kernel(x):
                return x + 1

            def host_refine(v):
                return np.asarray(v, np.float64)
        """)
        assert not [f for f in active(fs) if f.rule == "GT03"]


# -- GT04 -------------------------------------------------------------------


class TestGT04UnsyncedTiming:
    def test_unsynced_device_call_between_timestamps(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import time
            import jax

            @jax.jit
            def kern(x):
                return x + 1

            def timed(x):
                t0 = time.perf_counter()
                y = kern(x)
                dt = time.perf_counter() - t0
                return y, dt
        """)
        assert ("GT04", 11) in codes_lines(fs)

    def test_block_until_ready_syncs(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import time
            import jax

            @jax.jit
            def kern(x):
                return x + 1

            def timed(x):
                t0 = time.perf_counter()
                y = kern(x)
                y.block_until_ready()
                dt = time.perf_counter() - t0
                return y, dt
        """)
        assert not [f for f in active(fs) if f.rule == "GT04"]

    def test_np_asarray_counts_as_sync(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import time
            import jax
            import numpy as np

            @jax.jit
            def kern(x):
                return x + 1

            def timed(x):
                t0 = time.perf_counter()
                y = np.asarray(kern(x))
                dt = time.perf_counter() - t0
                return y, dt
        """)
        assert not [f for f in active(fs) if f.rule == "GT04"]


# -- GT05 -------------------------------------------------------------------


class TestGT05DeadJit:
    def test_dead_vs_live(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import jax

            @jax.jit
            def dead_kernel(x):
                return x + 1

            @jax.jit
            def live_kernel(x):
                return x * 2

            def use(x):
                return live_kernel(x)
        """)
        got = codes_lines(fs)
        assert ("GT05", 4) in got
        assert not any(r == "GT05" and ln != 4 for r, ln in got)

    def test_cross_module_reference_keeps_alive(self, tmp_path):
        (tmp_path / "kern.py").write_text(textwrap.dedent("""\
            import jax

            @jax.jit
            def exported_kernel(x):
                return x + 1
        """))
        (tmp_path / "caller.py").write_text(textwrap.dedent("""\
            from kern import exported_kernel

            def go(x):
                return exported_kernel(x)
        """))
        fs = lint_paths([str(tmp_path)], extra_ref_paths=[])
        assert not [f for f in active(fs) if f.rule == "GT05"]


# -- GT06 -------------------------------------------------------------------


class TestGT06MaskPlumbing:
    def test_sibling_sites_disagree(self, tmp_path):
        fs = lint_src(tmp_path, """\
            def scatter(mask, batch, allowed, compiled, dev, cached):
                if cached:
                    bidx, bexact = compiled.band_corrections(dev, batch)
                    mask = mask.at[bidx].set(bexact & allowed[bidx])
                else:
                    bidx, bexact = compiled.band_corrections(dev, batch)
                    bexact = bexact & batch.valid[bidx]
                    mask = mask.at[bidx].set(bexact)
                return mask
        """)
        assert ("GT06", 3) in codes_lines(fs)
        assert len([f for f in active(fs) if f.rule == "GT06"]) == 1

    def test_consistent_siblings_clean(self, tmp_path):
        fs = lint_src(tmp_path, """\
            def scatter(mask, batch, allowed, compiled, dev, cached):
                if cached:
                    bidx, bexact = compiled.band_corrections(dev, batch)
                    bexact = bexact & batch.valid[bidx]
                    mask = mask.at[bidx].set(bexact & allowed[bidx])
                else:
                    bidx, bexact = compiled.band_corrections(dev, batch)
                    bexact = bexact & batch.valid[bidx]
                    mask = mask.at[bidx].set(bexact)
                return mask
        """)
        assert not [f for f in active(fs) if f.rule == "GT06"]


# -- seeded advisor bugs, replayed ------------------------------------------


class TestSeededBugs:
    """Faithful pre-fix excerpts of the two advisor findings this PR
    fixed: the linter must catch both (they are the seed true positives
    for GT05 and GT06)."""

    def test_gt05_catches_dead_cx_nb(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import jax
            import jax.numpy as jnp

            class CompiledFilter:
                def _ensure_band_jits(self):
                    if hasattr(self, "_cx_nb"):
                        return
                    band_fn = self._band_fn
                    mask_fn = self._fn

                    def _nb(params, dev, extra):
                        b = band_fn(params, dev)
                        if extra is not None:
                            b = b & extra
                        return jnp.sum(b, dtype=jnp.int32)

                    def _gather(params, dev, extra, k):
                        b = band_fn(params, dev)
                        mm = mask_fn(params, dev)
                        return b, mm

                    self._cx_nb = jax.jit(_nb, static_argnames=())
                    self._cx_gather = jax.jit(_gather, static_argnames=("k",))

                def _band_rows(self, params, dev, extra):
                    return jax.device_get(
                        self._cx_gather(params, dev, extra, k=64))
        """)
        gt05 = [f for f in active(fs) if f.rule == "GT05"]
        assert len(gt05) == 1
        assert gt05[0].line == 22
        assert "_cx_nb" in gt05[0].message

    def test_gt06_catches_planner_cache_branch(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import jax.numpy as jnp

            def knn(self, plan, sb, batch, dev, mask, allowed, use_cache):
                if use_cache:
                    if plan.compiled is not None and plan.compiled.has_band:
                        bidx, bexact = plan.compiled.band_corrections(dev, batch)
                        if len(bidx):
                            import jax as _jax

                            pid_at = _jax.device_get(sb.pids[jnp.asarray(bidx)])
                            mask = mask.at[jnp.asarray(bidx)].set(
                                jnp.asarray(bexact & allowed[pid_at]))
                else:
                    if plan.compiled is not None and plan.compiled.has_band:
                        bidx, bexact = plan.compiled.band_corrections(dev, batch)
                        if len(bidx):
                            if batch.valid is not None:
                                bexact = bexact & batch.valid[bidx]
                            mask = mask.at[jnp.asarray(bidx)].set(
                                jnp.asarray(bexact))
                return mask
        """)
        gt06 = [f for f in active(fs) if f.rule == "GT06"]
        assert len(gt06) == 1
        assert gt06[0].line == 6
        assert "band_corrections" in gt06[0].message


# -- waiver file ------------------------------------------------------------


class TestWaiverFile:
    def test_file_waiver_by_glob_rule_and_line(self, tmp_path):
        src = """\
            import jax

            @jax.jit
            def dead_kernel(x):
                return x + 1
        """
        (tmp_path / "mod.py").write_text(textwrap.dedent(src))
        wf = tmp_path / "waivers.txt"
        wf.write_text("# seed waiver\nmod.py GT05 4\n")
        fs = lint_paths([str(tmp_path)], extra_ref_paths=[],
                        waiver_file=str(wf))
        gt05 = [f for f in fs if f.rule == "GT05"]
        assert gt05 and all(f.waived for f in gt05)
        assert not active(fs)

    def test_stale_line_pin_does_not_waive(self, tmp_path):
        (tmp_path / "mod.py").write_text(textwrap.dedent("""\
            import jax

            @jax.jit
            def dead_kernel(x):
                return x + 1
        """))
        wf = tmp_path / "waivers.txt"
        wf.write_text("mod.py GT05 99\n")
        fs = lint_paths([str(tmp_path)], extra_ref_paths=[],
                        waiver_file=str(wf))
        assert [f for f in active(fs) if f.rule == "GT05"]

    def test_malformed_waiver_file_raises(self, tmp_path):
        (tmp_path / "mod.py").write_text("x = 1\n")
        wf = tmp_path / "waivers.txt"
        wf.write_text("only-one-field\n")
        with pytest.raises(ValueError):
            lint_paths([str(tmp_path)], extra_ref_paths=[],
                       waiver_file=str(wf))


# -- output + exit codes ----------------------------------------------------


class TestOutputs:
    def test_exit_code_thresholds(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import jax

            @jax.jit
            def dead_kernel(x):
                return x + 1
        """)
        assert exit_code(fs, "warn") == 1
        assert exit_code(fs, "error") == 0   # warns don't trip error
        assert exit_code(fs, "never") == 0

    def test_json_render_roundtrips(self, tmp_path):
        import json

        fs = lint_src(tmp_path, """\
            import jax

            @jax.jit
            def dead_kernel(x):
                return x + 1
        """)
        doc = json.loads(render_json(fs))
        assert doc["active"] == len(active(fs))
        assert any(f["rule"] == "GT05" for f in doc["findings"])

    def test_cli_fails_on_violation_and_passes_clean(self, tmp_path):
        (tmp_path / "bad.py").write_text(textwrap.dedent("""\
            import jax

            @jax.jit
            def dead_kernel(x):
                return x + 1
        """))
        r = subprocess.run(
            [sys.executable, "-m", "geomesa_tpu.analysis",
             str(tmp_path), "--fail-on", "warn"],
            capture_output=True, text=True, cwd=REPO_ROOT)
        assert r.returncode == 1
        assert "GT05" in r.stdout
        (tmp_path / "bad.py").write_text("x = 1\n")
        r = subprocess.run(
            [sys.executable, "-m", "geomesa_tpu.analysis",
             str(tmp_path), "--fail-on", "warn"],
            capture_output=True, text=True, cwd=REPO_ROOT)
        assert r.returncode == 0

    def test_empty_scan_set_is_an_error_not_a_clean_pass(self, tmp_path):
        # default CWD-relative path from the wrong directory: zero
        # coverage must not read as a green gate
        r = subprocess.run(
            [sys.executable, "-m", "geomesa_tpu.analysis"],
            capture_output=True, text=True, cwd=str(tmp_path),
            env={**os.environ, "PYTHONPATH": REPO_ROOT})
        assert r.returncode == 2
        assert "no .py files" in r.stderr


class TestWaiverCascade:
    def test_directive_cascades_past_plain_comments_and_blanks(
            self, tmp_path):
        fs = lint_src(tmp_path, """\
            import jax

            # gt: waive GT05
            # explanation of why this entry point must stay

            @jax.jit
            def dead_kernel(x):
                return x + 1
        """)
        gt05 = [f for f in fs if f.rule == "GT05"]
        assert gt05 and all(f.waived for f in gt05)
        assert not active(fs)

    def test_directive_does_not_leak_past_the_next_code_line(
            self, tmp_path):
        fs = lint_src(tmp_path, """\
            import jax

            # gt: waive GT05
            x = 1

            @jax.jit
            def dead_kernel(x):
                return x + 1
        """)
        assert [f for f in active(fs) if f.rule == "GT05"]


class TestTextOutput:
    def test_summary_discloses_waived_count(self, tmp_path):
        from geomesa_tpu.analysis.linter import render_text

        fs = lint_src(tmp_path, """\
            import jax

            @jax.jit
            def dead_kernel(x):  # gt: waive GT05
                return x + 1
        """)
        out = render_text(fs)
        assert "0 finding(s), 1 waived" in out
        assert "dead_kernel" not in out          # waived line hidden...
        assert "dead_kernel" in render_text(fs, show_waived=True)


# -- GT15 -------------------------------------------------------------------


class TestGT15TelemetryDiscipline:
    """Wall-clock durations + un-scoped spans in serve/engine/telemetry
    (docs/OBSERVABILITY.md): time.time() feeding a subtraction, and a
    tracer .span() opened outside a `with` block."""

    def _findings(self, src, relpath="geomesa_tpu/serve/mod.py"):
        from geomesa_tpu.analysis.modinfo import ModInfo
        from geomesa_tpu.analysis.rules import gt15

        mod = ModInfo("/x.py", textwrap.dedent(src), relpath=relpath)
        return list(gt15(mod, None))

    DIRTY = """
        import time

        def latency():
            t0 = time.time()
            work()
            return time.time() - t0

        def direct():
            return time.time() - started
    """

    def test_duration_measurement_flagged(self):
        found = self._findings(self.DIRTY)
        assert found and all(f.rule == "GT15" for f in found)
        lines = {f.line for f in found}
        assert 5 in lines   # t0 = time.time() later subtracted
        assert 7 in lines   # time.time() as a direct Sub operand
        assert 10 in lines  # direct() body

    def test_clean_counterparts(self):
        clean = """
            import time

            def latency():
                t0 = time.perf_counter()
                work()
                return time.perf_counter() - t0

            def stamp(event):
                event.timestamp = time.time()   # a WHEN, not a duration

            def arithmetic():
                return a - b
        """
        assert self._findings(clean) == []

    def test_bare_time_import_flagged(self):
        src = """
            from time import time

            def f():
                t0 = time()
                return time() - t0
        """
        assert self._findings(src)

    def test_scope_is_path_limited(self):
        # plan/ keeps its perf_counter discipline via other means; the
        # wall-clock audit timestamps there are deliberate
        assert self._findings(self.DIRTY, "geomesa_tpu/plan/mod.py") == []
        assert self._findings(self.DIRTY, "bench.py") == []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/engine/mod.py")
        assert self._findings(
            self.DIRTY, "geomesa_tpu/telemetry/mod.py")

    def test_function_bodies_do_not_leak_scopes(self):
        """A timestamp in one function must not pair with an unrelated
        subtraction in another (or at module level): each def is its
        own scope, including defs seeded directly from the module."""
        src = """
            import time

            def stamp(ev):
                t0 = time.time()
                ev.ts = t0

            def width(a, t0):
                return a - t0
        """
        assert self._findings(src) == []

    def test_span_without_with_flagged(self):
        src = """
            def bad(tracer):
                s = tracer.span("phase")
                work()

            def good(tracer):
                with tracer.span("phase"):
                    work()

            def also_good(tracer, stack):
                stack.enter_context(tracer.span("phase"))
        """
        found = self._findings(src)
        assert [(f.rule, f.line) for f in found] == [("GT15", 3)]

    def test_waiver_and_registration(self):
        from geomesa_tpu.analysis.model import RULES
        from geomesa_tpu.analysis.rules import ALL_RULES

        assert "GT15" in RULES and "GT15" in ALL_RULES
        # inline waiver channel, through the full linter (the fixture
        # must live under a geomesa_tpu/serve/ path for GT15 scope)
        import pathlib
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            sub = pathlib.Path(td) / "geomesa_tpu" / "serve"
            sub.mkdir(parents=True)
            (sub / "mod.py").write_text(textwrap.dedent("""
                import time

                def f():
                    # gt: waive GT15
                    t0 = time.time()
                    return time.time() - t0
            """))
            fs = lint_paths([td], rules=["GT15"], extra_ref_paths=[])
            flagged = active(fs)
            # the waived assignment is suppressed; the direct operand
            # on the return line still flags
            assert all(f.line != 6 for f in flagged)


# -- GT16 -------------------------------------------------------------------


class TestGT16PipelineStageBlocking:
    """Blocking calls inside serve/pipeline.py prepare/transfer/launch
    stages (docs/SERVING.md "Pipelined dispatch"): a sync there
    silently re-serializes the window overlap."""

    def _findings(self, src,
                  relpath="geomesa_tpu/serve/pipeline.py"):
        from geomesa_tpu.analysis.modinfo import ModInfo
        from geomesa_tpu.analysis.rules import gt16

        mod = ModInfo("/x.py", textwrap.dedent(src), relpath=relpath)
        return list(gt16(mod, None))

    DIRTY = """
        import jax

        def _launch(self, win):
            fd = kernel(win.qx)
            fd.block_until_ready()

        def _transfer(self, win):
            return jax.device_get(win.staged)

        def submit(self, source, live):
            return live[0].future.result()
    """

    def test_blocking_in_stages_flagged(self):
        found = self._findings(self.DIRTY)
        assert sorted((f.rule, f.line) for f in found) == [
            ("GT16", 6), ("GT16", 9), ("GT16", 12)]

    def test_clean_counterparts(self):
        clean = """
            def _launch(self, win):
                win.launch = planner.knn_launch(win.qx)   # async

            def _prepare(self, win):
                win.running = [r for r in win.live
                               if r.future.set_running_or_notify_cancel()]

            def _sync(self, win):
                # the completer's job: blocking is CORRECT here
                win.launch.sync()
                win.fd.block_until_ready()

            def _complete_loop(self):
                fut.result()
        """
        assert self._findings(clean) == []

    def test_scope_is_path_limited(self):
        assert self._findings(
            self.DIRTY, "geomesa_tpu/serve/batcher.py") == []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/plan/planner.py") == []

    def test_waiver_and_registration(self):
        from geomesa_tpu.analysis.model import RULES
        from geomesa_tpu.analysis.rules import ALL_RULES

        assert "GT16" in RULES and "GT16" in ALL_RULES
        assert "GT23" in RULES and "GT23" in ALL_RULES
        import pathlib
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            sub = pathlib.Path(td) / "geomesa_tpu" / "serve"
            sub.mkdir(parents=True)
            (sub / "pipeline.py").write_text(textwrap.dedent("""
                def _launch(self, win):
                    # gt: waive GT16
                    win.fd.block_until_ready()
            """))
            fs = lint_paths([td], rules=["GT16"], extra_ref_paths=[])
            assert any(f.rule == "GT16" and f.waived for f in fs)
            assert not active([f for f in fs if f.rule == "GT16"])


# -- GT23 -------------------------------------------------------------------


class TestGT23RingFeedBlocking:
    """Blocking host syncs or naked per-window transfers inside the
    ring feed loop scope (docs/SERVING.md "Persistent serve loop"):
    per-window work is ONLY a stager slot write + one pre-compiled
    dispatch."""

    def _findings(self, src,
                  relpath="geomesa_tpu/serve/ringloop.py"):
        from geomesa_tpu.analysis.modinfo import ModInfo
        from geomesa_tpu.analysis.rules import gt23

        mod = ModInfo("/x.py", textwrap.dedent(src), relpath=relpath)
        return list(gt23(mod, None))

    DIRTY = """
        import jax

        def try_feed(self, win):
            fd = self.handle.call(win.qx)
            fd.block_until_ready()

        def _slot_write(self, win):
            return jax.device_put(win.qx)

        def _feed_one(self, win):
            win.staged = to_device(win.batch)
            return win.future.result()
    """

    def test_blocking_and_transfers_in_feed_scope_flagged(self):
        found = self._findings(self.DIRTY)
        assert sorted((f.rule, f.line) for f in found) == [
            ("GT23", 6), ("GT23", 9), ("GT23", 12), ("GT23", 13)]

    def test_clean_counterparts(self):
        clean = """
            def try_feed(self, win):
                # the DESIGNATED slot write: the stager owns the
                # device_put (retry fabric + rotation contract)
                win.staged = self._stager.stage(key, win.qx, win.qy)
                win.launch = prog.launch(win.staged, win.qx, win.qy)
                return True

            def _arm(self, key, win):
                # arm scope is NOT feed scope: the one-time setup may
                # sync (calibration, fused-count precompute)
                return planner.ring_arm(win.lead.query)

            def _sync(self, win):
                win.launch.sync()
        """
        assert self._findings(clean) == []

    def test_scope_is_path_limited(self):
        assert self._findings(
            self.DIRTY, "geomesa_tpu/serve/pipeline.py") == []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/plan/planner.py") == []

    def test_waiver(self):
        import pathlib
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            sub = pathlib.Path(td) / "geomesa_tpu" / "serve"
            sub.mkdir(parents=True)
            (sub / "ringloop.py").write_text(textwrap.dedent("""
                def try_feed(self, win):
                    # gt: waive GT23
                    win.fd.block_until_ready()
            """))
            fs = lint_paths([td], rules=["GT23"], extra_ref_paths=[])
            assert any(f.rule == "GT23" and f.waived for f in fs)
            assert not active([f for f in fs if f.rule == "GT23"])


# -- GT17 -------------------------------------------------------------------


class TestGT17ListenerBlocking:
    """Blocking calls inside subscription listener/callback bodies
    (docs/SERVING.md "Standing queries"): listeners run inside the
    Kafka fold with the store lock held — they must only buffer."""

    def _findings(self, src,
                  relpath="geomesa_tpu/subscribe/evaluator.py"):
        from geomesa_tpu.analysis.modinfo import ModInfo
        from geomesa_tpu.analysis.rules import gt17

        mod = ModInfo("/x.py", textwrap.dedent(src), relpath=relpath)
        return list(gt17(mod, None))

    DIRTY = """
        import time

        def on_feature_event(event):
            with open("/tmp/log", "a") as f:
                f.write(str(event))

        def my_listener(event):
            return fut.result()

        def install(cache):
            def hook(event):
                time.sleep(0.1)
                dev = to_device(event.batch)
            cache.add_listener(hook)
    """

    def test_blocking_in_listeners_flagged(self):
        found = self._findings(self.DIRTY)
        assert sorted((f.rule, f.line) for f in found) == [
            ("GT17", 5), ("GT17", 9), ("GT17", 13), ("GT17", 14)]

    def test_clean_counterparts(self):
        clean = """
            def on_feature_event(event):
                with buf_lock:
                    buffer.append((event.kind, event.fid))

            def pump(type_name):
                # NOT a listener: the post-fold pump is where device
                # work belongs
                dev = to_device(batch)
                out = jax.device_get(handle.call(dev))

            def install(cache):
                def hook(event):
                    buffer.append(event)
                cache.add_listener(hook)
        """
        assert self._findings(clean) == []

    def test_scope_is_path_limited(self):
        assert self._findings(
            self.DIRTY, "geomesa_tpu/serve/service.py") == []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/engine/device.py") == []

    def test_kafka_scope_and_registration(self):
        from geomesa_tpu.analysis.model import RULES
        from geomesa_tpu.analysis.rules import ALL_RULES

        assert "GT17" in RULES and "GT17" in ALL_RULES
        # kafka/ is in scope: cache listener helpers are covered
        found = self._findings(self.DIRTY,
                               "geomesa_tpu/kafka/cache.py")
        assert found

    def test_waiver(self):
        import pathlib
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            sub = pathlib.Path(td) / "geomesa_tpu" / "subscribe"
            sub.mkdir(parents=True)
            (sub / "x.py").write_text(textwrap.dedent("""
                def on_event(e):
                    # gt: waive GT17
                    fut.result()
            """))
            fs = lint_paths([td], rules=["GT17"], extra_ref_paths=[])
            assert any(f.rule == "GT17" and f.waived for f in fs)
            assert not active([f for f in fs if f.rule == "GT17"])


class TestGT18PerDevicePlacement:
    """Per-device placement bypassing NamedSharding (docs/SERVING.md
    "Sharded serving"): serve//plan/ place data ONCE via NamedSharding
    over the mesh — per-chip device_put loops and jax.devices()[i]
    indexing break the recorded tile ownership."""

    def _findings(self, src, relpath="geomesa_tpu/serve/batcher.py"):
        from geomesa_tpu.analysis.modinfo import ModInfo
        from geomesa_tpu.analysis.rules import gt18

        mod = ModInfo("/x.py", textwrap.dedent(src), relpath=relpath)
        return list(gt18(mod, None))

    DIRTY = """
        import jax

        def upload(batch):
            out = []
            for d in jax.devices():
                out.append(jax.device_put(batch.slice_for(d), d))
            return out

        def upload_alias(batch):
            devs = jax.devices()
            first = devs[0]
            return jax.device_put(batch, jax.devices()[1])

        def upload_to_device_loop(parts):
            for i, dev in enumerate(parts):
                to_device(parts[i], device=dev)
    """

    def test_loops_and_indexing_flagged(self):
        found = self._findings(self.DIRTY)
        lines = sorted((f.rule, f.line) for f in found)
        # loop device_put (7), alias subscript (12), direct
        # jax.devices()[1] subscript (13), dev-named loop (17)
        assert lines == [("GT18", 7), ("GT18", 12), ("GT18", 13),
                         ("GT18", 17)], lines

    def test_clean_counterparts(self):
        clean = """
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            def upload(batch, mesh):
                row = NamedSharding(mesh, P("shard"))
                return to_device(batch, device=row)

            def pin(mask, mesh):
                return jax.device_put(mask, NamedSharding(mesh, P()))

            def per_partition(parts):
                # a loop over PARTITIONS with one shared placement is
                # the single-chip residency path, not per-device
                for name in sorted(parts):
                    to_device(parts[name])
        """
        assert self._findings(clean) == []

    def test_scope_is_path_limited(self):
        assert self._findings(
            self.DIRTY, "geomesa_tpu/engine/device.py") == []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/parallel/mesh.py") == []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/plan/planner.py") != []

    def test_registration(self):
        from geomesa_tpu.analysis.model import RULES
        from geomesa_tpu.analysis.rules import ALL_RULES

        assert "GT18" in RULES and "GT18" in ALL_RULES

    def test_waiver(self):
        import pathlib
        import tempfile

        with tempfile.TemporaryDirectory() as td:
            sub = pathlib.Path(td) / "geomesa_tpu" / "serve"
            sub.mkdir(parents=True)
            (sub / "x.py").write_text(textwrap.dedent("""
                import jax

                def pick():
                    # gt: waive GT18
                    return jax.devices()[0]
            """))
            fs = lint_paths([td], rules=["GT18"], extra_ref_paths=[])
            assert any(f.rule == "GT18" and f.waived for f in fs)
            assert not active([f for f in fs if f.rule == "GT18"])


class TestGT19MetricLabelConsistency:
    """One metric family, different label-key sets across call sites
    (docs/OBSERVABILITY.md): the registry keys series by name+labels,
    so a label-schema fork renders one Prometheus family with
    incompatible schemas — strict scrapers reject it, joins drop
    samples silently."""

    def _findings(self, src, relpath="geomesa_tpu/serve/service.py"):
        from geomesa_tpu.analysis.modinfo import ModInfo
        from geomesa_tpu.analysis.rules import gt19

        mod = ModInfo("/x.py", textwrap.dedent(src), relpath=relpath)
        return list(gt19(mod, None))

    DIRTY = """
        from geomesa_tpu.utils.metrics import metrics

        def on_ok(kind, status, tenant):
            metrics.counter("serve.requests", kind=kind, status=status)
            metrics.counter("serve.requests", kind=kind, status=status)

        def on_shed(kind):
            metrics.counter("serve.requests", kind=kind)

        def scrape(depth):
            metrics.gauge("serve.queue.depth", depth, shard="0")

        def refresh(depth):
            metrics.gauge("serve.queue.depth", float(depth))
    """

    def test_minority_sites_flagged(self):
        found = self._findings(self.DIRTY)
        lines = sorted((f.rule, f.line) for f in found)
        # the {kind}-only counter site (9) forks serve.requests away
        # from the majority {kind,status} schema; the two queue.depth
        # gauge sites tie 1-1, so first-in-file-order ({shard}) wins
        # and the unlabeled site (15) is flagged
        assert lines == [("GT19", 9), ("GT19", 15)], lines
        assert "serve.requests" in found[0].message

    def test_clean_counterparts(self):
        clean = """
            from geomesa_tpu.utils.metrics import metrics

            def on_ok(kind, status):
                metrics.counter("serve.requests", kind=kind,
                                status=status)

            def on_shed(kind):
                # same schema everywhere = one family, no fork
                metrics.counter("serve.requests", kind=kind,
                                status="shed")

            def scrape(depth, name):
                metrics.gauge("serve.queue.depth", float(depth))
                # non-literal family names are not comparable: skipped
                metrics.gauge(f"fault.breaker.{name}", 1.0)
                # `inc` is the counter's amount param, not a label
                metrics.counter("serve.coalesced", inc=3)
                metrics.counter("serve.coalesced")
        """
        assert self._findings(clean) == []

    def test_scope_is_path_limited(self):
        assert self._findings(
            self.DIRTY, "geomesa_tpu/subscribe/registry.py") == []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/telemetry/slo.py") != []

    def test_cross_module_via_project(self, tmp_path):
        """The real gate path: two serve/ modules disagreeing on one
        family — the minority module's site is flagged."""
        import pathlib

        sub = pathlib.Path(tmp_path) / "geomesa_tpu" / "serve"
        sub.mkdir(parents=True)
        (sub / "a.py").write_text(textwrap.dedent("""
            def f(kind):
                metrics.counter("serve.widgets", kind=kind)
                metrics.counter("serve.widgets", kind=kind)
        """))
        (sub / "b.py").write_text(textwrap.dedent("""
            def g():
                metrics.counter("serve.widgets")
        """))
        fs = lint_paths([str(tmp_path)], rules=["GT19"],
                        extra_ref_paths=[])
        hits = {(f.path.replace("\\", "/"), f.line)
                for f in active(fs)}
        assert {(p.rsplit("geomesa_tpu/", 1)[-1], ln)
                for p, ln in hits} == {("serve/b.py", 3)}, hits

    def test_registration(self):
        from geomesa_tpu.analysis.model import RULES
        from geomesa_tpu.analysis.rules import ALL_RULES

        assert "GT19" in RULES and "GT19" in ALL_RULES

    def test_waiver(self, tmp_path):
        import pathlib

        sub = pathlib.Path(tmp_path) / "geomesa_tpu" / "serve"
        sub.mkdir(parents=True)
        (sub / "x.py").write_text(textwrap.dedent("""
            def f(kind):
                metrics.counter("serve.widgets", kind=kind)
                metrics.counter("serve.widgets", kind=kind)

            def g():
                # gt: waive GT19
                metrics.counter("serve.widgets")
        """))
        fs = lint_paths([str(tmp_path)], rules=["GT19"],
                        extra_ref_paths=[])
        assert any(f.rule == "GT19" and f.waived for f in fs)
        assert not active([f for f in fs if f.rule == "GT19"])


class TestGT20SocketTimeouts:
    """Unbounded socket calls in fleet scope (docs/ANALYSIS.md GT20):
    a connect/recv with no timeout in the router blocks its reader
    thread forever behind one dead peer — the whole fleet's failover
    wedges with it."""

    def _findings(self, src, relpath="geomesa_tpu/fleet/router.py"):
        from geomesa_tpu.analysis.modinfo import ModInfo
        from geomesa_tpu.analysis.rules import gt20

        mod = ModInfo("/x.py", textwrap.dedent(src), relpath=relpath)
        return list(gt20(mod, None))

    DIRTY = """
        import socket

        def dial(host, port):
            s = socket.socket()
            s.connect((host, port))
            return s.recv(4096)

        def dial2(host, port):
            return socket.create_connection((host, port))

        def serve(listener):
            conn, _ = listener.accept()
            return conn
    """

    def test_unbounded_calls_flagged(self):
        found = self._findings(self.DIRTY)
        lines = sorted((f.rule, f.line) for f in found)
        # connect(6), recv(7), create_connection(10), accept(13)
        assert lines == [("GT20", 6), ("GT20", 7),
                         ("GT20", 10), ("GT20", 13)], lines

    def test_clean_counterparts(self):
        clean = """
            import socket

            class Link:
                def __init__(self, host, port):
                    # cross-method: configured here, read elsewhere
                    self.sock = socket.create_connection(
                        (host, port), timeout=5.0)
                    self.sock.settimeout(0.25)

                def read(self):
                    return self.sock.recv(4096)

            def dial(host, port):
                s = socket.socket()
                s.settimeout(2.0)
                s.connect((host, port))
                return s.recv(64)

            def dial_positional(host, port):
                c = socket.create_connection((host, port), 5.0)
                return c

            def serve(listener):
                listener.settimeout(0.25)
                conn, _ = listener.accept()
                return conn
        """
        assert self._findings(clean) == []

    def test_setdefaulttimeout_exempts_module(self):
        src = """
            import socket

            socket.setdefaulttimeout(3.0)

            def dial(host, port):
                s = socket.socket()
                s.connect((host, port))
                return s.recv(64)
        """
        assert self._findings(src) == []

    def test_scope_is_path_limited(self):
        # the engine talks no sockets; other layers are out of scope
        assert self._findings(
            self.DIRTY, "geomesa_tpu/engine/device.py") == []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/serve/protocol.py") != []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/fleet/wire.py") != []

    def test_registration(self):
        from geomesa_tpu.analysis.model import RULES
        from geomesa_tpu.analysis.rules import ALL_RULES

        assert "GT20" in RULES and "GT20" in ALL_RULES

    def test_waiver(self, tmp_path):
        import pathlib

        sub = pathlib.Path(tmp_path) / "geomesa_tpu" / "fleet"
        sub.mkdir(parents=True)
        (sub / "x.py").write_text(textwrap.dedent("""
            import socket

            def dial(host, port):
                s = socket.socket()
                # gt: waive GT20
                s.connect((host, port))
                return s
        """))
        fs = lint_paths([str(tmp_path)], rules=["GT20"],
                        extra_ref_paths=[])
        assert any(f.rule == "GT20" and f.waived for f in fs)
        assert not active([f for f in fs if f.rule == "GT20"])


# -- self-lint --------------------------------------------------------------


class TestSelfLint:
    def test_shipped_package_is_clean_modulo_waivers(self):
        fs = lint_paths([os.path.join(REPO_ROOT, "geomesa_tpu")])
        bad = active(fs)
        assert not bad, "\n".join(f.render() for f in bad)
        # the deliberate f64 stats accumulations ride on inline waivers,
        # so the waiver channel itself is exercised by the shipped tree

    def test_subset_scan_sees_callers_outside_the_subset(self):
        # GT05 liveness: linting one engine file alone must not flag
        # kernels whose call sites live elsewhere in the package
        fs = lint_paths(
            [os.path.join(REPO_ROOT, "geomesa_tpu", "engine", "stats.py")])
        gt05 = [f for f in active(fs) if f.rule == "GT05"]
        assert not gt05, "\n".join(f.render() for f in gt05)
        assert any(f.waived and f.rule == "GT03" for f in fs)


class TestGT21RawCqlCacheKeys:
    """Result-cache keys built from raw CQL text (docs/ANALYSIS.md
    GT21): equivalent filter spellings fork the key space — a dashboard
    fleet's repeated queries become a cache-miss storm instead of dict
    hits."""

    def _findings(self, src, relpath="geomesa_tpu/serve/service.py"):
        from geomesa_tpu.analysis.modinfo import ModInfo
        from geomesa_tpu.analysis.rules import gt21

        mod = ModInfo("/x.py", textwrap.dedent(src), relpath=relpath)
        return list(gt21(mod, None))

    DIRTY = """
        from geomesa_tpu.approx.cache import result_key

        def peek(result_cache, req, version):
            key = result_key(req.kind, req.query.cql, version)
            return result_cache.get(key)

        def peek_wire(result_cache, doc, version):
            return result_cache.get(
                ("count", doc["typeName"], doc["cql"], version))

        def put_wire(result_cache, doc, out, version):
            result_cache.put(
                ("count", doc.get("cql"), version), out)
    """

    def test_raw_cql_keys_flagged(self):
        found = self._findings(self.DIRTY)
        # result_key(.cql) line 5, .get(doc["cql"]) line 9, .put(.get("cql")) line 13
        lines = sorted(f.line for f in found)
        assert len(found) == 3, found
        assert all(f.rule == "GT21" for f in found)
        assert lines == [5, 9, 13], lines

    def test_clean_counterparts(self):
        clean = """
            from geomesa_tpu.approx.cache import result_key
            from geomesa_tpu.cql import ast

            def peek(result_cache, req, version):
                # the Query OBJECT canonicalizes inside result_key
                key = result_key(req.kind, req.query, version)
                return result_cache.get(key)

            def peek_explicit(result_cache, query, version):
                cql = ast.to_cql(query.filter_ast)
                return result_cache.get(("count", cql, version))

            def unrelated(sub, filters):
                # .cql reads OUTSIDE cache-key construction never fire
                return filters[(sub.type_name, sub.cql)]
        """
        assert self._findings(clean) == []

    def test_scope_is_path_limited(self):
        assert self._findings(
            self.DIRTY, "geomesa_tpu/subscribe/evaluator.py") == []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/approx/cache.py") != []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/plan/planner.py") != []

    def test_registration(self):
        from geomesa_tpu.analysis.model import RULES
        from geomesa_tpu.analysis.rules import ALL_RULES

        assert "GT21" in RULES and "GT21" in ALL_RULES

    def test_waiver(self, tmp_path):
        import pathlib

        sub = pathlib.Path(tmp_path) / "geomesa_tpu" / "serve"
        sub.mkdir(parents=True)
        (sub / "x.py").write_text(textwrap.dedent("""
            def peek(result_cache, doc, version):
                # gt: waive GT21
                return result_cache.get(("count", doc["cql"], version))
        """))
        fs = lint_paths([str(tmp_path)], rules=["GT21"],
                        extra_ref_paths=[])
        assert any(f.rule == "GT21" and f.waived for f in fs)
        assert not active([f for f in fs if f.rule == "GT21"])


class TestGT22PerRowWireEncode:
    """Per-row serialization in a wire-encode loop (docs/ANALYSIS.md
    GT22): the columnar wire removed the per-feature dict +
    per-subscriber json.dumps pattern from the hot path — this rule
    keeps it from creeping back into serve//subscribe/."""

    def _findings(self, src, relpath="geomesa_tpu/serve/protocol.py"):
        from geomesa_tpu.analysis.modinfo import ModInfo
        from geomesa_tpu.analysis.rules import gt22

        mod = ModInfo("/x.py", textwrap.dedent(src), relpath=relpath)
        return list(gt22(mod, None))

    DIRTY = """
        import json

        def flush(subs, frame, write):
            for sub in subs:
                write(json.dumps(frame) + "\\n")

        def rows_json(batch, names):
            out = []
            for i in range(len(batch)):
                out.append({n: batch[n][i] for n in names})
            return out

        def rows_comp(batch, names, n):
            return [{k: batch[k][i] for k in names} for i in range(n)]
    """

    def test_per_row_encode_flagged(self):
        found = self._findings(self.DIRTY)
        lines = sorted(f.line for f in found)
        assert len(found) == 3, found
        assert all(f.rule == "GT22" for f in found)
        # dumps-in-loop line 6, dictcomp-in-loop line 11, dictcomp-in-
        # listcomp line 15
        assert lines == [6, 11, 15], lines

    def test_clean_counterparts(self):
        clean = """
            import json

            def flush_once(subs, frame, offer):
                # ONE encode, the same buffer fans to every sink
                buf = (json.dumps(frame) + "\\n").encode()
                for sub in subs:
                    offer(sub, buf)

            def respond(doc, write):
                # one dumps per CALL is fine even when callers loop
                write(json.dumps(doc) + "\\n")

            def explicit_rows(batch, names, n):
                # the JSON fallback's explicit per-row dict build
                # (protocol._rows_json shape) stays legal: the rule
                # targets comprehension-built row dicts + in-loop dumps
                rows = []
                for i in range(n):
                    row = {}
                    for name in names:
                        row[name] = batch[name][i]
                    rows.append(row)
                return rows

            TOP = {k: v for k, v in [("a", 1)]}
        """
        assert self._findings(clean) == []

    def test_scope_is_path_limited(self):
        assert self._findings(
            self.DIRTY, "geomesa_tpu/plan/planner.py") == []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/subscribe/manager.py") != []
        assert self._findings(
            self.DIRTY, "geomesa_tpu/serve/loadgen.py") != []

    def test_registration(self):
        from geomesa_tpu.analysis.model import RULES
        from geomesa_tpu.analysis.rules import ALL_RULES

        assert "GT22" in RULES and "GT22" in ALL_RULES

    def test_waiver(self, tmp_path):
        import pathlib

        sub = pathlib.Path(tmp_path) / "geomesa_tpu" / "serve"
        sub.mkdir(parents=True)
        (sub / "x.py").write_text(textwrap.dedent("""
            import json

            def flush(subs, frame, write):
                for sub in subs:
                    # gt: waive GT22
                    write(json.dumps(frame) + "\\n")
        """))
        fs = lint_paths([str(tmp_path)], rules=["GT22"],
                        extra_ref_paths=[])
        assert any(f.rule == "GT22" and f.waived for f in fs)
        assert not active([f for f in fs if f.rule == "GT22"])
