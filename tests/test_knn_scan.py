"""Fused-scan kNN kernel tests (Pallas interpret mode on CPU; the same
code path compiles via Mosaic on TPU — measured there at 570M pts/s
sparse / 259M dense on the 67M-point config-3 shape).

Parity oracle: NumPy f64 haversine + argpartition over the masked rows
(tests/reference_engine.py style), the same oracle the bench gates on.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from geomesa_tpu.engine.geodesy import haversine_m_np
from geomesa_tpu.engine.knn_scan import (
    chord_blockmin, knn_fullscan, knn_fullscan_tiled, knn_sparse_scan)

# tiny tiles: interpret mode executes the grid serially in Python — the
# TPU-targeted tile sizes would take minutes per call on CPU
TINY = dict(blk=256, data_tile=2048)


def oracle(qx, qy, x, y, mask, k):
    out = np.empty((len(qx), k))
    cx, cy = x[mask], y[mask]
    for i in range(len(qx)):
        d = haversine_m_np(qx[i], qy[i], cx, cy)
        if len(d) >= k:
            out[i] = np.sort(d[np.argpartition(d, k - 1)[:k]])
        else:
            out[i, : len(d)] = np.sort(d)
            out[i, len(d):] = np.inf
    return out


def make(n, q, seed=7, sorted_x=False, sel=0.4):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-180, 180, n)
    if sorted_x:
        x = np.sort(x)
    y = rng.uniform(-90, 90, n)
    mask = rng.random(n) < sel
    qx = rng.uniform(-30, 30, q)
    qy = rng.uniform(-60, 60, q)
    dev = [jnp.asarray(a, jnp.float32) for a in (qx, qy, x, y)]
    return qx, qy, x, y, mask, dev + [jnp.asarray(mask)]


class TestFullscan:
    def test_parity_random_mask(self):
        qx, qy, x, y, mask, dev = make(6000, 24)
        fd, fi = knn_fullscan(*dev, k=5, m_blocks=8, interpret=True, **TINY)
        exp = oracle(qx, qy, x, y, mask, 5)
        np.testing.assert_allclose(
            np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)
        # returned indices are real matches whose distances reproduce fd
        idx = np.asarray(fi)
        for i in range(5):
            dd = haversine_m_np(qx[i], qy[i], x[idx[i]], y[idx[i]])
            np.testing.assert_allclose(
                np.sort(dd), np.sort(np.asarray(fd)[i]), rtol=1e-4, atol=1.0)
            assert mask[idx[i]].all()

    def test_fewer_matches_than_k(self):
        qx, qy, x, y, _, dev = make(4096, 8)
        mask = np.zeros(4096, bool)
        mask[[5, 99, 3000]] = True
        dev[4] = jnp.asarray(mask)
        fd, fi = knn_fullscan(*dev, k=6, m_blocks=8, interpret=True, **TINY)
        fd = np.asarray(fd)
        assert np.isfinite(fd[:, :3]).all() and np.isinf(fd[:, 3:]).all()
        assert mask[np.asarray(fi)[:, :3]].all()

    def test_m_blocks_contract(self):
        _, _, _, _, _, dev = make(2048, 4)
        with pytest.raises(ValueError, match="m_blocks"):
            knn_fullscan(*dev, k=9, m_blocks=8, interpret=True, **TINY)

    def test_query_tiling(self):
        qx, qy, x, y, mask, dev = make(4096, 40)
        fd, _ = knn_fullscan_tiled(
            *dev, k=3, m_blocks=4, query_tile=16, interpret=True)
        exp = oracle(qx, qy, x, y, mask, 3)
        np.testing.assert_allclose(
            np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)

    def test_blockmin_matches_dense_key(self):
        rng = np.random.default_rng(3)
        n, q = 2048, 8
        x = rng.uniform(-180, 180, n).astype(np.float32)
        y = rng.uniform(-90, 90, n).astype(np.float32)
        mf = (rng.random(n) < 0.5).astype(np.float32)
        qx = rng.uniform(-30, 30, q).astype(np.float32)
        qy = rng.uniform(30, 60, q).astype(np.float32)
        minima, c = chord_blockmin(
            jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(x), jnp.asarray(y),
            jnp.asarray(mf), blk=256, data_tile=2048, interpret=True)

        def unit3(lon, lat):
            rl, rt = np.radians(lon), np.radians(lat)
            return np.stack([np.cos(rt) * np.cos(rl),
                             np.cos(rt) * np.sin(rl), np.sin(rt)], -1)

        qu = unit3(qx, qy).astype(np.float32)
        cc = qu.mean(0)
        dc = unit3(x, y).astype(np.float32) - cc
        nd = (dc * dc).sum(1) + (1 - mf) * 1e9
        key = nd[None, :] - 2 * ((qu - cc) @ dc.T)
        exp = key.reshape(q, -1, 256).min(-1)
        got = np.asarray(minima)
        # f32 association-order noise only
        assert np.abs(got - exp).max() / np.abs(exp).max() < 1e-2


class TestSparseScan:
    def test_parity_and_no_overflow_on_sorted(self):
        qx, qy, x, y, _, dev = make(16384, 12, sorted_x=True)
        mask = (x > -60) & (x < 60)
        dev[4] = jnp.asarray(mask)
        fd, fi, ov = knn_sparse_scan(
            *dev, k=5, tile_capacity=8, m_blocks=8, interpret=True, **TINY)
        assert not bool(ov)
        exp = oracle(qx, qy, x, y, mask, 5)
        np.testing.assert_allclose(
            np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)

    def test_overflow_flags_capacity_breach(self):
        _, _, x, y, _, dev = make(16384, 4)
        dev[4] = jnp.asarray(np.ones(16384, bool))
        _, _, ov = knn_sparse_scan(
            *dev, k=3, tile_capacity=4, m_blocks=8, interpret=True, **TINY)
        assert bool(ov)

    def test_empty_mask(self):
        qx, qy, x, y, _, dev = make(4096, 4)
        dev[4] = jnp.asarray(np.zeros(4096, bool))
        fd, _, ov = knn_sparse_scan(
            *dev, k=3, tile_capacity=4, m_blocks=8, interpret=True, **TINY)
        assert not bool(ov)
        assert np.isinf(np.asarray(fd)).all()

    def test_matches_only_in_last_tile(self):
        # selection order: tile ids must map back to ORIGINAL lanes
        qx, qy, x, y, _, dev = make(8192, 6)
        mask = np.zeros(8192, bool)
        mask[-50:] = True
        dev[4] = jnp.asarray(mask)
        fd, fi, ov = knn_sparse_scan(
            *dev, k=4, tile_capacity=2, m_blocks=8, interpret=True, **TINY)
        assert not bool(ov)
        exp = oracle(qx, qy, x, y, mask, 4)
        np.testing.assert_allclose(
            np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)
        assert (np.asarray(fi) >= 8192 - 50).all()


def test_sparse_dead_slots_never_duplicate_tile0():
    # capacity-padding programs alias data tile 0; with sparse matches
    # (fewer real blocks than m_blocks) their PENALTY minima used to win
    # selection and duplicate tile-0 lanes in the refine pool
    rng = np.random.default_rng(13)
    n, q, k = 8192, 6, 4
    x = np.sort(rng.uniform(-180, 180, n))
    y = rng.uniform(-90, 90, n)
    mask = np.zeros(n, bool)
    mask[:6] = True  # all matches in tile 0, fewer than k*blk
    qx = rng.uniform(-30, 30, q)
    qy = rng.uniform(-60, 60, q)
    dev = [jnp.asarray(a, jnp.float32) for a in (qx, qy, x, y)]
    fd, fi, ov = knn_sparse_scan(
        *dev, jnp.asarray(mask), k=k, tile_capacity=8, m_blocks=8,
        interpret=True, **TINY)
    assert not bool(ov)
    fd = np.asarray(fd)
    fi = np.asarray(fi)
    for i in range(q):
        fin = np.isfinite(fd[i])
        assert fin.sum() == k  # 6 matches exist, k=4 all fillable
        # no duplicated neighbor indices among finite results
        assert len(set(fi[i][fin].tolist())) == int(fin.sum())
    exp = oracle(qx, qy, x, y, mask, k)
    np.testing.assert_allclose(
        np.sort(fd, 1), exp, rtol=1e-4, atol=1.0)


class TestKnnExactRefine:
    # round 5 (VERDICT r4 task 10): f64 re-ranking at the k-th boundary
    # with a miss-impossible certificate

    def test_engineered_f32_ties_rerank_exactly(self):
        from geomesa_tpu.engine.geodesy import haversine_m_np
        from geomesa_tpu.engine.knn_scan import (
            knn_exact_refine, knn_sparse_auto)

        rng = np.random.default_rng(41)
        n, k, pad = 1 << 12, 5, 8
        qx, qy = np.array([10.0]), np.array([45.0])
        # the k-th boundary is a TIE CLUSTER that fits inside the pad:
        # 3 clearly-closer points (~50 km, distinct) + 8 points along ONE
        # bearing at ~71 km spaced ~1e-10 deg (~10 um) — far below f32
        # resolution, so the f32 kernel genuinely cannot order them
        # (review finding: a random-angle shell spread the distances by
        # 190 m - 2 km and never created a tie). The true top-5 = the 3
        # close + the f64-smallest 2 of the tied 8; only the f64 re-rank
        # can pick those 2, and the certificate holds because the whole
        # cluster fits within k' = k + pad.
        rr = 0.9 + np.arange(8) * 1e-10
        x = np.concatenate([
            qx[0] + np.array([0.63, 0.64, 0.65]),
            qx[0] + rr,
            rng.uniform(30, 60, n - 11),  # far background
        ])
        y = np.concatenate([
            np.full(11, qy[0]),
            rng.uniform(-60, -30, n - 11),
        ])
        mask = np.ones(n, bool)
        # the engineered tie cluster really is f32-indistinguishable
        d32 = haversine_m_np(qx[0], qy[0], x[3:11], y[3:11]).astype(np.float32)
        assert len(np.unique(d32)) < 8
        fd, fi, cap = knn_sparse_auto(
            jnp.asarray(qx, jnp.float32), jnp.asarray(qy, jnp.float32),
            jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
            jnp.asarray(mask), k=k + pad, interpret=True)
        d64, idx, cert = knn_exact_refine(qx, qy, x, y, fd, fi, k)
        exp_all = haversine_m_np(qx[0], qy[0], x, y)
        exp = np.sort(exp_all)[:k]
        # EXACT equality: both sides are the same f64 formula over the
        # same original coordinates
        np.testing.assert_array_equal(d64[0], exp)
        assert bool(cert[0])
        # the refined set is the true index set (distances here are
        # distinct in f64 by construction)
        assert set(idx[0].tolist()) == set(np.argsort(exp_all)[:k].tolist())

    def test_antipodal_boundary_decertifies(self):
        # near the antipode the f32 haversine error reaches km scale
        # (asin amplification); the certificate must refuse there even
        # with a comfortable-looking f32 margin (review finding: a flat
        # 4 m + 1e-5*d model falsely certified this regime)
        from geomesa_tpu.engine.knn_scan import (
            knn_exact_refine, knn_f32_err_m)

        assert knn_f32_err_m(100e3) < 10.0           # mid-range: meters
        assert knn_f32_err_m(19.9e6) > 2_000.0       # antipodal: km scale
        qx, qy = np.array([0.0]), np.array([0.0])
        # candidates ~100 km short of the antipode, 500 m apart in f64
        x = 179.0 + np.arange(64) * 0.005
        y = np.full(64, 0.5)
        from geomesa_tpu.engine.geodesy import haversine_m_np

        d_all = haversine_m_np(qx[0], qy[0], x, y)
        o = np.argsort(d_all)[:8]
        fd = d_all[o].astype(np.float32)[None]
        fi = o[None]
        d64, idx, cert = knn_exact_refine(qx, qy, x, y, fd, fi, k=5)
        assert not bool(cert[0])  # 1.5 km margin < km-scale f32 error

    def test_uncertified_when_pad_is_all_ties(self):
        from geomesa_tpu.engine.knn_scan import knn_exact_refine

        # every candidate within sub-resolution of the k-th boundary and
        # beyond the pad: the certificate must refuse
        qx, qy = np.array([0.0]), np.array([0.0])
        x = np.full(64, 1.0)
        y = np.zeros(64)
        fd = np.full((1, 8), np.float32(111194.9), np.float32)
        fi = np.arange(8, dtype=np.int64)[None]
        d64, idx, cert = knn_exact_refine(qx, qy, x, y, fd, fi, k=5)
        assert not bool(cert[0])

    def test_certified_short_result(self):
        from geomesa_tpu.engine.knn_scan import knn_exact_refine

        # fewer matches than k': nothing was cut off -> certified
        qx, qy = np.array([0.0]), np.array([0.0])
        x = np.array([1.0, 2.0, 3.0])
        y = np.zeros(3)
        fd = np.array([[111000.0, 222000.0, 333000.0, np.inf, np.inf,
                        np.inf, np.inf, np.inf]], np.float32)
        fi = np.array([[0, 1, 2, 0, 0, 0, 0, 0]], np.int64)
        d64, idx, cert = knn_exact_refine(qx, qy, x, y, fd, fi, k=5)
        assert bool(cert[0])
        assert np.isinf(d64[0, 3:]).all()
