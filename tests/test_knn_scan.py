"""Fused-scan kNN kernel tests (Pallas interpret mode on CPU; the same
code path compiles via Mosaic on TPU — measured there at 570M pts/s
sparse / 259M dense on the 67M-point config-3 shape).

Parity oracle: NumPy f64 haversine + argpartition over the masked rows
(tests/reference_engine.py style), the same oracle the bench gates on.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from geomesa_tpu.engine.geodesy import haversine_m_np
from geomesa_tpu.engine.knn_scan import (
    chord_blockmin, knn_fullscan, knn_fullscan_tiled, knn_sparse_scan)

# tiny tiles: interpret mode executes the grid serially in Python — the
# TPU-targeted tile sizes would take minutes per call on CPU
TINY = dict(blk=256, data_tile=2048)


def oracle(qx, qy, x, y, mask, k):
    out = np.empty((len(qx), k))
    cx, cy = x[mask], y[mask]
    for i in range(len(qx)):
        d = haversine_m_np(qx[i], qy[i], cx, cy)
        if len(d) >= k:
            out[i] = np.sort(d[np.argpartition(d, k - 1)[:k]])
        else:
            out[i, : len(d)] = np.sort(d)
            out[i, len(d):] = np.inf
    return out


def make(n, q, seed=7, sorted_x=False, sel=0.4):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-180, 180, n)
    if sorted_x:
        x = np.sort(x)
    y = rng.uniform(-90, 90, n)
    mask = rng.random(n) < sel
    qx = rng.uniform(-30, 30, q)
    qy = rng.uniform(-60, 60, q)
    dev = [jnp.asarray(a, jnp.float32) for a in (qx, qy, x, y)]
    return qx, qy, x, y, mask, dev + [jnp.asarray(mask)]


class TestFullscan:
    def test_parity_random_mask(self):
        qx, qy, x, y, mask, dev = make(6000, 24)
        fd, fi = knn_fullscan(*dev, k=5, m_blocks=8, interpret=True, **TINY)
        exp = oracle(qx, qy, x, y, mask, 5)
        np.testing.assert_allclose(
            np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)
        # returned indices are real matches whose distances reproduce fd
        idx = np.asarray(fi)
        for i in range(5):
            dd = haversine_m_np(qx[i], qy[i], x[idx[i]], y[idx[i]])
            np.testing.assert_allclose(
                np.sort(dd), np.sort(np.asarray(fd)[i]), rtol=1e-4, atol=1.0)
            assert mask[idx[i]].all()

    def test_fewer_matches_than_k(self):
        qx, qy, x, y, _, dev = make(4096, 8)
        mask = np.zeros(4096, bool)
        mask[[5, 99, 3000]] = True
        dev[4] = jnp.asarray(mask)
        fd, fi = knn_fullscan(*dev, k=6, m_blocks=8, interpret=True, **TINY)
        fd = np.asarray(fd)
        assert np.isfinite(fd[:, :3]).all() and np.isinf(fd[:, 3:]).all()
        assert mask[np.asarray(fi)[:, :3]].all()

    def test_m_blocks_contract(self):
        _, _, _, _, _, dev = make(2048, 4)
        with pytest.raises(ValueError, match="m_blocks"):
            knn_fullscan(*dev, k=9, m_blocks=8, interpret=True, **TINY)

    def test_query_tiling(self):
        qx, qy, x, y, mask, dev = make(4096, 40)
        fd, _ = knn_fullscan_tiled(
            *dev, k=3, m_blocks=4, query_tile=16, interpret=True)
        exp = oracle(qx, qy, x, y, mask, 3)
        np.testing.assert_allclose(
            np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)

    def test_blockmin_matches_dense_key(self):
        rng = np.random.default_rng(3)
        n, q = 2048, 8
        x = rng.uniform(-180, 180, n).astype(np.float32)
        y = rng.uniform(-90, 90, n).astype(np.float32)
        mf = (rng.random(n) < 0.5).astype(np.float32)
        qx = rng.uniform(-30, 30, q).astype(np.float32)
        qy = rng.uniform(30, 60, q).astype(np.float32)
        minima, c = chord_blockmin(
            jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(x), jnp.asarray(y),
            jnp.asarray(mf), blk=256, data_tile=2048, interpret=True)

        def unit3(lon, lat):
            rl, rt = np.radians(lon), np.radians(lat)
            return np.stack([np.cos(rt) * np.cos(rl),
                             np.cos(rt) * np.sin(rl), np.sin(rt)], -1)

        qu = unit3(qx, qy).astype(np.float32)
        cc = qu.mean(0)
        dc = unit3(x, y).astype(np.float32) - cc
        nd = (dc * dc).sum(1) + (1 - mf) * 1e9
        key = nd[None, :] - 2 * ((qu - cc) @ dc.T)
        exp = key.reshape(q, -1, 256).min(-1)
        got = np.asarray(minima)
        # f32 association-order noise only
        assert np.abs(got - exp).max() / np.abs(exp).max() < 1e-2


class TestSparseScan:
    def test_parity_and_no_overflow_on_sorted(self):
        qx, qy, x, y, _, dev = make(16384, 12, sorted_x=True)
        mask = (x > -60) & (x < 60)
        dev[4] = jnp.asarray(mask)
        fd, fi, ov = knn_sparse_scan(
            *dev, k=5, tile_capacity=8, m_blocks=8, interpret=True, **TINY)
        assert not bool(ov)
        exp = oracle(qx, qy, x, y, mask, 5)
        np.testing.assert_allclose(
            np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)

    def test_overflow_flags_capacity_breach(self):
        _, _, x, y, _, dev = make(16384, 4)
        dev[4] = jnp.asarray(np.ones(16384, bool))
        _, _, ov = knn_sparse_scan(
            *dev, k=3, tile_capacity=4, m_blocks=8, interpret=True, **TINY)
        assert bool(ov)

    def test_empty_mask(self):
        qx, qy, x, y, _, dev = make(4096, 4)
        dev[4] = jnp.asarray(np.zeros(4096, bool))
        fd, _, ov = knn_sparse_scan(
            *dev, k=3, tile_capacity=4, m_blocks=8, interpret=True, **TINY)
        assert not bool(ov)
        assert np.isinf(np.asarray(fd)).all()

    def test_matches_only_in_last_tile(self):
        # selection order: tile ids must map back to ORIGINAL lanes
        qx, qy, x, y, _, dev = make(8192, 6)
        mask = np.zeros(8192, bool)
        mask[-50:] = True
        dev[4] = jnp.asarray(mask)
        fd, fi, ov = knn_sparse_scan(
            *dev, k=4, tile_capacity=2, m_blocks=8, interpret=True, **TINY)
        assert not bool(ov)
        exp = oracle(qx, qy, x, y, mask, 4)
        np.testing.assert_allclose(
            np.sort(np.asarray(fd), 1), exp, rtol=1e-4, atol=1.0)
        assert (np.asarray(fi) >= 8192 - 50).all()


def test_sparse_dead_slots_never_duplicate_tile0():
    # capacity-padding programs alias data tile 0; with sparse matches
    # (fewer real blocks than m_blocks) their PENALTY minima used to win
    # selection and duplicate tile-0 lanes in the refine pool
    rng = np.random.default_rng(13)
    n, q, k = 8192, 6, 4
    x = np.sort(rng.uniform(-180, 180, n))
    y = rng.uniform(-90, 90, n)
    mask = np.zeros(n, bool)
    mask[:6] = True  # all matches in tile 0, fewer than k*blk
    qx = rng.uniform(-30, 30, q)
    qy = rng.uniform(-60, 60, q)
    dev = [jnp.asarray(a, jnp.float32) for a in (qx, qy, x, y)]
    fd, fi, ov = knn_sparse_scan(
        *dev, jnp.asarray(mask), k=k, tile_capacity=8, m_blocks=8,
        interpret=True, **TINY)
    assert not bool(ov)
    fd = np.asarray(fd)
    fi = np.asarray(fi)
    for i in range(q):
        fin = np.isfinite(fd[i])
        assert fin.sum() == k  # 6 matches exist, k=4 all fillable
        # no duplicated neighbor indices among finite results
        assert len(set(fi[i][fin].tolist())) == int(fin.sum())
    exp = oracle(qx, qy, x, y, mask, k)
    np.testing.assert_allclose(
        np.sort(fd, 1), exp, rtol=1e-4, atol=1.0)
