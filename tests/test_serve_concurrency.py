"""Concurrent-access regression tests: the device cache's locking and
mixed query traffic (durable store + Kafka live layer) racing a writer.

These exist because the serving layer makes concurrency the NORMAL
operating mode: before it, one thread owned the store; now the dispatch
thread, admission threads and ingest writers all touch the
DeviceCacheManager and storage manifests. JitTracker counters double as
the recompile-storm alarm (a shape leak under concurrency shows up as
compile-cache growth long before it shows up as wrong results).
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.query import Query
from geomesa_tpu.store.cache import DeviceCacheManager

SPEC = "name:String,score:Double,dtg:Date,*geom:Point"


def make_batch(sft, n, seed):
    rng = np.random.default_rng(seed)
    return FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })


class TestDeviceCacheLocking:
    def test_concurrent_readers_and_invalidating_writer(self, tmp_path):
        """Regression for the unlocked DeviceCacheManager: ensure/
        superbatch readers racing an invalidate/refresh writer must never
        throw or observe a superbatch whose row total disagrees with the
        entries it claims to hold (a torn rebuild)."""
        sft = SimpleFeatureType.from_spec("locked", SPEC)
        ds = DataStore(str(tmp_path))
        src = ds.create_schema(sft)
        src.write(make_batch(sft, 400, seed=1))
        cache = DeviceCacheManager(src.storage)
        parts = src.storage.partitions()
        errors = []
        stop = threading.Event()

        def reader():
            # Consistency is asserted on the snapshot ALONE (not against
            # later cache.get() calls — the writer may invalidate between
            # the two, which is allowed). Without the RLock this loop dies
            # with KeyErrors inside superbatch()/ensure() or observes a
            # half-built concat whose pid column disagrees with its id map.
            last_version = -1
            try:
                while not stop.is_set():
                    cache.ensure(parts)
                    sb = cache.superbatch()
                    if sb is not None:
                        pids = np.asarray(sb.pids)
                        assert len(sb.batch) == len(pids)
                        assert set(np.unique(pids)) == set(sb.ids.values())
                        assert sb.version >= last_version, (
                            sb.version, last_version)
                        last_version = sb.version
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def writer():
            try:
                for i in range(30):
                    if i % 3 == 0:
                        cache.invalidate()
                    elif i % 3 == 1:
                        cache.invalidate(parts[i % len(parts)])
                    else:
                        cache.refresh()
                    time.sleep(0.002)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        readers = [threading.Thread(target=reader) for _ in range(4)]
        w = threading.Thread(target=writer)
        for t in readers:
            t.start()
        w.start()
        w.join()
        stop.set()
        for t in readers:
            t.join()
        assert not errors, errors
        # cache still serves a full, coherent superbatch afterwards
        cache.ensure(parts)
        sb = cache.superbatch()
        assert sb is not None and set(sb.ids) == set(parts)

    def test_lock_is_reentrant_for_compound_ops(self, tmp_path):
        """refresh() calls ensure() under the same lock; a non-reentrant
        lock would deadlock here."""
        sft = SimpleFeatureType.from_spec("reent", SPEC)
        ds = DataStore(str(tmp_path))
        src = ds.create_schema(sft)
        src.write(make_batch(sft, 64, seed=2))
        cache = DeviceCacheManager(src.storage)
        with cache._lock:
            assert cache.refresh()  # re-enters ensure() without deadlock


class TestConcurrentMixedQueries:
    def test_mixed_queries_with_writer_no_torn_reads(self, tmp_path):
        """N threads of mixed queries against one durable store and one
        Kafka live layer while a writer mutates both: no exceptions, no
        torn reads (counts only ever observed at batch boundaries), and
        no recompile storm (JitTracker over the engine jit caches)."""
        from geomesa_tpu.analysis.runtime import guard_engine
        from geomesa_tpu.kafka import KafkaDataStore
        from geomesa_tpu.serve import QueryService, ServeConfig

        sft = SimpleFeatureType.from_spec("mixed", SPEC)
        ds = DataStore(str(tmp_path), use_device_cache=True)
        src = ds.create_schema(sft)
        base_n = 600
        src.write(make_batch(sft, base_n, seed=5))

        kds = KafkaDataStore()
        ksft = SimpleFeatureType.from_spec("livemixed", SPEC)
        ksrc = kds.create_schema(ksft)
        ksrc.write(make_batch(ksft, 200, seed=6))

        tracker = guard_engine()
        svc = QueryService(ds, ServeConfig(max_wait_ms=1.0))
        errors = []
        observed_counts = []
        stop = threading.Event()
        # writer appends in 10-row steps: durable count must only ever
        # be seen at a 10-row boundary, anything else is a torn read
        write_step = 10

        def querier(i):
            rng = np.random.default_rng(100 + i)
            try:
                while not stop.is_set():
                    mode = rng.integers(0, 4)
                    if mode == 0:
                        c = svc.count(
                            "mixed", "BBOX(geom, -170, -80, 170, 80)"
                        ).result(timeout=120)
                        observed_counts.append(c)
                    elif mode == 1:
                        svc.knn("mixed", "INCLUDE",
                                rng.uniform(-50, 50, 1),
                                rng.uniform(-50, 50, 1),
                                k=4).result(timeout=120)
                    elif mode == 2:
                        r = svc.query(
                            "mixed", "score > 0").result(timeout=120)
                        assert r.kind == "features"
                    else:
                        # live layer reads bypass the service (its own
                        # snapshot discipline) — still must be safe
                        n = ksrc.get_count("INCLUDE")
                        assert n % write_step == 0, n
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        def writer():
            try:
                for i in range(5):
                    src.write(make_batch(sft, write_step, seed=50 + i))
                    ksrc.write(make_batch(ksft, write_step, seed=70 + i))
                    time.sleep(0.01)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=querier, args=(i,))
                   for i in range(6)]
        wt = threading.Thread(target=writer)
        for t in threads:
            t.start()
        wt.start()
        wt.join()
        time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join()
        svc.close(drain=True)
        tracker.unwrap()

        assert not errors, errors
        # durable counts move only at write boundaries and monotonically
        assert observed_counts, "no counts observed"
        for c in observed_counts:
            assert base_n <= c <= base_n + 5 * write_step
            assert (c - base_n) % write_step == 0, c
        for a, b in zip(observed_counts, observed_counts[1:]):
            assert b >= a, "count went backwards (torn cache state)"
        # no recompile storm: the writer keeps every padded batch inside
        # one pow2 bucket, so each engine kernel compiles a handful of
        # shapes, not one per query
        report = tracker.report()
        assert report, "engine jit caches were never exercised"
        for name, rec in report.items():
            assert rec["recompiles"] <= 4, (name, rec)
