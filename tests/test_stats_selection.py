"""Stats-driven kernel selection (round 5, VERDICT r4 task 6).

kNN auto: the planner resolves sparse-vs-fullscan from its write-path
stats sketches (selectivity-typed) — a ~99%-selectivity filter routes to
the dense fullscan with no calibration fetch or overflow round trip; a
selective bbox keeps the sparse tile scan.

Density auto: a calibration that finds the dictionary kernel mostly
overflowing (random layout) caches a "scatter" marker, so the NEXT
identical query skips the zsparse attempt entirely.
"""

import numpy as np
import pytest

import geomesa_tpu.engine.knn_scan as knn_scan_mod
from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.query import Query


def _store(tmp_path, n=20_000, seed=3):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec("s", "v:Double,*geom:Point")
    x = rng.uniform(-170, 170, n)
    y = rng.uniform(-80, 80, n)
    ds = DataStore(str(tmp_path / "s"))
    src = ds.create_schema(sft)
    src.write(FeatureBatch.from_pydict(
        sft, {"v": rng.uniform(0, 1, n), "geom": np.stack([x, y], 1)}))
    return src


class TestKnnAutoSelectivity:
    def _spy(self, monkeypatch):
        calls = []
        # the sparse choke point is knn_sparse_launch: the planner's
        # async launch/sync seam calls it directly, and knn_sparse_auto
        # (the process stack's entry) composes it — one spy sees both
        real_sparse = knn_scan_mod.knn_sparse_launch
        real_full = knn_scan_mod.knn_fullscan_tiled

        def sparse(*a, **kw):
            calls.append("sparse")
            return real_sparse(*a, **kw)

        def full(*a, **kw):
            calls.append("fullscan")
            return real_full(*a, **kw)

        monkeypatch.setattr(knn_scan_mod, "knn_sparse_launch", sparse)
        monkeypatch.setattr(knn_scan_mod, "knn_fullscan_tiled", full)
        return calls

    def test_high_selectivity_routes_fullscan(self, tmp_path, monkeypatch):
        src = _store(tmp_path)
        calls = self._spy(monkeypatch)
        qx, qy = np.array([0.0, 10.0]), np.array([0.0, 5.0])
        # near-whole-world window: the sketch estimate is ~the full count
        d, i, batch = src.planner.knn(
            Query("s", "BBOX(geom, -179, -89, 179, 89)"), qx, qy, k=3,
            impl="auto")
        assert calls == ["fullscan"], calls
        assert np.isfinite(d).all()

    def test_selective_bbox_routes_sparse(self, tmp_path, monkeypatch):
        src = _store(tmp_path)
        calls = self._spy(monkeypatch)
        qx, qy = np.array([1.0, 2.0]), np.array([1.0, 2.0])
        d, i, batch = src.planner.knn(
            Query("s", "BBOX(geom, -5, -5, 5, 5)"), qx, qy, k=3,
            impl="auto")
        assert calls == ["sparse"], calls

    def test_no_stats_defaults_sparse(self, tmp_path, monkeypatch):
        src = _store(tmp_path)
        src.planner.stats_manager().invalidate()
        calls = self._spy(monkeypatch)
        d, i, batch = src.planner.knn(
            Query("s", "BBOX(geom, -179, -89, 179, 89)"),
            np.array([0.0]), np.array([0.0]), k=3, impl="auto")
        assert calls == ["sparse"], calls

    def test_process_auto_flows_to_planner(self, tmp_path, monkeypatch):
        from geomesa_tpu.process.knn import KNearestNeighborSearchProcess

        src = _store(tmp_path, n=1 << 11)
        # force the planner-scan branch regardless of store size
        monkeypatch.setattr(
            type(src.planner), "_knn_impl_from_stats",
            lambda self, plan: "fullscan")
        calls = self._spy(monkeypatch)
        qsft = SimpleFeatureType.from_spec("q", "*geom:Point")
        q = FeatureBatch.from_pydict(
            qsft, {"geom": np.array([[0.0, 0.0]])})
        proc = KNearestNeighborSearchProcess()
        res = proc.execute(
            q, src, num_desired=2, impl="sparse",
            estimated_distance_m=5e6, max_search_distance_m=2e7)
        assert "sparse" in calls  # explicit impl honored
        calls.clear()
        # auto: the monkeypatched stats decision must reach the kernel pick
        monkeypatch.setattr(
            type(src.planner.storage), "count",
            property(lambda self: 1 << 21))
        res = proc.execute(
            q, src, num_desired=2, impl="auto",
            estimated_distance_m=5e6, max_search_distance_m=2e7)
        assert "fullscan" in calls, calls


class TestDensityScatterPrediction:
    def test_overflow_calibration_caches_scatter_marker(self, monkeypatch):
        import jax.numpy as jnp

        import geomesa_tpu.engine.density_zsparse as dz_mod
        import geomesa_tpu.plan.runner as runner_mod
        from geomesa_tpu.engine.device import to_device
        from geomesa_tpu.plan.hints import QueryHints
        from geomesa_tpu.plan.runner import density_device_grid

        runner_mod._ZCALIB_CACHE.clear()
        rng = np.random.default_rng(7)
        n = 1 << 14
        sft = SimpleFeatureType.from_spec("d", "*geom:Point")
        # RANDOM order over a fine grid: nearly every tile exceeds capd
        x = rng.uniform(-170, 170, n)
        y = rng.uniform(-80, 80, n)
        batch = FeatureBatch.from_pydict(sft, {"geom": np.stack([x, y], 1)})
        dev = to_device(batch)
        hints = QueryHints(
            density_bbox=(-180.0, -90.0, 180.0, 90.0),
            density_width=256, density_height=256)
        calls = []
        real = dz_mod.density_zsparse

        def spy(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(dz_mod, "density_zsparse", spy)
        mask = jnp.ones(n, bool)
        g1 = np.asarray(density_device_grid(
            sft, batch, dev, mask, hints, mask_token=("t",)))
        assert calls, "first query must attempt the zsparse calibration"
        assert any(
            isinstance(v[1], str) for v in runner_mod._ZCALIB_CACHE.values()
        ), "overflow-dominated calibration must cache the scatter marker"
        calls.clear()
        g2 = np.asarray(density_device_grid(
            sft, batch, dev, mask, hints, mask_token=("t",)))
        assert not calls, "second identical query must go straight to scatter"
        np.testing.assert_allclose(g1, g2, rtol=1e-6, atol=1e-3)
