"""Runtime lockset harness tests: the Eraser-style detector catches an
injected two-thread race and a lock-order inversion, stays quiet on the
clean twins, runs the serve concurrency workload clean, and `gmtpu
guard --races` exits nonzero on violations."""

import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np

from geomesa_tpu.analysis.locksets import (
    note_access, trace_locks, tracked_lock)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_two(fn_a, fn_b):
    ts = [threading.Thread(target=fn_a), threading.Thread(target=fn_b)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()


class TestEraserLocksets:
    def test_injected_race_two_threads_two_locks(self):
        with trace_locks() as watch:
            shared = {"n": 0}
            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def writer(lock):
                for _ in range(50):
                    with lock:
                        note_access("shared.n", write=True)
                        shared["n"] += 1

            run_two(lambda: writer(lock_a), lambda: writer(lock_b))
            rep = watch.report()
        assert len(rep["races"]) == 1
        assert rep["races"][0]["key"] == "'shared.n'"
        assert len(rep["races"][0]["threads"]) == 2
        assert rep["violations"] >= 1

    def test_clean_twin_shared_lock(self):
        with trace_locks() as watch:
            shared = {"n": 0}
            lock = threading.Lock()

            def writer():
                for _ in range(50):
                    with lock:
                        note_access("shared.n", write=True)
                        shared["n"] += 1

            run_two(writer, writer)
            rep = watch.report()
        assert rep["races"] == []
        assert shared["n"] == 100

    def test_read_only_sharing_is_not_a_race(self):
        with trace_locks() as watch:
            def reader():
                for _ in range(10):
                    note_access("config", write=False)

            run_two(reader, reader)
            rep = watch.report()
        assert rep["races"] == []

    def test_single_thread_unlocked_is_not_a_race(self):
        with trace_locks() as watch:
            for _ in range(10):
                note_access("local.state", write=True)
            rep = watch.report()
        assert rep["races"] == []


class TestOrderInversions:
    def test_inversion_detected(self):
        with trace_locks() as watch:
            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def ab():
                with lock_a:
                    with lock_b:
                        pass

            def ba():
                with lock_b:
                    with lock_a:
                        pass

            # sequential on purpose: the detector works from the order
            # graph, no deadlock needs to actually happen
            ab()
            ba()
            rep = watch.report()
        assert len(rep["inversions"]) == 1
        assert rep["violations"] == 1

    def test_consistent_order_clean(self):
        with trace_locks() as watch:
            lock_a = threading.Lock()
            lock_b = threading.Lock()
            for _ in range(3):
                with lock_a:
                    with lock_b:
                        pass
            rep = watch.report()
        assert rep["inversions"] == []
        assert rep["order_edges"] == 1

    def test_reentrant_rlock_is_not_an_edge(self):
        with trace_locks() as watch:
            lk = threading.RLock()
            with lk:
                with lk:
                    pass
            rep = watch.report()
        assert rep["order_edges"] == 0

    def test_condition_on_lock_balances_through_wait(self):
        with trace_locks() as watch:
            lk = threading.Lock()
            cond = threading.Condition(lk)
            hits = []

            def waiter():
                with cond:
                    cond.wait(timeout=2.0)
                    hits.append(1)

            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cond:
                cond.notify()
            t.join()
            rep = watch.report()
        assert hits == [1]
        assert rep["inversions"] == []

    def test_tracked_lock_explicit_api(self):
        lk = tracked_lock("fixture.lock")
        with lk:
            assert lk.name == "fixture.lock"


class TestServeWorkloadClean:
    def test_serve_concurrency_workload_has_no_inversions(self, tmp_path):
        """The tests/test_serve_concurrency.py shape (mixed queries +
        writer over one store through QueryService) with every serving
        lock tracked: no lock-order inversions among geomesa_tpu locks
        and no Eraser violations."""
        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType

        rng = np.random.default_rng(3)
        n = 256
        sft = SimpleFeatureType.from_spec(
            "soak", "name:String,score:Double,dtg:Date,*geom:Point")

        def batch(n, seed):
            r = np.random.default_rng(seed)
            return FeatureBatch.from_pydict(sft, {
                "name": r.choice(["a", "b", "c"], n).tolist(),
                "score": r.uniform(-10, 10, n),
                "dtg": r.integers(1_590_000_000_000, 1_600_000_000_000, n),
                "geom": np.stack([r.uniform(-170, 170, n),
                                  r.uniform(-80, 80, n)], 1),
            })

        with trace_locks() as watch:
            # construct INSIDE the trace so every serving lock (store
            # manifest, stats manager, device cache, audit, scheduler,
            # service state) is tracked
            from geomesa_tpu.plan.datastore import DataStore
            from geomesa_tpu.serve import QueryService, ServeConfig

            ds = DataStore(str(tmp_path), use_device_cache=True)
            src = ds.create_schema(sft)
            src.write(batch(n, seed=4))
            svc = QueryService(ds, ServeConfig(max_wait_ms=1.0))
            errors = []
            stop = threading.Event()

            def querier(i):
                r = np.random.default_rng(10 + i)
                try:
                    while not stop.is_set():
                        if i % 2 == 0:
                            svc.count(
                                "soak", "BBOX(geom, -170, -80, 170, 80)"
                            ).result(timeout=60)
                        else:
                            svc.knn("soak", "INCLUDE",
                                    r.uniform(-50, 50, 1),
                                    r.uniform(-50, 50, 1),
                                    k=4).result(timeout=60)
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)

            def writer():
                try:
                    for i in range(3):
                        src.write(batch(10, seed=40 + i))
                        time.sleep(0.01)
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)

            qs = [threading.Thread(target=querier, args=(i,))
                  for i in range(3)]
            wt = threading.Thread(target=writer)
            for t in qs:
                t.start()
            wt.start()
            wt.join()
            time.sleep(0.05)
            stop.set()
            for t in qs:
                t.join()
            svc.close(drain=True)
            rep = watch.report(path_filter="geomesa_tpu")

        assert not errors, errors
        assert rep["locks_created"] > 0
        assert rep["inversions"] == [], rep["inversions"]
        assert rep["races"] == []


class TestGuardRacesCLI:
    def _run_guard(self, tmp_path, source, name):
        script = tmp_path / name
        script.write_text(textwrap.dedent(source))
        return subprocess.run(
            [sys.executable, "-m", "geomesa_tpu.cli", "guard",
             "--races", str(script)],
            capture_output=True, text=True, cwd=REPO_ROOT)

    def test_racy_script_exits_nonzero(self, tmp_path):
        r = self._run_guard(tmp_path, """\
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def ab():
                with lock_a:
                    with lock_b:
                        pass

            def ba():
                with lock_b:
                    with lock_a:
                        pass

            ab()
            ba()
        """, "racy.py")
        assert r.returncode == 1, r.stderr
        assert "INVERSION" in r.stderr

    def test_clean_script_exits_zero(self, tmp_path):
        r = self._run_guard(tmp_path, """\
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def ab():
                with lock_a:
                    with lock_b:
                        pass

            ab()
            ab()
        """, "clean.py")
        assert r.returncode == 0, r.stderr
        assert "locksets:" in r.stderr
        assert "0 inversion(s)" in r.stderr

    def test_empty_lockset_access_reported(self, tmp_path):
        r = self._run_guard(tmp_path, """\
            import threading

            from geomesa_tpu.analysis.locksets import note_access

            shared = {"n": 0}
            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def writer(lock):
                for _ in range(20):
                    with lock:
                        note_access("shared.n", write=True)
                        shared["n"] += 1

            ts = [threading.Thread(target=writer, args=(lk,))
                  for lk in (lock_a, lock_b)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        """, "eraser.py")
        assert r.returncode == 1, r.stderr
        assert "RACE" in r.stderr
