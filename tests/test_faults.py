"""Fault-injection harness + recovery fabric (docs/ROBUSTNESS.md).

Covers the PR-5 acceptance surface: the breaker state machine (fake
clock, no sleeps), backoff-with-jitter bounds and deadline awareness
(seeded, fake clock), deterministic plan replay, the device-OOM ->
host-eval fallback returning device-identical results on a small
workload, poison-query quarantine, ServeEvent recovery attribution,
the GT14 lint rule fixtures, the bounded kNN widen loop, and a seeded
chaos regression (the `gmtpu chaos --check` invariants in-process).
"""

import os
import textwrap
from random import Random

import numpy as np
import pytest

from geomesa_tpu import faults
from geomesa_tpu.faults.breaker import BreakerOpen, CircuitBreaker
from geomesa_tpu.faults.errors import (
    DeviceOOM, InjectedCrash, InjectedIOError, PermanentError, classify)
from geomesa_tpu.faults.plan import FaultPlan, FaultRule
from geomesa_tpu.faults.quarantine import QuarantineRegistry
from geomesa_tpu.faults.retry import RetryPolicy, retry_call

CQL = "BBOX(geom, -170, -80, 170, 80)"


def make_store(tmp_path, n=400, seed=9, device_cache=False):
    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore

    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec(
        "faulty", "name:String,score:Double,dtg:Date,*geom:Point")
    store = DataStore(str(tmp_path), use_device_cache=device_cache)
    store.create_schema(sft).write(FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_590_080_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    }))
    return store


@pytest.fixture(autouse=True)
def _pristine_fabric():
    """Every test starts and ends with no harness installed and closed
    breakers (the fabric is process-global by design)."""
    faults.uninstall()
    faults.BREAKERS.reset()
    yield
    faults.uninstall()
    faults.BREAKERS.reset()


# -- error taxonomy ---------------------------------------------------------


class TestTaxonomy:
    def test_classification(self):
        from geomesa_tpu.plan.planner import QueryTimeout

        assert classify(InjectedIOError("x")) == "transient"
        assert classify(ConnectionResetError("x")) == "transient"
        assert classify(DeviceOOM("x")) == "oom"
        assert classify(InjectedCrash("x")) == "permanent"
        assert classify(PermanentError("x")) == "permanent"
        assert classify(ValueError("x")) == "permanent"
        # definitive filesystem answers must not retry / trip breakers
        # (review finding: a compaction-raced FileNotFoundError burned
        # the whole backoff budget and counted 4 storage-breaker
        # failures on a healthy disk)
        assert classify(FileNotFoundError("gone")) == "permanent"
        assert classify(PermissionError("denied")) == "permanent"
        assert classify(IsADirectoryError("dir")) == "permanent"
        # a blown deadline must NEVER be retried
        assert classify(QueryTimeout("scan", 10.0, 5.0)) == "permanent"

    def test_typed_recognition(self):
        from geomesa_tpu.serve.scheduler import QueryRejected

        assert faults.is_typed(InjectedIOError("x"))
        assert faults.is_typed(QueryRejected("shed"))
        assert faults.is_typed(BreakerOpen("storage", 1.0))
        assert not faults.is_typed(RuntimeError("surprise"))


# -- circuit breaker (fake clock, no sleeps) --------------------------------


class TestBreaker:
    def test_state_machine(self):
        t = [0.0]
        b = CircuitBreaker("dep", failure_threshold=2,
                           reset_timeout_s=10.0, clock=lambda: t[0])
        assert b.state == "closed"
        b.allow(); b.record_failure()
        assert b.state == "closed"  # one failure below threshold
        b.allow(); b.record_failure()
        assert b.state == "open"
        with pytest.raises(BreakerOpen) as ei:
            b.allow()
        assert ei.value.reason == "breaker_open"
        assert 0 < ei.value.retry_after_s <= 10.0
        t[0] = 10.5  # reset timeout elapses -> half-open probe
        b.allow()
        assert b.state == "half_open"
        with pytest.raises(BreakerOpen):
            b.allow()  # probe budget (1) spent
        b.record_success()
        assert b.state == "closed"

    def test_half_open_failure_reopens(self):
        t = [0.0]
        b = CircuitBreaker("dep", failure_threshold=1,
                           reset_timeout_s=5.0, clock=lambda: t[0])
        b.record_failure()
        assert b.state == "open"
        t[0] = 6.0
        b.allow()
        assert b.state == "half_open"
        b.record_failure()
        assert b.state == "open"  # failed probe restarts the clock
        with pytest.raises(BreakerOpen):
            b.allow()

    def test_vanished_probe_does_not_wedge_half_open(self):
        """Review finding: a half-open probe whose failure is
        NON-transient reports neither success nor failure to the
        breaker (retry.py only records dependency-health signals). The
        stale probe slot must free after reset_timeout_s — pre-fix the
        breaker stayed half-open raising BreakerOpen forever."""
        t = [0.0]
        b = CircuitBreaker("dep", failure_threshold=1,
                           reset_timeout_s=5.0, clock=lambda: t[0])
        b.record_failure()
        t[0] = 6.0
        b.allow()  # probe granted... and it vanishes (OOM path)
        with pytest.raises(BreakerOpen):
            b.allow()  # budget spent, probe still fresh
        t[0] = 12.0  # the vanished probe's slot goes stale
        b.allow()  # new probe round instead of a permanent wedge
        b.record_success()
        assert b.state == "closed"

    def test_registry_config_scoped_override_restores(self):
        """Review finding: the chaos runner must hand back the tuning
        the process had, not reset to constructor defaults."""
        from geomesa_tpu.faults.breaker import BreakerRegistry

        reg = BreakerRegistry()
        reg.configure("storage", failure_threshold=10,
                      reset_timeout_s=5.0)
        prior = reg.current_config("storage")
        assert prior == {"failure_threshold": 10, "reset_timeout_s": 5.0}
        reg.configure("storage", failure_threshold=3,
                      reset_timeout_s=0.0)  # chaos-style override
        reg.restore_config("storage", prior)
        b = reg.get("storage")
        assert b.failure_threshold == 10
        assert b.reset_timeout_s == 5.0
        # never-configured dependency restores to defaults (None)
        assert reg.current_config("kafka") is None
        reg.configure("kafka", failure_threshold=1)
        reg.restore_config("kafka", None)
        assert reg.get("kafka").failure_threshold == 5

    def test_transitions_metered(self):
        from geomesa_tpu.utils.metrics import metrics

        t = [0.0]
        b = CircuitBreaker("metered_dep", failure_threshold=1,
                           reset_timeout_s=1.0, clock=lambda: t[0])
        b.record_failure()
        t[0] = 2.0
        b.allow()
        b.record_success()
        with metrics._lock:
            counters = dict(metrics.counters)
        assert counters.get("fault.breaker.metered_dep.open", 0) >= 1
        assert counters.get("fault.breaker.metered_dep.half_open", 0) >= 1
        assert counters.get("fault.breaker.metered_dep.close", 0) >= 1


# -- retry with backoff + jitter (seeded, no real sleeps) -------------------


class TestRetry:
    def test_backoff_bounds(self):
        policy = RetryPolicy(max_attempts=10, base_ms=10.0, cap_ms=500.0)
        rng = Random(42)
        for attempt in range(12):
            for _ in range(50):
                d = policy.backoff_ms(attempt, rng)
                assert 0.0 <= d <= min(500.0, 10.0 * 2 ** attempt)

    def test_transient_retries_then_succeeds(self):
        calls, sleeps = [], []
        policy = RetryPolicy(max_attempts=4, base_ms=10.0, cap_ms=100.0)

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise InjectedIOError("flap")
            return "ok"

        out = retry_call(flaky, policy=policy, label="t",
                         sleep=sleeps.append, rng=Random(1))
        assert out == "ok"
        assert len(calls) == 3
        assert len(sleeps) == 2
        for i, s in enumerate(sleeps):
            assert 0.0 <= s <= min(0.1, 0.01 * 2 ** i)

    def test_permanent_never_retries(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("permanent")

        with pytest.raises(ValueError):
            retry_call(bad, policy=RetryPolicy(max_attempts=5),
                       label="t", sleep=lambda s: None)
        assert len(calls) == 1

    def test_oom_never_retries_nor_trips_breaker(self):
        calls = []
        b = CircuitBreaker("oomdep", failure_threshold=1,
                           reset_timeout_s=60.0)

        def oom():
            calls.append(1)
            raise DeviceOOM("hbm")

        with pytest.raises(DeviceOOM):
            retry_call(oom, policy=RetryPolicy(max_attempts=5),
                       label="t", breaker=b, sleep=lambda s: None)
        assert len(calls) == 1
        # OOM is a program-size signal with its own ladder (halve ->
        # host-eval); it must not open the dependency breaker and
        # fail-fast the requests the ladder exists to save
        assert b.state == "closed"

    def test_exhaustion_raises_last_error(self):
        def always():
            raise InjectedIOError("down")

        with pytest.raises(InjectedIOError):
            retry_call(always, policy=RetryPolicy(max_attempts=3,
                                                  base_ms=0.1),
                       label="t", sleep=lambda s: None)

    def test_deadline_stops_retries(self):
        """The fabric never sleeps past the request deadline: with the
        next backoff crossing the budget, the last error surfaces NOW."""
        calls, sleeps = [], []

        class MaxRng:
            @staticmethod
            def uniform(a, b):
                return b

        def flaky():
            calls.append(1)
            raise InjectedIOError("flap")

        clock = lambda: 100.0  # frozen fake clock
        with faults.deadline_scope(100.005):  # 5ms of budget left
            with pytest.raises(InjectedIOError):
                retry_call(flaky,
                           policy=RetryPolicy(max_attempts=10,
                                              base_ms=10.0),
                           label="t", clock=clock, sleep=sleeps.append,
                           rng=MaxRng())
        assert len(calls) == 1  # 10ms backoff > 5ms budget: no retry
        assert sleeps == []

    def test_nested_deadline_keeps_tighter(self):
        with faults.deadline_scope(50.0):
            with faults.deadline_scope(80.0):
                assert faults.current_deadline() == 50.0
            with faults.deadline_scope(30.0):
                assert faults.current_deadline() == 30.0
        assert faults.current_deadline() is None

    def test_breaker_fail_fast(self):
        b = CircuitBreaker("fastdep", failure_threshold=2,
                           reset_timeout_s=60.0)
        calls = []

        def always():
            calls.append(1)
            raise InjectedIOError("down")

        with pytest.raises(InjectedIOError):
            retry_call(always, policy=RetryPolicy(max_attempts=2,
                                                  base_ms=0.1),
                       label="t", breaker=b, sleep=lambda s: None)
        assert b.state == "open"
        with pytest.raises(BreakerOpen):
            retry_call(always, policy=RetryPolicy(max_attempts=2),
                       label="t", breaker=b, sleep=lambda s: None)
        assert len(calls) == 2  # open breaker: fn never called again


# -- plan + harness determinism --------------------------------------------


class TestHarness:
    def test_plan_json_round_trip(self, tmp_path):
        plan = FaultPlan(
            rules=[FaultRule(site="fs.*", error="io", every=3,
                             max_fires=2, latency_ms=1.0),
                   FaultRule(site="kafka.poll", error="unavailable",
                             nth_call=2)],
            seed=11, expect_breakers=["storage"])
        p = str(tmp_path / "plan.json")
        plan.save(p)
        loaded = FaultPlan.load(p)
        assert loaded == plan

    def test_plan_validation(self):
        with pytest.raises(ValueError):
            FaultRule(site="x", error="nope", every=1)
        with pytest.raises(ValueError):
            FaultRule(site="x", error="io")  # no schedule
        with pytest.raises(ValueError):
            FaultRule(site="x", error="io", probability=1.5)
        with pytest.raises(ValueError):
            FaultRule(site="x", error="io", nth_call=0)

    def test_no_harness_is_noop(self):
        s = faults.site("test.noop.site")
        for _ in range(100):
            s.fire()  # must not raise, must not record anything
        assert faults.current() is None

    def test_schedules_fire_exactly(self):
        plan = FaultPlan(rules=[
            FaultRule(site="test.sched", error="io", every=3,
                      max_fires=2)])
        s = faults.site("test.sched")
        fired = []
        with faults.active(plan) as h:
            for i in range(1, 13):
                try:
                    s.fire()
                except InjectedIOError:
                    fired.append(i)
        assert fired == [3, 6]  # every 3rd call, capped at 2 fires
        assert h.fire_log() == [("test.sched", 3, "io"),
                                ("test.sched", 6, "io")]

    def test_probability_replays_exactly(self):
        plan = FaultPlan(rules=[
            FaultRule(site="test.prob", error="io", probability=0.3)],
            seed=123)
        s = faults.site("test.prob")

        def run():
            fired = []
            with faults.active(plan):
                for i in range(200):
                    try:
                        s.fire()
                    except InjectedIOError:
                        fired.append(i)
            return fired

        a, b = run(), run()
        assert a == b  # seeded per-site stream: exact replay
        assert 20 < len(a) < 100  # ~0.3 of 200, loose bounds

    def test_glob_sites_and_nested_install_rejected(self):
        plan = FaultPlan(rules=[
            FaultRule(site="fsx.*", error="io", nth_call=1)])
        a, b = faults.site("fsx.read"), faults.site("fsx.write")
        with faults.active(plan):
            with pytest.raises(RuntimeError):
                faults.install(plan)  # nested harness must be refused
            with pytest.raises(InjectedIOError):
                a.fire()
            with pytest.raises(InjectedIOError):
                b.fire()  # independent per-site counters: its call #1


# -- poison-query quarantine ------------------------------------------------


class TestQuarantine:
    def test_one_crash_of_coalesced_batch_is_one_strike(self, tmp_path):
        """Review finding: N coalesced riders share the fingerprint by
        construction — one crashing dispatch must count as ONE strike,
        not N (pre-fix a single crash of a 3-rider batch quarantined
        the query immediately)."""
        from geomesa_tpu.serve.service import QueryService, ServeConfig

        store = make_store(tmp_path)
        plan = FaultPlan(rules=[
            FaultRule(site="device.transfer", error="crash", every=1)])
        svc = QueryService(store, ServeConfig(
            max_wait_ms=50.0, quarantine_after=3), autostart=False)
        futs = [svc.knn("faulty", CQL, np.array([1.0]),
                        np.array([2.0]), k=3) for _ in range(3)]
        try:
            with faults.active(plan):
                svc.start()
                for f in futs:
                    with pytest.raises(InjectedCrash):
                        f.result(timeout=60)
                # one crashing dispatch = one strike: still admitted
                fut = svc.knn("faulty", CQL, np.array([3.0]),
                              np.array([4.0]), k=3)
                with pytest.raises(InjectedCrash):
                    fut.result(timeout=60)
        finally:
            svc.close(drain=True)
        assert svc.stats().get("quarantined", 0) == 0
        assert svc.quarantine.stats()["quarantined"] == 0

    def test_strikes_then_blocks_then_expires(self):
        t = [0.0]
        q = QuarantineRegistry(strikes=3, ttl_s=100.0,
                               clock=lambda: t[0])
        key = ("knn", "t", "cql")
        assert q.blocked(key) is None
        assert not q.strike(key)
        assert not q.strike(key)
        assert q.strike(key)  # third strike trips
        assert q.blocked(key) is not None
        assert q.blocked(("other",)) is None
        t[0] = 101.0  # TTL elapses: the deploy may have fixed it
        assert q.blocked(key) is None

    def test_full_blocked_table_keeps_striking_state(self):
        """Review finding: with the blocked table full, a threshold
        crossing must neither report tripped nor wipe the key's strike
        history — the key quarantines as soon as capacity frees."""
        t = [0.0]
        q = QuarantineRegistry(strikes=2, ttl_s=10.0, max_entries=1,
                               clock=lambda: t[0])
        q.strike("a"); assert q.strike("a")  # fills the one slot
        t[0] = 5.0
        assert not q.strike("b")
        assert not q.strike("b")  # threshold crossed but table full
        assert q.blocked("b") is None
        t[0] = 10.5  # "a" expires; "b"'s strikes (t=5) still live
        assert q.strike("b")  # history survived: next strike trips
        assert q.blocked("b") is not None

    def test_stale_strikes_expire(self):
        t = [0.0]
        q = QuarantineRegistry(strikes=2, ttl_s=10.0, clock=lambda: t[0])
        q.strike("k")
        t[0] = 11.0
        assert not q.strike("k")  # first strike aged out; count restarts

    def test_infrastructure_oserrors_never_strike(self, tmp_path):
        """Review finding: a compaction-raced FileNotFoundError is
        classified permanent (no futile retries) but it is an
        INFRASTRUCTURE answer — three raced reads must not quarantine a
        healthy hot query."""
        from geomesa_tpu.serve.service import QueryService, ServeConfig

        store = make_store(tmp_path)
        storage = store.get_feature_source("faulty").storage
        # pull a data file out from under the manifest (the race)
        name, entries = next(iter(storage.manifest_snapshot().items()))
        os.remove(os.path.join(storage.root, name, entries[0]["file"]))
        svc = QueryService(store, ServeConfig(
            max_wait_ms=0.0, quarantine_after=3))
        try:
            for _ in range(4):
                fut = svc.query("faulty", CQL)
                # every attempt fails with the typed FS error — never
                # with QueryRejected("quarantined")
                with pytest.raises(FileNotFoundError):
                    fut.result(timeout=60)
            assert svc.quarantine.stats() == {"quarantined": 0,
                                              "striking": 0}
        finally:
            svc.close(drain=True)

    def test_service_rejects_quarantined_fingerprint(self, tmp_path):
        from geomesa_tpu.serve.scheduler import QueryRejected
        from geomesa_tpu.serve.service import QueryService, ServeConfig

        store = make_store(tmp_path)
        plan = FaultPlan(rules=[
            FaultRule(site="device.transfer", error="crash", every=1)])
        svc = QueryService(store, ServeConfig(
            max_wait_ms=0.0, quarantine_after=3))
        try:
            with faults.active(plan):
                for _ in range(3):
                    fut = svc.knn("faulty", CQL, np.array([1.0]),
                                  np.array([2.0]), k=3)
                    with pytest.raises(InjectedCrash):
                        fut.result(timeout=60)
                # fingerprint has three strikes: rejected at ADMISSION
                with pytest.raises(QueryRejected) as ei:
                    svc.knn("faulty", CQL, np.array([5.0]),
                            np.array([5.0]), k=3)
                assert ei.value.reason == "quarantined"
                # different fingerprint (k differs) still admitted
                fut = svc.knn("faulty", CQL, np.array([1.0]),
                              np.array([2.0]), k=4)
                with pytest.raises(InjectedCrash):
                    fut.result(timeout=60)
            assert svc.stats()["quarantined"] >= 1
        finally:
            svc.close(drain=True)


    def test_degraded_request_strikes_admission_fingerprint(
            self, tmp_path):
        """Review finding: the ladder rewrites hints, and the
        fingerprint includes the hint string — strikes must land on the
        PRE-degrade key admission checks, or quarantine silently never
        trips for degraded poison queries."""
        from geomesa_tpu.plan.query import Query
        from geomesa_tpu.serve.service import (
            QueryService, ServeConfig, _quarantine_key)

        store = make_store(tmp_path)
        svc = QueryService(store, ServeConfig(
            max_wait_ms=0.0, degrade=True, quarantine_after=3),
            autostart=False)
        try:
            req = svc._request("count", Query("faulty", CQL),
                               allow_degraded=True)
            pre = _quarantine_key(req)
            svc._degrade(req, 2)
            # a sketch-eligible count takes the SPECULATIVE sketch rung
            # (docs/SERVING.md "Approximate answers"): hints rewritten
            # now, `degraded` marked only if a sketch answer is served —
            # the fingerprint stash happens either way, which is what
            # this test protects
            assert req.sketch_rung == 2 and not req.degraded
            assert req.quarantine_key == pre
            # the post-degrade computed key differs (hints rewritten)…
            assert _quarantine_key(req) != pre
            # …so a strike on the stashed key is what admission sees
            for _ in range(3):
                svc.quarantine.strike(req.quarantine_key)
            fresh = svc._request("count", Query("faulty", CQL))
            assert svc.quarantine.blocked(_quarantine_key(fresh))
        finally:
            svc.close(drain=False)


# -- OOM -> halve -> host-eval fallback ------------------------------------


class TestOOMFallback:
    def test_host_results_match_device(self, tmp_path):
        """Acceptance: with every device transfer OOMing, counts and
        kNN answers equal the healthy device path's on the same store."""
        from geomesa_tpu.serve.service import QueryService, ServeConfig

        store = make_store(tmp_path)
        qx, qy = np.array([10.0, -40.0]), np.array([20.0, 5.0])

        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            base_count = svc.count("faulty", CQL).result(timeout=60)
            bd, bi, _ = svc.knn("faulty", CQL, qx, qy,
                                k=5).result(timeout=60)
        finally:
            svc.close(drain=True)
        assert base_count > 0

        plan = FaultPlan(rules=[
            FaultRule(site="device.transfer", error="oom", every=1)])
        svc2 = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            with faults.active(plan):
                oom_count = svc2.count("faulty", CQL).result(timeout=60)
                hd, hi, _ = svc2.knn("faulty", CQL, qx, qy,
                                     k=5).result(timeout=60)
        finally:
            svc2.close(drain=True)
        assert oom_count == base_count
        assert np.array_equal(hi, bi)  # identical neighbor sets/order
        assert np.allclose(hd, bd, rtol=1e-3)  # f32 device noise only
        from geomesa_tpu.utils.metrics import metrics

        with metrics._lock:
            assert metrics.counters.get("fault.oom.hosteval", 0) >= 2

    def test_halving_splits_coalesced_batch(self, tmp_path):
        """A coalesced kNN group that OOMs once re-runs as two halves:
        every rider still gets its exact answer."""
        from geomesa_tpu.serve.service import QueryService, ServeConfig

        store = make_store(tmp_path)
        rng = np.random.default_rng(3)
        pts = rng.uniform(-60, 60, (6, 2))

        svc = QueryService(store, ServeConfig(max_wait_ms=50.0),
                           autostart=False)
        serial = []
        src = store.get_feature_source("faulty")
        for i in range(6):
            serial.append(src.planner.knn(
                CQL, pts[i:i + 1, 0], pts[i:i + 1, 1], k=4))
        # first transfer of the coalesced dispatch OOMs -> halves retry
        plan = FaultPlan(rules=[
            FaultRule(site="device.transfer", error="oom", nth_call=1)])
        futs = [svc.knn("faulty", CQL, pts[i:i + 1, 0], pts[i:i + 1, 1],
                        k=4) for i in range(6)]
        with faults.active(plan):
            svc.start()
            results = [f.result(timeout=120) for f in futs]
            svc.close(drain=True)
        for (d, ix, _), (sd, six, _) in zip(results, serial):
            assert np.array_equal(ix, six)
            assert np.allclose(d, sd, rtol=1e-3)
        from geomesa_tpu.utils.metrics import metrics

        with metrics._lock:
            assert metrics.counters.get("serve.oom.halved", 0) >= 1

    def test_shared_count_group_host_evals_once_without_halving(
            self, tmp_path):
        """Review finding: count/execute groups DEDUP to one planner
        run whose program size is independent of rider count — halving
        them just re-fails the identical allocation. They must go
        straight to ONE host evaluation shared by every rider."""
        from geomesa_tpu.serve.service import QueryService, ServeConfig
        from geomesa_tpu.utils.metrics import metrics

        store = make_store(tmp_path)
        svc = QueryService(store, ServeConfig(max_wait_ms=50.0))
        try:
            base = svc.count("faulty", CQL).result(timeout=60)
        finally:
            svc.close(drain=True)

        with metrics._lock:
            before = dict(metrics.counters)
        plan = FaultPlan(rules=[
            FaultRule(site="device.transfer", error="oom", every=1)])
        svc2 = QueryService(store, ServeConfig(max_wait_ms=50.0),
                            autostart=False)
        futs = [svc2.count("faulty", CQL) for _ in range(4)]
        with faults.active(plan):
            svc2.start()
            counts = [f.result(timeout=120) for f in futs]
            svc2.close(drain=True)
        assert counts == [base] * 4
        with metrics._lock:
            after = dict(metrics.counters)
        assert (after.get("serve.oom.halved", 0)
                == before.get("serve.oom.halved", 0))
        assert (after.get("fault.oom.hosteval", 0)
                - before.get("fault.oom.hosteval", 0)) == 1

    def test_aggregation_hints_surface_typed(self, tmp_path):
        from geomesa_tpu.faults.fallback import host_execute
        from geomesa_tpu.plan.hints import QueryHints
        from geomesa_tpu.plan.query import Query

        store = make_store(tmp_path)
        src = store.get_feature_source("faulty")
        q = Query("faulty", CQL,
                  hints=QueryHints(density_bbox=(-10, -10, 10, 10),
                                   density_width=8, density_height=8))
        with pytest.raises(PermanentError):
            host_execute(src, q)

    def test_host_fallback_respects_interceptor_chain(self, tmp_path):
        """Review finding: the host path must run the planner's
        QueryInterceptor chain exactly like the device path — a
        mandatory rewrite (e.g. tenant isolation) must bind on fallback
        results too."""
        import dataclasses

        from geomesa_tpu.cql import ast, parse_cql
        from geomesa_tpu.faults.fallback import host_count
        from geomesa_tpu.plan.query import Query

        store = make_store(tmp_path)
        src = store.get_feature_source("faulty")
        device_all = src.get_count(Query("faulty", CQL))

        def isolate(query):
            merged = ast.And((query.filter_ast,
                              parse_cql("score > 0")))
            return dataclasses.replace(query, filter=merged)

        src.planner.interceptors.append(isolate)
        device_n = src.get_count(Query("faulty", CQL))
        host_n = host_count(src, Query("faulty", CQL))
        assert host_n == device_n  # identical to the device path…
        assert host_n < device_all  # …and the guard actually bound


# -- storage write atomicity under manifest-commit failure ------------------


class TestManifestCommitRollback:
    def test_failed_commit_rolls_back_memory(self, tmp_path):
        """Review finding: a manifest-persist failure must roll the
        in-memory append back — pre-fix the 'failed' batch kept serving
        from memory, a client retry duplicated every row, and the next
        unrelated write silently committed it to disk."""
        import json as _json
        import os as _os

        store = make_store(tmp_path, n=64)
        src = store.get_feature_source("faulty")
        storage = src.storage
        before = storage.count
        snap_before = {k: list(v)
                       for k, v in storage.manifest_snapshot().items()}

        plan = FaultPlan(rules=[
            FaultRule(site="fs.write_manifest", error="io", nth_call=1)])
        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType

        rng = np.random.default_rng(4)
        batch = FeatureBatch.from_pydict(storage.sft, {
            "name": ["x"] * 8,
            "score": rng.uniform(-1, 1, 8),
            "dtg": rng.integers(1_590_000_000_000, 1_590_080_000_000, 8),
            "geom": rng.uniform(-10, 10, (8, 2)),
        })
        with faults.active(plan):
            with pytest.raises(OSError):
                src.write(batch)
        # memory matches disk: the failed batch is NOT visible
        assert storage.count == before
        assert {k: list(v)
                for k, v in storage.manifest_snapshot().items()} \
            == snap_before
        with open(_os.path.join(storage.root, "metadata.json")) as f:
            disk = _json.load(f)["manifest"]
        assert {k: v for k, v in disk.items()} == snap_before
        # a retry succeeds exactly once — no duplicated rows
        src.write(batch)
        assert storage.count == before + 8

    def test_failed_delete_commit_rolls_back_memory(self, tmp_path):
        """Same invariant on the delete path: a failed durable commit
        must not leave a phantom delete visible in memory (a restart
        would resurrect the rows)."""
        store = make_store(tmp_path, n=64)
        src = store.get_feature_source("faulty")
        storage = src.storage
        before = storage.count
        plan = FaultPlan(rules=[
            FaultRule(site="fs.write_manifest", error="io", nth_call=1)])
        with faults.active(plan):
            with pytest.raises(OSError):
                src.delete_features("name = 'a'")
        assert storage.count == before  # memory matches disk
        deleted = src.delete_features("name = 'a'")
        assert deleted > 0
        assert storage.count == before - deleted

    def test_failed_compact_commit_rolls_back_memory(self, tmp_path):
        """compact() too: a failed durable commit keeps the pre-compact
        manifest live in memory and does NOT delete the old files."""
        store = make_store(tmp_path, n=64)
        src = store.get_feature_source("faulty")
        storage = src.storage
        # second file in the same partitions so compact has work
        from geomesa_tpu.core.columnar import FeatureBatch

        rng = np.random.default_rng(6)
        src.write(FeatureBatch.from_pydict(storage.sft, {
            "name": ["y"] * 16,
            "score": rng.uniform(-1, 1, 16),
            "dtg": rng.integers(1_590_000_000_000, 1_590_080_000_000,
                                16),
            "geom": rng.uniform(-10, 10, (16, 2)),
        }))
        before = storage.count
        snap_before = {k: [e["file"] for e in v]
                       for k, v in storage.manifest_snapshot().items()}
        plan = FaultPlan(rules=[
            FaultRule(site="fs.write_manifest", error="io", nth_call=1)])
        with faults.active(plan):
            with pytest.raises(OSError):
                storage.compact()
        assert storage.count == before
        snap_after = {k: [e["file"] for e in v]
                      for k, v in storage.manifest_snapshot().items()}
        assert snap_after == snap_before
        # every pre-compact file survived (rollback skipped removal)
        for name, files in snap_before.items():
            for f in files:
                assert os.path.exists(
                    os.path.join(storage.root, name, f))
        # a retry compacts cleanly
        assert storage.compact() > 0
        assert storage.count == before


# -- ServeEvent recovery attribution ---------------------------------------


class TestServeEventAttribution:
    def test_retries_and_faults_attributed(self, tmp_path):
        from geomesa_tpu.plan.audit import ServeEvent
        from geomesa_tpu.serve.service import QueryService, ServeConfig

        store = make_store(tmp_path)
        plan = FaultPlan(rules=[
            FaultRule(site="fs.read_partition", error="io", nth_call=1)])
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            with faults.active(plan):
                # feature execute: the scan (and so the retry) runs on
                # the dispatch thread itself — the attribution window.
                # (Streaming counts read on the decode-ahead helper
                # thread; those retries are metered globally but not
                # attributed per-request — documented in _dispatch.)
                r = svc.query("faulty", CQL).result(timeout=60)
        finally:
            svc.close(drain=True)
        assert r.count > 0  # the retry absorbed the injected fault
        events = [e for e in store.audit.snapshot()
                  if isinstance(e, ServeEvent)]
        assert events, "serve event missing"
        ev = events[-1]
        assert ev.status == "ok"
        assert ev.retries >= 1
        assert ev.fault_injected >= 1
        assert ev.breaker_state == ""  # one hiccup: breakers closed

    def test_event_fields_default_clean(self, tmp_path):
        from geomesa_tpu.plan.audit import ServeEvent

        ev = ServeEvent(type_name="t", kind="count", tenant="",
                        priority="normal", queue_ms=0.0, exec_ms=0.0,
                        batch_size=1, status="ok")
        doc = ev.to_json()
        assert doc["retries"] == 0
        assert doc["fault_injected"] == 0
        assert doc["breaker_state"] == ""


# -- bounded kNN widen loop -------------------------------------------------


class TestKnnWidenBound:
    def test_partial_recall_instead_of_unbounded_loop(
            self, tmp_path, monkeypatch):
        import geomesa_tpu.process.knn as knn_mod
        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType

        monkeypatch.setattr(knn_mod, "MAX_WIDEN_ROUNDS", 4)
        store = make_store(tmp_path, n=2, seed=1)
        src = store.get_feature_source("faulty")
        sft = SimpleFeatureType.from_spec("q", "*geom:Point")
        qpts = FeatureBatch.from_pydict(
            sft, {"geom": np.array([[1.0, 2.0]])})
        proc = knn_mod.KNearestNeighborSearchProcess()
        # 5 neighbors wanted, 2 points exist, infinite search distance:
        # the recall window can NEVER fill — pre-fix this doubled the
        # radius forever; now it returns flagged after the cap
        result = proc.execute(
            qpts, src, num_desired=5, estimated_distance_m=1000.0,
            max_search_distance_m=float("inf"))
        assert result.partial_recall is True
        assert result.distances_m.shape == (1, 5)
        assert np.isfinite(result.distances_m[0]).sum() <= 2

    def test_satisfied_search_not_flagged(self, tmp_path):
        import geomesa_tpu.process.knn as knn_mod
        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType

        store = make_store(tmp_path, n=200, seed=2)
        src = store.get_feature_source("faulty")
        sft = SimpleFeatureType.from_spec("q", "*geom:Point")
        qpts = FeatureBatch.from_pydict(
            sft, {"geom": np.array([[1.0, 2.0]])})
        proc = knn_mod.KNearestNeighborSearchProcess()
        result = proc.execute(
            qpts, src, num_desired=3, estimated_distance_m=100_000.0,
            max_search_distance_m=30_000_000.0)
        assert result.partial_recall is False
        assert np.isfinite(result.distances_m).all()


# -- GT14 lint rule ---------------------------------------------------------


def lint_scoped(tmp_path, source, rel="geomesa_tpu/store/mod.py"):
    from geomesa_tpu.analysis import lint_paths

    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(tmp_path)], rules=["GT14"],
                      extra_ref_paths=[])


class TestGT14:
    DIRTY = """\
        def read(path):
            try:
                return open(path).read()
            except Exception:
                pass

        def read2(path):
            try:
                return open(path).read()
            except:
                pass

        def poll(broker):
            while True:
                try:
                    broker.consume()
                except Exception:
                    continue
    """

    def test_flags_swallows_and_unbounded_retry(self, tmp_path):
        fs = [f for f in lint_scoped(tmp_path, self.DIRTY)
              if not f.waived]
        got = {(f.rule, f.line) for f in fs}
        assert ("GT14", 4) in got   # except Exception: pass
        assert ("GT14", 10) in got  # bare except: pass
        assert ("GT14", 14) in got  # while True retry without exit
        assert len(fs) == 3

    CLEAN = """\
        import logging

        def read(path):
            try:
                return open(path).read()
            except Exception as e:
                logging.warning("read failed: %s", e)
                return None

        def read_narrow(path):
            try:
                return open(path).read()
            except FileNotFoundError:
                pass  # narrow type: a judgement call, not a swallow

        def poll_bounded(broker):
            for _ in range(3):
                try:
                    return broker.consume()
                except Exception:
                    continue
            raise RuntimeError("exhausted")

        def loop_with_exit(broker):
            while True:
                try:
                    return broker.consume()
                except Exception:
                    raise
    """

    def test_clean_twins_quiet(self, tmp_path):
        fs = [f for f in lint_scoped(tmp_path, self.CLEAN)
              if not f.waived]
        assert fs == []

    NESTED_BREAK = """\
        def poll(broker, backlog):
            while True:
                try:
                    broker.consume()
                except Exception:
                    pass
                for x in backlog:
                    if x:
                        break
    """

    def test_nested_loop_break_is_not_an_exit(self, tmp_path):
        """Review finding: a break belonging to a NESTED for/while
        exits only that inner loop — pre-fix it silenced the outer
        while-True retry-forever report."""
        fs = [f for f in lint_scoped(tmp_path, self.NESTED_BREAK)
              if not f.waived]
        assert ("GT14", 2) in {(f.rule, f.line) for f in fs}

    FOR_ELSE_BREAK = """\
        def poll(broker, attempts):
            while True:
                try:
                    for a in attempts:
                        if broker.consume(a):
                            raise StopIteration
                    else:
                        break
                except OSError:
                    pass
    """

    def test_for_else_break_exits_the_outer_loop(self, tmp_path):
        """Review finding: a break in a nested loop's `else:` clause
        targets the ENCLOSING loop (Python for/else) — flagging this
        bounded loop would force a spurious waiver."""
        fs = [f for f in lint_scoped(tmp_path, self.FOR_ELSE_BREAK)
              if not f.waived and "while True" in f.message]
        assert fs == []

    def test_out_of_scope_paths_ignored(self, tmp_path):
        fs = lint_scoped(tmp_path, self.DIRTY,
                         rel="geomesa_tpu/engine/mod.py")
        assert [f for f in fs if not f.waived] == []

    def test_waivable(self, tmp_path):
        src = """\
            def degrade(path):
                try:
                    return open(path).read()
                # gt: waive GT14
                except Exception:
                    pass
        """
        fs = lint_scoped(tmp_path, src)
        assert all(f.waived for f in fs if f.rule == "GT14")
        assert any(f.rule == "GT14" for f in fs)


# -- seeded chaos regression (gmtpu chaos --check, in-process) --------------


class TestChaosRegression:
    def test_cache_restore_does_not_double_platform_suffix(
            self, tmp_path):
        """Review finding: persistent_cache_dir() is already
        platform-suffixed; restoring it through the default
        per_platform=True re-joined the backend (<dir>/cpu/cpu) and
        silently orphaned every persisted executable."""
        import io

        from geomesa_tpu.compilecache.persist import (
            disable_persistent_cache, enable_persistent_cache,
            persistent_cache_dir)

        prior = enable_persistent_cache(
            cache_dir=str(tmp_path / "cc"), force=True)
        try:
            assert prior is not None and prior.endswith(os.sep + "cpu")
            plan = FaultPlan(rules=[
                FaultRule(site="kafka.poll", error="unavailable",
                          nth_call=1)])
            from geomesa_tpu.faults.chaos import run_chaos

            run_chaos(plan, requests=4, replay=False, out=io.StringIO())
            assert persistent_cache_dir() == prior  # not .../cpu/cpu
        finally:
            disable_persistent_cache()

    def test_setup_failure_leaks_nothing(self):
        """Review finding: a chaos setup failure (here: a harness is
        already installed) must not leak chaos breaker tuning or an
        orphaned dispatch thread into the process."""
        from geomesa_tpu.faults.chaos import run_chaos

        faults.BREAKERS.configure("storage", failure_threshold=10,
                                  reset_timeout_s=7.0)
        plan = FaultPlan(rules=[
            FaultRule(site="fs.read_partition", error="io", nth_call=1)])
        blocker = faults.install(FaultPlan(rules=[
            FaultRule(site="unused.site", error="io", nth_call=1)]))
        assert blocker is not None
        try:
            import io

            with pytest.raises(RuntimeError):
                run_chaos(plan, requests=2, replay=False,
                          out=io.StringIO())
        finally:
            faults.uninstall()
        # prior tuning survived the failed run
        b = faults.BREAKERS.get("storage")
        assert b.failure_threshold == 10
        assert b.reset_timeout_s == 7.0
        faults.BREAKERS.restore_config("storage", None)


    def test_smoke_plan_invariants_and_replay(self):
        import io

        from geomesa_tpu.faults.chaos import run_chaos

        plan_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "chaos_smoke_plan.json")
        plan = FaultPlan.load(plan_path)
        report = run_chaos(plan, requests=16, replay=True,
                           out=io.StringIO())
        assert report.invariant_failures == []
        assert report.ok_overall
        assert report.untyped_errors == []
        assert report.replay_match is True
        assert report.fires > 0
        # every acceptance site CLASS injected: storage read, kafka
        # poll, device transfer, compile-cache write
        fired = set(report.fired_sites)
        assert "fs.read_partition" in fired
        assert "kafka.poll" in fired
        assert "device.transfer" in fired
        assert "compilecache.persist" in fired
        # breaker open AND half-open transitions metered
        assert report.breaker_counters[
            "fault.breaker.storage.open"] >= 1
        assert report.breaker_counters[
            "fault.breaker.storage.half_open"] >= 1
        # the disabled harness stays a no-op check
        assert report.noop_us_per_call < 5.0
