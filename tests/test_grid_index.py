"""Grid-index kNN: exact recall vs the f64 oracle, certificate behavior.

The certificate must never falsely claim exactness; over-flagging is only a
performance issue (fallback runs), under-flagging is a correctness bug — so
these tests check final results AFTER the fallback, plus that the
no-fallback path is already exact when nothing is flagged.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from geomesa_tpu.engine.geodesy import haversine_m_np
from geomesa_tpu.engine.grid_index import (
    build_grid_index, knn_grid, knn_indexed)

rng = np.random.default_rng(77)


def oracle(qx, qy, dx, dy, mask, k):
    d = haversine_m_np(
        qx[:, None].astype(np.float64), qy[:, None].astype(np.float64),
        dx[None, mask].astype(np.float64), dy[None, mask].astype(np.float64),
    )
    return np.sort(d, axis=1)[:, :k]


def assert_recall(dists, exp, tol=1.5):
    got = np.sort(np.asarray(dists), axis=1)
    assert np.all(np.abs(got - exp) <= np.maximum(tol, 1e-4 * exp)), (
        np.abs(got - exp).max()
    )


class TestGridIndex:
    def setup_method(self):
        self.n, self.q, self.k = 60_000, 200, 10
        self.dx = rng.uniform(-20, 20, self.n).astype(np.float32)
        self.dy = rng.uniform(35, 65, self.n).astype(np.float32)
        self.mask = rng.random(self.n) < 0.5
        self.qx = rng.uniform(-15, 15, self.q).astype(np.float32)
        self.qy = rng.uniform(40, 60, self.q).astype(np.float32)

    def _args(self):
        return (
            jnp.asarray(self.qx), jnp.asarray(self.qy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask),
        )

    def test_build_partitions_all_matches(self):
        idx = build_grid_index(
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask), g=64,
        )
        assert int(np.asarray(idx.counts).sum()) == int(self.mask.sum())
        # every sorted prefix row is a real match, in its claimed cell
        sidx = np.asarray(idx.sidx)[: int(self.mask.sum())]
        assert self.mask[sidx].all()
        starts = np.asarray(idx.starts)
        sx, sy = np.asarray(idx.sx), np.asarray(idx.sy)
        for cell in rng.choice(64 * 64, 50, replace=False):
            a, b = starts[cell], starts[cell + 1]
            if a == b:
                continue
            cx = np.clip(((sx[a:b] + 180) / 360 * 64).astype(int), 0, 63)
            cy = np.clip(((sy[a:b] + 90) / 180 * 64).astype(int), 0, 63)
            assert (cy * 64 + cx == cell).all()

    def test_exact_after_fallback(self):
        exp = oracle(self.qx, self.qy, self.dx, self.dy, self.mask, self.k)
        kd, ki = knn_indexed(*self._args(), k=self.k, g=64,
                             ring_radius=2, cell_slots=128)
        assert_recall(kd, exp)
        ki = np.asarray(ki)
        assert self.mask[ki].all(), "returned a masked-out candidate"

    def test_certified_queries_already_exact(self):
        # whatever the certificate marks certain must match the oracle
        # WITHOUT any fallback help
        # g sized to the density: ~30k matches over ~7x11 deg-scale cells at
        # g=64 overflows every cell; g=256 keeps ~25 per cell
        idx = build_grid_index(
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask), g=256,
        )
        kd, ki, unc = knn_grid(
            jnp.asarray(self.qx), jnp.asarray(self.qy), idx,
            k=self.k, ring_radius=2, cell_slots=128,
        )
        unc = np.asarray(unc)
        assert (~unc).sum() > 0, "test needs some certified queries"
        exp = oracle(self.qx, self.qy, self.dx, self.dy, self.mask, self.k)
        assert_recall(np.asarray(kd)[~unc], exp[~unc])

    def test_sparse_region_flags_not_crashes(self):
        # queries far from all data: fewer than k in the neighborhood ->
        # flagged -> fallback produces the exact answer
        qx = np.full(8, 170.0, np.float32)
        qy = np.full(8, -80.0, np.float32)
        exp = oracle(qx, qy, self.dx, self.dy, self.mask, self.k)
        kd, _ = knn_indexed(
            jnp.asarray(qx), jnp.asarray(qy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask), k=self.k, g=64,
            ring_radius=1, cell_slots=64,
        )
        assert_recall(kd, exp)

    def test_dense_cell_overflow_fallback(self):
        # one cell holds far more points than cell_slots: overflow flag
        # must force the fallback, keeping exactness
        n = 20_000
        dx = rng.normal(2.0, 0.005, n).astype(np.float32)  # single-cell cluster
        dy = rng.normal(48.0, 0.005, n).astype(np.float32)
        mask = np.ones(n, bool)
        qx = rng.normal(2.0, 0.01, 16).astype(np.float32)
        qy = rng.normal(48.0, 0.01, 16).astype(np.float32)
        exp = oracle(qx, qy, dx, dy, mask, 5)
        kd, _ = knn_indexed(
            jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(dx),
            jnp.asarray(dy), jnp.asarray(mask), k=5, g=64, cell_slots=64,
        )
        assert_recall(kd, exp)

    def test_antimeridian_queries_flagged(self):
        # data on both sides of the seam; queries at the lon edge must not
        # be falsely certified (their square clips the grid edge)
        n = 5000
        dx = np.concatenate([
            rng.uniform(178, 180, n // 2), rng.uniform(-180, -178, n // 2)
        ]).astype(np.float32)
        dy = rng.uniform(-5, 5, n).astype(np.float32)
        mask = np.ones(n, bool)
        qx = np.asarray([179.9, -179.9, 179.5], np.float32)
        qy = np.asarray([0.0, 1.0, -1.0], np.float32)
        exp = oracle(qx, qy, dx, dy, mask, 5)
        kd, _ = knn_indexed(
            jnp.asarray(qx), jnp.asarray(qy), jnp.asarray(dx),
            jnp.asarray(dy), jnp.asarray(mask), k=5, g=64,
        )
        assert_recall(kd, exp)

    def test_sharded_matches_oracle(self):
        from geomesa_tpu.engine.grid_index import knn_indexed_sharded
        from geomesa_tpu.engine.knn import knn_sharded
        from geomesa_tpu.parallel.mesh import default_mesh

        mesh = default_mesh()
        n = self.n - (self.n % 8)
        dx, dy, mask = self.dx[:n], self.dy[:n], self.mask[:n]
        exp = oracle(self.qx, self.qy, dx, dy, mask, self.k)
        # per-shard density is 1/8th: size the grid to the shard
        kd, ki, unc = knn_indexed_sharded(
            mesh, jnp.asarray(self.qx), jnp.asarray(self.qy),
            jnp.asarray(dx), jnp.asarray(dy), jnp.asarray(mask),
            k=self.k, g=128, ring_radius=2, cell_slots=256,
        )
        kd, ki, unc = np.asarray(kd), np.asarray(ki), np.asarray(unc)
        if unc.any():
            fd, fi = knn_sharded(
                mesh, jnp.asarray(self.qx[unc]), jnp.asarray(self.qy[unc]),
                jnp.asarray(dx), jnp.asarray(dy), jnp.asarray(mask),
                k=self.k,
            )
            kd[unc] = np.asarray(fd)
            ki[unc] = np.asarray(fi)
        assert_recall(kd, exp)
        finite = np.isfinite(kd)
        assert mask[ki[finite]].all()

    def test_reused_index_matches_fresh(self):
        idx = build_grid_index(
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask), g=64,
        )
        kd1, ki1 = knn_indexed(*self._args(), k=self.k, g=64, index=idx)
        kd2, ki2 = knn_indexed(*self._args(), k=self.k, g=64)
        np.testing.assert_allclose(np.asarray(kd1), np.asarray(kd2), atol=1.0)
