"""Runtime-guard tests: recompile counters around jit caches, engine
sweep instrumentation, transfer-guard context, the `gmtpu guard` CLI,
and the metrics surfacing."""

import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import pytest

from geomesa_tpu.analysis.runtime import (
    JitTracker, guard_engine, is_jitted, run_guarded, transfer_guard)
from geomesa_tpu.utils.metrics import MetricsRegistry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestJitTracker:
    def test_counts_recompiles_per_shape(self):
        reg = MetricsRegistry()
        tracker = JitTracker(registry=reg)
        f = tracker.wrap(jax.jit(lambda x: x + 1), name="f")
        f(jnp.ones(4))
        f(jnp.ones(4))        # cache hit: no growth
        f(jnp.ones(8))        # new shape: recompile
        rep = tracker.report()
        assert rep["f"]["calls"] == 3
        assert rep["f"]["recompiles"] == 2
        assert reg.counters["analysis.recompiles"] == 2
        assert reg.gauges["analysis.recompiles.f"] == 2.0

    def test_storm_callback_fires_once(self):
        seen = []
        tracker = JitTracker(registry=MetricsRegistry(), warn_after=1,
                             on_storm=lambda n, c: seen.append((n, c)))
        f = tracker.wrap(jax.jit(lambda x: x * 2), name="g")
        for n in (2, 3, 4, 5):
            f(jnp.ones(n))
        assert len(seen) == 1
        assert seen[0][0] == "g" and seen[0][1] >= 2

    def test_wrap_rejects_plain_function(self):
        tracker = JitTracker(registry=MetricsRegistry())
        with pytest.raises(TypeError):
            tracker.wrap(lambda x: x)

    def test_results_unchanged(self):
        tracker = JitTracker(registry=MetricsRegistry())
        base = jax.jit(lambda x: x * 3)
        f = tracker.wrap(base, name="h")
        x = jnp.arange(5.0)
        assert jnp.array_equal(f(x), base(x))


class TestGuardEngine:
    def test_install_and_unwrap_stats_module(self):
        from geomesa_tpu.engine import stats as stats_mod

        orig = stats_mod.masked_count
        assert is_jitted(orig)
        tracker = guard_engine(registry=MetricsRegistry(),
                               modules=["geomesa_tpu.engine.stats"])
        try:
            assert stats_mod.masked_count is not orig
            n = int(stats_mod.masked_count(jnp.ones(8, bool)))
            assert n == 8
            rep = tracker.report()
            assert rep["stats.masked_count"]["calls"] == 1
        finally:
            tracker.unwrap()
        assert stats_mod.masked_count is orig

    def test_missing_module_skipped(self):
        tracker = guard_engine(registry=MetricsRegistry(),
                               modules=["geomesa_tpu.engine.nonexistent"])
        assert tracker.report() == {}


class TestTransferGuard:
    def test_modes_validate(self):
        with pytest.raises(ValueError):
            with transfer_guard("bogus"):
                pass

    def test_log_mode_is_noninvasive(self):
        with transfer_guard("log"):
            assert float(jnp.sum(jnp.ones(4))) == 4.0


class TestRunGuarded:
    def test_runs_script_with_tracking(self, tmp_path):
        script = tmp_path / "job.py"
        script.write_text(textwrap.dedent("""\
            import sys
            import jax.numpy as jnp
            from geomesa_tpu.engine.stats import masked_count

            n = int(sys.argv[1])
            print(int(masked_count(jnp.ones(n, bool))))
        """))
        reg = MetricsRegistry()
        report, status = run_guarded(str(script), argv=["641"],
                                     registry=reg)
        assert status == 0
        assert report["stats.masked_count"]["calls"] == 1
        assert report["stats.masked_count"]["recompiles"] == 1

    def test_cli_guard_reports(self, tmp_path):
        script = tmp_path / "job.py"
        script.write_text(textwrap.dedent("""\
            import jax.numpy as jnp
            from geomesa_tpu.engine.stats import masked_count

            print(int(masked_count(jnp.ones(4, bool))))
            print(int(masked_count(jnp.ones(9, bool))))
        """))
        r = subprocess.run(
            [sys.executable, "-m", "geomesa_tpu.cli.main", "guard",
             "--recompile-warn", "1", str(script)],
            capture_output=True, text=True, cwd=REPO_ROOT,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr
        assert "stats.masked_count: calls=2 recompiles=2" in r.stderr
        assert "retrace storm" in r.stderr

    def test_sys_exit_script_still_reports(self, tmp_path):
        # the standard `sys.exit(main())` idiom must not swallow the
        # report; the script's exit status propagates
        script = tmp_path / "job.py"
        script.write_text(textwrap.dedent("""\
            import sys
            import jax.numpy as jnp
            from geomesa_tpu.engine.stats import masked_count

            print(int(masked_count(jnp.ones(8, bool))))
            sys.exit(3)
        """))
        report, status = run_guarded(str(script))
        assert status == 3
        assert report["stats.masked_count"]["calls"] == 1
