"""Filesystem storage tests: partition schemes, pruning, parquet round-trips,
pushdown covering guarantees."""

import os

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.cql.extract import BBox, Interval
from geomesa_tpu.store import (
    AttributeScheme,
    CompositeScheme,
    DateTimeScheme,
    FileSystemStorage,
    XZ2Scheme,
    Z2Scheme,
    scheme_from_config,
)

SPEC = "name:String,score:Double,dtg:Date,*geom:Point"
T0 = int(np.datetime64("2020-06-01T00:00:00", "ms").astype(np.int64))
DAY = 86400_000

rng = np.random.default_rng(9)


def make_batch(n=1000, days=10, seed=0):
    r = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec("t", SPEC)
    return FeatureBatch.from_pydict(
        sft,
        {
            "name": r.choice(["a", "b", "c"], n).tolist(),
            "score": r.uniform(0, 1, n),
            "dtg": r.integers(T0, T0 + days * DAY, n),
            "geom": np.stack([r.uniform(-60, 60, n), r.uniform(-45, 45, n)], 1),
        },
        fids=[f"f{i}" for i in range(n)],
    )


class TestSchemes:
    def test_datetime_partitions(self):
        b = make_batch(100, days=3)
        s = DateTimeScheme("yyyy/MM/dd")
        parts = s.partitions_for(b)
        assert all(p.startswith("2020/06/") for p in parts)
        assert len(set(parts)) <= 4

    def test_datetime_prune(self):
        s = DateTimeScheme("yyyy/MM/dd")
        pruned = s.prune(BBox(-180, -90, 180, 90), Interval(T0, T0 + 2 * DAY))
        assert pruned == {"2020/06/01", "2020/06/02", "2020/06/03"}
        assert s.prune(BBox(-180, -90, 180, 90), Interval(None, None)) is None

    def test_z2_prune_covers(self):
        b = make_batch(200)
        s = Z2Scheme(bits=3)
        parts = np.asarray(s.partitions_for(b))
        bb = BBox(-30, -30, 30, 30)
        pruned = s.prune(bb, Interval(None, None))
        inbox = (
            (b.geometry.x >= -30) & (b.geometry.x <= 30)
            & (b.geometry.y >= -30) & (b.geometry.y <= 30)
        )
        for p in parts[inbox]:
            assert p in pruned

    def test_xz2_prune_covers(self):
        sft = SimpleFeatureType.from_spec("p", "name:String,*geom:Polygon")
        wkts, r = [], np.random.default_rng(2)
        for _ in range(50):
            cx, cy = r.uniform(-50, 50, 2)
            w = r.uniform(0.1, 5)
            wkts.append(f"POLYGON (({cx-w} {cy-w}, {cx+w} {cy-w}, {cx+w} {cy+w}, {cx-w} {cy+w}, {cx-w} {cy-w}))")
        b = FeatureBatch.from_pydict(sft, {"name": ["x"] * 50, "geom": wkts})
        s = XZ2Scheme(g=3)
        parts = np.asarray(s.partitions_for(b))
        bb = BBox(-20, -20, 20, 20)
        pruned = s.prune(bb, Interval(None, None))
        overlaps = (
            (b.geometry.bbox[:, 0] <= 20) & (b.geometry.bbox[:, 2] >= -20)
            & (b.geometry.bbox[:, 1] <= 20) & (b.geometry.bbox[:, 3] >= -20)
        )
        for p in parts[overlaps]:
            assert p in pruned

    def test_composite(self):
        b = make_batch(100, days=2)
        s = CompositeScheme([DateTimeScheme("yyyy/MM/dd"), Z2Scheme(bits=2)])
        parts = s.partitions_for(b)
        assert all("/z2/" in p for p in parts)
        pruned = s.prune(BBox(-10, -10, 10, 10), Interval(T0, T0 + DAY))
        assert pruned and all(p.startswith("2020/06/0") for p in pruned)

    def test_config_roundtrip(self):
        for s in [
            DateTimeScheme("yyyy/MM"),
            Z2Scheme(5, "geom"),
            XZ2Scheme(3),
            AttributeScheme("name"),
            CompositeScheme([DateTimeScheme(), Z2Scheme()]),
        ]:
            s2 = scheme_from_config(s.to_config())
            assert s2.to_config() == s.to_config()


class TestFileSystemStorage:
    def test_write_read_roundtrip(self, tmp_path):
        b = make_batch(500, days=5)
        store = FileSystemStorage.create(
            str(tmp_path / "s"), b.sft, DateTimeScheme("yyyy/MM/dd")
        )
        store.write(b)
        assert store.count == 500
        back = store.read_all()
        assert len(back) == 500
        # round-trip preserves values (order may shuffle across partitions)
        assert sorted(back.fids.decode()) == sorted(b.fids.decode())
        got = {f: s for f, s in zip(back.fids.decode(), back.column("score"))}
        exp = {f: s for f, s in zip(b.fids.decode(), b.column("score"))}
        for k in exp:
            assert got[k] == pytest.approx(exp[k])

    def test_load_existing(self, tmp_path):
        b = make_batch(100)
        root = str(tmp_path / "s")
        store = FileSystemStorage.create(root, b.sft, DateTimeScheme())
        store.write(b)
        store2 = FileSystemStorage.load(root)
        assert store2.count == 100
        assert store2.sft.to_spec() == b.sft.to_spec()
        assert len(store2.read_all()) == 100

    def test_create_twice_fails(self, tmp_path):
        b = make_batch(10)
        root = str(tmp_path / "s")
        FileSystemStorage.create(root, b.sft, DateTimeScheme())
        with pytest.raises(FileExistsError):
            FileSystemStorage.create(root, b.sft, DateTimeScheme())

    def test_scan_covering(self, tmp_path):
        """Every feature matching bounds must come back (covering), and the
        scan must not read partitions outside the pruned set."""
        b = make_batch(2000, days=10)
        store = FileSystemStorage.create(
            str(tmp_path / "s"), b.sft,
            CompositeScheme([DateTimeScheme("yyyy/MM/dd"), Z2Scheme(bits=2)]),
        )
        store.write(b)
        bb = BBox(-20, -20, 20, 20)
        iv = Interval(T0 + 2 * DAY, T0 + 5 * DAY)
        got = [f for batch in store.scan(bb, iv) for f in batch.fids.decode()]
        x, y, t = b.geometry.x, b.geometry.y, np.asarray(b.dtg)
        match = (
            (x >= bb.xmin) & (x <= bb.xmax) & (y >= bb.ymin) & (y <= bb.ymax)
            & (t >= iv.start) & (t <= iv.end)
        )
        expected = set(np.asarray(b.fids.decode(), dtype=object)[match])
        assert expected <= set(got)
        # pruning actually prunes
        assert len(store.prune_partitions(bb, iv)) < len(store.partitions())

    def test_scan_projection(self, tmp_path):
        b = make_batch(100)
        store = FileSystemStorage.create(str(tmp_path / "s"), b.sft, DateTimeScheme())
        store.write(b)
        out = list(store.scan(columns=["name", "geom"]))
        assert out and set(out[0].columns) == {"name", "geom"}

    def test_append(self, tmp_path):
        b1, b2 = make_batch(100, seed=1), make_batch(150, seed=2)
        store = FileSystemStorage.create(str(tmp_path / "s"), b1.sft, DateTimeScheme())
        store.write(b1)
        store.write(b2)
        assert store.count == 250
        assert len(store.read_all()) == 250

    def test_polygon_store(self, tmp_path):
        sft = SimpleFeatureType.from_spec("p", "name:String,*geom:Polygon")
        b = FeatureBatch.from_pydict(
            sft,
            {
                "name": ["a", "b"],
                "geom": [
                    "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))",
                    "POLYGON ((50 50, 54 50, 54 54, 50 54, 50 50))",
                ],
            },
        )
        store = FileSystemStorage.create(str(tmp_path / "s"), sft, XZ2Scheme(g=2))
        store.write(b)
        got = list(store.scan(BBox(-1, -1, 5, 5), Interval(None, None)))
        names = [n for batch in got for n in batch.column("name").decode()]
        assert "a" in names and "b" not in names  # pushdown pruned the far one
