"""Filesystem storage tests: partition schemes, pruning, parquet round-trips,
pushdown covering guarantees."""

import os

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.cql.extract import BBox, Interval
from geomesa_tpu.store import (
    AttributeScheme,
    CompositeScheme,
    DateTimeScheme,
    FileSystemStorage,
    XZ2Scheme,
    Z2Scheme,
    scheme_from_config,
)

SPEC = "name:String,score:Double,dtg:Date,*geom:Point"
T0 = int(np.datetime64("2020-06-01T00:00:00", "ms").astype(np.int64))
DAY = 86400_000

rng = np.random.default_rng(9)


def make_batch(n=1000, days=10, seed=0):
    r = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec("t", SPEC)
    return FeatureBatch.from_pydict(
        sft,
        {
            "name": r.choice(["a", "b", "c"], n).tolist(),
            "score": r.uniform(0, 1, n),
            "dtg": r.integers(T0, T0 + days * DAY, n),
            "geom": np.stack([r.uniform(-60, 60, n), r.uniform(-45, 45, n)], 1),
        },
        fids=[f"f{i}" for i in range(n)],
    )


class TestSchemes:
    def test_datetime_partitions(self):
        b = make_batch(100, days=3)
        s = DateTimeScheme("yyyy/MM/dd")
        parts = s.partitions_for(b)
        assert all(p.startswith("2020/06/") for p in parts)
        assert len(set(parts)) <= 4

    def test_datetime_prune(self):
        s = DateTimeScheme("yyyy/MM/dd")
        pruned = s.prune(BBox(-180, -90, 180, 90), Interval(T0, T0 + 2 * DAY))
        assert pruned == {"2020/06/01", "2020/06/02", "2020/06/03"}
        assert s.prune(BBox(-180, -90, 180, 90), Interval(None, None)) is None

    def test_z2_prune_covers(self):
        b = make_batch(200)
        s = Z2Scheme(bits=3)
        parts = np.asarray(s.partitions_for(b))
        bb = BBox(-30, -30, 30, 30)
        pruned = s.prune(bb, Interval(None, None))
        inbox = (
            (b.geometry.x >= -30) & (b.geometry.x <= 30)
            & (b.geometry.y >= -30) & (b.geometry.y <= 30)
        )
        for p in parts[inbox]:
            assert p in pruned

    def test_xz2_prune_covers(self):
        sft = SimpleFeatureType.from_spec("p", "name:String,*geom:Polygon")
        wkts, r = [], np.random.default_rng(2)
        for _ in range(50):
            cx, cy = r.uniform(-50, 50, 2)
            w = r.uniform(0.1, 5)
            wkts.append(f"POLYGON (({cx-w} {cy-w}, {cx+w} {cy-w}, {cx+w} {cy+w}, {cx-w} {cy+w}, {cx-w} {cy-w}))")
        b = FeatureBatch.from_pydict(sft, {"name": ["x"] * 50, "geom": wkts})
        s = XZ2Scheme(g=3)
        parts = np.asarray(s.partitions_for(b))
        bb = BBox(-20, -20, 20, 20)
        pruned = s.prune(bb, Interval(None, None))
        overlaps = (
            (b.geometry.bbox[:, 0] <= 20) & (b.geometry.bbox[:, 2] >= -20)
            & (b.geometry.bbox[:, 1] <= 20) & (b.geometry.bbox[:, 3] >= -20)
        )
        for p in parts[overlaps]:
            assert p in pruned

    def test_composite(self):
        b = make_batch(100, days=2)
        s = CompositeScheme([DateTimeScheme("yyyy/MM/dd"), Z2Scheme(bits=2)])
        parts = s.partitions_for(b)
        assert all("/z2/" in p for p in parts)
        pruned = s.prune(BBox(-10, -10, 10, 10), Interval(T0, T0 + DAY))
        assert pruned and all(p.startswith("2020/06/0") for p in pruned)

    def test_config_roundtrip(self):
        for s in [
            DateTimeScheme("yyyy/MM"),
            Z2Scheme(5, "geom"),
            XZ2Scheme(3),
            AttributeScheme("name"),
            CompositeScheme([DateTimeScheme(), Z2Scheme()]),
        ]:
            s2 = scheme_from_config(s.to_config())
            assert s2.to_config() == s.to_config()


class TestFileSystemStorage:
    def test_write_read_roundtrip(self, tmp_path):
        b = make_batch(500, days=5)
        store = FileSystemStorage.create(
            str(tmp_path / "s"), b.sft, DateTimeScheme("yyyy/MM/dd")
        )
        store.write(b)
        assert store.count == 500
        back = store.read_all()
        assert len(back) == 500
        # round-trip preserves values (order may shuffle across partitions)
        assert sorted(back.fids.decode()) == sorted(b.fids.decode())
        got = {f: s for f, s in zip(back.fids.decode(), back.column("score"))}
        exp = {f: s for f, s in zip(b.fids.decode(), b.column("score"))}
        for k in exp:
            assert got[k] == pytest.approx(exp[k])

    def test_load_existing(self, tmp_path):
        b = make_batch(100)
        root = str(tmp_path / "s")
        store = FileSystemStorage.create(root, b.sft, DateTimeScheme())
        store.write(b)
        store2 = FileSystemStorage.load(root)
        assert store2.count == 100
        assert store2.sft.to_spec() == b.sft.to_spec()
        assert len(store2.read_all()) == 100

    def test_create_twice_fails(self, tmp_path):
        b = make_batch(10)
        root = str(tmp_path / "s")
        FileSystemStorage.create(root, b.sft, DateTimeScheme())
        with pytest.raises(FileExistsError):
            FileSystemStorage.create(root, b.sft, DateTimeScheme())

    def test_scan_covering(self, tmp_path):
        """Every feature matching bounds must come back (covering), and the
        scan must not read partitions outside the pruned set."""
        b = make_batch(2000, days=10)
        store = FileSystemStorage.create(
            str(tmp_path / "s"), b.sft,
            CompositeScheme([DateTimeScheme("yyyy/MM/dd"), Z2Scheme(bits=2)]),
        )
        store.write(b)
        bb = BBox(-20, -20, 20, 20)
        iv = Interval(T0 + 2 * DAY, T0 + 5 * DAY)
        got = [f for batch in store.scan(bb, iv) for f in batch.fids.decode()]
        x, y, t = b.geometry.x, b.geometry.y, np.asarray(b.dtg)
        match = (
            (x >= bb.xmin) & (x <= bb.xmax) & (y >= bb.ymin) & (y <= bb.ymax)
            & (t >= iv.start) & (t <= iv.end)
        )
        expected = set(np.asarray(b.fids.decode(), dtype=object)[match])
        assert expected <= set(got)
        # pruning actually prunes
        assert len(store.prune_partitions(bb, iv)) < len(store.partitions())

    def test_scan_projection(self, tmp_path):
        b = make_batch(100)
        store = FileSystemStorage.create(str(tmp_path / "s"), b.sft, DateTimeScheme())
        store.write(b)
        out = list(store.scan(columns=["name", "geom"]))
        assert out and set(out[0].columns) == {"name", "geom"}

    def test_append(self, tmp_path):
        b1, b2 = make_batch(100, seed=1), make_batch(150, seed=2)
        store = FileSystemStorage.create(str(tmp_path / "s"), b1.sft, DateTimeScheme())
        store.write(b1)
        store.write(b2)
        assert store.count == 250
        assert len(store.read_all()) == 250

    def test_polygon_store(self, tmp_path):
        sft = SimpleFeatureType.from_spec("p", "name:String,*geom:Polygon")
        b = FeatureBatch.from_pydict(
            sft,
            {
                "name": ["a", "b"],
                "geom": [
                    "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))",
                    "POLYGON ((50 50, 54 50, 54 54, 50 54, 50 50))",
                ],
            },
        )
        store = FileSystemStorage.create(str(tmp_path / "s"), sft, XZ2Scheme(g=2))
        store.write(b)
        got = list(store.scan(BBox(-1, -1, 5, 5), Interval(None, None)))
        names = [n for batch in got for n in batch.column("name").decode()]
        assert "a" in names and "b" not in names  # pushdown pruned the far one


class TestArrowDeltaProtocol:
    """Sorted delta batches + client merge (DeltaWriter parity,
    SURVEY.md:260-262) and the ArrowDataStore (SURVEY.md:341)."""

    def _batch(self, n=200, seed=3):
        rng = np.random.default_rng(seed)
        sft = SimpleFeatureType.from_spec(
            "ais", "mmsi:String,speed:Double,dtg:Date,*geom:Point"
        )
        return sft, FeatureBatch.from_pydict(
            sft,
            {
                "mmsi": [f"m{i % 17}" for i in range(n)],
                "speed": rng.uniform(0, 30, n),
                "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
                "geom": np.stack(
                    [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1
                ),
            },
        )

    def test_sorted_merge_equals_global_sort(self):
        import io

        import pyarrow as pa

        from geomesa_tpu.core.arrow_io import (
            merge_sorted_ipc, to_sorted_ipc_bytes)

        sft, batch = self._batch(300)
        # three "shards"
        idx = np.arange(300)
        shards = [batch.select(idx[i::3]) for i in range(3)]
        streams = [to_sorted_ipc_bytes(s, "dtg") for s in shards]
        merged = merge_sorted_ipc(streams)
        t = pa.ipc.open_stream(io.BytesIO(merged)).read_all()
        got = t.column("dtg").to_numpy(zero_copy_only=False)
        # equals the globally-sorted single batch
        exp = np.sort(np.asarray(batch.column("dtg")))
        assert (got.astype("datetime64[ms]").astype(np.int64) == exp).all()
        # dictionaries re-keyed: every mmsi survives
        assert set(t.column("mmsi").to_pylist()) == set(
            batch.columns["mmsi"].decode()
        )

    def test_sorted_merge_rejects_mismatch_and_handles_empty(self):
        import pytest as _pytest

        from geomesa_tpu.core.arrow_io import (
            merge_sorted_ipc, to_ipc_bytes, to_sorted_ipc_bytes)

        sft, batch = self._batch(50)
        a = to_sorted_ipc_bytes(batch, "dtg")
        b = to_sorted_ipc_bytes(batch, "speed")
        with _pytest.raises(ValueError, match="sort mismatch"):
            merge_sorted_ipc([a, b])
        with _pytest.raises(ValueError, match="not a sorted delta"):
            merge_sorted_ipc([to_ipc_bytes(batch)])
        empty = batch.select(np.zeros(0, np.int64))
        s = merge_sorted_ipc([to_sorted_ipc_bytes(empty, "dtg")])
        import io

        import pyarrow as pa

        assert pa.ipc.open_stream(io.BytesIO(s)).read_all().num_rows == 0

    def test_delta_hint_through_datastore(self, tmp_path):
        import io

        import pyarrow as pa

        from geomesa_tpu.core.arrow_io import merge_sorted_ipc
        from geomesa_tpu.plan import DataStore, Query, QueryHints

        sft, batch = self._batch(240)
        ds = DataStore(str(tmp_path))
        src = ds.create_schema(sft)
        src.write(batch)
        q = Query(
            "ais", "speed > 10",
            hints=QueryHints(arrow_encode=True, arrow_sort_field="dtg"),
        )
        r = src.get_features(q)
        merged = merge_sorted_ipc([r.arrow_bytes, r.arrow_bytes])
        t = pa.ipc.open_stream(io.BytesIO(merged)).read_all()
        d = t.column("dtg").to_numpy(zero_copy_only=False)
        assert (d[1:] >= d[:-1]).all()
        exp = int((np.asarray(batch.column("speed")) > 10).sum())
        assert t.num_rows == 2 * exp

    def test_arrow_datastore_round_trip(self, tmp_path):
        from geomesa_tpu.core.arrow_io import write_ipc
        from geomesa_tpu.store import ArrowDataStore

        sft, batch = self._batch(180)
        p = str(tmp_path / "ais.arrow")
        write_ipc(p, [batch])
        store = ArrowDataStore(p)
        assert store.get_type_names() == ["ais"]
        src = store.get_feature_source()
        # full query stack incl. compiled mask + aggregation hints
        cql = "BBOX(geom, -60, -40, 60, 40) AND speed > 5"
        from tests.reference_engine import eval_filter
        from geomesa_tpu.cql import parse_cql

        exp = int(eval_filter(parse_cql(cql), batch).sum())
        assert src.get_count(cql) == exp
        from geomesa_tpu.plan import Query, QueryHints

        r = src.get_features(
            Query("ais", cql, hints=QueryHints(stats_string="MinMax(speed)"))
        )
        assert r.kind == "stats"

    def test_arrow_datastore_append_flush(self, tmp_path):
        from geomesa_tpu.core.arrow_io import write_ipc
        from geomesa_tpu.store import ArrowDataStore

        sft, batch = self._batch(100)
        p = str(tmp_path / "ais.arrow")
        write_ipc(p, [batch])
        store = ArrowDataStore(p)
        src = store.get_feature_source("ais")
        _, more = self._batch(40, seed=9)
        src.add_features(more)
        src.flush()
        assert src.get_count("INCLUDE") == 140
        # durable: reopen sees the appended rows
        assert ArrowDataStore(p).get_feature_source("ais").get_count() == 140


class TestWritePathStats:
    """StatUpdater analog (round 4, VERDICT #6): planner estimates are
    live immediately after ingest, with NO stats-analyze call."""

    def _mk(self, tmp_path, n=3000, seed=71):
        import numpy as np

        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.plan.datastore import DataStore

        rng = np.random.default_rng(seed)
        sft = SimpleFeatureType.from_spec(
            "ws", "kind:String,score:Double,dtg:Date,*geom:Point")
        batch = FeatureBatch.from_pydict(sft, {
            "kind": rng.choice(["a", "b"], n).tolist(),
            "score": rng.uniform(-5, 5, n),
            "dtg": rng.integers(1_590_000_000_000, 1_591_000_000_000, n),
            "geom": np.stack(
                [rng.uniform(-50, -30, n), rng.uniform(10, 30, n)], 1),
        })
        ds = DataStore(str(tmp_path / "ws"))
        return ds.create_schema(sft), batch, sft

    def test_estimates_live_after_write(self, tmp_path):
        from geomesa_tpu.cql.extract import BBox, Interval

        src, batch, sft = self._mk(tmp_path)
        src.write(batch)  # NO stats-analyze anywhere in this test
        mgr = src.planner.stats_manager()
        mgr.refresh()
        assert mgr.count == len(batch)
        # spatio-temporal estimate reflects the data region
        est_in = mgr.estimate_count(
            BBox(-60, 0, -20, 40),
            Interval(1_590_000_000_000, 1_591_000_000_000))
        est_out = mgr.estimate_count(
            BBox(100, 0, 140, 40),
            Interval(1_590_000_000_000, 1_591_000_000_000))
        assert est_in is not None and est_in > 0
        assert (est_out or 0) < est_in / 10
        lo, hi = mgr.minmax("score")
        assert -5 <= lo < hi <= 5

    def test_incremental_equals_analyze(self, tmp_path):
        # two writes then compare against a fresh full analyze: the
        # mergeable sketches must agree on count and minmax
        src, batch, sft = self._mk(tmp_path)
        half = len(batch) // 2
        import numpy as np

        src.write(batch.select(np.arange(half)))
        src.write(batch.select(np.arange(half, len(batch))))
        mgr = src.planner.stats_manager()
        mgr.refresh()
        live_count = mgr.count
        live_minmax = mgr.minmax("score")
        mgr.analyze()
        assert mgr.count == live_count == len(batch)
        assert mgr.minmax("score") == live_minmax


class TestDeleteFeatures:
    """delete-features + FS age-off (round 4, VERDICT #9)."""

    def _mk(self, tmp_path):
        import numpy as np

        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.plan.datastore import DataStore

        rng = np.random.default_rng(81)
        n = 2000
        sft = SimpleFeatureType.from_spec(
            "df", "kind:String,score:Double,dtg:Date,*geom:Point")
        t0 = 1_590_000_000_000
        batch = FeatureBatch.from_pydict(sft, {
            "kind": rng.choice(["keep", "drop"], n).tolist(),
            "score": rng.uniform(0, 10, n),
            "dtg": rng.integers(t0, t0 + 30 * 86_400_000, n),
            "geom": np.stack(
                [rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)], 1),
        })
        ds = DataStore(str(tmp_path / "df"))
        src = ds.create_schema(sft)
        src.write(batch)
        return src, batch, t0

    def test_delete_by_cql(self, tmp_path):
        import numpy as np

        src, batch, t0 = self._mk(tmp_path)
        kinds = np.asarray(batch.columns["kind"].decode(), dtype=object)
        score = np.asarray(batch.columns["score"])
        victims = int(((kinds == "drop") & (score > 5)).sum())
        n = src.delete_features("kind = 'drop' AND score > 5")
        assert n == victims
        assert src.get_count("INCLUDE") == len(batch) - victims
        assert src.get_count("kind = 'drop' AND score > 5") == 0
        # survivors still queryable and exact
        exp_keep = int((kinds == "keep").sum())
        assert src.get_count("kind = 'keep'") == exp_keep

    def test_age_off(self, tmp_path):
        import numpy as np

        src, batch, t0 = self._mk(tmp_path)
        cutoff = t0 + 15 * 86_400_000
        dtg = np.asarray(batch.columns["dtg"])
        old = int((dtg < cutoff).sum())
        n = src.age_off(cutoff)
        assert n == old
        assert src.get_count("INCLUDE") == len(batch) - old

    def test_delete_all_keeps_schema(self, tmp_path):
        src, batch, t0 = self._mk(tmp_path)
        n = src.delete_features("INCLUDE")
        assert n == len(batch)
        assert src.get_count("INCLUDE") == 0
        r = src.get_features("INCLUDE")
        assert r.count == 0


def test_stats_rebuild_after_delete_then_write(tmp_path):
    # round-4 review repro: delete invalidates sketches; the NEXT write
    # must re-analyze the whole store, not claim one-batch stats
    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore

    rng = np.random.default_rng(91)
    sft = SimpleFeatureType.from_spec("rs", "kind:String,*geom:Point")

    def mk(n, seed):
        r = np.random.default_rng(seed)
        return FeatureBatch.from_pydict(sft, {
            "kind": r.choice(["x", "y"], n).tolist(),
            "geom": np.stack(
                [r.uniform(-10, 10, n), r.uniform(-10, 10, n)], 1)})

    ds = DataStore(str(tmp_path / "rs"))
    src = ds.create_schema(sft)
    b1 = mk(1000, 1)
    src.write(b1)
    kinds = np.asarray(b1.columns["kind"].decode(), dtype=object)
    nx = int((kinds == "x").sum())
    src.delete_features("kind = 'x'")
    src.write(mk(500, 2))
    mgr = src.planner.stats_manager()
    mgr.refresh()
    assert mgr.count == (1000 - nx) + 500  # whole store, not last batch
