"""Core model tests: SFT spec round-trips, columnar batches, WKT, Arrow IO."""

import numpy as np
import pytest

from geomesa_tpu.core.arrow_io import from_arrow, read_ipc, to_arrow, write_ipc
from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import box, parse_wkt, point, to_wkt

SPEC = "name:String:index=true,age:Integer,weight:Double,dtg:Date,*geom:Point:srid=4326"


class TestSFT:
    def test_parse(self):
        sft = SimpleFeatureType.from_spec("test", SPEC)
        assert sft.attribute_names == ["name", "age", "weight", "dtg", "geom"]
        assert sft.attribute("name").options == {"index": "true"}
        assert sft.default_geometry.name == "geom"
        assert sft.default_dtg.name == "dtg"
        assert sft.attribute("geom").default_geom

    def test_roundtrip(self):
        sft = SimpleFeatureType.from_spec("test", SPEC)
        sft2 = SimpleFeatureType.from_spec("test", sft.to_spec())
        assert sft2.to_spec() == sft.to_spec()

    def test_user_data(self):
        sft = SimpleFeatureType.from_spec(
            "t", "dtg:Date,*geom:Point;geomesa.z3.interval=day,geomesa.index.dtg=dtg"
        )
        assert sft.user_data["geomesa.z3.interval"] == "day"
        assert sft.default_dtg.name == "dtg"

    def test_aliases_and_lists(self):
        sft = SimpleFeatureType.from_spec("t", "a:int,b:long,c:List[String],*g:Geometry")
        assert sft.attribute("a").type == "Integer"
        assert sft.attribute("b").type == "Long"
        assert sft.attribute("c").type == "List[String]"

    def test_bad_type_raises(self):
        with pytest.raises(ValueError):
            SimpleFeatureType.from_spec("t", "a:Blob")


class TestWKT:
    def test_point_roundtrip(self):
        g = parse_wkt("POINT (10 20)")
        assert g.point == (10.0, 20.0)
        assert to_wkt(g) == "POINT (10.0 20.0)"

    def test_polygon_with_hole(self):
        g = parse_wkt("POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (2 2, 4 2, 4 4, 2 4, 2 2))")
        assert g.kind == "Polygon" and len(g.rings) == 2
        assert g.bbox == (0.0, 0.0, 10.0, 10.0)
        g2 = parse_wkt(to_wkt(g))
        np.testing.assert_array_equal(g2.rings[1], g.rings[1])

    def test_multipolygon(self):
        g = parse_wkt("MULTIPOLYGON (((0 0, 1 0, 1 1, 0 0)), ((5 5, 6 5, 6 6, 5 5)))")
        assert g.kind == "MultiPolygon" and g.parts == [1, 1]
        g2 = parse_wkt(to_wkt(g))
        assert g2.parts == [1, 1]

    def test_linestring_and_multipoint(self):
        g = parse_wkt("LINESTRING (0 0, 1 1, 2 0)")
        assert g.rings[0].shape == (3, 2)
        g = parse_wkt("MULTIPOINT ((1 2), (3 4))")
        assert len(g.rings) == 2
        g = parse_wkt("MULTIPOINT (1 2, 3 4)")
        assert len(g.rings) == 2

    def test_box_helper(self):
        b = box(-10, -5, 10, 5)
        assert b.bbox == (-10.0, -5.0, 10.0, 5.0)


def make_batch(n=10):
    sft = SimpleFeatureType.from_spec("test", SPEC)
    rng = np.random.default_rng(0)
    return FeatureBatch.from_pydict(
        sft,
        {
            "name": [f"n{i % 3}" for i in range(n)],
            "age": np.arange(n),
            "weight": rng.uniform(0, 100, n),
            "dtg": np.arange(n) * 3600_000 + 1_600_000_000_000,
            "geom": rng.uniform(-90, 90, (n, 2)),
        },
        fids=[f"fid{i}" for i in range(n)],
    )


class TestFeatureBatch:
    def test_construct(self):
        b = make_batch(10)
        assert len(b) == 10
        assert isinstance(b.column("name"), DictColumn)
        assert b.column("name").decode()[:3] == ["n0", "n1", "n2"]
        assert b.geometry.is_point
        assert b.dtg.dtype == np.int64

    def test_select(self):
        b = make_batch(10)
        sel = b.select(np.array([0, 2, 4]))
        assert len(sel) == 3
        assert sel.column("age").tolist() == [0, 2, 4]
        assert sel.fids.decode() == ["fid0", "fid2", "fid4"]
        mask = np.zeros(10, dtype=bool)
        mask[7] = True
        assert b.select(mask).column("age").tolist() == [7]

    def test_pad(self):
        b = make_batch(10)
        p = b.pad_to(16)
        assert len(p) == 16
        assert p.num_valid == 10
        assert not p.valid[10:].any()

    def test_concat(self):
        b1, b2 = make_batch(5), make_batch(7)
        c = FeatureBatch.concat([b1, b2])
        assert len(c) == 12
        assert c.column("name").decode()[5] == "n0"

    def test_dict_concat_vocab_merge(self):
        # vectorized vocab-merge concat: shared values collapse to one
        # code, nulls survive, decode round-trips
        from geomesa_tpu.core.columnar import DictColumn

        a = DictColumn.encode(["x", None, "y", "x"])
        b = DictColumn.encode(["y", "z", None])
        c = DictColumn.concat([a, b])
        assert c.decode() == ["x", None, "y", "x", "y", "z", None]
        assert len(c.vocab) == 3

    def test_empty_geometry_column_keeps_declared_kind(self):
        # a zero-row batch of a non-Point type must not degrade to a Point
        # column (its arrow schema would disagree with the feature type)
        sft = SimpleFeatureType.from_spec("t", "name:String,*geom:Polygon")
        b = FeatureBatch.from_pydict(sft, {"name": [], "geom": []})
        assert not b.geometry.is_point
        from geomesa_tpu.core.arrow_io import to_arrow

        rb = to_arrow(b)  # must build a consistent zero-row record batch
        assert rb.num_rows == 0

    def test_extended_geometry_column(self):
        polys = [
            parse_wkt("POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))"),
            parse_wkt("POLYGON ((10 10, 12 10, 12 12, 10 12, 10 10), (10.5 10.5, 11 10.5, 11 11, 10.5 10.5))"),
        ]
        col = GeometryColumn.from_geometries(polys)
        assert not col.is_point
        assert col.bbox[0].tolist() == [0, 0, 4, 4]
        g = col.geometry(1)
        assert len(g.rings) == 2
        taken = col.take(np.array([1]))
        assert len(taken) == 1 and len(taken.geometry(0).rings) == 2


class TestArrowIO:
    def test_roundtrip(self):
        b = make_batch(10)
        rb = to_arrow(b)
        assert rb.num_rows == 10
        b2 = from_arrow(rb)
        assert b2.column("name").decode() == b.column("name").decode()
        np.testing.assert_array_equal(b2.column("age"), b.column("age"))
        np.testing.assert_allclose(b2.geometry.x, b.geometry.x)
        assert b2.fids.decode() == b.fids.decode()

    def test_polygon_roundtrip(self):
        sft = SimpleFeatureType.from_spec("p", "name:String,*geom:Polygon")
        b = FeatureBatch.from_pydict(
            sft,
            {
                "name": ["a", "b"],
                "geom": [
                    "POLYGON ((0 0, 4 0, 4 4, 0 0))",
                    "POLYGON ((1 1, 2 1, 2 2, 1 1))",
                ],
            },
        )
        b2 = from_arrow(to_arrow(b))
        assert b2.geometry.bbox[1].tolist() == [1, 1, 2, 2]

    def test_ipc_file(self, tmp_path):
        b = make_batch(10)
        path = str(tmp_path / "features.arrow")
        write_ipc(path, [b, b])
        batches = read_ipc(path)
        assert len(batches) == 2
        assert len(batches[0]) == 10
        assert batches[0].sft.name == "test"
