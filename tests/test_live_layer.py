"""Live layer: spatial indices, geohash, GeoMessage wire, Kafka cache/store,
Lambda two-tier merge.

Parity targets: geomesa-utils SpatialIndex/GeoHash, geomesa-kafka
KafkaDataStore/GeoMessage, geomesa-lambda LambdaDataStore [upstream,
unverified] — semantics tested against brute-force/NumPy oracles, per the
reference's TestGeoMesaDataStore idea (SURVEY.md §4).
"""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import parse_wkt, point
from geomesa_tpu.kafka import (
    Change,
    Clear,
    Delete,
    GeoMessageSerializer,
    InProcessBroker,
    KafkaDataStore,
    KafkaFeatureCache,
)
from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.plan.query import Query
from geomesa_tpu.utils import geohash
from geomesa_tpu.utils.spatial_index import BucketIndex, SizeSeparatedBucketIndex

SFT = SimpleFeatureType.from_spec(
    "live", "name:String,score:Double,dtg:Date,*geom:Point"
)


def _batch(n=200, seed=0):
    rng = np.random.default_rng(seed)
    return FeatureBatch.from_pydict(
        SFT,
        {
            "name": rng.choice(["a", "b", "c"], n).tolist(),
            "score": rng.uniform(-5, 5, n),
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack(
                [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], 1
            ),
        },
        fids=[f"f{i}" for i in range(n)],
    )


class TestBucketIndex:
    def test_insert_query_remove(self):
        rng = np.random.default_rng(0)
        xs, ys = rng.uniform(-180, 180, 500), rng.uniform(-90, 90, 500)
        idx = BucketIndex()
        for i, (x, y) in enumerate(zip(xs, ys)):
            idx.insert(f"k{i}", x, y, i)
        assert len(idx) == 500
        bbox = (-30.0, -20.0, 40.0, 50.0)
        got = sorted(v for _, v in idx.query(bbox))
        want = sorted(
            int(i)
            for i in np.nonzero(
                (xs >= bbox[0]) & (xs <= bbox[2]) & (ys >= bbox[1]) & (ys <= bbox[3])
            )[0]
        )
        assert got == want
        # upsert moves the entry
        idx.insert("k0", 0.0, 0.0, 999)
        assert idx.get("k0") == 999
        assert len(idx) == 500
        assert idx.remove("k0") == 999
        assert idx.get("k0") is None
        assert len(idx) == 499

    def test_query_all_and_clear(self):
        idx = BucketIndex()
        idx.insert("a", 0, 0, 1)
        idx.insert("b", 10, 10, 2)
        assert sorted(v for _, v in idx.query(None)) == [1, 2]
        idx.clear()
        assert len(idx) == 0


class TestSizeSeparated:
    def test_extended_geometries_found(self):
        idx = SizeSeparatedBucketIndex()
        # a large polygon whose center is far from the query box but which
        # overlaps it — plain center-binned BucketIndex would miss this
        idx.insert("big", (-50.0, -50.0, 50.0, 50.0), "big")
        idx.insert("small", (0.0, 0.0, 0.5, 0.5), "small")
        idx.insert("far", (100.0, 60.0, 101.0, 61.0), "far")
        got = sorted(v for _, v in idx.query((40.0, 40.0, 45.0, 45.0)))
        assert got == ["big"]
        got = sorted(v for _, v in idx.query((-1.0, -1.0, 1.0, 1.0)))
        assert got == ["big", "small"]
        assert idx.remove("big") == "big"
        assert sorted(v for _, v in idx.query((40.0, 40.0, 45.0, 45.0))) == []


class TestGeoHash:
    def test_known_values(self):
        # public reference vectors
        assert geohash.encode_one(-5.6, 42.6, 5) == "ezs42"
        assert geohash.encode_one(-0.1257, 51.5074, 7) == "gcpvj0s"

    def test_round_trip(self):
        rng = np.random.default_rng(3)
        lon = rng.uniform(-180, 180, 50)
        lat = rng.uniform(-90, 90, 50)
        for g, x, y in zip(geohash.encode(lon, lat, 9), lon, lat):
            bx = geohash.decode_bbox(str(g))
            assert bx[0] <= x <= bx[2] and bx[1] <= y <= bx[3]

    def test_neighbors_share_edge(self):
        for n in geohash.neighbors("ezs42"):
            a, b = geohash.decode_bbox("ezs42"), geohash.decode_bbox(n)
            # neighbor cells touch the cell's bbox
            assert a[0] <= b[2] + 1e-9 and a[2] >= b[0] - 1e-9
            assert a[1] <= b[3] + 1e-9 and a[3] >= b[1] - 1e-9

    def test_bboxes_cover(self):
        cells = geohash.bboxes_for((-10, -10, 10, 10), 2)
        rng = np.random.default_rng(4)
        for x, y in zip(rng.uniform(-10, 10, 30), rng.uniform(-10, 10, 30)):
            assert geohash.encode_one(x, y, 2) in cells


class TestGeoMessage:
    def test_change_round_trip(self):
        ser = GeoMessageSerializer(SFT)
        msg = Change(
            "id-1",
            {"name": "alpha", "score": 2.5, "dtg": 1_595_000_000_000,
             "geom": point(2.35, 48.85)},
        )
        out = ser.deserialize(ser.serialize(msg))
        assert isinstance(out, Change)
        assert out.fid == "id-1"
        assert out.attributes["name"] == "alpha"
        assert out.attributes["score"] == 2.5
        assert out.attributes["geom"].point == (2.35, 48.85)

    def test_nulls_and_polygon(self):
        sft = SimpleFeatureType.from_spec("p", "name:String,*geom:Polygon")
        ser = GeoMessageSerializer(sft)
        poly = parse_wkt("POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))")
        out = ser.deserialize(ser.serialize(Change("a", {"name": None, "geom": poly})))
        assert out.attributes["name"] is None
        assert out.attributes["geom"] == poly

    def test_delete_clear(self):
        ser = GeoMessageSerializer(SFT)
        assert ser.deserialize(ser.serialize(Delete("x"))).fid == "x"
        assert isinstance(ser.deserialize(ser.serialize(Clear())), Clear)


class TestKafkaCache:
    def test_upsert_latest_wins(self):
        cache = KafkaFeatureCache(SFT)
        cache.apply(Change("f1", {"name": "a", "score": 1.0,
                                  "dtg": 1_595_000_000_000, "geom": point(0, 0)}))
        cache.apply(Change("f1", {"name": "b", "score": 2.0,
                                  "dtg": 1_595_000_000_000, "geom": point(10, 10)}))
        assert len(cache) == 1
        assert cache.get("f1")["name"] == "b"
        assert [f for f, _ in cache.query_bbox((5, 5, 15, 15))] == ["f1"]
        assert cache.query_bbox((-5, -5, 5, 5)) == []

    def test_events_and_clear(self):
        cache = KafkaFeatureCache(SFT)
        events = []
        cache.add_listener(events.append)
        cache.apply(Change("f1", {"name": "a", "score": 1.0,
                                  "dtg": 0, "geom": point(0, 0)}))
        cache.apply(Delete("f1"))
        cache.apply(Clear())
        assert [e.kind for e in events] == ["changed", "removed", "cleared"]

    def test_expiry(self):
        cache = KafkaFeatureCache(SFT, expiry_ms=1)
        cache.apply(Change("f1", {"name": "a", "score": 1.0,
                                  "dtg": 0, "geom": point(0, 0)}))
        import time

        assert cache.expire(now=time.time() + 1.0) == 1
        assert len(cache) == 0

    def test_snapshot_caching(self):
        cache = KafkaFeatureCache(SFT)
        assert cache.snapshot() is None
        cache.apply(Change("f1", {"name": "a", "score": 1.0,
                                  "dtg": 0, "geom": point(1, 2)}))
        s1 = cache.snapshot()
        assert s1 is cache.snapshot()  # clean -> same object
        cache.apply(Change("f2", {"name": "b", "score": 2.0,
                                  "dtg": 0, "geom": point(3, 4)}))
        s2 = cache.snapshot()
        assert s2 is not s1 and len(s2) == 2


class TestKafkaDataStore:
    def test_write_query_live(self):
        ds = KafkaDataStore()
        src = ds.create_schema(SFT)
        batch = _batch(300)
        src.write(batch)
        res = src.get_features(Query("live", "BBOX(geom, -90, -45, 90, 45) AND score > 0"))
        gc = batch.geometry
        s = np.asarray(batch.column("score"))
        want = int(np.sum((gc.x >= -90) & (gc.x <= 90) & (gc.y >= -45)
                          & (gc.y <= 45) & (s > 0)))
        assert len(res.features) == want
        assert src.get_count("INCLUDE") == 300

    def test_upsert_and_delete_via_topic(self):
        ds = KafkaDataStore()
        src = ds.create_schema(SFT)
        src.write(_batch(10))
        ds.delete("live", "f0")
        assert src.get_count("INCLUDE") == 9
        ds.clear("live")
        assert src.get_count("INCLUDE") == 0

    def test_two_consumers_one_broker(self):
        broker = InProcessBroker()
        writer = KafkaDataStore(broker=broker)
        reader = KafkaDataStore(broker=broker)
        writer.create_schema(SFT)
        rsrc = reader.create_schema(SFT)
        writer.write("live", _batch(25))
        assert rsrc.get_count("INCLUDE") == 25

    def test_density_hint_over_live(self):
        from geomesa_tpu.plan.hints import QueryHints

        ds = KafkaDataStore()
        src = ds.create_schema(SFT)
        src.write(_batch(100))
        q = Query("live", "INCLUDE",
                  hints=QueryHints(density_bbox=(-180, -90, 180, 90),
                                   density_width=16, density_height=16))
        res = src.get_features(q)
        assert res.kind == "density"
        assert res.grid.sum() == pytest.approx(100.0)


class TestLambdaStore:
    def test_two_tier_merge_and_persist(self, tmp_path):
        from geomesa_tpu.lambda_store import LambdaDataStore

        lds = LambdaDataStore(str(tmp_path / "cat"), persist_after_ms=60_000)
        lds.create_schema(SFT)
        lds.write("live", _batch(50, seed=1))
        q = Query("live", "INCLUDE")
        assert lds.get_count(q) == 50
        # nothing old enough yet
        assert lds.persist("live") == 0
        # force-persist everything by pretending time passed
        import time

        n = lds.persist("live", now=time.time() + 120.0)
        assert n == 50
        assert lds.transient.cache("live").snapshot() is None
        assert lds.get_count(q) == 50  # now served by the persistent tier

    def test_transient_wins_on_fid(self, tmp_path):
        from geomesa_tpu.lambda_store import LambdaDataStore

        lds = LambdaDataStore(str(tmp_path / "cat"), persist_after_ms=0)
        lds.create_schema(SFT)
        b = _batch(5, seed=2)
        lds.write("live", b)
        import time

        lds.persist("live", now=time.time() + 1.0)
        # re-write f0 with a new score into the transient tier
        upd = FeatureBatch.from_pydict(
            SFT,
            {"name": ["zz"], "score": [99.0], "dtg": [0], "geom": np.array([[1.0, 2.0]])},
            fids=["f0"],
        )
        lds.write("live", upd)
        res = lds.get_features(Query("live", "INCLUDE"))
        assert len(res.features) == 5
        fids = res.features.fids.decode()
        scores = np.asarray(res.features.column("score"))
        assert scores[fids.index("f0")] == pytest.approx(99.0)


class TestLayerViews:
    def _store(self):
        import numpy as np

        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.kafka.store import KafkaDataStore

        rng = np.random.default_rng(8)
        n = 120
        sft = SimpleFeatureType.from_spec(
            "live", "actor:String,score:Double,dtg:Date,*geom:Point"
        )
        batch = FeatureBatch.from_pydict(
            sft,
            {
                "actor": rng.choice(["USA", "FRA"], n).tolist(),
                "score": rng.uniform(-10, 10, n),
                "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
                "geom": np.stack(
                    [rng.uniform(-60, 60, n), rng.uniform(-40, 40, n)], 1
                ),
            },
        )
        ds = KafkaDataStore()
        src = ds.create_schema(sft)
        src.write(batch)
        return ds, src, batch

    def test_view_filters_and_projects(self):
        import numpy as np

        ds, src, batch = self._store()
        view = ds.create_layer_view(
            "usa_only", "live", "actor = 'USA'", attributes=["actor", "score"]
        )
        actors = np.array(batch.columns["actor"].decode())
        assert view.get_count("INCLUDE") == int((actors == "USA").sum())
        r = view.get_features("score > 0")
        scores = np.asarray(batch.columns["score"])
        assert r.count == int(((actors == "USA") & (scores > 0)).sum())
        assert list(r.features.sft.attribute_names) == ["actor", "score"]

    def test_view_read_only_and_live(self):
        import numpy as np
        import pytest as _pytest

        from geomesa_tpu.core.columnar import FeatureBatch

        ds, src, batch = self._store()
        view = ds.create_layer_view("v", "live", "actor = 'FRA'")
        before = view.get_count()
        with _pytest.raises(TypeError):
            view.write(batch)
        # new writes to the base flow into the view
        sub = batch.select(np.arange(5))
        fra = FeatureBatch(
            sub.sft,
            {**sub.columns, "actor": type(sub.columns["actor"]).encode(["FRA"] * 5)},
            type(sub.columns["actor"]).encode([f"new-{i}" for i in range(5)]),
            sub.valid,
        )
        src.write(fra)
        assert view.get_count() == before + 5


class TestAgeOff:
    def test_kv_age_off(self):
        import numpy as np

        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.index import KVDataStore

        sft = SimpleFeatureType.from_spec("t", "v:Integer,dtg:Date,*geom:Point")
        now = 1_600_000_000_000
        dtg = np.array([now - 10_000, now - 5_000, now - 500, now - 100])
        batch = FeatureBatch.from_pydict(
            sft, {"v": [1, 2, 3, 4], "dtg": dtg, "geom": np.zeros((4, 2))}
        )
        ds = KVDataStore()
        src = ds.create_schema(sft)
        src.write(batch)
        removed = src.age_off(ttl_ms=1_000, now_ms=now)
        assert removed == 2
        assert src.live_count == 2
        r = src.get_features("v > 0")
        assert sorted(np.asarray(r.features.columns["v"]).tolist()) == [3, 4]


class TestArrowMerge:
    def test_dictionary_unification(self):
        import numpy as np

        from geomesa_tpu.core.arrow_io import from_arrow, merge_record_batches, to_arrow
        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType

        sft = SimpleFeatureType.from_spec("t", "name:String,*geom:Point")
        b1 = FeatureBatch.from_pydict(
            sft, {"name": ["a", "b", "a"], "geom": np.zeros((3, 2))}
        )
        b2 = FeatureBatch.from_pydict(
            sft, {"name": ["c", "b"], "geom": np.ones((2, 2))}
        )
        merged = merge_record_batches([to_arrow(b1), to_arrow(b2)])
        out = from_arrow(merged)
        assert len(out) == 5
        assert out.columns["name"].decode() == ["a", "b", "a", "c", "b"]


class TestAttributeIndexing:
    """CQEngine-analog attribute hash index in the live cache
    (SURVEY.md:323-324, round-1 missing #6)."""

    SFT_IDX = SimpleFeatureType.from_spec(
        "live2", "name:String:index=true,score:Double,dtg:Date,*geom:Point"
    )

    def _store(self, n=150):
        rng = np.random.default_rng(4)
        ds = KafkaDataStore()
        src = ds.create_schema(self.SFT_IDX)
        batch = FeatureBatch.from_pydict(
            self.SFT_IDX,
            {
                "name": rng.choice(["a", "b", "c"], n).tolist(),
                "score": rng.uniform(-5, 5, n),
                "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
                "geom": np.stack(
                    [rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)], 1
                ),
            },
            fids=[f"f{i}" for i in range(n)],
        )
        src.write(batch)
        return ds, src, batch

    def test_equality_served_from_index(self):
        ds, src, batch = self._store()
        cache = ds.cache("live2")
        assert cache.indexed_attributes == ["name"]
        before = cache.attr_index_hits
        r = src.get_features("name = 'a'")
        assert cache.attr_index_hits == before + 1, "full scan not avoided"
        names = np.array(batch.columns["name"].decode())
        assert len(r.features) == int((names == "a").sum())
        assert set(r.features.columns["name"].decode()) == {"a"}
        # IN rides the index too
        r = src.get_features("name IN ('a', 'b')")
        assert cache.attr_index_hits == before + 2
        assert len(r.features) == int(np.isin(names, ["a", "b"]).sum())

    def test_index_tracks_upsert_delete(self):
        ds, src, batch = self._store(n=10)
        cache = ds.cache("live2")
        names = batch.columns["name"].decode()
        # overwrite f0 with a new name: old value must leave the index
        from geomesa_tpu.core.wkt import point

        ds.write("live2", FeatureBatch.from_pydict(
            self.SFT_IDX,
            {"name": ["zzz"], "score": [1.0],
             "dtg": [1_595_000_000_000], "geom": [point(0.0, 0.0)]},
            fids=["f0"],
        ))
        ds.delete("live2", "f1")
        ds.poll("live2")
        r = src.get_features("name = 'zzz'")
        assert r.features is not None and r.features.fids.decode() == ["f0"]
        old0 = src.get_features(f"name = '{names[0]}'")
        got = [] if old0.features is None else old0.features.fids.decode()
        assert "f0" not in got and "f1" not in got

    def test_non_indexed_and_hinted_queries_bypass(self):
        ds, src, batch = self._store()
        cache = ds.cache("live2")
        before = cache.attr_index_hits
        # score is not indexed: planner path, parity preserved
        r = src.get_features("score > 0")
        assert cache.attr_index_hits == before
        scores = np.asarray(batch.column("score"))
        assert len(r.features) == int((scores > 0).sum())
        # hinted queries must not shortcut (hints change the result KIND)
        r = src.get_features(Query("live2", "name = 'a'", hints=QueryHints(
            density_bbox=(-180, -90, 180, 90),
            density_width=8, density_height=8)))
        assert r.kind == "density"
        assert cache.attr_index_hits == before


class TestVisibilitySecurity:
    """Feature-level visibility folded into every mask + per-attribute
    redaction folded into projection (SURVEY.md C21, :464)."""

    def _store(self, tmp_path):
        from geomesa_tpu.plan.datastore import DataStore

        sft = SimpleFeatureType.from_spec(
            "sec",
            "name:String,level:Double:visibility=admin,vis:String,"
            "dtg:Date,*geom:Point;geomesa.vis.attr=vis",
        )
        rng = np.random.default_rng(11)
        n = 60
        batch = FeatureBatch.from_pydict(
            sft,
            {
                "name": [f"n{i}" for i in range(n)],
                "level": rng.uniform(0, 9, n),
                "vis": (["admin"] * 20 + ["admin&usa"] * 20 + [None] * 20),
                "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
                "geom": np.stack(
                    [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1
                ),
            },
        )
        ds = DataStore(str(tmp_path / "sec"))
        ds.create_schema(sft).write(batch)
        return ds, batch

    def test_feature_level_masking_all_kinds(self, tmp_path):
        ds, batch = self._store(tmp_path)
        src = ds.get_feature_source("sec")
        # no auths: only the 20 public rows
        assert src.get_count(
            Query("sec", "INCLUDE", hints=QueryHints(exact_count=True))
        ) == 20
        q = Query("sec", "INCLUDE", hints=QueryHints(auths=("admin",)))
        assert src.get_count(q) == 40
        q = Query("sec", "INCLUDE", hints=QueryHints(auths=("admin", "usa")))
        assert src.get_count(q) == 60
        # density mass respects visibility too
        q = Query("sec", "INCLUDE", hints=QueryHints(
            auths=("admin",), density_bbox=(-180, -90, 180, 90),
            density_width=8, density_height=8))
        assert int(round(float(src.get_features(q).grid.sum()))) == 40

    def test_attribute_redaction(self, tmp_path):
        ds, batch = self._store(tmp_path)
        src = ds.get_feature_source("sec")
        q = Query("sec", "INCLUDE", hints=QueryHints(auths=("admin", "usa")))
        r = src.get_features(q)
        lv = np.asarray(r.features.column("level"))
        assert np.isfinite(lv).all()  # admin sees the column
        q2 = Query("sec", "INCLUDE", hints=QueryHints(auths=("usa",)))
        r2 = src.get_features(q2)
        lv2 = np.asarray(r2.features.column("level"))
        assert np.isnan(lv2).all(), "unauthorized attribute not redacted"
        # arrow export redacts identically
        import io

        import pyarrow as pa

        q3 = Query("sec", "INCLUDE", hints=QueryHints(
            auths=("usa",), arrow_encode=True))
        t = pa.ipc.open_stream(
            io.BytesIO(src.get_features(q3).arrow_bytes)).read_all()
        vals = t.column("level").to_numpy(zero_copy_only=False)
        assert np.isnan(vals).all()

    def test_aggregations_refuse_protected_attributes(self, tmp_path):
        # stats/bin/density-weight over a visibility-protected attribute
        # must refuse, not stream protected values (round-2 review leak)
        ds, batch = self._store(tmp_path)
        src = ds.get_feature_source("sec")
        q = Query("sec", "INCLUDE", hints=QueryHints(
            auths=("usa",), stats_string="MinMax(level)"))
        with pytest.raises(PermissionError, match="level"):
            src.get_features(q)
        q = Query("sec", "INCLUDE", hints=QueryHints(
            auths=("usa",), density_bbox=(-180, -90, 180, 90),
            density_width=8, density_height=8, density_weight="level"))
        with pytest.raises(PermissionError, match="level"):
            src.get_features(q)
        # authorized auths pass
        q = Query("sec", "INCLUDE", hints=QueryHints(
            auths=("admin",), stats_string="MinMax(level)"))
        assert src.get_features(q).kind == "stats"

    def test_int_attribute_redaction_drops_column(self, tmp_path):
        # ints have no null: redaction drops the column instead of
        # fabricating zeros (round-2 review)
        from geomesa_tpu.plan.datastore import DataStore

        sft = SimpleFeatureType.from_spec(
            "seci", "name:String,code:Integer:visibility=admin,*geom:Point"
        )
        rng = np.random.default_rng(2)
        batch = FeatureBatch.from_pydict(sft, {
            "name": ["a", "b"], "code": [7, 9],
            "geom": rng.uniform(-10, 10, (2, 2))})
        ds = DataStore(str(tmp_path / "seci"))
        ds.create_schema(sft).write(batch)
        src = ds.get_feature_source("seci")
        r = src.get_features(Query("seci", "INCLUDE",
                                   hints=QueryHints(auths=())))
        assert "code" not in r.features.columns
        r = src.get_features(Query("seci", "INCLUDE",
                                   hints=QueryHints(auths=("admin",))))
        assert np.asarray(r.features.column("code")).tolist() == [7, 9]

    def test_live_fast_path_declines_visibility_types(self, tmp_path):
        # the kafka attribute index has no auth awareness: visibility-
        # configured types always take the planner path (round-2 review
        # leak fix)
        sft = SimpleFeatureType.from_spec(
            "secl",
            "name:String:index=true,vis:String,*geom:Point;"
            "geomesa.vis.attr=vis",
        )
        rng = np.random.default_rng(3)
        n = 20
        batch = FeatureBatch.from_pydict(sft, {
            "name": ["a"] * 10 + ["b"] * 10,
            "vis": ["admin"] * 10 + [None] * 10,
            "geom": rng.uniform(-10, 10, (n, 2))},
            fids=[f"f{i}" for i in range(n)])
        kds = KafkaDataStore()
        src = kds.create_schema(sft)
        src.write(batch)
        cache = kds.cache("secl")
        r = src.get_features("name = 'a'")
        assert cache.attr_index_hits == 0, "fast path leaked protected rows"
        # name='a' rows are all admin-protected: invisible without auths
        got = 0 if r.features is None else len(r.features)
        assert got == 0

    def test_nonexact_count_respects_visibility(self, tmp_path):
        # the manifest-count shortcut must not leak the true row count
        # (round-2 review: exact_count=False returned 60 to auths=())
        ds, batch = self._store(tmp_path)
        src = ds.get_feature_source("sec")
        q = Query("sec", "INCLUDE", hints=QueryHints(exact_count=False))
        assert src.get_count(q) == 20
        q = Query("sec", "INCLUDE",
                  hints=QueryHints(exact_count=False, auths=("admin",)))
        assert src.get_count(q) == 40

    def test_z3histogram_stats_auth(self, tmp_path):
        # Z3Histogram reads a second (dtg) attribute: protect it too
        from geomesa_tpu.plan.datastore import DataStore

        sft = SimpleFeatureType.from_spec(
            "secz", "name:String,dtg:Date:visibility=admin,*geom:Point"
        )
        rng = np.random.default_rng(5)
        n = 10
        batch = FeatureBatch.from_pydict(sft, {
            "name": [f"n{i}" for i in range(n)],
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-10, 10, n),
                              rng.uniform(-10, 10, n)], 1)})
        ds = DataStore(str(tmp_path / "secz"))
        ds.create_schema(sft).write(batch)
        src = ds.get_feature_source("secz")
        q = Query("secz", "INCLUDE", hints=QueryHints(
            auths=(), stats_string="Z3Histogram(geom,dtg,week,4)"))
        with pytest.raises(PermissionError, match="dtg"):
            src.get_features(q)


class TestFastPathAudit:
    def test_attr_fast_path_writes_audit(self):
        from geomesa_tpu.plan.audit import AuditWriter

        sft = SimpleFeatureType.from_spec(
            "aud", "name:String:index=true,*geom:Point"
        )
        rng = np.random.default_rng(6)
        audit = AuditWriter()
        ds = KafkaDataStore(audit=audit)
        src = ds.create_schema(sft)
        src.write(FeatureBatch.from_pydict(sft, {
            "name": ["a", "b", "a"],
            "geom": rng.uniform(-10, 10, (3, 2))},
            fids=["f0", "f1", "f2"]))
        before = len(audit.events)
        r = src.get_features("name = 'a'")
        assert ds.cache("aud").attr_index_hits == 1  # fast path taken
        assert len(audit.events) == before + 1
        ev = audit.events[-1]
        assert ev.result_count == 2 and "name" in ev.filter

    def test_merged_view_aggregations(self, tmp_path):
        # round-3 (VERDICT #9): density/stats hints over the merged
        # two-tier view — deduped transient-wins, then the standard hint
        # dispatcher; parity vs aggregating the merged features directly
        from geomesa_tpu.lambda_store import LambdaDataStore
        from geomesa_tpu.plan.hints import QueryHints

        lds = LambdaDataStore(str(tmp_path / "cat"), persist_after_ms=0)
        lds.create_schema(SFT)
        b = _batch(40, seed=5)
        lds.write("live", b)
        import time

        lds.persist("live", now=time.time() + 1.0)
        # newer transient rows, one overwriting a persisted fid
        upd = FeatureBatch.from_pydict(
            SFT,
            {"name": ["a", "b"], "score": [5.0, 7.0], "dtg": [0, 0],
             "geom": np.array([[1.0, 2.0], [3.0, 4.0]])},
            fids=["f0", "new1"],
        )
        lds.write("live", upd)

        bbox = (-180.0, -90.0, 180.0, 90.0)
        q = Query("live", "INCLUDE", hints=QueryHints(
            density_bbox=bbox, density_width=32, density_height=32))
        res = lds.get_features(q)
        assert res.kind == "density"
        # merged view: 40 persisted + 1 new - 0 (f0 dedupe keeps count) = 41
        assert res.count == 41
        assert res.grid.sum() == pytest.approx(41.0)

        qs = Query("live", "INCLUDE", hints=QueryHints(
            stats_string="MinMax(score)"))
        rs = lds.get_features(qs)
        assert rs.kind == "stats"
        merged = lds.get_features(Query("live", "INCLUDE")).features
        sc = np.asarray(merged.column("score"))
        mm = rs.stats.stats[0]
        assert mm.result()[0] == pytest.approx(sc.min())
        assert mm.result()[1] == pytest.approx(sc.max())
