"""GT07..GT12 concurrency rule tests: for every rule a fixture module
with a seeded violation (asserting exact rule codes and lines) and a
clean twin, the pre-fix serving-path true positives replayed against
faithful excerpts, the waiver-validation / severity-config channels,
and the SARIF output shape."""

import json
import os
import textwrap

import pytest

from geomesa_tpu.analysis import lint_paths, render_sarif
from geomesa_tpu.analysis.linter import exit_code

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_src(tmp_path, source, name="mod.py", rules=None, **kw):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(tmp_path)], rules=rules,
                      extra_ref_paths=[], **kw)


def active(findings):
    return [f for f in findings if not f.waived]


def codes_lines(findings):
    return {(f.rule, f.line) for f in active(findings)}


# -- GT07: inconsistent lock discipline --------------------------------------


class TestGT07LockDiscipline:
    def test_unguarded_read_of_guarded_field(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}
                    self.total = 0

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v
                        self.total += 1

                def peek(self, k):
                    return self._items.get(k)
        """)
        got = codes_lines(fs)
        assert ("GT07", 15) in got          # unguarded read in peek
        assert all(f.rule == "GT07" for f in active(fs))

    def test_container_mutation_without_lock_in_lock_owner(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}
                    self._watchers = []

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def watch(self, fn):
                    self._watchers.append(fn)
        """)
        assert ("GT07", 14) in codes_lines(fs)

    def test_clean_when_all_accesses_guarded(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v

                def peek(self, k):
                    with self._lock:
                        return self._items.get(k)
        """)
        assert not [f for f in active(fs) if f.rule == "GT07"]

    def test_guard_only_helper_and_init_only_field_are_exempt(
            self, tmp_path):
        # _flush is only ever called with the lock held; `limit` is
        # written only in __init__ — neither may fire
        fs = lint_src(tmp_path, """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}
                    self.limit = 64

                def put(self, k, v):
                    with self._lock:
                        self._items[k] = v
                        if len(self._items) > self.limit:
                            self._flush()

                def _flush(self):
                    self._items.clear()
        """)
        assert not [f for f in active(fs) if f.rule == "GT07"]

    def test_locking_decorator_counts_as_guarded(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import functools
            import threading

            def _locked(fn):
                @functools.wraps(fn)
                def wrapper(self, *a, **kw):
                    with self._lock:
                        return fn(self, *a, **kw)
                return wrapper

            class Box:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._items = {}

                @_locked
                def put(self, k, v):
                    self._items[k] = v

                def peek(self, k):
                    return self._items.get(k)
        """)
        got = [f for f in active(fs) if f.rule == "GT07"]
        assert len(got) == 1 and got[0].line == 21  # only the bare peek


# -- GT08: lock-order cycles -------------------------------------------------


class TestGT08LockOrder:
    def test_module_lock_cycle(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def ab():
                with lock_a:
                    with lock_b:
                        pass

            def ba():
                with lock_b:
                    with lock_a:
                        pass
        """)
        gt08 = [f for f in active(fs) if f.rule == "GT08"]
        assert len(gt08) == 2               # one per edge of the cycle
        assert {f.line for f in gt08} == {8, 13}
        assert "deadlock" in gt08[0].message

    def test_consistent_order_is_clean(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            lock_a = threading.Lock()
            lock_b = threading.Lock()

            def ab():
                with lock_a:
                    with lock_b:
                        pass

            def ab2():
                with lock_a:
                    with lock_b:
                        pass
        """)
        assert not [f for f in active(fs) if f.rule == "GT08"]

    def test_cycle_through_typed_field_call(self, tmp_path):
        # Outer holds its lock and calls into Inner (which locks); Inner
        # calls back into Outer under ITS lock -> cycle across classes.
        # The back-reference is typed via a local annotation (the
        # kafka-store `cache: KafkaFeatureCache = ...` idiom).
        fs = lint_src(tmp_path, """\
            import threading

            class Inner:
                def __init__(self, outer):
                    self._lock = threading.Lock()
                    self._outer = outer

                def poke(self):
                    with self._lock:
                        outer: Outer = self._outer
                        outer.report(1)

            class Outer:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.inner = Inner(self)

                def run(self):
                    with self._lock:
                        self.inner.poke()

                def report(self, n):
                    with self._lock:
                        pass
        """)
        gt08 = [f for f in active(fs) if f.rule == "GT08"]
        assert gt08, "typed-field cycle not detected"
        assert any("Inner._lock" in f.message and "Outer._lock"
                   in f.message for f in gt08)


# -- GT09: blocking call under a lock ----------------------------------------


class TestGT09BlockingUnderLock:
    def test_open_and_sleep_under_lock(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading
            import time

            class Saver:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = []

                def save(self, path):
                    with self._lock:
                        time.sleep(0.1)
                        with open(path, "w") as f:
                            f.write(str(self.rows))
        """)
        got = codes_lines(fs)
        assert ("GT09", 11) in got   # sleep
        assert ("GT09", 12) in got   # open

    def test_snapshot_then_io_outside_lock_is_clean(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            class Saver:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = []

                def save(self, path):
                    with self._lock:
                        snap = list(self.rows)
                    with open(path, "w") as f:
                        f.write(str(snap))
        """)
        assert not [f for f in active(fs) if f.rule == "GT09"]

    def test_condition_wait_on_own_lock_is_clean(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._not_empty = threading.Condition(self._lock)
                    self.items = []

                def pop(self):
                    with self._lock:
                        while not self.items:
                            self._not_empty.wait()
                        return self.items.pop()
        """)
        assert not [f for f in active(fs) if f.rule == "GT09"]

    def test_jitted_dispatch_under_lock(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading
            import jax

            @jax.jit
            def kern(x):
                return x + 1

            def use(x):
                kern(x)

            class Runner:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.out = []

                def run(self, x):
                    with self._lock:
                        self.out.append(kern(x))
        """)
        gt09 = [f for f in active(fs) if f.rule == "GT09"]
        assert [f.line for f in gt09] == [18]
        assert "kern" in gt09[0].message


# -- GT10: per-call lock -----------------------------------------------------


class TestGT10PerCallLock:
    def test_function_local_lock_guards_nothing(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            class Counter:
                def __init__(self):
                    self.n = 0

                def bump(self):
                    lock = threading.Lock()
                    with lock:
                        self.n += 1
        """)
        gt10 = [f for f in active(fs) if f.rule == "GT10"]
        assert [f.line for f in gt10] == [8]

    def test_orchestrator_closure_lock_is_clean(self, tmp_path):
        # jobs.ingest_files shape: the per-call lock is shared with the
        # worker closures this function spawns — legitimate
        fs = lint_src(tmp_path, """\
            import threading

            def run_all(items, fn):
                lock = threading.Lock()
                out = []

                def work(it):
                    r = fn(it)
                    with lock:
                        out.append(r)

                ts = [threading.Thread(target=work, args=(i,))
                      for i in items]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                return out
        """)
        assert not [f for f in active(fs) if f.rule == "GT10"]


# -- GT11: callback / set_result under a lock --------------------------------


class TestGT11CallbackUnderLock:
    def test_set_result_and_callback_param_under_lock(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            class Notifier:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.pending = []

                def resolve(self, fut, value):
                    with self._lock:
                        fut.set_result(value)

                def drain(self, on_item):
                    with self._lock:
                        for item in self.pending:
                            on_item(item)
        """)
        got = codes_lines(fs)
        assert ("GT11", 10) in got
        assert ("GT11", 15) in got

    def test_resolve_outside_lock_is_clean(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            class Notifier:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.pending = []

                def drain(self, on_item):
                    with self._lock:
                        items = list(self.pending)
                        self.pending.clear()
                    for item in items:
                        on_item(item)
        """)
        assert not [f for f in active(fs) if f.rule == "GT11"]

    def test_listener_loop_under_lock(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            class Bus:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._listeners = []

                def emit(self, event):
                    with self._lock:
                        for cb in self._listeners:
                            cb(event)
        """)
        assert any(f.rule == "GT11" and f.line == 11 for f in active(fs))


# -- GT12: unguarded shared mutable state ------------------------------------


class TestGT12SharedState:
    def test_mutable_default_mutated(self, tmp_path):
        fs = lint_src(tmp_path, """\
            def collect(x, acc=[]):
                acc.append(x)
                return acc
        """)
        assert ("GT12", 1) in codes_lines(fs)

    def test_mutable_default_never_mutated_is_clean(self, tmp_path):
        fs = lint_src(tmp_path, """\
            def view(xs=()):
                return list(xs)

            def read(cfg={}):
                return cfg.get("x")
        """)
        assert not [f for f in active(fs) if f.rule == "GT12"]

    def test_module_global_mutated_from_thread(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            EVENTS = []

            def record(e):
                EVENTS.append(e)

            def start():
                t = threading.Thread(target=record, args=(1,))
                t.start()
                return t
        """)
        gt12 = [f for f in active(fs) if f.rule == "GT12"]
        assert [f.line for f in gt12] == [6]
        assert "EVENTS" in gt12[0].message

    def test_module_global_under_lock_is_clean(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            EVENTS = []
            _lock = threading.Lock()

            def record(e):
                with _lock:
                    EVENTS.append(e)

            def start():
                t = threading.Thread(target=record, args=(1,))
                t.start()
                return t
        """)
        assert not [f for f in active(fs) if f.rule == "GT12"]

    def test_lockfree_class_reached_from_thread(self, tmp_path):
        fs = lint_src(tmp_path, """\
            import threading

            class Buffer:
                def __init__(self):
                    self.items = []

                def add(self, x):
                    self.items.append(x)

            def pump(buf):
                buf.add(1)

            def start(buf):
                t = threading.Thread(target=pump, args=(buf,))
                t.start()
                return t
        """)
        gt12 = [f for f in active(fs) if f.rule == "GT12"]
        assert [f.line for f in gt12] == [8]
        assert "Buffer" in gt12[0].message

    def test_unreached_class_is_clean(self, tmp_path):
        fs = lint_src(tmp_path, """\
            class Buffer:
                def __init__(self):
                    self.items = []

                def add(self, x):
                    self.items.append(x)

            def use():
                b = Buffer()
                b.add(1)
                return len(b.items)
        """)
        assert not [f for f in active(fs) if f.rule == "GT12"]


# -- pre-fix serving-path true positives, replayed ---------------------------


class TestPreFixReplays:
    """Faithful excerpts of the concurrency bugs this PR fixed in the
    serving/store path, each verified detected (they are the GT07/GT12
    seed true positives; the fixes landed in the same PR)."""

    def test_gt07_catches_stats_manager_count(self, tmp_path):
        # plan/stats_manager.py pre-fix: every estimate is under the
        # RLock except the `count` property
        fs = lint_src(tmp_path, """\
            import functools
            import threading

            def _locked(fn):
                @functools.wraps(fn)
                def wrapper(self, *args, **kwargs):
                    with self._lock:
                        return fn(self, *args, **kwargs)
                return wrapper

            class StatsManager:
                def __init__(self, storage):
                    self.storage = storage
                    self.stats = {}
                    self._lock = threading.RLock()

                @_locked
                def refresh(self):
                    self.stats = {}

                @_locked
                def update(self, batch):
                    self.refresh()
                    self.stats["count"] = batch

                @property
                def count(self):
                    s = self.stats.get("count")
                    return int(s.count) if s is not None else None
        """)
        gt07 = [f for f in active(fs) if f.rule == "GT07"]
        assert len(gt07) == 1
        assert gt07[0].line == 28
        assert "'stats'" in gt07[0].message

    def test_gt12_catches_audit_writer_buffer(self, tmp_path):
        # plan/audit.py pre-fix: the dispatch thread and client threads
        # share one AuditWriter; append + trim had no lock
        fs = lint_src(tmp_path, """\
            import threading

            class AuditWriter:
                def __init__(self, max_events=100000):
                    self.max_events = max_events
                    self.events = []

                def write(self, event):
                    self.events.append(event)
                    if len(self.events) > self.max_events:
                        del self.events[: len(self.events) - self.max_events]

            class QueryService:
                def __init__(self, audit):
                    self.audit = audit
                    self._worker = None

                def start(self):
                    self._worker = threading.Thread(target=self._loop)
                    self._worker.start()

                def _loop(self):
                    self.audit.write({"kind": "knn"})
        """)
        gt12 = [f for f in active(fs) if f.rule == "GT12"]
        assert [f.line for f in gt12] == [9]
        assert "AuditWriter" in gt12[0].message
        assert "'events'" in gt12[0].message

    def test_gt12_catches_planner_compile_cache(self, tmp_path):
        # plan/planner.py pre-fix: the compiled-filter cache (getattr
        # lazy init + clear + insert) mutated from the dispatch thread
        # and direct callers with no lock
        fs = lint_src(tmp_path, """\
            import threading

            def compile_filter(residual, sft):
                return object()

            class QueryPlanner:
                def __init__(self, storage):
                    self.storage = storage

                def _compile_cached(self, residual, sft):
                    key = str(residual)
                    cached = getattr(self, "_compiled_filters", None)
                    if cached is None:
                        cached = self._compiled_filters = {}
                    if key not in cached:
                        if len(cached) > 256:
                            cached.clear()
                        cached[key] = compile_filter(residual, sft)
                    return cached[key]

                def execute(self, query):
                    return self._compile_cached(query, self.storage)

            class Service:
                def start(self, planner):
                    t = threading.Thread(target=self._loop,
                                         args=(planner,))
                    t.start()

                def _loop(self, planner):
                    planner.execute("INCLUDE")
        """)
        gt12 = [f for f in active(fs) if f.rule == "GT12"]
        assert gt12 and gt12[0].rule == "GT12"
        assert "_compiled_filters" in gt12[0].message
        # anchors at a mutation site inside _compile_cached
        assert gt12[0].line in (14, 17, 18)


# -- waiver validation + severity config -------------------------------------


class TestWaiverValidation:
    def test_unknown_rule_in_waiver_file_errors(self, tmp_path):
        (tmp_path / "mod.py").write_text("x = 1\n")
        wf = tmp_path / "waivers.txt"
        wf.write_text("mod.py GT99\n")
        with pytest.raises(ValueError, match="unknown rule code"):
            lint_paths([str(tmp_path)], extra_ref_paths=[],
                       waiver_file=str(wf))

    def test_unknown_rule_in_inline_waiver_errors(self, tmp_path):
        (tmp_path / "mod.py").write_text(
            "x = 1  # gt: waive GT99\n")
        with pytest.raises(ValueError, match="unknown rule code"):
            lint_paths([str(tmp_path)], extra_ref_paths=[])

    def test_severity_override_changes_gate(self, tmp_path):
        (tmp_path / "mod.py").write_text(textwrap.dedent("""\
            import jax

            @jax.jit
            def dead_kernel(x):
                return x + 1
        """))
        wf = tmp_path / "waivers.txt"
        wf.write_text("severity GT05 info\n")
        fs = lint_paths([str(tmp_path)], extra_ref_paths=[],
                        waiver_file=str(wf))
        gt05 = [f for f in fs if f.rule == "GT05"]
        assert gt05 and all(f.severity == "info" for f in gt05)
        assert exit_code(fs, "warn") == 0   # info no longer gates
        assert exit_code(fs, "info") == 1

    def test_malformed_severity_line_errors(self, tmp_path):
        (tmp_path / "mod.py").write_text("x = 1\n")
        wf = tmp_path / "waivers.txt"
        wf.write_text("severity GT05 loud\n")
        with pytest.raises(ValueError, match="severity"):
            lint_paths([str(tmp_path)], extra_ref_paths=[],
                       waiver_file=str(wf))


# -- SARIF output ------------------------------------------------------------


class TestSarif:
    def test_sarif_shape_and_suppressions(self, tmp_path):
        (tmp_path / "mod.py").write_text(textwrap.dedent("""\
            import jax

            @jax.jit
            def dead_kernel(x):
                return x + 1

            @jax.jit
            def waived_kernel(x):  # gt: waive GT05
                return x + 2
        """))
        fs = lint_paths([str(tmp_path)], extra_ref_paths=[])
        doc = json.loads(render_sarif(fs))
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "gmtpu-lint"
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {"GT01", "GT07", "GT12"} <= rule_ids
        results = run["results"]
        live = [r for r in results if "suppressions" not in r]
        waived = [r for r in results if "suppressions" in r]
        assert len(live) == 1 and live[0]["ruleId"] == "GT05"
        loc = live[0]["locations"][0]["physicalLocation"]
        # out-of-repo fixture scans carry absolute paths; in-repo runs
        # are repo-relative (see test_lint_gate_sarif_mode)
        assert loc["artifactLocation"]["uri"].endswith("mod.py")
        assert loc["region"]["startLine"] == 4
        assert len(waived) == 1
        assert waived[0]["suppressions"][0]["kind"] == "inSource"

    def test_lint_gate_sarif_mode(self):
        import subprocess
        import sys

        gate = os.path.join(REPO_ROOT, "scripts", "lint_gate.py")
        r = subprocess.run([sys.executable, gate, "--format", "sarif"],
                           capture_output=True, text=True, cwd=REPO_ROOT)
        assert r.returncode == 0, r.stdout + r.stderr
        doc = json.loads(r.stdout)
        # the shipped tree is clean: every emitted result is suppressed
        assert all("suppressions" in res
                   for res in doc["runs"][0]["results"])


# -- self-lint: the shipped tree under the concurrency pass ------------------


class TestConcurrencySelfLint:
    def test_shipped_tree_clean_under_gt07_gt12(self):
        fs = lint_paths(
            [os.path.join(REPO_ROOT, "geomesa_tpu")],
            rules=["GT07", "GT08", "GT09", "GT10", "GT11", "GT12"])
        bad = active(fs)
        assert not bad, "\n".join(f.render() for f in bad)
        # the deliberate designs ride on waivers, so the channel itself
        # is exercised: device-cache persistence/upload under its lock
        # (GT09), the scheduler's atomic pop+mark callback (GT11), and
        # the documented single-thread-by-construction classes (GT12)
        waived_rules = {f.rule for f in fs if f.waived}
        assert {"GT09", "GT11", "GT12"} <= waived_rules
