"""Space-filling curve tests: round-trips, covering guarantees, golden vectors.

Mirrors the reference's pure-unit tier (SURVEY.md §4): Z3SFCTest-style
round-trip and range-cover correctness, plus known-answer Morton vectors.
"""

import numpy as np
import pytest

from geomesa_tpu.curve import (
    XZ2SFC,
    XZ3SFC,
    Z2SFC,
    Z3SFC,
    BinnedTime,
    TimePeriod,
    deinterleave2,
    deinterleave3,
    interleave2,
    interleave3,
    zranges,
)
from geomesa_tpu.curve.binned_time import (
    bin_to_epoch_millis,
    bins_for_interval,
    max_offset_seconds,
    to_binned_time,
)

rng = np.random.default_rng(42)


class TestMorton:
    def test_golden_2d(self):
        # x=5 (101), y=3 (011), x on even bits: y2x2 y1x1 y0x0 = 011011 = 27
        assert int(interleave2(5, 3)) == 0b011011
        assert int(interleave2(0, 0)) == 0
        assert int(interleave2(1, 0)) == 1
        assert int(interleave2(0, 1)) == 2
        assert int(interleave2(1, 1)) == 3
        assert int(interleave2(2**31 - 1, 2**31 - 1)) == 2**62 - 1

    def test_golden_3d(self):
        assert int(interleave3(1, 0, 0)) == 1
        assert int(interleave3(0, 1, 0)) == 2
        assert int(interleave3(0, 0, 1)) == 4
        assert int(interleave3(1, 1, 1)) == 7
        assert int(interleave3(2**21 - 1, 2**21 - 1, 2**21 - 1)) == 2**63 - 1

    def test_roundtrip_2d(self):
        x = rng.integers(0, 2**31, size=1000)
        y = rng.integers(0, 2**31, size=1000)
        z = interleave2(x, y)
        rx, ry = deinterleave2(z)
        np.testing.assert_array_equal(rx, x)
        np.testing.assert_array_equal(ry, y)

    def test_roundtrip_3d(self):
        x = rng.integers(0, 2**21, size=1000)
        y = rng.integers(0, 2**21, size=1000)
        t = rng.integers(0, 2**21, size=1000)
        z = interleave3(x, y, t)
        rx, ry, rt = deinterleave3(z)
        np.testing.assert_array_equal(rx, x)
        np.testing.assert_array_equal(ry, y)
        np.testing.assert_array_equal(rt, t)

    def test_ordering_locality(self):
        # z of (x, y) and (x+1, y) in the same quad share high bits
        assert int(interleave2(4, 4)) // 16 == int(interleave2(5, 5)) // 16


class TestZ2:
    def test_index_invert_roundtrip(self):
        sfc = Z2SFC()
        lon = rng.uniform(-180, 180, size=500)
        lat = rng.uniform(-90, 90, size=500)
        z = sfc.index(lon, lat)
        rlon, rlat = sfc.invert(z)
        # within half a cell
        assert np.max(np.abs(rlon - lon)) <= 360.0 / 2**31
        assert np.max(np.abs(rlat - lat)) <= 180.0 / 2**31

    def test_ranges_cover(self):
        sfc = Z2SFC()
        box = (-10.0, -10.0, 10.0, 10.0)
        ranges = sfc.ranges(*box, max_ranges=500)
        assert ranges
        # every point in the box must fall in some range (covering guarantee)
        lon = rng.uniform(box[0], box[2], size=300)
        lat = rng.uniform(box[1], box[3], size=300)
        z = sfc.index(lon, lat)
        for zi in z:
            assert any(r.lower <= int(zi) <= r.upper for r in ranges)

    def test_ranges_exclude_far_points(self):
        sfc = Z2SFC()
        ranges = sfc.ranges(-10, -10, 10, 10, max_ranges=2000)
        # a far-away point should not be inside (tight covering)
        z = int(sfc.index(120.0, 70.0))
        assert not any(r.lower <= z <= r.upper for r in ranges)

    def test_more_ranges_is_tighter(self):
        sfc = Z2SFC()
        coarse = sfc.ranges(-10, -10, 10, 10, max_ranges=16)
        fine = sfc.ranges(-10, -10, 10, 10, max_ranges=2000)
        size = lambda rs: sum(r.upper - r.lower + 1 for r in rs)
        assert size(fine) <= size(coarse)


class TestBinnedTime:
    def test_week_bins(self):
        # 1970-01-01 was Thursday; epoch is in ISO week starting Mon 1969-12-29
        b, off = to_binned_time(np.int64(0), TimePeriod.WEEK)
        assert int(b) == 0
        assert float(off) == 4 * 86400.0  # Thu is 4 days after Mon

    def test_day_bins(self):
        ms = np.int64(86400_000 * 3 + 3600_000)
        b, off = to_binned_time(ms, TimePeriod.DAY)
        assert int(b) == 3 and float(off) == 3600.0

    def test_month_year(self):
        ms = np.int64(np.datetime64("2020-03-15T12:00:00", "ms").astype(np.int64))
        b, off = to_binned_time(ms, TimePeriod.MONTH)
        assert int(b) == (2020 - 1970) * 12 + 2
        assert float(off) == 14 * 86400.0 + 12 * 3600.0
        b, off = to_binned_time(ms, TimePeriod.YEAR)
        assert int(b) == 50

    def test_bin_start_roundtrip(self):
        for period in TimePeriod:
            ms = int(np.datetime64("2021-06-05T00:00:00", "ms").astype(np.int64))
            b, off = to_binned_time(np.int64(ms), period)
            start = bin_to_epoch_millis(int(b), period)
            assert start + float(off) * 1000 == ms

    def test_bins_for_interval(self):
        start = int(np.datetime64("2020-01-01T00:00:00", "ms").astype(np.int64))
        end = int(np.datetime64("2020-01-20T00:00:00", "ms").astype(np.int64))
        bins = bins_for_interval(start, end, TimePeriod.WEEK)
        assert len(bins) == 4  # spans 4 ISO weeks
        assert bins[0][1] > 0  # first bin starts mid-week
        assert bins[-1][2] < max_offset_seconds(TimePeriod.WEEK)


class TestZ3:
    def test_roundtrip(self):
        sfc = Z3SFC("week")
        lon = rng.uniform(-180, 180, size=200)
        lat = rng.uniform(-90, 90, size=200)
        t = rng.integers(1_500_000_000_000, 1_600_000_000_000, size=200)
        bins, z = sfc.index(lon, lat, t)
        rlon, rlat, roff = sfc.invert(z)
        assert np.max(np.abs(rlon - lon)) <= 360.0 / 2**21
        assert np.max(np.abs(rlat - lat)) <= 180.0 / 2**21

    def test_ranges_cover(self):
        sfc = Z3SFC("week")
        t0 = int(np.datetime64("2020-06-01T00:00:00", "ms").astype(np.int64))
        t1 = int(np.datetime64("2020-06-10T00:00:00", "ms").astype(np.int64))
        per_bin = sfc.ranges(-20, -20, 20, 20, t0, t1, max_ranges=4000)
        lon = rng.uniform(-20, 20, size=200)
        lat = rng.uniform(-20, 20, size=200)
        t = rng.integers(t0, t1, size=200)
        bins, z = sfc.index(lon, lat, t)
        for b, zi in zip(bins, z):
            ranges = per_bin[int(b)]
            assert any(r.lower <= int(zi) <= r.upper for r in ranges), (b, zi)


class TestZRangesGeneric:
    def test_full_domain(self):
        rs = zranges((0, 0), (2**31 - 1, 2**31 - 1), 31)
        assert len(rs) == 1
        assert rs[0].lower == 0 and rs[0].upper == 2**62 - 1

    def test_single_cell(self):
        rs = zranges((5, 3), (5, 3), 4, max_ranges=10000)
        z = int(interleave2(5, 3))
        assert any(r.lower <= z <= r.upper for r in rs)

    def test_covering_3d(self):
        mins, maxs = (100, 200, 300), (150, 260, 310)
        rs = zranges(mins, maxs, 21, max_ranges=300)
        for _ in range(100):
            p = [int(rng.integers(mins[d], maxs[d] + 1)) for d in range(3)]
            z = int(interleave3(*p))
            assert any(r.lower <= z <= r.upper for r in rs)


class TestXZ2:
    def test_point_like_max_resolution(self):
        sfc = XZ2SFC(g=12)
        code = sfc.index(10.0, 10.0, 10.0, 10.0)
        assert code > 0

    def test_query_finds_indexed_boxes(self):
        sfc = XZ2SFC(g=12)
        # boxes inside the query window must be found
        query = (-20.0, -20.0, 20.0, 20.0)
        ranges = sfc.ranges(*query, max_ranges=2000)
        for _ in range(100):
            x0 = rng.uniform(-19, 18)
            y0 = rng.uniform(-19, 18)
            w = rng.uniform(0.001, 1.0)
            code = sfc.index(x0, y0, x0 + w, y0 + w)
            assert any(r.lower <= code <= r.upper for r in ranges), (x0, y0, w)

    def test_query_finds_overlapping_boxes(self):
        sfc = XZ2SFC(g=12)
        query = (0.0, 0.0, 10.0, 10.0)
        ranges = sfc.ranges(*query, max_ranges=2000)
        # a box straddling the query edge must also be found
        code = sfc.index(-5.0, -5.0, 5.0, 5.0)
        assert any(r.lower <= code <= r.upper for r in ranges)
        # a big box containing the whole query must be found
        code = sfc.index(-50.0, -50.0, 50.0, 50.0)
        assert any(r.lower <= code <= r.upper for r in ranges)

    def test_disjoint_box_excluded(self):
        sfc = XZ2SFC(g=12)
        ranges = sfc.ranges(0.0, 0.0, 10.0, 10.0, max_ranges=2000)
        code = sfc.index(100.0, 50.0, 101.0, 51.0)
        assert not any(r.lower <= code <= r.upper for r in ranges)


class TestXZ3:
    def test_query_finds_indexed_boxes(self):
        sfc = XZ3SFC("week", g=8)
        t0 = int(np.datetime64("2020-06-01T00:00:00", "ms").astype(np.int64))
        t1 = int(np.datetime64("2020-06-03T00:00:00", "ms").astype(np.int64))
        per_bin = sfc.ranges(-20, -20, 20, 20, t0, t1, max_ranges=4000)
        for _ in range(50):
            x0 = rng.uniform(-19, 18)
            y0 = rng.uniform(-19, 18)
            ts = int(rng.integers(t0, t1 - 3600_000))
            b, code = sfc.index(x0, y0, x0 + 0.5, y0 + 0.5, ts, ts + 3600_000)
            assert b in per_bin
            assert any(r.lower <= code <= r.upper for r in per_bin[b]), (x0, y0, ts)
