"""Multi-host (multi-process DCN) smoke test.

C26 validation: the same sharded kernels run over a mesh that SPANS OS
processes, with collectives crossing the process boundary over the
distributed runtime (Gloo/gRPC on CPU here; DCN on real pods) — so
"multi-host by construction" becomes "multi-host demonstrated".

Gated on GEOMESA_TPU_MULTIHOST=1: spawning jax.distributed workers takes
~30-60s and needs free localhost ports, which not every CI sandbox allows.
Run explicitly with:

    GEOMESA_TPU_MULTIHOST=1 python -m pytest tests/test_multihost.py -q
"""

import os

import pytest


@pytest.mark.skipif(
    os.environ.get("GEOMESA_TPU_MULTIHOST") != "1",
    reason="set GEOMESA_TPU_MULTIHOST=1 to run the 2-process DCN smoke",
)
def test_two_process_smoke():
    from geomesa_tpu.parallel.launch import launch_local

    assert launch_local(2, port=29517) == 0
