"""st_* spatial SQL function tests (geomesa-spark-jts parity surface)."""

import numpy as np
import pytest

from geomesa_tpu import sql
from geomesa_tpu.core.wkt import parse_wkt


SQUARE = parse_wkt("POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))")
HOLED = parse_wkt(
    "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (4 4, 6 4, 6 6, 4 6, 4 4))"
)
LINE = parse_wkt("LINESTRING (0 0, 3 4)")


class TestConstructorsAccessors:
    def test_point(self):
        p = sql.st_point(2.0, 3.0)
        assert (sql.st_x(p), sql.st_y(p)) == (2.0, 3.0)
        assert sql.st_geometryType(p) == "Point"

    def test_bbox_and_envelope(self):
        b = sql.st_makeBBOX(0, 0, 4, 2)
        assert sql.st_bbox(b) == (0, 0, 4, 2)
        assert sql.st_bbox(sql.st_envelope(SQUARE)) == (0, 0, 10, 10)

    def test_wkt_round_trip(self):
        g = sql.st_geomFromWKT("POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))")
        assert sql.st_geomFromText(sql.st_asText(g)) == g

    def test_line_builders(self):
        pts = [sql.st_point(0, 0), sql.st_point(1, 0), sql.st_point(1, 1)]
        line = sql.st_makeLine(pts)
        assert sql.st_numPoints(line) == 3
        assert sql.st_pointN(line, 2).point == (1.0, 0.0)
        assert sql.st_pointN(line, -1).point == (1.0, 1.0)
        poly = sql.st_makePolygon(line)
        assert "Polygon" in sql.st_geometryType(poly)


class TestMeasures:
    def test_area(self):
        assert sql.st_area(SQUARE) == pytest.approx(100.0)
        assert sql.st_area(HOLED) == pytest.approx(96.0)
        assert sql.st_area(LINE) == 0.0

    def test_length(self):
        assert sql.st_length(LINE) == pytest.approx(5.0)
        assert sql.st_length(SQUARE) == pytest.approx(40.0)  # perimeter

    def test_length_sphere(self):
        # 1 degree of longitude at the equator ~ 111.19 km
        l = parse_wkt("LINESTRING (0 0, 1 0)")
        assert sql.st_lengthSphere(l) == pytest.approx(111_195, rel=1e-3)

    def test_centroid(self):
        c = sql.st_centroid(SQUARE)
        assert c.point == (pytest.approx(5.0), pytest.approx(5.0))

    def test_distance(self):
        a = sql.st_point(0, 0)
        b = sql.st_point(3, 4)
        assert sql.st_distance(a, b) == pytest.approx(5.0)
        # point to polygon edge
        p = sql.st_point(15, 5)
        assert sql.st_distance(p, SQUARE) == pytest.approx(5.0)
        assert sql.st_distance(sql.st_point(5, 5), SQUARE) == 0.0

    def test_distance_sphere(self):
        paris = sql.st_point(2.35, 48.85)
        london = sql.st_point(-0.1257, 51.5074)
        assert sql.st_distanceSphere(paris, london) == pytest.approx(
            343_000, rel=0.02
        )


class TestPredicates:
    def test_contains_point(self):
        assert sql.st_contains(SQUARE, sql.st_point(5, 5))
        assert not sql.st_contains(SQUARE, sql.st_point(15, 5))
        assert not sql.st_contains(HOLED, sql.st_point(5, 5))  # in the hole

    def test_contains_columnar(self):
        xs = np.array([5.0, 15.0, 5.0])
        ys = np.array([5.0, 5.0, 5.0])
        m = sql.st_contains(SQUARE, xs, ys)
        assert m.tolist() == [True, False, True]
        mh = sql.st_contains(HOLED, xs, ys)
        assert mh.tolist() == [False, False, False]

    def test_within(self):
        inner = parse_wkt("POLYGON ((2 2, 4 2, 4 4, 2 4, 2 2))")
        assert sql.st_within(inner, SQUARE)
        assert not sql.st_within(SQUARE, inner)

    def test_intersects_disjoint(self):
        other = parse_wkt("POLYGON ((5 5, 15 5, 15 15, 5 15, 5 5))")
        far = parse_wkt("POLYGON ((20 20, 30 20, 30 30, 20 30, 20 20))")
        assert sql.st_intersects(SQUARE, other)
        assert sql.st_disjoint(SQUARE, far)
        assert sql.st_intersects(SQUARE, LINE)

    def test_crossing_polygons_without_contained_vertices(self):
        # a tall thin rect crossing a wide flat rect: no vertex of either
        # inside the other — only the edge test catches this
        tall = parse_wkt("POLYGON ((4 -5, 6 -5, 6 15, 4 15, 4 -5))")
        assert sql.st_intersects(SQUARE, tall)
        assert sql.st_crosses(SQUARE, tall)

    def test_touches_overlaps(self):
        adjacent = parse_wkt("POLYGON ((10 0, 20 0, 20 10, 10 10, 10 0))")
        overlapping = parse_wkt("POLYGON ((5 0, 15 0, 15 10, 5 10, 5 0))")
        assert sql.st_touches(SQUARE, adjacent)
        assert not sql.st_overlaps(SQUARE, adjacent)
        assert sql.st_overlaps(SQUARE, overlapping)

    def test_dwithin(self):
        a = sql.st_point(0, 0)
        assert sql.st_dwithin(a, sql.st_point(3, 4), 5.01)
        assert not sql.st_dwithin(a, sql.st_point(3, 4), 4.99)
        xs = np.array([0.0, 1.0])
        ys = np.array([0.0, 1.0])
        m = sql.st_dwithin(a, xs, ys, dist=1.0)
        assert m.tolist() == [True, False]
        mm = sql.st_dwithin(a, xs, ys, dist=200_000.0, meters=True)
        assert mm.tolist() == [True, True]

    def test_equals(self):
        assert sql.st_equals(SQUARE, parse_wkt(sql.st_asText(SQUARE)))
        assert not sql.st_equals(SQUARE, HOLED)


class TestProcessors:
    def test_translate(self):
        t = sql.st_translate(sql.st_point(1, 2), 2, 3)
        assert t.point == (3.0, 5.0)
        ts = sql.st_translate(SQUARE, 1, 1)
        assert sql.st_bbox(ts) == (1, 1, 11, 11)

    def test_convex_hull(self):
        cloud = parse_wkt("MULTIPOINT ((0 0), (4 0), (4 4), (0 4), (2 2), (1 1))")
        hull = sql.st_convexHull(cloud)
        assert sql.st_area(hull) == pytest.approx(16.0)
        assert sql.st_contains(hull, sql.st_point(2, 2))

    def test_registry(self):
        fns = sql.register()
        assert "st_contains" in fns and fns["st_point"](1, 2).point == (1.0, 2.0)
        assert len(fns) >= 30


def test_st_area_multipolygon_parts():
    g = parse_wkt(
        "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), ((5 5, 6 5, 6 6, 5 6, 5 5)))"
    )
    assert sql.st_area(g) == pytest.approx(2.0)
    c = sql.st_centroid(g)
    assert c.point == pytest.approx((3.0, 3.0))


def test_st_area_polygon_with_hole():
    g = parse_wkt(
        "POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0), (1 1, 2 1, 2 2, 1 2, 1 1))"
    )
    assert sql.st_area(g) == pytest.approx(15.0)


def test_st_touches_line_line():
    cross = (parse_wkt("LINESTRING (0 0, 2 2)"), parse_wkt("LINESTRING (0 2, 2 0)"))
    endpoint = (parse_wkt("LINESTRING (0 0, 1 1)"), parse_wkt("LINESTRING (1 1, 2 0)"))
    overlap = (parse_wkt("LINESTRING (0 0, 2 0)"), parse_wkt("LINESTRING (1 0, 3 0)"))
    assert not sql.st_touches(*cross)  # interiors cross
    assert sql.st_touches(*endpoint)  # endpoint only
    assert not sql.st_touches(*overlap)  # collinear interior overlap


class TestStBuffer:
    """st_buffer = d-level contour of the signed distance field
    (SURVEY.md:378 processor parity)."""

    def test_point_buffer_area(self):
        from geomesa_tpu.sql.functions import st_area, st_buffer, st_point

        b = st_buffer(st_point(10.0, 45.0), 2.0)
        assert b.kind == "Polygon"
        np.testing.assert_allclose(st_area(b), np.pi * 4, rtol=5e-3)

    def test_line_buffer_capsule(self):
        from geomesa_tpu.core.wkt import parse_wkt
        from geomesa_tpu.sql.functions import st_area, st_buffer

        b = st_buffer(parse_wkt("LINESTRING(0 0, 10 0)"), 1.0, resolution=128)
        np.testing.assert_allclose(st_area(b), 20 + np.pi, rtol=2e-2)

    def test_polygon_grow_shrink(self):
        from geomesa_tpu.core.wkt import parse_wkt
        from geomesa_tpu.sql.functions import st_area, st_buffer

        sq = parse_wkt("POLYGON((0 0, 10 0, 10 10, 0 10, 0 0))")
        np.testing.assert_allclose(
            st_area(st_buffer(sq, 1.0, resolution=128)),
            100 + 40 + np.pi, rtol=2e-2,
        )
        np.testing.assert_allclose(
            st_area(st_buffer(sq, -1.0, resolution=128)), 64.0, rtol=2e-2
        )

    def test_hole_preserved_and_shrunk(self):
        from geomesa_tpu.core.wkt import parse_wkt
        from geomesa_tpu.engine.pip import points_in_polygon_np
        from geomesa_tpu.sql.functions import st_area, st_buffer

        hp = parse_wkt(
            "POLYGON((0 0, 20 0, 20 20, 0 20, 0 0),"
            " (8 8, 12 8, 12 12, 8 12, 8 8))"
        )
        b = st_buffer(hp, 1.0, resolution=160)
        exp = 22 * 22 - 4 + np.pi - 4  # grown shell - shrunk 2x2 hole
        np.testing.assert_allclose(st_area(b), exp, rtol=2e-2)
        assert not points_in_polygon_np([10.0], [10.0], b)[0]
        assert points_in_polygon_np([5.0], [-0.5], b)[0]

    def test_multipoint_union_and_disjoint(self):
        from geomesa_tpu.core.wkt import parse_wkt
        from geomesa_tpu.sql.functions import st_area, st_buffer

        near = st_buffer(
            parse_wkt("MULTIPOINT((0 0), (1.5 0))"), 1.0, resolution=128
        )
        assert near.kind == "Polygon"  # overlapping circles union
        th = np.arccos(0.75)
        lens_area = 2 * (th - 0.75 * np.sin(th))
        np.testing.assert_allclose(
            st_area(near), 2 * np.pi - lens_area, rtol=2e-2
        )
        far = st_buffer(
            parse_wkt("MULTIPOINT((0 0), (10 0))"), 1.0, resolution=128
        )
        assert far.kind == "MultiPolygon"
        np.testing.assert_allclose(st_area(far), 2 * np.pi, rtol=2e-2)

    def test_degenerate_inputs_never_crash(self):
        from geomesa_tpu.core.wkt import Geometry, parse_wkt
        from geomesa_tpu.sql.functions import st_area, st_buffer

        assert st_area(st_buffer(parse_wkt("LINESTRING(0 0, 1 1)"), -0.5)) == 0
        assert st_area(st_buffer(Geometry("Polygon", []), 1.0)) == 0
        # shrink past extinction: empty, not garbage
        sq = parse_wkt("POLYGON((0 0, 1 0, 1 1, 0 1, 0 0))")
        assert st_area(st_buffer(sq, -5.0, resolution=64)) == 0

    def test_buffer_point_geodesic_high_latitude(self):
        from geomesa_tpu.core.wkt import parse_wkt
        from geomesa_tpu.engine.geodesy import haversine_m_np
        from geomesa_tpu.sql.functions import st_bufferPoint

        b = st_bufferPoint(parse_wkt("POINT(10 80)"), 10_000)
        v = b.rings[0][:-1]
        d = haversine_m_np(v[:, 0], v[:, 1], 10.0, 80.0)
        np.testing.assert_allclose(d, 10_000, rtol=1e-3)
