"""Stat sketch tests: merge laws, accuracy, DSL parsing, serialization."""

import numpy as np
import pytest

from geomesa_tpu.stats import (
    Cardinality,
    DescriptiveStats,
    EnumerationStat,
    Frequency,
    Histogram,
    MinMax,
    Stat,
    TopK,
    Z3HistogramStat,
    parse_stats,
)

rng = np.random.default_rng(5)


class TestMergeLaws:
    """merge(a, b) must equal observing the union — the property the
    cross-shard reduction tree relies on."""

    def test_minmax(self):
        v = rng.uniform(-100, 100, 1000)
        a, b, c = MinMax("x"), MinMax("x"), MinMax("x")
        a.observe(v[:500])
        b.observe(v[500:])
        c.observe(v)
        assert a.merge(b).result() == c.result()

    def test_descriptive(self):
        v = rng.uniform(-10, 10, 1000)
        a, b, c = DescriptiveStats("x"), DescriptiveStats("x"), DescriptiveStats("x")
        a.observe(v[:300])
        b.observe(v[300:])
        c.observe(v)
        got, exp = a.merge(b).result(), c.result()
        assert got["count"] == exp["count"]
        assert got["mean"] == pytest.approx(exp["mean"])
        assert got["variance"] == pytest.approx(exp["variance"])
        assert exp["mean"] == pytest.approx(v.mean())
        assert exp["variance"] == pytest.approx(v.var(ddof=1), rel=1e-6)

    def test_histogram(self):
        v = rng.uniform(0, 100, 2000)
        a, b, c = (Histogram("x", 10, 0, 100) for _ in range(3))
        a.observe(v[:1000]); b.observe(v[1000:]); c.observe(v)
        np.testing.assert_array_equal(a.merge(b).result(), c.result())

    def test_topk_and_enumeration(self):
        v = rng.choice(["a", "b", "c", "d"], 1000, p=[0.5, 0.3, 0.15, 0.05])
        a, b, c = TopK("x", 2), TopK("x", 2), TopK("x", 2)
        a.observe(v[:500]); b.observe(v[500:]); c.observe(v)
        assert a.merge(b).result() == c.result()
        assert c.result()[0][0] == "a"
        e = EnumerationStat("x")
        e.observe(v)
        assert sum(e.result().values()) == 1000

    def test_cardinality_merge_and_accuracy(self):
        vals = np.array([f"v{i}" for i in range(20_000)])
        a, b = Cardinality("x"), Cardinality("x")
        a.observe(vals[:10_000]); b.observe(vals[5_000:])  # overlapping
        est = a.merge(b).result()
        assert est == pytest.approx(20_000, rel=0.05)

    def test_frequency(self):
        v = np.array(["x"] * 700 + ["y"] * 200 + ["z"] * 100)
        a, b = Frequency("a"), Frequency("a")
        a.observe(v[:500]); b.observe(v[500:])
        a.merge(b)
        assert a.count("x") >= 700  # CM sketch overestimates only
        assert a.count("x") <= 1000
        assert a.count("zzz") <= 5

    def test_frequency_observe_counts(self):
        f = Frequency("a")
        f.observe_counts(["p", "q"], np.array([10, 3]))
        assert f.count("p") >= 10


class TestZ3Histogram:
    def test_observe_and_estimate(self):
        z = Z3HistogramStat("geom", "dtg", "week", 16)
        grid = np.zeros((16, 16), np.int64)
        grid[8, 8] = 100  # center cell: lon ~ 11.25, lat ~ 5.6
        z.observe_grid(2600, grid)
        assert z.estimate(-180, -90, 180, 90, [2600]) == 100
        assert z.estimate(0, 0, 22, 11, [2600]) == 100
        assert z.estimate(-90, -45, -60, -30, [2600]) == 0
        assert z.estimate(0, 0, 22, 11, [2601]) == 0

    def test_merge(self):
        a, b = Z3HistogramStat("g", "d"), Z3HistogramStat("g", "d")
        g = np.ones((16, 16), np.int64)
        a.observe_grid(1, g)
        b.observe_grid(1, g)
        b.observe_grid(2, g)
        a.merge(b)
        assert a.estimate(-180, -90, 180, 90, [1]) == 512
        assert a.estimate(-180, -90, 180, 90, [2]) == 256


class TestDSL:
    def test_parse(self):
        seq = parse_stats(
            "MinMax(dtg);Frequency(name);TopK(actor,5);"
            "Histogram(score,20,-10,10);Cardinality(id);DescriptiveStats(score)"
        )
        kinds = [s.kind for s in seq.stats]
        assert kinds == ["minmax", "frequency", "topk", "histogram",
                         "cardinality", "descriptive"]
        assert seq.stats[2].k == 5

    def test_parse_errors(self):
        with pytest.raises(ValueError):
            parse_stats("Bogus(x)")
        with pytest.raises(ValueError):
            parse_stats("Histogram(x)")


class TestSerialization:
    def test_roundtrip(self):
        v = rng.uniform(0, 10, 100)
        stats = [
            MinMax("a"), Histogram("a", 5, 0, 10), DescriptiveStats("a"),
        ]
        for s in stats:
            s.observe(v)
        t = TopK("s", 3)
        t.observe(np.array(["x", "y", "x"]))
        stats.append(t)
        c = Cardinality("s")
        c.observe(np.array(["p", "q"]))
        stats.append(c)
        for s in stats:
            s2 = Stat.from_json(s.to_json())
            r1, r2 = s.result(), s2.result()
            if isinstance(r1, np.ndarray):
                np.testing.assert_array_equal(r1, r2)
            else:
                assert r1 == r2


class TestDeviceSketchObservation:
    """Device-side hash+fold kernels (engine.stats.hll_registers /
    cms_table) must be bit-compatible with the host sketch pipeline —
    the merge laws only hold if both observers agree per value."""

    def test_hll_registers_match_host(self):
        import jax.numpy as jnp

        from geomesa_tpu.engine.stats import hll_registers
        from geomesa_tpu.stats.sketches import Cardinality

        rng = np.random.default_rng(3)
        for vals in (
            rng.integers(0, 10_000, 40_000),
            rng.uniform(-1000, 1000, 40_000),
        ):
            mask = rng.random(len(vals)) < 0.7
            host = Cardinality("a")
            host.observe(vals, mask)
            dev = Cardinality("a")
            dev.observe_registers(
                np.asarray(hll_registers(jnp.asarray(vals), jnp.asarray(mask)))
            )
            np.testing.assert_array_equal(dev.registers, host.registers)
            # merge law: folding device registers into a host-observed
            # sketch is a no-op when they saw the same values
            host.observe_registers(dev.registers)
            np.testing.assert_array_equal(dev.registers, host.registers)

    def test_cms_table_matches_numeric_keyed_host(self):
        import jax.numpy as jnp

        from geomesa_tpu.engine.stats import cms_table
        from geomesa_tpu.stats.sketches import Frequency

        rng = np.random.default_rng(5)
        vals = rng.integers(0, 50, 20_000)
        mask = rng.random(len(vals)) < 0.5
        host = Frequency("a", numeric_keys=True)
        host.observe(vals, mask)
        dev = Frequency("a", numeric_keys=True)
        dev.observe_table(
            np.asarray(cms_table(jnp.asarray(vals), jnp.asarray(mask)))
        )
        np.testing.assert_array_equal(dev.table, host.table)
        # point lookups over-estimate but never under-estimate
        true = np.bincount(vals[mask], minlength=50)
        for v in range(50):
            assert dev.count(v) >= true[v]

    def test_cms_keying_contract(self):
        import pytest as _pytest

        from geomesa_tpu.stats.sketches import Frequency, Stat

        s = Frequency("a")  # string-keyed
        with _pytest.raises(ValueError, match="numeric"):
            s.observe_table(np.zeros((4, 1024)))
        n = Frequency("a", numeric_keys=True)
        with _pytest.raises(ValueError, match="merge"):
            n.merge(s)
        # keying survives the JSON round trip
        j = Stat.from_json(n.to_json())
        assert j.numeric_keys is True

    def test_stats_scan_uses_device_hll(self, tmp_path):
        # end-to-end: a stats-scan over a numeric column produces the
        # same HLL estimate as a pure host observation
        import jax.numpy as jnp  # noqa: F401

        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.plan.datastore import DataStore
        from geomesa_tpu.plan.hints import QueryHints
        from geomesa_tpu.plan.query import Query
        from geomesa_tpu.stats.sketches import Cardinality

        rng = np.random.default_rng(11)
        n = 4000
        score = rng.integers(0, 500, n).astype(np.float64)
        sft = SimpleFeatureType.from_spec("t", "score:Double,*geom:Point")
        ds = DataStore(str(tmp_path / "cat"))
        src = ds.create_schema(sft)
        src.write(FeatureBatch.from_pydict(sft, {
            "score": score,
            "geom": np.stack([rng.uniform(-10, 10, n),
                              rng.uniform(-10, 10, n)], 1),
        }))
        r = src.get_features(Query(
            "t", "INCLUDE",
            hints=QueryHints(stats_string="Cardinality(score)"),
        ))
        got = [s for s in r.stats.stats if isinstance(s, Cardinality)]
        assert got, "stats scan returned no Cardinality sketch"
        host = Cardinality("score")
        host.observe(score)
        np.testing.assert_array_equal(got[0].registers, host.registers)
