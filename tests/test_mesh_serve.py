"""Sharded serving (docs/SERVING.md "Sharded serving"): CPU mesh parity.

The load-bearing claims, proven on a 4-device CPU mesh (conftest forces
an 8-device host platform):

- a coalesced kNN window dispatches as ONE sharded program across the
  mesh (service dispatch counters + the `knn.mesh.dispatches` metric +
  JitTracker over the engine jit caches), with per-query results
  BIT-identical to the single-chip serial path;
- count and density answers off the mesh residency tier are bit-
  identical to single-chip;
- shard-affinity admission routes a window whose pruned partitions all
  live on one chip to THAT chip's resident rows (the
  `knn.mesh.local_dispatches` route), again bit-identical;
- ServeEvents carry the mesh_shape/shards attribution the telemetry
  per-shard lanes slice on.

Budget note (tier-1 wall): ONE tiny 4-partition store (1024 rows), all
tests share its warm mesh programs — the mesh-keyed registry entries
compile once per process.
"""

import json

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.audit import ServeEvent
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.serve import QueryService, ServeConfig
from geomesa_tpu.utils.metrics import metrics

MESH_D = 4
ROWS_PER_DAY = 256
DAYS = ("2020-06-01", "2020-06-02", "2020-06-03", "2020-06-04")
CQL = "BBOX(geom, -170, -80, 170, 80) AND score > -5"
# prunes (DateTimeScheme yyyy/MM/dd) to day 3 = partition index 2 only
CQL_DAY3 = (
    "BBOX(geom, -170, -80, 170, 80) AND score > -5 AND "
    "dtg DURING 2020-06-03T00:00:00Z/2020-06-03T23:59:59Z"
)


def _day_millis(day: str) -> int:
    return int(np.datetime64(day, "ms").astype(np.int64))


def make_batch():
    """4 day-partitions x 256 rows: each partition pow2-pads to exactly
    256 rows, so under a 4-chip mesh (shard_rows = 1024/4 = 256)
    partition i is owned by shard i alone — the affinity fixture."""
    rng = np.random.default_rng(11)
    n = ROWS_PER_DAY * len(DAYS)
    dtg = np.concatenate([
        _day_millis(day)
        + rng.integers(6 * 3600_000, 18 * 3600_000, ROWS_PER_DAY)
        for day in DAYS
    ])
    sft = SimpleFeatureType.from_spec(
        "meshed", "name:String,score:Double,dtg:Date,*geom:Point")
    return sft, FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": dtg,
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })


@pytest.fixture(scope="module")
def catalog(tmp_path_factory):
    sft, batch = make_batch()
    root = str(tmp_path_factory.mktemp("mesh_serve"))
    ds = DataStore(root, use_device_cache=True)
    ds.create_schema(sft).write(batch)
    del ds
    return root


@pytest.fixture(scope="module")
def mesh_store(catalog):
    return DataStore(catalog, use_device_cache=True)


@pytest.fixture(scope="module")
def serial_store(catalog):
    """Independent single-chip store over the same files — the oracle
    the mesh answers must match bit-for-bit."""
    return DataStore(catalog, use_device_cache=True)


def _counter(name: str) -> float:
    return json.loads(metrics.to_json())["counters"].get(name, 0.0)


def _mesh_service(store, **kw) -> QueryService:
    return QueryService(
        store, ServeConfig(mesh=MESH_D, max_wait_ms=20.0, **kw),
        autostart=False)


def test_mesh_window_one_dispatch_bit_identical(mesh_store, serial_store):
    """>= 8 concurrent compatible kNN queries execute as ONE sharded
    program across the 4-chip mesh, bit-identical to serial single-chip
    runs of the same queries."""
    import geomesa_tpu.engine.knn_scan as knn_scan_mod

    from geomesa_tpu.analysis.runtime import JitTracker

    rng = np.random.default_rng(42)
    n_req = 10
    qpts = rng.uniform(-60, 60, (n_req, 2))

    serial_src = serial_store.get_feature_source("meshed")
    serial = [
        serial_src.knn(CQL, qpts[i:i + 1, 0], qpts[i:i + 1, 1], k=5)
        for i in range(n_req)
    ]

    svc = _mesh_service(mesh_store)
    assert svc.mesh is not None and int(svc.mesh.devices.size) == MESH_D
    # warm the mesh route at the SAME coalesced [Q] bucket (10 -> pow2
    # 16) so the dispatch-count run below measures dispatches, not
    # compiles (the registry entries persist process-wide)
    warm = [svc.knn("meshed", CQL, qpts[i:i + 1, 0] + 1.0,
                    qpts[i:i + 1, 1], k=5) for i in range(n_req)]
    svc.start()
    for f in warm:
        f.result(timeout=300)
    svc.close(drain=True)

    tracker = JitTracker()
    tracker.install(knn_scan_mod)
    try:
        base_mesh = _counter("knn.mesh.dispatches")
        svc = _mesh_service(mesh_store)
        futs = [
            svc.knn("meshed", CQL, qpts[i:i + 1, 0], qpts[i:i + 1, 1], k=5)
            for i in range(n_req)
        ]
        svc.start()
        results = [f.result(timeout=300) for f in futs]
        svc.close(drain=True)
        mesh_calls = sum(rec["calls"] for rec in tracker.report().values())
    finally:
        tracker.unwrap()

    # ONE coalesced window -> ONE mesh program dispatch; the engine's
    # module-level jit caches saw no per-request kernel launches at all
    # (the window ran through the mesh-keyed AOT registry entry)
    assert svc.stats()["dispatches"] == 1, svc.stats()
    assert _counter("knn.mesh.dispatches") - base_mesh == 1
    assert mesh_calls == 0, tracker.report()

    for (d, ix, _), (sd, six, _) in zip(results, serial):
        np.testing.assert_array_equal(ix, six)
        assert np.array_equal(d, sd), (d, sd)  # BIT-identical meters

    # attribution: every member's ServeEvent names the topology and the
    # owning shards (a whole-mesh window credits every chip)
    events = [e for e in mesh_store.audit.events[-n_req:]
              if isinstance(e, ServeEvent)]
    assert len(events) == n_req
    assert all(e.mesh_shape == f"({MESH_D},)" for e in events), events
    assert all(e.shards == "0,1,2,3" for e in events), events


def test_count_and_density_bit_identical(mesh_store, serial_store):
    serial_src = serial_store.get_feature_source("meshed")
    svc = _mesh_service(mesh_store)
    svc.start()
    try:
        cnt = svc.count("meshed", CQL).result(timeout=300)
        hints = QueryHints(density_bbox=(-170, -80, 170, 80),
                           density_width=32, density_height=32)
        dens = svc.query("meshed", CQL, hints=hints).result(timeout=300)
    finally:
        svc.close(drain=True)
    assert cnt == serial_src.get_count(CQL)
    from geomesa_tpu.plan.query import Query

    sgrid = serial_src.get_features(
        Query("meshed", CQL, hints=hints)).grid
    assert np.array_equal(np.asarray(dens.grid), np.asarray(sgrid))


def test_shard_affinity_routes_to_owner(mesh_store, serial_store):
    """A window whose pruned partitions live on ONE chip runs on that
    chip alone (no collectives), lands bit-identical, and its ServeEvent
    names the single owning shard."""
    svc = _mesh_service(mesh_store)
    svc.start()
    try:
        # residency is built by the first query; then the ownership map
        # must place each day-partition on exactly one shard
        svc.count("meshed", CQL).result(timeout=300)
        src = mesh_store.get_feature_source("meshed")
        sb = src.planner.cache.superbatch()
        assert sb.mesh is not None and sb.shard_rows == ROWS_PER_DAY
        owned = sorted(sb.owners.items())
        assert [o for _, o in owned] == [(0,), (1,), (2,), (3,)], owned

        rng = np.random.default_rng(7)
        qpts = rng.uniform(-60, 60, (1, 2))
        base_local = _counter("knn.mesh.local_dispatches")
        base_events = len(mesh_store.audit.events)
        d, ix, _ = svc.knn(
            "meshed", CQL_DAY3, qpts[:, 0], qpts[:, 1], k=5,
        ).result(timeout=300)
    finally:
        svc.close(drain=True)

    assert _counter("knn.mesh.local_dispatches") - base_local == 1
    events = [e for e in mesh_store.audit.events[base_events:]
              if isinstance(e, ServeEvent) and e.kind == "knn"]
    assert len(events) == 1
    # day 3 = partition index 2 = shard 2, and the window ran there alone
    assert events[0].shards == "2", events[0]
    assert events[0].mesh_shape == f"({MESH_D},)"

    serial_src = serial_store.get_feature_source("meshed")
    sd, six, _ = serial_src.knn(CQL_DAY3, qpts[:, 0], qpts[:, 1], k=5)
    np.testing.assert_array_equal(ix, six)
    assert np.array_equal(d, sd), (d, sd)


def test_admission_tags_affinity(mesh_store):
    """Admission computes the shard-affinity hint from metadata only
    (partition pruning + the cache's ownership map) once residency is
    warm — the routing signal the scheduler and telemetry lanes use."""
    svc = _mesh_service(mesh_store)
    svc.start()
    try:
        svc.count("meshed", CQL).result(timeout=300)  # residency warm
        base = _counter('serve.affinity.admitted{shards="2"}')
        svc.knn("meshed", CQL_DAY3, np.array([1.0]), np.array([2.0]),
                k=5).result(timeout=300)
    finally:
        svc.close(drain=True)
    assert _counter('serve.affinity.admitted{shards="2"}') - base == 1
