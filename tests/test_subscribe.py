"""geomesa_tpu.subscribe: standing queries over the Kafka live layer.

The load-bearing test is TestIncrementalParity: ≥8 mixed subscriptions
(bbox, dwithin, CQL-attribute, density windows) folded over ≥20 Kafka
batches, where after EVERY batch each subscription's incrementally
maintained matched set equals a fresh one-shot planner query over the
live snapshot (bit-identical fids; density grids allclose), the pushed
enter/exit event stream replays to exactly the diff of consecutive
snapshots (zero missed / duplicate / phantom events), and evaluation is
ONE coalesced device dispatch per poll with zero fused-kernel
recompiles once warm (evaluator dispatch counters + the AOT registry's
miss counter).

Wall-clock discipline (tier-1 budget is effectively full): one Kafka
store per test class, constant fid populations so snapshot shapes stay
in one pow2 bucket, and small density grids.
"""

import json
import time

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.kafka.cache import KafkaFeatureCache
from geomesa_tpu.kafka.store import KafkaDataStore
from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.plan.query import Query
from geomesa_tpu.serve.scheduler import QueryRejected
from geomesa_tpu.subscribe import (
    DensityWindow, SubscribeConfig, Subscription, SubscriptionManager,
    SubscriptionRegistry)

SFT = SimpleFeatureType.from_spec(
    "live", "name:String,score:Double,dtg:Date,*geom:Point"
)

N_FIDS = 48


def _rows(seed, fids):
    """Deterministic attribute rows for a set of fids."""
    rng = np.random.default_rng(seed)
    n = len(fids)
    return FeatureBatch.from_pydict(SFT, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-5, 5, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack([rng.uniform(-60, 60, n),
                          rng.uniform(-30, 30, n)], 1),
    }, fids=list(fids))


def _density_oracle(window: DensityWindow, batch) -> np.ndarray:
    """Host f64 grid over a snapshot, with the f32 cell binning the
    device kernels use (engine.density.density_grid arithmetic)."""
    grid = np.zeros((window.height, window.width), np.float64)
    if batch is None or len(batch) == 0:
        return grid
    g = SFT.default_geometry.name
    col_g = batch.columns[g]
    x32 = np.asarray(col_g.x, np.float32)
    y32 = np.asarray(col_g.y, np.float32)
    x0, y0, x1, y1 = window.bbox
    dx = np.float32((x1 - x0) / window.width)
    dy = np.float32((y1 - y0) / window.height)
    col = np.floor((x32 - np.float32(x0)) / dx).astype(np.int64)
    row = np.floor((y32 - np.float32(y0)) / dy).astype(np.int64)
    inb = ((col >= 0) & (col < window.width)
           & (row >= 0) & (row < window.height))
    w = (np.ones(len(batch), np.float64) if window.weight_attr is None
         else np.asarray(batch.columns[window.weight_attr], np.float64))
    np.add.at(grid, (row[inb], col[inb]), w[inb])
    return grid


class _EventLog:
    """Collects push frames and replays enter/exit streams per
    subscription, asserting zero duplicate/phantom transitions."""

    def __init__(self):
        self.frames = []

    def push(self, frame):
        self.frames.append(frame)

    def replay_matched(self, sub_id) -> set:
        state = set()
        for f in sorted((f for f in self.frames
                         if f.get("subscription") == sub_id
                         and f.get("event") in ("enter", "exit", "state")),
                        key=lambda f: f["seq"]):
            if f["event"] == "state":
                state = set(f["fids"])
            elif f["event"] == "enter":
                dup = set(f["fids"]) & state
                assert not dup, f"duplicate enter events for {dup}"
                state |= set(f["fids"])
            else:
                ghost = set(f["fids"]) - state
                assert not ghost, f"phantom exit events for {ghost}"
                state -= set(f["fids"])
        return state


class TestIncrementalParity:
    """The acceptance gate: incremental == one-shot, one dispatch per
    poll, event streams are exactly the snapshot diffs."""

    CQLS = [
        "BBOX(geom, -20, -15, 25, 20)",
        "BBOX(geom, -50, -25, -10, 5)",
        "DWITHIN(geom, POINT(10 5), 2000000, meters)",
        "DWITHIN(geom, POINT(-30 -10), 1500000, meters)",
        "name = 'a'",
        "score > 0 AND BBOX(geom, -40, -30, 40, 30)",
    ]
    WINDOWS = [
        DensityWindow((-60.0, -30.0, 60.0, 30.0), 16, 8),
        DensityWindow((-30.0, -20.0, 30.0, 20.0), 12, 10,
                      weight_attr="score"),
    ]

    def test_parity_over_20_batches(self):
        store = KafkaDataStore()
        src = store.create_schema(SFT)
        mgr = SubscriptionManager(store)
        subs = [mgr.subscribe("live", cql) for cql in self.CQLS]
        subs += [mgr.subscribe("live", density=w) for w in self.WINDOWS]
        assert len(subs) == 8
        log = _EventLog()
        from geomesa_tpu.compilecache.registry import registry as aot

        fids = [f"f{i}" for i in range(N_FIDS)]
        base_ev = mgr.evaluator.stats()
        base_misses = aot.stats()["misses"]
        polls_with_delta = 0
        warm_misses = None
        for b in range(20):
            if b == 0:
                store.write("live", _rows(1000, fids))     # seed all
            elif b == 7:
                for fid in fids[:3]:
                    store.delete("live", fid)              # shrink
            elif b == 8:
                store.write("live", _rows(2000 + b, fids[:3]))  # re-add
            elif b == 10:
                store.clear("live")                        # wipe
            elif b == 11:
                store.write("live", _rows(3000, fids))     # re-seed
            else:
                # moving fleet: half the population drifts each batch
                moving = [fids[(b * 7 + j) % N_FIDS] for j in range(24)]
                store.write("live", _rows(4000 + b, moving))
            applied = store.poll("live")
            assert applied > 0
            polls_with_delta += 1
            mgr.flush(log.push)
            snap = store.cache("live").snapshot()
            # one-shot parity: every predicate subscription's matched
            # set is bit-identical to a fresh planner query's fids
            for sub, cql in zip(subs[:6], self.CQLS):
                res = src.get_features(Query("live", cql))
                got = (set() if res.features is None
                       else set(res.features.fids.decode()))
                assert sub.matched == got, (
                    f"batch {b}: {cql!r} incremental != one-shot")
                # and the replayed event stream reconstructs it
                assert log.replay_matched(sub.sub_id) == got
            # density parity: grids allclose against the host oracle
            for sub, window in zip(subs[6:], self.WINDOWS):
                oracle = _density_oracle(window, snap)
                assert np.allclose(sub.grid, oracle, atol=1e-9), (
                    f"batch {b}: density window diverged "
                    f"(max err {np.abs(sub.grid - oracle).max()})")
        ev = mgr.evaluator.stats()
        d_folds = ev["folds"] - base_ev["folds"]
        d_disp = ev["dispatches"] - base_ev["dispatches"]
        # three coalesced device dispatches per poll — the bbox lane,
        # the dwithin lane, and the fused remainder (attribute/compound
        # predicates + both density windows) — independent of how many
        # subscriptions each lane carries; the two windows with no
        # changed rows (b=7 deletes-only, b=10 clear-only) fold
        # set-difference-only and dispatch nothing
        assert d_disp == 3 * (polls_with_delta - 2), (
            ev, polls_with_delta)
        assert d_folds == polls_with_delta
        assert (ev["lane_dispatches"]
                - base_ev.get("lane_dispatches", 0)) == d_disp // 3 * 2
        assert ev["fallbacks"] == base_ev.get("fallbacks", 0)
        # each kernel compiles once per pow2 delta bucket (the 20-batch
        # run sees three: 64-seed, 32-move, 16-readd) — fused remainder
        # plus one per lane class — NEVER per batch or per
        # subscription...
        warm_misses = aot.stats()["misses"]
        assert warm_misses - base_misses <= 9
        # ...and repeated buckets are pure AOT hits: further batches
        # add zero compiles (the zero-recompile steady state)
        for b in range(3):
            moving = [fids[(b * 11 + j) % N_FIDS] for j in range(24)]
            store.write("live", _rows(5000 + b, moving))
            store.poll("live")
        assert aot.stats()["misses"] == warm_misses, (
            "kernel recompiled on a warm pow2 bucket")
        assert (mgr.evaluator.stats()["dispatches"]
                - base_ev["dispatches"]) == d_disp + 9
        mgr.close()


class TestExactlyOnce:
    """Injected kafka.poll outage: typed error from the poll, zero
    missed and zero double-applied events across the outage."""

    def test_poll_fault_then_heal(self):
        from geomesa_tpu.faults import harness as _h
        from geomesa_tpu.faults.plan import FaultPlan, FaultRule

        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(store)
        sub = mgr.subscribe("live", "BBOX(geom, -20, -15, 25, 20)")
        log = _EventLog()
        fids = [f"f{i}" for i in range(24)]
        store.write("live", _rows(1, fids))
        store.poll("live")
        mgr.flush(log.push)
        matched_before = set(sub.matched)
        # the kafka retry policy makes 4 attempts: every=1 x 4 fires
        # exhausts the FIRST poll (typed), leaves the second clean
        plan = FaultPlan(seed=3, rules=[FaultRule(
            site="kafka.poll", error="unavailable", every=1, max_fires=4)])
        store.write("live", _rows(2, fids))
        with _h.active(plan):
            with pytest.raises(ConnectionError):
                store.poll("live")
            mgr.flush(log.push)
            # failed poll: no fold, no events, state untouched
            assert sub.matched == matched_before
            assert log.replay_matched(sub.sub_id) == matched_before
            healed = store.poll("live")
        from geomesa_tpu.faults.breaker import BREAKERS

        BREAKERS.reset("kafka")
        assert healed == 24
        mgr.flush(log.push)
        # the outage window folded exactly once: replayed events match
        # a fresh one-shot over the live snapshot
        src = store.get_feature_source("live")
        res = src.get_features(Query("live", sub.cql))
        want = set(res.features.fids.decode()) if res.features is not None else set()
        assert sub.matched == want
        assert log.replay_matched(sub.sub_id) == want
        mgr.close()


class TestSlowConsumer:
    """Bounded outbox: overflow flips lagged mode with a typed
    subscription_lagged frame and a latest-state-only re-sync —
    memory never grows past the bound."""

    def test_outbox_overflow_lagged_resync(self):
        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(
            store, SubscribeConfig(outbox_limit=3))
        sub = mgr.subscribe("live", "BBOX(geom, -20, -15, 25, 20)",
                            initial_state=False)
        fids = [f"f{i}" for i in range(16)]
        # no flush between batches: the outbox must overflow its bound
        for b in range(8):
            store.write("live", _rows(100 + b, fids))
            store.poll("live")
        assert sub.lagged
        assert sub.outbox_depth() <= 3
        assert sub.overflows >= 1
        log = _EventLog()
        mgr.flush(log.push)
        kinds = [f["event"] for f in log.frames]
        assert "subscription_lagged" in kinds
        assert kinds[-1] == "state"
        state = [f for f in log.frames if f["event"] == "state"][-1]
        assert set(state["fids"]) == sub.matched
        assert not sub.lagged
        # incremental delivery resumes after the re-sync
        store.write("live", _rows(999, fids))
        store.poll("live")
        mgr.flush(log.push)
        assert log.replay_matched(sub.sub_id) == sub.matched

    def test_terminal_frames_bypass_lagged_drop(self):
        # a lagged subscription still hears that it DIED: expired /
        # quarantined frames are the last thing the client ever gets
        sub = Subscription("live", "INCLUDE", outbox_limit=2)
        sub.offer({"event": "enter", "fids": ["a"]})
        sub.offer({"event": "enter", "fids": ["b"]})
        sub.offer({"event": "enter", "fids": ["c"]})  # overflow -> lagged
        assert sub.lagged
        assert sub.offer({"event": "enter", "fids": ["d"]}) is False
        assert sub.offer({"event": "quarantined", "message": "boom"})
        kinds = [f["event"] for f in sub.drain()]
        assert kinds == ["subscription_lagged", "quarantined"]

    def test_quarantined_subscription_swept_by_ttl(self):
        reg = SubscriptionRegistry()
        now = [0.0]
        sub = Subscription("live", "INCLUDE", clock=lambda: now[0])
        reg.register(sub)
        reg.quarantine(sub.sub_id)
        sub.expires_at = 50.0  # what the evaluator stamps on trip
        assert reg.expire_tick(now=10.0) == []
        assert reg.expire_tick(now=60.0) == [sub]
        assert reg.maybe(sub.sub_id) is None  # no longer pinned/flushed

    def test_rate_limited_drain_backpressures(self):
        sub = Subscription("live", "INCLUDE", rate=2.0, rate_burst=2.0,
                           outbox_limit=64)
        for i in range(6):
            sub.offer({"event": "enter", "fids": [f"f{i}"]})
        got = sub.drain()
        # burst of 2 frames passes; the rest stay queued (backpressure
        # into the bounded outbox, not silent drops)
        assert len(got) == 2
        assert sub.outbox_depth() == 4

    def test_failing_push_sink_loses_no_frames(self):
        # a sink that raises mid-flush must leave the undelivered
        # remainder queued (front of the outbox, seq order preserved),
        # not silently drop drained frames
        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(store)
        sub = mgr.subscribe("live", "BBOX(geom, -20, -15, 25, 20)",
                            initial_state=False)
        fids = [f"f{i}" for i in range(8)]
        for b in range(3):
            store.write("live", _rows(40 + b, fids))
            store.poll("live")
        assert sub.outbox_depth() >= 2
        delivered = []

        def broken(frame):
            if delivered:
                raise BrokenPipeError("sink gone")
            delivered.append(frame)

        with pytest.raises(BrokenPipeError):
            mgr.flush(broken)
        assert len(delivered) == 1
        log = _EventLog()
        mgr.flush(log.push)
        seqs = [f["seq"] for f in delivered + log.frames]
        # contiguous seqs across both flushes: zero lost, zero dup
        assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))
        mgr.close()


class TestQuarantine:
    """A predicate that crashes evaluation is struck and quarantined —
    not retried forever — while healthy subscriptions keep folding."""

    class _Poison:
        filter_ast = None
        _band_fn = None

        def params(self, batch):
            return {}

        def mask_fn(self):
            def bad(params, dev):
                raise RuntimeError("poisoned predicate")

            return bad

        def mask_refined(self, dev, batch):
            raise RuntimeError("poisoned predicate")

    def test_crashing_predicate_quarantined(self):
        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(
            store, SubscribeConfig(quarantine_after=2))
        healthy = mgr.subscribe("live", "BBOX(geom, -20, -15, 25, 20)")
        poisoned = mgr.subscribe("live", "score > 1.5")
        mgr.evaluator._filters[("live", "score > 1.5")] = self._Poison()
        fids = [f"f{i}" for i in range(16)]
        log = _EventLog()
        ev0 = mgr.evaluator.stats()
        for b in range(3):
            store.write("live", _rows(200 + b, fids))
            store.poll("live")
            mgr.flush(log.push)
        ev = mgr.evaluator.stats()
        # the first two crashing folds degrade to the per-subscription
        # fallback and strike; the third runs fused again (poisoned
        # predicate quarantined out of the kernel)
        assert ev["fallbacks"] - ev0.get("fallbacks", 0) == 2
        assert ev["strikes"] == 2
        assert poisoned.status == "quarantined"
        assert any(f["event"] == "quarantined"
                   and f["subscription"] == poisoned.sub_id
                   for f in log.frames)
        # healthy subscription never missed a window
        src = store.get_feature_source("live")
        res = src.get_features(Query("live", healthy.cql))
        assert healthy.matched == set(res.features.fids.decode())
        assert log.replay_matched(healthy.sub_id) == healthy.matched
        # re-registering the same predicate is rejected at admission
        with pytest.raises(QueryRejected) as exc:
            mgr.subscribe("live", "score > 1.5")
        assert exc.value.reason == "quarantined"
        mgr.close()

    def test_apply_phase_crash_strikes_not_stalls(self):
        # a predicate that crashes only in the per-subscription apply
        # phase (host-band refinement, density weights) — AFTER the
        # fused kernel succeeded — must be struck and quarantined like
        # a fused-kernel crash, not retried forever via the
        # buffer-retaining infra path, and must not stall the type
        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(store,
                                  SubscribeConfig(quarantine_after=2))
        healthy = mgr.subscribe("live", "BBOX(geom, -20, -15, 25, 20)")
        dens = mgr.subscribe("live", density=DensityWindow(
            (-60, -30, 60, 30), 8, 4, weight_attr="score"))

        def boom(d, batch):
            raise RuntimeError("weights crashed")

        mgr.evaluator._weights = boom
        fids = [f"f{i}" for i in range(16)]
        log = _EventLog()
        for b in range(3):
            store.write("live", _rows(500 + b, fids))
            store.poll("live")
            mgr.flush(log.push)
        assert dens.status == "quarantined"
        # the crashing apply never stalled the fold: the buffer was
        # consumed each poll and the healthy subscription kept folding
        assert mgr.evaluator.stats()["folds"] == 3
        src = store.get_feature_source("live")
        res = src.get_features(Query("live", healthy.cql))
        assert healthy.matched == set(res.features.fids.decode())
        mgr.close()

    def test_quarantine_after_zero_disables(self):
        # quarantine_after=0 means DISABLED (the serve layer's
        # contract), not first-strike-kills
        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(store,
                                  SubscribeConfig(quarantine_after=0))
        sub = mgr.subscribe("live", "score > 1.5")
        mgr.evaluator._filters[("live", "score > 1.5")] = self._Poison()
        fids = [f"f{i}" for i in range(8)]
        for b in range(3):
            store.write("live", _rows(300 + b, fids))
            store.poll("live")
        assert sub.status == "active"
        assert mgr.evaluator.stats().get("strikes", 0) == 0
        mgr.close()

    def test_infra_errors_do_not_strike(self):
        # the serving layer's quarantine exemption applies here too:
        # transient failures and the OSError family are infrastructure
        # answers — an infra blip must not quarantine standing
        # subscriptions (they re-seed from the snapshot instead)
        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(store,
                                  SubscribeConfig(quarantine_after=2))
        sub = mgr.subscribe("live", "BBOX(geom, -20, -15, 25, 20)")
        ev = mgr.evaluator
        for _ in range(3):
            ev._strike(sub, ConnectionError("broker blip"))
        # OSError family exempt even when classified permanent
        ev._strike(sub, FileNotFoundError("compaction-raced read"))
        st = ev.stats()
        assert st.get("strikes", 0) == 0 and st["eval_errors"] == 4
        assert sub.status == "active" and sub._resync_pending()
        mgr.close()


class TestLifecycle:
    def test_ttl_expiry_and_registry_transitions(self):
        reg = SubscriptionRegistry()
        now = [0.0]
        sub = Subscription("live", "INCLUDE", ttl_s=10.0,
                           clock=lambda: now[0])
        reg.register(sub)
        v0 = reg.version("live")
        assert reg.expire_tick(now=5.0) == []
        assert reg.expire_tick(now=11.0) == [sub]
        assert sub.status == "expired"
        assert reg.maybe(sub.sub_id) is None
        assert reg.version("live") > v0
        assert reg.take_parting() == [sub]

    def test_expired_frame_queued_before_parting_visible(self):
        # the terminal `expired` frame must already be in the outbox
        # when the subscription first becomes visible to take_parting:
        # a flush racing the sweep pops-and-drains parting subs, and a
        # frame offered after that drain is stranded forever
        reg = SubscriptionRegistry()
        now = [0.0]
        sub = Subscription("live", "INCLUDE", ttl_s=5.0,
                           clock=lambda: now[0])
        reg.register(sub)
        now[0] = 10.0
        assert reg.expire_tick() == [sub]
        assert reg.take_parting() == [sub]
        assert [f["event"] for f in sub.drain()] == ["expired"]

    def test_pause_resume_resyncs(self):
        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(store)
        sub = mgr.subscribe("live", "BBOX(geom, -20, -15, 25, 20)",
                            initial_state=False)
        fids = [f"f{i}" for i in range(16)]
        store.write("live", _rows(5, fids))
        store.poll("live")
        mgr.pause(sub.sub_id)
        assert mgr.registry.active_for("live") == []
        log = _EventLog()
        mgr.flush(log.push)
        assert log.frames == []  # paused consumers hold their outbox
        # batches folded WHILE paused never reach this subscription's
        # state (no active subs: the evaluator may even drop the
        # window) — resume must re-seed from the live snapshot, not
        # re-announce the pre-pause matched set
        store.write("live", _rows(6, fids))
        store.poll("live")
        mgr.resume(sub.sub_id)
        mgr.flush(log.push)
        # a resumed subscription re-syncs: state frame, then increments
        assert any(f["event"] == "state" for f in log.frames)
        assert log.replay_matched(sub.sub_id) == sub.matched
        src = store.get_feature_source("live")
        res = src.get_features(Query("live", sub.cql))
        oneshot = (set(res.features.fids.decode())
                   if res.features is not None else set())
        assert sub.matched == oneshot  # post-resume state is LIVE state
        mgr.unsubscribe(sub.sub_id)
        assert len(mgr.registry) == 0

    def test_density_jit_cache_is_per_instance(self):
        # the window-geometry → jitted binning executable cache must
        # die with its evaluator (one wire connection), not accrete
        # process-wide across every connection's distinct windows
        store = KafkaDataStore()
        store.create_schema(SFT)
        m1, m2 = SubscriptionManager(store), SubscriptionManager(store)
        try:
            assert m1.evaluator._cells_cache is not m2.evaluator._cells_cache
        finally:
            m1.close()
            m2.close()

    def test_close_detaches_store_hooks(self):
        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(store)
        mgr.subscribe("live", "BBOX(geom, -20, -15, 25, 20)")
        fids = [f"f{i}" for i in range(8)]
        store.write("live", _rows(1, fids))
        store.poll("live")
        folds = mgr.evaluator.stats()["folds"]
        assert folds == 1
        mgr.close()
        # a closed manager must stop costing polls: no fold hook, no
        # cache listener, no buffered events
        assert store._fold_hooks == []
        store.write("live", _rows(2, fids))
        store.poll("live")
        assert mgr.evaluator.stats()["folds"] == folds
        st = mgr.evaluator._state("live")
        assert st.buffer == [] and not st.listening

    def test_subscribe_validation(self):
        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(
            store, SubscribeConfig(max_subscriptions=1))
        with pytest.raises(ValueError):
            mgr.subscribe("live", "nosuch = 3")   # bad attribute
        with pytest.raises(KeyError):
            mgr.subscribe("ghost", "INCLUDE")     # unknown type
        # density weight column validated at admission too — typo'd or
        # non-numeric answers typed HERE, not as the first fold's crash
        with pytest.raises(ValueError):
            mgr.subscribe("live", density=DensityWindow(
                (-60, -30, 60, 30), 8, 4, weight_attr="nosuch"))
        with pytest.raises(ValueError):
            mgr.subscribe("live", density=DensityWindow(
                (-60, -30, 60, 30), 8, 4, weight_attr="name"))
        mgr.subscribe("live", "INCLUDE")
        with pytest.raises(QueryRejected) as exc:
            mgr.subscribe("live", "name = 'a'")
        assert exc.value.reason == "subscription_limit"


class TestExpiryEvents:
    """Satellite regression: expiry-driven removals emit `removed`
    FeatureEvents (geofence EXITs fire when features age out), and a
    concurrently refreshed fid survives the sweep."""

    def test_expire_emits_removed_events(self):
        cache = KafkaFeatureCache(SFT, expiry_ms=1000)
        seen = []
        cache.add_listener(lambda e: seen.append((e.kind, e.fid)))
        from geomesa_tpu.kafka.messages import Change

        t0 = time.time()
        cache.apply(Change("a", {"name": "x"}))
        cache.apply(Change("b", {"name": "y"}))
        seen.clear()
        evicted = cache.expire(now=t0 + 10.0)
        assert evicted == 2
        assert sorted(seen) == [("removed", "a"), ("removed", "b")]
        assert len(cache) == 0
        assert cache.snapshot() is None

    def test_fresh_fid_survives_sweep(self):
        cache = KafkaFeatureCache(SFT, expiry_ms=1000)
        from geomesa_tpu.kafka.messages import Change

        t0 = time.time()
        cache.apply(Change("old", {"name": "x"}))
        cache._stamps["old"] = t0 - 100.0
        cache.apply(Change("fresh", {"name": "y"}))
        assert cache.expire(now=t0 + 0.5) == 1
        assert cache.get("fresh") is not None
        assert cache.get("old") is None

    def test_expiry_drives_geofence_exit(self):
        store = KafkaDataStore(expiry_ms=30)
        store.create_schema(SFT)
        mgr = SubscriptionManager(store)
        sub = mgr.subscribe("live", "BBOX(geom, -180, -90, 180, 90)",
                            initial_state=False)
        store.write("live", _rows(7, ["f0", "f1"]))
        store.poll("live")
        assert len(sub.matched) == 2
        time.sleep(0.06)
        store.poll("live")  # expiry sweep emits removed -> EXIT events
        log = _EventLog()
        mgr.flush(log.push)
        exits = [f for f in log.frames if f["event"] == "exit"]
        assert exits and set(exits[-1]["fids"]) == {"f0", "f1"}
        assert sub.matched == set()
        mgr.close()


class TestWireProtocol:
    """subscribe/unsubscribe/poll verbs + push frames on the JSON-lines
    stream (docs/SERVING.md wire protocol)."""

    def _run(self, lines_iter, store):
        from geomesa_tpu.serve.protocol import serve_lines
        from geomesa_tpu.serve.service import ServeConfig

        out = []
        serve_lines(store, lines_iter, out.append,
                    ServeConfig(pipeline=False))
        return [json.loads(s) for s in out]

    def test_subscribe_poll_unsubscribe_round_trip(self):
        store = KafkaDataStore()
        store.create_schema(SFT)
        fids = [f"f{i}" for i in range(12)]
        store.write("live", _rows(1, fids))

        def lines():
            yield json.dumps({
                "id": "s1", "op": "subscribe", "typeName": "live",
                "cql": "BBOX(geom, -20, -15, 25, 20)"})
            yield json.dumps({
                "id": "s2", "op": "subscribe", "typeName": "live",
                "density": {"bbox": [-60, -30, 60, 30],
                            "width": 8, "height": 4}})
            yield json.dumps({"id": "p1", "op": "poll"})
            store.write("live", _rows(2, fids))
            yield json.dumps({"id": "p2", "op": "poll"})
            yield json.dumps({"id": "q1", "op": "count",
                              "typeName": "live"})
            yield json.dumps({"id": "ls", "op": "subscriptions"})
            yield json.dumps({"id": "u1", "op": "unsubscribe",
                              "subscription": "sub-1"})
            yield json.dumps({"id": "bad", "op": "subscribe",
                              "typeName": "live", "cql": "nosuch = 1"})
            yield json.dumps({"id": "u2", "op": "unsubscribe",
                              "subscription": "sub-999"})

        # fresh id space per Subscription module counter is global —
        # resolve the actual id from the response instead of sub-1
        docs = self._run(lines(), store)
        by_id = {d["id"]: d for d in docs if "id" in d}
        events = [d for d in docs if "event" in d]
        sid = by_id["s1"]["subscription"]
        assert by_id["s1"]["ok"] and by_id["s2"]["mode"] == "density"
        assert by_id["p1"]["ok"] and by_id["p1"]["applied"]["live"] == 12
        assert by_id["q1"]["count"] == 12
        assert by_id["ls"]["subscriptions"] == 2
        assert not by_id["bad"]["ok"]
        # unknown id on a LIVE session: typed answer, no leaked KeyError
        assert by_id["u2"]["ok"] is False
        assert by_id["u2"]["message"] == "no such subscription"
        # push frames interleaved: initial state, enters on p1,
        # enter/exit churn on p2, density folds
        kinds = {e["event"] for e in events}
        assert "state" in kinds and "enter" in kinds
        assert any(e["event"] == "density" for e in events)
        log = _EventLog()
        log.frames = [e for e in events if e.get("subscription") == sid]
        assert isinstance(log.replay_matched(sid), set)
        # the registration-time state frame is stamped exactly once —
        # the client's very first frame is seq 1 (offer() re-stamping
        # it to 2 would read as a phantom lost frame under the
        # monotonic-seq contract)
        first = min(log.frames, key=lambda f: f["seq"])
        assert first["event"] == "state" and first["seq"] == 1

    def test_unsubscribe_wrong_store_and_ids(self):
        import tempfile

        from geomesa_tpu.plan.datastore import DataStore

        with tempfile.TemporaryDirectory() as tmp:
            fs_store = DataStore(tmp, use_device_cache=False)

            def lines():
                # poll / introspection verbs answer cheaply without
                # instantiating a manager (works on durable stores too)
                yield json.dumps({"id": "p0", "op": "poll"})
                yield json.dumps({"id": "l0", "op": "subscriptions"})
                yield json.dumps({"id": "u0", "op": "unsubscribe",
                                  "subscription": "sub-999"})
                yield json.dumps({"id": "s1", "op": "subscribe",
                                  "typeName": "x", "cql": "INCLUDE"})

            docs = self._run(lines(), fs_store)
            by_id = {d["id"]: d for d in docs}
            assert by_id["p0"]["ok"] and by_id["p0"]["applied"] == {}
            assert by_id["l0"]["ok"] and by_id["l0"]["subscriptions"] == 0
            assert by_id["u0"]["ok"] is False
            assert by_id["s1"]["ok"] is False  # durable store: typed error


class TestLanes:
    """Vmapped parametric geofence lanes (docs/SERVING.md "Standing
    queries"): same-shape geofence classes evaluate as ONE [S]-batched
    dispatch per class whose compiled program is independent of S;
    membership churn is a parameter-row write, never a rebuild."""

    LANE_CQLS = [
        "BBOX(geom, -20, -15, 25, 20)",
        "BBOX(geom, -50, -25, -10, 5)",
        "DWITHIN(geom, POINT(10 5), 2000000, meters)",
        "DWITHIN(geom, POINT(-30 -10), 1500000, meters)",
        "INTERSECTS(geom, POLYGON((-40 -20, 10 -25, 30 15, -25 22,"
        " -40 -20)))",
        "name = 'a'",  # lane-ineligible: stays on the fused path
    ]
    WINDOW = DensityWindow((-60.0, -30.0, 60.0, 30.0), 16, 8)

    def test_lane_vs_slot_parity_with_mid_run_churn(self):
        """Matched sets and density grids bit-identical between
        lanes=True and lanes=False over 12 batches of moves/deletes/
        re-adds, with a registration AND a cancellation landing
        mid-run, and both modes equal to a fresh one-shot planner
        query after every batch."""
        stores = (KafkaDataStore(), KafkaDataStore())
        for s in stores:
            s.create_schema(SFT)
        mgrs = (SubscriptionManager(stores[0], SubscribeConfig(lanes=True)),
                SubscriptionManager(stores[1],
                                    SubscribeConfig(lanes=False)))
        subs = {m: [m.subscribe("live", cql) for cql in self.LANE_CQLS]
                + [m.subscribe("live", density=self.WINDOW)]
                for m in mgrs}
        fids = [f"f{i}" for i in range(N_FIDS)]
        base = mgrs[0].evaluator.stats()
        src = stores[0].get_feature_source("live")
        for b in range(12):
            if b == 0:
                rows = _rows(1000, fids)
            elif b == 6:
                for store in stores:
                    for fid in fids[:4]:
                        store.delete("live", fid)
                rows = None
            elif b == 7:
                rows = _rows(2000, fids[:4])
            else:
                moving = [fids[(b * 7 + j) % N_FIDS] for j in range(24)]
                rows = _rows(4000 + b, moving)
            if rows is not None:
                for store in stores:
                    store.write("live", rows)
            if b == 4:  # mid-run registration: a parameter-row write
                for m in mgrs:
                    subs[m].append(m.subscribe(
                        "live", "BBOX(geom, -5, -5, 45, 25)"))
            if b == 8:  # mid-run cancellation: a row release
                for m in mgrs:
                    m.unsubscribe(subs[m][0].sub_id)
            for store, m in zip(stores, mgrs):
                store.poll("live")
                m.flush(lambda _f: None)
            live = ([] if b >= 8 else [0]) + list(
                range(1, len(subs[mgrs[0]])))
            for i in live:
                a, c = subs[mgrs[0]][i], subs[mgrs[1]][i]
                if a.density is not None:
                    assert np.array_equal(a.grid, c.grid), (
                        f"batch {b}: lane-mode density grid diverged")
                    continue
                assert a.matched == c.matched, (
                    f"batch {b}: {a.cql!r} lanes != fused slots")
                res = src.get_features(Query("live", a.cql))
                want = (set() if res.features is None
                        else set(res.features.fids.decode()))
                assert a.matched == want, (
                    f"batch {b}: {a.cql!r} lanes != one-shot")
        ev = mgrs[0].evaluator.stats()
        assert ev["lane_dispatches"] > base.get("lane_dispatches", 0)
        lanes = mgrs[0].stats()["lanes"]
        assert lanes["enabled"]
        assert lanes["classes"]["bbox"]["rows"] == 2  # churned 3 -> 2
        assert lanes["classes"]["dwithin"]["rows"] == 2
        assert lanes["classes"]["polygon"]["rows"] == 1
        assert lanes["ineligible"] == {"non_spatial": 1}
        fused = mgrs[1].stats()["lanes"]
        assert not fused["enabled"] and fused["classes"] == {}
        for m in mgrs:
            m.close()

    def test_bucket_growth_compiles_once_then_zero_recompiles(self):
        """JitTracker over engine/lanes.py: the [S]-bucket compiles at
        most once per pow2 capacity; register/cancel churn WITHIN a
        bucket is a row write with ZERO recompiles."""
        from geomesa_tpu.analysis.runtime import (
            acquire_engine_tracker, release_engine_tracker)

        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(store,
                                  SubscribeConfig(max_subscriptions=64))
        # 96 fids -> a 128-row delta bucket no other test compiles, so
        # the per-[S]-bucket compile counts below are exact, not
        # best-effort against a warm process-wide jit cache
        fids = [f"f{i}" for i in range(96)]
        tracker, _ = acquire_engine_tracker(
            modules=["geomesa_tpu.engine.lanes"])
        try:
            def compiles():
                return tracker.recompiles.get("lanes.lane_bbox", 0)

            def boxes(seed, k):
                rng = np.random.default_rng(seed)
                out = []
                for _ in range(k):
                    x0 = float(rng.uniform(-60, 20))
                    y0 = float(rng.uniform(-30, 5))
                    out.append(mgr.subscribe(
                        "live",
                        f"BBOX(geom, {x0}, {y0}, {x0 + 8}, {y0 + 6})"))
                return out

            subs = boxes(1, 8)  # fills the smallest [8]-row bucket
            store.write("live", _rows(1, fids))
            store.poll("live")
            mgr.flush(lambda _f: None)
            assert compiles() == 1, "first [S=8] bucket must compile once"
            # churn WITHIN the bucket: cancel + register recycle rows
            for i in range(5):
                mgr.unsubscribe(subs[i].sub_id)
                subs.append(boxes(100 + i, 1)[0])
                store.write("live", _rows(10 + i, fids))
                store.poll("live")
                mgr.flush(lambda _f: None)
            assert compiles() == 1, (
                "register/cancel churn within an [S] bucket recompiled "
                f"the lane kernel ({tracker.report()})")
            # growth past capacity: exactly one more compile ([16])
            subs += boxes(2, 6)
            store.write("live", _rows(20, fids))
            store.poll("live")
            mgr.flush(lambda _f: None)
            assert compiles() == 2, "bucket growth must compile exactly once"
            for i in range(5, 8):
                mgr.unsubscribe(subs[i].sub_id)
                boxes(200 + i, 1)
                store.write("live", _rows(30 + i, fids))
                store.poll("live")
                mgr.flush(lambda _f: None)
            assert compiles() == 2, (
                "churn within the grown bucket recompiled "
                f"({tracker.report()})")
        finally:
            release_engine_tracker(tracker)
            mgr.close()

    def test_ten_thousand_geofences_bounded_dispatches(self):
        """The acceptance bound: 10^4 same-class registered geofences
        evaluate per poll in <=4 device dispatches (one [S]-batched
        bbox-lane dispatch), with matched sets equal to one-shot
        planner queries on a sample."""
        S = 10_000
        store = KafkaDataStore()
        store.create_schema(SFT)
        mgr = SubscriptionManager(
            store, SubscribeConfig(max_subscriptions=S + 8))
        rng = np.random.default_rng(11)
        subs = []
        for _ in range(S):
            x0 = float(rng.uniform(-60, 26))
            y0 = float(rng.uniform(-30, 8))
            subs.append(mgr.subscribe(
                "live",
                f"BBOX(geom, {x0:.4f}, {y0:.4f}, "
                f"{x0 + 2:.4f}, {y0 + 2:.4f})"))
        fids = [f"f{i}" for i in range(N_FIDS)]
        base = mgr.evaluator.stats()
        store.write("live", _rows(5, fids))
        store.poll("live")
        mgr.flush(lambda _f: None)
        ev = mgr.evaluator.stats()
        assert ev["dispatches"] - base["dispatches"] <= 4, (
            "10^4 same-class geofences must evaluate in <=4 dispatches")
        assert ev["lane_dispatches"] - base.get("lane_dispatches", 0) == 1
        lanes = mgr.stats()["lanes"]
        assert lanes["classes"]["bbox"]["rows"] == S
        assert lanes["ineligible"] == {}
        src = store.get_feature_source("live")
        for sub in [subs[i] for i in (0, 17, 4096, 9999)]:
            res = src.get_features(Query("live", sub.cql))
            want = (set() if res.features is None
                    else set(res.features.fids.decode()))
            assert sub.matched == want, f"{sub.cql!r} lane != one-shot"
        mgr.close()

    def test_lane_floor_at_1024(self):
        """The >=10x events/s acceptance floor at S=1024 on CPU CI:
        both legs run the identical register-before-seed protocol with
        the first (compiling) poll inside the measured window — the
        fused slot path pays an S-proportional trace+compile there,
        the lane path one S-independent batched kernel. Churn is
        excluded HERE only to keep the fused leg to a single compile
        inside the tier-1 budget; the churn-inclusive comparison runs
        in scripts/lint_gate.py lane_smoke and the zero-recompile
        churn contract is JitTracker-asserted above."""
        from geomesa_tpu.serve.loadgen import run_subscribe_lanes

        def make_store():
            store = KafkaDataStore()
            store.create_schema(SFT)
            return store

        fids = [f"f{i}" for i in range(N_FIDS)]

        def make_batch(i):
            return _rows(600 + i, fids)

        rep = run_subscribe_lanes(make_store, "live", make_batch,
                                  subscriptions=1024, batches=1,
                                  churn=False)
        lanes, fused = rep["lanes"], rep["fused"]
        # the speedup must not be bought with dropped events
        assert lanes["events_total"] == fused["events_total"] > 0
        assert rep["speedup"] >= 10.0, (
            f"lane floor missed: {rep['speedup']}x "
            f"(lanes {lanes['events_per_s']}/s vs fused "
            f"{fused['events_per_s']}/s)")
        assert lanes["dispatches_per_poll"] <= 4.0
        assert lanes["lane_dispatches"] == lanes["polls"]


class TestHandoff:
    """Matched-set handoff on failover (docs/ROBUSTNESS.md): a standing
    query re-homes onto a survivor replica via handoff_snapshot ->
    subscribe(handoff=...), continuing the client's sequence numbers
    with a state resync frame instead of starting over."""

    CQL = "BBOX(geom, -20, -15, 25, 20)"

    def test_handoff_round_trip(self):
        store = KafkaDataStore()
        store.create_schema(SFT)
        a = SubscriptionManager(store)
        sub = a.subscribe("live", self.CQL)
        log_a = _EventLog()
        fids = [f"f{i}" for i in range(24)]
        store.write("live", _rows(1, fids))
        store.poll("live")
        a.flush(log_a.push)
        matched = set(sub.matched)
        snap = sub.handoff_snapshot()
        assert snap["type"] == "live"
        assert set(snap["matched"]) == matched
        # drained outbox: everything stamped was delivered
        assert snap["watermark"] == snap["seq"]
        # the old replica dies AFTER exporting
        a.close()
        b = SubscriptionManager(store)
        # acceptor validation: the handoff must describe THIS predicate
        with pytest.raises(ValueError):
            b.subscribe("live", "BBOX(geom, 0, 0, 1, 1)", handoff=snap)
        sub2 = b.subscribe("live", self.CQL, handoff=snap)
        log_b = _EventLog()
        b.flush(log_b.push)
        states = [f for f in log_b.frames if f.get("event") == "state"]
        assert states, "handoff acceptance must answer a state resync"
        # sequence numbers CONTINUE from the delivered watermark: the
        # resync frame is the next seq the client sees
        assert states[0]["seq"] == snap["watermark"] + 1
        assert set(states[0]["fids"]) == matched
        assert log_b.replay_matched(sub2.sub_id) == matched
        # and the re-homed query keeps flowing with one-shot parity
        store.write("live", _rows(2, fids))
        store.poll("live")
        b.flush(log_b.push)
        src = store.get_feature_source("live")
        res = src.get_features(Query("live", self.CQL))
        want = (set() if res.features is None
                else set(res.features.fids.decode()))
        assert sub2.matched == want
        assert log_b.replay_matched(sub2.sub_id) == want
        # density grids never hand off: replica-local float state
        with pytest.raises(ValueError):
            b.subscribe("live", density=DensityWindow(
                (-60.0, -30.0, 60.0, 30.0), 8, 4), handoff=snap)
        dens = b.subscribe("live", density=DensityWindow(
            (-60.0, -30.0, 60.0, 30.0), 8, 4))
        with pytest.raises(ValueError):
            dens.handoff_snapshot()
        b.close()

    def test_wire_export_subscription(self):
        """The export_subscription verb round-trips the snapshot over
        the JSON-lines wire and a re-subscribe WITH it answers the
        state resync frame on the new session."""
        from geomesa_tpu.serve.protocol import serve_lines
        from geomesa_tpu.serve.service import ServeConfig

        store = KafkaDataStore()
        store.create_schema(SFT)
        fids = [f"f{i}" for i in range(12)]
        store.write("live", _rows(1, fids))
        out = []
        sid = {}

        def lines_a():
            yield json.dumps({"id": "s1", "op": "subscribe",
                              "typeName": "live", "cql": self.CQL})
            yield json.dumps({"id": "p1", "op": "poll"})
            yield json.dumps({"id": "x1", "op": "export_subscription",
                              "subscription": "PLACEHOLDER"})
            yield json.dumps({"id": "x2", "op": "export_subscription",
                              "subscription": "sub-999999"})

        # two-pass: the export needs the real sub id from the ack
        def lines_resolved():
            for ln in lines_a():
                doc = json.loads(ln)
                if doc.get("subscription") == "PLACEHOLDER":
                    doc["subscription"] = sid["v"]
                    ln = json.dumps(doc)
                yield ln
                if doc["id"] == "s1":
                    got = [json.loads(s) for s in out]
                    sid["v"] = next(d["subscription"] for d in got
                                    if d.get("id") == "s1")

        serve_lines(store, lines_resolved(), out.append,
                    ServeConfig(pipeline=False))
        docs = [json.loads(s) for s in out]
        by_id = {d["id"]: d for d in docs if "id" in d}
        assert by_id["x1"]["ok"], by_id["x1"]
        snap = by_id["x1"]["handoff"]
        assert snap["type"] == "live" and snap["cql"] == self.CQL
        assert snap["matched"] and snap["watermark"] >= 1
        assert by_id["x2"]["ok"] is False
        assert by_id["x2"]["message"] == "no such subscription"
        # the snapshot is pure JSON: accepted verbatim on a NEW session
        out_b = []

        def lines_b():
            yield json.dumps({"id": "s2", "op": "subscribe",
                              "typeName": "live", "cql": self.CQL,
                              "handoff": snap})

        serve_lines(store, lines_b(), out_b.append,
                    ServeConfig(pipeline=False))
        docs_b = [json.loads(s) for s in out_b]
        state = [d for d in docs_b if d.get("event") == "state"]
        assert state and state[0]["seq"] == snap["watermark"] + 1
        assert set(state[0]["fids"]) == set(snap["matched"])


class TestLoadgen:
    def test_run_subscribe_reports(self):
        from geomesa_tpu.serve.loadgen import run_subscribe

        store = KafkaDataStore()
        store.create_schema(SFT)
        fids = [f"f{i}" for i in range(24)]

        def make_batch(i):
            return _rows(700 + i, fids)

        rep = run_subscribe(store, "live", make_batch,
                            subscriptions=3, batches=4)
        assert rep.mode == "subscribe"
        assert rep.subscriptions == 3 and rep.batches == 4
        assert rep.events_total > 0 and rep.events_per_s > 0
        # three dispatches per folded batch: the 3 cycling subscription
        # kinds land one each in the bbox lane, the dwithin lane and
        # the fused remainder (the density window)
        assert rep.dispatches == 12
        assert rep.p99_ms >= rep.p50_ms >= 0
        # a caller-owned manager gets its bench subscriptions cancelled
        # at return (repeated comparison runs must not accumulate 8
        # stale subs each until the table bound rejects the run)
        from geomesa_tpu.subscribe import SubscriptionManager
        mgr = SubscriptionManager(store)
        try:
            run_subscribe(store, "live", make_batch,
                          subscriptions=3, batches=2, manager=mgr)
            assert len(mgr.registry) == 0
        finally:
            mgr.close()
