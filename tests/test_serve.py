"""Serve subsystem: admission control, request coalescing, degradation,
drain, and the latency-histogram observability contract.

The load-bearing test is test_coalescing_fewer_dispatches_same_results:
>= 8 concurrent compatible kNN queries must execute in FEWER device
dispatches than serial execution (dispatch counters + JitTracker over
the engine jit caches) while returning per-query results identical to
serial runs — the whole point of the serving layer.
"""

import json
import threading
import time

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.audit import ServeEvent
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.plan.planner import QueryTimeout
from geomesa_tpu.plan.query import Query
from geomesa_tpu.serve import (
    AdmissionQueue, QueryRejected, QueryService, ServeConfig, ServeRequest,
    TokenBucket, compat_key)
from geomesa_tpu.utils.metrics import Histogram, metrics

CQL = "BBOX(geom, -170, -80, 170, 80) AND score > -5"


def make_batch(n=600, seed=3):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec(
        "served", "name:String,score:Double,dtg:Date,*geom:Point")
    return sft, FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    sft, batch = make_batch()
    ds = DataStore(
        str(tmp_path_factory.mktemp("serve")), use_device_cache=True)
    ds.create_schema(sft).write(batch)
    return ds


# -- metrics: Histogram ----------------------------------------------------


class TestHistogram:
    def test_counts_sum_quantiles(self):
        h = Histogram()
        for v in [0.001] * 50 + [0.004] * 45 + [0.3] * 5:
            h.update(v)
        assert h.count == 100
        assert h.sum == pytest.approx(0.05 + 0.18 + 1.5)
        assert h.quantile(0.5) <= 0.004
        assert h.quantile(0.99) >= 0.1
        snap = h.snapshot()
        assert snap["count"] == 100
        assert snap["p50_s"] <= snap["p95_s"] <= snap["p99_s"]

    def test_empty_and_bounds(self):
        h = Histogram()
        assert h.quantile(0.99) == 0.0
        with pytest.raises(ValueError):
            h.quantile(1.5)
        with pytest.raises(ValueError):
            Histogram(buckets=[2.0, 1.0])

    def test_overflow_clamps_to_last_bound(self):
        h = Histogram(buckets=[0.001, 0.01])
        h.update(5.0)  # lands in +Inf bucket
        assert h.quantile(0.99) == 0.01

    def test_merge(self):
        a, b = Histogram(), Histogram()
        for v in [0.001, 0.002]:
            a.update(v)
        for v in [0.004, 0.008, 0.016]:
            b.update(v)
        a.merge(b)
        assert a.count == 5
        assert a.sum == pytest.approx(0.031)
        with pytest.raises(ValueError):
            a.merge(Histogram(buckets=[1.0]))

    def test_thread_safety(self):
        h = Histogram()

        def worker():
            for _ in range(2000):
                h.update(0.001)

        ts = [threading.Thread(target=worker) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert h.count == 16000
        assert h.sum == pytest.approx(16.0, rel=1e-6)

    def test_registry_exports(self):
        metrics.histogram("serve.test.latency").update(0.012)
        prom = metrics.to_prometheus()
        assert "# TYPE serve_test_latency_seconds histogram" in prom
        assert 'serve_test_latency_seconds_bucket{le="+Inf"} 1' in prom
        for q in ("p50", "p95", "p99"):
            assert f"serve_test_latency_seconds_{q} " in prom
        doc = json.loads(metrics.to_json())
        assert doc["histograms"]["serve.test.latency"]["count"] == 1


# -- scheduler units -------------------------------------------------------


class TestScheduler:
    def test_token_bucket(self):
        tb = TokenBucket(rate=1000.0, burst=2.0)
        assert tb.try_acquire()
        assert tb.try_acquire()
        assert not tb.try_acquire()
        time.sleep(0.01)  # 1000/s refills ~10 tokens, capped at burst
        assert tb.try_acquire()

    def test_queue_bounded_and_priority_order(self):
        q = AdmissionQueue(max_depth=3)
        batch = ServeRequest(kind="count", query=Query("t"), priority=2)
        normal = ServeRequest(kind="count", query=Query("t"), priority=1)
        inter = ServeRequest(kind="count", query=Query("t"), priority=0)
        q.put(batch)
        q.put(normal)
        q.put(inter)
        with pytest.raises(QueryRejected) as ei:
            q.put(ServeRequest(kind="count", query=Query("t")))
        assert ei.value.reason == "queue_full"
        assert q.pop(0.01) is inter  # priority class beats FIFO age
        assert q.pop(0.01) is normal
        assert q.pop(0.01) is batch
        assert q.pop(0.01) is None

    def test_drain_compatible_keeps_others(self):
        q = AdmissionQueue(max_depth=10)
        a1 = ServeRequest(kind="count", query=Query("t", "score > 0"))
        b = ServeRequest(kind="count", query=Query("t", "score > 1"))
        a2 = ServeRequest(kind="count", query=Query("t", "score>0"))
        for r in (a1, b, a2):
            q.put(r)
        key = compat_key(a1)
        got = q.drain_compatible(key, compat_key, limit=10)
        # textual CQL variants canonicalize to the same key
        assert got == [a1, a2]
        assert q.pop(0.01) is b

    def test_cancelled_requests_skipped(self):
        q = AdmissionQueue(max_depth=4)
        a = ServeRequest(kind="count", query=Query("t"))
        b = ServeRequest(kind="count", query=Query("t"))
        q.put(a)
        q.put(b)
        assert a.cancel()
        assert q.pop(0.01) is b
        assert q.pop(0.01) is None

    def test_compat_keys(self):
        def knn(cql, k=5, hints=None):
            r = ServeRequest(
                kind="knn",
                query=Query("t", cql, hints=hints or QueryHints()))
            r.k = k
            return r

        assert compat_key(knn("score > 0")) == compat_key(knn("score>0"))
        assert compat_key(knn("score > 0")) != compat_key(knn("score > 1"))
        assert compat_key(knn("score > 0", k=5)) != \
            compat_key(knn("score > 0", k=7))
        # auths are part of the hints: different tenants' visibility
        # contexts must never alias into one dispatch
        assert compat_key(knn("score > 0", hints=QueryHints(auths=("A",)))) \
            != compat_key(knn("score > 0"))
        e1 = ServeRequest(kind="execute", query=Query("t", "score > 0"))
        c1 = ServeRequest(kind="count", query=Query("t", "score > 0"))
        assert compat_key(e1) != compat_key(c1)


# -- service integration ---------------------------------------------------


class TestService:
    def test_coalescing_fewer_dispatches_same_results(self, store):
        """Acceptance: >= 8 concurrent compatible kNN queries in fewer
        device dispatches than serial, identical per-query results."""
        import geomesa_tpu.engine.knn_scan as knn_scan_mod

        from geomesa_tpu.analysis.runtime import JitTracker

        src = store.get_feature_source("served")
        rng = np.random.default_rng(42)
        n_req = 10
        qpts = rng.uniform(-60, 60, (n_req, 2))

        tracker = JitTracker()
        tracker.install(knn_scan_mod)
        try:
            # serial baseline: one dispatch per request (warms jit +
            # device caches too, so the comparison isolates dispatches)
            serial = [
                src.knn(CQL, qpts[i:i + 1, 0], qpts[i:i + 1, 1], k=5)
                for i in range(n_req)
            ]
            serial_calls = sum(
                rec["calls"] for rec in tracker.report().values())

            svc = QueryService(
                store, ServeConfig(max_wait_ms=20.0), autostart=False)
            futs = [
                svc.knn("served", CQL, qpts[i:i + 1, 0], qpts[i:i + 1, 1],
                        k=5)
                for i in range(n_req)
            ]
            svc.start()
            results = [f.result(timeout=120) for f in futs]
            svc.close(drain=True)
            coalesced_calls = sum(
                rec["calls"] for rec in tracker.report().values()
            ) - serial_calls
        finally:
            tracker.unwrap()

        st = svc.stats()
        assert st["dispatches"] < n_req, st
        assert st["coalesced"] >= n_req - st["dispatches"]
        # the engine's jit caches saw fewer kernel invocations too
        assert coalesced_calls < serial_calls
        for (d, ix, _), (sd, six, _) in zip(results, serial):
            np.testing.assert_allclose(d, sd, rtol=1e-6)
            np.testing.assert_array_equal(ix, six)

    def test_count_dedup_single_dispatch(self, store):
        svc = QueryService(store, autostart=False)
        futs = [svc.count("served", CQL) for _ in range(6)]
        svc.start()
        counts = [f.result(timeout=120) for f in futs]
        svc.close(drain=True)
        assert len(set(counts)) == 1
        assert svc.stats()["dispatches"] == 1

    def test_overload_bounded_queue_typed_rejection(self, store):
        """Overload never buffers unboundedly: the queue admits exactly
        max_queue requests, rejects the rest with a typed reason, and
        still completes everything it admitted."""
        svc = QueryService(
            store, ServeConfig(max_queue=4), autostart=False)
        admitted = [svc.count("served", f"score > {i}") for i in range(4)]
        rejected = 0
        for i in range(6):
            with pytest.raises(QueryRejected) as ei:
                svc.count("served", f"score > {10 + i}")
            assert ei.value.reason == "queue_full"
            rejected += 1
        assert rejected == 6
        assert len(svc.queue) == 4  # bounded, not grown
        svc.start()
        for f in admitted:
            assert isinstance(f.result(timeout=120), int)
        svc.close(drain=True)
        assert svc.stats()["rejected"] == 6

    def test_deadline_expired_in_queue_raises_query_timeout(self, store):
        svc = QueryService(store, autostart=False)
        fut = svc.count("served", CQL, timeout_ms=1)
        time.sleep(0.05)
        svc.start()
        with pytest.raises(QueryTimeout) as ei:
            fut.result(timeout=60)
        assert ei.value.phase == "queued"
        svc.close(drain=True)

    def test_tenant_rate_limit(self, store):
        svc = QueryService(
            store, ServeConfig(tenant_rate=0.001, tenant_burst=2),
            autostart=False)
        svc.count("served", CQL, tenant="tA")
        svc.count("served", CQL, tenant="tA")
        with pytest.raises(QueryRejected) as ei:
            svc.count("served", CQL, tenant="tA")
        assert ei.value.reason == "rate_limited"
        # other tenants have their own bucket
        svc.count("served", CQL, tenant="tB")
        svc.start()
        svc.close(drain=True)

    def test_degradation_ladder(self, store):
        cfg = ServeConfig(max_queue=4, degrade=True,
                          degrade_watermark=0.5, shed_watermark=0.75)
        svc = QueryService(store, cfg, autostart=False)
        svc.count("served", "score > 1")
        svc.count("served", "score > 2")
        assert svc.degrade_level() == 1
        # level 1: consenting requests get downgraded hints. CQL
        # carries an attribute predicate (`score > -5`) the sketches
        # cannot see, so the ladder keeps the LEGACY loose-bbox rung
        # for it (the sketch rung takes only sketch-eligible filters —
        # docs/SERVING.md "Approximate answers"; tests/test_approx.py
        # covers that branch)
        fut_req = svc._request("count", Query("served", CQL),
                               allow_degraded=True)
        svc.submit(fut_req)
        assert fut_req.degraded and fut_req.query.hints.loose_bbox
        assert fut_req.sketch_rung == 0
        assert svc.degrade_level() == 2
        # level 2: batch class is shed with the typed reason
        with pytest.raises(QueryRejected) as ei:
            svc.count("served", "score > 3", priority="batch")
        assert ei.value.reason == "shed"
        # interactive work still admits (queue permitting)
        svc.count("served", "score > 4", priority="interactive")
        svc.start()
        svc.close(drain=True)
        assert svc.stats()["degraded"] == 1

    def test_graceful_drain_and_shutdown_rejection(self, store):
        svc = QueryService(store, autostart=False)
        futs = [svc.count("served", f"score > {i % 3}") for i in range(5)]
        svc.start()
        svc.close(drain=True)
        for f in futs:
            assert isinstance(f.result(timeout=1), int)  # already done
        with pytest.raises(QueryRejected) as ei:
            svc.count("served", CQL)
        assert ei.value.reason == "shutting_down"

    def test_non_drain_close_rejects_queued(self, store):
        svc = QueryService(store, autostart=False)
        fut = svc.count("served", CQL)
        svc.close(drain=False)
        with pytest.raises(QueryRejected) as ei:
            fut.result(timeout=1)
        assert ei.value.reason == "shutting_down"

    def test_bad_type_name_fails_future_not_dispatcher(self, store):
        """An unknown typeName raises in get_feature_source BEFORE the
        guarded execute_batch; it must fail that request's future and
        leave the dispatch thread alive for everyone else."""
        svc = QueryService(store)
        bad = svc.count("no_such_type", "INCLUDE")
        with pytest.raises(Exception):
            bad.result(timeout=60)
        # dispatcher survived: a valid request still completes
        assert isinstance(
            svc.count("served", "score > 5").result(timeout=120), int)
        svc.close(drain=True)

    def test_cancel_between_pop_and_execute_is_survivable(self, store):
        """A future cancelled while queued resolves as cancelled and the
        post-dispatch accounting skips it instead of raising
        CancelledError into the dispatch loop."""
        svc = QueryService(store, autostart=False)
        req = svc._request("count", Query("served", CQL))
        svc.submit(req)
        assert req.cancel()
        svc.start()
        ok = svc.count("served", "score > 8")
        assert isinstance(ok.result(timeout=120), int)
        svc.close(drain=True)
        assert req.future.cancelled()

    def test_serve_events_audited(self, store):
        base = len(store.audit.events)
        svc = QueryService(store, autostart=False)
        futs = [svc.count("served", "score > 6") for _ in range(3)]
        svc.start()
        for f in futs:
            f.result(timeout=120)
        svc.close(drain=True)
        events = [e for e in store.audit.events[base:]
                  if isinstance(e, ServeEvent)]
        assert len(events) == 3
        assert all(e.status == "ok" and e.batch_size == 3 for e in events)
        assert all(e.queue_ms >= 0 and e.timestamp > 0 for e in events)

    def test_latency_histograms_exported(self, store):
        svc = QueryService(store)
        svc.count("served", "score > 7").result(timeout=120)
        svc.close(drain=True)
        prom = metrics.to_prometheus()
        for family in ("serve_latency_seconds", "serve_queue_wait_seconds"):
            assert f"# TYPE {family} histogram" in prom
            assert f'{family}_bucket{{le="+Inf"}}' in prom
            for q in ("p50", "p95", "p99"):
                assert f"{family}_{q} " in prom


# -- JSON-lines protocol + CLI ---------------------------------------------


class TestProtocol:
    def test_serve_lines_round_trip(self, store):
        from geomesa_tpu.serve.protocol import serve_lines

        lines = [
            json.dumps({"id": "c1", "op": "count", "typeName": "served",
                        "cql": CQL}),
            json.dumps({"id": "k1", "op": "knn", "typeName": "served",
                        "cql": CQL, "x": [10.0], "y": [20.0], "k": 3}),
            json.dumps({"id": "q1", "op": "query", "typeName": "served",
                        "cql": "score > 9", "maxFeatures": 5}),
            "not json at all",
            json.dumps({"id": "bad", "op": "nope", "typeName": "served"}),
        ]
        out = []
        n = serve_lines(store, lines, out.append)
        assert n == 5
        docs = {d.get("id"): d for d in map(json.loads, out)}
        assert docs["c1"]["ok"] and docs["c1"]["count"] > 0
        assert docs["k1"]["ok"]
        assert len(docs["k1"]["dists"][0]) == 3
        assert len(docs["k1"]["indices"]) == 1
        assert docs["q1"]["ok"] and docs["q1"]["kind"] == "features"
        assert len(docs["q1"]["features"]) <= 5
        assert not docs["bad"]["ok"] and docs["bad"]["error"] == "error"
        # the malformed line answered under its sequence number
        assert sum(1 for d in docs.values() if not d["ok"]) == 2

    def test_cli_self_check(self):
        from geomesa_tpu.cli.main import main

        assert main(["serve", "--self-check"]) == 0

    def test_cli_serve_requires_catalog(self):
        from geomesa_tpu.cli.main import main

        assert main(["serve"]) == 2


@pytest.mark.slow
class TestLoadSoak:
    def test_bench_serve_smoke(self):
        from geomesa_tpu.cli.main import main

        assert main(["bench-serve", "--smoke", "--duration", "1",
                     "--n", "1500"]) == 0

    def test_open_loop_sheds_over_capacity(self, store):
        from geomesa_tpu.serve.loadgen import (
            knn_request_factory, run_open_loop)

        svc = QueryService(store, ServeConfig(max_queue=8))
        try:
            rep = run_open_loop(
                svc, knn_request_factory("served", CQL, k=4),
                rate_qps=500.0, duration_s=2.0)
        finally:
            svc.close(drain=True)
        # over-capacity offered load resolves as served + shed, never as
        # an unbounded queue
        assert rep.sent == rep.ok + rep.rejected + rep.timeouts + rep.errors
        assert rep.ok > 0
