"""SPMD pass tests (gmtpu-lint GT24..GT27) + the incremental engine.

Per rule: a dirty fixture (exact rule codes + line numbers), a clean
twin for every precision guard (interprocedural binding, parameter
axes, gate recognition, path scoping), and the waiver channel. The
pre-fix shapes of every true positive this pass found on the shipped
tree — the ungated sidecar/manifest/metadata writes, the env-switched
x64 branch, the unbound/misarity drafts of the multi-host uniformity
probe — are replayed as faithful excerpts so a regression that stops a
rule matching its real catch fails here, not in production review.

Fixtures are miniature repo skeletons (pyproject.toml +
geomesa_tpu/<subsystem>/mod.py): GT25's multi-process reachability and
GT27's subsystem scoping key on project-relative paths, so a bare
tmp-file fixture would silently skip both rules.

Also here: the incremental lint engine's contract — warm and partial
runs byte-identical to a cold scan (render_json equality), warm replay
with zero re-analysis, corrupted-cache fallback — and the single-process
runtime behavior of the new parallel.distributed helpers
(is_coordinator / process_suffix / runtime_fingerprint /
assert_uniform_runtime).
"""

import json
import os
import textwrap

import pytest

from geomesa_tpu.analysis.incremental import (
    DEFAULT_CACHE_FILENAME, lint_paths_incremental)
from geomesa_tpu.analysis.linter import exit_code, lint_paths, render_json

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPMD = ["GT24", "GT25", "GT26", "GT27"]


def write_tree(tmp_path, files):
    """Materialize a miniature repo: pyproject.toml marks the root so
    fixture modules get project-relative paths (geomesa_tpu/...)."""
    (tmp_path / "pyproject.toml").write_text(
        "[project]\nname = \"spmd-fixture\"\n")
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def lint_tree(tmp_path, files, rules=SPMD, **kw):
    write_tree(tmp_path, files)
    return lint_paths([str(tmp_path / "geomesa_tpu")], rules=rules,
                      extra_ref_paths=[], **kw)


def active(findings):
    return [f for f in findings if not f.waived]


def codes_lines(findings):
    return {(f.rule, f.line) for f in active(findings)}


# -- GT24: unbound collective axis ------------------------------------------


class TestGT24UnboundCollective:
    def test_unbound_helper_and_module_level(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/ops.py": """\
            import jax
            from jax import lax


            def merge(x):
                return lax.psum(x, "shard")


            TOTAL = lax.psum(1, "shard")
        """})
        got = codes_lines(fs)
        assert ("GT24", 6) in got    # helper: axis bound nowhere
        assert ("GT24", 9) in got    # module level: nothing CAN bind it
        assert all(f.rule == "GT24" for f in active(fs))

    def test_clean_decorator_wrap_binds(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/ops.py": """\
            import functools

            import jax
            import numpy as np
            from jax import lax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P

            AXIS = "shard"


            def mesh():
                return Mesh(np.array(jax.devices()), (AXIS,))


            @functools.partial(shard_map, mesh=mesh(), in_specs=(P(AXIS),),
                               out_specs=P(AXIS), check_vma=False)
            def merge(x):
                return lax.psum(x, AXIS)
        """})
        assert not active(fs)

    def test_clean_interprocedural_caller_binding(self, tmp_path):
        # the _shard_merge_topk shape: the collective lives in a helper
        # whose ONLY callers are shard_map-wrapped — bound through the
        # calling context, not lexically
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/ops.py": """\
            import functools

            import jax
            import numpy as np
            from jax import lax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P


            def _merge(x):
                return lax.pmax(x, "shard")


            def run(mesh, v):
                @functools.partial(shard_map, mesh=mesh,
                                   in_specs=(P("shard"),),
                                   out_specs=P())
                def kern(s):
                    return _merge(s)

                return kern(v)
        """})
        assert not active(fs)

    def test_clean_parameter_axis_skipped(self, tmp_path):
        # axis-generic helpers (jaxcompat.pcast shape) stay silent
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/ops.py": """\
            from jax import lax


            def pcast(x, axis_name):
                return lax.all_gather(x, axis_name)
        """})
        assert not active(fs)

    def test_dirty_caller_does_not_bind(self, tmp_path):
        # a caller exists but nothing in the chain ever binds the axis
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/ops.py": """\
            from jax import lax


            def _merge(x):
                return lax.psum(x, "shard")


            def run(v):
                return _merge(v)
        """})
        assert ("GT24", 5) in codes_lines(fs)


# -- GT25: process-divergent control flow -----------------------------------


class TestGT25ProcessDivergence:
    def test_dirty_process_branch_on_entry_path(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/launch.py": """\
            import jax


            def boot():
                if jax.process_index() == 0:
                    jax.config.update("jax_enable_x64", True)
        """})
        assert ("GT25", 5) in codes_lines(fs)

    def test_dirty_env_branch_divergent_collectives(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/app.py": """\
            import os

            import jax
            from jax import lax


            def step(x):
                if os.environ.get("FAST_PATH") == "1":
                    return lax.psum(x, "shard")
                return lax.pmean(x, "shard")
        """})
        assert any(f.rule == "GT25" and f.line == 8 for f in active(fs))

    def test_clean_identical_arms(self, tmp_path):
        # divergence is about COLLECTIVE-RELEVANT effects, not any
        # branch: logging per process rank is fine
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/launch.py": """\
            import jax
            from jax import lax


            def step(x):
                if jax.process_index() == 0:
                    print("coordinator")
                return lax.psum(x, "shard")
        """}, rules=["GT25"])
        assert not active(fs)

    def test_clean_unreachable_module_scope_twin(self, tmp_path):
        # byte-identical branch in a module no multi-process entry
        # imports: out of scope, no finding
        fs = lint_tree(tmp_path, {"geomesa_tpu/cql/helpers.py": """\
            import jax


            def boot():
                if jax.process_index() == 0:
                    jax.config.update("jax_enable_x64", True)
        """})
        assert not active(fs)

    def test_waiver_twin(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/launch.py": """\
            import jax


            def boot():
                # gt: waive GT25
                if jax.process_index() == 0:
                    jax.config.update("jax_enable_x64", True)
        """})
        assert not active(fs)
        assert any(f.rule == "GT25" and f.waived for f in fs)


# -- GT26: sharding-spec drift ----------------------------------------------


class TestGT26SpecDrift:
    def test_dirty_ghost_axis_and_arity(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/ops.py": """\
            import jax
            import numpy as np
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


            def kernel(a):
                return a


            def run():
                mesh = Mesh(np.array(jax.devices()), ("data",))
                spec = NamedSharding(mesh, P("ghost"))
                wrapped = shard_map(kernel, mesh=mesh,
                                    in_specs=(P("data"), P("data")),
                                    out_specs=P("data"))
                return wrapped, spec
        """})
        got = codes_lines(fs)
        assert ("GT26", 13) in got    # ghost not bound by ("data",)
        assert ("GT26", 14) in got    # 2 in_specs, kernel takes 1
        assert all(f.rule == "GT26" for f in active(fs))

    def test_clean_matching_axes_and_arity(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/ops.py": """\
            import jax
            import numpy as np
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


            def kernel(a, b):
                return a + b


            def run():
                mesh = Mesh(np.array(jax.devices()), ("data",))
                spec = NamedSharding(mesh, P("data"))
                wrapped = shard_map(kernel, mesh=mesh,
                                    in_specs=(P("data"), P("data")),
                                    out_specs=P("data"))
                return wrapped, spec
        """})
        assert not active(fs)

    def test_clean_vararg_mapped_fn_skipped(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/ops.py": """\
            import jax
            import numpy as np
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P


            def kernel(*args):
                return args


            def run():
                mesh = Mesh(np.array(jax.devices()), ("data",))
                return shard_map(kernel, mesh=mesh,
                                 in_specs=(P("data"), P("data")),
                                 out_specs=P("data"))
        """})
        assert not active(fs)

    def test_clean_unresolvable_mesh_unknown_axis(self, tmp_path):
        # mesh arrives as a parameter AND no project mesh exists: the
        # axis universe is empty, so the rule stays conservative
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/ops.py": """\
            from jax.sharding import NamedSharding, PartitionSpec as P


            def place(mesh):
                return NamedSharding(mesh, P("anything"))
        """})
        assert not active(fs)


# -- GT27: ungated process-local side effects -------------------------------


class TestGT27UngatedSideEffects:
    def test_dirty_persist_and_bind(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/store/meta.py": """\
                import os


                def save(path, doc):
                    tmp = path + ".tmp"
                    with open(tmp, "w") as fh:
                        fh.write(doc)
                    os.replace(tmp, path)
            """,
            "geomesa_tpu/serve/http.py": """\
                from http.server import ThreadingHTTPServer


                def start(handler, port):
                    return ThreadingHTTPServer(("0.0.0.0", port), handler)
            """,
        })
        got = codes_lines(fs)
        assert ("GT27", 8) in got    # os.replace in store/
        assert ("GT27", 5) in got    # port bind in serve/
        assert all(f.rule == "GT27" for f in active(fs))

    def test_clean_entry_gate(self, tmp_path):
        # the shape every fixed site in this repo uses: coordinator
        # early-return at function entry
        fs = lint_tree(tmp_path, {"geomesa_tpu/store/meta.py": """\
            import os

            from geomesa_tpu.parallel.distributed import is_coordinator


            def save(path, doc):
                if not is_coordinator():
                    return
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    fh.write(doc)
                os.replace(tmp, path)
        """})
        assert not active(fs)

    def test_clean_inline_if_gate(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/store/meta.py": """\
            import os

            import jax


            def save(path, doc):
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    fh.write(doc)
                if jax.process_index() == 0:
                    os.replace(tmp, path)
        """})
        assert not active(fs)

    def test_clean_path_scope_twin(self, tmp_path):
        # identical persist outside the multi-host subsystems (a CLI
        # report writer, say) is out of scope
        fs = lint_tree(tmp_path, {"geomesa_tpu/cql/report.py": """\
            import os


            def save(path, doc):
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    fh.write(doc)
                os.replace(tmp, path)
        """})
        assert not active(fs)

    def test_clean_caller_gated_helper(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/store/meta.py": """\
            import os

            from geomesa_tpu.parallel.distributed import is_coordinator


            def _persist(tmp, path):
                os.replace(tmp, path)


            def save(path, doc):
                if not is_coordinator():
                    return
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    fh.write(doc)
                _persist(tmp, path)
        """})
        assert not active(fs)

    def test_waiver_twin(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/store/meta.py": """\
            import os


            def save(path, doc):
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    fh.write(doc)
                # gt: waive GT27
                os.replace(tmp, path)
        """})
        assert not active(fs)
        assert any(f.rule == "GT27" and f.waived for f in fs)


# -- pre-fix replays: the true positives this pass caught --------------------


class TestPreFixReplays:
    """Faithful excerpts of the shipped code BEFORE this PR's fixes.
    Each must still fire; its committed post-fix twin is covered by the
    self-lint test below (the real tree is the clean fixture)."""

    def test_sketch_sidecar_prefix(self, tmp_path):
        # approx/sketches.py save_sidecar before the coordinator gate
        fs = lint_tree(tmp_path, {"geomesa_tpu/approx/sketches.py": """\
            import json
            import os


            def save_sidecar(path, doc):
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as fh:
                    json.dump(doc, fh)
                os.replace(tmp, path)
                return path
        """})
        assert ("GT27", 9) in codes_lines(fs)

    def test_warmup_manifest_prefix(self, tmp_path):
        # compilecache/manifest.py WarmupManifest.save before the gate:
        # the persist lives in a nested retry closure — the rule must
        # see through it
        fs = lint_tree(tmp_path, {"geomesa_tpu/compilecache/manifest.py": """\
            import json
            import os


            class WarmupManifest:
                def save(self, path):
                    def attempt():
                        tmp = f"{path}.tmp.{os.getpid()}"
                        with open(tmp, "w") as fh:
                            json.dump({}, fh)
                        os.replace(tmp, path)

                    attempt()
        """})
        assert ("GT27", 11) in codes_lines(fs)

    def test_store_metadata_prefix(self, tmp_path):
        # store/fs.py _save_metadata before the gate
        fs = lint_tree(tmp_path, {"geomesa_tpu/store/fs.py": """\
            import json
            import os


            def _save_metadata(root, doc):
                path = os.path.join(root, "metadata.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    json.dump(doc, fh)
                os.replace(tmp, path)
        """})
        assert ("GT27", 10) in codes_lines(fs)

    def test_x64_env_branch_prefix(self, tmp_path):
        # engine/device.py's env-switched x64 config before the waiver +
        # runtime fingerprint check: reachable from the serve layer, one
        # arm reshapes every compiled program
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/serve/service.py": """\
                from geomesa_tpu.engine import device
            """,
            "geomesa_tpu/engine/device.py": """\
                import os

                import jax

                if os.environ.get("GEOMESA_TPU_ENABLE_X64", "1") == "1":
                    jax.config.update("jax_enable_x64", True)
            """,
        })
        assert any(f.rule == "GT25" and f.path.endswith("device.py")
                   for f in active(fs))

    def test_uniform_runtime_probe_draft_unbound(self, tmp_path):
        # the first draft of assert_uniform_runtime ran its pmin/pmax
        # in a bare helper — no wrap, axis bound nowhere (GT24 caught
        # it during this PR's multi-host helper work)
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/distributed.py": """\
            import jax
            from jax import lax

            AXIS = "shard"


            def _minmax(v):
                return (lax.pmin(v, AXIS), lax.pmax(v, AXIS))


            def assert_uniform_runtime(vals):
                lo, hi = _minmax(vals)
                if int(lo) != int(hi):
                    raise RuntimeError("divergent runtime")
        """})
        got = {(f.rule, f.line) for f in active(fs)}
        assert ("GT24", 8) in got

    def test_uniform_runtime_probe_draft_arity(self, tmp_path):
        # the second draft passed two in_specs to a one-argument mapped
        # function (GT26 caught the copy-paste from a two-input kernel)
        fs = lint_tree(tmp_path, {"geomesa_tpu/parallel/distributed.py": """\
            import functools

            import jax
            import numpy as np
            from jax import lax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P

            AXIS = "shard"


            def assert_uniform_runtime(vals):
                mesh = Mesh(np.array(jax.devices()), (AXIS,))

                @functools.partial(shard_map, mesh=mesh,
                                   in_specs=(P(AXIS), P(AXIS)),
                                   out_specs=(P(), P()))
                def minmax(v):
                    return (lax.pmin(v[0], AXIS), lax.pmax(v[0], AXIS))

                return minmax(vals)
        """})
        assert any(f.rule == "GT26" for f in active(fs))


# -- self-lint: the shipped tree is the clean fixture ------------------------


class TestSelfLint:
    def test_shipped_tree_spmd_clean(self):
        fs = lint_paths([os.path.join(REPO_ROOT, "geomesa_tpu")],
                        rules=SPMD)
        assert not active(fs), [f.render() for f in active(fs)]
        # the justified waivers are present, not silently lost
        assert any(f.rule == "GT25" and f.waived for f in fs)
        assert any(f.rule == "GT27" and f.waived for f in fs)
        assert exit_code(fs, "warn") == 0


# -- incremental engine ------------------------------------------------------


class TestIncremental:
    FILES = {
        "geomesa_tpu/parallel/ops.py": """\
            import jax
            from jax import lax


            def merge(x):
                return lax.psum(x, "shard")
        """,
        "geomesa_tpu/store/meta.py": """\
            import os


            def save(path, doc):
                tmp = path + ".tmp"
                with open(tmp, "w") as fh:
                    fh.write(doc)
                os.replace(tmp, path)
        """,
        "geomesa_tpu/cql/util.py": """\
            def ident(x):
                return x
        """,
    }

    def test_warm_and_partial_byte_identical(self, tmp_path):
        write_tree(tmp_path, self.FILES)
        scan = [str(tmp_path / "geomesa_tpu")]
        cold = lint_paths(scan)
        inc1 = lint_paths_incremental(scan)   # populates the cache
        assert (tmp_path / DEFAULT_CACHE_FILENAME).exists()
        inc2 = lint_paths_incremental(scan)   # warm replay
        assert render_json(cold) == render_json(inc1) == render_json(inc2)

        # edit: a new violation must surface through the cache, and the
        # rest of the replayed findings must still match a cold scan
        mod = tmp_path / "geomesa_tpu" / "cql" / "util.py"
        mod.write_text(textwrap.dedent("""\
            import jax


            @jax.jit
            def bad(x):
                return float(x)
        """))
        inc3 = lint_paths_incremental(scan)
        cold3 = lint_paths(scan)
        assert render_json(cold3) == render_json(inc3)
        assert any(f.path.endswith("util.py") for f in active(inc3))
        # and the pre-edit findings are still there (replayed, not lost)
        assert codes_lines(inc1) <= codes_lines(inc3)

    def test_warm_replay_does_not_reparse(self, tmp_path, monkeypatch):
        write_tree(tmp_path, self.FILES)
        scan = [str(tmp_path / "geomesa_tpu")]
        lint_paths_incremental(scan)
        import geomesa_tpu.analysis.incremental as inc_mod

        def boom(*a, **k):
            raise AssertionError("warm replay must not build a project")

        monkeypatch.setattr(inc_mod, "build_project", boom)
        warm = lint_paths_incremental(scan)
        assert warm  # the fixture has findings and they replayed

    def test_corrupted_cache_falls_back_cold(self, tmp_path):
        write_tree(tmp_path, self.FILES)
        scan = [str(tmp_path / "geomesa_tpu")]
        cold = lint_paths(scan)
        (tmp_path / DEFAULT_CACHE_FILENAME).write_text("{not json")
        inc = lint_paths_incremental(scan)
        assert render_json(cold) == render_json(inc)
        # and the rewrite repaired the cache: next run replays warm
        doc = json.loads((tmp_path / DEFAULT_CACHE_FILENAME).read_text())
        assert doc["findings"]

    def test_waiver_file_change_invalidates(self, tmp_path):
        write_tree(tmp_path, self.FILES)
        scan = [str(tmp_path / "geomesa_tpu")]
        before = lint_paths_incremental(scan)
        assert any(f.rule == "GT24" and not f.waived for f in before)
        (tmp_path / ".gmtpu-waivers").write_text(
            "# fixture waiver\ngeomesa_tpu/parallel/ops.py GT24\n")
        after = lint_paths_incremental(scan)
        cold = lint_paths(scan)
        assert render_json(cold) == render_json(after)
        assert not [f for f in active(after) if f.rule == "GT24"]


# -- runtime behavior of the new distributed helpers -------------------------


class TestDistributedHelpers:
    def test_is_coordinator_single_process(self):
        from geomesa_tpu.parallel import is_coordinator

        assert is_coordinator() is True

    def test_process_suffix_single_process(self):
        from geomesa_tpu.parallel.distributed import process_suffix

        assert process_suffix() == ""

    def test_runtime_fingerprint_deterministic(self):
        from geomesa_tpu.parallel.distributed import runtime_fingerprint

        a, b = runtime_fingerprint(), runtime_fingerprint()
        assert a == b
        assert 0 <= a < 2 ** 31

    def test_assert_uniform_runtime_single_process(self):
        # one process is trivially uniform; the probe must be a cheap
        # no-op-equivalent, not a crash, on CPU CI
        from geomesa_tpu.parallel.distributed import assert_uniform_runtime

        assert_uniform_runtime()

    def test_flight_dump_path_unsuffixed_single_process(self, tmp_path):
        from geomesa_tpu.telemetry.recorder import FlightRecorder

        r = FlightRecorder()
        r.note_event("unit")
        out = r.dump(path=str(tmp_path / "dump.json"))
        assert out == str(tmp_path / "dump.json")
        assert json.load(open(out))["event_count"] == 1
