"""Zero-recompile serving: persistent cache, AOT registry, warmup
manifests.

The load-bearing test is
TestServeWarmup::test_zero_recompiles_after_warmup — a mixed kNN/count
workload recorded into a manifest, engine jit caches dropped (the
in-process stand-in for a fresh process), the manifest replayed through
QueryService.warmup(), and the SAME workload run twice with JitTracker
proving ZERO engine recompiles — the serving cold-start contract of
docs/SERVING.md's "Cold start" section.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from geomesa_tpu.compilecache.manifest import (
    KernelEntry, QueryEntry, UnrecordableArg, WarmupManifest,
    WarmupRecorder, encode_arg)
from geomesa_tpu.compilecache.registry import ExecutableRegistry
from geomesa_tpu.compilecache import warmup as cc_warmup
from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.audit import ServeEvent
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.serve.service import QueryService, ServeConfig
from geomesa_tpu.utils.metrics import Histogram

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CQL = "BBOX(geom, -170, -80, 170, 80) AND score > -5"


def make_store(tmp_path_factory, n=600, seed=3):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec(
        "served", "name:String,score:Double,dtg:Date,*geom:Point")
    batch = FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })
    ds = DataStore(
        str(tmp_path_factory.mktemp("compilecache")), use_device_cache=True)
    ds.create_schema(sft).write(batch)
    return ds


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    return make_store(tmp_path_factory)


def run_mixed_workload(svc, knn=6, counts=3):
    """The serving workload shape of the regression: compatible kNN
    requests (coalesce into one padded [8] launch) + count dedup."""
    rng = np.random.default_rng(11)
    pts = rng.uniform(-60, 60, (knn, 2))
    futs = [svc.knn("served", CQL, pts[i:i + 1, 0], pts[i:i + 1, 1], k=5)
            for i in range(knn)]
    cfuts = [svc.count("served", CQL) for _ in range(counts)]
    out = [f.result(timeout=120) for f in futs]
    out += [f.result(timeout=120) for f in cfuts]
    return out


# -- persistent cache ------------------------------------------------------


class TestPersistentCache:
    def test_enable_idempotent_and_per_platform(self, tmp_path):
        import jax

        from geomesa_tpu.compilecache import persist

        old_dir = persist._enabled_dir
        old_cfg = jax.config.jax_compilation_cache_dir
        try:
            got = persist.enable_persistent_cache(
                str(tmp_path / "cc"), force=True)
            assert got is not None
            # per-backend subdir: CPU and TPU artifacts never mix
            assert os.path.basename(got) == jax.default_backend()
            assert os.path.isdir(got)
            assert jax.config.jax_compilation_cache_dir == got
            # idempotent: a later default call does not move the cache
            again = persist.enable_persistent_cache()
            assert again == got
            assert persist.persistent_cache_dir() == got
        finally:
            persist._enabled_dir = old_dir
            jax.config.update("jax_compilation_cache_dir", old_cfg)

    def test_disable_token(self):
        from geomesa_tpu.compilecache import persist

        old_dir = persist._enabled_dir
        try:
            assert persist.enable_persistent_cache("off", force=True) is None
        finally:
            persist._enabled_dir = old_dir


# -- metrics: sub-millisecond buckets --------------------------------------


class TestSubMillisecondBuckets:
    def test_sub_ms_timings_resolve(self):
        h = Histogram()
        assert h.bounds[0] < 0.0005  # explicit sub-ms buckets exist
        for _ in range(100):
            h.update(0.00003)  # a 30µs dispatch
        # previously everything below 0.5ms hit the bottom bucket and
        # quantiles reported up to 0.5ms; now they resolve to ~µs scale
        assert h.quantile(0.99) <= 0.0001

    def test_compile_scale_still_fits(self):
        h = Histogram()
        h.update(120.0)  # a cold Mosaic compile through the tunnel
        assert h.quantile(0.5) >= 1.0


# -- ExecutableRegistry ----------------------------------------------------


class TestExecutableRegistry:
    def test_aot_compile_hit_miss_and_call(self):
        import jax
        import jax.numpy as jnp

        reg = ExecutableRegistry()
        reg.register("t.add", jax.jit(lambda a, b: a + b))
        sds = jax.ShapeDtypeStruct((4,), jnp.float32)
        h = reg.compile("t.add", sds, sds)
        assert reg.stats()["misses"] == 1
        out = h.call(jnp.ones(4, jnp.float32),
                     jnp.full(4, 2.0, jnp.float32))
        np.testing.assert_allclose(np.asarray(out), 3.0)
        # same signature from CONCRETE arrays keys identically: hit
        h2 = reg.compile("t.add", jnp.zeros(4, jnp.float32),
                         jnp.zeros(4, jnp.float32))
        assert h2 is h
        assert reg.stats()["hits"] == 1
        # a different bucket is a different executable
        sds8 = jax.ShapeDtypeStruct((8,), jnp.float32)
        assert reg.compile("t.add", sds8, sds8) is not h
        with pytest.raises(KeyError):
            reg.compile("t.missing", sds)

    def test_static_args_baked_into_executable(self):
        import jax
        import jax.numpy as jnp

        reg = ExecutableRegistry()
        reg.register("t.mul", jax.jit(
            lambda x, n=2: x * n, static_argnames=("n",)))
        h = reg.compile("t.mul", jax.ShapeDtypeStruct((3,), jnp.float32),
                        n=5)
        # AOT contract: statics are baked; call takes only array args
        np.testing.assert_allclose(
            np.asarray(h.call(jnp.ones(3, jnp.float32))), 5.0)

    def test_donation_opt_in(self):
        import jax
        import jax.numpy as jnp

        reg = ExecutableRegistry()
        reg.register("t.don", lambda x: x + 1.0, donate_argnums=(0,))
        h = reg.compile("t.don", jax.ShapeDtypeStruct((3,), jnp.float32))
        np.testing.assert_allclose(
            np.asarray(h.call(jnp.ones(3, jnp.float32))), 2.0)

    def test_install_defaults_covers_hot_kernels(self):
        import jax
        import jax.numpy as jnp

        reg = ExecutableRegistry()
        n = reg.install_defaults()
        assert n > 0
        names = reg.names()
        assert "knn_scan.knn_sparse_scan" in names
        assert "knn_scan.count_match_tiles" in names
        # AOT-compile a real engine kernel per the planner's pow2 bucket
        h = reg.compile(
            "knn_scan.count_match_tiles",
            jax.ShapeDtypeStruct((4096,), jnp.bool_), data_tile=2048)
        assert int(np.asarray(h.call(jnp.zeros(4096, jnp.bool_)))) == 0


# -- manifest record / round-trip ------------------------------------------


class TestManifest:
    def test_encode_args(self):
        import jax.numpy as jnp

        assert encode_arg(jnp.zeros((2, 3), jnp.float32)) == {
            "shape": [2, 3], "dtype": "float32"}
        assert encode_arg(np.zeros(4, bool)) == {
            "shape": [4], "dtype": "bool"}
        assert encode_arg(7) == {"static": 7}
        assert encode_arg(True) == {"static": True}
        with pytest.raises(UnrecordableArg):
            encode_arg({"a": 1})  # pytrees don't record

    def test_recorder_dedups_and_counts(self):
        rec = WarmupRecorder()
        rec.record_kernel("m.x", "f", (np.zeros(4, np.float32),), {}, 1.0)
        rec.record_kernel("m.x", "f", (np.zeros(4, np.float32),), {}, 2.0)
        rec.record_kernel("m.x", "f", (np.zeros(8, np.float32),), {}, 0.5)
        rec.record_query("count", "t", "INCLUDE")
        rec.record_query("count", "t", "INCLUDE")
        m = rec.manifest()
        kernels = {tuple(e.args[0]["shape"]): e for e in m.kernel_entries}
        assert kernels[(4,)].count == 2
        assert kernels[(4,)].compile_s == 2.0  # max observed
        assert kernels[(8,)].count == 1
        assert m.query_entries[0].count == 2

    def test_recorder_skips_unrecordable(self):
        rec = WarmupRecorder()
        rec.record_kernel("m.x", "f", ({"pytree": 1},), {}, 0.0)
        assert rec.skipped == 1
        assert len(rec.manifest()) == 0

    def test_recorder_bounded_on_high_cardinality(self):
        rec = WarmupRecorder(max_entries=4)
        for i in range(10):
            rec.record_query("count", "t", f"score > {i}")
        rec.record_query("count", "t", "score > 0")  # existing key: counts
        m = rec.manifest()
        assert len(m) == 4
        assert rec.skipped == 6
        assert next(e for e in m.query_entries
                    if e.cql == "score > 0").count == 2

    def test_save_load_round_trip(self, tmp_path):
        m = WarmupManifest([
            KernelEntry("geomesa_tpu.engine.knn_scan", "count_match_tiles",
                        [{"shape": [4096], "dtype": "bool"}],
                        {"data_tile": {"static": 2048}}),
            QueryEntry("knn", "served", CQL, q=8, k=5, impl="sparse"),
        ])
        path = str(tmp_path / "m.json")
        m.save(path)
        m2 = WarmupManifest.load(path)
        assert [e.to_json() for e in m2.entries] == [
            e.to_json() for e in m.entries]

    def test_version_gate(self, tmp_path):
        path = str(tmp_path / "bad.json")
        with open(path, "w") as f:
            json.dump({"version": 99, "entries": []}, f)
        with pytest.raises(ValueError):
            WarmupManifest.load(path)


# -- warmup replay / check -------------------------------------------------


FIXTURE = os.path.join(REPO_ROOT, "scripts", "warmup_smoke_manifest.json")


class TestWarmupReplay:
    @pytest.mark.slow  # the tier-1 lint-gate subprocess runs this same
    def test_fixture_manifest_check_passes(self):  # check every CI run
        report = cc_warmup.check(WarmupManifest.load(FIXTURE))
        assert report.kernels_failed == 0
        assert report.residual_recompiles == 0
        assert report.ok

    def test_bad_kernel_entry_fails_soft(self):
        m = WarmupManifest([KernelEntry(
            "geomesa_tpu.engine.knn_scan", "no_such_kernel", [], {})])
        report = cc_warmup.replay(m)
        assert report.kernels_failed == 1
        assert not report.ok
        assert report.errors

    def test_query_entries_without_store_are_skipped(self):
        m = WarmupManifest([QueryEntry("count", "t", "INCLUDE")])
        report = cc_warmup.replay(m)
        assert report.queries_skipped == 1

    @pytest.mark.slow  # compiles the fixture kernels; the lint-gate
    def test_warmup_cli_check(self, capsys):  # smoke covers this in tier-1
        from geomesa_tpu.cli.main import main

        assert main(["warmup", "-m", FIXTURE, "--check"]) == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["residual_recompiles"] == 0

    def test_warmup_cli_check_refuses_unverifiable_queries(
            self, tmp_path, capsys):
        from geomesa_tpu.cli.main import main

        m = WarmupManifest([QueryEntry("count", "t", "INCLUDE")])
        path = str(tmp_path / "q.json")
        m.save(path)
        # query entries with no --catalog: the check proved nothing
        # about the serving path, so a green exit would lie
        assert main(["warmup", "-m", path, "--check"]) == 1


# -- the serving regression ------------------------------------------------


class TestServeWarmup:
    def test_track_compiles_config_installs_tracker(self, store):
        svc = QueryService(store, ServeConfig(track_compiles=True),
                           autostart=False)
        assert svc.tracker is not None
        # the engine jits are module globals: a second service SHARES
        # the installed tracker instead of silently counting nothing
        svc2 = QueryService(store, ServeConfig(track_compiles=True),
                            autostart=False)
        assert svc2.tracker is svc.tracker
        svc2.close()
        # refcounted: closing ONE of two live services must not disable
        # tracking for the survivor
        assert svc.tracker.is_installed()
        svc.close()
        assert not svc.tracker.is_installed()  # last release unwraps
        assert svc.tracker.total_recompiles() >= 0  # readable after close

    def test_acquire_shares_foreign_guard_tracker(self):
        """The gmtpu-guard composition: a tracker installed via bare
        guard_engine() must be SHARED by acquire, never shadowed by a
        dead tracker that wraps (and counts) nothing."""
        import geomesa_tpu.analysis.runtime as rt

        guard = rt.guard_engine()
        try:
            got, owner = rt.acquire_engine_tracker()
            assert got is guard and not owner
            # even with the active slot lost (an installer that predates
            # the slot protocol), the wrapper back-pointers recover it
            with rt._active_lock:
                rt._active_tracker = None
            got2, owner2 = rt.acquire_engine_tracker()
            assert got2 is guard and not owner2
        finally:
            guard.unwrap()
        # after unwrap the modules are bare again: a fresh acquire
        # installs for real
        fresh, owner3 = rt.acquire_engine_tracker()
        try:
            assert owner3 and fresh.is_installed()
        finally:
            rt.release_engine_tracker(fresh)

    def test_failed_constructor_does_not_leak_wrappers(self, store):
        from geomesa_tpu.analysis.runtime import (
            acquire_engine_tracker, release_engine_tracker)

        with pytest.raises(FileNotFoundError):
            QueryService(store, ServeConfig(
                warmup_manifest="no/such/manifest.json",
                track_compiles=True), autostart=False)
        # the failed constructor released the process-global wrappers:
        # a fresh tracker can install (owner=True) and actually wrap
        tracker, owner = acquire_engine_tracker()
        try:
            assert owner and tracker.is_installed()
        finally:
            release_engine_tracker(tracker)

    def test_record_roundtrip_warmup_zero_recompiles(self, store, tmp_path):
        """The whole contract in one lifecycle: a COLD workload records a
        manifest and its dispatches carry compile-stall attribution; the
        manifest survives save/load; after dropping every engine cache
        (fresh-process stand-in) a warmed service runs the same mixed
        workload twice with ZERO JitTracker recompiles and all-zero
        ServeEvent.compile_ms."""
        from geomesa_tpu.analysis.runtime import clear_engine_jit_caches

        # --- record phase (cold caches so the kernel tuples appear) ---
        if clear_engine_jit_caches() == 0:
            pytest.skip("this jax has no jit clear_cache")
        svc1 = QueryService(store, ServeConfig(max_wait_ms=20.0),
                            autostart=False)
        rec = svc1.record_warmup()
        svc1.start()
        audit0 = len(store.audit.snapshot())
        run_mixed_workload(svc1)
        svc1.close(drain=True)
        # the cold kNN dispatch compiled inline: the audit record names
        # the kernel and carries the stall — the p99 forensics contract
        cold = [e for e in store.audit.snapshot()[audit0:]
                if isinstance(e, ServeEvent)]
        stalled = [e for e in cold if e.compile_ms > 0]
        assert stalled, [(e.compiled, e.compile_ms) for e in cold]
        assert any("knn" in e.compiled for e in stalled)
        manifest = rec.manifest()
        assert manifest.kernel_entries, (
            "cold workload must record compiling kernel signatures")
        # the workload dispatched knn + count: both query shapes recorded
        ops = {e.op for e in manifest.query_entries}
        assert {"knn", "count"} <= ops
        knn_entry = next(e for e in manifest.query_entries
                         if e.op == "knn")
        assert knn_entry.q == 8  # padded pow2 stacked-query bucket

        # --- save -> load round trip ----------------------------------
        path = str(tmp_path / "serve_manifest.json")
        manifest.save(path)
        loaded = WarmupManifest.load(path)
        assert [e.to_json() for e in loaded.entries] == [
            e.to_json() for e in manifest.entries]

        # --- fresh "process": drop every engine dispatch cache --------
        assert clear_engine_jit_caches() > 0

        # --- warmup (+check), then the workload compiles NOTHING ------
        svc2 = QueryService(store, ServeConfig(max_wait_ms=20.0),
                            autostart=False)
        from geomesa_tpu.utils.metrics import metrics

        stalls0 = metrics.counters.get("compile.stalls", 0.0)
        report = svc2.warmup(path, check=True)
        # warmup compiles are ahead-of-time by definition: the inline
        # stall counter (what operators alert on) must not move
        assert metrics.counters.get("compile.stalls", 0.0) == stalls0
        assert report.kernels_failed == 0 and report.queries_failed == 0
        assert report.residual_recompiles == 0
        # warmup did the compiling (query-entry replay may warm a kernel
        # before its own kernel entry comes up — either way the tracker
        # saw the compiles happen inside warmup, not under traffic)
        base = svc2.tracker.total_recompiles()
        assert base >= 1
        svc2.start()
        audit1 = len(store.audit.snapshot())
        run_mixed_workload(svc2)
        run_mixed_workload(svc2)
        svc2.close(drain=True)
        assert svc2.tracker.total_recompiles() == base, (
            f"workload recompiled after warmup: {svc2.tracker.report()}")
        assert svc2.stats()["recompiles"] == base
        # and the audit trail agrees: no dispatch carried a kernel
        # compile stall (filter compiles were warmed by the query replay)
        events = [e for e in store.audit.snapshot()[audit1:]
                  if isinstance(e, ServeEvent)]
        assert events
        assert all(e.compile_ms == 0.0 for e in events), (
            [(e.compiled, e.compile_ms) for e in events])


# -- GT13 ------------------------------------------------------------------


class TestGT13:
    def _findings(self, src, relpath):
        from geomesa_tpu.analysis.modinfo import ModInfo
        from geomesa_tpu.analysis.rules import gt13

        mod = ModInfo("/x.py", src, relpath=relpath)
        return list(gt13(mod, None))

    SRC = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x + 1\n"
        "g = jax.jit(lambda x: x * 2)\n"
    )

    def test_flags_serve_and_plan_jits(self):
        found = self._findings(self.SRC, "geomesa_tpu/serve/fast.py")
        assert len(found) == 2
        assert all(f.rule == "GT13" for f in found)
        assert self._findings(self.SRC, "geomesa_tpu/plan/hot.py")

    def test_engine_and_elsewhere_out_of_scope(self):
        assert self._findings(self.SRC, "geomesa_tpu/engine/kernel.py") == []
        assert self._findings(self.SRC, "bench.py") == []

    def test_from_import_alias_decorator(self):
        src = ("from jax import jit\n"
               "@jit\n"
               "def f(x):\n"
               "    return x\n")
        assert self._findings(src, "geomesa_tpu/serve/x.py")

    def test_registered_rule_and_shipped_tree_clean(self):
        from geomesa_tpu.analysis.model import RULES
        from geomesa_tpu.analysis.rules import ALL_RULES

        assert "GT13" in RULES and "GT13" in ALL_RULES


# -- lint gate smoke -------------------------------------------------------


@pytest.mark.slow
def test_lint_gate_runs_warmup_smoke():
    """The gate's text mode ends with the warmup smoke; json mode keeps
    stdout machine-pure (test_lint_gate.py parses it)."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "lint_gate.py")],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "warmup smoke" in r.stderr
