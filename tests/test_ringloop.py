"""Persistent on-device serve loop (serve/ringloop.py + planner ring
tier).

The load-bearing assertions, per the acceptance contract:

- **bit-identity**: K>=16 consecutive coalesced windows through the
  ring path equal the serial route (and the ring-off pipelined route)
  bit for bit, fused count riders included — same kernels, same frozen
  f64-exact mask, same `_canonical_dists` recompute;
- **zero per-window compiles**: after warmup the ring serves from ONE
  armed AOT program (JitTracker sees no recompiles across the run);
- **dispatch amortization**: `dispatches_per_window` (the
  serve.device.ops delta per window) is strictly below the PR-7
  pipelined baseline on CPU CI — the structural form of the TPU
  dispatch-RTT claim;
- **typed fallback**: a write makes the armed program stale → the next
  window takes the pipelined route (fresh residency) and the ring
  re-arms; a fault-injected slot-write OOM runs the batcher's halving
  ladder from host copies exactly like a pipelined window;
- **drain/close**: every in-flight window is harvested exactly once.

Shapes deliberately mirror tests/test_pipeline.py (600-row store, k=5,
single-point windows padding to the same pow2 bucket) so the kernel jit
caches stay warm across the suite — the ROADMAP wall-time rule.
"""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.query import Query
from geomesa_tpu.serve import QueryService, ServeConfig
from geomesa_tpu.serve.loadgen import device_ops_count

CQL = "BBOX(geom, -170, -80, 170, 80) AND score > -5"
WINDOWS = 18  # >= 16 consecutive ring windows (acceptance floor)


def make_batch(n=600, seed=3, start=0):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec(
        "served", "name:String,score:Double,dtg:Date,*geom:Point")
    return sft, FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    sft, batch = make_batch()
    ds = DataStore(
        str(tmp_path_factory.mktemp("ringloop")), use_device_cache=True)
    ds.create_schema(sft).write(batch)
    return ds


@pytest.fixture(scope="module")
def qpts():
    return np.random.default_rng(42).uniform(-60, 60, (WINDOWS + 4, 2))


@pytest.fixture(scope="module")
def serial_oracle(store, qpts):
    src = store.get_feature_source("served")
    return [src.planner.knn(Query("served", CQL), qpts[i:i + 1, 0],
                            qpts[i:i + 1, 1], k=5)
            for i in range(len(qpts))]


def _sequential(store, qpts, config, lo=0, hi=WINDOWS, svc=None):
    """`hi - lo` consecutive single-request windows (each resolves
    before the next submits — the steady-state serve shape the ring
    exists for). Returns (results, pipeline stats)."""
    own = svc is None
    if own:
        svc = QueryService(store, config)
    try:
        out = []
        for i in range(lo, hi):
            out.append(svc.knn("served", CQL, qpts[i:i + 1, 0],
                               qpts[i:i + 1, 1], k=5).result(timeout=300))
        return out, svc.stats()["pipeline"]
    finally:
        if own:
            svc.close(drain=True)


class TestRingIdentity:
    def test_ring_bit_identical_to_serial_and_pipelined(
            self, store, qpts, serial_oracle):
        """Acceptance: K>=16 consecutive windows, ring vs serial vs
        ring-off pipelined — identical bits, every window past warmup
        on ONE armed program with zero fallbacks."""
        ring_res, ring_p = _sequential(
            store, qpts, ServeConfig(max_wait_ms=1.0))
        pipe_res, pipe_p = _sequential(
            store, qpts, ServeConfig(max_wait_ms=1.0, ring=False))
        for i in range(WINDOWS):
            d, ix, _ = ring_res[i]
            sd, six, _ = serial_oracle[i]
            np.testing.assert_array_equal(d, sd, err_msg=f"knn {i}")
            np.testing.assert_array_equal(ix, six, err_msg=f"knn {i}")
            pd, pix, _ = pipe_res[i]
            np.testing.assert_array_equal(d, pd, err_msg=f"knn {i}")
            np.testing.assert_array_equal(ix, pix, err_msg=f"knn {i}")
        ring = ring_p["ring"]
        assert ring["windows"] == WINDOWS
        assert ring["armed"] == 1 and ring["programs"] == 1
        assert ring["fallbacks"] == {}
        assert "ring" not in pipe_p

    def test_fused_count_rider_resolves_from_armed_scalar(self, store):
        """COUNT riders on a ring window resolve from the arm-time mask
        reduction — equal to planner.count, zero extra dispatches."""
        src = store.get_feature_source("served")
        exact = src.planner.count(Query("served", CQL))
        rng = np.random.default_rng(7)
        pts = rng.uniform(-60, 60, (5, 2))
        svc = QueryService(store, ServeConfig(max_wait_ms=50.0),
                           autostart=False)
        # warm window first so the riders land on a WARM ring program
        warm = svc.knn("served", CQL, pts[0:1, 0], pts[0:1, 1], k=5)
        svc.start()
        warm.result(timeout=300)
        futs = [svc.knn("served", CQL, pts[i:i + 1, 0], pts[i:i + 1, 1],
                        k=5) for i in range(1, 4)]
        cfuts = [svc.count("served", CQL) for _ in range(3)]
        for f in futs:
            f.result(timeout=300)
        counts = [f.result(timeout=300) for f in cfuts]
        st = svc.stats()["pipeline"]
        svc.close(drain=True)
        assert all(c == exact for c in counts)
        assert st["fused_counts"] >= 1
        assert st["ring"]["windows"] >= 1

    def test_zero_recompiles_after_warmup(self, store, qpts):
        """JitTracker across the post-warmup run: the ring path traces
        and compiles NOTHING per window (the AOT handle is armed
        once)."""
        svc = QueryService(store, ServeConfig(max_wait_ms=1.0,
                                              track_compiles=True))
        try:
            _sequential(store, qpts, None, lo=0, hi=2, svc=svc)  # warmup
            base = svc.tracker.total_recompiles()
            _sequential(store, qpts, None, lo=2, hi=WINDOWS, svc=svc)
            assert svc.tracker.total_recompiles() == base
            ring = svc.stats()["pipeline"]["ring"]
            assert ring["windows"] == WINDOWS
            assert ring["armed"] == 1
        finally:
            svc.close(drain=True)

    def test_dispatches_per_window_strictly_below_pipelined(
            self, store, qpts):
        """Acceptance: the measured per-window device-interaction count
        (serve.device.ops delta / windows) on the ring route is
        STRICTLY below the PR-7 pipelined baseline for identical
        work."""
        def measured(config):
            svc = QueryService(store, config)
            try:
                _sequential(store, qpts, None, lo=0, hi=2, svc=svc)
                o0 = device_ops_count()
                _sequential(store, qpts, None, lo=2, hi=WINDOWS, svc=svc)
                return (device_ops_count() - o0) / (WINDOWS - 2)
            finally:
                svc.close(drain=True)

        ring_pw = measured(ServeConfig(max_wait_ms=1.0))
        pipe_pw = measured(ServeConfig(max_wait_ms=1.0, ring=False))
        assert ring_pw < pipe_pw, (ring_pw, pipe_pw)

    def test_sustained_loadgen_reports_ring_fields(self, store):
        from geomesa_tpu.serve import knn_request_factory, run_sustained

        svc = QueryService(store, ServeConfig(max_wait_ms=1.0))
        try:
            rep = run_sustained(
                svc, knn_request_factory("served", CQL, k=5),
                duration_s=30.0, max_outstanding=4,
                points_per_query=600, requests=10)
        finally:
            svc.close(drain=True)
        assert rep.ok == 10 and rep.errors == 0
        assert rep.ring_windows >= 1
        assert rep.dispatches_per_window > 0
        doc = rep.to_json()
        assert doc["ring_windows"] == rep.ring_windows
        assert doc["dispatches_per_window"] == rep.dispatches_per_window


class TestRingFallbacks:
    def test_write_goes_stale_then_rearms_fresh(self, tmp_path):
        """A committed write makes the armed program stale: the next
        window takes the pipelined route (fresh residency, new rows
        visible at the batch boundary) and the ring re-arms against
        the new version — results stay exact throughout."""
        sft, batch = make_batch(n=300, seed=11)
        ds = DataStore(str(tmp_path), use_device_cache=True)
        src = ds.create_schema(sft)
        src.write(batch)
        rng = np.random.default_rng(5)
        pts = rng.uniform(-60, 60, (8, 2))
        svc = QueryService(ds, ServeConfig(max_wait_ms=1.0))
        try:
            for i in range(4):
                svc.knn("served", CQL, pts[i:i + 1, 0], pts[i:i + 1, 1],
                        k=5).result(timeout=300)
            st0 = svc.stats()["pipeline"]["ring"]
            assert st0["windows"] >= 3
            # commit more rows: the armed mask/version is now stale
            _, more = make_batch(n=200, seed=13)
            src.write(more)
            results = []
            for i in range(4, 8):
                results.append(svc.knn(
                    "served", CQL, pts[i:i + 1, 0], pts[i:i + 1, 1],
                    k=5).result(timeout=300))
            st1 = svc.stats()["pipeline"]["ring"]
        finally:
            svc.close(drain=True)
        assert st1["fallbacks"].get("stale", 0) >= 1
        assert st1["armed"] >= st0["armed"] + 1  # re-armed post-write
        # exactness against a fresh serial replay over the grown store
        planner = ds.get_feature_source("served").planner
        for j, i in enumerate(range(4, 8)):
            sd, six, _ = planner.knn(
                Query("served", CQL), pts[i:i + 1, 0], pts[i:i + 1, 1],
                k=5)
            d, ix, _ = results[j]
            np.testing.assert_array_equal(d, sd)
            np.testing.assert_array_equal(ix, six)

    def test_slot_write_oom_runs_the_halving_ladder(self, tmp_path):
        """OOM-ladder parity: an injected OOM on the ring's slot write
        (the device.transfer fault site) halves the coalesced window
        and re-runs from the HOST query copies — every rider exact,
        like the pipelined path."""
        from geomesa_tpu.faults import harness as faults
        from geomesa_tpu.faults.plan import FaultPlan, FaultRule

        sft, batch = make_batch(n=300, seed=17)
        ds = DataStore(str(tmp_path), use_device_cache=True)
        ds.create_schema(sft).write(batch)
        rng = np.random.default_rng(3)
        pts = rng.uniform(-60, 60, (6, 2))
        planner = ds.get_feature_source("served").planner
        serial = [planner.knn(Query("served", CQL), pts[i:i + 1, 0],
                              pts[i:i + 1, 1], k=5) for i in range(6)]
        svc = QueryService(ds, ServeConfig(max_wait_ms=50.0),
                           autostart=False)
        # warm (and arm) with one window OUTSIDE the fault plan
        warm = svc.knn("served", CQL, pts[0:1, 0], pts[0:1, 1], k=5)
        svc.start()
        warm.result(timeout=300)
        futs = [svc.knn("served", CQL, pts[i:i + 1, 0], pts[i:i + 1, 1],
                        k=5) for i in range(6)]
        plan = FaultPlan(rules=[
            FaultRule(site="device.transfer", error="oom", nth_call=1)])
        with faults.active(plan):
            results = [f.result(timeout=300) for f in futs]
        svc.close(drain=True)
        for (d, ix, _), (sd, six, _) in zip(results, serial):
            assert np.array_equal(ix, six)
            assert np.allclose(d, sd, rtol=1e-3)

    def test_drain_close_harvests_every_window_once(self, store, qpts):
        """Submit a burst, close(drain=True) immediately: every future
        resolves exactly once with a real result, nothing is left
        in flight, and the slot accounting balances."""
        svc = QueryService(store, ServeConfig(max_wait_ms=1.0))
        futs = [svc.knn("served", CQL, qpts[i:i + 1, 0],
                        qpts[i:i + 1, 1], k=5) for i in range(8)]
        svc.close(drain=True)
        done = [f for f in futs if f.done()]
        assert len(done) == 8
        for f in futs:
            d, ix, _ = f.result(timeout=1)
            assert d.shape == (1, 5) and ix.shape == (1, 5)
        p = svc.stats()["pipeline"]
        assert p["inflight"] == 0


class TestDensitySlotParity:
    def test_slotted_density_matches_static_kernel(self):
        """The slot-parameterized density variant (engine/density.py,
        ring groundwork) is bit-identical to the static-bbox kernel on
        f32-exact envelopes — the eligibility gate the ring tier would
        apply."""
        import jax.numpy as jnp

        from geomesa_tpu.engine.density import (
            density_grid, density_grid_slotted)

        rng = np.random.default_rng(9)
        n = 512
        x = jnp.asarray(rng.uniform(-170, 170, n), jnp.float32)
        y = jnp.asarray(rng.uniform(-80, 80, n), jnp.float32)
        w = jnp.ones(n, jnp.float32)
        m = jnp.asarray(rng.random(n) > 0.25)
        bbox = (-180.0, -90.0, 180.0, 90.0)  # f32-exact envelope
        static = density_grid(x, y, w, m, bbox, 64, 32)
        slot = jnp.asarray(np.asarray(bbox, np.float32))
        slotted = density_grid_slotted(x, y, w, m, slot, 64, 32)
        np.testing.assert_array_equal(np.asarray(static),
                                      np.asarray(slotted))
