"""Converter formats: fixed-width, XML, shapefile round-trip, Avro gate."""

import io

import numpy as np
import pytest

from geomesa_tpu.convert.converter import converter_from_config
from geomesa_tpu.convert.formats import (
    ShapefileConverter,
    read_shapefile,
    write_shapefile,
)
from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType


class TestFixedWidth:
    def test_basic(self):
        sft = SimpleFeatureType.from_spec("fw", "name:String,*geom:Point")
        config = {
            "type": "fixed-width",
            "fields": [
                {"name": "name", "start": 0, "width": 5},
                {"name": "lat", "start": 5, "width": 6, "transform": "$0::double"},
                {"name": "lon", "start": 11, "width": 7, "transform": "$0::double"},
                {"name": "geom", "transform": "point($lon, $lat)"},
            ],
        }
        text = "alpha 48.85   2.35\nbeta  29.90 -90.10\n"
        conv = converter_from_config(sft, config)
        batch = conv.convert(io.StringIO(text))
        assert len(batch) == 2
        assert batch.column("name").decode() == ["alpha", "beta"]
        np.testing.assert_allclose(batch.geometry.y, [48.85, 29.9])
        np.testing.assert_allclose(batch.geometry.x, [2.35, -90.1])

    def test_skip_lines(self):
        sft = SimpleFeatureType.from_spec("fw", "*geom:Point")
        config = {
            "type": "fixed-width",
            "options": {"skip-lines": 1},
            "fields": [
                {"name": "x", "start": 0, "width": 4, "transform": "$0::double"},
                {"name": "y", "start": 4, "width": 4, "transform": "$0::double"},
                {"name": "geom", "transform": "point($x, $y)"},
            ],
        }
        batch = converter_from_config(sft, config).convert(
            io.StringIO("XXYY\n1.0 2.0\n")
        )
        assert len(batch) == 1


class TestXml:
    XML = """<doc>
      <row id="a"><props><name>alpha</name></props><lon>2.35</lon><lat>48.85</lat></row>
      <row id="b"><props><name>beta</name></props><lon>-90.1</lon><lat>29.9</lat></row>
    </doc>"""

    def test_paths_and_attrs(self):
        sft = SimpleFeatureType.from_spec("x", "rid:String,name:String,*geom:Point")
        config = {
            "type": "xml",
            "feature-path": "doc/row",
            "fields": [
                {"name": "rid", "path": "@id"},
                {"name": "name", "path": "props/name"},
                {"name": "lon", "path": "lon", "transform": "$0::double"},
                {"name": "lat", "path": "lat", "transform": "$0::double"},
                {"name": "geom", "transform": "point($lon, $lat)"},
            ],
            "id-field": "$rid",
        }
        batch = converter_from_config(sft, config).convert(io.StringIO(self.XML))
        assert len(batch) == 2
        assert batch.fids.decode() == ["a", "b"]
        assert batch.column("name").decode() == ["alpha", "beta"]
        np.testing.assert_allclose(batch.geometry.x, [2.35, -90.1])

    def test_missing_path_is_null(self):
        sft = SimpleFeatureType.from_spec("x", "name:String,*geom:Point")
        config = {
            "type": "xml",
            "feature-path": "doc/row",
            "fields": [
                {"name": "name", "path": "props/nope",
                 "transform": "withDefault($0, 'UNK')"},
                {"name": "lon", "path": "lon", "transform": "$0::double"},
                {"name": "lat", "path": "lat", "transform": "$0::double"},
                {"name": "geom", "transform": "point($lon, $lat)"},
            ],
        }
        batch = converter_from_config(sft, config).convert(io.StringIO(self.XML))
        assert batch.column("name").decode() == ["UNK", "UNK"]


class TestShapefile:
    def test_point_round_trip(self, tmp_path):
        sft = SimpleFeatureType.from_spec("s", "name:String,score:Double,*geom:Point")
        batch = FeatureBatch.from_pydict(
            sft,
            {
                "name": ["alpha", "beta", "gamma"],
                "score": [1.5, -2.25, 0.0],
                "geom": np.array([[2.35, 48.85], [-90.1, 29.9], [0.0, 0.0]]),
            },
        )
        path = str(tmp_path / "pts.shp")
        write_shapefile(path, batch)
        recs = list(read_shapefile(path))
        assert len(recs) == 3
        assert recs[0].geometry.point == (2.35, 48.85)
        assert recs[0].attributes["name"] == "alpha"
        assert recs[1].attributes["score"] == pytest.approx(-2.25)

    def test_converter_facade(self, tmp_path):
        sft = SimpleFeatureType.from_spec("s", "name:String,score:Double,*geom:Point")
        batch = FeatureBatch.from_pydict(
            sft,
            {"name": ["a", "b"], "score": [1.0, 2.0],
             "geom": np.array([[1.0, 2.0], [3.0, 4.0]])},
        )
        path = str(tmp_path / "pts.shp")
        write_shapefile(path, batch)
        conv = ShapefileConverter(sft, {"type": "shp"})
        out = conv.convert(path)
        assert len(out) == 2
        assert out.column("name").decode() == ["a", "b"]
        np.testing.assert_allclose(out.geometry.x, [1.0, 3.0])


class TestAvroGate:
    def test_raises_clearly(self):
        sft = SimpleFeatureType.from_spec("a", "*geom:Point")
        with pytest.raises(ImportError, match="[Aa]vro"):
            converter_from_config(sft, {"type": "avro"})


def test_dbf_large_float_roundtrip(tmp_path):
    """Floats whose repr is scientific notation must survive dbf export."""
    sft = SimpleFeatureType.from_spec("t", "v:Double,*geom:Point")
    batch = FeatureBatch.from_pydict(
        sft, {"v": [1e20, 0.5, 1e-7], "geom": np.zeros((3, 2))}
    )
    from geomesa_tpu.convert.formats import _read_dbf, _write_dbf

    path = str(tmp_path / "t.dbf")
    _write_dbf(path, batch)
    rows = _read_dbf(path)
    assert rows[0]["v"] == pytest.approx(1e20)
    assert rows[1]["v"] == pytest.approx(0.5)
    assert rows[2]["v"] == pytest.approx(1e-7, abs=1e-9)


class TestParquetConverter:
    def test_parquet_input(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as papq

        from geomesa_tpu.convert import converter_from_config

        p = str(tmp_path / "in.parquet")
        papq.write_table(
            pa.table({
                "name": ["a", "b", "c"],
                "score": [1.5, 2.5, None],
                "lon": [10.0, 20.0, 30.0],
                "lat": [1.0, 2.0, 3.0],
            }),
            p,
        )
        sft = SimpleFeatureType.from_spec(
            "t", "name:String,score:Double,*geom:Point"
        )
        conv = converter_from_config(sft, {
            "type": "parquet",
            "id-field": "$name",
            "fields": [
                {"name": "name", "path": "name"},
                {"name": "score", "path": "score"},
                {"name": "geom", "transform": "point($lon, $lat)"},
            ],
        })
        batch = conv.convert(p)
        assert len(batch) == 3
        assert batch.fids.decode() == ["a", "b", "c"]
        assert batch.columns["name"].decode() == ["a", "b", "c"]
        np.testing.assert_allclose(batch.columns["geom"].x, [10, 20, 30])

    def test_jdbc_input(self, tmp_path):
        import sqlite3

        from geomesa_tpu.convert import converter_from_config

        db = str(tmp_path / "obs.db")
        conn = sqlite3.connect(db)
        conn.execute("CREATE TABLE obs (id TEXT, lon REAL, lat REAL, v REAL)")
        conn.executemany(
            "INSERT INTO obs VALUES (?, ?, ?, ?)",
            [("o1", 1.0, 2.0, 7.5), ("o2", 3.0, 4.0, 8.5)],
        )
        conn.commit()
        conn.close()
        sft = SimpleFeatureType.from_spec("t", "v:Double,*geom:Point")
        conv = converter_from_config(sft, {
            "type": "jdbc",
            "query": "SELECT id, lon, lat, v FROM obs ORDER BY id",
            "id-field": "$id",
            "fields": [
                {"name": "v", "path": "v"},
                {"name": "geom", "transform": "point($lon, $lat)"},
            ],
        })
        batch = conv.convert(db)
        assert len(batch) == 2
        assert batch.fids.decode() == ["o1", "o2"]
        np.testing.assert_allclose(np.asarray(batch.column("v")), [7.5, 8.5])
