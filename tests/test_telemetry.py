"""geomesa_tpu.telemetry tests: span core semantics + the hard
per-span overhead budget, trace round-trip through the Perfetto export
under a concurrent serve workload (parent/child + monotonic-nesting
invariants), flight-recorder bounded memory + crash-dump path, labeled
metrics export, the /metrics HTTP endpoint, and the dispatch-gap
report. Everything runs in-process on tiny stores; the serve workload
reuses the shapes test_serve.py already compiled so the suite pays no
new kernel compiles."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from geomesa_tpu.telemetry.export import (MetricsServer, from_perfetto,
                                          to_perfetto, write_jsonl)
from geomesa_tpu.telemetry.gap import gap_report, render_gap
from geomesa_tpu.telemetry.recorder import FlightRecorder
from geomesa_tpu.telemetry.trace import NOOP_SPAN, Trace, Tracer


# -- span core --------------------------------------------------------------


class TestTraceCore:
    def test_disabled_returns_shared_noop(self):
        tr = Tracer()
        s = tr.span("x")
        assert s is NOOP_SPAN
        with s as inner:
            inner.set(a=1)  # no-op, no error
        assert tr.start_trace("q") is None
        assert tr.current_trace() is None

    def test_enabled_but_unscoped_is_noop(self):
        tr = Tracer()
        tr.enable()
        assert tr.span("x") is NOOP_SPAN

    def test_nesting_and_parentage(self):
        tr = Tracer()
        tr.enable()
        trace = tr.start_trace("q", kind="knn")
        with tr.scope(trace):
            with tr.span("outer") as outer:
                with tr.span("inner", k=5) as inner:
                    pass
                # the scope's SHARED handle holds the just-closed span:
                # read ids immediately after each block exits
                inner_id = inner.span_id
            outer_id = outer.span_id
            with tr.span("sibling"):
                pass
        trace.finish(status="ok")
        spans = {s.name: s for s in trace.snapshot_spans()}
        assert spans["outer"].parent_id == trace.root.span_id
        assert spans["inner"].parent_id == outer_id
        assert spans["sibling"].parent_id == trace.root.span_id
        assert spans["inner"].attrs == {"k": 5}
        assert inner_id == spans["inner"].span_id
        assert outer_id == spans["outer"].span_id
        # monotonic nesting
        assert (spans["outer"].start_ns <= spans["inner"].start_ns
                <= spans["inner"].end_ns <= spans["outer"].end_ns)
        assert trace.root.attrs["status"] == "ok"
        assert trace.root.end_ns >= spans["sibling"].end_ns

    def test_exception_marks_error_and_unwinds(self):
        tr = Tracer()
        tr.enable()
        trace = tr.start_trace("q")
        with tr.scope(trace):
            with pytest.raises(ValueError):
                with tr.span("boom"):
                    raise ValueError("x")
            with tr.span("after"):
                pass
        spans = {s.name: s for s in trace.snapshot_spans()}
        assert spans["boom"].attrs["error"] == "ValueError"
        # the stack unwound: "after" is a root child, not boom's child
        assert spans["after"].parent_id == trace.root.span_id

    def test_record_and_finish_idempotent(self):
        tr = Tracer()
        tr.enable()
        trace = tr.start_trace("q")
        t0 = time.perf_counter_ns()
        trace.record("queue.wait", t0, t0 + 1000, waited=True)
        trace.finish(status="ok")
        end1 = trace.root.end_ns
        trace.finish(status="late")
        assert trace.root.end_ns == end1  # first close wins
        assert trace.root.attrs["status"] == "ok"

    def test_adopt_reparents_and_clamps(self):
        tr = Tracer()
        tr.enable()
        lead = tr.start_trace("lead")
        with tr.scope(lead):
            with tr.span("dispatch") as d:
                with tr.span("kernel.dispatch"):
                    pass
        rider = tr.start_trace("rider")
        clamp = rider.root.start_ns
        rider.adopt(lead.snapshot_spans(), clamp_start_ns=clamp)
        spans = {s.name: s for s in rider.snapshot_spans()}
        # the dispatch span re-parented to the rider's root; its child
        # kept its real parent (ids are preserved for gap dedup)
        assert spans["dispatch"].parent_id == rider.root.span_id
        assert spans["kernel.dispatch"].parent_id == d.span_id
        assert all(s.start_ns >= clamp for s in rider.snapshot_spans())


class TestOverheadBudget:
    """The hard budget: <2µs per live span, unmeasurable when off.

    Methodology: min over 9 trials with gc paused and a FRESH trace per
    trial (a shared multi-hundred-k span list would measure list
    growth, not span cost). The shared CI host sometimes throttles a
    whole process ~2.5x — visible as the no-op loop (pure `with`
    machinery, no clock/alloc) costing 3x its quiet-floor; the relative
    fallback (live ≤ 6x no-op, measured in the SAME process) keeps the
    assertion about OUR code's overhead rather than the host's mood. A
    genuinely regressed hot path fails both arms on a quiet host."""

    N = 10_000
    _cached = None  # one measurement serves both assertions (wall-
    # clock budget: the suite sits within ~40s of the tier-1 timeout)

    def _measure(self):
        if TestOverheadBudget._cached is not None:
            return TestOverheadBudget._cached
        import gc

        tr_on = Tracer()
        tr_on.enable()
        tr_off = Tracer()
        live = noop = float("inf")
        gc.disable()
        try:
            for _ in range(7):
                trace = tr_on.start_trace("bench")
                t0 = time.perf_counter_ns()
                with tr_on.scope(trace):
                    for _ in range(self.N):
                        with tr_on.span("s"):
                            pass
                live = min(live,
                           (time.perf_counter_ns() - t0) / self.N)
                t0 = time.perf_counter_ns()
                for _ in range(self.N):
                    with tr_off.span("s"):
                        pass
                noop = min(noop,
                           (time.perf_counter_ns() - t0) / self.N)
        finally:
            gc.enable()
        TestOverheadBudget._cached = (live, noop)
        return live, noop

    def test_live_span_under_2us(self):
        live, noop = self._measure()
        assert live < 2000 or live < 6 * noop, (
            f"live span cost {live:.0f}ns/span "
            f"(no-op floor {noop:.0f}ns in the same process)")

    def test_noop_fast_path_unmeasurable(self):
        live, noop = self._measure()
        # "unmeasurable": no allocation, no clock read — a shared
        # singleton and one tls read, far under the live-span cost
        assert noop < max(500.0, live * 0.5), (
            f"no-op span cost {noop:.0f}ns/span (live {live:.0f}ns)")
        tr = Tracer()
        assert tr.span("s") is tr.span("t")  # shared singleton


# -- serve round-trip -------------------------------------------------------


@pytest.fixture(scope="module")
def traced_workload(tmp_path_factory):
    """One concurrent traced serve workload shared by the round-trip
    assertions: 8 coalescible kNN + 3 dedup counts submitted from 4
    client threads (same store/kernel shapes as test_serve.py, so the
    jit caches are already warm when the suite runs in order)."""
    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.serve.service import QueryService, ServeConfig
    from geomesa_tpu.telemetry.recorder import RECORDER
    from geomesa_tpu.telemetry.trace import TRACER

    rng = np.random.default_rng(7)
    n = 512
    sft = SimpleFeatureType.from_spec(
        "teletrip", "name:String,score:Double,dtg:Date,*geom:Point")
    batch = FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })
    cql = "BBOX(geom, -180, -90, 180, 90)"
    tmp = tmp_path_factory.mktemp("teletrip")
    store = DataStore(str(tmp), use_device_cache=True)
    src = store.create_schema(sft)
    src.write(batch)
    RECORDER.clear()
    TRACER.enable()
    try:
        svc = QueryService(store, ServeConfig(max_wait_ms=25.0),
                           autostart=False)
        qp = rng.uniform(-60, 60, (8, 2))
        futs = []
        futs_lock = threading.Lock()

        def client(idxs):
            for i in idxs:
                if i < 8:
                    f = svc.knn("teletrip", cql, qp[i:i + 1, 0],
                                qp[i:i + 1, 1], k=5)
                else:
                    f = svc.count("teletrip", cql)
                with futs_lock:
                    futs.append(f)

        threads = [threading.Thread(target=client, args=(range(c, 11, 4),))
                   for c in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        svc.start()
        for f in futs:
            f.result(timeout=120)
        svc.close(drain=True)
    finally:
        TRACER.disable()
    traces = RECORDER.traces()
    events = store.audit.snapshot()
    return {"traces": traces, "audit": events}


class TestServeRoundTrip:
    def _check_invariants(self, traces):
        assert traces, "no traces recorded"
        for t in traces:
            root = t["root"]
            ids = {root["id"]}
            by_id = {root["id"]: root}
            for s in t["spans"]:
                ids.add(s["id"])
                by_id[s["id"]] = s
            for s in t["spans"]:
                # every parent exists in the same trace
                assert s["parent"] in ids, (t["trace_id"], s)
                assert s["t1_ns"] >= s["t0_ns"]
                # monotonic nesting: a child lies within its parent
                # (root children may start before the root only never —
                # adoption clamps to the rider's root start)
                p = by_id[s["parent"]]
                if p is not root:
                    assert s["t0_ns"] >= p["t0_ns"] - 1, (s, p)
                    assert s["t1_ns"] <= p["t1_ns"] + 1, (s, p)
                else:
                    assert s["t0_ns"] >= root["t0_ns"], (s, root)

    def test_trace_structure_and_phases(self, traced_workload):
        traces = traced_workload["traces"]
        assert len(traces) == 11
        self._check_invariants(traces)
        for t in traces:
            names = {s["name"] for s in t["spans"]}
            assert {"admit", "queue.wait", "dispatch"} <= names, names
            assert t["root"]["attrs"]["status"] == "ok"
        # kNN traces reached the kernel seams
        knn = [t for t in traces if t["root"]["attrs"]["kind"] == "knn"]
        assert knn and all(
            "kernel.dispatch" in {s["name"] for s in t["spans"]}
            for t in knn)

    def test_perfetto_round_trip(self, traced_workload):
        traces = traced_workload["traces"]
        doc = json.loads(json.dumps(to_perfetto(traces)))
        assert all(e["ph"] in ("M", "X") for e in doc["traceEvents"])
        back = from_perfetto(doc)
        assert len(back) == len(traces)
        self._check_invariants(back)
        by_id = {t["trace_id"]: t for t in back}
        for t in traces:
            rt = by_id[t["trace_id"]]
            assert {s["id"] for s in rt["spans"]} == {
                s["id"] for s in t["spans"]}
            assert {(s["name"], s["parent"]) for s in rt["spans"]} == {
                (s["name"], s["parent"]) for s in t["spans"]}

    def test_jsonl_export(self, traced_workload):
        lines = []
        n = write_jsonl(traced_workload["traces"], lines.append)
        assert n == 11 and len(lines) == 11
        assert all(json.loads(ln)["trace_id"] for ln in lines)

    def test_audit_correlation(self, traced_workload):
        """ServeEvent.trace_id joins the audit log to the recorder."""
        from geomesa_tpu.plan.audit import ServeEvent

        events = [e for e in traced_workload["audit"]
                  if isinstance(e, ServeEvent)
                  and e.type_name == "teletrip"]
        assert len(events) == 11
        trace_ids = {t["trace_id"] for t in traced_workload["traces"]}
        assert all(e.trace_id in trace_ids for e in events)
        assert len({e.trace_id for e in events}) == 11

    def test_gap_report_coverage(self, traced_workload):
        traces = traced_workload["traces"]
        rep = gap_report(traces)
        assert rep["traces"] == 11
        assert rep["dispatch_gap"]["windows"] >= 1
        # acceptance bar: per-phase root coverage within 5% of wall
        assert rep["coverage"] >= 0.95, rep
        assert {"admit", "queue.wait", "dispatch"} <= set(rep["phases"])
        g = rep["dispatch_gap"]
        assert 0 <= g["gap_fraction"] <= 1
        assert g["device_ms"] + g["host_gap_ms"] <= g["exec_ms"] * 1.01
        text = render_gap(rep)
        assert "dispatch windows" in text and "coverage" in text

    def test_shared_window_dedup(self, traced_workload):
        """Coalesced riders adopt copies of the lead's window spans with
        ids preserved; the gap report counts each window once."""
        traces = traced_workload["traces"]
        dispatch_ids = [s["id"] for t in traces for s in t["spans"]
                        if s["name"] == "dispatch"]
        rep = gap_report(traces)
        assert rep["dispatch_gap"]["windows"] == len(set(dispatch_ids))
        assert len(dispatch_ids) > len(set(dispatch_ids))  # sharing real


# -- flight recorder --------------------------------------------------------


class TestFlightRecorder:
    def _trace(self, name="q"):
        t = Trace(name)
        return t.finish(status="ok")

    def test_bounded_memory(self):
        rec = FlightRecorder(capacity=4, event_capacity=8)
        for _ in range(10):
            rec.record(self._trace())
        for i in range(20):
            rec.note_event("fault", site=f"s{i}")
        snap = rec.snapshot()
        assert len(snap["traces"]) == 4
        assert len(snap["events"]) == 8
        assert snap["dropped_traces"] == 6
        assert snap["dropped_events"] == 12
        assert snap["events"][-1]["site"] == "s19"  # newest kept

    def test_record_accepts_none_and_dict(self):
        rec = FlightRecorder(capacity=4)
        rec.record(None)
        rec.record({"trace_id": "x", "root": {}, "spans": []})
        assert len(rec.traces()) == 1

    def test_crash_dump(self, tmp_path):
        rec = FlightRecorder(capacity=4)
        rec.auto_dump_path = str(tmp_path / "flight.json")
        rec.record(self._trace())
        path = rec.crash_dump("dispatch loop error",
                              RuntimeError("boom"))
        assert path == rec.auto_dump_path
        doc = json.loads((tmp_path / "flight.json").read_text())
        assert doc["reason"] == "dispatch loop error"
        assert doc["traces"] and doc["events"][-1]["kind"] == "crash"
        assert "RuntimeError: boom" in doc["events"][-1]["error"]

    def test_breaker_transitions_land_in_recorder(self):
        from geomesa_tpu.faults.breaker import CircuitBreaker
        from geomesa_tpu.telemetry.recorder import RECORDER

        before = len(RECORDER.events())
        b = CircuitBreaker("teledep", failure_threshold=1,
                           reset_timeout_s=0.0)
        b.record_failure()   # -> open
        b.allow()            # -> half_open
        b.record_success()   # -> closed
        new = RECORDER.events()[before:]
        got = [(e["dependency"], e["state"]) for e in new
               if e["kind"] == "breaker" and e["dependency"] == "teledep"]
        assert got == [("teledep", "open"), ("teledep", "half_open"),
                       ("teledep", "closed")]

    def test_quarantine_strikes_land_in_recorder(self):
        from geomesa_tpu.faults.quarantine import QuarantineRegistry
        from geomesa_tpu.telemetry.recorder import RECORDER

        before = len(RECORDER.events())
        q = QuarantineRegistry(strikes=2, ttl_s=60.0)
        assert not q.strike(("k",))
        assert q.strike(("k",))
        acts = [e["action"] for e in RECORDER.events()[before:]
                if e["kind"] == "quarantine"]
        assert acts == ["strike", "trip"]


# -- labeled metrics --------------------------------------------------------


class TestMetricsLabels:
    def test_label_series_and_export(self):
        from geomesa_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        m.counter("serve.requests")
        m.counter("serve.requests", kind="knn", status="ok")
        m.counter("serve.requests", 2, kind="knn", status="ok")
        m.counter("serve.requests", kind="count", status="error")
        m.gauge("depth", 3, shard="a")
        txt = m.to_prometheus()
        # one TYPE declaration per family, proper label syntax
        assert txt.count("# TYPE serve_requests counter") == 1
        assert 'serve_requests{kind="knn",status="ok"} 3.0' in txt
        assert 'serve_requests{kind="count",status="error"} 1.0' in txt
        assert "serve_requests 1.0" in txt.splitlines()
        assert 'depth{shard="a"} 3.0' in txt

    def test_labeled_histograms_merge_and_export(self):
        from geomesa_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        ha = m.histogram("lat", tenant="a")
        hb = m.histogram("lat", tenant="b")
        assert m.histogram("lat", tenant="a") is ha  # stable series
        ha.update(0.01)
        hb.update(0.02)
        ha.merge(hb)  # merge() works across labeled series
        assert ha.count == 2
        txt = m.to_prometheus()
        assert 'lat_seconds_bucket{tenant="a",le="0.016"} 1' in txt
        assert 'lat_seconds_count{tenant="a"} 2' in txt
        assert 'lat_seconds_count{tenant="b"} 1' in txt

    def test_families_render_contiguously(self):
        """The text format requires every sample of a family to be
        contiguous — interleaved insertion across families must not
        interleave the rendered output (strict parsers reject it)."""
        from geomesa_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        m.counter("serve.requests", kind="knn")
        m.counter("serve.tenant.requests", tenant="a")
        m.counter("serve.requests", kind="count")
        ha = m.histogram("lat", tenant="a")
        m.histogram("other")
        hb = m.histogram("lat", tenant="b")
        ha.update(0.01)
        hb.update(0.02)
        lines = m.to_prometheus().splitlines()
        idx = [i for i, ln in enumerate(lines)
               if ln.startswith("serve_requests{")]
        assert len(idx) == 2 and idx[1] == idx[0] + 1
        # the lat_seconds family (bucket/sum/count samples of BOTH
        # label sets) must form one contiguous block with no foreign
        # family (other_seconds) inside it
        fam = [i for i, ln in enumerate(lines)
               if ln.startswith(("lat_seconds_bucket{",
                                 "lat_seconds_sum", "lat_seconds_count"))]
        inside = lines[fam[0]:fam[-1] + 1]
        assert not any(ln.startswith("other_seconds") for ln in inside)
        # TYPE declared exactly once per family, before its samples
        assert sum(ln == "# TYPE serve_requests counter"
                   for ln in lines) == 1
        assert lines.index("# TYPE serve_requests counter") < idx[0]

    def test_label_cardinality_bounded(self):
        """Client-controlled label values (per-tenant series) must not
        grow the registry without bound: past the per-family cap, new
        label sets fold into the unlabeled aggregate."""
        from geomesa_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        cap = MetricsRegistry.MAX_LABELED_SERIES_PER_FAMILY
        for i in range(cap + 50):
            m.counter("serve.tenant.requests", tenant=f"t{i}")
        labeled = [k for k in m.counters
                   if k.startswith("serve.tenant.requests{")]
        assert len(labeled) == cap
        # the 50 overflow increments landed on the aggregate series
        assert m.counters["serve.tenant.requests"] == 50.0
        # an already-registered series keeps updating past the cap
        m.counter("serve.tenant.requests", tenant="t0")
        assert m.counters['serve.tenant.requests{tenant="t0"}'] == 2.0

    def test_label_escaping(self):
        from geomesa_tpu.utils.metrics import MetricsRegistry

        m = MetricsRegistry()
        m.counter("c", cql='BBOX(geom, "x")\n')
        txt = m.to_prometheus()
        assert 'c{cql="BBOX(geom, \\"x\\")\\n"} 1.0' in txt


# -- metrics server ---------------------------------------------------------


class TestMetricsServer:
    def _get(self, url):
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()

    def test_endpoints(self):
        rec = FlightRecorder(capacity=4)
        rec.record(Trace("q").finish(status="ok"))
        scraped = []
        server = MetricsServer(
            port=0, stats_fn=lambda: {"dispatches": 3},
            pre_scrape=lambda: scraped.append(1), recorder=rec)
        port = server.start()
        try:
            base = f"http://127.0.0.1:{port}"
            status, body = self._get(f"{base}/metrics")
            assert status == 200 and "# TYPE" in body
            assert scraped  # pre_scrape hook ran
            status, body = self._get(f"{base}/healthz")
            doc = json.loads(body)
            assert status == 200 and doc["ok"]
            assert doc["serve"] == {"dispatches": 3}
            _, body = self._get(f"{base}/debug/traces")
            assert len(from_perfetto(json.loads(body))) == 1
            _, body = self._get(f"{base}/debug/stats")
            doc = json.loads(body)
            assert doc["serve"] == {"dispatches": 3}
            assert doc["recorder"]["traces_held"] == 1
            assert "breakers" in doc
            _, body = self._get(f"{base}/debug/gap")
            assert json.loads(body)["traces"] == 1
            try:
                self._get(f"{base}/nope")
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()


# -- gap report math --------------------------------------------------------


class TestGapReport:
    def test_synthetic_attribution(self):
        us = 1000  # ns per µs keeps the arithmetic readable
        root = {"name": "query", "id": 1, "parent": None,
                "t0_ns": 0, "t1_ns": 100 * us, "thread": 0}
        spans = [
            {"name": "queue.wait", "id": 2, "parent": 1,
             "t0_ns": 0, "t1_ns": 40 * us, "thread": 0},
            {"name": "dispatch", "id": 3, "parent": 1,
             "t0_ns": 40 * us, "t1_ns": 100 * us, "thread": 0},
            {"name": "kernel.dispatch", "id": 4, "parent": 3,
             "t0_ns": 50 * us, "t1_ns": 70 * us, "thread": 0},
            {"name": "plan", "id": 5, "parent": 3,
             "t0_ns": 41 * us, "t1_ns": 49 * us, "thread": 0},
        ]
        rep = gap_report([{"trace_id": "t1", "name": "query",
                           "root": root, "spans": spans}])
        assert rep["wall_ms"] == pytest.approx(0.1)
        assert rep["coverage"] == pytest.approx(1.0)
        g = rep["dispatch_gap"]
        assert g["windows"] == 1
        assert g["exec_ms"] == pytest.approx(0.06)
        assert g["device_ms"] == pytest.approx(0.02)
        assert g["host_gap_ms"] == pytest.approx(0.04)
        assert g["gap_fraction"] == pytest.approx(0.04 / 0.06, abs=1e-3)

    def test_empty_input(self):
        rep = gap_report([])
        assert rep["traces"] == 0 and rep["phases"] == {}
        assert render_gap(rep)

    def test_multi_process_dumps_do_not_collide(self):
        """Span ids are per-process counters; merged replica dumps
        dedup by (process, id) — trace ids are pid-qualified exactly
        so this works."""
        def trace_from(pid, trace_seq):
            us = 1000
            return {
                "trace_id": f"{pid}-{trace_seq}", "name": "query",
                "root": {"name": "query", "id": 1, "parent": None,
                         "t0_ns": 0, "t1_ns": 10 * us, "thread": 0},
                "spans": [{"name": "dispatch", "id": 2, "parent": 1,
                           "t0_ns": 0, "t1_ns": 10 * us, "thread": 0}],
            }

        rep = gap_report([trace_from("aa", 1), trace_from("bb", 1)])
        assert rep["dispatch_gap"]["windows"] == 2
        assert rep["phases"]["dispatch"]["count"] == 2
        # same process, same ids = one shared (adopted) window
        rep = gap_report([trace_from("aa", 1), trace_from("aa", 2)])
        assert rep["dispatch_gap"]["windows"] == 1
