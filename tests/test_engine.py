"""Kernel suite tests: kNN recall parity, density grid equality, stats,
tube-select — single-device and sharded over the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from geomesa_tpu.engine.bin import bin_pack, decode_bin, encode_bin
from geomesa_tpu.engine.density import density_grid, density_sharded, gaussian_blur
from geomesa_tpu.engine.geodesy import haversine_m, haversine_m_np
from geomesa_tpu.engine.knn import knn, knn_mxu, knn_ring, knn_sharded
from geomesa_tpu.engine.stats import (
    masked_count,
    masked_histogram,
    masked_minmax,
    masked_moments,
    masked_value_counts,
    stats_sharded,
    z3_histogram,
)
from geomesa_tpu.engine.tube import tube_select, tube_select_sharded
from geomesa_tpu.parallel import default_mesh

rng = np.random.default_rng(11)


def recall_at_k(got_idx, got_d, oracle_d, k, tol=1.0):
    """Tie-tolerant recall: a returned neighbor counts if its true distance
    is within `tol` meters of the oracle's k-th distance."""
    hits = 0
    for q in range(got_idx.shape[0]):
        kth = oracle_d[q][k - 1]
        hits += int(np.sum(got_d[q] <= kth + tol))
    return hits / (got_idx.shape[0] * k)


class TestHaversine:
    def test_matches_numpy(self):
        lon1, lat1 = rng.uniform(-180, 180, 100), rng.uniform(-89, 89, 100)
        lon2, lat2 = rng.uniform(-180, 180, 100), rng.uniform(-89, 89, 100)
        d_jax = np.asarray(haversine_m(lon1, lat1, lon2, lat2))
        d_np = haversine_m_np(lon1, lat1, lon2, lat2)
        np.testing.assert_allclose(d_jax, d_np, rtol=1e-6)

    def test_known_distance(self):
        # London -> Paris ~ 343 km great circle
        d = float(haversine_m(-0.1276, 51.5072, 2.3522, 48.8566))
        assert 330_000 < d < 350_000


class TestKNN:
    def setup_method(self):
        self.n, self.q, self.k = 5000, 64, 10
        self.dx = rng.uniform(-10, 10, self.n)
        self.dy = rng.uniform(40, 60, self.n)
        self.qx = rng.uniform(-10, 10, self.q)
        self.qy = rng.uniform(40, 60, self.q)
        self.mask = np.ones(self.n, bool)
        # oracle: full f64 distance sort
        d = haversine_m_np(
            self.qx[:, None], self.qy[:, None], self.dx[None, :], self.dy[None, :]
        )
        self.oracle_d = np.sort(d, axis=1)

    def test_exact_recall_single_device(self):
        dists, idx = knn(
            jnp.asarray(self.qx), jnp.asarray(self.qy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask), k=self.k, query_tile=16,
        )
        true_d = haversine_m_np(
            self.qx[:, None], self.qy[:, None],
            self.dx[np.asarray(idx)], self.dy[np.asarray(idx)],
        )
        r = recall_at_k(np.asarray(idx), true_d, self.oracle_d, self.k)
        assert r == 1.0

    def _mxu_queries(self, q=160):
        # >= 128 queries so knn_mxu takes the matmul path, not the small-Q
        # exact fallback (q < 128 falls back to `knn` by design)
        mqx = rng.uniform(-10, 10, q)
        mqy = rng.uniform(40, 60, q)
        d = haversine_m_np(
            mqx[:, None], mqy[:, None], self.dx[None, :], self.dy[None, :]
        )
        return mqx, mqy, np.sort(d, axis=1)

    def test_mxu_recall_parity(self):
        # the matmul-similarity path must hit full tie-tolerant recall
        mqx, mqy, oracle = self._mxu_queries()
        dists, idx = knn_mxu(
            jnp.asarray(mqx), jnp.asarray(mqy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask), k=self.k, query_tile=32,
        )
        true_d = haversine_m_np(
            mqx[:, None], mqy[:, None],
            self.dx[np.asarray(idx)], self.dy[np.asarray(idx)],
        )
        r = recall_at_k(np.asarray(idx), true_d, oracle, self.k)
        assert r == 1.0
        # refined distances match the oracle to sub-meter
        np.testing.assert_allclose(
            np.sort(np.asarray(dists), 1), oracle[:, : self.k], atol=1.0
        )

    def test_compact_recall_and_index_mapping(self):
        # knn_compact: masked selectivity + capacity > count; the returned
        # indices must point at unmasked ORIGINAL rows and reproduce the
        # reported distances
        from geomesa_tpu.engine.knn import knn_compact

        mask = rng.random(self.n) < 0.4
        mqx, mqy, _ = self._mxu_queries()
        d = haversine_m_np(
            mqx[:, None], mqy[:, None],
            self.dx[None, mask], self.dy[None, mask],
        )
        oracle = np.sort(d, axis=1)
        cap = 1 << int(mask.sum() - 1).bit_length()
        dists, idx, overflow = knn_compact(
            jnp.asarray(mqx), jnp.asarray(mqy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(mask), k=self.k, capacity=cap,
        )
        assert not bool(overflow)
        idx = np.asarray(idx)
        assert mask[idx].all(), "index into a masked-out row"
        true_d = haversine_m_np(
            mqx[:, None], mqy[:, None], self.dx[idx], self.dy[idx]
        )
        np.testing.assert_allclose(
            np.sort(true_d, 1), np.sort(np.asarray(dists), 1), atol=1.0
        )
        np.testing.assert_allclose(
            np.sort(np.asarray(dists), 1), oracle[:, : self.k], atol=1.0
        )

    def test_compact_capacity_exceeds_n(self):
        # capacity above the data length must clamp, not crash (lax.top_k
        # requires k <= lane count)
        from geomesa_tpu.engine.knn import knn_compact

        mqx, mqy, oracle = self._mxu_queries()
        dists, _, overflow = knn_compact(
            jnp.asarray(mqx), jnp.asarray(mqy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask), k=self.k, capacity=4 * self.n,
        )
        assert not bool(overflow)
        np.testing.assert_allclose(
            np.sort(np.asarray(dists), 1), oracle[:, : self.k], atol=1.0
        )

    def test_compact_overflow_flag(self):
        # capacity below the true match count must raise the overflow flag
        # (the silent-wrong-results contract the round-1 advisor flagged)
        from geomesa_tpu.engine.knn import knn_compact

        mqx, mqy, _ = self._mxu_queries()
        cap = int(self.mask.sum()) // 2
        _, _, overflow = knn_compact(
            jnp.asarray(mqx), jnp.asarray(mqy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask), k=self.k, capacity=cap,
        )
        assert bool(overflow)

    def test_mxu_clustered_near_ties(self):
        # dense cluster: many near-equal distances stress the f32 margin
        n, q, k = 20_000, 160, 8
        cdx = rng.normal(2.0, 0.01, n)  # ~1km cluster
        cdy = rng.normal(48.0, 0.01, n)
        cqx = rng.normal(2.0, 0.01, q)
        cqy = rng.normal(48.0, 0.01, q)
        mask = np.ones(n, bool)
        d_or = np.sort(
            haversine_m_np(cqx[:, None], cqy[:, None], cdx[None, :], cdy[None, :]), 1
        )
        dists, idx = knn_mxu(
            jnp.asarray(cqx), jnp.asarray(cqy), jnp.asarray(cdx),
            jnp.asarray(cdy), jnp.asarray(mask), k=k, query_tile=32,
        )
        true_d = haversine_m_np(cqx[:, None], cqy[:, None],
                                cdx[np.asarray(idx)], cdy[np.asarray(idx)])
        assert recall_at_k(np.asarray(idx), true_d, d_or, k, tol=1.0) == 1.0

    def test_mxu_antipodal_neighbors_stay_finite(self):
        # a legitimate neighbor at a query's antipode has chord^2 == 4.0,
        # the maximum possible — the refine cut must not confuse it with a
        # masked slot (chord2 == BIG) and report +inf (regression)
        n, q, k = 4_096, 160, 3
        dx = np.full(n, 180.0) - rng.uniform(0, 1e-4, n)
        dy = rng.uniform(-1e-4, 1e-4, n)
        qx = np.zeros(q) + rng.uniform(0, 1e-4, q)
        qy = rng.uniform(-1e-4, 1e-4, q)
        dists, idx = knn_mxu(
            jnp.asarray(qx, jnp.float32), jnp.asarray(qy, jnp.float32),
            jnp.asarray(dx, jnp.float32), jnp.asarray(dy, jnp.float32),
            jnp.asarray(np.ones(n, bool)), k=k, query_tile=32,
        )
        got = np.asarray(dists)
        assert np.all(np.isfinite(got))
        # half the meridian circumference, to within f32 slack
        np.testing.assert_allclose(got, 2.00151e7, rtol=1e-3)

    def test_mxu_masked_and_small_n(self):
        mqx, mqy, _ = self._mxu_queries()
        mask = self.mask.copy()
        mask[:2500] = False
        dists, idx = knn_mxu(
            jnp.asarray(mqx), jnp.asarray(mqy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(mask), k=self.k,
        )
        assert np.asarray(idx).min() >= 2500
        # n < k (and q < 128: the exact-fallback path): pads with inf
        d, i = knn_mxu(
            jnp.asarray(self.qx[:4]), jnp.asarray(self.qy[:4]),
            jnp.asarray(self.dx[:3]), jnp.asarray(self.dy[:3]),
            jnp.ones(3, bool), k=self.k,
        )
        assert np.isinf(np.asarray(d)[:, 3:]).all()

    def test_mxu_small_q_falls_back_exact(self):
        # q < 128 must route to the bit-exact haversine kernel
        d1, i1 = knn(
            jnp.asarray(self.qx), jnp.asarray(self.qy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask), k=self.k, query_tile=64,
        )
        d2, i2 = knn_mxu(
            jnp.asarray(self.qx), jnp.asarray(self.qy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(self.mask), k=self.k,
        )
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))

    def test_mxu_certificate_flags_boundary_tiles(self):
        # mixed workload: meters-dense port cluster + spread sea queries.
        # The sorted-order tile straddling the cluster boundary loses f32
        # precision; the exactness certificate must flag every query whose
        # error exceeds the refine tolerance, and flag far fewer than all.
        r = np.random.default_rng(5)
        n, q, k = 30_000, 256, 8
        pts = np.concatenate([
            np.stack([r.normal(4.0, 0.005, n // 2), r.normal(51.9, 0.005, n // 2)], 1),
            np.stack([r.uniform(-10, 10, n - n // 2), r.uniform(48, 58, n - n // 2)], 1),
        ])
        qpts = np.concatenate([
            np.stack([r.normal(4.0, 0.005, q // 2), r.normal(51.9, 0.005, q // 2)], 1),
            np.stack([r.uniform(-10, 10, q - q // 2), r.uniform(48, 58, q - q // 2)], 1),
        ])
        dists, idx, flags = knn_mxu(
            jnp.asarray(qpts[:, 0], jnp.float32), jnp.asarray(qpts[:, 1], jnp.float32),
            jnp.asarray(pts[:, 0], jnp.float32), jnp.asarray(pts[:, 1], jnp.float32),
            jnp.ones(n, bool), k=k, with_flags=True,
        )
        oracle = np.sort(haversine_m_np(
            qpts[:, 0:1], qpts[:, 1:2], pts[None, :, 0], pts[None, :, 1]
        ), axis=1)[:, :k]
        err = np.abs(np.sort(np.asarray(dists), 1) - oracle).max(1)
        flags = np.asarray(flags)
        assert np.all(flags[err > 1.0]), "unflagged query with >1m error"
        assert flags.sum() < q // 2, "certificate flags too much to be useful"

    def test_process_mxu_exact_via_fallback(self):
        # process layer must deliver oracle-exact results for impl=mxu by
        # re-solving flagged queries on the exact path
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.process.knn import KNearestNeighborSearchProcess

        r = np.random.default_rng(6)
        n, q, k = 20_000, 256, 6
        pts = np.concatenate([
            np.stack([r.normal(4.0, 0.004, n // 2), r.normal(51.9, 0.004, n // 2)], 1),
            np.stack([r.uniform(-10, 10, n - n // 2), r.uniform(48, 58, n - n // 2)], 1),
        ])
        qpts = np.concatenate([
            np.stack([r.normal(4.0, 0.004, q // 2), r.normal(51.9, 0.004, q // 2)], 1),
            np.stack([r.uniform(-10, 10, q - q // 2), r.uniform(48, 58, q - q // 2)], 1),
        ])
        sft = SimpleFeatureType.from_spec("t", "*geom:Point")
        data = FeatureBatch.from_pydict(sft, {"geom": pts})
        queries = FeatureBatch.from_pydict(sft, {"geom": qpts})
        res = KNearestNeighborSearchProcess().execute(
            queries, data, num_desired=k, impl="mxu"
        )
        oracle = np.sort(haversine_m_np(
            qpts[:, 0:1], qpts[:, 1:2], pts[None, :, 0], pts[None, :, 1]
        ), axis=1)[:, :k]
        np.testing.assert_allclose(
            np.sort(res.distances_m, 1), oracle, atol=1.0
        )

    def test_sharded_mxu_impl(self):
        mesh = default_mesh()
        mqx, mqy, _ = self._mxu_queries()
        args = (
            jnp.asarray(mqx), jnp.asarray(mqy),
            jnp.asarray(self.dx[:4096]), jnp.asarray(self.dy[:4096]),
            jnp.asarray(self.mask[:4096]),
        )
        d1, _ = knn(*args, k=self.k)
        d2, _ = knn_sharded(mesh, *args, k=self.k, impl="mxu")
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), atol=1.0)

    def test_ring_mxu_impl(self):
        mesh = default_mesh()
        mqx, mqy, _ = self._mxu_queries(q=256)  # shards to 32/device: mxu
        # pad queries... ring shards queries: 256/8 = 32 per device < 128
        # so per-device calls fall back exact; still exercises the hoisted
        # sort + presorted plumbing end to end
        args_d = (
            jnp.asarray(self.dx[:4096]), jnp.asarray(self.dy[:4096]),
            jnp.asarray(self.mask[:4096]),
        )
        d1, _ = knn(jnp.asarray(mqx), jnp.asarray(mqy), *args_d, k=self.k)
        d2, _ = knn_ring(
            mesh, jnp.asarray(mqx), jnp.asarray(mqy), *args_d,
            k=self.k, query_tile=32, impl="mxu",
        )
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), atol=1.0)

    def test_masked_points_excluded(self):
        mask = self.mask.copy()
        mask[:2500] = False
        dists, idx = knn(
            jnp.asarray(self.qx), jnp.asarray(self.qy),
            jnp.asarray(self.dx), jnp.asarray(self.dy),
            jnp.asarray(mask), k=self.k,
        )
        assert np.asarray(idx).min() >= 2500

    def test_sharded_matches_single(self):
        mesh = default_mesh()
        args = (
            jnp.asarray(self.qx), jnp.asarray(self.qy),
            jnp.asarray(self.dx[:4096]), jnp.asarray(self.dy[:4096]),
            jnp.asarray(self.mask[:4096]),
        )
        d1, i1 = knn(*args, k=self.k)
        d2, i2 = knn_sharded(mesh, *args, k=self.k)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)

    def test_sharded_debug_check_replication(self):
        # debug mode verifies the check_vma=False replication claim on
        # device: every device must hold bitwise-identical merged top-ks
        mesh = default_mesh()
        args = (
            jnp.asarray(self.qx), jnp.asarray(self.qy),
            jnp.asarray(self.dx[:4096]), jnp.asarray(self.dy[:4096]),
            jnp.asarray(self.mask[:4096]),
        )
        d1, _ = knn(*args, k=self.k)
        d2, _ = knn_sharded(mesh, *args, k=self.k, debug_check=True)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)

    def test_sharded_debug_check_inf_padding(self):
        # fewer valid matches than k: results are +inf-padded; the debug
        # equality check must not read inf agreement as divergence
        # (inf - inf = NaN regression from the round-2 review)
        mesh = default_mesh()
        mask = np.zeros(4096, bool)
        mask[:3] = True
        args = (
            jnp.asarray(self.qx), jnp.asarray(self.qy),
            jnp.asarray(self.dx[:4096]), jnp.asarray(self.dy[:4096]),
            jnp.asarray(mask),
        )
        d2, _ = knn_sharded(mesh, *args, k=self.k, debug_check=True)
        assert np.isinf(np.asarray(d2)[:, 3:]).all()

    def test_ring_matches_single(self):
        mesh = default_mesh()
        qn = 64  # queries shard over 8 devices
        args_q = (jnp.asarray(self.qx[:qn]), jnp.asarray(self.qy[:qn]))
        args_d = (
            jnp.asarray(self.dx[:4096]), jnp.asarray(self.dy[:4096]),
            jnp.asarray(self.mask[:4096]),
        )
        d1, i1 = knn(*args_q, *args_d, k=self.k)
        d2, i2 = knn_ring(mesh, *args_q, *args_d, k=self.k, query_tile=8)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)
        # indices agree wherever distances aren't ties
        agree = np.asarray(i1) == np.asarray(i2)
        ties = np.isclose(np.asarray(d1), np.roll(np.asarray(d1), 1, axis=1))
        assert (agree | ties).mean() > 0.99


class TestDensity:
    def test_grid_equals_numpy(self):
        n = 10_000
        x = rng.uniform(-74.1, -73.9, n)
        y = rng.uniform(40.6, 40.9, n)
        w = rng.uniform(0, 2, n).astype(np.float32)
        mask = rng.random(n) < 0.7
        bbox = (-74.1, 40.6, -73.9, 40.9)
        W = H = 64
        got = np.asarray(
            density_grid(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w),
                         jnp.asarray(mask), bbox, W, H)
        )
        # numpy oracle
        col = np.clip(((x - bbox[0]) / ((bbox[2] - bbox[0]) / W)).astype(int), 0, W - 1)
        row = np.clip(((y - bbox[1]) / ((bbox[3] - bbox[1]) / H)).astype(int), 0, H - 1)
        exp = np.zeros((H, W), np.float64)
        np.add.at(exp, (row[mask], col[mask]), w[mask])
        np.testing.assert_allclose(got, exp, rtol=1e-5)
        assert got.sum() == pytest.approx(w[mask].sum(), rel=1e-5)

    def test_mxu_matches_scatter(self):
        # the one-hot matmul formulation must reproduce the scatter grid
        # cell-for-cell (bf16 hi/lo weight split keeps f32-level exactness)
        from geomesa_tpu.engine.density import density_grid_mxu

        n = 20_000
        x = rng.uniform(-74.1, -73.9, n)
        y = rng.uniform(40.6, 40.9, n)
        w = rng.uniform(0, 2, n).astype(np.float32)
        mask = rng.random(n) < 0.7
        bbox = (-74.1, 40.6, -73.9, 40.9)
        W, H = 96, 64
        ref = np.asarray(
            density_grid(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w),
                         jnp.asarray(mask), bbox, W, H)
        )
        got = np.asarray(
            density_grid_mxu(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w),
                             jnp.asarray(mask), bbox, W, H,
                             point_tile=4096)
        )
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-4)
        # unweighted counts must be bit-exact (0/1 one-hots, f32 accum)
        ones = jnp.ones(n, jnp.float32)
        ref_c = np.asarray(
            density_grid(jnp.asarray(x), jnp.asarray(y), ones,
                         jnp.asarray(mask), bbox, W, H)
        )
        got_c = np.asarray(
            density_grid_mxu(jnp.asarray(x), jnp.asarray(y), ones,
                             jnp.asarray(mask), bbox, W, H,
                             point_tile=4096)
        )
        np.testing.assert_array_equal(got_c, ref_c)

    def test_outside_points_dropped(self):
        x = np.array([0.0, 200.0])  # second is out of any lon range
        y = np.array([0.0, 0.0])
        g = np.asarray(
            density_grid(jnp.asarray(x), jnp.asarray(y), jnp.ones(2),
                         jnp.ones(2, bool), (-1.0, -1.0, 1.0, 1.0), 8, 8)
        )
        assert g.sum() == 1.0

    def test_sharded_equals_single(self):
        mesh = default_mesh()
        n = 8 * 512
        x = rng.uniform(-74.1, -73.9, n)
        y = rng.uniform(40.6, 40.9, n)
        w = np.ones(n, np.float32)
        mask = np.ones(n, bool)
        bbox = (-74.1, 40.6, -73.9, 40.9)
        g1 = density_grid(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w),
                          jnp.asarray(mask), bbox, 32, 32)
        g2 = density_sharded(mesh, jnp.asarray(x), jnp.asarray(y),
                             jnp.asarray(w), jnp.asarray(mask), bbox, 32, 32)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5)

    def test_blur_preserves_mass(self):
        g = jnp.zeros((32, 32)).at[16, 16].set(100.0)
        b = np.asarray(gaussian_blur(g, 4))
        assert b.sum() == pytest.approx(100.0, rel=1e-3)
        assert b[16, 16] < 100.0


class TestStats:
    def test_basics(self):
        v = rng.uniform(-100, 100, 1000)
        mask = rng.random(1000) < 0.5
        assert int(masked_count(jnp.asarray(mask))) == mask.sum()
        mn, mx = masked_minmax(jnp.asarray(v), jnp.asarray(mask))
        assert float(mn) == pytest.approx(v[mask].min())
        assert float(mx) == pytest.approx(v[mask].max())
        c, s, ss = masked_moments(jnp.asarray(v), jnp.asarray(mask))
        assert float(s) == pytest.approx(v[mask].sum())
        assert float(ss) == pytest.approx((v[mask] ** 2).sum())

    def test_histogram(self):
        v = rng.uniform(0, 10, 1000)
        h = np.asarray(masked_histogram(jnp.asarray(v), jnp.ones(1000, bool), 0.0, 10.0, 20))
        exp, _ = np.histogram(v, bins=20, range=(0, 10))
        np.testing.assert_array_equal(h, exp)

    def test_value_counts(self):
        codes = rng.integers(-1, 5, 1000).astype(np.int32)
        mask = np.ones(1000, bool)
        counts = np.asarray(masked_value_counts(jnp.asarray(codes), jnp.asarray(mask), 5))
        for c in range(5):
            assert counts[c] == (codes == c).sum()
        assert counts.sum() == (codes >= 0).sum()

    def test_z3_histogram_total(self):
        n = 500
        x = rng.uniform(-180, 180, n)
        y = rng.uniform(-90, 90, n)
        tb = rng.integers(0, 4, n).astype(np.int32)
        h = np.asarray(z3_histogram(jnp.asarray(x), jnp.asarray(y), jnp.asarray(tb),
                                    jnp.ones(n, bool), 4, bins_per_dim=8))
        assert h.shape == (4, 8, 8)
        assert h.sum() == n

    def test_sharded_moments(self):
        mesh = default_mesh()
        n = 8 * 256
        v = rng.uniform(0, 1, n)
        mask = np.ones(n, bool)
        c, s, ss = stats_sharded(
            mesh, lambda v, m: masked_moments(v, m), jnp.asarray(v), jnp.asarray(mask)
        )
        assert int(c) == n
        assert float(s) == pytest.approx(v.sum())


class TestTube:
    def test_matches_numpy(self):
        n, T = 2000, 37
        x = rng.uniform(-10, 10, n)
        y = rng.uniform(50, 60, n)
        t = rng.integers(0, 1_000_000_000, n)
        tx = rng.uniform(-10, 10, T)
        ty = rng.uniform(50, 60, T)
        tt = rng.integers(0, 1_000_000_000, T)
        r, w = 50_000.0, 50_000_000
        got = np.asarray(tube_select(
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(t), jnp.ones(n, bool),
            jnp.asarray(tx), jnp.asarray(ty), jnp.asarray(tt), r, w, tube_tile=16,
        ))
        d = haversine_m_np(x[:, None], y[:, None], tx[None, :], ty[None, :])
        dt = np.abs(t[:, None] - tt[None, :])
        exp = ((d <= r) & (dt <= w)).any(axis=1)
        np.testing.assert_array_equal(got, exp)

    def test_sharded_matches_single(self):
        mesh = default_mesh()
        n, T = 8 * 256, 5
        x = rng.uniform(-10, 10, n)
        y = rng.uniform(50, 60, n)
        t = rng.integers(0, 10_000, n)
        tx = rng.uniform(-10, 10, T)
        ty = rng.uniform(50, 60, T)
        tt = rng.integers(0, 10_000, T)
        args = (jnp.asarray(x), jnp.asarray(y), jnp.asarray(t), jnp.ones(n, bool),
                jnp.asarray(tx), jnp.asarray(ty), jnp.asarray(tt), 100_000.0, 5_000)
        m1 = np.asarray(tube_select(*args))
        m2 = np.asarray(tube_select_sharded(mesh, *args))
        np.testing.assert_array_equal(m1, m2)


class TestBin:
    def test_roundtrip(self):
        n = 100
        track = rng.integers(0, 50, n).astype(np.int32)
        dtg = rng.integers(1_500_000_000_000, 1_600_000_000_000, n)
        lat = rng.uniform(-90, 90, n).astype(np.float32)
        lon = rng.uniform(-180, 180, n).astype(np.float32)
        packed = bin_pack(jnp.asarray(track), jnp.asarray(dtg),
                          jnp.asarray(lat), jnp.asarray(lon))
        buf = encode_bin(packed)
        assert len(buf) == n * 16
        rec = decode_bin(buf)
        np.testing.assert_array_equal(rec["track"], track)
        np.testing.assert_array_equal(rec["dtg_s"], dtg // 1000)
        np.testing.assert_allclose(rec["lat"], lat)
        np.testing.assert_allclose(rec["lon"], lon)

    def test_selection(self):
        packed = bin_pack(jnp.arange(10, dtype=jnp.int32), jnp.zeros(10, jnp.int64),
                          jnp.zeros(10), jnp.zeros(10))
        sel = np.array([1, 3, 5])
        rec = decode_bin(encode_bin(packed, sel))
        np.testing.assert_array_equal(rec["track"], [1, 3, 5])


class TestKNNSmallN:
    def test_indices_in_range_when_k_exceeds_n(self):
        """Padded top-k slots must keep indices < N (documented contract)."""
        import jax.numpy as jnp
        import numpy as np

        from geomesa_tpu.engine.knn import knn

        dx = jnp.asarray(np.array([0.0, 1.0, 2.0], np.float32))
        dy = jnp.asarray(np.zeros(3, np.float32))
        mask = jnp.asarray(np.array([True, True, False]))
        d, i = knn(jnp.zeros(2, jnp.float32), jnp.zeros(2, jnp.float32),
                   dx, dy, mask, k=5, query_tile=2)
        assert int(jnp.max(i)) < 3
        assert bool(jnp.all(jnp.isinf(d[:, 2:])))  # only 2 valid candidates
