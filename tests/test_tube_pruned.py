"""Tile-pruned tube-select tests: parity with the dense kernel (which
test_engine.py gates against a NumPy sweep) on Z-ordered and random
inputs, overflow fallback, and the sharded variant."""

import numpy as np
import pytest

import jax.numpy as jnp

from geomesa_tpu.engine.tube import (
    tube_select, tube_select_pruned, tube_select_pruned_sharded)


def make(n=40_000, seed=3, z_order=True):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-20, 20, n)
    y = rng.uniform(40, 70, n)
    if z_order:
        o = np.argsort(x + 1e-3 * y)  # cheap store-order proxy
        x, y = x[o], y[o]
    t = rng.integers(0, 86_400_000, n)
    T = 192
    tx = np.linspace(-15, 15, T)
    ty = np.linspace(42, 68, T) + rng.normal(0, 0.05, T)
    tt = np.linspace(0, 86_400_000, T).astype(np.int64)
    return x, y, t, tx, ty, tt


def dev_args(x, y, t, mask, tx, ty, tt, radius, win):
    return (
        jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
        jnp.asarray(t, jnp.int64), jnp.asarray(mask),
        jnp.asarray(tx, jnp.float32), jnp.asarray(ty, jnp.float32),
        jnp.asarray(tt, jnp.int64),
        jnp.float32(radius), jnp.int64(win),
    )


class TestTubePruned:
    @pytest.mark.parametrize("z_order", [True, False])
    def test_parity_with_dense(self, z_order):
        x, y, t, tx, ty, tt = make(z_order=z_order)
        mask = np.random.default_rng(5).random(len(x)) < 0.8
        args = dev_args(x, y, t, mask, tx, ty, tt, 30_000.0, 3_600_000)
        dense = np.asarray(tube_select(*args, data_tile=2048))
        pruned, cap = tube_select_pruned(*args, data_tile=2048)
        assert cap != 0
        np.testing.assert_array_equal(np.asarray(pruned), dense)
        assert dense.sum() > 0  # non-vacuous

    def test_prunes_far_tiles(self):
        # corridor confined to a corner: most Z-ordered tiles are out of
        # reach, so a small capacity suffices without overflow
        x, y, t, tx, ty, tt = make()
        tx = np.linspace(-19, -17, len(tx))
        ty = np.linspace(41, 43, len(ty))
        mask = np.ones(len(x), bool)
        args = dev_args(x, y, t, mask, tx, ty, tt, 10_000.0, 86_400_000)
        dense = np.asarray(tube_select(*args, data_tile=2048))
        pruned, cap = tube_select_pruned(
            *args, data_tile=2048, tile_capacity=8)
        assert cap == 8  # no overflow at a tiny capacity = real pruning
        np.testing.assert_array_equal(np.asarray(pruned), dense)

    def test_overflow_falls_back_exactly(self):
        x, y, t, tx, ty, tt = make(n=20_000)
        mask = np.ones(len(x), bool)
        # 100km corridor across everything at capacity 1: must overflow
        args = dev_args(x, y, t, mask, tx, ty, tt, 100_000.0, 86_400_000)
        dense = np.asarray(tube_select(*args, data_tile=1024))
        pruned, cap = tube_select_pruned(
            *args, data_tile=1024, tile_capacity=1)
        assert cap == -1  # fallback ran
        np.testing.assert_array_equal(np.asarray(pruned), dense)

    def test_time_pruning(self):
        # spatially-overlapping corridor, disjoint time range: nothing
        # matches, and the time envelope prune keeps capacity tiny
        x, y, t, tx, ty, tt = make(n=10_000)
        tt = tt + 200 * 86_400_000
        mask = np.ones(len(x), bool)
        args = dev_args(x, y, t, mask, tx, ty, tt, 30_000.0, 60_000)
        pruned, cap = tube_select_pruned(
            *args, data_tile=1024, tile_capacity=1)
        assert cap == 1 and not np.asarray(pruned).any()

    def test_polar_corridor_spans_all_longitudes(self):
        # a corridor whose radius reaches the pole matches points at ANY
        # longitude (review repro: the old 89.5-deg clamp dropped them)
        n = 5000
        x = np.full(n, 100.0)
        y = np.full(n, 89.8)
        t = np.zeros(n, np.int64)
        mask = np.ones(n, bool)
        args = dev_args(x, y, t, mask, np.array([0.0]), np.array([89.8]),
                        np.array([0], np.int64), 50_000.0, 1_000_000)
        dense = np.asarray(tube_select(*args, data_tile=1024))
        pruned, _ = tube_select_pruned(*args, data_tile=1024)
        np.testing.assert_array_equal(np.asarray(pruned), dense)
        assert dense.all()  # 34 km away: every point matches

    def test_f64_path(self):
        # the process path runs f64 coords through the same kernel
        x, y, t, tx, ty, tt = make(n=8_000)
        mask = np.ones(len(x), bool)
        args = (
            jnp.asarray(x, jnp.float64), jnp.asarray(y, jnp.float64),
            jnp.asarray(t, jnp.int64), jnp.asarray(mask),
            jnp.asarray(tx, jnp.float64), jnp.asarray(ty, jnp.float64),
            jnp.asarray(tt, jnp.int64),
            30_000.0, 3_600_000,
        )
        dense = np.asarray(tube_select(
            args[0], args[1], args[2], args[3], args[4], args[5], args[6],
            jnp.float32(30_000.0), jnp.int64(3_600_000), data_tile=1024))
        pruned, _ = tube_select_pruned(*args, data_tile=1024)
        np.testing.assert_array_equal(np.asarray(pruned), dense)


class TestTubePrunedSharded:
    def test_matches_dense(self):
        import jax
        from jax.sharding import Mesh

        from geomesa_tpu.parallel.mesh import SHARD_AXIS

        devs = jax.devices()
        if len(devs) < 4:
            pytest.skip("needs >=4 virtual devices")
        mesh = Mesh(np.asarray(devs[:4]), (SHARD_AXIS,))
        x, y, t, tx, ty, tt = make(n=4 * 8192)
        mask = np.ones(len(x), bool)
        args = dev_args(x, y, t, mask, tx, ty, tt, 30_000.0, 3_600_000)
        dense = np.asarray(tube_select(*args, data_tile=1024))
        hits, ov = tube_select_pruned_sharded(
            mesh, *args, data_tile=1024, tile_capacity=8)
        assert not bool(np.asarray(ov))
        np.testing.assert_array_equal(np.asarray(hits), dense)


def test_small_radius_f32_exact():
    # round-4 review repro: the dot-form chord test lost true matches at
    # small radii (cos(r/R) rounds to 1.0f below ~2.2 km); the
    # difference form must find every point 50 m from a sample at a
    # 500 m radius, in f32
    rng = np.random.default_rng(41)
    T = 8
    tx = np.linspace(10.0, 10.01, T)
    ty = np.linspace(45.0, 45.01, T)
    tt = np.zeros(T, np.int64)
    # points planted ~50 m east of each sample (1 deg lon ~ 78.8 km at 45N)
    n = 2000
    pick = rng.integers(0, T, n)
    px = tx[pick] + 50.0 / 78_847.0
    py = ty[pick]
    pt = np.zeros(n, np.int64)
    args = (
        jnp.asarray(px, jnp.float32), jnp.asarray(py, jnp.float32),
        jnp.asarray(pt), jnp.ones(n, bool),
        jnp.asarray(tx, jnp.float32), jnp.asarray(ty, jnp.float32),
        jnp.asarray(tt), jnp.float32(500.0), jnp.int64(1000),
    )
    got = np.asarray(tube_select(*args, data_tile=1024))
    assert got.all(), f"missed {int((~got).sum())}/{n} at 500 m radius"
    # and a 500 m-away point must NOT match a 100 m radius
    args2 = args[:7] + (jnp.float32(100.0), jnp.int64(1000))
    px2 = tx[pick] + 500.0 / 78_847.0
    args2 = (jnp.asarray(px2, jnp.float32),) + args2[1:]
    got2 = np.asarray(tube_select(*args2, data_tile=1024))
    assert not got2.any()
