"""SLO fabric tests (docs/OBSERVABILITY.md): the SLO engine's burn
math on a fake clock, the closed serve loop (injected latency fault ->
budget burn -> degradation ladder engages -> recovery, observable via
/debug/slo), the continuous profiler's fold semantics + overhead
budget, the regression sentinel's typed verdicts, and the
scrape-vs-fold races (/debug/gap + /metrics + /debug/prof under
concurrent mesh+pipelined traffic).

Wall-clock discipline (tier-1 budget is near-full): the serve fixtures
reuse the exact store/kernel shapes test_serve.py and
test_mesh_serve.py already compiled (512-row point store with k=5 kNN;
the 4-day/1024-row mesh store under a 4-chip mesh), all SLO window
arithmetic runs on a fake clock, and the single injected-latency fault
adds ~0.4s once.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from geomesa_tpu.telemetry import sentinel
from geomesa_tpu.telemetry.prof import ContinuousProfiler, render_prof
from geomesa_tpu.telemetry.slo import (SloEngine, SloSpec,
                                       parse_toml_subset, render_slo)

# -- spec parsing -----------------------------------------------------------


class TestSloSpec:
    def test_toml_subset_round_trip(self, tmp_path):
        p = tmp_path / "slo.toml"
        p.write_text("""
# serve objectives
[slo]
fast_window_s = 2.0
slow_window_s = 8.0   # scaled for tests
burn_threshold = 2.0

[objective.knn_p99]
kind = "latency"
threshold_ms = 25.0
goal = 0.9
query_kind = "knn"
degrade = true

[objective.availability]
kind = "availability"
goal = 0.999
""")
        spec = SloSpec.load(str(p))
        assert spec.fast_window_s == 2.0
        assert spec.budget_window_s == 8.0  # defaults to slow
        assert spec.objectives["knn_p99"].threshold_ms == 25.0
        assert spec.objectives["knn_p99"].degrade
        assert spec.objectives["availability"].kind == "availability"

    def test_json_spec(self, tmp_path):
        p = tmp_path / "slo.json"
        p.write_text(json.dumps({
            "slo": {"fast_window_s": 1.0, "slow_window_s": 4.0},
            "objective": {
                "tput": {"kind": "throughput", "min_per_s": 10.0},
            }}))
        spec = SloSpec.load(str(p))
        assert spec.objectives["tput"].min_per_s == 10.0

    def test_validation(self):
        with pytest.raises(ValueError, match="no .objective"):
            SloSpec.from_dict({"slo": {}})
        with pytest.raises(ValueError, match="unknown kind"):
            SloSpec.from_dict(
                {"objective": {"x": {"kind": "nope"}}})
        with pytest.raises(ValueError, match="threshold_ms"):
            SloSpec.from_dict(
                {"objective": {"x": {"kind": "latency"}}})
        with pytest.raises(ValueError, match="unknown key"):
            SloSpec.from_dict(
                {"objective": {"x": {"kind": "availability",
                                     "typo_ms": 3}}})
        with pytest.raises(ValueError, match="fast window"):
            SloSpec.from_dict({
                "slo": {"fast_window_s": 10.0, "slow_window_s": 5.0},
                "objective": {"x": {"kind": "availability"}}})

    def test_toml_parser_errors(self):
        with pytest.raises(ValueError, match="key = value"):
            parse_toml_subset("just words\n")
        with pytest.raises(ValueError, match="cannot parse"):
            parse_toml_subset("x = [1, 2]\n")


# -- burn math on a fake clock ----------------------------------------------


def make_engine(**objective_kw):
    spec = SloSpec.from_dict({
        "slo": {"fast_window_s": 2.0, "slow_window_s": 8.0,
                "burn_threshold": 2.0},
        "objective": {"obj": dict(
            {"kind": "latency", "threshold_ms": 10.0, "goal": 0.9,
             "degrade": True, "min_count": 4}, **objective_kw)},
    })
    now = [1000.0]
    eng = SloEngine(spec, clock=lambda: now[0])
    eng.boost_ttl_s = 0.0  # tests assert step-for-step
    return eng, now


class TestBurnMath:
    def test_clean_traffic_burns_nothing(self):
        eng, now = make_engine()
        for _ in range(20):
            eng.observe("knn", "ok", 0.001)
            now[0] += 0.05
        obj = eng.spec.objectives["obj"]
        rates = eng.burn_rates(obj)
        assert rates["fast"] == 0.0 and rates["slow"] == 0.0
        assert eng.budget_remaining(obj) == 1.0
        assert eng.breaching() == [] and eng.degrade_boost() == 0

    def test_bad_traffic_burns_and_recovers(self):
        eng, now = make_engine()
        obj = eng.spec.objectives["obj"]
        for _ in range(10):
            eng.observe("knn", "ok", 0.5)  # 500ms >> 10ms threshold
            now[0] += 0.05
        rates = eng.burn_rates(obj)
        # all-bad traffic burns at 1/budget = 10x
        assert rates["fast"] == pytest.approx(10.0)
        assert rates["slow"] == pytest.approx(10.0)
        assert eng.budget_remaining(obj) == 0.0
        assert eng.breaching() == ["obj"]
        assert eng.degrade_boost() == 2
        rep = eng.report()
        assert rep["objectives"]["obj"]["state"] == "violated"
        # recovery: the breach ages out of the windows
        now[0] += 10.0
        assert eng.breaching() == [] and eng.degrade_boost() == 0
        assert eng.budget_remaining(obj) == 1.0
        assert render_slo(eng.report())  # renders without data too

    def test_multiwindow_gate_needs_both(self):
        """A burst that clears the fast window while still polluting
        the slow one must NOT breach (and vice versa) — the classic
        multi-window rule."""
        eng, now = make_engine()
        for _ in range(10):
            eng.observe("knn", "ok", 0.5)
            now[0] += 0.05
        assert eng.breaching() == ["obj"]
        # 3s later: out of the 2s fast window, inside the 8s slow one
        now[0] += 3.0
        for _ in range(10):
            eng.observe("knn", "ok", 0.001)  # fast traffic now good
            now[0] += 0.01
        obj = eng.spec.objectives["obj"]
        rates = eng.burn_rates(obj)
        assert rates["fast"] == 0.0 and rates["slow"] > 2.0
        assert eng.breaching() == []

    def test_query_kind_filter(self):
        eng, now = make_engine(query_kind="knn")
        for _ in range(10):
            eng.observe("count", "ok", 0.5)  # wrong kind: ignored
        assert eng.burn_rates(eng.spec.objectives["obj"])["fast"] == 0.0

    def test_availability_counts_typed_errors_not_shedding(self):
        eng, now = make_engine(kind="availability", threshold_ms=0.0)
        obj = eng.spec.objectives["obj"]
        for status in ("ok", "error", "timeout", "rejected"):
            for _ in range(5):
                eng.observe("knn", status, 0.01)
        # 10 bad (error+timeout) of 20 counted (rejected excluded from
        # the bad set but still in the denominator)
        assert eng.burn_rates(obj)["fast"] == pytest.approx(
            (10 / 20) / 0.1)

    def test_exactness_counts_degraded(self):
        eng, now = make_engine(kind="exactness", threshold_ms=0.0)
        for i in range(10):
            eng.observe("knn", "ok", 0.01, degraded=(i % 2 == 0))
        assert eng.burn_rates(
            eng.spec.objectives["obj"])["fast"] == pytest.approx(5.0)

    def test_throughput_floor(self):
        eng, now = make_engine(kind="throughput", threshold_ms=0.0,
                               min_per_s=10.0)
        obj = eng.spec.objectives["obj"]
        # 2s of traffic at 20/s: above the floor
        for _ in range(40):
            eng.observe("knn", "ok", 0.001)
            now[0] += 0.05
        assert eng.burn_rates(obj)["fast"] == 0.0
        # traffic stops; the fast window drains to ~zero rate
        now[0] += 2.0
        assert eng.burn_rates(obj)["fast"] > 2.0

    def test_boost_cache_honors_ttl(self):
        eng, now = make_engine()
        eng.boost_ttl_s = 0.5
        for _ in range(10):
            eng.observe("knn", "ok", 0.5)
            now[0] += 0.01
        assert eng.degrade_boost() == 2
        # breach ages out, but the cache still answers until the TTL
        now[0] += 10.0
        eng.boost_ttl_s = 1e9
        eng._boost_cache = (now[0], 2)
        assert eng.degrade_boost() == 2  # cached
        eng.boost_ttl_s = 0.0
        assert eng.degrade_boost() == 0  # recomputed


# -- the closed serve loop --------------------------------------------------


@pytest.fixture(scope="module")
def slo_store(tmp_path_factory):
    """Same shapes as test_serve/test_telemetry (512-row point store,
    k=5 whole-world kNN) so the kernels are warm by suite order."""
    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore

    rng = np.random.default_rng(7)
    n = 512
    sft = SimpleFeatureType.from_spec(
        "sloserve", "name:String,score:Double,dtg:Date,*geom:Point")
    batch = FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })
    tmp = tmp_path_factory.mktemp("sloserve")
    store = DataStore(str(tmp), use_device_cache=True)
    store.create_schema(sft).write(batch)
    return store


CQL = "BBOX(geom, -180, -90, 180, 90)"


class TestServeClosedLoop:
    """The acceptance demo: injected latency fault -> budget burn ->
    burn gauges flip -> ladder engages -> recovery, via /debug/slo."""

    def test_injected_latency_burns_budget_and_degrades(self, slo_store):
        from geomesa_tpu.faults import harness as fharness
        from geomesa_tpu.faults.plan import FaultPlan, FaultRule
        from geomesa_tpu.serve.service import QueryService, ServeConfig
        from geomesa_tpu.serve.scheduler import QueryRejected
        from geomesa_tpu.telemetry.export import MetricsServer
        from geomesa_tpu.utils.metrics import metrics

        # warm pass: residency upload + kernel compiles happen on a
        # throwaway service, so the measured phases see steady-state
        # latencies (the objective threshold is a wall-clock bound)
        rngw = np.random.default_rng(4)
        warm = QueryService(slo_store, ServeConfig(max_wait_ms=5.0))
        warm.knn("sloserve", CQL, rngw.uniform(-60, 60, 1),
                 rngw.uniform(-60, 60, 1), k=5).result(timeout=300)
        warm.close(drain=True)

        now = [5000.0]
        spec = SloSpec.from_dict({
            "slo": {"fast_window_s": 2.0, "slow_window_s": 8.0,
                    "burn_threshold": 2.0},
            "objective": {
                "knn_p99": {"kind": "latency", "threshold_ms": 150.0,
                            "goal": 0.9, "query_kind": "knn",
                            "degrade": True, "min_count": 4},
                "availability": {"kind": "availability", "goal": 0.99,
                                 "min_count": 4},
            }})
        eng = SloEngine(spec, clock=lambda: now[0])
        eng.boost_ttl_s = 0.0
        svc = QueryService(slo_store, ServeConfig(
            max_wait_ms=20.0, degrade=True, slo=eng), autostart=False)
        server = MetricsServer(port=0, stats_fn=svc.stats,
                               pre_scrape=svc.export_gauges,
                               slo_fn=eng.report)
        port = server.start()
        rng = np.random.default_rng(3)
        qp = rng.uniform(-60, 60, (8, 2))

        def burst(count):
            futs = [svc.knn("sloserve", CQL, qp[i:i + 1, 0],
                            qp[i:i + 1, 1], k=5) for i in range(count)]
            for f in futs:
                f.result(timeout=300)

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return r.read().decode()

        try:
            svc.start()
            # phase 1 — healthy traffic: no burn, ladder off
            burst(6)
            now[0] += 0.2
            doc = json.loads(get("/debug/slo"))
            assert doc["enabled"] and doc["breaching"] == []
            assert doc["objectives"]["knn_p99"]["state"] in (
                "ok", "insufficient-data")
            assert svc.degrade_level() == 0

            # phase 2 — inject a 400ms latency fault at the device
            # transfer boundary: every served kNN blows the 150ms
            # objective, the budget burns, the multi-window gate trips
            plan = FaultPlan(rules=[FaultRule(
                site="device.transfer", error="latency",
                probability=1.0, latency_ms=400.0)])
            fharness.install(plan)
            try:
                burst(6)
            finally:
                fharness.uninstall()
            now[0] += 0.2
            doc = json.loads(get("/debug/slo"))
            assert "knn_p99" in doc["breaching"], doc
            assert doc["objectives"]["knn_p99"]["burn_rate"]["fast"] > 2.0
            assert doc["objectives"]["knn_p99"]["budget_remaining"] < 1.0
            assert doc["degrade_boost"] >= 1
            # the ladder is engaged on burn alone — the queue is EMPTY
            assert len(svc.queue) == 0
            level = svc.degrade_level()
            assert level >= 1
            # level 2 (budget exhausted): batch-class work sheds typed
            if level >= 2:
                with pytest.raises(QueryRejected, match="shed"):
                    svc.count("sloserve", CQL, priority="batch")
            # degraded execution: an opted-in request gets the hint
            # rewrite, visible in the service counters
            before = svc.stats().get("degraded", 0)
            f = svc.knn("sloserve", CQL, qp[0:1, 0], qp[0:1, 1], k=5)
            # allow_degraded rides the kwargs path
            f2 = svc.knn("sloserve", CQL, qp[1:2, 0], qp[1:2, 1], k=5,
                         allow_degraded=True)
            f.result(timeout=300)
            f2.result(timeout=300)
            assert svc.stats().get("degraded", 0) == before + 1

            # the burn gauges export at scrape time
            body = get("/metrics")
            assert 'slo_burn_rate{objective="knn_p99",window="fast"}' \
                in body
            assert 'slo_budget_remaining{objective="knn_p99"}' in body

            # phase 3 — recovery: the breach ages out of both windows,
            # healthy traffic resumes, the ladder releases
            now[0] += 10.0
            burst(4)
            now[0] += 0.1
            doc = json.loads(get("/debug/slo"))
            assert doc["breaching"] == [] and doc["degrade_boost"] == 0
            assert doc["objectives"]["knn_p99"]["budget_remaining"] == 1.0
            assert svc.degrade_level() == 0
            # availability never burned: latency was slow, not failing
            assert doc["objectives"]["availability"]["burn_rate"][
                "slow"] == 0.0
            # /debug/stats carries the slo report for gmtpu top
            stats = json.loads(get("/debug/stats"))
            assert stats["serve"]["slo"]["enabled"]
        finally:
            server.stop()
            svc.close(drain=True)

    def test_window_rejection_observed_as_rejected_not_error(
            self, slo_store):
        """A pipelined window failed with QueryRejected (shutdown/
        drain) fans the rejection out to its members through
        _finish_window, where the wire status is 'error' — but the SLO
        observation must stay 'rejected': shedding never burns the
        availability budget (review regression)."""
        import time as _time

        from geomesa_tpu.serve.scheduler import (QueryRejected,
                                                 ServeRequest)
        from geomesa_tpu.serve.service import QueryService, ServeConfig
        from geomesa_tpu.plan.query import Query

        spec = {"slo": {"fast_window_s": 2.0, "slow_window_s": 8.0},
                "objective": {"avail": {"kind": "availability",
                                        "goal": 0.99}}}
        svc = QueryService(slo_store,
                           ServeConfig(max_wait_ms=1.0, slo=spec),
                           autostart=False)
        try:
            req = ServeRequest(kind="count",
                               query=Query("sloserve", CQL))
            req.enqueued_at = _time.monotonic()
            req.future.set_running_or_notify_cancel()
            req.future.set_exception(
                QueryRejected("shutting_down", "service closed"))
            svc._finish_window([req], [], req, req.enqueued_at,
                               _time.monotonic(), 0, None, 0, 0, [], [],
                               pipelined=True)
            obs = list(svc.slo._obs)
            assert obs and obs[-1][2] == "rejected", obs
        finally:
            svc.close(drain=False)

    def test_wire_stats_verb_carries_slo(self, slo_store):
        from geomesa_tpu.serve.protocol import serve_lines
        from geomesa_tpu.serve.service import QueryService, ServeConfig

        spec = {"slo": {"fast_window_s": 2.0, "slow_window_s": 8.0},
                "objective": {"avail": {"kind": "availability",
                                        "goal": 0.99}}}
        svc = QueryService(slo_store,
                           ServeConfig(max_wait_ms=5.0, slo=spec))
        out = []
        serve_lines(
            slo_store,
            [json.dumps({"id": "c1", "op": "count",
                         "typeName": "sloserve", "cql": CQL}),
             json.dumps({"id": "s1", "op": "stats"})],
            out.append, service=svc)
        docs = [json.loads(ln) for ln in out]
        stats = next(d for d in docs if d["id"] == "s1")
        assert stats["ok"] and stats["stats"]["slo"]["enabled"]
        assert "avail" in stats["stats"]["slo"]["objectives"]


# -- continuous profiler ----------------------------------------------------


def synth_trace(i, scale=1.0, proc="aa", shards=None, overlap=False):
    us = 1000
    # overlapping windows share wall time across traces
    t0 = (i * 50 if overlap else i * 200) * us
    attrs = {"kernel": "knn_sparse"}
    if shards:
        attrs["shards"] = shards
    return {
        "trace_id": f"{proc}-{i}", "name": "query",
        "root": {"name": "query", "id": i * 10 + 1, "parent": None,
                 "t0_ns": t0, "t1_ns": t0 + int(100 * us * scale),
                 "thread": 0},
        "spans": [
            {"name": "queue.wait", "id": i * 10 + 2, "parent": i * 10 + 1,
             "t0_ns": t0, "t1_ns": t0 + 40 * us, "thread": 0},
            {"name": "dispatch", "id": i * 10 + 3, "parent": i * 10 + 1,
             "t0_ns": t0 + 40 * us,
             "t1_ns": t0 + int(95 * us * scale), "thread": 0},
            {"name": "kernel.dispatch", "id": i * 10 + 4,
             "parent": i * 10 + 3, "t0_ns": t0 + 50 * us,
             "t1_ns": t0 + int(70 * us * scale), "thread": 0,
             "attrs": attrs},
        ],
    }


class TestProfiler:
    def test_fold_phases_kernels_and_root(self):
        p = ContinuousProfiler()
        p.enable()
        for i in range(20):
            p.fold(synth_trace(i))
        snap = p.snapshot()
        assert snap["traces"] == 20
        assert snap["phases"]["dispatch"]["n"] == 20
        assert snap["phases"]["query"]["n"] == 20  # root fold
        assert snap["phases"]["dispatch"]["p50_ms"] == pytest.approx(
            0.055, rel=0.01)
        k = snap["kernels"]["knn_sparse"]
        assert k["device"]["n"] == 20
        assert k["device"]["p50_ms"] == pytest.approx(0.02, rel=0.01)
        # host gap = window (55) - device (20) = 35µs, all attributed
        # to the only kernel family
        assert k["gap"]["p50_ms"] == pytest.approx(0.035, rel=0.01)
        assert render_prof(snap)

    def test_rider_dedup_by_span_id(self):
        """A rider-adopted copy of the shared window (same proc, same
        span ids) must not double-count the window."""
        p = ContinuousProfiler()
        p.enable()
        t = synth_trace(1)
        p.fold(t)
        rider = dict(synth_trace(1), trace_id="aa-99")
        p.fold(rider)
        snap = p.snapshot()
        assert snap["phases"]["dispatch"]["n"] == 1
        assert snap["traces"] == 2
        # a DIFFERENT process's identical ids are distinct spans
        p.fold(synth_trace(1, proc="bb"))
        assert p.snapshot()["phases"]["dispatch"]["n"] == 2

    def test_shard_lanes_and_imbalance(self):
        p = ContinuousProfiler()
        p.enable()
        for i in range(10):
            p.fold(synth_trace(i, shards="0,1"))
        for i in range(10, 14):
            p.fold(synth_trace(i, scale=3.0, shards="1"))
        snap = p.snapshot()
        lanes = snap["shards"]["lanes"]
        assert set(lanes) == {"0", "1"}
        assert lanes["1"]["device_ms"] > lanes["0"]["device_ms"]
        assert snap["shards"]["imbalance_ratio"] > 1.1

    def test_pipeline_overlap_estimate(self):
        p = ContinuousProfiler()
        p.enable()
        for i in range(10):
            p.fold(synth_trace(i, overlap=True))
        pl = p.snapshot()["pipeline"]
        assert pl["windows_in_flight_max"] >= 2
        assert pl["overlap_ms"] > 0.0
        # pairwise sums are clamped per window: at depth > 2 the share
        # must still read as a fraction of window time, never > 100%
        assert pl["overlap_share"] <= 1.0
        # serial windows report no overlap
        p2 = ContinuousProfiler()
        p2.enable()
        for i in range(10):
            p2.fold(synth_trace(i, overlap=False))
        assert p2.snapshot()["pipeline"]["overlap_ms"] == 0.0
        # depth-4: four identical windows would sum 3x pairwise
        # overlap per window without the clamp
        p3 = ContinuousProfiler()
        p3.enable()
        us = 1000
        for i in range(8):
            p3.fold({"trace_id": f"cc-{i}", "name": "q",
                     "root": {"name": "q", "id": i * 10 + 1,
                              "parent": None, "t0_ns": 0,
                              "t1_ns": 100 * us, "thread": 0},
                     "spans": [{"name": "dispatch", "id": i * 10 + 2,
                                "parent": i * 10 + 1, "t0_ns": 0,
                                "t1_ns": 100 * us, "thread": 0}]})
        deep = p3.snapshot()["pipeline"]
        assert deep["windows_in_flight_max"] >= 4
        assert deep["overlap_share"] <= 1.0, deep

    def test_recorder_hook_and_disable(self):
        from geomesa_tpu.telemetry.prof import PROFILER
        from geomesa_tpu.telemetry.recorder import FlightRecorder

        rec = FlightRecorder(capacity=4)
        PROFILER.reset()
        PROFILER.enable()
        try:
            rec.record(synth_trace(1))
            assert PROFILER.snapshot()["traces"] == 1
        finally:
            PROFILER.disable()
        rec.record(synth_trace(2))
        assert PROFILER.snapshot()["traces"] == 1  # off = no fold
        PROFILER.reset()

    def test_fold_overhead_budget(self):
        """The cost contract: the fold is one pass, 2µs per unit of
        work — a unit per span plus two fixed units (the root fold and
        the window/overlap bookkeeping, which amortize away on real
        ~15-span serve traces but dominate a 3-span synthetic). Same
        same-process relative fallback discipline as the tracer tests:
        a throttled CI host is measured against its own floor loop
        (the minimal possible span walk), and a structural regression
        — an O(n) seen-table sweep per fold, an unbounded window ring —
        blows the 25x-floor ratio on any host."""
        import gc

        p = ContinuousProfiler()
        p.enable()
        traces = [synth_trace(i) for i in range(2000)]
        spans_per = len(traces[0]["spans"])
        fold = floor = float("inf")
        # let the preceding serve tests' dispatcher/completer threads
        # finish dying: a busy sibling core reads as fold overhead
        time.sleep(0.1)
        gc.disable()
        try:
            for _ in range(9):
                p.reset()
                t0 = time.perf_counter_ns()
                for t in traces:
                    p.fold(t)
                fold = min(fold,
                           (time.perf_counter_ns() - t0) / len(traces))
                acc = 0
                t0 = time.perf_counter_ns()
                for t in traces:
                    for s in t["spans"]:
                        acc += s["t1_ns"] - s["t0_ns"]
                floor = min(floor,
                            (time.perf_counter_ns() - t0) / len(traces))
        finally:
            gc.enable()
        budget = 2000.0 * (spans_per + 2)
        assert fold < budget or fold < 25 * floor, (
            f"fold cost {fold:.0f}ns/trace ({spans_per} spans; floor "
            f"{floor:.0f}ns in the same process)")

    def test_disabled_maybe_fold_is_noop_cheap(self):
        p = ContinuousProfiler()
        doc = synth_trace(1)
        n = 20000
        t0 = time.perf_counter_ns()
        for _ in range(n):
            p.maybe_fold(doc)
        per = (time.perf_counter_ns() - t0) / n
        assert p.snapshot()["traces"] == 0
        # one attribute read + branch; generous bound for slow hosts
        assert per < 1000.0, f"disabled maybe_fold {per:.0f}ns"


# -- sentinel ---------------------------------------------------------------


def profile_metrics(scale=1.0, n=40, seed=1):
    rng = np.random.default_rng(seed)
    p = ContinuousProfiler()
    p.enable()
    for i in range(n):
        p.fold(synth_trace(i, scale=scale * (1 + rng.uniform(0, 0.04))))
    return sentinel.baseline_from_profile(
        p.snapshot(include_samples=True))


class TestSentinel:
    def test_identical_replay_is_ok(self):
        base = profile_metrics(seed=1)
        cur = profile_metrics(seed=2)
        rep = sentinel.compare(base, cur)
        assert not rep["regressed"]
        assert sentinel.exit_code(rep) == 0
        assert all(v["verdict"] == "ok"
                   for v in rep["metrics"].values()), rep["metrics"]

    def test_slowdown_regresses_and_exit_nonzero(self):
        rep = sentinel.compare(profile_metrics(), profile_metrics(3.0))
        assert rep["regressed"] and sentinel.exit_code(rep) == 1
        assert rep["metrics"]["phase.dispatch"]["verdict"] == "regressed"
        # queue.wait is unscaled in the synth trace: still ok
        assert rep["metrics"]["phase.queue.wait"]["verdict"] == "ok"
        assert "regressed" in sentinel.render_verdicts(rep)

    def test_speedup_reports_improved(self):
        rep = sentinel.compare(profile_metrics(3.0), profile_metrics())
        assert not rep["regressed"]
        assert rep["metrics"]["phase.dispatch"]["verdict"] == "improved"

    def test_insufficient_data_never_verdicts(self):
        base = profile_metrics(n=3)
        cur = profile_metrics(n=3, seed=5)
        rep = sentinel.compare(base, cur)
        assert all(v["verdict"] == "insufficient-data"
                   for v in rep["metrics"].values())
        assert not rep["regressed"]
        # a metric missing from one side is insufficient, not a crash
        rep = sentinel.compare(profile_metrics(),
                               {"metrics": {"only.here": {
                                   "n": 99, "median_ms": 1.0,
                                   "samples_ms": [1.0] * 99}}})
        assert rep["metrics"]["only.here"]["verdict"] == \
            "insufficient-data"
        # lost instrumentation must not read as green under --strict:
        # the default exit stays regression-driven, strict fails on
        # any uncompared metric
        assert sentinel.exit_code(rep) == 0
        assert sentinel.exit_code(rep, strict=True) == 1

    def test_noise_within_overlap_is_not_regression(self):
        """A modest median shift with overlapping distributions stays
        ok — the noise-tolerance property that keeps CI quiet."""
        rng = np.random.default_rng(0)
        base = {"metrics": {"m": {
            "n": 64, "median_ms": 1.0,
            "samples_ms": sorted(rng.normal(1.0, 0.4, 64).clip(0.01))}}}
        cur = {"metrics": {"m": {
            "n": 64, "median_ms": 1.6,
            "samples_ms": sorted(rng.normal(1.6, 0.4, 64).clip(0.01))}}}
        rep = sentinel.compare(base, cur)
        assert rep["metrics"]["m"]["verdict"] == "ok"

    def test_baseline_round_trip_and_validation(self, tmp_path):
        base = profile_metrics()
        base["context"] = {"mode": "test"}
        path = str(tmp_path / "BASELINE_SERVE.json")
        sentinel.save_baseline(path, base)
        loaded = sentinel.load_baseline(path)
        assert loaded["metrics"].keys() == base["metrics"].keys()
        (tmp_path / "bad.json").write_text("{}")
        with pytest.raises(ValueError, match="not a v1"):
            sentinel.load_baseline(str(tmp_path / "bad.json"))

    def test_latency_samples_ride_loadgen_reports(self):
        from geomesa_tpu.serve.loadgen import _report

        rep = _report("closed", 1.0, [0.001 * i for i in range(1, 40)],
                      39, 0, 0, 0, {})
        assert rep.samples_ms and rep.samples_ms == sorted(
            rep.samples_ms)
        doc = sentinel.baseline_from_profile(
            {"phases": {}}, latency_samples_ms=rep.samples_ms)
        assert doc["metrics"]["serve.latency"]["n"] == len(
            rep.samples_ms)
        # the JSON report line stays sample-free
        assert "samples_ms" not in rep.to_json()


class TestCliVerbs:
    def test_prof_and_sentinel_from_files(self, tmp_path, capsys):
        import argparse

        from geomesa_tpu.cli.commands import _prof, _sentinel

        p = ContinuousProfiler()
        p.enable()
        for i in range(20):
            p.fold(synth_trace(i))
        prof_doc = p.snapshot(include_samples=True)
        prof_path = tmp_path / "prof.json"
        prof_path.write_text(json.dumps(prof_doc))
        rc = _prof(argparse.Namespace(input=str(prof_path), url=None,
                                      host="", port=0, json=False))
        assert rc == 0
        assert "continuous profile" in capsys.readouterr().out

        base_path = tmp_path / "base.json"
        sentinel.save_baseline(
            str(base_path), sentinel.baseline_from_profile(prof_doc))
        ns = argparse.Namespace(
            baseline=str(base_path), input=str(prof_path), url=None,
            host="", port=0, threshold=None, min_overlap=None,
            min_n=None, json=True)
        assert _sentinel(ns) == 0  # identical profile: no regression
        out = json.loads(capsys.readouterr().out)
        assert not out["regressed"]
        p3 = ContinuousProfiler()
        p3.enable()
        for i in range(20):
            p3.fold(synth_trace(i, scale=3.0))
        slow_path = tmp_path / "slow.json"
        slow_path.write_text(
            json.dumps(p3.snapshot(include_samples=True)))
        ns.input = str(slow_path)
        assert _sentinel(ns) == 1  # 3x slowdown: nonzero exit


# -- gmtpu top --------------------------------------------------------------


class TestTopFrame:
    def test_mesh_subscriptions_and_slo_lines(self):
        from geomesa_tpu.cli.commands import _top_frame

        doc = {
            "metrics": {
                "histograms": {"serve.latency": {
                    "count": 40, "p50_s": 0.01, "p95_s": 0.02,
                    "p99_s": 0.03}},
                "counters": {
                    "knn.mesh.dispatches": 7.0,
                    "knn.mesh.local_dispatches": 2.0,
                    'serve.affinity.admitted{shards="0"}': 6.0,
                    'serve.affinity.admitted{shards="1,2"}': 4.0,
                },
                "gauges": {"serve.queue.depth": 1.0},
            },
            "serve": {
                "dispatches": 9, "coalesced": 3,
                "mesh": {"shape": [4], "devices": 4},
                "subscriptions": {"subscriptions": 5, "lagged": 1,
                                  "by_status": {"active": 3,
                                                "quarantined": 1}},
                "slo": {"enabled": True,
                        "objectives": {"p99": {"budget_remaining": 0.4}},
                        "breaching": ["p99"], "degrade_boost": 1},
                "quarantine": {},
            },
            "recorder": {},
            "breakers": {},
        }
        frame = _top_frame(doc, None, None)
        assert "mesh" in frame and "(4 dev)" in frame
        assert "7 mesh / 2 local" in frame
        # lane totals on the first poll: shard 0 = 6, shards 1/2 = 4
        assert "0:6" in frame and "1:4" in frame and "2:4" in frame
        assert "3 active, 1 lagged, 1 quarantined (5 total)" in frame
        assert "BREACHING: p99" in frame and "40.0%" in frame
        # second poll: lanes render as rates from counter deltas
        prev = json.loads(json.dumps(doc))
        doc["metrics"]["counters"][
            'serve.affinity.admitted{shards="0"}'] = 16.0
        frame2 = _top_frame(doc, prev, 2.0)
        assert "0:5.0/s" in frame2

    def test_plain_frame_unchanged_without_new_sections(self):
        from geomesa_tpu.cli.commands import _top_frame

        doc = {"metrics": {"histograms": {}, "counters": {},
                           "gauges": {}},
               "serve": {"quarantine": {}}, "recorder": {},
               "breakers": {}}
        frame = _top_frame(doc, None, None)
        assert "mesh" not in frame and "subs" not in frame
        assert "slo" not in frame


# -- scrape-vs-fold races ---------------------------------------------------


MESH_D = 4
ROWS_PER_DAY = 256
DAYS = ("2020-06-01", "2020-06-02", "2020-06-03", "2020-06-04")
MESH_CQL = "BBOX(geom, -170, -80, 170, 80) AND score > -5"


def _mesh_batch():
    """Identical shapes to test_mesh_serve.make_batch (4 day-partitions
    x 256 rows) so the mesh-keyed AOT executables are already warm when
    the suite runs in order."""
    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType

    rng = np.random.default_rng(11)
    n = ROWS_PER_DAY * len(DAYS)
    dtg = np.concatenate([
        int(np.datetime64(day, "ms").astype(np.int64))
        + rng.integers(6 * 3600_000, 18 * 3600_000, ROWS_PER_DAY)
        for day in DAYS
    ])
    sft = SimpleFeatureType.from_spec(
        "meshed", "name:String,score:Double,dtg:Date,*geom:Point")
    return sft, FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": dtg,
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })


class TestScrapeVsFoldRaces:
    """/debug/gap, /metrics (gauge export) and /debug/prof answered
    WHILE mesh+pipelined traffic records traces and folds profiles —
    the scrape-vs-fold interleavings were previously untested. The
    assertions are response integrity (every scrape parses, gap
    coverage sane, no 500s) under genuine concurrency, not timing."""

    def test_concurrent_scrapes_parse_under_mesh_traffic(
            self, tmp_path_factory):
        from geomesa_tpu.plan.datastore import DataStore
        from geomesa_tpu.serve.service import QueryService, ServeConfig
        from geomesa_tpu.telemetry import RECORDER, TRACER
        from geomesa_tpu.telemetry.export import MetricsServer
        from geomesa_tpu.telemetry.prof import PROFILER

        sft, batch = _mesh_batch()
        root = str(tmp_path_factory.mktemp("slo_mesh"))
        store = DataStore(root, use_device_cache=True)
        store.create_schema(sft).write(batch)
        RECORDER.clear()
        PROFILER.reset()
        PROFILER.enable()
        TRACER.enable()
        spec = {"slo": {"fast_window_s": 30.0, "slow_window_s": 60.0},
                "objective": {"p99": {"kind": "latency",
                                      "threshold_ms": 5000.0,
                                      "goal": 0.9}}}
        svc = QueryService(store, ServeConfig(
            mesh=MESH_D, max_wait_ms=10.0, slo=spec), autostart=False)
        server = MetricsServer(port=0, stats_fn=svc.stats,
                               pre_scrape=svc.export_gauges,
                               slo_fn=svc.slo.report)
        port = server.start()
        scrape_errors = []
        gap_docs = []
        stop = threading.Event()

        def scraper():
            import re

            sample = re.compile(
                r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^ ]+$')
            while not stop.is_set():
                try:
                    for path in ("/debug/gap", "/metrics",
                                 "/debug/prof", "/debug/slo"):
                        with urllib.request.urlopen(
                                f"http://127.0.0.1:{port}{path}",
                                timeout=10) as r:
                            body = r.read().decode()
                        if path == "/metrics":
                            bad = [ln for ln in body.splitlines()
                                   if ln and not ln.startswith("#")
                                   and not sample.match(ln)]
                            if bad:
                                scrape_errors.append(
                                    f"unparseable: {bad[:2]}")
                        else:
                            doc = json.loads(body)
                            if path == "/debug/gap":
                                gap_docs.append(doc)
                                if doc.get("coverage", 0) > 1.0:
                                    scrape_errors.append(
                                        f"coverage > 1: {doc}")
                except Exception as e:  # noqa: BLE001 — the assertion
                    scrape_errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=scraper, daemon=True)
                   for _ in range(2)]
        rng = np.random.default_rng(23)
        try:
            svc.start()
            for t in threads:
                t.start()
            futs = []
            for i in range(16):
                qp = rng.uniform(-60, 60, (1, 2))
                futs.append(svc.knn("meshed", MESH_CQL, qp[:, 0],
                                    qp[:, 1], k=5))
                if i % 5 == 4:
                    futs.append(svc.count("meshed", MESH_CQL))
            for f in futs:
                f.result(timeout=300)
            # at least one scrape lands while traffic is in flight;
            # give the scrapers one more full round over a non-empty
            # recorder before stopping them
            time.sleep(0.2)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            server.stop()
            svc.close(drain=True)
            TRACER.disable()
            PROFILER.disable()
        assert not scrape_errors, scrape_errors[:5]
        assert gap_docs, "no /debug/gap scrape completed"
        # the final gap view over the drained recorder is coherent
        final = gap_docs[-1]
        assert final["traces"] >= 1
        assert 0.0 <= final["coverage"] <= 1.0
        # the profiler folded the same traffic the recorder holds
        snap = PROFILER.snapshot()
        assert snap["traces"] >= final["traces"]
        assert "dispatch" in snap["phases"]
        PROFILER.reset()
