"""Provenance dataflow pass tests (gmtpu-lint GT28..GT31).

Per rule: a dirty fixture (exact rule codes + line numbers), a clean
twin for every precision guard (bucketing recognition, interprocedural
marker resolution, registration universes, hot-path scoping), the
anchor-waiver channel, and the chain-origin waiver channel (a
`# gt: waive GTnn` where the shape is BORN suppresses the downstream
dispatch finding, including across files). The pre-fix shapes of every
true positive this pass found on the shipped tree — the len(batch)
ones-weight and bin-dtg extents in plan/runner, the unbucketed
histogram/vocab static args in run_stats, the raw uncertain-query
fallback tile in engine/grid_index — are replayed as faithful excerpts
so a regression that stops a rule matching its real catch fails here,
not in production review.

Also here: the incremental engine's dataflow contract — warm and
partial runs byte-identical to a cold scan with the provenance chains
(SARIF relatedLocations) surviving the cache round trip, warm replay
with zero re-analysis, the ruleset-fingerprint stamp invalidating
caches written by an older rule set, two concurrent lint processes
racing the tmp+rename cache write — plus the single-build discipline
(SPMD and dataflow passes share one `build_project`, one flow
extraction per module) and the `--changed` scope resolver.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from geomesa_tpu.analysis.incremental import (
    DEFAULT_CACHE_FILENAME, _ruleset_sig, lint_paths_incremental)
from geomesa_tpu.analysis.linter import (
    changed_paths, lint_paths, render_json, render_sarif)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DATAFLOW = ["GT28", "GT29", "GT30", "GT31"]
SPMD = ["GT24", "GT25", "GT26", "GT27"]


def write_tree(tmp_path, files):
    """Materialize a miniature repo: pyproject.toml marks the root so
    fixture modules get project-relative paths (geomesa_tpu/...) — the
    hot-path scoping (GT28/GT31) and module-name resolution key on
    them."""
    (tmp_path / "pyproject.toml").write_text(
        "[project]\nname = \"dataflow-fixture\"\n")
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def lint_tree(tmp_path, files, rules=DATAFLOW, **kw):
    write_tree(tmp_path, files)
    return lint_paths([str(tmp_path / "geomesa_tpu")], rules=rules,
                      extra_ref_paths=[], **kw)


def active(findings):
    return [f for f in findings if not f.waived]


def codes_lines(findings):
    return {(f.rule, f.line) for f in active(findings)}


# -- GT28: raw shape reaching a dispatch -------------------------------------


DIRTY_GT28 = """\
    import jax
    import numpy as np


    @jax.jit
    def score(x):
        return x * 2.0


    def handle(payload):
        qx = np.frombuffer(payload)
        return score(qx)
"""


class TestGT28RawShapeDispatch:
    def test_raw_wire_extent_reaches_jit(self, tmp_path):
        fs = lint_tree(tmp_path,
                       {"geomesa_tpu/serve/handler.py": DIRTY_GT28})
        assert codes_lines(fs) == {("GT28", 12)}
        (f,) = active(fs)
        # the provenance chain walks back to the frombuffer origin
        chain = f.extra["chain"]
        assert any(s["line"] == 11 and "frombuffer" in s["note"]
                   for s in chain)

    def test_clean_bucketed_twin(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/handler.py": """\
            import jax
            import numpy as np


            @jax.jit
            def score(x):
                return x * 2.0


            def next_pow2(n):
                p = 1
                while p < n:
                    p *= 2
                return p


            def pad_to(x, n):
                return np.concatenate([x, np.zeros(n - len(x))])


            def handle(payload):
                raw = np.frombuffer(payload)
                qx = pad_to(raw, next_pow2(max(len(raw), 1)))
                return score(qx)
        """})
        assert not active(fs)

    def test_interprocedural_raw_through_helper(self, tmp_path):
        # the shape is born in one module and dispatched in another:
        # the param:qx marker resolves against launch's callers
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/serve/entry.py": """\
                import numpy as np

                from geomesa_tpu.serve.work import launch


                def handle(payload):
                    qx = np.frombuffer(payload)
                    return launch(qx)
            """,
            "geomesa_tpu/serve/work.py": """\
                import jax


                @jax.jit
                def score(x):
                    return x * 2.0


                def launch(qx):
                    return score(qx)
            """,
        })
        assert codes_lines(fs) == {("GT28", 10)}
        (f,) = active(fs)
        assert f.path.endswith("work.py")
        # the cross-file chain names the caller that passed the raw in
        assert any(s["path"].endswith("entry.py")
                   for s in f.extra["chain"])

    def test_path_scope_cold_module_silent(self, tmp_path):
        # one-shot scripts and CLI helpers dispatch raw shapes
        # legitimately: the same code outside serve//plan//subscribe//
        # engine/ does not fire
        fs = lint_tree(tmp_path,
                       {"geomesa_tpu/cli/handler.py": DIRTY_GT28},
                       rules=["GT28"])
        assert not fs

    def test_anchor_waiver(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/handler.py": """\
            import jax
            import numpy as np


            @jax.jit
            def score(x):
                return x * 2.0


            def handle(payload):
                qx = np.frombuffer(payload)
                return score(qx)  # gt: waive GT28
        """})
        assert not active(fs)
        assert [(f.rule, f.waived) for f in fs] == [("GT28", True)]

    def test_origin_chain_waiver(self, tmp_path):
        # waive where the shape is BORN: a directive on the raw origin
        # suppresses the downstream dispatch finding entirely
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/handler.py": """\
            import jax
            import numpy as np


            @jax.jit
            def score(x):
                return x * 2.0


            def handle(payload):
                # request-scoped probe: bounded by the protocol cap
                # gt: waive GT28
                qx = np.frombuffer(payload)
                return score(qx)
        """})
        assert not fs

    def test_lane_param_table_len_sized(self, tmp_path):
        # the vmapped-lane hazard (docs/SERVING.md "Standing
        # queries"): a len(subs)-sized parameter table reaching the
        # [S]-batched lane dispatch recompiles on EVERY membership
        # change — exactly the per-subscription compile the lanes
        # exist to eliminate
        fs = lint_tree(tmp_path, {"geomesa_tpu/subscribe/lanetab.py": """\
            import jax
            import numpy as np


            @jax.jit
            def lane_bbox(params, active, x, y):
                hit = ((x[None, :] >= params[:, 0:1])
                       & (x[None, :] <= params[:, 1:2]))
                return hit & active[:, None]


            def evaluate(subs, x, y):
                params = np.zeros((len(subs), 8), np.float32)
                active = np.ones(len(subs), bool)
                return lane_bbox(params, active, x, y)
        """})
        assert ("GT28", 15) in codes_lines(fs)
        f = next(f for f in active(fs) if f.rule == "GT28")
        assert any("len" in s["note"] for s in f.extra["chain"])

    def test_lane_param_table_clean_bucketed_twin(self, tmp_path):
        # the shipped discipline: pow2 [S]-bucket capacity + an active
        # mask column, so membership churn is a row write and the
        # compiled program only changes when the bucket grows
        fs = lint_tree(tmp_path, {"geomesa_tpu/subscribe/lanetab.py": """\
            import jax
            import numpy as np


            @jax.jit
            def lane_bbox(params, active, x, y):
                hit = ((x[None, :] >= params[:, 0:1])
                       & (x[None, :] <= params[:, 1:2]))
                return hit & active[:, None]


            def next_pow2(n):
                p = 1
                while p < n:
                    p *= 2
                return p


            def evaluate(subs, x, y):
                cap = next_pow2(max(len(subs), 8))
                params = np.zeros((cap, 8), np.float32)
                active = np.zeros(cap, bool)
                active[: len(subs)] = True
                return lane_bbox(params, active, x, y)
        """})
        assert not active(fs)

    def test_origin_chain_waiver_cross_file(self, tmp_path):
        # the origin waiver reaches dispatches in OTHER modules: one
        # directive at the birth site instead of one per consumer
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/serve/entry.py": """\
                import numpy as np

                from geomesa_tpu.serve.work import launch


                def handle(payload):
                    # request-scoped probe: bounded by the protocol cap
                    # gt: waive GT28
                    qx = np.frombuffer(payload)
                    return launch(qx)
            """,
            "geomesa_tpu/serve/work.py": """\
                import jax


                @jax.jit
                def score(x):
                    return x * 2.0


                def launch(qx):
                    return score(qx)
            """,
        })
        assert not fs


# -- GT29: f32 laundered into an exact-f64 consumer --------------------------


DIRTY_GT29 = """\
    import numpy as np


    def refine(q):
        small = np.asarray(q, np.float32)
        exact = small.astype(np.float64)
        return exact
"""


class TestGT29F32Launder:
    def test_astype_launder(self, tmp_path):
        fs = lint_tree(tmp_path,
                       {"geomesa_tpu/serve/refine.py": DIRTY_GT29})
        assert codes_lines(fs) == {("GT29", 6)}
        (f,) = active(fs)
        # the chain walks back to the rounding cast
        assert any(s["line"] == 5 and "f32 cast" in s["note"]
                   for s in f.extra["chain"])

    def test_clean_f64_from_source(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/refine.py": """\
            import numpy as np


            def refine(q):
                canon = np.asarray(q, np.float64)
                out = canon.astype(np.float64)
                return out
        """})
        assert not fs

    def test_interprocedural_f64_param(self, tmp_path):
        # an f32-cast value fed to a callee parameter named *_f64:
        # the consumer's name states the exactness contract
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/serve/dists.py": """\
                def canonical(dists_f64):
                    return dists_f64.sum()
            """,
            "geomesa_tpu/serve/refine.py": """\
                import numpy as np

                from geomesa_tpu.serve.dists import canonical


                def go(q):
                    small = np.asarray(q, np.float32)
                    return canonical(small)
            """,
        })
        assert codes_lines(fs) == {("GT29", 8)}

    def test_clean_f64_param_fed_f64(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/serve/dists.py": """\
                def canonical(dists_f64):
                    return dists_f64.sum()
            """,
            "geomesa_tpu/serve/refine.py": """\
                import numpy as np

                from geomesa_tpu.serve.dists import canonical


                def go(q):
                    exact = np.asarray(q, np.float64)
                    return canonical(exact)
            """,
        })
        assert not fs

    def test_anchor_waiver(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/refine.py": """\
            import numpy as np


            def refine(q):
                small = np.asarray(q, np.float32)
                exact = small.astype(np.float64)  # gt: waive GT29
                return exact
        """})
        assert not active(fs)
        assert [(f.rule, f.waived) for f in fs] == [("GT29", True)]

    def test_origin_chain_waiver(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/refine.py": """\
            import numpy as np


            def refine(q):
                # probe only feeds a tolerance check
                # gt: waive GT29
                small = np.asarray(q, np.float32)
                exact = small.astype(np.float64)
                return exact
        """})
        assert not fs

    def test_sarif_carries_provenance_chain(self, tmp_path):
        fs = lint_tree(tmp_path,
                       {"geomesa_tpu/serve/refine.py": DIRTY_GT29})
        doc = json.loads(render_sarif(fs))
        (result,) = [r for r in doc["runs"][0]["results"]
                     if r["ruleId"] == "GT29"]
        related = result["relatedLocations"]
        assert related, "GT29 must render its chain as relatedLocations"
        assert any("f32 cast" in loc["message"]["text"]
                   for loc in related)
        assert all(
            loc["physicalLocation"]["artifactLocation"]["uri"].endswith(
                "refine.py") for loc in related)


# -- GT30: unmatchable registry key ------------------------------------------


class TestGT30UnmatchableKey:
    def test_unregistered_serve_variant(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/lookup.py": """\
            def fetch(registry, q):
                handle = registry.compile("knn.score@serve", q)
                return handle.call(q)
        """})
        assert codes_lines(fs) == {("GT30", 2)}
        (f,) = active(fs)
        assert "serve_variant" in f.message

    def test_ring_depth_mismatch(self, tmp_path):
        # registered at depth 2, looked up at depth 4: the manifest
        # can never warm the caller's key
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/serve/reg.py": """\
                def install(registry, fn):
                    registry.register("knn.score", fn)
                    registry.ring_variant("knn.score", 2, fn=fn)
            """,
            "geomesa_tpu/serve/lookup.py": """\
                def fetch(registry, q):
                    h = registry.compile("knn.score@ring4", q)
                    return h.call(q)
            """,
        })
        assert codes_lines(fs) == {("GT30", 2)}
        (f,) = active(fs)
        assert f.path.endswith("lookup.py")
        assert "depth 4" in f.message

    def test_clean_registered_in_scan_set(self, tmp_path):
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/serve/reg.py": """\
                def install(registry, fn):
                    registry.register("knn.score", fn)
                    registry.serve_variant("knn.score", fn=fn)
                    registry.ring_variant("knn.score", 2, fn=fn)
            """,
            "geomesa_tpu/serve/lookup.py": """\
                def fetch(registry, q):
                    a = registry.compile("knn.score@serve", q)
                    b = registry.compile("knn.score@ring2", q)
                    return a.call(q), b.call(q)
            """,
        })
        assert not fs

    def test_registration_in_reference_universe(self, tmp_path):
        # the GT05 discipline: a subset scan must still see
        # registration sites OUTSIDE the scan set
        files = {
            "geomesa_tpu/serve/lookup.py": """\
                def fetch(registry, q):
                    h = registry.compile("knn.score@serve", q)
                    return h.call(q)
            """,
            "tools/install.py": """\
                def install(registry, fn):
                    registry.serve_variant("knn.score", fn=fn)
            """,
        }
        write_tree(tmp_path, files)
        scan = [str(tmp_path / "geomesa_tpu")]
        blind = lint_paths(scan, rules=["GT30"], extra_ref_paths=[])
        assert codes_lines(blind) == {("GT30", 2)}
        seeing = lint_paths(scan, rules=["GT30"],
                            extra_ref_paths=[str(tmp_path / "tools")])
        assert not seeing

    def test_dynamic_registration_wildcards(self, tmp_path):
        # computed registration names wildcard that variant space;
        # install_defaults wildcards the base key space
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/serve/reg.py": """\
                def install(registry, fn, name):
                    registry.install_defaults()
                    registry.serve_variant(name, fn=fn)
            """,
            "geomesa_tpu/serve/lookup.py": """\
                def fetch(registry, q):
                    a = registry.compile("anything.goes@serve", q)
                    b = registry.compile("some.base.key", q)
                    return a.call(q), b.call(q)
            """,
        })
        assert not fs

    def test_base_key_registered_nowhere(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/lookup.py": """\
            def fetch(registry, q):
                h = registry.compile("ghost.key", q)
                return h.call(q)
        """})
        assert codes_lines(fs) == {("GT30", 2)}
        (f,) = active(fs)
        assert "registered nowhere" in f.message

    def test_anchor_waiver(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/lookup.py": """\
            def fetch(registry, q):
                h = registry.compile("ghost.key@serve", q)  # gt: waive GT30
                return h.call(q)
        """})
        assert not active(fs)
        assert [(f.rule, f.waived) for f in fs] == [("GT30", True)]


# -- GT31: device->host->device bounce ---------------------------------------


DIRTY_GT31 = """\
    import jax


    @jax.jit
    def score(x):
        return x * 2.0


    def pump(out):
        host = jax.device_get(out)
        back = jax.device_put(host)
        return score(host), back
"""


class TestGT31HostBounce:
    def test_bounce_through_put_and_dispatch(self, tmp_path):
        fs = lint_tree(tmp_path,
                       {"geomesa_tpu/serve/pump.py": DIRTY_GT31})
        assert codes_lines(fs) == {("GT31", 11), ("GT31", 12)}
        for f in active(fs):
            assert any("device_get" in s["note"]
                       for s in f.extra["chain"])

    def test_clean_host_only_consumer(self, tmp_path):
        # fetching to host for a host-side consumer is the normal exit
        # path; only RE-ENTERING the device is the bounce
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/pump.py": """\
            import jax


            def finish(out):
                host = jax.device_get(out)
                return host.tolist()
        """})
        assert not fs

    def test_path_scope_cold_module_silent(self, tmp_path):
        fs = lint_tree(tmp_path,
                       {"geomesa_tpu/store/pump.py": DIRTY_GT31},
                       rules=["GT31"])
        assert not fs

    def test_origin_chain_waiver(self, tmp_path):
        fs = lint_tree(tmp_path, {"geomesa_tpu/serve/pump.py": """\
            import jax


            def pump(out):
                # snapshot seam: the host copy is the checkpoint format
                # gt: waive GT31
                host = jax.device_get(out)
                return jax.device_put(host)
        """})
        assert not fs


# -- pre-fix replays of the true positives this pass found -------------------


class TestPreFixReplays:
    """Faithful excerpts of the shipped-tree true positives, pre-fix:
    a regression that stops GT28 matching its real catches fails here."""

    def test_density_ones_weight_len_batch(self, tmp_path):
        # plan/runner.py density_device_grid, pre-fix: the ones-weight
        # sized by len(batch) instead of the staged coordinate array
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/engine/density.py": """\
                import jax


                @jax.jit
                def density_grid(x, y, w):
                    return (x * w).sum() + y.sum()
            """,
            "geomesa_tpu/plan/runner.py": """\
                import jax.numpy as jnp

                from geomesa_tpu.engine.density import density_grid


                def density_device_grid(dev, batch, g):
                    w = jnp.ones(len(batch), jnp.float32)
                    return density_grid(dev[g + "__x"], dev[g + "__y"], w)
            """,
        })
        assert codes_lines(fs) == {("GT28", 8)}

    def test_density_ones_weight_fixed_shape_clean(self, tmp_path):
        # the shipped fix: tie the weight extent to the staged device
        # array (whatever capacity bucket the batch was padded to)
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/engine/density.py": """\
                import jax


                @jax.jit
                def density_grid(x, y, w):
                    return (x * w).sum() + y.sum()
            """,
            "geomesa_tpu/plan/runner.py": """\
                import jax.numpy as jnp

                from geomesa_tpu.engine.density import density_grid


                def density_device_grid(dev, batch, g):
                    w = jnp.ones_like(dev[g + "__x"], dtype=jnp.float32)
                    return density_grid(dev[g + "__x"], dev[g + "__y"], w)
            """,
        })
        assert not fs

    def test_bin_dtg_zeros_len_batch(self, tmp_path):
        # plan/runner.py bin path, pre-fix: the dtg placeholder sized
        # by len(batch) forked the bin_pack executable per batch length
        fs = lint_tree(tmp_path, {
            "geomesa_tpu/engine/bin.py": """\
                import jax


                @jax.jit
                def bin_pack(track, dtg, y, x):
                    return track.sum() + dtg.sum() + y.sum() + x.sum()
            """,
            "geomesa_tpu/plan/runner.py": """\
                import jax.numpy as jnp

                from geomesa_tpu.engine.bin import bin_pack


                def run_bin(dev, batch, g, d=None):
                    dtg = dev[d] if d else jnp.zeros(len(batch), jnp.int64)
                    return bin_pack(jnp.asarray(batch), dtg, dev[g + "__y"],
                                    dev[g + "__x"])
            """,
        })
        assert codes_lines(fs) == {("GT28", 8)}

    def test_stats_unbucketed_static_args(self, tmp_path):
        # plan/runner.py run_stats, pre-fix: len(ub) time-bin count and
        # the per-column vocab size fed as static args — every distinct
        # value compiled a fresh histogram/value-count executable
        fs = lint_tree(tmp_path, {"geomesa_tpu/plan/stats.py": """\
            import jax


            @jax.jit
            def z3_histogram(z, tb, mask, nbins):
                return z.sum() + tb.sum() + mask.sum() + nbins


            @jax.jit
            def masked_value_counts(codes, mask, nvals):
                return codes.sum() + mask.sum() + nvals


            def run_stats(dev, ub, vocab, jmask):
                grids = z3_histogram(dev["z"], dev["tb"], jmask, len(ub))
                counts = masked_value_counts(dev["codes"], jmask,
                                             max(len(vocab), 1))
                return grids, counts
        """})
        assert codes_lines(fs) == {("GT28", 15), ("GT28", 16)}

    def test_stats_bucketed_static_args_clean(self, tmp_path):
        # the shipped fix: pow2-bucket both static args (the result
        # slice drops the padded tail)
        fs = lint_tree(tmp_path, {"geomesa_tpu/plan/stats.py": """\
            import jax


            @jax.jit
            def z3_histogram(z, tb, mask, nbins):
                return z.sum() + tb.sum() + mask.sum() + nbins


            @jax.jit
            def masked_value_counts(codes, mask, nvals):
                return codes.sum() + mask.sum() + nvals


            def next_pow2(n):
                p = 1
                while p < n:
                    p *= 2
                return p


            def run_stats(dev, ub, vocab, jmask):
                grids = z3_histogram(dev["z"], dev["tb"], jmask,
                                     next_pow2(max(len(ub), 1)))
                counts = masked_value_counts(dev["codes"], jmask,
                                             next_pow2(max(len(vocab), 1)))
                return grids, counts
        """})
        assert not fs

    def test_grid_index_fallback_tile(self, tmp_path):
        # engine/grid_index.py knn_indexed, pre-fix: the uncertain-query
        # fallback gathered a raw row set and sized query_tile from it
        fs = lint_tree(tmp_path, {"geomesa_tpu/engine/gridx.py": """\
            import jax
            import jax.numpy as jnp
            import numpy as np


            @jax.jit
            def knn(qx, qy, k=8, query_tile=64):
                return qx.sum() + qy.sum()


            def knn_indexed(qx, qy, flags, k):
                rows = np.nonzero(flags)[0]
                return knn(
                    jnp.take(qx, jnp.asarray(rows)),
                    jnp.take(qy, jnp.asarray(rows)),
                    k=k,
                    query_tile=max(1, min(1024, len(rows))),
                )
        """})
        assert codes_lines(fs) == {("GT28", 13)}

    def test_grid_index_fallback_bucketed_clean(self, tmp_path):
        # the shipped fix: pow2-pad the fallback row set (padded slots
        # re-run rows[0]; the slice drops them before the scatter-back)
        fs = lint_tree(tmp_path, {"geomesa_tpu/engine/gridx.py": """\
            import jax
            import jax.numpy as jnp
            import numpy as np


            @jax.jit
            def knn(qx, qy, k=8, query_tile=64):
                return qx.sum() + qy.sum()


            def next_pow2(n):
                p = 1
                while p < n:
                    p *= 2
                return p


            def knn_indexed(qx, qy, flags, k):
                rows = np.nonzero(flags)[0]
                nb = next_pow2(max(len(rows), 1))
                rpad = np.concatenate(
                    [rows, np.full(nb - len(rows), rows[0], rows.dtype)])
                return knn(
                    jnp.take(qx, jnp.asarray(rpad)),
                    jnp.take(qy, jnp.asarray(rpad)),
                    k=k,
                    query_tile=max(1, min(1024, nb)),
                )
        """})
        assert not fs


# -- the shipped tree itself -------------------------------------------------


class TestSelfLint:
    def test_shipped_tree_clean_under_dataflow(self):
        fs = lint_paths([os.path.join(REPO_ROOT, "geomesa_tpu")],
                        rules=DATAFLOW)
        assert not active(fs), render_json(active(fs))
        # the deliberate data-axis shapes (calibration plans, per-layer
        # tiling) and accumulation-only upcasts are documented waivers
        assert any(f.waived for f in fs)


# -- incremental engine with the dataflow pass -------------------------------


class TestIncrementalDataflow:
    FILES = {
        "geomesa_tpu/serve/handler.py": DIRTY_GT28,
        "geomesa_tpu/serve/refine.py": DIRTY_GT29,
        "geomesa_tpu/cql/util.py": """\
            def ident(x):
                return x
        """,
    }

    def test_warm_and_partial_byte_identical(self, tmp_path):
        write_tree(tmp_path, self.FILES)
        scan = [str(tmp_path / "geomesa_tpu")]
        cold = lint_paths(scan, rules=DATAFLOW)
        assert codes_lines(cold) == {("GT28", 12), ("GT29", 6)}
        # provenance chains ride Finding.extra, never the JSON render
        assert '"extra"' not in render_json(cold)
        inc1 = lint_paths_incremental(scan, rules=DATAFLOW)
        assert (tmp_path / DEFAULT_CACHE_FILENAME).exists()
        inc2 = lint_paths_incremental(scan, rules=DATAFLOW)  # warm
        assert render_json(cold) == render_json(inc1) == render_json(inc2)

        # edit: a new f32-launder must surface through the cache, and
        # the replayed findings must still match a cold scan
        mod = tmp_path / "geomesa_tpu" / "cql" / "util.py"
        mod.write_text(textwrap.dedent("""\
            import numpy as np


            def launder(q):
                small = np.asarray(q, np.float32)
                return small.astype(np.float64)
        """))
        inc3 = lint_paths_incremental(scan, rules=DATAFLOW)
        cold3 = lint_paths(scan, rules=DATAFLOW)
        assert render_json(cold3) == render_json(inc3)
        assert any(f.path.endswith("util.py") for f in active(inc3))
        assert codes_lines(inc1) <= codes_lines(inc3)

    def test_warm_replay_does_not_reparse(self, tmp_path, monkeypatch):
        write_tree(tmp_path, self.FILES)
        scan = [str(tmp_path / "geomesa_tpu")]
        lint_paths_incremental(scan, rules=DATAFLOW)
        import geomesa_tpu.analysis.incremental as inc_mod

        def boom(*a, **k):
            raise AssertionError("warm replay must not build a project")

        monkeypatch.setattr(inc_mod, "build_project", boom)
        warm = lint_paths_incremental(scan, rules=DATAFLOW)
        assert codes_lines(warm) == {("GT28", 12), ("GT29", 6)}

    def test_chain_survives_cache_roundtrip(self, tmp_path):
        # a warm replay's SARIF must carry the same relatedLocations as
        # a cold scan: Finding.extra rides the cache
        write_tree(tmp_path, self.FILES)
        scan = [str(tmp_path / "geomesa_tpu")]
        cold = lint_paths(scan, rules=DATAFLOW)
        lint_paths_incremental(scan, rules=DATAFLOW)
        warm = lint_paths_incremental(scan, rules=DATAFLOW)
        assert render_sarif(warm) == render_sarif(cold)
        assert "relatedLocations" in render_sarif(warm)

    def test_ruleset_stamp_invalidates_stale_cache(self, tmp_path):
        # satellite: a cache written by an older rule set must fall
        # through to a cold scan, never warm-replay stale findings
        write_tree(tmp_path, self.FILES)
        scan = [str(tmp_path / "geomesa_tpu")]
        cold = lint_paths(scan, rules=DATAFLOW)
        lint_paths_incremental(scan, rules=DATAFLOW)
        cache = tmp_path / DEFAULT_CACHE_FILENAME
        doc = json.loads(cache.read_text())
        assert doc["ruleset"] == _ruleset_sig()
        # doctor the stamp AND the payload: a buggy warm replay would
        # now return zero findings
        doc["ruleset"] = "written-by-an-older-rule-set"
        doc["findings"] = []
        cache.write_text(json.dumps(doc))
        inc = lint_paths_incremental(scan, rules=DATAFLOW)
        assert render_json(inc) == render_json(cold)
        # and the rewrite restamped the cache: next run replays warm
        doc2 = json.loads(cache.read_text())
        assert doc2["ruleset"] == _ruleset_sig()
        assert doc2["findings"]

    def test_concurrent_processes_race_cache_write(self, tmp_path):
        # satellite: two lint processes racing the tmp+rename cache
        # write — both report byte-identical to a cold scan and the
        # surviving cache is uncorrupted (pid-suffixed tmp names)
        write_tree(tmp_path, self.FILES)
        scan_dir = str(tmp_path / "geomesa_tpu")
        cold = render_json(lint_paths([scan_dir], rules=DATAFLOW))
        prog = textwrap.dedent("""\
            import sys

            from geomesa_tpu.analysis.incremental import \\
                lint_paths_incremental
            from geomesa_tpu.analysis.linter import render_json

            fs = lint_paths_incremental(
                [sys.argv[1]],
                rules=["GT28", "GT29", "GT30", "GT31"])
            sys.stdout.write(render_json(fs))
        """)
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get(
            "PYTHONPATH", "")
        procs = [subprocess.Popen(
            [sys.executable, "-c", prog, scan_dir],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO_ROOT, env=env) for _ in range(2)]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err
            outs.append(out)
        assert outs[0] == cold
        assert outs[1] == cold
        doc = json.loads((tmp_path / DEFAULT_CACHE_FILENAME).read_text())
        assert doc["findings"]
        # no orphaned tmp files leaked by the race
        leftovers = [n for n in os.listdir(tmp_path)
                     if n.startswith(DEFAULT_CACHE_FILENAME + ".tmp")]
        assert not leftovers


# -- single-build discipline -------------------------------------------------


class TestSingleBuild:
    def test_spmd_and_dataflow_share_one_project_pass(
            self, tmp_path, monkeypatch):
        # one build_project per lint run and one flow extraction per
        # module, however many dataflow rules consume the index
        write_tree(tmp_path, {
            "geomesa_tpu/serve/handler.py": DIRTY_GT28,
            "geomesa_tpu/parallel/ops.py": """\
                import jax
                from jax import lax


                def merge(x):
                    return lax.psum(x, "shard")
            """,
        })
        import geomesa_tpu.analysis.dataflow as df_mod
        import geomesa_tpu.analysis.linter as lint_mod

        builds = []
        real_build = lint_mod.build_project

        def counting_build(*a, **k):
            builds.append(1)
            return real_build(*a, **k)

        extracted = []
        real_extract = df_mod.extract_flow

        def counting_extract(mod):
            extracted.append(mod.relpath)
            return real_extract(mod)

        monkeypatch.setattr(lint_mod, "build_project", counting_build)
        monkeypatch.setattr(df_mod, "extract_flow", counting_extract)
        fs = lint_paths([str(tmp_path / "geomesa_tpu")],
                        rules=sorted(set(SPMD) | set(DATAFLOW)),
                        extra_ref_paths=[])
        assert {f.rule for f in active(fs)} == {"GT24", "GT28"}
        assert len(builds) == 1
        assert sorted(extracted) == [
            "geomesa_tpu/parallel/ops.py",
            "geomesa_tpu/serve/handler.py",
        ]


# -- `gmtpu lint --changed` scope resolution ---------------------------------


class TestChangedPaths:
    def _git(self, cwd, *args):
        r = subprocess.run(
            ["git", "-c", "user.email=t@fixture", "-c", "user.name=t",
             *args],
            cwd=cwd, capture_output=True, text=True, timeout=60)
        assert r.returncode == 0, r.stderr
        return r.stdout

    def test_changed_scope_and_untracked(self, tmp_path):
        write_tree(tmp_path, {
            "geomesa_tpu/serve/handler.py": DIRTY_GT28,
            "geomesa_tpu/cql/util.py": """\
                def ident(x):
                    return x
            """,
        })
        (tmp_path / "tool.py").write_text("X = 1\n")
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", "-A")
        self._git(tmp_path, "commit", "-q", "-m", "seed")
        # modify one tracked file in scope, add one untracked file in
        # scope, modify one OUT of scope
        (tmp_path / "geomesa_tpu" / "cql" / "util.py").write_text(
            "def ident(x):\n    return x  # touched\n")
        new = tmp_path / "geomesa_tpu" / "serve" / "fresh.py"
        new.write_text("Y = 2\n")
        (tmp_path / "tool.py").write_text("X = 3\n")
        got = changed_paths([str(tmp_path / "geomesa_tpu")], "HEAD")
        rels = sorted(os.path.relpath(p, tmp_path).replace(os.sep, "/")
                      for p in got)
        assert rels == ["geomesa_tpu/cql/util.py",
                        "geomesa_tpu/serve/fresh.py"]

    def test_unborn_head_falls_back_to_empty_tree(self, tmp_path):
        # the pre-commit hook's default ref is HEAD, which does not
        # exist before the initial commit — changed_paths degrades to
        # the empty tree so the very first commit lints its staged
        # files instead of dying on `git diff HEAD`
        write_tree(tmp_path, {
            "geomesa_tpu/serve/handler.py": DIRTY_GT28,
        })
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", "-A")
        got = changed_paths([str(tmp_path / "geomesa_tpu")], "HEAD")
        rels = sorted(os.path.relpath(p, tmp_path).replace(os.sep, "/")
                      for p in got)
        assert rels == ["geomesa_tpu/serve/handler.py"]
        # an explicitly bad ref still errors
        with pytest.raises(RuntimeError, match="no-such-ref"):
            changed_paths([str(tmp_path / "geomesa_tpu")], "no-such-ref")

    def test_narrow_scan_keeps_registration_universe(self, tmp_path):
        # the guarantee a changed-only run DOES keep: the registration
        # universe (GT30, like GT05/GT13) spans the whole repo, so a
        # one-file scan of the lookup module still sees the
        # registration site in the unchanged module and stays clean —
        # narrowing never invents a false unmatchable-key finding
        write_tree(tmp_path, {
            "geomesa_tpu/serve/reg.py": """\
                def install(registry, fn):
                    registry.serve_variant("knn.score", fn=fn)
            """,
            "geomesa_tpu/serve/lookup.py": """\
                def fetch(registry, q):
                    h = registry.compile("knn.score@serve", q)
                    return h.call(q)
            """,
        })
        narrow = lint_paths(
            [str(tmp_path / "geomesa_tpu" / "serve" / "lookup.py")],
            rules=["GT30"])
        assert not narrow
