"""Test environment: force JAX onto 8 virtual CPU devices.

Multi-chip sharding logic is tested without TPU hardware, per the reference's
"mini-cluster in one JVM" testing idea (SURVEY.md §4): all roles in-process.
Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
