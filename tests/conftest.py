"""Test environment: force JAX onto 8 virtual CPU devices.

Multi-chip sharding logic is tested without TPU hardware, per the reference's
"mini-cluster in one JVM" testing idea (SURVEY.md §4): all roles in-process.

Environment note: this image registers an experimental 'axon' TPU PJRT plugin
via sitecustomize (PYTHONPATH=/root/.axon_site) and pins JAX_PLATFORMS=axon in
jax.config at register time. Initializing ANY backend then dials the TPU
tunnel and can hang for minutes, so tests must (1) deregister the axon/tpu
factories and (2) reset jax_platforms to cpu — env vars alone are not enough
because register() already overrode the config.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# keep the suite free of persistent-compilation-cache I/O: the planner
# and QueryService enable it by default (compilecache/persist.py), and
# with the serve-grade thresholds every tiny test compile would be
# serialized to ~/.cache — pure overhead against the tier-1 wall-clock
# budget. Tests that exercise the cache itself pass explicit dirs with
# force=True, which overrides this. setdefault: a dev can still opt in.
os.environ.setdefault("GEOMESA_TPU_COMPILE_CACHE_DIR", "off")

import jax
import jax.experimental.pallas  # noqa: F401  (register TPU lowering rules
# while the tpu platform is still a known backend — popping the factories
# first makes pallas_call's registration fail with "unknown platform tpu",
# even in interpret mode)
from jax._src import xla_bridge as _xb

for _name in ("axon", "tpu"):
    _xb._backend_factories.pop(_name, None)
jax.config.update("jax_platforms", "cpu")
