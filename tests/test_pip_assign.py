"""Per-polygon assignment (relation-join) kernel tests.

Oracle: f64 per-polygon crossing parity over ALL edges of every polygon
(nothing shared with the pair build)."""

import numpy as np

from geomesa_tpu.engine.pip_sparse import pip_layer_assign

from test_pip_sparse import make_layer, make_points


def assign_oracle(px, py, x1, y1, x2, y2, pol):
    """[N] containing polygon id (-1 none; -1 also for >1, with count)."""
    n = len(px)
    acc_id = np.full(n, -1, np.int64)
    acc_n = np.zeros(n, np.int64)
    for pid in np.unique(pol):
        m = pol == pid
        a1, b1, a2, b2 = x1[m], y1[m], x2[m], y2[m]
        condx = (b1[None] <= py[:, None]) != (b2[None] <= py[:, None])
        t = (py[:, None] - b1[None]) / np.where(b2 == b1, 1.0, b2 - b1)[None]
        xc = a1[None] + t * (a2 - a1)[None]
        inside = (np.sum(condx & (xc > px[:, None]), 1) % 2) == 1
        acc_id = np.where(inside, pid, acc_id)
        acc_n += inside
    return np.where(acc_n == 1, acc_id, -1), acc_n


class TestPipAssign:
    def test_disjoint_layer_assignment(self):
        rng = np.random.default_rng(2)
        x1, y1, x2, y2, pol = make_layer(rng)
        px, py = make_points(rng, x1, y1, x2, y2, n=20_000, na=200)
        pid, cnt, info = pip_layer_assign(
            px, py, x1, y1, x2, y2, pol, interpret=True)
        exp_id, exp_n = assign_oracle(px, py, x1, y1, x2, y2, pol)
        np.testing.assert_array_equal(pid, exp_id)
        np.testing.assert_array_equal(cnt, exp_n)
        assert (exp_n == 1).sum() > 500  # non-vacuous
        assert info["refined"] > 0       # adversarial points exercised

    def test_multi_tile_polygons(self):
        # >512-edge rings: the per-polygon flush must span several edge
        # tiles of the same polygon within a row
        th = np.linspace(0, 2 * np.pi, 2000, endpoint=False)
        x1a = 30 * np.cos(th); y1a = 20 * np.sin(th)
        x2a = np.roll(x1a, -1); y2a = np.roll(y1a, -1)
        th2 = np.linspace(0, 2 * np.pi, 700, endpoint=False)
        x1b = 45 + 10 * np.cos(th2); y1b = 10 + 15 * np.sin(th2)
        x2b = np.roll(x1b, -1); y2b = np.roll(y1b, -1)
        x1 = np.concatenate([x1a, x1b]); y1 = np.concatenate([y1a, y1b])
        x2 = np.concatenate([x2a, x2b]); y2 = np.concatenate([y2a, y2b])
        pol = np.concatenate([np.zeros(2000, np.int64),
                              np.ones(700, np.int64)])
        rng = np.random.default_rng(3)
        px, py = make_points(rng, x1, y1, x2, y2, n=8192, na=64)
        pid, cnt, info = pip_layer_assign(
            px, py, x1, y1, x2, y2, pol, interpret=True)
        exp_id, exp_n = assign_oracle(px, py, x1, y1, x2, y2, pol)
        np.testing.assert_array_equal(pid, exp_id)
        assert (exp_id == 0).sum() > 100 and (exp_id == 1).sum() > 50

    def test_overlapping_polygons_flagged_by_count(self):
        # two overlapping squares: points in the intersection must report
        # count==2 and poly_id -1 (assignment undefined), non-overlap
        # regions assign normally
        sq = np.array([[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]], float)
        sq2 = sq + 5.0
        x1 = np.concatenate([sq[:-1, 0], sq2[:-1, 0]])
        y1 = np.concatenate([sq[:-1, 1], sq2[:-1, 1]])
        x2 = np.concatenate([sq[1:, 0], sq2[1:, 0]])
        y2 = np.concatenate([sq[1:, 1], sq2[1:, 1]])
        pol = np.array([0] * 4 + [1] * 4)
        rng = np.random.default_rng(5)
        px = np.sort(rng.uniform(-2, 18, 4000))
        py = rng.uniform(-2, 18, 4000)
        pid, cnt, info = pip_layer_assign(
            px, py, x1, y1, x2, y2, pol, interpret=True)
        exp_id, exp_n = assign_oracle(px, py, x1, y1, x2, y2, pol)
        np.testing.assert_array_equal(cnt, exp_n)
        np.testing.assert_array_equal(pid, exp_id)
        assert (exp_n == 2).sum() > 100

    def test_empty_region(self):
        rng = np.random.default_rng(7)
        x1, y1, x2, y2, pol = make_layer(rng, npoly=4, grid=2)
        px = np.sort(rng.uniform(100, 170, 2000))
        py = rng.uniform(-80, 80, 2000)
        pid, cnt, info = pip_layer_assign(
            px, py, x1, y1, x2, y2, pol, interpret=True)
        assert (pid == -1).all() and (cnt == 0).all()

    def test_prep_reuse(self):
        from geomesa_tpu.engine.pip_sparse import prepare_layer

        rng = np.random.default_rng(9)
        x1, y1, x2, y2, pol = make_layer(rng, npoly=6, grid=3)
        px, py = make_points(rng, x1, y1, x2, y2, n=6000, na=0)
        prep = prepare_layer(px, py, x1, y1, x2, y2, pol)
        a1_, c1_, _ = pip_layer_assign(
            px, py, x1, y1, x2, y2, pol, interpret=True, prep=prep)
        a2_, c2_, _ = pip_layer_assign(
            px, py, x1, y1, x2, y2, pol, interpret=True)
        np.testing.assert_array_equal(a1_, a2_)
        np.testing.assert_array_equal(c1_, c2_)


def test_sparse_large_polygon_ids():
    # public contract (round-4 review): polygon ids may be sparse and
    # huge (e.g. feature ids) — no O(max id) allocation, no i32
    # overflow; outputs carry the ORIGINAL ids
    th = np.linspace(0, 2 * np.pi, 32, endpoint=False)
    def ring(cx, cy, r):
        x1 = cx + r * np.cos(th); y1 = cy + r * np.sin(th)
        return x1, y1, np.roll(x1, -1), np.roll(y1, -1)
    a = ring(-20.0, 0.0, 8.0)
    b = ring(20.0, 0.0, 8.0)
    x1 = np.concatenate([a[0], b[0]]); y1 = np.concatenate([a[1], b[1]])
    x2 = np.concatenate([a[2], b[2]]); y2 = np.concatenate([a[3], b[3]])
    big_a, big_b = 3_000_000_000_017, 9_000_000_000_001
    pol = np.concatenate([np.full(32, big_a, np.int64),
                          np.full(32, big_b, np.int64)])
    rng = np.random.default_rng(13)
    px = np.sort(rng.uniform(-35, 35, 4096)); py = rng.uniform(-12, 12, 4096)
    pid, cnt, info = pip_layer_assign(px, py, x1, y1, x2, y2, pol,
                                      interpret=True)
    exp_id, exp_n = assign_oracle(px, py, x1, y1, x2, y2, pol)
    np.testing.assert_array_equal(pid, exp_id)
    assert set(np.unique(pid)) <= {-1, big_a, big_b}
    assert (pid == big_a).sum() > 50 and (pid == big_b).sum() > 50
