"""The lint gate as a tier-1 test: the shipped package must pass
`gmtpu lint --fail-on warn` (scripts/lint_gate.py), so a PR that
introduces a GT01..GT06 hazard without a waiver fails the suite the
same way it would fail CI."""

import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE = os.path.join(REPO_ROOT, "scripts", "lint_gate.py")


def test_lint_gate_passes_on_shipped_tree():
    # --no-spmd-smoke / --no-dataflow-smoke / --no-chaos-smoke /
    # --no-telemetry-smoke /
    # --no-sentinel-smoke / --no-fleet-smoke / --no-wire-smoke /
    # --no-ring-smoke: those invariants already run in-process in this
    # same tier-1 suite (tests/test_analysis_spmd.py dirty-fixture
    # replays for every SPMD rule; tests/test_analysis_dataflow.py
    # dirty/clean fixtures for every dataflow rule plus the SARIF
    # provenance-chain assertion; tests/test_faults.py chaos
    # regression; tests/test_telemetry.py trace/scrape/gap checks;
    # tests/test_slo_observability.py sentinel record/replay/verdict;
    # tests/test_fleet.py kill-mid-burst failover + subscription
    # re-home across an owner kill (TestRehome); tests/test_wire.py
    # columnar parity + one-encode fan-out; tests/test_ringloop.py ring
    # bit-identity + dispatches_per_window; tests/test_subscribe.py
    # lane-vs-fused floor + parity); repeating them in a cold
    # subprocess would only re-pay jax startup + kernel compiles
    # against the suite's wall-clock budget. All smokes still guard
    # standalone `python scripts/lint_gate.py` CI runs.
    r = subprocess.run([sys.executable, GATE, "--no-spmd-smoke",
                        "--no-dataflow-smoke", "--no-chaos-smoke",
                        "--no-telemetry-smoke", "--no-sentinel-smoke",
                        "--no-fleet-smoke", "--no-rehome-smoke",
                        "--no-approx-smoke",
                        "--no-wire-smoke", "--no-ring-smoke",
                        "--no-lane-smoke"],
                       capture_output=True, text=True, cwd=REPO_ROOT)
    assert r.returncode == 0, (
        f"lint gate failed:\n{r.stdout}\n{r.stderr}")
    assert "0 finding(s)" in r.stdout


def test_lint_gate_json_mode():
    import json

    r = subprocess.run([sys.executable, GATE, "--format", "json"],
                       capture_output=True, text=True, cwd=REPO_ROOT)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    assert doc["active"] == 0
    # the shipped tree documents its deliberate f64 paths via waivers
    assert doc["waived"] >= 1
