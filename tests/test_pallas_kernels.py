"""Pallas kernel parity tests (interpret mode on CPU; same code path the
TPU compiles). Oracle: f64 NumPy with the identical half-open edge rule."""

import numpy as np
import pytest

from geomesa_tpu.core.wkt import parse_wkt
from geomesa_tpu.engine.pip import points_in_polygon, polygon_edges
from geomesa_tpu.engine.pip_pallas import (
    points_in_polygon_np_edges,
    points_in_polygon_pallas,
)


def _random_polygon(rng, nv=12, cx=0.0, cy=0.0, r=10.0):
    """A random star-convex polygon (no self-intersections)."""
    angles = np.sort(rng.uniform(0, 2 * np.pi, nv))
    radii = rng.uniform(0.3 * r, r, nv)
    xs = cx + radii * np.cos(angles)
    ys = cy + radii * np.sin(angles)
    pts = np.stack([xs, ys], 1)
    return np.concatenate([pts, pts[:1]], 0)


def _edges_from_rings(rings):
    x1 = np.concatenate([r[:-1, 0] for r in rings])
    y1 = np.concatenate([r[:-1, 1] for r in rings])
    x2 = np.concatenate([r[1:, 0] for r in rings])
    y2 = np.concatenate([r[1:, 1] for r in rings])
    return x1, y1, x2, y2


@pytest.mark.parametrize("n,nv", [(100, 8), (777, 40), (2048, 3)])
def test_pallas_pip_parity_random(n, nv):
    rng = np.random.default_rng(nv * 1000 + n)
    ring = _random_polygon(rng, nv)
    x1, y1, x2, y2 = _edges_from_rings([ring])
    px = rng.uniform(-15, 15, n)
    py = rng.uniform(-15, 15, n)
    exp = points_in_polygon_np_edges(px, py, x1, y1, x2, y2)
    got = np.asarray(
        points_in_polygon_pallas(
            px.astype(np.float32), py.astype(np.float32),
            x1.astype(np.float32), y1.astype(np.float32),
            x2.astype(np.float32), y2.astype(np.float32),
            interpret=True,
        )
    )
    # f32 tolerance: only points within ~1e-5 deg of an edge may flip
    disagree = np.nonzero(got != exp)[0]
    for i in disagree:
        d = _min_edge_dist(px[i], py[i], x1, y1, x2, y2)
        assert d < 1e-4, f"point {i} disagrees at distance {d} from boundary"
    assert len(disagree) <= max(1, n // 100)


def _min_edge_dist(px, py, x1, y1, x2, y2):
    ex, ey = x2 - x1, y2 - y1
    L2 = ex * ex + ey * ey
    t = np.clip(((px - x1) * ex + (py - y1) * ey) / np.where(L2 == 0, 1, L2), 0, 1)
    qx, qy = x1 + t * ex, y1 + t * ey
    return float(np.min(np.hypot(px - qx, py - qy)))


def test_pallas_pip_holes_multipart():
    g = parse_wkt(
        "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (3 3, 7 3, 7 7, 3 7, 3 3))"
    )
    x1, y1, x2, y2 = polygon_edges(g)
    px = np.array([5.0, 1.0, 5.0, -1.0, 8.0])
    py = np.array([5.0, 1.0, 3.5, 5.0, 8.0])
    exp = np.array([False, True, False, False, True])  # hole center excluded
    got = np.asarray(
        points_in_polygon_pallas(px, py, x1, y1, x2, y2, interpret=True)
    )
    np.testing.assert_array_equal(got, exp)


def test_pallas_pip_large_edge_table_streams():
    """Edge count beyond one tile exercises the accumulation grid axis."""
    rng = np.random.default_rng(0)
    # many small squares: 5 vertices each -> E >> EDGE_TILE
    rings = []
    for i in range(400):
        cx, cy = rng.uniform(-100, 100, 2)
        s = 0.5
        rings.append(
            np.array(
                [[cx - s, cy - s], [cx + s, cy - s], [cx + s, cy + s],
                 [cx - s, cy + s], [cx - s, cy - s]]
            )
        )
    x1, y1, x2, y2 = _edges_from_rings(rings)
    assert len(x1) > 1024  # spans multiple edge tiles
    px = rng.uniform(-100, 100, 300)
    py = rng.uniform(-100, 100, 300)
    exp = points_in_polygon_np_edges(px, py, x1, y1, x2, y2)
    got = np.asarray(
        points_in_polygon_pallas(px, py, x1, y1, x2, y2, interpret=True)
    )
    np.testing.assert_array_equal(got, exp)


def test_pip_dense_and_pallas_agree_exact_f64():
    """At f64 the two implementations are bit-identical on the same rule."""
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    ring = _random_polygon(rng, 20)
    x1, y1, x2, y2 = _edges_from_rings([ring])
    px, py = rng.uniform(-12, 12, 500), rng.uniform(-12, 12, 500)
    dense = np.asarray(
        points_in_polygon(
            jnp.asarray(px), jnp.asarray(py),
            jnp.asarray(x1), jnp.asarray(y1), jnp.asarray(x2), jnp.asarray(y2),
        )
    )
    pallas = np.asarray(
        points_in_polygon_pallas(px, py, x1, y1, x2, y2, interpret=True)
    )
    np.testing.assert_array_equal(dense, pallas)


# -- borderline band + f64 refinement (SURVEY.md:824-827) -------------------


def _near_edge_points(rng, x1, y1, x2, y2, n, offset):
    """Points within `offset` deg of random edge positions (both sides)."""
    e = rng.integers(0, len(x1), n)
    t = rng.uniform(0, 1, n)
    ex, ey = x2[e] - x1[e], y2[e] - y1[e]
    L = np.hypot(ex, ey)
    nx, ny = -ey / L, ex / L  # unit normal
    side = rng.choice([-1.0, 1.0], n)
    d = rng.uniform(0, offset, n)
    px = x1[e] + t * ex + side * d * nx
    py = y1[e] + t * ey + side * d * ny
    return px, py


@pytest.mark.parametrize("offset", [1e-8, 1e-6])
def test_band_flags_near_edge_points(offset):
    """Every point close enough to flip at f32 must be flagged."""
    from geomesa_tpu.engine.pip import points_in_polygon_band

    import jax.numpy as jnp

    rng = np.random.default_rng(11)
    ring = _random_polygon(rng, 24)
    x1, y1, x2, y2 = _edges_from_rings([ring])
    px, py = _near_edge_points(rng, x1, y1, x2, y2, 500, offset)
    flags = np.asarray(
        points_in_polygon_band(
            jnp.asarray(px, jnp.float32), jnp.asarray(py, jnp.float32),
            jnp.asarray(x1), jnp.asarray(y1),
            jnp.asarray(x2), jnp.asarray(y2),
        )
    )
    assert flags.all(), f"{(~flags).sum()} near-edge points unflagged"


def test_band_pallas_matches_lax():
    from geomesa_tpu.engine.pip import points_in_polygon_band
    from geomesa_tpu.engine.pip_pallas import points_in_polygon_band_pallas

    import jax.numpy as jnp

    rng = np.random.default_rng(13)
    ring = _random_polygon(rng, 16)
    x1, y1, x2, y2 = _edges_from_rings([ring])
    px = rng.uniform(-12, 12, 700)
    py = rng.uniform(-12, 12, 700)
    a = np.asarray(points_in_polygon_band(
        jnp.asarray(px, jnp.float32), jnp.asarray(py, jnp.float32),
        jnp.asarray(x1, jnp.float32), jnp.asarray(y1, jnp.float32),
        jnp.asarray(x2, jnp.float32), jnp.asarray(y2, jnp.float32)))
    b = np.asarray(points_in_polygon_band_pallas(
        px, py, x1, y1, x2, y2, interpret=True))
    np.testing.assert_array_equal(a, b)


def test_refined_mask_matches_f64_oracle_adversarial():
    """The full compiled-filter path with refinement: adversarial points
    within 1e-8 deg of edges must match the f64 oracle EXACTLY."""
    import jax.numpy as jnp

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.core.wkt import Geometry
    from geomesa_tpu.cql import compile_filter, parse_cql
    from geomesa_tpu.cql.hosteval import eval_filter_host
    from geomesa_tpu.engine.device import to_device

    rng = np.random.default_rng(17)
    ring = _random_polygon(rng, 24, cx=2.0, cy=45.0, r=3.0)
    x1, y1, x2, y2 = _edges_from_rings([ring])
    px, py = _near_edge_points(rng, x1, y1, x2, y2, 400, 1e-8)
    # plus some clearly in/out points
    px = np.concatenate([px, rng.uniform(-5, 9, 200)])
    py = np.concatenate([py, rng.uniform(38, 52, 200)])

    sft = SimpleFeatureType.from_spec("t", "*geom:Point")
    batch = FeatureBatch.from_pydict(sft, {"geom": np.stack([px, py], 1)})
    wkt_ring = ", ".join(f"{a:.17g} {b:.17g}" for a, b in ring)
    f = parse_cql(f"WITHIN(geom, POLYGON(({wkt_ring})))")
    compiled = compile_filter(f, sft)
    assert compiled.has_band
    dev = to_device(batch)  # default f32 coords: the adversarial regime
    refined = compiled.mask_refined(dev, batch)
    oracle = eval_filter_host(f, batch)
    np.testing.assert_array_equal(refined, oracle)
    # and without refinement the f32 path alone would NOT be exact (guards
    # against the test silently weakening if dtypes change)
    raw = np.asarray(compiled.mask(dev, batch))
    assert (raw != oracle).any()
