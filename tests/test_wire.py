"""Columnar wire (docs/SERVING.md "Columnar wire"): negotiated binary
record-batch framing + the PushMux one-encode fan-out.

The load-bearing suite is round-trip PARITY: columnar-decoded
`execute`/density/topk/push payloads must be bit-identical to the
JSON-lines path for the same queries — the fast path is only a fast
path if nobody can tell the difference after decode. Alongside it:
the hello negotiation + typed pyarrow-absent fallback, the bulk-ingest
path (record-batch buffers in as NumPy views), the one-encode-per-frame
fan-out invariant at 1000 sinks, writer-thread isolation of a dead
mirror, the replica-socket transport, and a CPU throughput floor
(columnar >= 5x JSON rows/s — the acceptance criterion, with ~40x
margin measured).

Wall-clock discipline (tier-1 budget is effectively full): module-
scoped stores reusing test_serve's 600-row shapes (same pow2 kernel
buckets), one 20k-row store for the throughput floor, and in-memory
streams everywhere a socket is not itself under test.
"""

import json
import queue
import threading
import time

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.serve import columnar as colwire
from geomesa_tpu.serve.protocol import serve_connection
from geomesa_tpu.serve.service import QueryService, ServeConfig

DENSITY = {"bbox": [-180, -90, 180, 90], "width": 64, "height": 32}


def make_batch(n=600, seed=3, with_nulls=True):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec(
        "served", "name:String,score:Double,dtg:Date,*geom:Point")
    names = rng.choice(["a", "b", "c"], n).tolist()
    if with_nulls:
        # null strings must decode identically on both paths
        names = [None if i % 97 == 0 else v for i, v in enumerate(names)]
    return sft, FeatureBatch.from_pydict(sft, {
        "name": names,
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    sft, batch = make_batch()
    ds = DataStore(
        str(tmp_path_factory.mktemp("wire")), use_device_cache=True)
    ds.create_schema(sft).write(batch)
    return ds


def drive(store, svc, requests, payloads=None, timeout_s=60.0):
    """Run one in-memory conversation; returns {id: (doc, payload)}
    plus the ordered response list. Query responses resolve on the
    dispatch thread AFTER serve_connection returns (the shared service
    stays open), so this polls the output stream until every request
    id has answered — a torn mid-write frame parse simply retries."""
    mem = colwire.MemoryWire()
    payloads = payloads or {}
    for doc in requests:
        mem.add(doc, payloads.get(doc.get("id")))
    out = bytearray()
    serve_connection(store, svc, mem.lines(),
                     lambda s: out.extend(s.encode()),
                     write_bytes=out.extend, read_bytes=mem.read_exact)
    want = {d["id"] for d in requests if "id" in d}
    deadline = time.monotonic() + timeout_s
    resp = []
    while time.monotonic() < deadline:
        try:
            resp = colwire.parse_stream(bytes(out))
        except ValueError:
            time.sleep(0.005)  # mid-frame write in flight
            continue
        if want <= {d.get("id") for d, _ in resp}:
            break
        time.sleep(0.005)
    by_id = {d.get("id"): (d, p) for d, p in resp if "id" in d}
    assert want <= set(by_id), (want, sorted(by_id))
    return by_id, resp


class TestNegotiation:
    def test_hello_advertises_and_upgrades(self, store):
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            by_id, _ = drive(store, svc, [
                {"id": "h", "op": "hello", "wire": "columnar"}])
            hello = by_id["h"][0]
            assert hello["wire"] == ["json", "columnar"]
            assert hello["wireMode"] == "columnar"
        finally:
            svc.close(drain=True)

    def test_no_binary_sink_downgrades_typed(self, store):
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            mem = colwire.MemoryWire()
            mem.add({"id": "h", "op": "hello", "wire": "columnar"})
            mem.add({"id": "q", "op": "query", "typeName": "served",
                     "cql": "INCLUDE", "maxFeatures": 5,
                     "wire": "columnar"})
            lines_out = []
            # TEXT-ONLY transport: no write_bytes
            serve_connection(store, svc, mem.lines(), lines_out.append)
            deadline = time.monotonic() + 30.0
            while len(lines_out) < 2 and time.monotonic() < deadline:
                time.sleep(0.005)
            docs = [json.loads(s) for s in list(lines_out)]
            hello, q = docs[0], docs[1]
            assert hello["wireMode"] == "json"
            assert hello["wireFallback"] == "no_binary_sink"
            assert q["wireFallback"] == "no_binary_sink"
            assert len(q["features"]) == 5  # JSON fallback still serves
        finally:
            svc.close(drain=True)

    def test_pyarrow_absent_skips_typed_to_json(self, store,
                                                monkeypatch):
        # simulate a pyarrow-less container: capability drops, every
        # columnar opt-in downgrades typed — never a crash
        monkeypatch.setattr(colwire, "_PA", None)
        monkeypatch.setattr(colwire, "_PA_CHECKED", True)
        assert colwire.wire_capabilities() == ["json"]
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            by_id, _ = drive(store, svc, [
                {"id": "h", "op": "hello", "wire": "columnar"},
                {"id": "q", "op": "query", "typeName": "served",
                 "cql": "INCLUDE", "maxFeatures": 5,
                 "wire": "columnar"}])
            assert by_id["h"][0]["wire"] == ["json"]
            assert by_id["h"][0]["wireFallback"] == "pyarrow_unavailable"
            q, payload = by_id["q"]
            assert payload is None
            assert q["wireFallback"] == "pyarrow_unavailable"
            assert len(q["features"]) == 5
        finally:
            svc.close(drain=True)


class TestParity:
    """Columnar decode == JSON path, bit-identical, per payload kind."""

    def test_execute_rows_bit_identical(self, store):
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            by_id, _ = drive(store, svc, [
                {"id": "h", "op": "hello", "wire": "columnar"},
                {"id": "c", "op": "query", "typeName": "served",
                 "cql": "BBOX(geom,-170,-80,170,80) AND score > -5",
                 "maxFeatures": 600},
                {"id": "j", "op": "query", "typeName": "served",
                 "cql": "BBOX(geom,-170,-80,170,80) AND score > -5",
                 "maxFeatures": 600, "wire": "json"}])
            cdoc, payload = by_id["c"]
            jdoc, _ = by_id["j"]
            assert payload is not None and "features" not in cdoc
            rows = colwire.decode_execute_payload(payload)
            # the JSON doc round-trips through json.dumps/loads in
            # drive(), so equality here IS wire-level bit-parity
            assert rows == jdoc["features"]
            assert cdoc["count"] == jdoc["count"] == len(rows)
        finally:
            svc.close(drain=True)

    def test_density_grid_single_buffer(self, store):
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            by_id, _ = drive(store, svc, [
                {"id": "c", "op": "query", "typeName": "served",
                 "cql": "INCLUDE", "density": DENSITY,
                 "wire": "columnar"},
                {"id": "j", "op": "query", "typeName": "served",
                 "cql": "INCLUDE", "density": DENSITY}])
            cdoc, payload = by_id["c"]
            jdoc, _ = by_id["j"]
            assert payload is not None
            grid = colwire.decode_density_payload(cdoc["frame"], payload)
            assert cdoc["shape"] == jdoc["shape"] == list(grid.shape)
            assert cdoc["total"] == jdoc["total"] == float(grid.sum())
            # the columnar response is a SUPERSET: actual cells, one
            # contiguous f64 buffer, no per-cell JSON
            assert grid.dtype == np.float64
            assert len(payload) == grid.size * 8
        finally:
            svc.close(drain=True)

    def test_topk_cells_codec_bit_identical(self):
        cells = [{"row": 3, "col": 7,
                  "bbox": [-180.0, -90.0, -174.375, -87.1875],
                  "count": 41, "bound": 3},
                 {"row": 0, "col": 0,
                  "bbox": [0.0, 0.0, 5.625, 2.8125],
                  "count": 12, "bound": 0}]
        desc, payload = colwire.encode_topk_frame(cells)
        assert colwire.decode_topk_payload(desc, payload) == cells

    def test_push_frame_codec_bit_identical(self):
        # fids are user data off the ingest path: separators and empty
        # strings must round-trip exactly (length-prefixed offsets)
        frame = {"event": "enter", "subscription": "sub-9", "seq": 4,
                 "fids": [f"f{i}" for i in range(57)]
                 + ["has\nnewline", "", "tab\tand spaces"]}
        jbuf = colwire.encode_push(frame, "json")
        assert json.loads(jbuf.decode()) == frame
        cbuf = colwire.encode_push(frame, "columnar")
        (doc, payload), = colwire.parse_stream(cbuf)
        assert colwire.decode_push(doc, payload) == frame
        # scalar frames (density totals, lifecycle) stay JSON lines
        scalar = {"event": "density", "subscription": "s", "seq": 1,
                  "total": 4.0, "cells": 2}
        assert json.loads(colwire.encode_push(
            scalar, "columnar").decode()) == scalar

    def test_knn_binary_staging_parity(self, store):
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            qx = np.array([1.5, -20.25, 33.0])
            qy = np.array([2.5, 10.125, -44.0])
            desc, payload = colwire.knn_sections(qx, qy)
            by_id, _ = drive(store, svc, [
                {"id": "b", "op": "knn", "typeName": "served",
                 "cql": "INCLUDE", "k": 4,
                 "frame": {"sections": desc}},
                {"id": "j", "op": "knn", "typeName": "served",
                 "cql": "INCLUDE", "k": 4, "x": qx.tolist(),
                 "y": qy.tolist()}],
                payloads={"b": payload})
            assert by_id["b"][0]["dists"] == by_id["j"][0]["dists"]
            assert by_id["b"][0]["indices"] == by_id["j"][0]["indices"]
        finally:
            svc.close(drain=True)


class TestIngest:
    def test_wire_ingest_roundtrip(self, store, tmp_path):
        from geomesa_tpu.core.arrow_io import to_ipc_bytes

        sft, batch = make_batch(n=256, seed=9, with_nulls=False)
        ds = DataStore(str(tmp_path / "ingest"), use_device_cache=True)
        ds.create_schema(sft)
        svc = QueryService(ds, ServeConfig(max_wait_ms=0.0))
        try:
            payload = to_ipc_bytes(batch)
            by_id, _ = drive(ds, svc, [
                {"id": "w", "op": "ingest", "typeName": "served",
                 "frame": {"kind": "ingest"}},
                {"id": "n", "op": "count", "typeName": "served",
                 "cql": "INCLUDE"}],
                payloads={"w": payload})
            assert by_id["w"][0] == {"id": "w", "ok": True,
                                     "rows": 256, "batches": 1}
            assert by_id["n"][0]["count"] == 256
        finally:
            svc.close(drain=True)
        # written-through-the-wire rows answer queries identically to
        # the direct write path
        got = ds.get_feature_source("served").get_features("INCLUDE")
        assert sorted(np.asarray(got.features.columns["score"])) \
            == sorted(np.asarray(batch.columns["score"]))

    def test_cli_arrow_ingest_creates_schema_from_metadata(self,
                                                           tmp_path):
        # fresh catalog, no create-schema: the IPC stream's embedded
        # geomesa.sft.spec metadata seeds the schema (typed refusal
        # when absent — never a raw FileNotFoundError traceback)
        from types import SimpleNamespace

        from geomesa_tpu.cli import commands
        from geomesa_tpu.core.arrow_io import write_ipc

        sft, batch = make_batch(n=64, seed=2, with_nulls=False)
        path = str(tmp_path / "d.arrow")
        write_ipc(path, [batch])
        args = SimpleNamespace(
            catalog=str(tmp_path / "cat"), feature_name="served",
            converter=None, arrow=False, files=[path], workers=1,
            no_resume=False)
        assert commands._ingest(args) == 0
        ds = DataStore(str(tmp_path / "cat"))
        assert ds.get_feature_source("served").get_count() == 64

    def test_write_batch_accepts_record_batch_and_ipc(self, tmp_path):
        from geomesa_tpu.core.arrow_io import to_arrow, to_ipc_bytes

        sft, batch = make_batch(n=128, seed=5, with_nulls=False)
        ds = DataStore(str(tmp_path / "wb"), use_device_cache=False)
        ds.create_schema(sft)
        rows, nb = ds.write_batch("served", to_arrow(batch))
        assert (rows, nb) == (128, 1)
        rows, nb = ds.write_batch("served", to_ipc_bytes(batch))
        assert (rows, nb) == (128, 1)
        assert ds.get_feature_source("served").get_count() == 256


class TestPushMux:
    def test_one_encode_per_frame_at_1000_sinks(self):
        mux = colwire.PushMux()
        seen = [0] * 1000
        sinks = []
        for i in range(1000):
            def make(i=i):
                def w(buf):
                    seen[i] += 1
                return w
            sinks.append(mux.register(make(), mode="json",
                                      threaded=False))
        frames = 7
        for k in range(frames):
            n = mux.publish({"event": "enter", "subscription": "s",
                             "seq": k + 1,
                             "fids": [f"f{j}" for j in range(64)]},
                            sinks)
            assert n == 1000
        st = mux.stats()
        # THE acceptance invariant: 1000 subscribers, one encode/frame
        assert st["encodes"] == frames
        assert st["frames"] == frames
        assert st["fanout"] == frames * 1000
        assert set(seen) == {frames}
        mux.close()

    def test_mixed_modes_encode_once_per_mode(self):
        mux = colwire.PushMux()
        bufs = {"json": [], "columnar": []}
        sinks = [mux.register(bufs["json"].append, mode="json",
                              threaded=False),
                 mux.register(bufs["columnar"].append, mode="columnar",
                              threaded=False)]
        frame = {"event": "exit", "subscription": "s", "seq": 1,
                 "fids": ["a", "b"]}
        mux.publish(frame, sinks)
        assert mux.stats()["encodes"] == 2  # one per MODE, not per sink
        assert json.loads(bufs["json"][0].decode()) == frame
        (doc, payload), = colwire.parse_stream(bufs["columnar"][0])
        assert colwire.decode_push(doc, payload) == frame
        mux.close()

    def test_threaded_writer_isolation_and_reap(self):
        mux = colwire.PushMux()
        good = []
        dead_calls = []

        def bad_write(buf):
            dead_calls.append(1)
            raise OSError("peer gone")

        ids = [mux.register(good.append, threaded=True),
               mux.register(bad_write, threaded=True)]
        mux.publish({"event": "enter", "subscription": "s", "seq": 1,
                     "fids": ["x"]}, ids)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if good and mux.stats()["dead"] >= 1:
                break
            time.sleep(0.01)
        st = mux.stats()
        assert len(good) == 1 and dead_calls  # healthy sink delivered
        assert st["dead"] == 1
        # the dead sink is reaped on the next publish; the healthy one
        # keeps receiving
        mux.publish({"event": "enter", "subscription": "s", "seq": 2,
                     "fids": ["y"]}, ids)
        deadline = time.monotonic() + 5.0
        while len(good) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(good) == 2
        assert mux.stats()["sinks"] == 1
        mux.close()

    def test_attach_modes_get_distinct_mirror_sinks(self, store):
        # a second attach asking for a DIFFERENT encoding must not be
        # silently served by the first mode's sink — the response's
        # wireMode states the encoding actually delivered
        from geomesa_tpu.serve.protocol import _WireState

        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            out = bytearray()
            w = _WireState(svc, lambda s: out.extend(s.encode()),
                           out.extend, threading.Lock())
            a = w.ensure_mirror("json")
            b = w.ensure_mirror("columnar")
            assert a != b
            assert w.ensure_mirror("json") == a  # idempotent per mode
            w.close()
            assert svc.wire_mux().stats()["sinks"] == 0
        finally:
            svc.close(drain=True)

    def test_bounded_queue_drops_counted(self):
        mux = colwire.PushMux(queue_limit=2)
        blocked = threading.Event()
        release = threading.Event()

        def slow_write(buf):
            blocked.set()
            release.wait(10.0)

        sid = mux.register(slow_write, threaded=True)
        for k in range(8):
            mux.publish({"event": "enter", "subscription": "s",
                         "seq": k + 1, "fids": ["a"]}, [sid])
        assert blocked.wait(5.0)
        st = mux.stats()
        assert st["dropped"] >= 1  # bounded: excess dropped, counted
        release.set()
        mux.close()


class TestSubscribeFanout:
    """Push frames through the wire: owner connection + an attached
    mirror connection, one encode, decoded parity vs the dict frames
    the manager flushed."""

    def _kafka_store(self):
        from geomesa_tpu.kafka.store import KafkaDataStore

        sft = SimpleFeatureType.from_spec(
            "live", "name:String,score:Double,dtg:Date,*geom:Point")
        store = KafkaDataStore()
        store.create_schema(sft)
        return store, sft

    def _rows(self, sft, seed, fids):
        rng = np.random.default_rng(seed)
        n = len(fids)
        return FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b"], n).tolist(),
            "score": rng.uniform(-5, 5, n),
            "dtg": rng.integers(1_590_000_000_000,
                                1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-60, 60, n),
                              rng.uniform(-30, 30, n)], 1),
        }, fids=list(fids))

    def test_owner_and_mirror_one_encode(self):
        store, sft = self._kafka_store()
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        fids = [f"f{i}" for i in range(12)]
        a_out = bytearray()
        a_lines: "queue.Queue" = queue.Queue()

        def a_iter():
            while True:
                item = a_lines.get()
                if item is None:
                    return
                yield item

        t = threading.Thread(target=serve_connection, args=(
            store, svc, a_iter(), lambda s: a_out.extend(s.encode())),
            kwargs={"write_bytes": a_out.extend}, daemon=True)
        t.start()
        try:
            a_lines.put(json.dumps(
                {"id": "h", "op": "hello", "wire": "columnar"}))
            a_lines.put(json.dumps(
                {"id": "s1", "op": "subscribe", "typeName": "live",
                 "cql": "BBOX(geom,-60,-30,60,30)"}))
            deadline = time.monotonic() + 10.0
            while b'"s1"' not in bytes(a_out):
                assert time.monotonic() < deadline
                time.sleep(0.01)
            ack = next(d for d, _ in
                       colwire.parse_stream(bytes(a_out))
                       if d.get("id") == "s1")
            sub_id = ack["subscription"]
            # mirror connection attaches to A's subscription
            b_out = bytearray()
            mem = colwire.MemoryWire()
            mem.add({"id": "h", "op": "hello"})
            mem.add({"id": "at", "op": "attach", "subscription": sub_id,
                     "wire": "columnar"})
            serve_connection(store, svc, mem.lines(),
                             lambda s: b_out.extend(s.encode()),
                             write_bytes=b_out.extend,
                             read_bytes=mem.read_exact)
            # NOTE: connection B returned (its lines ended) but its
            # mirror sink lives until wire.close() ran — which it did.
            # Re-attach a raw mirror sink to model a LIVE connection.
            c_out = bytearray()
            sid = svc.wire_mux().register(c_out.extend,
                                          mode="columnar",
                                          threaded=True)
            svc.wire_mux().attach(sid, sub_id)
            enc0 = svc.wire_mux().stats()["encodes"]
            store.write("live", self._rows(sft, 1, fids))
            a_lines.put(json.dumps({"id": "p1", "op": "poll"}))
            deadline = time.monotonic() + 10.0
            while b"enter" not in bytes(a_out) \
                    or b"enter" not in bytes(c_out):
                assert time.monotonic() < deadline, (
                    bytes(a_out), bytes(c_out))
                time.sleep(0.01)
            by_b = {d.get("id"): d for d, _ in
                    colwire.parse_stream(bytes(b_out))}
            assert by_b["at"]["ok"] and by_b["at"]["sinks"] >= 1
            a_frames = [colwire.decode_push(d, p) for d, p in
                        colwire.parse_stream(bytes(a_out))
                        if d.get("event")]
            c_frames = [colwire.decode_push(d, p) for d, p in
                        colwire.parse_stream(bytes(c_out))
                        if d.get("event")]
            a_enter = [f for f in a_frames if f["event"] == "enter"]
            c_enter = [f for f in c_frames if f["event"] == "enter"]
            assert a_enter and a_enter == c_enter  # decoded parity
            assert sorted(a_enter[0]["fids"]) == sorted(fids)
            # owner (columnar) + mirror (columnar): ONE encode per
            # frame covers both; stats count one per distinct mode
            encodes = svc.wire_mux().stats()["encodes"] - enc0
            frames_routed = len([f for f in a_frames
                                 if f.get("subscription") == sub_id])
            assert encodes <= frames_routed + 1  # never per-sink
        finally:
            a_lines.put(None)
            t.join(timeout=10.0)
            svc.close(drain=True)


class TestThroughputFloor:
    def test_columnar_5x_json_at_20k_rows(self, tmp_path):
        from geomesa_tpu.serve.loadgen import run_wire

        sft, batch = make_batch(n=20_000, seed=7, with_nulls=False)
        ds = DataStore(str(tmp_path / "tp"), use_device_cache=True)
        ds.create_schema(sft).write(batch)
        rep = run_wire(ds, "served", rows=20_000, iters_json=2,
                       iters_columnar=4, push_sinks=32, push_frames=10)
        assert rep.wire_parity_ok
        # acceptance floor (>=5x); measured ~40-200x on CPU CI
        assert rep.wire_speedup >= 5.0, rep.wire_speedup
        assert rep.push_encodes == rep.push_frames
        assert rep.wire_rows == 20_000


class TestReplicaSocketTransport:
    """The real socket path: a ReplicaServer + JsonLineConn must carry
    frames intact in both directions (and the frame-aware docs()
    attaches payloads)."""

    def test_columnar_over_socket(self, tmp_path):
        from geomesa_tpu.fleet.replica import ReplicaServer
        from geomesa_tpu.fleet.wire import connect_json

        # own store: the ingest leg writes rows, which must not
        # perturb the module fixture other classes count against
        sft, batch = make_batch()
        store = DataStore(str(tmp_path / "sock"),
                          use_device_cache=True)
        store.create_schema(sft).write(batch)
        server = ReplicaServer(store, ServeConfig(max_wait_ms=0.0),
                               replica_id="rw")
        port = server.start()
        assert server.wait_state("ready", timeout=120.0) == "ready"
        conn = connect_json("127.0.0.1", port)
        try:
            hello = conn.request(
                {"id": "h", "op": "hello", "wire": "columnar"})
            assert hello["wireMode"] == "columnar"
            got = conn.request(
                {"id": "q", "op": "query", "typeName": "served",
                 "cql": "INCLUDE", "maxFeatures": 100})
            payload = got.pop("_payload")
            ref = conn.request(
                {"id": "r", "op": "query", "typeName": "served",
                 "cql": "INCLUDE", "maxFeatures": 100, "wire": "json"})
            assert colwire.decode_execute_payload(payload) \
                == ref["features"]
            # inbound binary over the socket: bulk ingest is refused
            # typed on a durable store only when the type is unknown —
            # here it lands
            from geomesa_tpu.core.arrow_io import to_ipc_bytes

            _, extra = make_batch(n=64, seed=21, with_nulls=False)
            conn.send_frame({"id": "w", "op": "ingest",
                             "typeName": "served",
                             "frame": {"kind": "ingest"}},
                            to_ipc_bytes(extra))
            stop = threading.Event()
            timer = threading.Timer(30.0, stop.set)
            timer.start()
            try:
                for doc in conn.docs(stop):
                    if doc.get("id") == "w":
                        assert doc["ok"] and doc["rows"] == 64
                        break
            finally:
                timer.cancel()
        finally:
            conn.close()
            server.stop()
