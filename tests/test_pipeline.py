"""Pipelined dispatch (serve/pipeline.py + planner.knn_launch).

The load-bearing assertions, per the acceptance contract:

- **overlap**: window N+1's transfer/launch happen BEFORE window N's
  deferred sync completes (fake planner with gated syncs — no real
  clocks, no sleeps on the assert path), and the depth bound holds
  (window N+2 must NOT launch while N is unsynced at depth 2);
- **identity**: pipelined results are bit-identical to the serial path
  for the same coalesced window shape, and fused counts equal
  planner.count for banded and band-free filters;
- **gap report**: pipelined runs report windows_in_flight_max >= 2 with
  transfer time overlapping other windows, coverage <= 1.0, and the
  invariants survive a Perfetto export round-trip (the CPU-CI stand-in
  for the TPU sustained-throughput claim).
"""

import threading
import warnings
from types import SimpleNamespace

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.query import Query
from geomesa_tpu.serve import QueryService, ServeConfig
from geomesa_tpu.telemetry.gap import gap_report

CQL = "BBOX(geom, -170, -80, 170, 80) AND score > -5"
CQL_PLAIN = "BBOX(geom, -120, -60, 120, 60)"


def make_batch(n=600, seed=3):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec(
        "served", "name:String,score:Double,dtg:Date,*geom:Point")
    return sft, FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    sft, batch = make_batch()
    ds = DataStore(
        str(tmp_path_factory.mktemp("pipeline")), use_device_cache=True)
    ds.create_schema(sft).write(batch)
    return ds


# -- fake-planner overlap harness ------------------------------------------


class FakeLaunch:
    """A KnnLaunch stand-in whose sync blocks on a per-window gate —
    the test decides exactly when each window's device work 'finishes',
    so the overlap assertions are deterministic and clock-free."""

    fused_ok = False
    mask_count = None
    deadline = None

    def __init__(self, seq, q, k, events, gate, trace_sync=False):
        self.seq = seq
        self.q = q
        self.k = k
        self.events = events
        self.gate = gate
        self.trace_sync = trace_sync

    def sync(self):
        self.events.append(("sync_start", self.seq))
        assert self.gate.wait(timeout=30), "test gate never opened"
        if self.trace_sync:
            from geomesa_tpu.telemetry.trace import TRACER

            with TRACER.span("device.sync"):
                pass
        self.events.append(("sync_done", self.seq))
        return (np.full((self.q, self.k), float(self.seq)),
                np.zeros((self.q, self.k), np.int32), None)


class FakePlanner:
    """Records launch order; per-window latency is injected through the
    FakeLaunch gates (per-stage latency without wall-clock sleeps)."""

    def __init__(self, events, gates, trace_sync=False):
        self.events = events
        self.gates = gates
        self.trace_sync = trace_sync
        self.seq = 0

    def knn_launch(self, query, qx, qy, k=10, impl="sparse",
                   timeout_ms=None, staged=None, want_mask_count=False,
                   donate=False):
        self.seq += 1
        assert staged is not None, "pipeline must stage before launch"
        self.events.append(("launch", self.seq))
        return FakeLaunch(self.seq, len(qx), k, self.events,
                          self.gates[self.seq - 1], self.trace_sync)


def fake_service(events, gates, **cfg):
    planner = FakePlanner(events, gates,
                          trace_sync=cfg.pop("trace_sync", False))
    source = SimpleNamespace(planner=planner)
    store = SimpleNamespace(
        get_feature_source=lambda name: source, audit=None)
    cfg.setdefault("max_wait_ms", 0.0)
    cfg.setdefault("max_batch", 1)
    svc = QueryService(store, ServeConfig(**cfg), autostart=False)
    return svc


class TestPipelineOverlap:
    def test_next_window_launches_before_previous_sync(self):
        """Window 2's transfer+launch proceed while window 1's device
        work is still in flight; window 3 (depth 2) must wait."""
        events: list = []
        gates = [threading.Event() for _ in range(3)]
        svc = fake_service(events, gates, pipeline_depth=2)
        futs = [svc.knn("t", f"BBOX(geom, 0, 0, 1, {i + 1})",
                        np.array([0.0]), np.array([0.0]), k=5)
                for i in range(3)]
        svc.start()

        def wait_for(ev, timeout=10.0):
            import time as _t

            deadline = _t.monotonic() + timeout
            while ev not in events:
                assert _t.monotonic() < deadline, (ev, events)
                _t.sleep(0.002)

        # window 2 launches while window 1 is mid-sync (gate closed)
        wait_for(("launch", 2))
        assert ("sync_done", 1) not in events
        # depth bound: window 3 must NOT have launched yet
        assert ("launch", 3) not in events
        gates[0].set()
        wait_for(("launch", 3))
        gates[1].set()
        gates[2].set()
        for f in futs:
            f.result(timeout=30)
        svc.close(drain=True)
        # transfer precedes launch (staged asserted inside the fake),
        # and launch(2) strictly precedes sync_done(1) in the log
        assert events.index(("launch", 2)) < events.index(
            ("sync_done", 1))
        p = svc.stats()["pipeline"]
        assert p["max_inflight"] >= 2
        assert p["windows"] == 3
        assert p["inflight"] == 0

    def test_results_split_per_window(self):
        events: list = []
        gates = [threading.Event() for _ in range(2)]
        for g in gates:
            g.set()  # no injected latency: plain pass-through
        svc = fake_service(events, gates)
        f1 = svc.knn("t", "BBOX(geom, 0, 0, 1, 1)",
                     np.array([0.0]), np.array([0.0]), k=5)
        f2 = svc.knn("t", "BBOX(geom, 0, 0, 1, 2)",
                     np.array([0.0]), np.array([0.0]), k=5)
        svc.start()
        d1, _, _ = f1.result(timeout=30)
        d2, _, _ = f2.result(timeout=30)
        svc.close(drain=True)
        # each window's rows came from ITS OWN launch (seq-valued)
        assert float(d1[0, 0]) == 1.0
        assert float(d2[0, 0]) == 2.0

    def test_traced_pipeline_gap_invariants(self):
        """The CPU-CI structural invariant: a traced pipelined run's
        gap report shows >=2 windows in flight with transfer overlap,
        coverage <= 1.0 — and survives the Perfetto round-trip."""
        from geomesa_tpu.telemetry import RECORDER, TRACER
        from geomesa_tpu.telemetry.export import from_perfetto, to_perfetto

        events: list = []
        gates = [threading.Event() for _ in range(3)]
        RECORDER.clear()
        TRACER.enable()
        try:
            svc = fake_service(events, gates, pipeline_depth=2,
                               trace_sync=True)
            futs = [svc.knn("t", f"BBOX(geom, 0, 0, 1, {i + 1})",
                            np.array([0.0]), np.array([0.0]), k=5)
                    for i in range(3)]
            svc.start()
            # hold window 1 open until window 2 is launched, so the two
            # window intervals (gather -> sync end) genuinely overlap
            import time as _t

            deadline = _t.monotonic() + 10
            while ("launch", 2) not in events:
                assert _t.monotonic() < deadline, events
                _t.sleep(0.002)
            for g in gates:
                g.set()
            for f in futs:
                f.result(timeout=30)
            svc.close(drain=True)
        finally:
            TRACER.disable()
        traces = RECORDER.traces()
        assert len(traces) >= 3
        for docs in (traces, from_perfetto(to_perfetto(traces))):
            rep = gap_report(docs)
            assert rep["coverage"] <= 1.0
            assert rep["dispatch_gap"]["windows"] >= 3
            p = rep["pipeline"]
            assert p["windows_in_flight_max"] >= 2, rep
            assert p["transfer_overlap_ms"] > 0.0, rep
            assert p["multi_window_ms"] > 0.0, rep


# -- identity against the serial path --------------------------------------


def _run(ds, qpts, config, counts=3):
    svc = QueryService(ds, config, autostart=False)
    futs = [svc.knn("served", CQL, qpts[i:i + 1, 0], qpts[i:i + 1, 1],
                    k=5) for i in range(len(qpts))]
    cfuts = [svc.count("served", CQL) for _ in range(counts)]
    svc.start()
    res = [f.result(timeout=120) for f in futs]
    cnts = [f.result(timeout=120) for f in cfuts]
    svc.close(drain=True)
    return res, cnts, svc.stats()


class TestPipelineIdentity:
    def test_bit_identical_to_serial_and_counts_fused(self, store):
        """Acceptance: the pipelined path produces bit-identical results
        to the serial path for the same coalesced window, and fused
        counts match the serial (dedup'd) planner count while saving a
        whole dispatch."""
        rng = np.random.default_rng(42)
        qpts = rng.uniform(-60, 60, (8, 2))
        res_p, cnt_p, st_p = _run(
            store, qpts, ServeConfig(max_wait_ms=50.0))
        res_s, cnt_s, st_s = _run(
            store, qpts, ServeConfig(max_wait_ms=50.0, pipeline=False))
        for i, ((d, ix, _), (sd, six, _)) in enumerate(zip(res_p, res_s)):
            np.testing.assert_array_equal(d, sd, err_msg=f"knn {i}")
            np.testing.assert_array_equal(ix, six, err_msg=f"knn {i}")
        assert cnt_p == cnt_s
        # the counts rode the kNN window: one dispatch total vs two
        assert st_p["pipeline"]["fused_counts"] == 3
        assert st_p["dispatches"] < st_s["dispatches"]
        assert st_p["pipelined_windows"] >= 1

    def test_fused_count_matches_planner_banded_and_plain(self, store):
        """The fused mask reduction equals planner.count exactly — for
        an f32-band-refined filter (score comparison / bbox band) and a
        plain one; the kNN mask carries the same f64-exact corrections
        the count path applies."""
        src = store.get_feature_source("served")
        rng = np.random.default_rng(7)
        qpts = rng.uniform(-60, 60, (4, 2))
        for cql in (CQL, CQL_PLAIN):
            launch = src.planner.knn_launch(
                Query("served", cql), qpts[:, 0], qpts[:, 1], k=5,
                want_mask_count=True)
            launch.sync()
            assert launch.fused_ok
            assert launch.mask_count == src.planner.count(
                Query("served", cql))

    def test_serial_launch_sync_composition(self, store):
        """planner.knn == planner.knn_launch(...).sync() bit-for-bit
        (the serial path IS the composition)."""
        src = store.get_feature_source("served")
        rng = np.random.default_rng(9)
        qx, qy = rng.uniform(-60, 60, 8), rng.uniform(-60, 60, 8)
        d1, i1, _ = src.planner.knn(Query("served", CQL), qx, qy, k=5)
        d2, i2, _ = src.planner.knn_launch(
            Query("served", CQL), qx, qy, k=5).sync()
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(i1, i2)

    def test_sustained_loadgen_reports_pipeline_depth(self, store):
        from geomesa_tpu.serve import knn_request_factory, run_sustained

        svc = QueryService(store, ServeConfig(max_wait_ms=1.0))
        try:
            rep = run_sustained(
                svc, knn_request_factory("served", CQL, k=5),
                duration_s=30.0, max_outstanding=8,
                points_per_query=600, requests=12)
        finally:
            svc.close(drain=True)
        assert rep.mode == "sustained"
        assert rep.ok == 12 and rep.errors == 0
        assert rep.pts_per_s > 0
        assert rep.pipelined_windows >= 1
        assert rep.windows_in_flight_max >= 1
        assert rep.to_json()["pts_per_s"] == rep.pts_per_s


# -- gap report on synthetic pipelined spans --------------------------------


def _span(name, sid, parent, t0, t1):
    return {"name": name, "id": sid, "parent": parent,
            "t0_ns": t0, "t1_ns": t1, "thread": 1}


class TestGapPipelineMath:
    def test_overlapping_windows_dedup_and_clamp(self):
        """Two overlapping windows: exec is the interval UNION (not the
        sum), per-stage intervals clamp to their window, coverage <=
        1.0, and transfer overlapping the other window is reported."""
        ms = 1_000_000
        root = _span("query", 1, None, 0, 100 * ms)
        spans = [
            # window A [10, 60]: kernel [12, 20], sync [40, 60]
            _span("dispatch", 10, 1, 10 * ms, 60 * ms),
            _span("kernel.dispatch", 11, 10, 12 * ms, 20 * ms),
            _span("device.sync", 12, 10, 40 * ms, 60 * ms),
            # window B [40, 90]: transfer [42, 50] overlaps window A
            _span("dispatch", 20, 1, 40 * ms, 90 * ms),
            _span("device.transfer", 21, 20, 42 * ms, 50 * ms),
            _span("device.sync", 22, 20, 70 * ms, 90 * ms),
            # child extending past its window: clamps, never inflates
            _span("prepare", 23, 20, 35 * ms, 45 * ms),
        ]
        rep = gap_report([{"trace_id": "p-1", "root": root,
                           "spans": spans}])
        g = rep["dispatch_gap"]
        assert g["windows"] == 2
        # union of [10,60] and [40,90] = 80ms, not 50+50=100
        assert g["exec_ms"] == pytest.approx(80.0)
        assert rep["coverage"] <= 1.0
        p = rep["pipeline"]
        assert p["windows_in_flight_max"] == 2
        assert p["multi_window_ms"] == pytest.approx(20.0)
        # window B's transfer [42, 50] lies inside window A's [10, 60]
        assert p["transfer_overlap_ms"] == pytest.approx(8.0)
        # device time: union across stages AND windows — window B's
        # transfer [42, 50] hides entirely behind window A's sync
        # [40, 60], so that wall time counts ONCE (the pre-fix sum
        # reported 56 and could exceed exec on deeper pipelines)
        assert g["device_ms"] == pytest.approx(8 + 20 + 20)

    def test_serial_run_unchanged(self):
        """Non-overlapping windows: union == sum, no pipeline section
        noise — the pre-pipelining report shape is preserved."""
        ms = 1_000_000
        root = _span("query", 1, None, 0, 100 * ms)
        spans = [
            _span("dispatch", 10, 1, 10 * ms, 40 * ms),
            _span("kernel.dispatch", 11, 10, 12 * ms, 35 * ms),
            _span("dispatch", 20, 1, 50 * ms, 80 * ms),
            _span("kernel.dispatch", 21, 20, 52 * ms, 75 * ms),
        ]
        rep = gap_report([{"trace_id": "p-1", "root": root,
                           "spans": spans}])
        g = rep["dispatch_gap"]
        assert g["exec_ms"] == pytest.approx(60.0)
        assert g["device_ms"] == pytest.approx(46.0)
        assert rep["pipeline"]["windows_in_flight_max"] == 1
        assert rep["pipeline"]["transfer_overlap_ms"] == 0.0


# -- staging + donation tier ------------------------------------------------


class TestStagerAndDonation:
    def test_stager_rotation_and_value_identity(self):
        import jax.numpy as jnp

        from geomesa_tpu.engine.device import QueryStager

        stager = QueryStager(depth=2)
        rng = np.random.default_rng(5)
        qx = rng.uniform(-60, 60, 8)
        qy = rng.uniform(-60, 60, 8)
        pairs = [stager.stage(("t", 5, "sparse", 8), qx, qy)
                 for _ in range(3)]
        # value identity with the serial conversion
        serial = jnp.asarray(np.asarray(qx), jnp.float32)
        for dx, _dy in pairs:
            np.testing.assert_array_equal(np.asarray(dx),
                                          np.asarray(serial))
        st = stager.stats()
        assert st == {"keys": 1, "staged": 3}
        # slots bounded at depth per key (the double buffer)
        slot = stager._slots[("t", 5, "sparse", 8)]
        assert len(slot) - 1 == 2
        # ... and the key table itself is bounded (LRU): a long-lived
        # multi-tenant service must not pin stale pairs per key forever
        for i in range(QueryStager.MAX_KEYS + 5):
            stager.stage(("t2", i, "sparse", 8), qx[:1], qy[:1])
        assert stager.stats()["keys"] <= QueryStager.MAX_KEYS
        assert ("t", 5, "sparse", 8) not in stager._slots  # evicted
        with pytest.raises(ValueError):
            QueryStager(depth=1)

    def test_registry_serve_variant(self):
        """The donation tier: a @serve-keyed AOT variant compiles and
        runs (donation itself is a no-op on CPU — JAX warns and
        ignores — which is exactly why the pipeline gates on backend),
        is idempotent, and never aliases the base registration."""
        import jax.numpy as jnp

        from geomesa_tpu.compilecache.registry import ExecutableRegistry

        reg = ExecutableRegistry()

        def addmul(a, b, scale):
            return a * scale + b

        reg.register("t.addmul", addmul, static_argnames=("scale",))
        vname = reg.serve_variant("t.addmul", donate_argnums=(0,))
        assert vname == "t.addmul@serve"
        assert reg.serve_variant("t.addmul", donate_argnums=(0,)) == vname
        assert vname in reg.names() and "t.addmul" in reg.names()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            h = reg.compile(vname, jnp.ones(8), jnp.ones(8), scale=2.0)
            out = h.call(jnp.ones(8), jnp.ones(8))
        np.testing.assert_allclose(np.asarray(out), 3.0)
        with pytest.raises(KeyError):
            reg.serve_variant("t.missing", donate_argnums=(0,))
