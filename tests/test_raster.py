"""Extended-geometry density rasterization vs independent NumPy oracles.

Oracles use deliberately different algorithms from the kernels:
- lines: Amanatides-Woo cell walking per segment (vs the kernel's sorted
  crossing-parameter formulation)
- polygons: per-feature even-odd crossing-number test of cell centers
  (vs the kernel's winding scatter + reversed row cumsum)
"""

import numpy as np
import pytest

import jax.numpy as jnp

from geomesa_tpu.core.columnar import FeatureBatch, GeometryColumn
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import Geometry, parse_wkt
from geomesa_tpu.engine.device import to_device
from geomesa_tpu.engine.raster import (
    density_grid_geometry,
    line_density,
    polygon_density,
)


# ---------------------------------------------------------------- oracles


def _clip_liang_barsky(x1, y1, x2, y2, bbox):
    xmin, ymin, xmax, ymax = bbox
    ddx, ddy = x2 - x1, y2 - y1
    t0, t1 = 0.0, 1.0
    for p, q in (
        (-ddx, x1 - xmin),
        (ddx, xmax - x1),
        (-ddy, y1 - ymin),
        (ddy, ymax - y1),
    ):
        if p == 0:
            if q < 0:
                return None
        elif p < 0:
            t0 = max(t0, q / p)
        else:
            t1 = min(t1, q / p)
    if t0 > t1:
        return None
    return t0, t1


def line_oracle(features, weights, bbox, width, height):
    """Amanatides-Woo traversal, f64. `features` = list of list of
    (M, 2) paths (one entry per feature; each path is a polyline)."""
    xmin, ymin, xmax, ymax = bbox
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    grid = np.zeros((height, width))
    for paths, w in zip(features, weights):
        total = sum(
            float(np.sum(np.hypot(np.diff(p[:, 0]), np.diff(p[:, 1]))))
            for p in paths
        )
        if total == 0:
            continue
        for p in paths:
            for (x1, y1), (x2, y2) in zip(p[:-1], p[1:]):
                seg_len = float(np.hypot(x2 - x1, y2 - y1))
                if seg_len == 0:
                    continue
                clip = _clip_liang_barsky(x1, y1, x2, y2, bbox)
                if clip is None:
                    continue
                t0, t1 = clip
                ddx, ddy = x2 - x1, y2 - y1
                t = t0
                # current cell from a nudged start point
                eps = 1e-12
                while t < t1 - eps:
                    xm = x1 + (t + eps) * ddx
                    ym = y1 + (t + eps) * ddy
                    c = int(np.floor((xm - xmin) / dx))
                    r = int(np.floor((ym - ymin) / dy))
                    # next crossing out of this cell
                    tnx = np.inf
                    if ddx > 0:
                        tnx = ((c + 1) * dx + xmin - x1) / ddx
                    elif ddx < 0:
                        tnx = (c * dx + xmin - x1) / ddx
                    tny = np.inf
                    if ddy > 0:
                        tny = ((r + 1) * dy + ymin - y1) / ddy
                    elif ddy < 0:
                        tny = (r * dy + ymin - y1) / ddy
                    tn = min(tnx, tny, t1)
                    if tn <= t + eps:
                        tn = t + eps * 10
                    if 0 <= c < width and 0 <= r < height:
                        grid[r, c] += w * (tn - t) * seg_len / total
                    t = tn
    return grid


def polygon_oracle(features, weights, bbox, width, height):
    """Even-odd cell-center coverage, f64. `features` = list of list of
    rings per feature (holes included, any orientation)."""
    xmin, ymin, xmax, ymax = bbox
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    cx = xmin + (np.arange(width) + 0.5) * dx
    cy = ymin + (np.arange(height) + 0.5) * dy
    grid = np.zeros((height, width))
    for rings, w in zip(features, weights):
        if not rings:
            continue
        allv = np.concatenate(rings)
        gxmin, gymin = allv.min(0)
        gxmax, gymax = allv.max(0)
        c0 = max(0, int(np.floor((gxmin - xmin) / dx)))
        c1 = min(width, int(np.ceil((gxmax - xmin) / dx)) + 1)
        r0 = max(0, int(np.floor((gymin - ymin) / dy)))
        r1 = min(height, int(np.ceil((gymax - ymin) / dy)) + 1)
        if c1 <= c0 or r1 <= r0:
            continue
        px = cx[c0:c1][None, :, None]  # [1, C, 1]
        py = cy[r0:r1][:, None, None]  # [R, 1, 1]
        count = np.zeros((r1 - r0, c1 - c0), dtype=np.int64)
        for ring in rings:
            if len(ring) < 3:
                continue
            closed = (
                ring
                if np.array_equal(ring[0], ring[-1])
                else np.concatenate([ring, ring[:1]])
            )
            x1 = closed[:-1, 0][None, None, :]
            y1 = closed[:-1, 1][None, None, :]
            x2 = closed[1:, 0][None, None, :]
            y2 = closed[1:, 1][None, None, :]
            cond = (y1 <= py) != (y2 <= py)
            tt = (py - y1) / np.where(y2 == y1, 1.0, y2 - y1)
            xc = x1 + tt * (x2 - x1)
            count += np.sum(cond & (xc > px), axis=2)
        grid[r0:r1, c0:c1] += w * (count % 2)
    return grid


# ------------------------------------------------------------- generators


def random_lines(rng, n, nseg=4, extent=(-10, -10, 10, 10)):
    xmin, ymin, xmax, ymax = extent
    feats = []
    for _ in range(n):
        x = rng.uniform(xmin, xmax, nseg + 1)
        y = rng.uniform(ymin, ymax, nseg + 1)
        feats.append([np.stack([x, y], 1)])
    return feats


def random_polys(rng, n, extent=(-10, -10, 10, 10), rmax=2.0):
    xmin, ymin, xmax, ymax = extent
    feats = []
    for _ in range(n):
        cx = rng.uniform(xmin, xmax)
        cy = rng.uniform(ymin, ymax)
        k = rng.integers(3, 9)
        ang = np.sort(rng.uniform(0, 2 * np.pi, k))
        rad = rng.uniform(0.3, rmax, k)
        ring = np.stack(
            [cx + rad * np.cos(ang), cy + rad * np.sin(ang)], 1
        )
        ring = np.concatenate([ring, ring[:1]])
        feats.append([ring])
    return feats


def _line_geoms(feats):
    return [Geometry("LineString", [p for p in paths]) for paths in feats]


def _poly_geoms(feats):
    return [Geometry("Polygon", rings) for rings in feats]


def _run_geometry(geoms, kind, weights, bbox, width, height, mask=None):
    col = GeometryColumn.from_geometries(geoms, kind=kind)
    sft = SimpleFeatureType.from_spec("t", f"*geom:{kind}")
    batch = FeatureBatch(sft, {"geom": col})
    dev = to_device(batch)
    n = len(col)
    m = (
        jnp.asarray(mask)
        if mask is not None
        else jnp.ones(n, dtype=bool)
    )
    return np.asarray(
        density_grid_geometry(
            col, dev, "geom", jnp.asarray(weights, jnp.float32), m,
            bbox, width, height,
        )
    )


BBOX = (-8.0, -8.0, 8.0, 8.0)


class TestLineDensity:
    def test_matches_oracle(self):
        rng = np.random.default_rng(42)
        feats = random_lines(rng, 60)
        w = rng.uniform(0.5, 3.0, len(feats))
        got = _run_geometry(_line_geoms(feats), "LineString", w, BBOX, 32, 24)
        want = line_oracle(feats, w, BBOX, 32, 24)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_total_weight_is_inside_fraction(self):
        # one segment fully inside one cell: all weight lands there
        seg = np.array([[0.1, 0.1], [0.4, 0.3]])
        got = _run_geometry(
            [Geometry("LineString", [seg])], "LineString",
            np.array([2.0]), BBOX, 16, 16,
        )
        assert got.sum() == pytest.approx(2.0, rel=1e-5)
        assert (got > 0).sum() == 1

    def test_outside_portion_drops(self):
        # half the length is outside the envelope -> half the weight
        seg = np.array([[0.0, 0.0], [16.0, 0.0]])  # envelope ends at x=8
        got = _run_geometry(
            [Geometry("LineString", [seg])], "LineString",
            np.array([1.0]), BBOX, 16, 16,
        )
        assert got.sum() == pytest.approx(0.5, rel=1e-5)

    def test_mask_excludes_features(self):
        rng = np.random.default_rng(3)
        feats = random_lines(rng, 10)
        w = np.ones(10)
        mask = np.zeros(10, bool)
        mask[::2] = True
        got = _run_geometry(
            _line_geoms(feats), "LineString", w, BBOX, 16, 16, mask=mask
        )
        want = line_oracle(
            [f for f, m in zip(feats, mask) if m],
            w[mask], BBOX, 16, 16,
        )
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_mixed_line_kinds_stay_linear(self):
        # concat of LineString + MultiLineString batches must unify to a
        # line kind (not "Geometry", which would close phantom rings and
        # dispatch to the polygon rasterizer)
        a = GeometryColumn.from_geometries(
            [parse_wkt("LINESTRING(0 0, 2 1)")]
        )
        b = GeometryColumn.from_geometries(
            [parse_wkt("MULTILINESTRING((0 0, 1 1), (2 2, 3 3))")]
        )
        sft = SimpleFeatureType.from_spec("t", "*geom:MultiLineString")
        merged = FeatureBatch.concat(
            [FeatureBatch(sft, {"geom": a}), FeatureBatch(sft, {"geom": b})]
        )
        assert merged.columns["geom"].kind == "MultiLineString"
        feats = [
            [np.array([[0.0, 0.0], [2.0, 1.0]])],
            [
                np.array([[0.0, 0.0], [1.0, 1.0]]),
                np.array([[2.0, 2.0], [3.0, 3.0]]),
            ],
        ]
        dev = to_device(merged)
        got = np.asarray(
            density_grid_geometry(
                merged.columns["geom"], dev, "geom",
                jnp.ones(2, jnp.float32), jnp.ones(2, dtype=bool),
                BBOX, 16, 16,
            )
        )
        want = line_oracle(feats, [1.0, 1.0], BBOX, 16, 16)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_multilinestring(self):
        g = parse_wkt(
            "MULTILINESTRING((0 0, 2 0.5, 3 2), (-4 -4, -2 -3.5))"
        )
        paths = [r for r in g.rings]
        got = _run_geometry([g], "MultiLineString", np.array([1.5]), BBOX, 20, 20)
        want = line_oracle([paths], [1.5], BBOX, 20, 20)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


class TestPolygonDensity:
    def test_matches_oracle(self):
        rng = np.random.default_rng(7)
        feats = random_polys(rng, 80)
        w = rng.uniform(0.5, 3.0, len(feats))
        got = _run_geometry(_poly_geoms(feats), "Polygon", w, BBOX, 40, 32)
        want = polygon_oracle(feats, w, BBOX, 40, 32)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_hole_excluded(self):
        outer = np.array(
            [[-4.0, -4.0], [4.0, -4.0], [4.0, 4.0], [-4.0, 4.0], [-4.0, -4.0]]
        )
        hole = np.array(
            [[-1.0, -1.0], [1.0, -1.0], [1.0, 1.0], [-1.0, 1.0], [-1.0, -1.0]]
        )
        g = Geometry("Polygon", [outer, hole])
        got = _run_geometry([g], "Polygon", np.array([1.0]), BBOX, 32, 32)
        want = polygon_oracle([[outer, hole]], [1.0], BBOX, 32, 32)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        # center cell is inside the hole -> zero
        assert got[16, 16] == 0.0
        # cell (10, 10) center = (-2.75, -2.75): inside shell, outside hole
        assert got[10, 10] == 1.0

    def test_orientation_invariance(self):
        # same polygon, shell given CW and CCW: identical grids (the edge
        # table normalizes orientation)
        ring = np.array(
            [[-3.0, -3.0], [3.0, -3.0], [3.0, 3.0], [-3.0, 3.0], [-3.0, -3.0]]
        )
        g_ccw = Geometry("Polygon", [ring])
        g_cw = Geometry("Polygon", [ring[::-1].copy()])
        a = _run_geometry([g_ccw], "Polygon", np.array([1.0]), BBOX, 16, 16)
        b = _run_geometry([g_cw], "Polygon", np.array([1.0]), BBOX, 16, 16)
        np.testing.assert_array_equal(a, b)

    def test_multipolygon_with_weight(self):
        g = parse_wkt(
            "MULTIPOLYGON(((0 0, 3 0, 3 3, 0 3, 0 0)),"
            "((-5 -5, -4 -5, -4 -4, -5 -4, -5 -5)))"
        )
        rings = [r for r in g.rings]
        got = _run_geometry([g], "MultiPolygon", np.array([2.5]), BBOX, 32, 32)
        want = polygon_oracle([rings], [2.5], BBOX, 32, 32)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_mask_and_padding(self):
        rng = np.random.default_rng(11)
        feats = random_polys(rng, 9)
        w = rng.uniform(1, 2, 9)
        mask = np.array([True, False] * 4 + [True])
        col = GeometryColumn.from_geometries(_poly_geoms(feats), kind="Polygon")
        sft = SimpleFeatureType.from_spec("t", "*geom:Polygon")
        batch = FeatureBatch(
            sft, {"geom": col}
        ).pad_to(16)  # padded rows must contribute nothing
        dev = to_device(batch)
        m = jnp.asarray(np.concatenate([mask, np.zeros(7, bool)]))
        wp = jnp.asarray(
            np.concatenate([w, np.zeros(7)]), jnp.float32
        )
        got = np.asarray(
            density_grid_geometry(
                batch.columns["geom"], dev, "geom", wp, m, BBOX, 24, 24
            )
        )
        want = polygon_oracle(
            [f for f, mm in zip(feats, mask) if mm], w[mask], BBOX, 24, 24
        )
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestMultiPointDensity:
    def test_each_vertex_counts(self):
        g = parse_wkt("MULTIPOINT((0.1 0.1), (0.15 0.12), (5 5))")
        got = _run_geometry([g], "MultiPoint", np.array([1.0]), BBOX, 16, 16)
        assert got.sum() == pytest.approx(3.0)


class TestMixedGeometryDensity:
    def test_mixed_kinds_split_not_cancelled(self):
        # a mixed "Geometry" column must rasterize each feature by its own
        # base kind — running lines/points through the polygon winding
        # kernel cancels their contributions to zero (round-2 review bug)
        line = parse_wkt("LINESTRING(0 0, 4 3)")
        poly = parse_wkt("POLYGON((-6 -6, -2 -6, -2 -2, -6 -2, -6 -6))")
        pt = parse_wkt("POINT(5.5 5.5)")
        w = np.array([1.0, 2.0, 3.0])
        got = _run_geometry([line, poly, pt], "Geometry", w, BBOX, 16, 16)
        want = line_oracle([[line.rings[0]]], [1.0], BBOX, 16, 16)
        want = want + polygon_oracle([poly.rings], [2.0], BBOX, 16, 16)
        # point cell: bbox (-8..8) / 16 -> cell edge 1.0; (5.5, 5.5) -> col
        # 13, row 13
        want[13, 13] += 3.0
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
        assert got.sum() == pytest.approx(want.sum())

    def test_mixed_mask_and_weights_align(self):
        # masking a feature inside a mixed column removes exactly its
        # contribution (per-subset weight/mask gathers must stay aligned)
        line = parse_wkt("LINESTRING(0 0, 4 0)")
        poly = parse_wkt("POLYGON((-6 -6, -2 -6, -2 -2, -6 -2, -6 -6))")
        w = np.array([2.0, 1.5])
        full = _run_geometry([line, poly], "Geometry", w, BBOX, 16, 16)
        masked = _run_geometry(
            [line, poly], "Geometry", w, BBOX, 16, 16,
            mask=np.array([True, False]),
        )
        only_line = line_oracle([[line.rings[0]]], [2.0], BBOX, 16, 16)
        np.testing.assert_allclose(masked, only_line, rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            full - masked,
            polygon_oracle([poly.rings], [1.5], BBOX, 16, 16),
            rtol=2e-4, atol=2e-4,
        )

    def test_mixed_multi_kinds_round_trip_exact(self):
        # single-part MULTIPOINT must stay MultiPoint through a mixed
        # column (round-2 review: kind collapse changes declared types)
        mp = parse_wkt("MULTIPOINT((1 1))")
        ln = parse_wkt("LINESTRING(0 0, 2 2)")
        col = GeometryColumn.from_geometries([mp, ln], kind=None)
        assert col.kind == "Geometry"
        assert col.geometry(0).kind == "MultiPoint"
        assert col.geometry(1).kind == "LineString"

    def test_geometry_collection_not_cancelled(self):
        # collection features have no single base kind: they degrade to
        # representative-point binning, never to a silent zero via the
        # polygon winding kernel
        gc = parse_wkt("GEOMETRYCOLLECTION(LINESTRING(0 0, 4 3), POINT(1 1))")
        poly = parse_wkt("POLYGON((-6 -6, -2 -6, -2 -2, -6 -2, -6 -6))")
        col = GeometryColumn.from_geometries([gc, poly], kind=None)
        assert col.geometry(0).kind == "GeometryCollection"
        got = _run_geometry(
            [gc, poly], "Geometry", np.array([1.0, 1.0]), BBOX, 16, 16
        )
        want = polygon_oracle([poly.rings], [1.0], BBOX, 16, 16)
        # the collection's representative point (first vertex, (0,0)) bins
        # its full weight at col 8, row 8
        want[8, 8] += 1.0
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_mixed_concat_preserves_feature_kinds(self):
        from geomesa_tpu.core.columnar import GeometryColumn

        lines = GeometryColumn.from_geometries(
            [parse_wkt("LINESTRING(0 0, 1 1)")], kind="LineString"
        )
        polys = GeometryColumn.from_geometries(
            [parse_wkt("POLYGON((0 0, 1 0, 1 1, 0 0))")], kind="Polygon"
        )
        sft_l = SimpleFeatureType.from_spec("t", "*geom:LineString")
        sft_p = SimpleFeatureType.from_spec("t", "*geom:Polygon")
        merged = FeatureBatch.concat(
            [
                FeatureBatch(sft_l, {"geom": lines}),
                FeatureBatch(sft_p, {"geom": polys}),
            ]
        )
        col = merged.columns["geom"]
        assert col.kind == "Geometry"
        assert col.feature_kinds is not None
        assert col.feature_kinds.tolist() == [1, 2]
        # reconstruction keeps base kinds
        assert col.geometry(0).kind == "LineString"
        assert col.geometry(1).kind == "Polygon"


class TestEndToEndPolygonLayer:
    """XZ2-partitioned polygon store -> planner -> device rasterization."""

    def test_density_query(self, tmp_path):
        from geomesa_tpu.plan import DataStore, Query, QueryHints
        from geomesa_tpu.store.partition import XZ2Scheme

        rng = np.random.default_rng(5)
        feats = random_polys(rng, 200, extent=(-60, -30, 60, 30), rmax=3.0)
        geoms = _poly_geoms(feats)
        sft = SimpleFeatureType.from_spec(
            "polys", "name:String,score:Double,*geom:Polygon"
        )
        batch = FeatureBatch.from_pydict(
            sft,
            {
                "name": [f"p{i}" for i in range(len(geoms))],
                "score": rng.uniform(0, 10, len(geoms)),
                "geom": geoms,
            },
        )
        ds = DataStore(str(tmp_path))
        src = ds.create_schema(sft, XZ2Scheme(g=2))
        src.write(batch)

        bbox = (-30.0, -20.0, 30.0, 20.0)
        res = src.get_features(
            Query(
                "polys",
                f"BBOX(geom, {bbox[0]}, {bbox[1]}, {bbox[2]}, {bbox[3]})",
                hints=QueryHints(
                    density_bbox=bbox, density_width=48, density_height=32
                ),
            )
        )
        # oracle: features whose bbox intersects the query bbox (loose
        # BBOX() semantics on extended geometries = envelope intersects),
        # rasterized by cell-center coverage
        keep = [
            i
            for i, g in enumerate(geoms)
            if not (
                g.bbox[2] < bbox[0]
                or g.bbox[0] > bbox[2]
                or g.bbox[3] < bbox[1]
                or g.bbox[1] > bbox[3]
            )
        ]
        want = polygon_oracle(
            [feats[i] for i in keep], np.ones(len(keep)), bbox, 48, 32
        )
        np.testing.assert_allclose(res.grid, want, rtol=1e-5, atol=1e-5)

    def test_weighted_line_layer(self, tmp_path):
        from geomesa_tpu.plan import DataStore, Query, QueryHints
        from geomesa_tpu.store.partition import XZ2Scheme

        rng = np.random.default_rng(6)
        feats = random_lines(rng, 50, extent=(-5, -5, 5, 5))
        geoms = _line_geoms(feats)
        sft = SimpleFeatureType.from_spec(
            "tracks", "w:Double,*geom:LineString"
        )
        w = rng.uniform(1, 4, len(geoms))
        batch = FeatureBatch.from_pydict(
            sft, {"w": w, "geom": geoms}
        )
        ds = DataStore(str(tmp_path))
        src = ds.create_schema(sft, XZ2Scheme(g=2))
        src.write(batch)
        bbox = (-6.0, -6.0, 6.0, 6.0)
        res = src.get_features(
            Query(
                "tracks",
                "INCLUDE",
                hints=QueryHints(
                    density_bbox=bbox,
                    density_width=24,
                    density_height=24,
                    density_weight="w",
                ),
            )
        )
        want = line_oracle(feats, w, bbox, 24, 24)
        np.testing.assert_allclose(res.grid, want, rtol=2e-4, atol=2e-4)
