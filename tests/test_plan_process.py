"""End-to-end planner + process tests over a real on-disk catalog."""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.engine.bin import decode_bin
from geomesa_tpu.engine.geodesy import haversine_m_np
from geomesa_tpu.plan import AuditWriter, DataStore, Query, QueryHints
from geomesa_tpu.process import (
    DensityProcess,
    JoinProcess,
    KNearestNeighborSearchProcess,
    LineGapFill,
    Point2PointProcess,
    ProximitySearchProcess,
    QueryProcess,
    SamplingProcess,
    StatsProcess,
    TubeSelectProcess,
    UniqueProcess,
)
from geomesa_tpu.store.partition import CompositeScheme, DateTimeScheme, Z2Scheme

import reference_engine as oracle
from geomesa_tpu.cql import parse_cql

SPEC = "vessel:String,speed:Double,heading:Double,dtg:Date,*geom:Point"
T0 = int(np.datetime64("2021-03-01T00:00:00", "ms").astype(np.int64))
DAY = 86400_000


def make_batch(n=3000, seed=1):
    r = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec("ais", SPEC)
    return FeatureBatch.from_pydict(
        sft,
        {
            "vessel": r.choice(["v1", "v2", "v3", "v4", "v5"], n).tolist(),
            "speed": r.uniform(0, 30, n),
            "heading": r.uniform(0, 360, n),
            "dtg": r.integers(T0, T0 + 7 * DAY, n),
            "geom": np.stack([r.uniform(-5, 5, n), r.uniform(50, 60, n)], 1),
        },
        fids=[f"a{i}" for i in range(n)],
    )


@pytest.fixture(scope="module")
def catalog(tmp_path_factory):
    root = tmp_path_factory.mktemp("catalog")
    audit = AuditWriter()
    ds = DataStore(str(root), audit=audit)
    batch = make_batch()
    src = ds.create_schema(
        batch.sft, CompositeScheme([DateTimeScheme("yyyy/MM/dd"), Z2Scheme(bits=2)])
    )
    src.write(batch)
    return ds, batch, audit


class TestDataStore:
    def test_type_names_and_schema(self, catalog):
        ds, batch, _ = catalog
        assert ds.get_type_names() == ["ais"]
        assert ds.get_schema("ais").to_spec() == batch.sft.to_spec()

    def test_query_matches_oracle(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        cql = ("BBOX(geom, -2, 52, 3, 58) AND dtg DURING "
               "2021-03-02T00:00:00Z/2021-03-05T00:00:00Z AND speed > 10")
        r = src.get_features(Query("ais", cql))
        exp = oracle.eval_filter(parse_cql(cql), batch)
        assert r.kind == "features"
        assert sorted(r.features.fids.decode()) == sorted(
            np.asarray(batch.fids.decode(), dtype=object)[exp].tolist()
        )

    def test_count_and_audit(self, catalog):
        ds, batch, audit = catalog
        src = ds.get_feature_source("ais")
        n0 = len(audit.events)
        assert src.get_count("speed > 10") == int(
            (np.asarray(batch.column("speed")) > 10).sum()
        )
        assert len(audit.events) > n0
        ev = audit.events[-1]
        assert ev.partitions_total >= ev.partitions_scanned > 0

    def test_fast_count_include(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        q = Query("ais", "INCLUDE", hints=QueryHints(exact_count=False))
        assert src.get_count(q) == len(batch)

    def test_arrow_encode_hint(self, catalog):
        # ARROW_ENCODE analog: results arrive as a readable Arrow IPC
        # stream whose rows match the plain feature query
        import io as _io

        import pyarrow as _pa

        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        cql = "speed > 10"
        q = Query("ais", cql, hints=QueryHints(arrow_encode=True))
        r = src.get_features(q)
        assert r.kind == "arrow" and r.arrow_bytes
        table = _pa.ipc.open_stream(_io.BytesIO(r.arrow_bytes)).read_all()
        exp = int((np.asarray(batch.column("speed")) > 10).sum())
        assert table.num_rows == exp == r.count
        # empty result still yields a valid schema-only stream
        q0 = Query("ais", "speed > 1e9", hints=QueryHints(arrow_encode=True))
        r0 = src.get_features(q0)
        t0 = _pa.ipc.open_stream(_io.BytesIO(r0.arrow_bytes)).read_all()
        assert t0.num_rows == 0

    def test_query_interceptors_and_guard(self, catalog):
        import pytest as _pytest

        from geomesa_tpu.plan.interceptor import (
            FullTableScanGuard, QueryGuardException)
        from geomesa_tpu.utils.config import SystemProperties

        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        planner = src.planner if hasattr(src, "planner") else None
        assert planner is not None
        # rewrite interceptor: force a speed predicate into every query
        def clamp(q):
            import dataclasses as _dc

            from geomesa_tpu.cql import parse_cql
            from geomesa_tpu.cql import ast as _ast

            f = _ast.And((q.filter_ast, parse_cql("speed > 10")))
            return _dc.replace(q, filter=f)

        planner.interceptors.append(clamp)
        try:
            got = src.get_count("speed >= 0")
            exp = int((np.asarray(batch.column("speed")) > 10).sum())
            assert got == exp
        finally:
            planner.interceptors.clear()

        # guard: unconstrained scans rejected when the property is set
        planner.interceptors.append(FullTableScanGuard())
        try:
            with _pytest.raises(QueryGuardException):
                src.get_count("INCLUDE")
            # sampled previews pass the guard
            q = Query("ais", "INCLUDE", hints=QueryHints(sampling=2))
            assert src.get_features(q).features is not None
        finally:
            planner.interceptors.clear()

        # hint rewrites must take effect in execution, not just planning
        def limit_two(q):
            import dataclasses as _dc

            return _dc.replace(q, max_features=2)

        planner.interceptors.append(limit_two)
        try:
            assert len(src.get_features("speed >= 0").features) == 2
        finally:
            planner.interceptors.clear()

        # the estimated-count shortcut must see the post-interceptor query
        planner.interceptors.append(clamp)
        try:
            q = Query("ais", "INCLUDE", hints=QueryHints(exact_count=False))
            exp = int((np.asarray(batch.column("speed")) > 10).sum())
            assert src.get_count(q) == exp
        finally:
            planner.interceptors.clear()

        # NON-idempotent interceptors apply exactly once, even on the
        # count -> execute -> plan re-entrant path (round-1 advisor: the
        # upstream SPI makes no idempotence promise)
        calls = []

        def counting_clamp(q):
            calls.append(1)
            return clamp(q)

        planner.interceptors.append(counting_clamp)
        try:
            got = src.get_count("speed >= 0")
            exp = int((np.asarray(batch.column("speed")) > 10).sum())
            assert got == exp
            assert len(calls) == 1, "interceptor chain ran more than once"
        finally:
            planner.interceptors.clear()

    def test_interceptor_loading_gated(self, catalog):
        # dotted-path interceptors from SFT user_data execute arbitrary
        # importable callables -> load only under the opt-in property; the
        # built-in guard name always loads
        from geomesa_tpu.plan.interceptor import (
            FullTableScanGuard, load_interceptors)
        from geomesa_tpu.utils.config import SystemProperties

        ds, batch, _ = catalog
        sft = ds.get_feature_source("ais").planner.storage.sft
        ud = dict(sft.user_data or {})
        ud["geomesa.query.interceptors"] = (
            "full-table-scan-guard, os.getcwd"
        )
        import dataclasses as _dc

        sft2 = _dc.replace(sft, user_data=ud)
        loaded = load_interceptors(sft2)
        assert len(loaded) == 1 and isinstance(loaded[0], FullTableScanGuard)
        SystemProperties.set("geomesa.query.interceptors.load", True)
        try:
            loaded = load_interceptors(sft2)
            assert len(loaded) == 2
        finally:
            SystemProperties.clear("geomesa.query.interceptors.load")

    def test_count_honors_max_features(self, catalog):
        # GeoTools getCount semantics: the query limit caps the count (the
        # count_only device fast path must match the features path)
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        q = Query("ais", "speed >= 0", max_features=5)
        assert len(src.get_features(q).features) == 5
        assert src.get_count(q) == 5

    def test_projection_sort_limit(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        q = Query(
            "ais", "speed > 25",
            attributes=["vessel", "speed", "geom"],
            sort_by=[("speed", False)],
            max_features=5,
        )
        r = src.get_features(q)
        assert len(r.features) <= 5
        s = r.features.column("speed")
        assert all(s[i] >= s[i + 1] for i in range(len(s) - 1))
        assert set(r.features.columns) == {"vessel", "speed", "geom"}

    def test_explain(self, catalog):
        ds, _, _ = catalog
        src = ds.get_feature_source("ais")
        text = src.explain("BBOX(geom, -2, 52, 3, 58) AND speed > 10")
        assert "Partitions:" in text and "Residual predicate" in text

    def test_density_hint(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        bbox = (-5.0, 50.0, 5.0, 60.0)
        q = Query(
            "ais", "speed > 10",
            hints=QueryHints(density_bbox=bbox, density_width=64, density_height=64),
        )
        r = src.get_features(q)
        assert r.kind == "density" and r.grid.shape == (64, 64)
        exp = (np.asarray(batch.column("speed")) > 10).sum()
        assert r.grid.sum() == pytest.approx(exp)

    def test_stats_hint(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        q = Query(
            "ais", "INCLUDE",
            hints=QueryHints(stats_string="MinMax(speed);TopK(vessel,2);DescriptiveStats(speed)"),
        )
        stats = src.get_features(q).stats
        mn, mx = stats.stats[0].result()
        sp = np.asarray(batch.column("speed"))
        assert mn == pytest.approx(sp.min()) and mx == pytest.approx(sp.max())
        top = stats.stats[1].result()
        vc = {}
        for v in batch.column("vessel").decode():
            vc[v] = vc.get(v, 0) + 1
        assert top[0][1] == max(vc.values())
        desc = stats.stats[2].result()
        assert desc["mean"] == pytest.approx(sp.mean())

    def test_bin_hint(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        q = Query("ais", "speed > 20", hints=QueryHints(bin_track="vessel"))
        r = src.get_features(q)
        rec = decode_bin(r.bin_bytes)
        exp = (np.asarray(batch.column("speed")) > 20).sum()
        assert len(rec) == exp

    def test_loose_bbox_hint(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        cql = "BBOX(geom, -2, 52, 3, 58) AND speed > 10"
        strict = src.get_features(Query("ais", cql)).count
        loose = src.get_features(
            Query("ais", cql, hints=QueryHints(loose_bbox=True))
        ).count
        # loose accepts the covering pushdown: superset of strict
        assert loose >= strict
        text = src.explain(Query("ais", cql, hints=QueryHints(loose_bbox=True)))
        assert "Loose bbox" in text

    def test_sample_by_hint(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        r = src.get_features(
            Query("ais", "INCLUDE", hints=QueryHints(sampling=5, sample_by="vessel"))
        )
        per = {}
        for v in r.features.column("vessel").decode():
            per[v] = per.get(v, 0) + 1
        assert set(per) == {"v1", "v2", "v3", "v4", "v5"}  # every track kept

    def test_bin_label_hint(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        q = Query(
            "ais", "speed > 25",
            hints=QueryHints(bin_track="vessel", bin_label="vessel"),
        )
        r = src.get_features(q)
        rec = decode_bin(r.bin_bytes, labeled=True)
        exp = (np.asarray(batch.column("speed")) > 25).sum()
        assert len(rec) == exp
        np.testing.assert_array_equal(rec["label"], rec["track"])

    def test_sampling_hint(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        full = src.get_features(Query("ais", "speed > 10")).count
        s = src.get_features(
            Query("ais", "speed > 10", hints=QueryHints(sampling=10))
        )
        assert s.count == pytest.approx(full / 10, abs=2)

    def test_remove_schema(self, tmp_path):
        ds = DataStore(str(tmp_path / "c"))
        b = make_batch(50)
        ds.create_schema(b.sft)
        assert ds.get_type_names() == ["ais"]
        ds.remove_schema("ais")
        assert ds.get_type_names() == []


class TestProcesses:
    def test_knn(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        r = np.random.default_rng(7)
        qsft = SimpleFeatureType.from_spec("q", "name:String,*geom:Point")
        qx, qy = r.uniform(-4, 4, 8), r.uniform(51, 59, 8)
        queries = FeatureBatch.from_pydict(
            qsft, {"name": [f"q{i}" for i in range(8)],
                   "geom": np.stack([qx, qy], 1)}
        )
        res = KNearestNeighborSearchProcess().execute(
            queries, src, num_desired=5, estimated_distance_m=20_000
        )
        # oracle: exact 5-NN over the full dataset
        d = haversine_m_np(qx[:, None], qy[:, None],
                           batch.geometry.x[None, :], batch.geometry.y[None, :])
        exp = np.sort(d, axis=1)[:, :5]
        np.testing.assert_allclose(res.distances_m, exp, rtol=1e-6)

    def test_knn_grid_impl_matches_oracle(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        r = np.random.default_rng(8)
        qsft = SimpleFeatureType.from_spec("q", "name:String,*geom:Point")
        nq = 32
        qx, qy = r.uniform(-4, 4, nq), r.uniform(51, 59, nq)
        queries = FeatureBatch.from_pydict(
            qsft, {"name": [f"q{i}" for i in range(nq)],
                   "geom": np.stack([qx, qy], 1)}
        )
        res = KNearestNeighborSearchProcess().execute(
            queries, src, num_desired=5, estimated_distance_m=20_000,
            impl="grid",
        )
        d = haversine_m_np(qx[:, None], qy[:, None],
                           batch.geometry.x[None, :], batch.geometry.y[None, :])
        exp = np.sort(d, axis=1)[:, :5]
        np.testing.assert_allclose(
            res.distances_m, exp, rtol=1e-4, atol=1.0
        )

    def test_knn_respects_max_distance(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        qsft = SimpleFeatureType.from_spec("q", "name:String,*geom:Point")
        # a far-away query point: nothing within 50km
        queries = FeatureBatch.from_pydict(
            qsft, {"name": ["far"], "geom": np.array([[120.0, -40.0]])}
        )
        res = KNearestNeighborSearchProcess().execute(
            queries, src, num_desired=3, estimated_distance_m=10_000,
            max_search_distance_m=50_000,
        )
        assert np.isinf(res.distances_m).all()

    def test_density_process(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        grid = DensityProcess().execute(src, (-5, 50, 5, 60), 32, 32)
        assert grid.shape == (32, 32)
        assert grid.sum() == pytest.approx(len(batch))
        blurred = DensityProcess().execute(
            src, (-5, 50, 5, 60), 32, 32, radius_pixels=2
        )
        # blur spreads mass; only border spill may be lost
        assert 0.9 * len(batch) <= blurred.sum() <= len(batch) + 1

    def test_tube_select(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        tsft = SimpleFeatureType.from_spec("t", "name:String,dtg:Date,*geom:Point")
        track = FeatureBatch.from_pydict(
            tsft,
            {
                "name": ["t"] * 3,
                "dtg": [T0 + DAY, T0 + 2 * DAY, T0 + 3 * DAY],
                "geom": np.array([[-2.0, 52.0], [0.0, 55.0], [2.0, 58.0]]),
            },
        )
        hits = TubeSelectProcess().execute(
            track, src, fill=LineGapFill(50_000), buffer_m=50_000,
            max_time_window_ms=12 * 3600_000,
        )
        # every hit must satisfy the tube condition vs some interpolated sample
        assert len(hits) > 0
        from geomesa_tpu.process.tube import Tube

        for i in range(min(len(hits), 20)):
            x, y = hits.geometry.x[i], hits.geometry.y[i]
            t = int(np.asarray(hits.dtg)[i])
            d = haversine_m_np(
                np.array([x]), np.array([y]),
                np.array([-2.0, 0.0, 2.0]), np.array([52.0, 55.0, 58.0]),
            )
            # within 50km+interp of the coarse track: loose sanity check
            assert d.min() < 500_000

    def test_proximity(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        qsft = SimpleFeatureType.from_spec("q", "name:String,*geom:Point")
        probe = FeatureBatch.from_pydict(
            qsft, {"name": ["p"], "geom": np.array([[0.0, 55.0]])}
        )
        hits = ProximitySearchProcess().execute(probe, src, 100_000)
        d = haversine_m_np(batch.geometry.x, batch.geometry.y, 0.0, 55.0)
        assert len(hits) == (d <= 100_000).sum()

    def test_query_sampling_stats_unique(self, catalog):
        ds, batch, _ = catalog
        src = ds.get_feature_source("ais")
        assert len(QueryProcess().execute(src, "speed > 25")) == (
            np.asarray(batch.column("speed")) > 25
        ).sum()
        thin = SamplingProcess().execute(src, 7)
        assert len(thin) == pytest.approx(len(batch) / 7, abs=2)
        stats = StatsProcess().execute(src, "Histogram(speed,10,0,30)")
        assert stats.stats[0].result().sum() == len(batch)
        uniq = UniqueProcess().execute(src, "vessel")
        assert {u[0] for u in uniq} == {"v1", "v2", "v3", "v4", "v5"}
        assert sum(u[1] for u in uniq) == len(batch)

    def test_join(self, catalog):
        ds, batch, _ = catalog
        rsft = SimpleFeatureType.from_spec("meta", "vessel:String,flag:String,*geom:Point")
        right = FeatureBatch.from_pydict(
            rsft,
            {
                "vessel": ["v1", "v2", "v3"],
                "flag": ["NL", "DE", "FR"],
                "geom": np.zeros((3, 2)),
            },
        )
        joined = JoinProcess().execute(batch, right, "vessel", "vessel", ["flag"])
        assert "flag" in joined.sft.attribute_names
        vs = joined.column("vessel").decode()
        fl = joined.column("flag").decode()
        assert all((v, f) in {("v1", "NL"), ("v2", "DE"), ("v3", "FR")} for v, f in zip(vs, fl))

    def test_point2point(self, catalog):
        ds, batch, _ = catalog
        tracks = Point2PointProcess().execute(batch, "vessel")
        assert len(tracks) == 5
        assert tracks.sft.attribute("geom").type == "LineString"
        # each vessel's track has as many vertices as its pings
        counts = {}
        for v in batch.column("vessel").decode():
            counts[v] = counts.get(v, 0) + 1
        for i in range(len(tracks)):
            name = tracks.column("track").decode()[i]
            assert len(tracks.geometry.geometry(i).rings[0]) == counts[name]
