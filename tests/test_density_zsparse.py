"""Z-locality density kernel tests (interpret-mode Pallas on CPU).

Oracle: the scatter-path `density_grid` (itself gated against
np.histogram2d in test_engine.py) — the zsparse kernel must reproduce it
exactly for counts and to f32-summation noise for weights, on Z-ordered
AND random-ordered (fallback-heavy) inputs."""

import numpy as np
import pytest

import jax.numpy as jnp

from geomesa_tpu.engine.density import density_grid
from geomesa_tpu.engine.density_zsparse import (
    calibrate_density, density_zsparse)

BBOX = (-60.0, -45.0, 60.0, 45.0)


def _morton64(x, y):
    qx = ((np.asarray(x, np.float64) + 180) / 360 * (1 << 16)).astype(np.uint64)
    qy = ((np.asarray(y, np.float64) + 90) / 180 * (1 << 16)).astype(np.uint64)

    def spread(v):
        v = (v | (v << np.uint64(16))) & np.uint64(0x0000FFFF0000FFFF)
        v = (v | (v << np.uint64(8))) & np.uint64(0x00FF00FF00FF00FF)
        v = (v | (v << np.uint64(4))) & np.uint64(0x0F0F0F0F0F0F0F0F)
        v = (v | (v << np.uint64(2))) & np.uint64(0x3333333333333333)
        v = (v | (v << np.uint64(1))) & np.uint64(0x5555555555555555)
        return v

    return spread(qx) | (spread(qy) << np.uint64(1))


def make(n, seed=5, z_order=True, clustered=False):
    rng = np.random.default_rng(seed)
    if clustered:
        k = 20
        cx = rng.uniform(-50, 50, k)
        cy = rng.uniform(-40, 40, k)
        pick = rng.integers(0, k, n)
        x = np.clip(cx[pick] + rng.normal(0, 2, n), -180, 180)
        y = np.clip(cy[pick] + rng.normal(0, 2, n), -90, 90)
        bg = rng.random(n) < 0.1
        x[bg] = rng.uniform(-180, 180, bg.sum())
        y[bg] = rng.uniform(-90, 90, bg.sum())
    else:
        x = rng.uniform(-80, 80, n)
        y = rng.uniform(-60, 60, n)
    if z_order:
        o = np.argsort(_morton64(x, y))
        x, y = x[o], y[o]
    w = rng.uniform(0.5, 2.0, n)
    mask = rng.random(n) < 0.7
    return x, y, w, mask


def run_both(x, y, w, mask, W=64, H=64, data_tile=2048, weights=None):
    jx = jnp.asarray(x, jnp.float32)
    jy = jnp.asarray(y, jnp.float32)
    jw = jnp.asarray(w if weights is None else weights, jnp.float32)
    jm = jnp.asarray(mask)
    ref = np.asarray(density_grid(jx, jy, jw, jm, BBOX, W, H))
    got, calib = density_zsparse(
        jx, jy, jw, jm, BBOX, W, H, data_tile=data_tile, interpret=True)
    return np.asarray(got), ref, calib


class TestZsparseDensity:
    def test_counts_exact_z_order(self):
        x, y, w, mask = make(1 << 15)
        got, ref, calib = run_both(x, y, w, mask, weights=np.ones(len(x)))
        np.testing.assert_array_equal(got, ref)
        assert len(calib.tile_ids) > 0  # the sparse path actually ran

    def test_weighted_close_z_order(self):
        x, y, w, mask = make(1 << 15, seed=7)
        got, ref, calib = run_both(x, y, w, mask)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-3)
        np.testing.assert_allclose(got.sum(), ref.sum(), rtol=1e-6)

    def test_random_order_falls_back_exactly(self):
        # unsorted input: spans blow past cap, tiles route to the dense
        # path — result must still match (the correctness-for-any-order
        # contract); here weights=1 so equality is exact
        x, y, w, mask = make(1 << 14, seed=9, z_order=False)
        # 256x256: random-order tile spans exceed MAX_CAP, forcing the
        # dense route (64x64 fits entirely within one cap)
        got, ref, calib = run_both(
            x, y, w, mask, W=256, H=256, weights=np.ones(len(x)))
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-4)
        assert len(calib.dense_ids) > 0  # fallback exercised

    def test_clustered_z_order(self):
        x, y, w, mask = make(1 << 15, seed=11, clustered=True)
        got, ref, calib = run_both(x, y, w, mask, weights=np.ones(len(x)))
        np.testing.assert_array_equal(got, ref)

    def test_calib_reuse(self):
        x, y, w, mask = make(1 << 14, seed=13)
        jx = jnp.asarray(x, jnp.float32)
        jy = jnp.asarray(y, jnp.float32)
        jw = jnp.asarray(np.ones(len(x)), jnp.float32)
        jm = jnp.asarray(mask)
        g1, calib = density_zsparse(
            jx, jy, jw, jm, BBOX, 64, 64, data_tile=2048, interpret=True)
        g2, _ = density_zsparse(
            jx, jy, jw, jm, BBOX, 64, 64, calib=calib, data_tile=2048,
            interpret=True)
        np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))

    def test_empty_mask(self):
        x, y, w, mask = make(1 << 12, seed=15)
        got, ref, calib = run_both(
            x, y, w, np.zeros_like(mask), weights=np.ones(len(x)))
        assert got.sum() == 0
        assert len(calib.tile_ids) == 0 and len(calib.dense_ids) == 0

    def test_all_points_outside_bbox(self):
        rng = np.random.default_rng(17)
        n = 1 << 12
        x = rng.uniform(100, 170, n)
        y = rng.uniform(50, 80, n)
        got, ref, calib = run_both(
            x, y, np.ones(n), np.ones(n, bool), weights=np.ones(n))
        assert got.sum() == 0

    def test_dictionaries_cover_distinct_cells(self):
        # each selected tile's dictionary holds exactly its distinct
        # matching cells (pads are -1)
        x, y, w, mask = make(1 << 13, seed=25)
        jx = jnp.asarray(x, jnp.float32)
        jy = jnp.asarray(y, jnp.float32)
        jm = jnp.asarray(mask)
        calib = calibrate_density(jx, jy, jm, BBOX, 64, 64, data_tile=1024)
        from geomesa_tpu.engine.density_zsparse import _bin_cells
        cells = np.asarray(_bin_cells(jx, jy, jm, BBOX, 64, 64)[0])
        ok = np.asarray(_bin_cells(jx, jy, jm, BBOX, 64, 64)[1])
        dicts = np.asarray(calib.dicts)
        for row, t in enumerate(calib.tile_ids[:8]):
            sl = slice(t * 1024, (t + 1) * 1024)
            exp = np.unique(cells[sl][ok[sl]])
            got = dicts[row][dicts[row] >= 0]
            np.testing.assert_array_equal(np.sort(got), exp)

    def test_non_square_grid(self):
        x, y, w, mask = make(1 << 14, seed=19)
        got, ref, calib = run_both(
            x, y, w, mask, W=96, H=40, weights=np.ones(len(x)))
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-4)


def test_calibration_prunes_empty_tiles():
    # points concentrated in one corner: most tiles carry no matches and
    # must be absent from BOTH lists (pruned, never scanned)
    rng = np.random.default_rng(21)
    n = 1 << 14
    x = np.sort(rng.uniform(-59, -50, n))
    y = rng.uniform(-44, -40, n)
    calib = calibrate_density(
        jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32),
        jnp.ones(n, bool), BBOX, 64, 64, data_tile=1024,
    )
    assert len(calib.tile_ids) + len(calib.dense_ids) <= calib.n_tiles


def test_density_zsparse_sharded_matches_scatter():
    # the mesh variant (round 5, VERDICT task 4): global calibration
    # partitioned by shard, per-shard kernel + dense fallback, psum merge
    from geomesa_tpu.engine.density_zsparse import density_zsparse_sharded
    from geomesa_tpu.parallel import default_mesh

    mesh = default_mesh()
    D = int(np.prod(mesh.devices.shape))
    dt = 512
    n = D * dt * 4  # 4 tiles per shard
    x, y, w, mask = make(n, seed=9, z_order=True)
    jx = jnp.asarray(x, jnp.float32)
    jy = jnp.asarray(y, jnp.float32)
    jw = jnp.asarray(w, jnp.float32)
    jm = jnp.asarray(mask)
    got = np.asarray(density_zsparse_sharded(
        mesh, jx, jy, jw, jm, BBOX, 64, 64, data_tile=dt, interpret=True))
    exp = np.asarray(density_grid(jx, jy, jw, jm, BBOX, 64, 64))
    np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-2)
    # random order: overflow tiles exercise the per-shard dense fallback
    xr, yr, wr, mr = make(n, seed=10, z_order=False)
    got = np.asarray(density_zsparse_sharded(
        mesh, jnp.asarray(xr, jnp.float32), jnp.asarray(yr, jnp.float32),
        jnp.asarray(wr, jnp.float32), jnp.asarray(mr), BBOX, 64, 64,
        data_tile=dt, interpret=True))
    exp = np.asarray(density_grid(
        jnp.asarray(xr, jnp.float32), jnp.asarray(yr, jnp.float32),
        jnp.asarray(wr, jnp.float32), jnp.asarray(mr), BBOX, 64, 64))
    np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-2)


def test_density_zsparse_hint_through_datastore(tmp_path):
    # product wiring: the density_zsparse hint produces the same grid as
    # the default scatter path through the full DataStore query
    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.plan.hints import QueryHints
    from geomesa_tpu.plan.query import Query

    rng = np.random.default_rng(23)
    n = 20_000
    sft = SimpleFeatureType.from_spec("d", "*geom:Point")
    x = rng.uniform(-50, 50, n)
    y = rng.uniform(-40, 40, n)
    o = np.argsort(_morton64(x, y))
    batch = FeatureBatch.from_pydict(
        sft, {"geom": np.stack([x[o], y[o]], 1)})
    ds = DataStore(str(tmp_path / "d"))
    src = ds.create_schema(sft)
    src.write(batch)

    def q(zs):
        hints = QueryHints(
            density_bbox=(-60.0, -45.0, 60.0, 45.0),
            density_width=64, density_height=64, density_zsparse=zs)
        return src.get_features(
            Query("d", "BBOX(geom, -45, -35, 45, 35)", hints=hints)).grid

    np.testing.assert_allclose(q(True), q(False), rtol=1e-6, atol=1e-3)
    assert q(True).sum() > 0
    # AUTO default (hint unset = None): a plain density query must take
    # the zsparse path for point layers (VERDICT r4 task 3 — fast by
    # default) and still match the forced-scatter grid
    np.testing.assert_allclose(q(None), q(False), rtol=1e-6, atol=1e-3)


def test_density_auto_default_routes_zsparse(monkeypatch):
    # the auto decision itself: with no hints, density_device_grid calls
    # the zsparse kernel; with exact_weights + weight it pins scatter
    import geomesa_tpu.plan.runner as runner_mod
    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.hints import QueryHints
    from geomesa_tpu.plan.runner import density_device_grid

    rng = np.random.default_rng(31)
    n = 4096
    sft = SimpleFeatureType.from_spec("d", "w:Double,*geom:Point")
    x = rng.uniform(-50, 50, n)
    y = rng.uniform(-40, 40, n)
    w = rng.uniform(0, 2, n)
    batch = FeatureBatch.from_pydict(
        sft, {"w": w, "geom": np.stack([x, y], 1)})
    from geomesa_tpu.engine.device import to_device

    dev = to_device(batch)
    calls = []
    real = runner_mod._zsparse_grid

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(runner_mod, "_zsparse_grid", spy)
    mask = jnp.ones(n, bool)
    base = QueryHints(
        density_bbox=(-60.0, -45.0, 60.0, 45.0),
        density_width=32, density_height=32)
    g_auto = np.asarray(density_device_grid(sft, batch, dev, mask, base))
    assert calls, "auto default must route point density to zsparse"
    # exact_weights + weight column pins the scatter path even under auto
    calls.clear()
    import dataclasses

    pinned = dataclasses.replace(
        base, density_weight="w", density_exact_weights=True)
    g_pin = np.asarray(density_device_grid(sft, batch, dev, mask, pinned))
    assert not calls, "exact_weights pin must bypass zsparse"
    assert g_auto.sum() > 0 and g_pin.sum() > 0
