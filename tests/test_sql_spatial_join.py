"""SQL spatial join (JOIN ... ON st_contains/st_within/st_intersects)
end-to-end over on-disk stores, vs a f64 all-edges containment oracle."""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch, Geometry
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.sql.engine import SqlContext, SqlError


def ring(cx, cy, r, ne=24, reverse=False):
    th = np.linspace(0, 2 * np.pi, ne, endpoint=False)
    if reverse:
        th = th[::-1]
    pts = np.stack([cx + r * np.cos(th), cy + r * np.sin(th)], 1)
    return np.concatenate([pts, pts[:1]])


@pytest.fixture()
def stores(tmp_path):
    rng = np.random.default_rng(41)
    rsft = SimpleFeatureType.from_spec("regions", "name:String,*geom:Polygon")
    centers = [(-20.0, -10.0), (0.0, 15.0), (25.0, -5.0), (40.0, 20.0)]
    polys = [Geometry("Polygon", [ring(cx, cy, 8.0)]) for cx, cy in centers]
    # region 1 gets a hole (points inside it must NOT join)
    polys[1] = Geometry(
        "Polygon", [ring(0.0, 15.0, 8.0), ring(0.0, 15.0, 3.0, reverse=True)]
    )
    regions = FeatureBatch.from_pydict(
        rsft,
        {"name": [f"r{i}" for i in range(len(polys))], "geom": polys},
    )
    esft = SimpleFeatureType.from_spec("events", "val:Double,*geom:Point")
    n = 4000
    px = np.sort(rng.uniform(-40, 60, n))
    py = rng.uniform(-30, 40, n)
    events = FeatureBatch.from_pydict(
        esft, {"val": rng.uniform(0, 10, n), "geom": np.stack([px, py], 1)}
    )
    ds = DataStore(str(tmp_path / "cat"))
    ds.create_schema(rsft).write(regions)
    ds.create_schema(esft).write(events)
    return ds, centers, polys


def oracle_assign(polys, px, py):
    """[N] region row containing each point (-1 none), f64 all edges."""
    out = np.full(len(px), -1, np.int64)
    for i, g in enumerate(polys):
        inside = np.zeros(len(px), bool)
        cross = np.zeros(len(px), np.int64)
        for rg in g.rings:
            a = np.asarray(rg)
            x1, y1 = a[:-1, 0], a[:-1, 1]
            x2, y2 = a[1:, 0], a[1:, 1]
            condx = (y1[None] <= py[:, None]) != (y2[None] <= py[:, None])
            t = (py[:, None] - y1[None]) / np.where(
                y2 == y1, 1.0, y2 - y1)[None]
            xc = x1[None] + t * (x2 - x1)[None]
            cross += np.sum(condx & (xc > px[:, None]), 1)
        inside = (cross % 2) == 1
        out[inside] = i
    return out


class TestSqlSpatialJoin:
    def test_st_contains_assignment(self, stores):
        ds, centers, polys = stores
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.val AS val, r.name AS region FROM events e "
            "JOIN regions r ON st_contains(r.geom, e.geom)"
        )
        ev = ds.get_feature_source("events").get_features().features
        g = ev.columns["geom"]
        exp = oracle_assign(polys, np.asarray(g.x), np.asarray(g.y))
        assert r.count == int((exp >= 0).sum())
        # every joined row names the oracle's region for its point: match
        # multisets of (region name) counts
        got_names = list(r.features.columns["region"].decode())
        import collections

        exp_names = collections.Counter(
            f"r{i}" for i in exp[exp >= 0])
        assert collections.Counter(got_names) == exp_names

    def test_st_within_and_intersects_equivalent(self, stores):
        ds, centers, polys = stores
        ctx = SqlContext(ds)
        base = ctx.sql(
            "SELECT e.val AS val, r.name AS region FROM events e "
            "JOIN regions r ON st_contains(r.geom, e.geom)")
        w = ctx.sql(
            "SELECT e.val AS val, r.name AS region FROM events e "
            "JOIN regions r ON st_within(e.geom, r.geom)")
        i = ctx.sql(
            "SELECT e.val AS val, r.name AS region FROM regions r "
            "JOIN events e ON st_intersects(r.geom, e.geom)")
        assert base.count == w.count == i.count

    def test_left_outer_spatial(self, stores):
        ds, centers, polys = stores
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT e.val AS val, r.name AS region FROM events e "
            "LEFT JOIN regions r ON st_contains(r.geom, e.geom)"
        )
        ev = ds.get_feature_source("events").get_features().features
        g = ev.columns["geom"]
        exp = oracle_assign(polys, np.asarray(g.x), np.asarray(g.y))
        # every event appears; unmatched ones carry a null region
        assert r.count == len(ev)
        got_names = np.asarray(list(r.features.columns["region"].decode()),
                               dtype=object)
        n_null = int(sum(1 for v in got_names if v is None))
        assert n_null == int((exp < 0).sum())

    def test_aggregate_over_spatial_join(self, stores):
        ds, centers, polys = stores
        ctx = SqlContext(ds)
        r = ctx.sql(
            "SELECT r.name AS region, COUNT(*) AS n FROM events e "
            "JOIN regions r ON st_contains(r.geom, e.geom) "
            "GROUP BY r.name ORDER BY region"
        )
        ev = ds.get_feature_source("events").get_features().features
        g = ev.columns["geom"]
        exp = oracle_assign(polys, np.asarray(g.x), np.asarray(g.y))
        import collections

        expc = collections.Counter(f"r{i}" for i in exp[exp >= 0])
        names = list(r.features.columns["region"].decode())
        counts = np.asarray(r.features.columns["n"])
        assert dict(zip(names, counts.tolist())) == dict(expc)

    def test_point_point_join_rejected(self, stores):
        ds, _, _ = stores
        ctx = SqlContext(ds)
        with pytest.raises(SqlError, match="polygon"):
            ctx.sql(
                "SELECT e.val AS v FROM events e "
                "JOIN events f ON st_intersects(e.geom, f.geom)")
